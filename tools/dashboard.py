#!/usr/bin/env python
"""Live terminal dashboard over the health engine + metrics registry
(doc/health.md).

Renders, from `gethealth` + `getmetrics` on a running daemon's unix
JSON-RPC socket:

  * the rolled-up health state (healthy/degraded/unhealthy) with the
    breached SLO names;
  * the SLO panel — per SLO: status, observed value vs threshold,
    short/long error-budget burn rates, lifetime breach entries;
  * per-family rate sparklines read from the engine's time-series
    rings (the SAME rings `obs_snapshot capture --watch` folds into
    its ticks, so the two surfaces always agree);
  * the breaker / overload / shed panel (circuit-breaker states,
    degradation-ladder states, shed counts by priority:reason);
  * the incidents panel (doc/incidents.md) — the black-box recorder's
    recent bundles from ``listincidents``: naming trigger, age, size,
    and how many duplicate triggers the cooldown suppressed.

Stdlib only (ANSI escapes, no curses dependency), jax-free.  Live mode
redraws every ``--interval`` seconds until Ctrl-C; ``--once`` prints a
single plain-text frame — the CI-friendly mode tools/health_smoke.py
asserts against.

Usage:
  python tools/dashboard.py --rpc <lightning-rpc> [--interval 2]
  python tools/dashboard.py --rpc <lightning-rpc> --once
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from lightning_tpu.obs.health import HEADLINE_RATES  # noqa: E402

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _TOOLS)
from obs_snapshot import rpc_call  # noqa: E402

SPARK = "▁▂▃▄▅▆▇█"
_STATE_COLOR = {"healthy": "32", "degraded": "33", "unhealthy": "31",
                "unknown": "90"}
_STATUS_MARK = {"ok": "·", "warn": "!", "breach": "✗"}


def sparkline(points, width: int = 32) -> str:
    """Unicode sparkline over the last `width` numeric points (None =
    no data for that tick, rendered as a space)."""
    pts = list(points)[-width:]
    vals = [p for p in pts if isinstance(p, (int, float))]
    if not vals:
        return " " * min(width, len(pts))
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    out = []
    for p in pts:
        if not isinstance(p, (int, float)):
            out.append(" ")
        else:
            idx = int((p - lo) / span * (len(SPARK) - 1))
            out.append(SPARK[idx])
    return "".join(out)


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _color(text: str, code: str, enable: bool) -> str:
    return f"\x1b[{code}m{text}\x1b[0m" if enable else text


def merge_family_points(rings: dict, family: str) -> list:
    """Sum a family's per-child ring points elementwise (tail-aligned:
    every series ticks together, so index -1 is the same tick in each).
    Histogram points are (rate, p50, p99) tuples — the rate leads."""
    children = []
    for key, ser in sorted(rings.items()):
        if key != family and not key.startswith(family + "{"):
            continue
        pts = [p[0] if isinstance(p, (list, tuple)) else p
               for p in (ser.get("points") or [])]
        children.append([p if isinstance(p, (int, float)) else None
                         for p in pts])
    if not children:
        return []
    width = max(len(c) for c in children)
    merged: list = [None] * width
    for pts in children:
        off = width - len(pts)
        for i, p in enumerate(pts):
            if p is not None:
                j = off + i
                merged[j] = (merged[j] or 0.0) + p
    return merged


def fetch(rpc_path: str, points: int = 40, incident_rows: int = 5,
          journey_rows: int = 5,
          ) -> tuple[dict, dict, dict | None, dict | None]:
    """One (gethealth, getmetrics, listincidents, getjourney) tuple;
    the ring extract asks for the headline families the sparkline panel
    draws.  A daemon without the listincidents/getjourney commands
    (older harness) yields None for that panel."""
    health = rpc_call(rpc_path, "gethealth",
                      {"series": sorted(set(HEADLINE_RATES.values())),
                       "points": points})
    metrics = rpc_call(rpc_path, "getmetrics")
    try:
        incidents = rpc_call(rpc_path, "listincidents",
                             {"limit": incident_rows})
    except SystemExit:
        incidents = None
    try:
        journeys = rpc_call(rpc_path, "getjourney",
                            {"limit": journey_rows})
    except SystemExit:
        journeys = None
    return health, metrics, incidents, journeys


def _fmt_bytes(n) -> str:
    if not isinstance(n, (int, float)):
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return "-"


def _fmt_age(s) -> str:
    if not isinstance(s, (int, float)):
        return "-"
    if s < 120:
        return f"{s:.0f}s"
    if s < 7200:
        return f"{s / 60:.0f}m"
    return f"{s / 3600:.1f}h"


def render(health: dict, metrics: dict, incidents: dict | None = None,
           journeys: dict | None = None,
           color: bool = False, width: int = 40) -> str:
    """One text frame (shared by --once and the live loop)."""
    lines: list[str] = []
    state = health.get("state", "unknown")
    breached = health.get("breached") or []
    head = (f"lightning-tpu health  state={state.upper()}"
            + (f"  breached={','.join(breached)}" if breached else ""))
    lines.append(_color(head, _STATE_COLOR.get(state, "0"), color))
    lines.append(
        f"  ticks={health.get('ticks', 0)}"
        f"  interval={_fmt(health.get('interval_s'))}s"
        f"  windows={health.get('short_ticks', '-')}"
        f"/{health.get('long_ticks', '-')} ticks"
        f"  transitions={health.get('transitions', 0)}"
        f"  running={health.get('running', False)}")

    lines.append("")
    lines.append("SLOs                status   observed    threshold  "
                 "burn_s  burn_l  breaches")
    for name, s in sorted((health.get("slos") or {}).items()):
        mark = _STATUS_MARK.get(s.get("status"), "?")
        row = (f"  {mark} {name:<17} {s.get('status', '?'):<8} "
               f"{_fmt(s.get('observed')):>9}   {_fmt(s.get('threshold')):>9}"
               f"  {_fmt(s.get('burn_short')):>6}  {_fmt(s.get('burn_long')):>6}"
               f"  {s.get('breaches_total', 0):>8}")
        code = {"breach": "31", "warn": "33"}.get(s.get("status"))
        lines.append(_color(row, code, color and code is not None))

    lines.append("")
    lines.append("rates (short window, from the health rings)")
    rings = health.get("rings") or {}
    rates = health.get("rates") or {}
    for label, fam in sorted(HEADLINE_RATES.items()):
        lines.append(
            f"  {label:<24} {_fmt(rates.get(label)):>10}/s "
            f"|{sparkline(merge_family_points(rings, fam), width)}|")

    lines.append("")
    lines.append("breakers / overload / shed")
    for fam, b in sorted((health.get("breakers") or {}).items()):
        extra = (f" open_s={_fmt(b.get('open_s'))}"
                 if b.get("state") != "closed" else "")
        lines.append(f"  breaker {fam:<8} {b.get('state', '?')}"
                     f" trips={b.get('trips', 0)}{extra}")
    ovl = (metrics.get("overload") or {}).get("families", {})
    for fam, o in sorted(ovl.items()):
        lines.append(
            f"  overload {fam:<7} {o.get('state', '?'):<9} "
            f"backlog={o.get('backlog', 0)}/{o.get('high_wm', '-')} "
            f"peak={o.get('peak_backlog', 0)} "
            f"widen={_fmt(o.get('widen_factor'))}")
        for key, n in sorted((o.get("shed") or {}).items()):
            lines.append(f"    shed {key}: {n}")

    # incidents panel (doc/incidents.md): the black-box recorder's
    # recent bundles, fed from listincidents — trigger, age, size
    if incidents is not None:
        lines.append("")
        rows = incidents.get("incidents") or []
        head = (f"incidents ({incidents.get('count', 0)} bundles, "
                f"{_fmt_bytes(incidents.get('total_bytes', 0))})"
                if incidents.get("enabled")
                else "incidents (recorder not installed)")
        lines.append(_color(head, "31" if rows else "0",
                            color and bool(rows)))
        for row in rows:
            supp = (f" suppressed={row.get('suppressed')}"
                    if row.get("suppressed") else "")
            lines.append(
                f"  {row.get('id', '?'):<24} "
                f"{row.get('trigger', '?'):<16} "
                f"age={_fmt_age(row.get('age_s')):<6} "
                f"{_fmt_bytes(row.get('bytes'))}{supp}")
        if incidents.get("enabled") and not rows:
            lines.append("  (none)")

    # journeys panel (doc/journeys.md): the most recently touched
    # sampled entities with their last hop and e2e latency, plus the
    # rolling tail — fed from getjourney
    if journeys is not None:
        lines.append("")
        summ = journeys.get("summary") or {}
        if not journeys.get("enabled"):
            lines.append("journeys (sampling disabled — set "
                         "LIGHTNING_TPU_JOURNEY_SAMPLE)")
        else:
            lines.append(
                f"journeys (1/{summ.get('sample', '?')} sampled, "
                f"{summ.get('entities', 0)} tracked, "
                f"e2e p99={_fmt(summ.get('e2e_ms_p99'))}ms)")
            for j in journeys.get("journeys") or []:
                last = j["hops"][-1] if j.get("hops") else None
                state = "done" if j.get("done") else "open"
                lines.append(
                    f"  {j.get('kind', '?'):<8} {str(j.get('key')):<20.20} "
                    f"{(last or {}).get('hop', '-'):<11} "
                    f"{(last or {}).get('outcome', '-'):<10} "
                    f"{len(j.get('hops') or [])} hops "
                    f"{_fmt(j.get('e2e_ms'))}ms {state}")
            if not journeys.get("journeys"):
                lines.append("  (none)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/dashboard.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--rpc", required=True,
                    help="daemon unix socket (lightning-rpc)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="redraw period in live mode (seconds)")
    ap.add_argument("--points", type=int, default=40,
                    help="sparkline width (ring points requested)")
    ap.add_argument("--once", action="store_true",
                    help="print one plain-text frame and exit (CI mode)")
    ap.add_argument("--json", action="store_true",
                    help="with --once: dump the raw gethealth report "
                         "instead of the rendered frame")
    args = ap.parse_args(argv)
    if args.interval <= 0:
        ap.error("--interval must be positive")
    if args.points <= 0:
        ap.error("--points must be positive")

    if args.once:
        health, metrics, incidents, journeys = fetch(
            args.rpc, points=args.points)
        if args.json:
            print(json.dumps(health, indent=1, default=str))
        else:
            print(render(health, metrics, incidents, journeys,
                         color=False, width=args.points))
        return 0

    color = sys.stdout.isatty()
    try:
        while True:
            health, metrics, incidents, journeys = fetch(
                args.rpc, points=args.points)
            frame = render(health, metrics, incidents, journeys,
                           color=color, width=args.points)
            # ANSI full redraw: clear + home (stdlib-portable; no
            # curses dependency so --once and CI pipes behave)
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
