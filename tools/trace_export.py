#!/usr/bin/env python
"""Export the daemon's span + dispatch flight rings as Chrome
trace-event JSON (Perfetto-loadable; doc/tracing.md).

The reference ships cln-tracer (contrib/cln-tracer) to turn
common/trace.c's USDT probes into a scrubabble timeline; this CLI is
the same operator tool for our batched pipelines: one lane per
thread/flush loop, flow arrows along correlation ids from each enqueue
span to the prep/dispatch/readback spans it caused, and one synthetic
lane per dispatch family carrying the full DispatchRecords
(obs/flight.py).  Open the output at https://ui.perfetto.dev or
chrome://tracing.

Modes:
  --rpc <unix-socket> [-o trace.json] [--dispatches N]
      Call `gettrace` on a running daemon and write its export.
  --spans spans.jsonl [-o trace.json]
      Export from a span sink file (trace.set_sink(path) JSON lines) —
      the post-mortem path when the daemon is already gone.
  --validate trace.json
      Schema-check an existing export (the fields Perfetto actually
      enforces: ph/ts/dur/pid/tid, flow arrow pairing + binding).
  --selfcheck
      Run a synthetic cross-thread workload in-process, export it, and
      validate both the schema and the corr-id flow connectivity.
      Exit 1 on any problem — wired into tools/run_suite.sh so a
      schema drift fails the suite instead of silently rendering an
      empty timeline in Perfetto.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from obs_snapshot import rpc_call  # noqa: E402  (shared unix-RPC helper)


def export_rpc(rpc_path: str, dispatches: int | None = None) -> dict:
    """gettrace over the daemon's unix JSON-RPC socket."""
    params = {} if dispatches is None else {"dispatches": dispatches}
    return rpc_call(rpc_path, "gettrace", params)


def export_spans_file(path: str) -> dict:
    """Export from a trace.set_sink(path) JSON-lines file."""
    from lightning_tpu.obs import traceexport

    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return traceexport.chrome_trace(records)


def selfcheck() -> list[str]:
    """Synthesize the cross-thread shape the exporter exists for — an
    enqueue span minting a carrier, a worker thread opening the
    prep/dispatch spans with it, a flight record for the dispatch —
    then export and validate.  Returns problems (empty == pass)."""
    from lightning_tpu.obs import flight, traceexport
    from lightning_tpu.utils import trace

    records: list[dict] = []
    trace.add_tap(records.append)
    try:
        with trace.span("selfcheck/enqueue") as enq:
            corr = trace.new_corr()

        def worker():
            with trace.span("selfcheck/prep", corr=corr):
                pass
            with flight.dispatch("verify", corr_ids=(corr.corr_id,),
                                 n_real=3, lanes=8,
                                 shape=(8, 4)) as rec:
                with trace.span("selfcheck/dispatch", corr=corr,
                                dispatch_id=rec["dispatch_id"]):
                    rec["outcome"] = "ok"

        th = threading.Thread(target=worker, name="selfcheck-worker")
        th.start()
        th.join()
    finally:
        trace.remove_tap(records.append)

    flights = flight.recent("verify", 1)
    trace_obj = traceexport.chrome_trace(records, flights)
    errs = traceexport.validate(trace_obj)

    # beyond the schema: the corr chain must actually CONNECT the
    # enqueue span to the cross-thread dispatch span
    flows = [e for e in trace_obj["traceEvents"]
             if e.get("ph") in ("s", "t", "f")
             and e.get("id") == corr.corr_id]
    if len(flows) != 3:
        errs.append(f"corr {corr.corr_id}: want s+t+f hops, got "
                    f"{[e['ph'] for e in flows]}")
    tids = {e["tid"] for e in flows}
    if len(tids) != 2:
        errs.append("corr flow stayed on one thread — cross-thread "
                    "correlation is broken")
    if not any(e["ph"] == "X" and e["name"] == "dispatch/verify"
               for e in trace_obj["traceEvents"]):
        errs.append("flight record missing from the export")
    return errs


def main() -> int:
    p = argparse.ArgumentParser(prog="trace_export")
    p.add_argument("--rpc", help="daemon unix socket (lightning-rpc)")
    p.add_argument("--spans", help="span sink file (JSON lines)")
    p.add_argument("--dispatches", type=int, metavar="N",
                   help="with --rpc: include only the last N flight "
                        "records")
    p.add_argument("--validate", metavar="TRACE_JSON",
                   help="schema-check an existing export and exit")
    p.add_argument("--selfcheck", action="store_true",
                   help="synthetic export + schema/connectivity check")
    p.add_argument("-o", "--out", default="-")
    args = p.parse_args()

    if args.selfcheck:
        errs = selfcheck()
        if errs:
            print("trace_export selfcheck FAILED:")
            for e in errs:
                print(f"  {e}")
            return 1
        print("trace_export selfcheck: export valid, corr flow "
              "connected across threads")
        return 0

    if args.validate:
        from lightning_tpu.obs import traceexport

        with open(args.validate) as f:
            errs = traceexport.validate(json.load(f))
        if errs:
            print(f"{args.validate}: INVALID")
            for e in errs:
                print(f"  {e}")
            return 1
        print(f"{args.validate}: valid Chrome trace-event JSON")
        return 0

    if args.rpc:
        trace_obj = export_rpc(args.rpc, args.dispatches)
    elif args.spans:
        trace_obj = export_spans_file(args.spans)
    else:
        p.error("need --rpc, --spans, --validate, or --selfcheck")

    text = json.dumps(trace_obj, indent=1)
    if args.out == "-":
        print(text)
    else:
        with open(args.out, "w") as f:
            f.write(text)
        n = len(trace_obj.get("traceEvents", []))
        print(f"wrote {args.out} ({n} events) — open at "
              "https://ui.perfetto.dev", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
