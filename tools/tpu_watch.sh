#!/bin/bash
# Watch the axon tunnel; the moment it works, run the measurement session.
# Single-shot: exits after one successful session (or after max wait).
#
# The probe must be a REAL backend init, not a port check: the wedge
# mode observed rounds 4-5 keeps the port accepting while backend init
# hangs forever — a port-only watcher then launches a session that
# burns its probe budget and falls back to a uselessly slow CPU sweep.
# The init probe runs in a throwaway subprocess (a hung init holds the
# in-process backend lock unrecoverably); it never compiles anything,
# so it touches no jax compilation cache.
cd "$(dirname "$0")/.."
LOG=tpu_watch.log
echo "$(date '+%F %T') watcher start" >> "$LOG"
while [ "$SECONDS" -lt 43200 ]; do  # 12h deadline regardless of probe speed
  if timeout 150 \
      python -c "import jax; assert jax.default_backend() != 'cpu'" \
      2>/dev/null; then
    echo "$(date '+%F %T') tunnel UP — starting measurement session" >> "$LOG"
    bash tools/tpu_measure.sh >> "$LOG" 2>&1
    rc=$?
    echo "$(date '+%F %T') measurement session done rc=$rc" >> "$LOG"
    exit 0
  fi
  sleep 30
done
echo "$(date '+%F %T') watcher gave up" >> "$LOG"
