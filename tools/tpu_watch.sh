#!/bin/bash
# Watch the axon tunnel; the moment it opens, run the measurement session.
# Single-shot: exits after one successful session (or after max wait).
cd "$(dirname "$0")/.."
LOG=tpu_watch.log
echo "$(date '+%F %T') watcher start" >> "$LOG"
for i in $(seq 1 960); do  # up to ~12h at 45s
  if timeout 3 bash -c 'echo > /dev/tcp/127.0.0.1/8083' 2>/dev/null; then
    echo "$(date '+%F %T') tunnel UP — starting measurement session" >> "$LOG"
    bash tools/tpu_measure.sh >> "$LOG" 2>&1
    rc=$?
    echo "$(date '+%F %T') measurement session done rc=$rc" >> "$LOG"
    exit 0
  fi
  sleep 45
done
echo "$(date '+%F %T') watcher gave up" >> "$LOG"
