#!/usr/bin/env python
"""Render per-entity journeys (doc/journeys.md) from a running daemon.

The flight ring answers "what did batch #417 do"; `getjourney` answers
"what happened to THIS scid / THIS payment".  This CLI turns those
hop records into an operator timeline — one line per hop with the
queue-wait/service split and the flight-ring dispatch each hop rode —
and can splice the journeys into a Chrome trace-event export whose
corr-ids bind to the daemon's existing Perfetto flow chains.

Modes:
  --rpc <unix-socket> [--scid S | --payment-hash H | --node-id N]
      Call `getjourney` and render the timeline(s).  With no selector,
      the most recent journeys plus the rolling summary.
  --rpc <unix-socket> --trace journeys.json
      Fetch `gettrace` AND `getjourney`, convert each journey hop to a
      synthetic span slice (tid band 1<<29, one track per journey) and
      merge both event lists: Perfetto binds flow events by id, so the
      journey slices hook into the same corr-id arrows as the live
      enqueue/flush spans.  Open at https://ui.perfetto.dev.
  --selfcheck
      Record a synthetic gossip + payment journey in-process, export,
      and validate the schema + the journey/flow splice.  Exit 1 on
      any problem (wired into tools/run_suite.sh).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from obs_snapshot import rpc_call  # noqa: E402  (shared unix-RPC helper)


def _fmt_key(kind: str, key) -> str:
    if kind == "channel":
        from lightning_tpu.gossip.gossmap import scid_str

        return scid_str(int(key))
    s = str(key)
    return s[:16] + "…" if len(s) > 16 else s


def render_journey(j: dict, out=sys.stdout) -> None:
    """One journey as a text timeline, hops offset from the first."""
    state = "done" if j.get("done") else "open"
    head = (f"{j['kind']} {_fmt_key(j['kind'], j['key'])} — "
            f"{len(j['hops'])} hop(s), {j.get('e2e_ms', 0)} ms e2e, "
            f"{state}")
    if j.get("truncated"):
        head += f" ({j['truncated']} hop(s) truncated)"
    print(head, file=out)
    t0 = j["hops"][0]["t_ns"] if j["hops"] else 0
    for h in j["hops"]:
        off_ms = (h["t_ns"] - t0) / 1e6
        line = (f"  +{off_ms:9.3f}ms  {h['hop']:<11} {h['outcome']}"
                f"  (wait {h['wait_ms']}ms, service {h['service_ms']}ms")
        if h.get("dispatch_id") is not None:
            line += f", dispatch #{h['dispatch_id']}"
        if h.get("corr_id") is not None:
            line += f", corr {h['corr_id']}"
        line += ")"
        for k, v in (h.get("attrs") or {}).items():
            line += f" {k}={v}"
        print(line, file=out)


def render_summary(s: dict, out=sys.stdout) -> None:
    print(f"journeys: sample=1/{s['sample']} entities={s['entities']} "
          f"finished={s['finished']} evicted={s['evicted']} "
          f"e2e p50={s['e2e_ms_p50']} p99={s['e2e_ms_p99']} ms",
          file=out)
    for name, row in sorted(s.get("by_hop", {}).items()):
        print(f"  {name:<11} n={row['count']:<5} "
              f"wait p50/p99 {row['wait_ms_p50']}/{row['wait_ms_p99']} ms"
              f"  service p50/p99 {row['service_ms_p50']}/"
              f"{row['service_ms_p99']} ms", file=out)


def journeys_to_span_records(journeys: list[dict]) -> list[dict]:
    """Hop records → span-record dicts chrome_trace() understands.
    Client-side twin of obs/journey.journey_span_records (which reads
    the in-process table; this one works off the RPC payload)."""
    from lightning_tpu.obs.journey import JOURNEY_TID_BASE

    out = []
    for j in journeys:
        tid = JOURNEY_TID_BASE + j["seq"]
        for i, h in enumerate(j["hops"]):
            busy_ns = int((h["wait_ms"] + h["service_ms"]) * 1e6)
            out.append({
                "name": "journey/" + h["hop"],
                "start_ns": h["t_ns"] - max(busy_ns, 1_000),
                "duration_ns": max(busy_ns, 1_000),
                "tid": tid,
                "thread": "journey:" + j["kind"],
                "span_id": -(j["seq"] * 1_000 + i),
                "corr_ids": ([h["corr_id"]]
                             if h.get("corr_id") is not None else []),
                "attributes": {
                    "kind": j["kind"], "key": str(j["key"]),
                    "outcome": h["outcome"],
                    "dispatch_id": h.get("dispatch_id"),
                },
            })
    return out


def splice_trace(trace_obj: dict, journeys: list[dict]) -> dict:
    """Merge journey slices into a gettrace export.  Perfetto binds
    flow events ('s'/'t'/'f') by id across the whole file, so the
    journey events' corr-ids chain into the daemon's existing arrows."""
    from lightning_tpu.obs import traceexport

    jtrace = traceexport.chrome_trace(journeys_to_span_records(journeys))
    merged = dict(trace_obj)
    merged["traceEvents"] = (list(trace_obj.get("traceEvents", []))
                             + jtrace["traceEvents"])
    return merged


def selfcheck() -> list[str]:
    """Synthesize both journey shapes, export, validate.  Returns
    problems (empty == pass)."""
    os.environ["LIGHTNING_TPU_JOURNEY_SAMPLE"] = "1"
    from lightning_tpu.obs import journey, traceexport
    from lightning_tpu.utils import trace

    journey.reset_for_tests()
    errs: list[str] = []

    corr = trace.new_corr()
    journey.hop("recv", "channel", 0x123, outcome="ok")
    journey.hop("admit", "channel", 0x123, corr_id=corr.corr_id)
    journey.hop("verify", "channel", 0x123, wait_s=0.004,
                service_s=0.002, dispatch_id=1, corr_id=corr.corr_id)
    journey.hop("fold", "channel", 0x123, service_s=0.001)
    journey.hop("planes", "channel", 0x123, outcome="patched")
    journey.hop("enqueue", "payment", b"\x01" * 32)
    journey.hop("mcf_flush", "payment", b"\x01" * 32, wait_s=0.003,
                service_s=0.008, dispatch_id=2)
    journey.hop("parts", "payment", b"\x01" * 32, parts=2)
    journey.hop("htlc_settle", "payment", b"\x01" * 32)

    js = journey.recent()
    if len(js) != 2:
        errs.append(f"want 2 journeys, got {len(js)}")
    for j in js:
        render_journey(j)
        ts = [h["t_ns"] for h in j["hops"]]
        if ts != sorted(ts):
            errs.append(f"{j['kind']} {j['key']}: non-monotonic hops")
    render_summary(journey.summary())

    trace_obj = splice_trace({"traceEvents": [],
                              "displayTimeUnit": "ms"}, js)
    errs += traceexport.validate(trace_obj)
    ev = trace_obj["traceEvents"]
    if not any(e.get("ph") == "X"
               and str(e.get("name", "")).startswith("journey/")
               for e in ev):
        errs.append("no journey slices in the export")
    if not any(e.get("ph") in ("s", "t", "f")
               and e.get("id") == corr.corr_id for e in ev):
        errs.append("journey corr-id produced no flow events — the "
                    "Perfetto splice is broken")
    journey.reset_for_tests()
    return errs


def main() -> int:
    p = argparse.ArgumentParser(prog="journey")
    p.add_argument("--rpc", help="daemon unix socket (lightning-rpc)")
    p.add_argument("--scid", help="one channel's journey (BLOCKxTXxOUT)")
    p.add_argument("--payment-hash", help="one payment's journey (hex)")
    p.add_argument("--node-id", help="one node's journey (hex)")
    p.add_argument("--limit", type=int, default=20,
                   help="recent journeys to fetch (no selector)")
    p.add_argument("--trace", metavar="OUT_JSON",
                   help="write a Perfetto export splicing journeys "
                        "into the daemon's gettrace flow chains")
    p.add_argument("--json", action="store_true",
                   help="dump the raw getjourney payload")
    p.add_argument("--selfcheck", action="store_true",
                   help="synthetic journeys + export/splice validation")
    args = p.parse_args()

    if args.selfcheck:
        errs = selfcheck()
        if errs:
            print("journey selfcheck FAILED:")
            for e in errs:
                print(f"  {e}")
            return 1
        print("journey selfcheck: timelines render, export valid, "
              "corr flows spliced")
        return 0

    if not args.rpc:
        p.error("need --rpc or --selfcheck")
    params: dict = {}
    if args.scid:
        params["scid"] = args.scid
    elif args.payment_hash:
        params["payment_hash"] = args.payment_hash
    elif args.node_id:
        params["node_id"] = args.node_id
    else:
        params["limit"] = args.limit
    res = rpc_call(args.rpc, "getjourney", params)

    if args.json:
        print(json.dumps(res, indent=1))
        return 0

    if args.trace:
        trace_obj = rpc_call(args.rpc, "gettrace", {})
        merged = splice_trace(trace_obj, res.get("journeys", []))
        with open(args.trace, "w") as f:
            json.dump(merged, f, indent=1)
        n = len(merged["traceEvents"])
        print(f"wrote {args.trace} ({n} events) — open at "
              "https://ui.perfetto.dev", file=sys.stderr)
        return 0

    if not res.get("enabled"):
        print("journey sampling disabled "
              "(set LIGHTNING_TPU_JOURNEY_SAMPLE)")
    journeys = res.get("journeys", [])
    if not journeys:
        print("no journeys recorded for that selector" if params
              and "limit" not in params else "no journeys recorded")
    for j in journeys:
        render_journey(j)
    if "summary" in res:
        render_summary(res["summary"])
    return 0


if __name__ == "__main__":
    sys.exit(main())
