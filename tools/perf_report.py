#!/usr/bin/env python
"""Perf observatory CLI (doc/perf.md): stage attribution + the
bench-regression gate.

Subcommands / modes:

  --rpc <unix-socket> [--family F] [--kernel-rate R]
      Call `getperf` on a running daemon and render the report.  The
      kernel roofline defaults to the best measured kernel rate in
      bench_last_tpu.json (sweep_best, falling back to kernel_only).

  --capture snapshot.json
      Render the report OFFLINE from a saved obs_snapshot capture that
      includes a dispatch_log (capture --dispatches N).

  --local
      Attribute THIS process's registry/flight rings (only useful
      under -c/import after driving a workload — the live-daemon
      equivalent of `obs_snapshot capture --local`).

  --selfcheck [--inflate STAGE]
      Synthetic pipeline proof (the run_suite.sh perf-smoke pass):
      drives the REAL flight ring + clntpu_replay_* counters with a
      hand-built workload whose STAGE (default dispatch) is
      deliberately inflated, then asserts the attribution model names
      exactly that stage as the bottleneck, reproduces the
      hand-computed speedup-if-removed, and reconciles ring vs counter
      sums within the stated epsilon.  Jax-free and fast.

  --compare [--history BENCH_HISTORY.jsonl] [--tolerance 0.10]
      The regression gate: for every metric in the bench trajectory,
      compare the newest measurement against the most recent prior
      baseline of the same platform class (hardware compares against
      the last REAL-hardware baseline, never against a cpu-fallback)
      and exit non-zero when throughput dropped — or kernel
      ms-per-call rose — beyond the noise tolerance.  Replayed
      records (measurement "replayed:*") are skipped as candidates:
      they carry no new measurement.

All output is deterministic text (or --json); exit codes: 0 ok,
1 selfcheck/regression failure, 2 usage/data error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the gate's stated noise tolerance: BENCH_NOTES.md rounds show ±5-8%
# run-to-run wobble on the tunneled backend; 10% keeps the gate quiet
# on noise and loud on real regressions
DEFAULT_TOLERANCE = 0.10


def load_kernel_rate() -> float | None:
    """The best measured kernel-alone rate (sigs/s) from
    bench_last_tpu.json — the roofline the e2e pipeline is compared
    against (sweep_best is the tuned number; kernel_only the last
    e2e-round measurement)."""
    try:
        with open(os.path.join(REPO, "bench_last_tpu.json")) as f:
            last = json.load(f)
    except Exception:
        return None
    for key in ("sweep_best", "kernel_only"):
        thr = (last.get(key) or {}).get("throughput")
        if thr:
            return float(thr)
    return None


# ---------------------------------------------------------------------------
# Rendering


def _fmt_s(v) -> str:
    return "-" if v is None else f"{v:.4f}s"


def render(report: dict) -> str:
    lines = []
    kr = report.get("kernel_rate")
    lines.append(f"# perf report (epsilon {report.get('epsilon')}"
                 + (f", kernel roofline {kr:.0f}/s" if kr else "") + ")")
    for fam, sec in sorted(report.get("families", {}).items()):
        lines.append("")
        occ = sec.get("occupancy")
        lines.append(
            f"family {fam}: {sec['dispatches']} dispatches, "
            f"{sec['items']} items"
            + (f", occupancy {occ:.2f}" if occ is not None else "")
            + f", pipeline {sec['pipeline']}")
        st = sec["stages"]
        lines.append(
            "  stages  queue_wait " + _fmt_s(st["queue_wait_s"])
            + "  prep " + _fmt_s(st["prep_s"])
            + "  stall " + _fmt_s(st["stall_s"])
            + "  dispatch " + _fmt_s(st["dispatch_s"])
            + "  readback " + _fmt_s(st["readback_s"]))
        ov = sec.get("overlap_ratio")
        lines.append(
            f"  critical path {_fmt_s(sec['critical_path_s'])}"
            f" ({'+'.join(sec['critical_path'])})"
            + (f", overlap {ov:.1%}" if ov is not None else "")
            + f", idle {_fmt_s(sec['idle_s'])}")
        bn = sec.get("bottleneck")
        if bn:
            sp = sec["speedup_if_removed"].get(bn)
            lines.append(
                f"  bottleneck: {bn}"
                + (f" — {sp}x e2e if removed" if sp else
                   " — the entire critical path"))
        thr = sec.get("throughput_per_s")
        if thr:
            lines.append(f"  throughput {thr:.1f} items/s")
        roof = sec.get("roofline")
        if roof:
            lines.append(
                f"  roofline: {roof['fraction_of_roofline']:.1%} of "
                f"kernel rate ({roof['achieved_items_per_s']:.0f} vs "
                f"{roof['kernel_items_per_s']:.0f}/s, gap "
                f"{roof['gap_x']}x)")
        tr = sec.get("transfer", {})
        if tr.get("h2d_bytes") or tr.get("d2h_bytes"):
            lines.append(
                f"  transfer: h2d {tr['h2d_bytes']} B, "
                f"d2h {tr['d2h_bytes']} B")
        recon = sec.get("reconciliation")
        if recon and recon.get("checked"):
            lines.append(
                f"  reconciliation: max rel err "
                f"{recon['max_rel_err']:.4f} "
                + ("OK" if recon["ok"] else
                   "FAIL (unattributed wall time beyond epsilon)"))
    rt = report.get("retraces", {})
    lines.append("")
    lines.append(
        f"retraces: {rt.get('total', 0)} "
        f"(detector {'armed' if rt.get('armed') else 'not armed'})")
    for ev in rt.get("recent", [])[-5:]:
        lines.append(f"  RETRACE {ev.get('program')} {ev.get('key')}")
    dm = report.get("device_memory") or {}
    for dev, stats in sorted(dm.items()):
        lines.append(f"device {dev}: " + ", ".join(
            f"{k}={v}" for k, v in sorted(stats.items())))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Selfcheck: the synthetic inflated-stage proof


# per-dispatch stage costs (ms); the inflated stage gets 12x
SELF_BASE_MS = {"queue_wait": 4.0, "dispatch": 3.0, "readback": 2.0}
SELF_HIDDEN_PREP_MS = 6.0
SELF_INFLATE = 12.0
SELF_N = 40


def run_selfcheck(inflate: str = "dispatch", as_json: bool = False) -> int:
    """Drive the real flight ring + replay counters with a synthetic
    verify workload whose `inflate` stage is 12x too slow, then hold
    the attribution model to its contract (doc/perf.md):

      1. it names exactly that stage as the bottleneck;
      2. its speedup-if-removed equals the hand-computed Amdahl value;
      3. per-stage totals reconcile with the flight-ring sums AND the
         clntpu_replay_* counter sums within the stated epsilon (no
         unattributed wall time).

    `inflate` is a critical-path stage name: the visible-prep stall is
    spelled "stall" (driven by inflating the producer-queue wait)."""
    from lightning_tpu.obs import attribution, families, flight

    if inflate not in ("stall", "dispatch", "readback"):
        print(f"--inflate must be stall|dispatch|readback, "
              f"got {inflate!r}", file=sys.stderr)
        return 2
    flight.reset_for_tests()
    attribution.reset_for_tests()

    ms = dict(SELF_BASE_MS)
    key = "queue_wait" if inflate == "stall" else inflate
    ms[key] *= SELF_INFLATE
    # prep = what the producer thread burned: the visible share is the
    # queue wait (stall), the rest was hidden behind device compute
    prep_ms = ms["queue_wait"] + SELF_HIDDEN_PREP_MS
    items = 64

    for _ in range(SELF_N):
        rec = flight.begin("verify", shape=(items, 8), n_real=items,
                           lanes=items, queue_wait_ms=ms["queue_wait"],
                           prep_ms=prep_ms, breaker_state="closed")
        rec["readback_ms"] = ms["readback"]
        rec["h2d_bytes"] = 37_000
        rec["d2h_bytes"] = items
        flight.finish(rec, "ok", dispatch_ms=ms["dispatch"])
    # the counters the live pipeline meters (gossip/verify._run_pipeline)
    families.REPLAY_PREP.inc(SELF_N * prep_ms / 1e3)
    families.REPLAY_STALL.inc(SELF_N * ms["queue_wait"] / 1e3)
    families.REPLAY_DISPATCH.inc(SELF_N * ms["dispatch"] / 1e3)
    families.REPLAY_READBACK.inc(SELF_N * ms["readback"] / 1e3)

    report = attribution.report_local(kernel_rate=200_000.0)
    fam = report["families"]["verify"]

    crit_ms = ms["queue_wait"] + ms["dispatch"] + ms["readback"]
    stage_ms = ms[key]
    expected_speedup = round(crit_ms / (crit_ms - stage_ms), 4)
    expected_crit_s = round(SELF_N * crit_ms / 1e3, 6)

    failures = []

    def check(name: str, ok: bool, detail: str) -> None:
        print(f"{'PASS' if ok else 'FAIL'}: {name} ({detail})")
        if not ok:
            failures.append(name)

    check("bottleneck named", fam["bottleneck"] == inflate,
          f"model says {fam['bottleneck']!r}, inflated {inflate!r}")
    got_sp = fam["speedup_if_removed"].get(inflate)
    check("speedup-if-removed matches hand-computed value",
          got_sp is not None and abs(got_sp - expected_speedup) < 1e-3,
          f"model {got_sp} vs hand {expected_speedup}")
    check("critical path total attributed",
          abs(fam["critical_path_s"] - expected_crit_s)
          <= attribution.EPSILON * expected_crit_s,
          f"model {fam['critical_path_s']}s vs hand {expected_crit_s}s")
    recon = fam.get("reconciliation", {})
    check("ring vs clntpu_replay_* reconciliation",
          bool(recon.get("checked")) and bool(recon.get("ok")),
          f"max rel err {recon.get('max_rel_err')} "
          f"<= epsilon {recon.get('epsilon')}")
    check("no unattributed wall time",
          (recon.get("unattributed_s") or 0.0)
          <= attribution.EPSILON * expected_crit_s,
          f"unattributed {recon.get('unattributed_s')}s")
    check("overlap ratio reflects hidden prep",
          fam["overlap_ratio"] is not None
          and abs(fam["overlap_ratio"]
                  - (1 - ms["queue_wait"] / prep_ms)) < 1e-3,
          f"model {fam['overlap_ratio']}")
    if as_json:
        print(json.dumps(report, indent=1))
    if failures:
        print(f"perf selfcheck FAILED: {', '.join(failures)}")
        return 1
    print("perf selfcheck ok")
    return 0


# ---------------------------------------------------------------------------
# The regression gate


# how many prior same-class candidates the gate scans for its
# baseline: comparing only against the IMMEDIATELY previous record
# would let a regression that slipped into the history become the next
# baseline (the gate would fire exactly once, and sub-tolerance drift
# could compound forever) — gating against the best of the recent
# window keeps the bar where the last good measurement put it
BASELINE_WINDOW = 5


def _platform_class(rec: dict) -> str:
    p = rec.get("platform")
    if not p:
        # pre-contract legacy seeds may lack the key entirely; they
        # must never serve as (or gate against) a hardware baseline
        return "unknown"
    return "cpu" if p in ("cpu", "cpu-fallback") else "hardware"


def _is_candidate(rec: dict) -> bool:
    if "error" in rec or not isinstance(rec.get("value"), (int, float)):
        return False
    return not str(rec.get("measurement", "live")).startswith("replayed")


def compare_records(base: dict, cand: dict, tolerance: float) -> list[str]:
    """Regressions of `cand` against `base` beyond the tolerance
    (empty = clean).  Throughput-shaped values regress downward;
    latency-shaped values regress upward."""
    regressions = []
    bv, cv = base.get("value"), cand.get("value")
    if bv and cv is not None and cv < bv * (1 - tolerance):
        regressions.append(
            f"throughput {cv:.1f} < baseline {bv:.1f} "
            f"(-{(1 - cv / bv):.1%}, tolerance {tolerance:.0%})")
    bk = base.get("kernel_only") or {}
    ck = cand.get("kernel_only") or {}
    bkt, ckt = bk.get("throughput"), ck.get("throughput")
    if bkt and ckt and ckt < bkt * (1 - tolerance):
        regressions.append(
            f"kernel throughput {ckt:.1f} < baseline {bkt:.1f} "
            f"(-{(1 - ckt / bkt):.1%})")
    bkm, ckm = bk.get("ms_per_call"), ck.get("ms_per_call")
    if bkm and ckm and ckm > bkm * (1 + tolerance):
        regressions.append(
            f"kernel ms/call {ckm:.2f} > baseline {bkm:.2f} "
            f"(+{(ckm / bkm - 1):.1%})")
    # stage-latency gate: rounds run with --metrics embed the
    # clntpu_replay_* stage sums; compare per-item stage cost
    for stage in ("prep", "prep_stall", "dispatch", "readback"):
        name = f"clntpu_replay_{stage}_seconds_total"
        bs = _stage_per_item(base, name)
        cs = _stage_per_item(cand, name)
        if bs and cs and cs > bs * (1 + tolerance):
            regressions.append(
                f"stage {stage} {cs * 1e6:.2f}us/item > baseline "
                f"{bs * 1e6:.2f}us/item (+{(cs / bs - 1):.1%})")
    return regressions


def _stage_per_item(rec: dict, counter: str) -> float | None:
    fam = (rec.get("metrics") or {}).get(counter)
    n = rec.get("n_sigs")
    if not fam or not n:
        return None
    total = sum(s.get("delta", s.get("value", 0.0))
                for s in fam.get("samples", ()))
    return total / n if total else None


def run_compare(history_path: str, tolerance: float,
                metric: str | None = None) -> int:
    import bench

    try:
        entries = bench.load_history(history_path)
    except (OSError, ValueError) as e:
        print(f"compare: {e}", file=sys.stderr)
        return 2
    by_metric: dict[str, list[dict]] = {}
    for e in entries:
        rec = e["record"]
        m = rec.get("metric")
        if m and (metric is None or m == metric):
            by_metric.setdefault(m, []).append(rec)
    if metric is not None and metric not in by_metric:
        print(f"compare: no history for metric {metric!r}",
              file=sys.stderr)
        return 2
    any_regression = False
    for m, recs in sorted(by_metric.items()):
        cands = [r for r in recs if _is_candidate(r)]
        if not cands:
            print(f"{m}: no measurable candidate (errors/replays only)")
            continue
        cand = cands[-1]
        cls = _platform_class(cand)
        baselines = [r for r in cands[:-1] if _platform_class(r) == cls]
        if not baselines:
            print(f"{m}: no prior {cls} baseline — nothing to gate "
                  f"(candidate {cand.get('value')})")
            continue
        base = max(baselines[-BASELINE_WINDOW:],
                   key=lambda r: r.get("value") or 0.0)
        regs = compare_records(base, cand, tolerance)
        if regs:
            any_regression = True
            print(f"{m} [{cls}]: REGRESSION vs baseline "
                  f"{base.get('measured_at', '?')}")
            for r in regs:
                print(f"  {r}")
        else:
            print(f"{m} [{cls}]: ok ({cand.get('value')} vs baseline "
                  f"{base.get('value')}, tolerance {tolerance:.0%})")
    return 1 if any_regression else 0


# ---------------------------------------------------------------------------


def main() -> int:
    p = argparse.ArgumentParser(prog="perf_report")
    p.add_argument("--rpc", help="daemon unix socket (lightning-rpc)")
    p.add_argument("--capture", help="saved obs_snapshot capture "
                                     "(with --dispatches) to attribute")
    p.add_argument("--local", action="store_true",
                   help="attribute this process's registry")
    p.add_argument("--selfcheck", action="store_true",
                   help="synthetic inflated-stage model proof")
    p.add_argument("--inflate", default="dispatch",
                   help="selfcheck: which critical stage to inflate "
                        "(stall|dispatch|readback)")
    p.add_argument("--compare", action="store_true",
                   help="bench-regression gate over the history")
    p.add_argument("--history", default=None,
                   help="history path (default: repo "
                        "BENCH_HISTORY.jsonl / $BENCH_HISTORY)")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                   help="relative noise tolerance for --compare "
                        f"(default {DEFAULT_TOLERANCE})")
    p.add_argument("--metric", default=None,
                   help="--compare: gate only this metric")
    p.add_argument("--family", default=None,
                   help="--rpc: restrict to one dispatch family")
    p.add_argument("--kernel-rate", type=float, default=None,
                   help="roofline items/s (default: bench_last_tpu.json)")
    p.add_argument("--json", action="store_true",
                   help="emit the raw report JSON instead of text")
    args = p.parse_args()

    if args.selfcheck:
        return run_selfcheck(args.inflate, as_json=args.json)
    if args.compare:
        import bench

        return run_compare(args.history or bench.HISTORY_PATH,
                           args.tolerance, args.metric)

    kernel_rate = args.kernel_rate or load_kernel_rate()
    if args.rpc:
        from tools.obs_snapshot import rpc_call

        params: dict = {}
        if args.family:
            params["family"] = args.family
        if kernel_rate:
            params["kernel_rate"] = kernel_rate
        report = rpc_call(args.rpc, "getperf", params)
    elif args.capture:
        from lightning_tpu.obs import attribution

        with open(args.capture) as f:
            snap = json.load(f)
        report = attribution.report_from_snapshot(
            snap, kernel_rate=kernel_rate)
    elif args.local:
        from lightning_tpu.obs import attribution

        report = attribution.report_local(kernel_rate=kernel_rate)
    else:
        p.error("need one of --rpc/--capture/--local/"
                "--selfcheck/--compare")
    print(json.dumps(report, indent=1) if args.json else render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
