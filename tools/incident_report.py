#!/usr/bin/env python
"""Postmortem renderer for incident bundles (doc/incidents.md).

The black-box recorder (lightning_tpu/obs/incident.py) freezes a
correlated forensic bundle when a trigger fires; this CLI turns a
bundle back into the perf-report/health vocabulary an operator already
reads:

  incident_report.py BUNDLE_DIR           render one bundle
  incident_report.py --rpc SOCK [--id I]  render over a live daemon's
                                          getincident RPC (default:
                                          the newest bundle)
  incident_report.py --diff A B           what changed between two
                                          bundles: trigger/manifest
                                          deltas + the metrics diff
                                          (obs_snapshot vocabulary)
  incident_report.py --validate DIR       schema/consistency gate:
                                          manifest fields, artifact
                                          presence+sizes, Chrome-trace
                                          validation, flight-ring <->
                                          clntpu_dispatches_total
                                          reconciliation
  incident_report.py --selfcheck          jax-free synthetic drive for
                                          tools/run_suite.sh: a
                                          fault-shaped mini workload
                                          must produce exactly one
                                          bundle that passes --validate
                                          and renders

``--json`` dumps the structured report instead of the text frame.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _TOOLS)

from lightning_tpu.obs import incident as _incident  # noqa: E402

# flight-ring lifetime counts vs clntpu_dispatches_total: both are
# lifetime totals frozen milliseconds apart during capture (the ring
# append lands before the counter inc), so a busy daemon may be off by
# the dispatches in flight at freeze time
_RECONCILE_ABS = 3
_RECONCILE_REL = 0.01


# ---------------------------------------------------------------------------
# loading


def load_bundle(path: str) -> dict:
    """Bundle dir -> {"manifest": ..., "<artifact>": ...} for whatever
    is present on disk."""
    out: dict = {"_path": os.path.abspath(path)}
    man = os.path.join(path, "manifest.json")
    with open(man, encoding="utf8") as f:
        out["manifest"] = json.load(f)
    for name in _incident.ARTIFACTS:
        p = os.path.join(path, name)
        if os.path.isfile(p):
            with open(p, encoding="utf8") as f:
                out[name] = json.load(f)
    return out


def load_bundle_rpc(rpc_path: str, incident_id: str | None = None) -> dict:
    """The same bundle shape fetched over a live daemon's
    listincidents/getincident RPCs."""
    from obs_snapshot import rpc_call

    if incident_id is None:
        listing = rpc_call(rpc_path, "listincidents", {"limit": 1})
        rows = listing.get("incidents") or []
        if not rows:
            raise SystemExit("no incident bundles on this daemon")
        incident_id = rows[0]["id"]
    got = rpc_call(rpc_path, "getincident", {"id": incident_id})
    out: dict = {"_path": f"rpc:{incident_id}",
                 "manifest": got["manifest"]}
    for name in got["manifest"].get("artifacts", {}):
        try:
            art = rpc_call(rpc_path, "getincident",
                           {"id": incident_id, "artifact": name})
            out[name] = art["artifact"]["content"]
        except SystemExit:
            pass
    return out


# ---------------------------------------------------------------------------
# rendering


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _age(ts) -> str:
    if not ts:
        return "-"
    s = max(0.0, time.time() - ts)
    if s < 120:
        return f"{s:.0f}s"
    if s < 7200:
        return f"{s / 60:.0f}m"
    return f"{s / 3600:.1f}h"


def _flight_digest(flight_art: dict) -> dict:
    """Per-family outcome histogram + worst dispatch off the embedded
    ring."""
    fams: dict = {}
    for rec in flight_art.get("records", ()):
        fam = rec.get("family", "?")
        d = fams.setdefault(fam, {"ring": 0, "outcomes": {},
                                  "faults": 0, "quarantined": 0,
                                  "slowest_ms": 0.0, "slowest_id": None})
        d["ring"] += 1
        out = rec.get("outcome") or "?"
        d["outcomes"][out] = d["outcomes"].get(out, 0) + 1
        if rec.get("faults"):
            d["faults"] += 1
        d["quarantined"] += rec.get("quarantined") or 0
        total_ms = ((rec.get("queue_wait_ms") or 0)
                    + (rec.get("prep_ms") or 0)
                    + (rec.get("dispatch_ms") or 0))
        if total_ms > d["slowest_ms"]:
            d["slowest_ms"] = round(total_ms, 1)
            d["slowest_id"] = rec.get("dispatch_id")
    for fam, summ in (flight_art.get("summary", {})
                      .get("families", {})).items():
        fams.setdefault(fam, {"ring": 0, "outcomes": {}, "faults": 0,
                              "quarantined": 0, "slowest_ms": 0.0,
                              "slowest_id": None})["total"] = \
            summ.get("total")
    return fams


def build_report(bundle: dict) -> dict:
    """The structured report (--json form; render() draws it)."""
    man = bundle.get("manifest", {})
    rep: dict = {
        "id": man.get("id"),
        "trigger": man.get("trigger"),
        "correlation": man.get("correlation"),
        "episode": man.get("episode"),
        "history": man.get("history"),
        "suppressed": man.get("suppressed"),
        "captured_at": man.get("captured_at"),
        "recaptures": man.get("recaptures"),
        "capture_errors": man.get("capture_errors"),
        "artifacts": man.get("artifacts"),
        "trace_problems": man.get("trace_problems"),
    }
    health = bundle.get("health.json")
    if health:
        rep["health"] = {
            "state": health.get("state"),
            "breached": health.get("breached"),
            "slos": {n: {"status": s.get("status"),
                         "observed": s.get("observed"),
                         "threshold": s.get("threshold"),
                         "burn_short": s.get("burn_short"),
                         "burn_long": s.get("burn_long"),
                         "breaches_total": s.get("breaches_total")}
                     for n, s in (health.get("slos") or {}).items()},
            "rates": health.get("rates"),
        }
    res = bundle.get("resilience.json")
    if res:
        rep["breakers"] = (res.get("resilience") or {}).get("breakers")
        rep["faults_armed"] = (res.get("resilience") or {}).get(
            "faults_armed")
        rep["overload"] = {
            f: {"state": o.get("state"),
                "backlog": o.get("backlog"),
                "peak_backlog": o.get("peak_backlog"),
                "shed": o.get("shed")}
            for f, o in ((res.get("overload") or {})
                         .get("families") or {}).items()}
    flight_art = bundle.get("flight.json")
    if flight_art:
        rep["flight"] = _flight_digest(flight_art)
        # the perf-observatory vocabulary over the FROZEN rings — the
        # same attribution model getperf/perf_report serve live
        metrics = (bundle.get("metrics.json") or {}).get("metrics", {})
        try:
            from lightning_tpu.obs import attribution

            perf = attribution.report_from_snapshot({
                "metrics": metrics,
                "dispatch_log": flight_art.get("records", ()),
                "dispatches": flight_art.get("summary", {}),
            })
            rep["perf"] = attribution.compact(perf)
        except Exception as e:
            rep["perf_error"] = f"{type(e).__name__}: {e}"
    knobs = bundle.get("knobs.json")
    if knobs:
        rep["knobs_set"] = {k: v.get("value")
                            for k, v in sorted(knobs.items())
                            if v.get("source") == "env"}
    trace_art = bundle.get("trace.json")
    if trace_art:
        rep["trace_events"] = len(trace_art.get("traceEvents") or ())
    return rep


def render(bundle: dict) -> str:
    rep = build_report(bundle)
    trig = rep.get("trigger") or {}
    lines = [
        f"incident {rep.get('id')}  trigger={trig.get('class')}"
        f"  severity={trig.get('severity')}"
        f"  captured={_age(rep.get('captured_at'))} ago"
        f"  recaptures={rep.get('recaptures', 0)}",
        f"  correlation: {json.dumps(rep.get('correlation') or {})}",
    ]
    hist = rep.get("history") or []
    if hist:
        lines.append("  history: " + " -> ".join(
            f"{h.get('class')}({h.get('action')})" for h in hist))
    supp = rep.get("suppressed") or {}
    if supp:
        lines.append("  suppressed in cooldown: " + ", ".join(
            f"{k}={v}" for k, v in sorted(supp.items())))
    errs = rep.get("capture_errors") or {}
    if errs:
        lines.append("  CAPTURE ERRORS: " + ", ".join(
            f"{k}: {v}" for k, v in sorted(errs.items())))
    h = rep.get("health")
    if h:
        lines.append("")
        lines.append(f"health at capture: {h.get('state')}"
                     + (f"  breached={','.join(h.get('breached') or [])}"
                        if h.get("breached") else ""))
        lines.append("  SLO                 status   observed   "
                     "threshold  burn_s  burn_l  breaches")
        for name, s in sorted((h.get("slos") or {}).items()):
            lines.append(
                f"    {name:<17} {s.get('status', '?'):<8} "
                f"{_fmt(s.get('observed')):>9}  "
                f"{_fmt(s.get('threshold')):>9}  "
                f"{_fmt(s.get('burn_short')):>6}  "
                f"{_fmt(s.get('burn_long')):>6}  "
                f"{s.get('breaches_total', 0):>8}")
    brk = rep.get("breakers")
    if brk:
        lines.append("")
        lines.append("breakers: " + "  ".join(
            f"{f}={b.get('state')}(trips {b.get('trips', 0)})"
            for f, b in sorted(brk.items())))
    if rep.get("faults_armed"):
        lines.append("faults armed: " + ",".join(rep["faults_armed"]))
    ovl = rep.get("overload")
    if ovl:
        for fam, o in sorted(ovl.items()):
            shed = o.get("shed") or {}
            lines.append(
                f"overload {fam:<7} {o.get('state', '?'):<9} "
                f"backlog={o.get('backlog', 0)} "
                f"peak={o.get('peak_backlog', 0)}"
                + (f" shed={sum(shed.values())}" if shed else ""))
    fl = rep.get("flight")
    if fl:
        lines.append("")
        lines.append("flight rings (frozen)")
        for fam, d in sorted(fl.items()):
            outcomes = ",".join(f"{k}:{v}" for k, v in
                                sorted(d.get("outcomes", {}).items()))
            lines.append(
                f"  {fam:<8} ring={d.get('ring', 0)}"
                f"/{_fmt(d.get('total'))} lifetime  [{outcomes}]"
                + (f" faults={d['faults']}" if d.get("faults") else "")
                + (f" quarantined={d['quarantined']}"
                   if d.get("quarantined") else "")
                + (f" slowest={d['slowest_ms']}ms"
                   f"(id {d['slowest_id']})"
                   if d.get("slowest_id") else ""))
    perf = rep.get("perf")
    if perf:
        lines.append("")
        lines.append("perf attribution (frozen rings; doc/perf.md)")
        for fam, row in sorted((perf.get("families") or {}).items()):
            lines.append(
                f"  {fam:<8} bottleneck={row.get('bottleneck')}"
                f"  critical_path={_fmt(row.get('critical_path_s'))}s"
                f"  overlap={_fmt(row.get('overlap_ratio'))}")
        if perf.get("retraces"):
            lines.append(f"  retraces: {perf['retraces']}")
    lines.append("")
    lines.append(
        f"trace: {rep.get('trace_events', 0)} events, "
        f"{rep.get('trace_problems') if rep.get('trace_problems') is not None else '?'} "
        "validation problems")
    knobs = rep.get("knobs_set")
    if knobs:
        lines.append("knobs set via env: " + ", ".join(
            f"{k}={v}" for k, v in knobs.items()))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# diff


def diff_bundles(a: dict, b: dict) -> dict:
    """What changed between two bundles: the manifest-level deltas plus
    the metrics diff in tools/obs_snapshot.py's vocabulary."""
    from obs_snapshot import diff_snapshots

    am, bm = a.get("manifest", {}), b.get("manifest", {})
    out: dict = {
        "a": {"id": am.get("id"),
              "trigger": (am.get("trigger") or {}).get("class"),
              "captured_at": am.get("captured_at")},
        "b": {"id": bm.get("id"),
              "trigger": (bm.get("trigger") or {}).get("class"),
              "captured_at": bm.get("captured_at")},
    }
    ah = a.get("health.json") or {}
    bh = b.get("health.json") or {}
    if ah or bh:
        out["health"] = {"a": ah.get("state"), "b": bh.get("state"),
                         "breached_a": ah.get("breached"),
                         "breached_b": bh.get("breached")}
    if "metrics.json" in a and "metrics.json" in b:
        out["metrics_delta"] = diff_snapshots(a["metrics.json"],
                                              b["metrics.json"])
    abrk = ((a.get("resilience.json") or {}).get("resilience")
            or {}).get("breakers") or {}
    bbrk = ((b.get("resilience.json") or {}).get("resilience")
            or {}).get("breakers") or {}
    changed = {f: {"a": abrk.get(f, {}).get("state"),
                   "b": bbrk.get(f, {}).get("state")}
               for f in sorted(set(abrk) | set(bbrk))
               if abrk.get(f, {}).get("state")
               != bbrk.get(f, {}).get("state")}
    if changed:
        out["breakers_changed"] = changed
    return out


# ---------------------------------------------------------------------------
# validation


def validate_bundle(bundle: dict) -> list[str]:
    """Consistency gate over one loaded bundle; returns problems
    (empty == valid)."""
    problems: list[str] = []
    man = bundle.get("manifest")
    if not isinstance(man, dict):
        return ["manifest.json missing or not an object"]
    if man.get("schema") != _incident.MANIFEST_SCHEMA:
        problems.append(f"manifest schema {man.get('schema')!r} != "
                        f"{_incident.MANIFEST_SCHEMA}")
    trig = man.get("trigger") or {}
    if trig.get("class") not in _incident.SEVERITY:
        problems.append(f"unknown trigger class {trig.get('class')!r}")
    for key in ("id", "correlation", "episode", "history",
                "captured_at", "artifacts"):
        if man.get(key) is None:
            problems.append(f"manifest lacks {key!r}")
    if (man.get("correlation") or {}).get("class") != trig.get("class"):
        problems.append("correlation block does not name the trigger "
                        "class")
    # artifact presence + recorded sizes (on-disk bundles only)
    path = bundle.get("_path", "")
    for name, info in (man.get("artifacts") or {}).items():
        if name not in bundle:
            problems.append(f"artifact {name} listed but not loaded")
            continue
        if path and not path.startswith("rpc:"):
            p = os.path.join(path, name)
            if not os.path.isfile(p):
                problems.append(f"artifact {name} missing on disk")
            elif os.path.getsize(p) != info.get("bytes"):
                problems.append(
                    f"artifact {name} size {os.path.getsize(p)} != "
                    f"manifest {info.get('bytes')}")
    # trace export must satisfy the Perfetto-enforced subset
    trace_art = bundle.get("trace.json")
    if trace_art is not None:
        from lightning_tpu.obs import traceexport

        errs = traceexport.validate(trace_art)
        if errs:
            problems.append(
                f"trace.json fails validation ({len(errs)}): {errs[0]}")
    elif "trace.json" in (man.get("artifacts") or {}):
        problems.append("trace.json listed but unreadable")
    # ring<->counter reconciliation: the embedded flight summary's
    # lifetime totals must agree with clntpu_dispatches_total in the
    # frozen metrics snapshot (both lifetime counts, frozen together)
    flight_art = bundle.get("flight.json")
    metrics = (bundle.get("metrics.json") or {}).get("metrics")
    if flight_art is not None and metrics is not None:
        fam_counts: dict[str, float] = {}
        disp = metrics.get("clntpu_dispatches_total") or {}
        for s in disp.get("samples", ()):
            fam = (s.get("labels") or {}).get("family")
            fam_counts[fam] = fam_counts.get(fam, 0.0) \
                + s.get("value", 0.0)
        for fam, summ in (flight_art.get("summary", {})
                          .get("families", {})).items():
            ring_total = summ.get("total", 0)
            counter = fam_counts.get(fam, 0.0)
            tol = max(_RECONCILE_ABS, _RECONCILE_REL * max(ring_total,
                                                           counter))
            if abs(counter - ring_total) > tol:
                problems.append(
                    f"ring<->counter reconciliation failed for {fam}: "
                    f"flight lifetime {ring_total} vs "
                    f"clntpu_dispatches_total {counter}")
            ring_len = summ.get("ring", 0)
            in_ring = sum(1 for r in flight_art.get("records", ())
                          if r.get("family") == fam)
            if in_ring != ring_len:
                problems.append(
                    f"{fam}: summary says ring={ring_len} but "
                    f"{in_ring} records embedded")
    # journeys.json (doc/journeys.md): hop vocabulary, per-journey
    # timestamp monotonicity, and every hop's dispatch_id must resolve
    # into the flight.json records frozen beside it
    journeys_art = bundle.get("journeys.json")
    if journeys_art is not None:
        from lightning_tpu.obs.journey import HOP_SET
        ring_ids = {r.get("dispatch_id")
                    for r in (flight_art or {}).get("records", ())}
        # the flight ring is bounded: a dispatch older than the oldest
        # record still in the ring has been legitimately evicted, not
        # lost — only ids inside the ring's span must resolve
        ring_floor = min(ring_ids) if ring_ids else None
        for j in journeys_art.get("journeys", ()):
            label = f"{j.get('kind')} {j.get('key')}"
            ts = [h.get("t_ns") for h in j.get("hops", ())]
            if ts != sorted(ts):
                problems.append(
                    f"journeys.json: {label} has non-monotonic hops")
            for h in j.get("hops", ()):
                if h.get("hop") not in HOP_SET:
                    problems.append(
                        f"journeys.json: {label} carries unknown hop "
                        f"{h.get('hop')!r}")
                did = h.get("dispatch_id")
                if (did is not None and flight_art is not None
                        and did not in ring_ids
                        and (ring_floor is None or did >= ring_floor)):
                    problems.append(
                        f"journeys.json: {label} hop {h.get('hop')} "
                        f"rode dispatch #{did} which is not in "
                        "flight.json")
    elif "journeys.json" in (man.get("artifacts") or {}):
        problems.append("journeys.json listed but unreadable")
    return problems


# ---------------------------------------------------------------------------
# selfcheck (the run_suite.sh incident-smoke pass)


def selfcheck() -> int:
    """Jax-free synthetic drive: a fault-shaped mini workload against
    the REAL recorder must produce exactly one bundle whose manifest
    names the breaker-open trigger, whose embedded verify ring holds
    the failing dispatch records, and which passes validate_bundle()
    and renders."""
    import tempfile

    from lightning_tpu.obs import flight
    from lightning_tpu.resilience import breaker
    from lightning_tpu.utils import events, trace

    failures: list[str] = []
    tmp = tempfile.mkdtemp(prefix="incident_selfcheck_")
    rec = _incident.IncidentRecorder(tmp, cooldown_s=120,
                                     max_bundles=4)
    rec.start()

    # a mini "daemon": correlated enqueue -> dispatch spans + flight
    # records, two of which eat an injected-fault-shaped failure
    n_ok, n_err = 6, 2
    for i in range(n_ok + n_err):
        failing = i >= n_ok
        with trace.span("ingest/submit"):
            carrier = trace.new_corr()
        with trace.span("verify/dispatch", corr=carrier):
            try:
                with flight.dispatch(
                        "verify", corr_ids=flight.corr_ids([carrier]),
                        shape=(64, 8), n_real=50 + i, lanes=64) as drec:
                    if failing:
                        drec["faults"].append("dispatch:verify")
                        raise RuntimeError("selfcheck injected failure")
            except RuntimeError:
                pass
    # quarantine first (low severity), then the breaker opens: ONE
    # bundle, escalated to breaker_open, quarantine in its history
    events.emit("quarantine",
                {"family": "verify", "row": 1, "reason": "bisect"})
    brk = breaker.get("verify")
    brk.force_open()
    brk.force_open()    # duplicate inside the cooldown -> absorbed
    if not rec.drain(15.0):
        failures.append("capture worker did not drain")
    rec.stop()

    summ = rec.summary()
    if summ["count"] != 1:
        failures.append(f"expected exactly 1 bundle, found "
                        f"{summ['count']}")
    report_txt = ""
    if summ["incidents"]:
        row = summ["incidents"][0]
        if row["trigger"] != "breaker_open":
            failures.append(
                f"bundle named {row['trigger']!r}, want breaker_open")
        bundle = load_bundle(os.path.join(tmp, row["id"]))
        man = bundle["manifest"]
        if (man.get("correlation") or {}).get("family") != "verify":
            failures.append("manifest correlation does not name the "
                            "verify family")
        if not any(h.get("class") == "quarantine"
                   for h in man.get("history", ())):
            failures.append("quarantine trigger missing from history")
        if man.get("suppressed", {}).get("breaker_open", 0) < 1:
            failures.append("cooldown did not record the suppressed "
                            "duplicate breaker_open")
        recs = [r for r in bundle.get("flight.json", {})
                .get("records", ()) if r.get("family") == "verify"]
        if len(recs) != n_ok + n_err:
            failures.append(f"verify ring holds {len(recs)} records, "
                            f"want {n_ok + n_err}")
        if sum(1 for r in recs if r.get("outcome") == "error") != n_err:
            failures.append("failing dispatches missing from the "
                            "embedded ring")
        if not any("dispatch:verify" in (r.get("faults") or ())
                   for r in recs):
            failures.append("fault annotation missing from the ring")
        problems = validate_bundle(bundle)
        for p in problems:
            failures.append(f"validate: {p}")
        try:
            report_txt = render(bundle)
            if "breaker_open" not in report_txt:
                failures.append("render does not name the trigger")
        except Exception as e:
            failures.append(f"render raised {type(e).__name__}: {e}")
        # --diff plumbing against itself must run clean
        try:
            diff_bundles(bundle, bundle)
        except Exception as e:
            failures.append(f"diff raised {type(e).__name__}: {e}")
    breaker.get("verify").reset()
    if report_txt:
        print(report_txt)
        print()
    for f in failures:
        print(f"incident-selfcheck: FAIL: {f}", file=sys.stderr)
    print("incident-selfcheck: PASS" if not failures
          else "incident-selfcheck: FAIL")
    return 0 if not failures else 1


# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/incident_report.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("bundle", nargs="?",
                    help="incident bundle directory to render")
    ap.add_argument("--rpc", help="daemon unix socket: render via "
                                  "listincidents/getincident")
    ap.add_argument("--id", help="bundle id (with --rpc; default "
                                 "newest)")
    ap.add_argument("--diff", nargs=2, metavar=("A", "B"),
                    help="diff two bundle directories")
    ap.add_argument("--validate", metavar="DIR",
                    help="validate a bundle directory (exit 1 on any "
                         "problem)")
    ap.add_argument("--selfcheck", action="store_true",
                    help="synthetic end-to-end gate (run_suite.sh)")
    ap.add_argument("--json", action="store_true",
                    help="structured output instead of the text frame")
    args = ap.parse_args(argv)

    if args.selfcheck:
        return selfcheck()
    if args.validate:
        problems = validate_bundle(load_bundle(args.validate))
        for p in problems:
            print(f"INVALID: {p}", file=sys.stderr)
        print("valid" if not problems else
              f"{len(problems)} problem(s)")
        return 0 if not problems else 1
    if args.diff:
        a, b = (load_bundle(p) for p in args.diff)
        print(json.dumps(diff_bundles(a, b), indent=1, default=str))
        return 0
    if args.rpc:
        bundle = load_bundle_rpc(args.rpc, args.id)
    elif args.bundle:
        bundle = load_bundle(args.bundle)
    else:
        ap.error("need a bundle dir, --rpc, --diff, --validate, or "
                 "--selfcheck")
    if args.json:
        print(json.dumps(build_report(bundle), indent=1, default=str))
    else:
        print(render(bundle))
    return 0


if __name__ == "__main__":
    sys.exit(main())
