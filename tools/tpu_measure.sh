#!/bin/bash
# One-command TPU measurement session for the verify-throughput target.
#
# Run when the axon tunnel (127.0.0.1:8083) is alive.  Everything is
# SERIALIZED (the tunneled TPU is single-tenant: a second process's
# backend init hangs), and all timing inside bench.py is readback-based
# (block_until_ready does not block on this backend).
#
#   bash tools/tpu_measure.sh            # full session (~30-45 min)
#   bash tools/tpu_measure.sh sweep      # kernel sweep only
#
# Outputs append to bench_tpu_session.log; bench.py also refreshes
# bench_last_tpu.json (picked up as last_measured_tpu metadata by every
# later run, including cpu-fallback driver rounds).
set -u
cd "$(dirname "$0")/.."
LOG=bench_tpu_session.log
stamp() { date "+%Y-%m-%d %H:%M:%S"; }

probe() {
  timeout 3 bash -c 'echo > /dev/tcp/127.0.0.1/8083' 2>/dev/null
}

if ! probe; then
  echo "$(stamp) tunnel DOWN — aborting" | tee -a "$LOG"
  exit 1
fi
echo "=== $(stamp) TPU measurement session ===" | tee -a "$LOG"

echo "--- kernel sweep (impl x bucket, kernel-only, readback-timed)" \
  | tee -a "$LOG"
BENCH_IMPLS=pallas_fb+pp,pallas_fbj,pallas_fbj+pp \
BENCH_BUCKETS=8192,16384 \
  timeout 2400 python bench.py --sweep 2>>"$LOG" | tee -a "$LOG"

[ "${1:-}" = "sweep" ] && exit 0

# pick the best impl from the sweep record for the e2e runs
BEST=$(python - <<'EOF'
import json
try:
    rec = json.load(open("bench_last_tpu.json"))
    print(rec.get("sweep_best", {}).get("impl", "glv"))
except Exception:
    print("glv")
EOF
)
BBKT=$(python - <<'EOF'
import json
try:
    rec = json.load(open("bench_last_tpu.json"))
    print(rec.get("sweep_best", {}).get("bucket", 8192))
except Exception:
    print(8192)
EOF
)
echo "--- best impl: $BEST bucket $BBKT" | tee -a "$LOG"

for CH in 25000 100000; do
  echo "--- e2e store replay, $CH channels ($BEST)" | tee -a "$LOG"
  LIGHTNING_TPU_DUAL_MUL=$BEST BENCH_BUCKET=$BBKT BENCH_CHANNELS=$CH \
  BENCH_DEADLINE=3000 timeout 3100 python bench.py 2>>"$LOG" \
    | tee -a "$LOG"
done

echo "=== $(stamp) session done — update BENCH_NOTES.md ===" \
  | tee -a "$LOG"
