#!/usr/bin/env python
"""Repo lint: span names, events topics, dispatch families, and metric
label values must come from a FIXED vocabulary — never constructed at
the call site.

Metric cardinality is bounded only because every label value and span
name is a code-bounded constant (doc/observability.md §vocabulary).
One `trace.span(f"verify/{scid}")` or `.labels(peer_id)` with an
interpolated id turns a bounded family into an unbounded one: the span
histogram grows a bucket set per peer, the exporter draws a lane per
scid, and the registry's cardinality cap starts silently dropping the
labels operators actually query.  This lint rejects the construction
itself:

* `trace.span(name, ...)` / `trace.device_span` / `trace.annotation`
  and `events.emit(topic, ...)` and `flight.dispatch/begin(family, ..)`
  must get a STRING LITERAL first argument;
* `.labels(...)` arguments must not be f-strings, %-formatting,
  str.format()/join() calls, or string concatenation — plain variables
  are fine (they carry values from fixed vocabularies; the registry's
  max_label_sets cap backstops them), building a NEW string at the
  call site is not.

Scanned: lightning_tpu/{obs,gossip,routing,resilience,parallel}/ and
lightning_tpu/daemon/hsmd.py — the dispatch-path modules feeding the
span ring and flight recorder.  Pre-existing violations would be
grandfathered in ALLOWLIST by (relpath, kind, offending source);
currently none are.  Exit 0 clean, 1 violations (listed on stdout).
"""
from __future__ import annotations

import ast
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCAN = ("lightning_tpu/obs", "lightning_tpu/gossip",
        "lightning_tpu/routing", "lightning_tpu/resilience",
        "lightning_tpu/parallel", "lightning_tpu/daemon/hsmd.py")

# call sites whose FIRST argument names a span/topic/family
NAMED_SITES = {"span", "device_span", "annotation", "emit",
               "dispatch", "begin"}
# modules the attr must hang off for NAMED_SITES to apply (so a
# dataclass's own `begin()` or an unrelated `emit` is not flagged)
NAMED_BASES = {"trace", "_trace", "events", "_ev", "_nev", "flight",
               "_flight"}

ALLOWLIST: set[tuple[str, str, str]] = set()


def _is_constructed_str(node: ast.AST) -> bool:
    """True if the expression BUILDS a string: f-string, %-format,
    concatenation involving a str literal, str.format()/join()."""
    if isinstance(node, ast.JoinedStr):
        return True
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.Add, ast.Mod)):
        for side in (node.left, node.right):
            if isinstance(side, ast.Constant) and isinstance(
                    side.value, str):
                return True
            if _is_constructed_str(side):
                return True
    if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute) and node.func.attr in (
            "format", "join"):
        return True
    return False


def scan_file(relpath: str) -> list[tuple[str, int, str, str]]:
    """Return (relpath, lineno, kind, source) violations."""
    with open(os.path.join(ROOT, relpath)) as f:
        tree = ast.parse(f.read(), relpath)
    hits: list[tuple[str, int, str, str]] = []

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        if fn.attr in NAMED_SITES:
            base = fn.value
            if not (isinstance(base, ast.Name)
                    and base.id in NAMED_BASES):
                continue
            if not node.args:
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                hits.append((relpath, node.lineno,
                             f"{base.id}.{fn.attr}",
                             ast.unparse(first)))
        elif fn.attr == "labels":
            for arg in node.args:
                if _is_constructed_str(arg):
                    hits.append((relpath, node.lineno, "labels",
                                 ast.unparse(arg)))
    return hits


def _files() -> list[str]:
    out = []
    for entry in SCAN:
        path = os.path.join(ROOT, entry)
        if os.path.isfile(path):
            out.append(entry)
            continue
        for dirpath, _, files in os.walk(path):
            for fname in sorted(files):
                if fname.endswith(".py"):
                    out.append(os.path.relpath(
                        os.path.join(dirpath, fname), ROOT))
    return out


def main() -> int:
    violations = []
    for rel in _files():
        for relpath, lineno, kind, src in scan_file(rel):
            if (relpath, kind, src) not in ALLOWLIST:
                violations.append((relpath, lineno, kind, src))
    if violations:
        print("span/label cardinality violations — names and label "
              "values must be fixed-vocabulary constants "
              "(doc/tracing.md):")
        for relpath, lineno, kind, src in violations:
            print(f"  {relpath}:{lineno} {kind}({src})")
        return 1
    print(f"lint_spans: clean ({len(_files())} files)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
