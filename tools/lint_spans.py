#!/usr/bin/env python
"""Repo lint: span names, events topics, dispatch families, and metric
label values must come from a FIXED vocabulary — never constructed at
the call site.  Since ISSUE 6 this is a thin shim over the graftlint
`spans` pass (lightning_tpu/analysis/passes/spans.py — rule rationale
lives there and in doc/static_analysis.md); CLI and exit semantics are
unchanged.  Violations would be grandfathered in the shared baseline
(tools/graftlint_baseline.json); currently none are.

Exit status: 0 clean, 1 violations (listed on stdout).
"""
from __future__ import annotations

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from lightning_tpu.analysis import run_repo  # noqa: E402
from lightning_tpu.analysis.core import Config, Engine  # noqa: E402
from lightning_tpu.analysis.passes.spans import (  # noqa: E402
    SpanVocabularyPass)

SCAN = SpanVocabularyPass.default_scope


def scan_file(relpath: str) -> list[tuple[str, int, str, str]]:
    """Return (relpath, lineno, kind, source) violations — the
    historical API, now answered by the framework pass."""
    p = SpanVocabularyPass()
    Engine([p], Config(root=ROOT, scan_roots=(relpath,),
                       scopes={p.name: ("",)})).run()
    out = []
    for f in p.findings:
        kind, sep, src = f.detail.partition("(")
        if not sep:                      # e.g. syntax-error
            kind, src = f.code, f.detail + ")"
        out.append((f.path, f.lineno, kind, src[:-1]))
    return out


def main() -> int:
    result = run_repo(pass_names=(SpanVocabularyPass.name,))
    bad = result.new_findings
    if bad or result.stale_baseline or result.unjustified:
        if bad:
            print("span/label cardinality violations — names and label "
                  "values must be fixed-vocabulary constants "
                  "(doc/tracing.md):")
            for f in bad:
                print(f"  {f.path}:{f.lineno} {f.detail}")
        for stale in result.stale_baseline:
            print(f"  stale baseline entry {stale['fingerprint']} "
                  f"({stale.get('file')}) — violation fixed; delete it")
        for uj in result.unjustified:
            print(f"  unjustified baseline entry {uj['fingerprint']} "
                  f"({uj.get('file')}) — add a justification")
        return 1
    print(f"lint_spans: clean ({', '.join(SCAN)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
