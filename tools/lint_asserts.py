#!/usr/bin/env python
"""Repo lint: no NEW bare `assert` statements as input contracts in
the dispatch-path packages.  Since ISSUE 6 this is a thin shim over the
graftlint `asserts` pass (lightning_tpu/analysis/passes/asserts.py —
rule rationale lives there and in doc/static_analysis.md); the CLI,
exit semantics, and the grandfathered-violation model are unchanged.

Grandfathered violations moved from the old in-file ALLOWLIST to the
shared fingerprint baseline (tools/graftlint_baseline.json), each with
a justification.  Fix one → delete its entry; never add entries for
new code.

Exit status: 0 clean, 1 new violations (listed on stdout).
"""
from __future__ import annotations

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from lightning_tpu.analysis import run_repo  # noqa: E402
from lightning_tpu.analysis.core import Config, Engine  # noqa: E402
from lightning_tpu.analysis.passes.asserts import (  # noqa: E402
    InputContractAssertPass)

SCAN_DIRS = InputContractAssertPass.default_scope


def scan_file(relpath: str) -> list[tuple[str, str, str, int]]:
    """Return (relpath, funcname, condition, lineno) for every
    parameter-referencing assert — the historical API, now answered by
    the framework pass."""
    p = InputContractAssertPass()
    Engine([p], Config(root=ROOT, scan_roots=(relpath,),
                       scopes={p.name: ("",)})).run()
    out = []
    for f in p.findings:
        if f.code != "input-contract":   # e.g. syntax-error
            continue
        cond = f.detail.split(": assert ", 1)[1]
        out.append((f.path, f.scope, cond, f.lineno))
    return out


def main() -> int:
    result = run_repo(pass_names=(InputContractAssertPass.name,))
    bad = result.new_findings
    if bad or result.stale_baseline or result.unjustified:
        if bad:
            print("new input-contract assert(s) — raise ValueError "
                  "instead (stripped under python -O):")
            for f in bad:
                if f.code != "input-contract":   # e.g. syntax-error
                    print(f"  {f.path}:{f.lineno} {f.message}")
                    continue
                cond = f.detail.split(": assert ", 1)[1]
                print(f"  {f.path}:{f.lineno} in {f.scope}(): "
                      f"assert {cond}")
        for stale in result.stale_baseline:
            print(f"  stale baseline entry {stale['fingerprint']} "
                  f"({stale.get('file')}) — violation fixed; delete it")
        for uj in result.unjustified:
            print(f"  unjustified baseline entry {uj['fingerprint']} "
                  f"({uj.get('file')}) — add a justification")
        return 1
    print(f"lint_asserts: clean ({', '.join(SCAN_DIRS)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
