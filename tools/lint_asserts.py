#!/usr/bin/env python
"""Repo lint: no NEW bare `assert` statements as input contracts in
`lightning_tpu/gossip/`, `lightning_tpu/crypto/`,
`lightning_tpu/routing/`, and `lightning_tpu/resilience/`.

A bare assert is stripped under `python -O`, so a contract like
"oversized rows require z_host" silently degrades into an incidental
TypeError (ADVICE.md round 5 — the bug this lint exists to prevent
recurring).  Contracts on inputs must `raise ValueError(...)`.

Operationalization: an `assert` whose condition references one of the
enclosing function's parameters is treated as an input contract.
Internal invariant asserts (locals-only, loop-carried bound proofs in
the kernel builders, etc.) stay legal — they check OUR math, not a
caller's data, and stripping them under -O is acceptable.

Pre-existing violations are grandfathered in ALLOWLIST by a
line-number-independent fingerprint (file, function, condition).  Fix
one → delete its entry; never add entries for new code.

Exit status: 0 clean, 1 new violations (listed on stdout).
"""
from __future__ import annotations

import ast
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCAN_DIRS = ("lightning_tpu/gossip", "lightning_tpu/crypto",
             "lightning_tpu/routing", "lightning_tpu/resilience")

# (relpath, enclosing function, unparsed condition) — grandfathered.
ALLOWLIST = {
    ("lightning_tpu/crypto/field.py", "int_to_limbs",
     "0 <= x < 1 << LIMB_BITS * n"),
    ("lightning_tpu/crypto/field.py", "__init__",
     "1 << 255 < m < 1 << 256"),
    ("lightning_tpu/crypto/field.py", "_reduce",
     "lbound <= STORED_LIMB_MAX and vmax <= STORED_VMAX"),
    ("lightning_tpu/crypto/field.py", "_reduce",
     "new_vmax < vmax"),
    ("lightning_tpu/crypto/field.py", "mul_small",
     "0 <= k < 6144"),
    ("lightning_tpu/crypto/field.py", "pow_const",
     "e >= 1"),
    ("lightning_tpu/crypto/field.py", "from_bytes_be",
     "data.shape[-1] == 32"),
    ("lightning_tpu/crypto/pallas_secp.py", "_reduceT",
     "lbound <= SLM and vmax <= SVM"),
    ("lightning_tpu/crypto/pallas_secp.py", "_reduceT",
     "new_vmax < vmax"),
    ("lightning_tpu/crypto/ref_python.py", "pubkey_serialize",
     "not pt.inf"),
    ("lightning_tpu/crypto/ref_python.py", "pubkey_create",
     "0 < seckey < N"),
    ("lightning_tpu/crypto/ref_python.py", "schnorr_sign",
     "schnorr_verify(msg, pt.x, sig)"),
}


def _param_names(fn: ast.AST) -> set[str]:
    a = fn.args
    names = [p.arg for p in
             (*a.posonlyargs, *a.args, *a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names) - {"self", "cls"}


def scan_file(relpath: str) -> list[tuple[str, str, str, int]]:
    """Return (relpath, funcname, condition, lineno) for every
    parameter-referencing assert."""
    with open(os.path.join(ROOT, relpath)) as f:
        tree = ast.parse(f.read(), relpath)
    hits = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack: list[ast.AST] = []

        def visit_FunctionDef(self, node):
            self.stack.append(node)
            self.generic_visit(node)
            self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Assert(self, node):
            if self.stack:
                fn = self.stack[-1]
                params = _param_names(fn)
                used = {n.id for n in ast.walk(node.test)
                        if isinstance(n, ast.Name)}
                if used & params:
                    hits.append((relpath, fn.name,
                                 ast.unparse(node.test), node.lineno))
            self.generic_visit(node)

    V().visit(tree)
    return hits


def main() -> int:
    violations = []
    for d in SCAN_DIRS:
        for dirpath, _, files in os.walk(os.path.join(ROOT, d)):
            for fname in sorted(files):
                if not fname.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fname), ROOT)
                for relpath, fn, cond, lineno in scan_file(rel):
                    if (relpath, fn, cond) not in ALLOWLIST:
                        violations.append((relpath, lineno, fn, cond))
    if violations:
        print("new input-contract assert(s) — raise ValueError instead "
              "(stripped under python -O):")
        for relpath, lineno, fn, cond in violations:
            print(f"  {relpath}:{lineno} in {fn}(): assert {cond}")
        return 1
    print(f"lint_asserts: clean ({', '.join(SCAN_DIRS)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
