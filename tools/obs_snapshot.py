#!/usr/bin/env python
"""Capture and diff metrics snapshots (the bench-side consumer of the
lightning_tpu.obs registry).

Subcommands:
  capture --rpc <unix-socket> [-o out.json]
      Call `getmetrics` on a running daemon and write the snapshot.
  capture --url http://host:port [-o out.json]
      Scrape the REST `getmetrics` POST surface instead.
  capture --local [-o out.json]
      Snapshot THIS process's registry (only useful under -c/import).
  diff a.json b.json
      Print per-metric deltas b-a: counters as deltas, gauges as the
      new value, histograms as count/sum deltas plus the mean.
  capture ... --watch N
      Periodic-diff mode: re-capture every N seconds and print the
      delta since the previous capture (one JSON object per tick,
      prefixed with an ISO timestamp comment).  This is how replay
      overlap is observed live during a run: watch the
      clntpu_replay_prep/_stall/_dispatch stage counters and the
      overlap-ratio histogram move while verify_store streams buckets
      (doc/replay_pipeline.md).  Ctrl-C exits cleanly.
  capture ... --dispatches N
      Fold the last N flight records (listdispatches, doc/tracing.md)
      into the capture as `dispatch_log`; diff/--watch then print only
      the dispatches NEW since the previous snapshot — the "which
      dispatch blew up that counter delta" view.

The diff output is the "what did this flush/bench actually do" view:
two snapshots bracket a workload and the delta is attributable to it.
`bench.py --metrics` embeds the same diff in its emitted JSON line so
offline bench rounds and live scrapes finally share one vocabulary.

Captures carry the getmetrics `perf` section (the stage-attribution
report, doc/perf.md) — capture --local computes it in-process, and
diffs/--watch ticks fold in its compact per-family view (bottleneck +
critical-path seconds), so a watch tick NAMES the bottleneck as the
stage counters move.

RPC/REST captures also carry the health engine's `gethealth` report
when the daemon runs one (doc/health.md); --watch ticks then print the
rolled-up state, per-SLO statuses, and window rates read from the
engine's time-series rings — the same numbers tools/dashboard.py
renders — falling back to plain local diffing on daemons without the
engine.

Captures additionally carry the `listincidents` summary when the
daemon runs the black-box recorder (doc/incidents.md); --watch prints
a `# NEW INCIDENT ...` line (plus the bundle summary in the delta)
the tick a new bundle lands mid-watch.

When the daemon samples per-item journeys (doc/journeys.md,
LIGHTNING_TPU_JOURNEY_SAMPLE) the `getjourney` summary rides along
too; --watch then prints a `# SLOW JOURNEY ...` line naming the
slowest finished entity the tick the rolling e2e p99 breaches
--slow-journey-ms (default 1000).
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def rpc_call(rpc_path: str, method: str, params: dict | None = None) -> dict:
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(30)
    s.connect(rpc_path)
    s.sendall(json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                          "params": params or {}}).encode())
    buf = b""
    while b"\n\n" not in buf:
        chunk = s.recv(1 << 20)
        if not chunk:
            break
        buf += chunk
    s.close()
    resp = json.loads(buf.split(b"\n\n")[0])
    if "error" in resp:
        raise SystemExit(f"{method} failed: {resp['error']}")
    return resp["result"]


def capture_rpc(rpc_path: str, dispatches: int | None = None) -> dict:
    """getmetrics over the daemon's unix JSON-RPC socket;
    --dispatches N folds the last N flight records in (listdispatches,
    doc/tracing.md).  When the daemon runs the health engine the
    gethealth report rides along too, so --watch ticks read their
    window rates from the SAME rings the dashboard renders
    (doc/health.md); a daemon without the engine falls back to plain
    local diffing."""
    snap = rpc_call(rpc_path, "getmetrics")
    if dispatches:
        snap["dispatch_log"] = rpc_call(
            rpc_path, "listdispatches",
            {"limit": dispatches})["dispatches"]
    try:
        health = rpc_call(rpc_path, "gethealth")
        # a daemon that registers gethealth but never installed/ran an
        # engine answers with an empty zero-tick report — that is the
        # "no engine" case too, not a health signal worth folding
        if health.get("ticks"):
            snap["health"] = health
    except (SystemExit, OSError, ValueError, KeyError):
        pass
    try:
        inc = rpc_call(rpc_path, "listincidents", {"limit": 8})
        if inc.get("enabled"):
            snap["incidents"] = inc
    except (SystemExit, OSError, ValueError, KeyError):
        pass  # no black-box recorder behind this socket
    try:
        jr = rpc_call(rpc_path, "getjourney", {"limit": 5})
        if jr.get("enabled"):
            snap["journeys"] = jr
    except (SystemExit, OSError, ValueError, KeyError):
        pass  # no journey sampling behind this socket
    return snap


def capture_url(url: str, rune: str | None = None,
                dispatches: int | None = None) -> dict:
    """getmetrics over the REST gateway (POST /v1/getmetrics).  A
    rune-gated daemon (commando configured) needs --rune."""
    import urllib.request

    headers = {"Rune": rune} if rune else {}

    def post(method: str, body: dict) -> dict:
        req = urllib.request.Request(
            url.rstrip("/") + "/v1/" + method,
            data=json.dumps(body).encode(), method="POST",
            headers=headers)
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.load(r)

    snap = post("getmetrics", {})
    if dispatches:
        snap["dispatch_log"] = post(
            "listdispatches", {"limit": dispatches})["dispatches"]
    try:
        health = post("gethealth", {})
        if health.get("ticks"):      # zero ticks = no engine running
            snap["health"] = health
    except Exception:
        pass  # no health engine behind this gateway: local diffing only
    try:
        inc = post("listincidents", {"limit": 8})
        if inc.get("enabled"):
            snap["incidents"] = inc
    except Exception:
        pass  # no black-box recorder behind this gateway
    try:
        jr = post("getjourney", {"limit": 5})
        if jr.get("enabled"):
            snap["journeys"] = jr
    except Exception:
        pass  # no journey sampling behind this gateway
    return snap


def capture_local(dispatches: int | None = None) -> dict:
    from lightning_tpu import obs
    # well-known families owned by heavyweight modules (routing.device,
    # daemon.hsmd) are declared in this jax-free module so they appear
    # present-at-zero in a fresh capture process — a diff against a
    # later in-daemon snapshot then attributes deltas correctly.  The
    # attribution import does the same for the perf-observatory
    # families (clntpu_retrace_total, clntpu_transfer_bytes_total,
    # clntpu_device_memory_bytes) and adds the `perf` section the
    # getmetrics RPC carries (doc/perf.md).
    from lightning_tpu.obs import attribution, families, flight  # noqa: F401

    snap = obs.snapshot()
    snap["perf"] = attribution.report_local(metrics=snap["metrics"])
    if dispatches:
        snap["dispatch_log"] = flight.recent(limit=dispatches)
    from lightning_tpu.obs import incident as _incident

    rec = _incident.current()
    if rec is not None:
        snap["incidents"] = rec.summary(limit=8)
    from lightning_tpu.obs import journey as _journey

    if _journey.enabled():
        snap["journeys"] = {"enabled": True,
                            "summary": _journey.summary(),
                            "journeys": _journey.recent(5)}
    return snap


def _sample_key(rec: dict) -> tuple:
    return tuple(sorted(rec.get("labels", {}).items()))


def diff_snapshots(a: dict, b: dict) -> dict:
    """Per-metric delta of two snapshot dicts (the getmetrics shape).
    Metrics/samples absent from `a` count from zero."""
    out: dict = {}
    am = a.get("metrics", {})
    for name, fam in b.get("metrics", {}).items():
        prev = {_sample_key(s): s
                for s in am.get(name, {}).get("samples", [])}
        rows = []
        for s in fam["samples"]:
            p = prev.get(_sample_key(s), {})
            labels = s.get("labels", {})
            if fam["kind"] == "histogram":
                dc = s["count"] - p.get("count", 0)
                ds = s["sum"] - p.get("sum", 0.0)
                if dc == 0:
                    continue
                rows.append({"labels": labels, "count": dc,
                             "sum": round(ds, 6),
                             "mean": round(ds / dc, 6)})
            elif fam["kind"] == "counter":
                d = s["value"] - p.get("value", 0.0)
                if d == 0:
                    continue
                rows.append({"labels": labels, "delta": d})
            else:  # gauge: point-in-time, report the new value
                rows.append({"labels": labels, "value": s["value"]})
        if rows:
            out[name] = {"kind": fam["kind"], "samples": rows}
    # the perf section (getmetrics "perf" / capture_local) is a
    # point-in-time analysis like a gauge: the diff carries `b`'s
    # compact view (bottleneck + critical path per family) so a
    # --watch tick names the bottleneck as the counters move
    if "perf" in b:
        try:
            from lightning_tpu.obs import attribution

            out["perf"] = attribution.compact(b["perf"])
        except Exception:
            out["perf"] = b["perf"]
    # the health engine's report (gethealth) is point-in-time like the
    # perf section: a --watch tick carries the compact view — rolled-up
    # state, per-SLO statuses, and the short-window rates read from the
    # engine's own rings, so watch output and tools/dashboard.py agree
    # on the same numbers (doc/health.md)
    if "health" in b:
        try:
            from lightning_tpu.obs import health as _health

            out["health"] = _health.compact(b["health"])
        except Exception:
            out["health"] = b["health"]
    # incident bundles (listincidents, doc/incidents.md): the diff
    # keeps only the bundles NEW since `a` — the "--watch prints a line
    # when a new incident lands" hook reads this
    if "incidents" in b:
        seen_inc = {r.get("id")
                    for r in (a.get("incidents") or {}).get(
                        "incidents", [])}
        new_inc = [r for r in b["incidents"].get("incidents", [])
                   if r.get("id") not in seen_inc]
        if new_inc:
            out["incidents"] = {
                "new": new_inc,
                "count": b["incidents"].get("count"),
                "total_bytes": b["incidents"].get("total_bytes"),
            }
    # the journey summary (getjourney, doc/journeys.md) is
    # point-in-time like the perf/health sections: a --watch tick
    # carries the compact view — table occupancy, the rolling e2e
    # tail, and the slowest finished entity — so the SLOW JOURNEY
    # hook below has its numbers in the delta too
    if "journeys" in b:
        s = b["journeys"].get("summary") or {}
        slowest = s.get("slowest")
        out["journeys"] = {
            "entities": s.get("entities"),
            "finished": s.get("finished"),
            "evicted": s.get("evicted"),
            "e2e_ms_p50": s.get("e2e_ms_p50"),
            "e2e_ms_p99": s.get("e2e_ms_p99"),
            "slowest": None if not slowest else {
                "kind": slowest.get("kind"),
                "key": str(slowest.get("key")),
                "e2e_ms": slowest.get("e2e_ms"),
            },
        }
    # flight records captured with --dispatches: the diff keeps only
    # the dispatches NEW since `a`, so a --watch tick shows WHICH
    # dispatch blew up a counter delta, not just that one did
    if "dispatch_log" in b:
        seen = {r.get("dispatch_id") for r in a.get("dispatch_log", [])}
        new = [r for r in b["dispatch_log"]
               if r.get("dispatch_id") not in seen]
        if new:
            out["dispatch_log"] = new
    return out


def watch(capture, interval: float, out=None,
          ticks: int | None = None, sleep=None,
          slow_journey_ms: float = 1000.0) -> None:
    """Capture every `interval` seconds, printing the per-tick delta
    (the live view of a replay's clntpu_replay_* stage counters, or of
    the clntpu_breaker_* / clntpu_quarantine_* resilience families
    while a fault plays out).  `ticks` bounds the number of deltas
    printed (None = until Ctrl-C); `sleep` injects a waiter (tests).
    A tick whose journey e2e p99 exceeds `slow_journey_ms` calls it out
    on a `# SLOW JOURNEY` line naming the slowest finished entity."""
    import datetime
    import time

    if out is None:
        out = sys.stdout
    if sleep is None:
        sleep = time.sleep
    prev = capture()
    printed = 0
    try:
        while ticks is None or printed < ticks:
            sleep(interval)
            cur = capture()
            stamp = datetime.datetime.now().isoformat(timespec="seconds")
            delta = diff_snapshots(prev, cur)
            print(f"# {stamp} (+{interval:g}s)", file=out, flush=False)
            for row in (delta.get("incidents") or {}).get("new", []):
                # a bundle landed mid-watch: call it out on its own
                # line, not just inside the delta JSON
                print(f"# NEW INCIDENT {row.get('id')} "
                      f"trigger={row.get('trigger')} "
                      f"bytes={row.get('bytes')}", file=out,
                      flush=False)
            jsum = delta.get("journeys") or {}
            p99 = jsum.get("e2e_ms_p99")
            if isinstance(p99, (int, float)) and p99 > slow_journey_ms:
                slow = jsum.get("slowest") or {}
                print(f"# SLOW JOURNEY e2e p99={p99:.1f}ms > "
                      f"{slow_journey_ms:g}ms slowest="
                      f"{slow.get('kind')} {slow.get('key')} "
                      f"({slow.get('e2e_ms')}ms)", file=out,
                      flush=False)
            print(json.dumps(delta if delta else {}, indent=1),
                  file=out, flush=True)
            prev = cur
            printed += 1
    except KeyboardInterrupt:
        pass


def main() -> int:
    p = argparse.ArgumentParser(prog="obs_snapshot")
    sub = p.add_subparsers(dest="cmd", required=True)
    cap = sub.add_parser("capture")
    cap.add_argument("--rpc", help="daemon unix socket (lightning-rpc)")
    cap.add_argument("--url", help="REST base url (http://127.0.0.1:PORT)")
    cap.add_argument("--rune", help="rune for a commando-gated REST "
                                    "server (with --url)")
    cap.add_argument("--local", action="store_true",
                     help="snapshot this process's registry")
    cap.add_argument("--watch", type=float, metavar="N",
                     help="periodic-diff mode: re-capture every N "
                          "seconds and print the delta since the "
                          "previous capture")
    cap.add_argument("--ticks", type=int, metavar="K",
                     help="with --watch: stop after K deltas instead "
                          "of running until Ctrl-C")
    cap.add_argument("--dispatches", type=int, metavar="N",
                     help="include the last N flight records "
                          "(listdispatches) in the capture; with "
                          "--watch, each tick prints only the "
                          "dispatches NEW since the previous tick")
    cap.add_argument("--slow-journey-ms", type=float, default=1000.0,
                     metavar="MS",
                     help="with --watch: print a SLOW JOURNEY line "
                          "when the rolling journey e2e p99 exceeds "
                          "MS (doc/journeys.md)")
    cap.add_argument("-o", "--out", default="-")
    d = sub.add_parser("diff")
    d.add_argument("a")
    d.add_argument("b")
    args = p.parse_args()

    if args.cmd == "capture":
        if args.dispatches is not None and args.dispatches <= 0:
            p.error("--dispatches must be positive")
        if args.rpc:
            capture = lambda: capture_rpc(args.rpc,
                                          dispatches=args.dispatches)
        elif args.url:
            capture = lambda: capture_url(args.url, rune=args.rune,
                                          dispatches=args.dispatches)
        elif args.local:
            capture = lambda: capture_local(dispatches=args.dispatches)
        else:
            p.error("need --rpc, --url, or --local")
        if args.watch is not None:
            if args.watch <= 0:
                p.error("--watch interval must be positive")
            if args.ticks is not None and args.ticks <= 0:
                p.error("--ticks must be positive")
            if args.slow_journey_ms <= 0:
                p.error("--slow-journey-ms must be positive")
            if args.out == "-":
                watch(capture, args.watch, ticks=args.ticks,
                      slow_journey_ms=args.slow_journey_ms)
            else:
                with open(args.out, "w") as f:
                    watch(capture, args.watch, out=f, ticks=args.ticks,
                          slow_journey_ms=args.slow_journey_ms)
            return 0
        snap = capture()
        text = json.dumps(snap, indent=1)
        if args.out == "-":
            print(text)
        else:
            with open(args.out, "w") as f:
                f.write(text)
    else:
        with open(args.a) as f:
            a = json.load(f)
        with open(args.b) as f:
            b = json.load(f)
        print(json.dumps(diff_snapshots(a, b), indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
