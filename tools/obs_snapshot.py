#!/usr/bin/env python
"""Capture and diff metrics snapshots (the bench-side consumer of the
lightning_tpu.obs registry).

Subcommands:
  capture --rpc <unix-socket> [-o out.json]
      Call `getmetrics` on a running daemon and write the snapshot.
  capture --url http://host:port [-o out.json]
      Scrape the REST `getmetrics` POST surface instead.
  capture --local [-o out.json]
      Snapshot THIS process's registry (only useful under -c/import).
  diff a.json b.json
      Print per-metric deltas b-a: counters as deltas, gauges as the
      new value, histograms as count/sum deltas plus the mean.

The diff output is the "what did this flush/bench actually do" view:
two snapshots bracket a workload and the delta is attributable to it.
`bench.py --metrics` embeds the same diff in its emitted JSON line so
offline bench rounds and live scrapes finally share one vocabulary.
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture_rpc(rpc_path: str) -> dict:
    """getmetrics over the daemon's unix JSON-RPC socket."""
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(30)
    s.connect(rpc_path)
    s.sendall(json.dumps({"jsonrpc": "2.0", "id": 1,
                          "method": "getmetrics"}).encode())
    buf = b""
    while b"\n\n" not in buf:
        chunk = s.recv(1 << 20)
        if not chunk:
            break
        buf += chunk
    s.close()
    resp = json.loads(buf.split(b"\n\n")[0])
    if "error" in resp:
        raise SystemExit(f"getmetrics failed: {resp['error']}")
    return resp["result"]


def capture_url(url: str, rune: str | None = None) -> dict:
    """getmetrics over the REST gateway (POST /v1/getmetrics).  A
    rune-gated daemon (commando configured) needs --rune."""
    import urllib.request

    headers = {"Rune": rune} if rune else {}
    req = urllib.request.Request(url.rstrip("/") + "/v1/getmetrics",
                                 data=b"{}", method="POST",
                                 headers=headers)
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.load(r)


def capture_local() -> dict:
    from lightning_tpu import obs

    return obs.snapshot()


def _sample_key(rec: dict) -> tuple:
    return tuple(sorted(rec.get("labels", {}).items()))


def diff_snapshots(a: dict, b: dict) -> dict:
    """Per-metric delta of two snapshot dicts (the getmetrics shape).
    Metrics/samples absent from `a` count from zero."""
    out: dict = {}
    am = a.get("metrics", {})
    for name, fam in b.get("metrics", {}).items():
        prev = {_sample_key(s): s
                for s in am.get(name, {}).get("samples", [])}
        rows = []
        for s in fam["samples"]:
            p = prev.get(_sample_key(s), {})
            labels = s.get("labels", {})
            if fam["kind"] == "histogram":
                dc = s["count"] - p.get("count", 0)
                ds = s["sum"] - p.get("sum", 0.0)
                if dc == 0:
                    continue
                rows.append({"labels": labels, "count": dc,
                             "sum": round(ds, 6),
                             "mean": round(ds / dc, 6)})
            elif fam["kind"] == "counter":
                d = s["value"] - p.get("value", 0.0)
                if d == 0:
                    continue
                rows.append({"labels": labels, "delta": d})
            else:  # gauge: point-in-time, report the new value
                rows.append({"labels": labels, "value": s["value"]})
        if rows:
            out[name] = {"kind": fam["kind"], "samples": rows}
    return out


def main() -> int:
    p = argparse.ArgumentParser(prog="obs_snapshot")
    sub = p.add_subparsers(dest="cmd", required=True)
    cap = sub.add_parser("capture")
    cap.add_argument("--rpc", help="daemon unix socket (lightning-rpc)")
    cap.add_argument("--url", help="REST base url (http://127.0.0.1:PORT)")
    cap.add_argument("--rune", help="rune for a commando-gated REST "
                                    "server (with --url)")
    cap.add_argument("--local", action="store_true",
                     help="snapshot this process's registry")
    cap.add_argument("-o", "--out", default="-")
    d = sub.add_parser("diff")
    d.add_argument("a")
    d.add_argument("b")
    args = p.parse_args()

    if args.cmd == "capture":
        if args.rpc:
            snap = capture_rpc(args.rpc)
        elif args.url:
            snap = capture_url(args.url, rune=args.rune)
        elif args.local:
            snap = capture_local()
        else:
            p.error("need --rpc, --url, or --local")
        text = json.dumps(snap, indent=1)
        if args.out == "-":
            print(text)
        else:
            with open(args.out, "w") as f:
                f.write(text)
    else:
        with open(args.a) as f:
            a = json.load(f)
        with open(args.b) as f:
            b = json.load(f)
        print(json.dumps(diff_snapshots(a, b), indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
