#!/usr/bin/env python
"""Headline benchmark: gossip_store replay signature throughput on TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "sig_verifies_per_sec", "vs_baseline": N}

Workload (BASELINE.md configs 2-3): a synthetic gossip_store in the
reference's on-disk format — channel_announcements (4 ECDSA sigs each,
matching gossipd/sigcheck.c:45-113's cost model), channel_updates and
node_announcements (1 sig each) — replay-verified end to end: load →
native scan → field gathers → chained sha256d+ECDSA batched kernels.

vs_baseline divides by BASELINE_CPU_OPS = 50k verifies/sec, the upper end
of single-core libsecp256k1 throughput cited in BASELINE.md (the library
itself cannot be built here: vendored submodule is empty and the image has
no network).  Using the upper end keeps the ratio conservative.

Robustness (round-1 postmortem: the TPU backend failed to init and the
whole run died with parsed=null): backend acquisition retries with
backoff, falls back to the CPU backend with a smaller workload if the
accelerator never comes up, and ANY error still emits the JSON line
(value 0 + error detail) so the driver always has a parseable record.

Env knobs: BENCH_CHANNELS (default 25000 → ~112k sigs), BENCH_BUCKET,
BENCH_STORE (reuse an existing store file), BENCH_CPU_CHANNELS (fallback
workload size, default 200), BENCH_FORCE_CPU=1 (skip the accelerator
probe entirely), BENCH_PROBE_TIMEOUT/RETRIES, BENCH_DEADLINE (watchdog
seconds before a guaranteed JSON line + exit), LIGHTNING_TPU_DUAL_MUL
(verify engine: xla | glv | pallas | pallas_v2 | pallas_glv).

Every emitted line also carries:
* kernel_only: steady-state device throughput of the verify kernel alone
  (N queued dispatches + ONE readback — `block_until_ready` does not
  block on the tunneled backend, so readback timing is the only honest
  clock), separating kernel speed from store-scan/host overhead;
* last_measured_tpu: the most recent REAL-accelerator measurement
  (persisted in bench_last_tpu.json by any successful accelerator run),
  so a cpu-fallback round still carries the hardware signal.

`--metrics` brackets the run with lightning_tpu.obs snapshots and embeds
the per-counter diff (verify flush latency/occupancy/compile events, and
the clntpu_replay_* pipeline-stage/overlap counters) in the emitted
line — the same registry a live daemon serves via the `getmetrics` RPC
and REST `GET /metrics` (doc/observability.md).

Emitted-record contract (checked by `bench.py --selfcheck [files...]`):
the TOP-LEVEL value/platform/engine/bucket always describe the best
real measurement of the metric — a cpu-fallback round with a prior
hardware e2e record replays that record to the top level
(`measurement: "replayed:bench_last_tpu.json"`, fallback numbers in
`fallback_run`) instead of headlining `platform: cpu-fallback` with
the hardware signal buried in metadata (VERDICT rounds 3-5).
"""
import json
import os
import sys
import time
import tempfile
import traceback

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_CPU_OPS = 50_000.0
METRIC = "gossip_store_replay_sig_verify_throughput"
UNIT = "sig_verifies_per_sec"
# `bench.py route` workload (PR-3): batched device pathfinding vs the
# single-query host dijkstra over the same synth gossmap
ROUTE_METRIC = "getroute_batched_throughput"
ROUTE_UNIT = "routes_per_sec"
# `bench.py mcf` workload: batched device min-cost-flow MPP solves vs
# the serial host mcf.getroutes oracle (doc/routing.md §MCF/MPP)
MCF_METRIC = "mcf_batched_throughput"
MCF_UNIT = "solves_per_sec"
LAST_TPU_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_last_tpu.json")
# Every emitted record also appends to this JSONL trajectory (schema-
# gated by check_history_line); tools/perf_report.py --compare gates
# regressions against it (doc/perf.md).  BENCH_HISTORY overrides.
HISTORY_PATH = os.environ.get(
    "BENCH_HISTORY",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "BENCH_HISTORY.jsonl"))
HISTORY_VERSION = 1


def _load_last_tpu() -> dict | None:
    try:
        if os.path.exists(LAST_TPU_PATH):
            with open(LAST_TPU_PATH) as f:
                return json.load(f)
    except Exception:
        pass
    return None


# which workload this process is measuring — error/watchdog lines must
# carry the metric they were running, not the default replay headline
# (a failed `route` round attributed to the sig-verify metric would
# poison that series in the driver's dashboards)
_ACTIVE = {"metric": METRIC, "unit": UNIT}


def emit(value: float, vs_baseline: float, **extra):
    line = {"metric": _ACTIVE["metric"], "value": value,
            "unit": _ACTIVE["unit"], "vs_baseline": vs_baseline}
    last = _load_last_tpu()
    if last is not None:
        line["last_measured_tpu"] = last
    line.update(extra)
    append_history(line)
    print(json.dumps(line), flush=True)


# -- BENCH_HISTORY.jsonl: the bench trajectory -------------------------------
#
# One JSON object per line: {"v": 1, "appended_at": ..., "source": ...,
# "record": <the emitted bench line>}.  Records seeded from pre-history
# driver artifacts carry "legacy": true (they predate the measurement/
# engine/bucket contract and are exempt from it — but never from the
# metric/value/unit core).  perf_report.py --compare consumes this file
# as the regression baseline (doc/perf.md).


def check_history_line(entry: dict) -> list[str]:
    """Schema violations in one BENCH_HISTORY.jsonl entry (empty = ok)."""
    problems = []
    if entry.get("v") != HISTORY_VERSION:
        problems.append(f"v must be {HISTORY_VERSION}")
    for key in ("appended_at", "source"):
        if not isinstance(entry.get(key), str) or not entry.get(key):
            problems.append(f"missing/empty key: {key}")
    rec = entry.get("record")
    if not isinstance(rec, dict):
        return problems + ["record must be an object"]
    if entry.get("legacy"):
        # pre-contract artifact: only the core is enforced
        for k in ("metric", "unit"):
            if not rec.get(k):
                problems.append(f"legacy record missing key: {k}")
        if "error" not in rec \
                and not isinstance(rec.get("value"), (int, float)):
            problems.append("legacy record value must be numeric")
    else:
        problems += [f"record: {p}" for p in check_bench_line(rec)]
    return problems


def append_history(line: dict, source: str = "bench.py",
                   legacy: bool = False, path: str | None = None) -> bool:
    """Append one emitted record to the history, gated on the schema:
    an entry that fails check_history_line is NOT written (the gate's
    whole point — a malformed record would poison every later
    --compare) and the violation goes to stderr."""
    entry = {"v": HISTORY_VERSION,
             "appended_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
             "source": source, "record": line}
    if legacy:
        entry["legacy"] = True
    probs = check_history_line(entry)
    if probs:
        print(f"bench: NOT appending to history (schema): "
              f"{'; '.join(probs)}", file=sys.stderr, flush=True)
        return False
    try:
        with open(path or HISTORY_PATH, "a") as f:
            f.write(json.dumps(entry) + "\n")
        return True
    except OSError as e:
        print(f"bench: history append failed: {e}", file=sys.stderr,
              flush=True)
        return False


def load_history(path: str | None = None) -> list[dict]:
    """Parse + validate the history; raises ValueError naming the bad
    line on any schema violation (the file is a gated artifact — a
    corrupt line is a bug, not data)."""
    entries = []
    with open(path or HISTORY_PATH) as f:
        for i, raw in enumerate(f, 1):
            raw = raw.strip()
            if not raw:
                continue
            try:
                entry = json.loads(raw)
            except json.JSONDecodeError as e:
                raise ValueError(f"history line {i}: invalid JSON: {e}")
            probs = check_history_line(entry)
            if probs:
                raise ValueError(
                    f"history line {i}: {'; '.join(probs)}")
            entries.append(entry)
    return entries


def seed_history(paths: list[str] | None = None) -> int:
    """`bench.py --seed-history [BENCH_rNN.json ...]` — bootstrap
    BENCH_HISTORY.jsonl from the existing driver artifacts (default:
    every BENCH_r*.json beside this file) plus the persisted real-
    hardware measurement in bench_last_tpu.json, so perf_report.py
    --compare has both a cpu-fallback trajectory and a hardware
    baseline from day one.  Artifacts whose `parsed` is null (the
    round-1 backend-init failure) are skipped with a note — there is
    no measurement in them to gate against."""
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    if not paths:
        paths = sorted(glob.glob(os.path.join(here, "BENCH_r*.json")))
    rc = 0
    for p in paths:
        name = os.path.basename(p)
        try:
            with open(p) as f:
                rec = json.load(f)
        except Exception as e:
            print(f"{name}: unreadable ({e}) — skipped")
            rc = 1
            continue
        if "metric" not in rec and "parsed" in rec:
            rec = rec["parsed"]
        if rec is None:
            print(f"{name}: parsed is null (errored round) — skipped")
            continue
        ok = append_history(rec, source=f"seed:{name}", legacy=True)
        print(f"{name}: {'seeded (legacy)' if ok else 'REJECTED'}")
        rc |= not ok
    last = _load_last_tpu()
    hw = (last or {}).get("end_to_end_sig_verifies_per_sec")
    if hw:
        line = {"metric": METRIC, "unit": UNIT, "value": float(hw),
                "vs_baseline": round(float(hw) / BASELINE_CPU_OPS, 3),
                "platform": last.get("platform", "tpu"),
                "engine": last.get("impl"),
                "bucket": last.get("bucket"), "measurement": "live",
                "measured_at": last.get("e2e_date"),
                "n_sigs": last.get("n_sigs"),
                "kernel_only": last.get("kernel_only")}
        ok = append_history(line, source="seed:bench_last_tpu.json")
        print("bench_last_tpu.json: "
              + ("seeded (hardware baseline)" if ok else "REJECTED"))
        rc |= not ok
    return rc


_AUTO_LAST = object()  # sentinel: "read bench_last_tpu.json yourself"


def compose_line(value: float, platform: str, *, engine=None, bucket=None,
                 extra: dict | None = None, last=_AUTO_LAST) -> dict:
    """Build the emitted record, promoting the most recent REAL
    accelerator e2e measurement to the TOP LEVEL when this run itself
    fell back to CPU.  Three rounds of VERDICTs flagged the old shape —
    headline `platform: cpu-fallback` with the hardware numbers buried
    in `last_measured_tpu` metadata — as unreadable by the driver; now
    the headline value/platform/engine always belong to the best real
    measurement of THIS metric, `measurement` says whether it was
    measured live or replayed from bench_last_tpu.json, and the
    fallback run's own numbers ride in `fallback_run`."""
    line = {"metric": METRIC, "unit": UNIT}
    if last is _AUTO_LAST:
        last = _load_last_tpu()
    run = {"value": value,
           "vs_baseline": round(value / BASELINE_CPU_OPS, 3),
           "platform": platform, "engine": engine, "bucket": bucket}
    run.update(extra or {})
    hw = (last or {}).get("end_to_end_sig_verifies_per_sec")
    if platform == "cpu-fallback" and hw:
        line.update({
            "value": float(hw),
            "vs_baseline": round(float(hw) / BASELINE_CPU_OPS, 3),
            "platform": last.get("platform", "tpu"),
            "engine": last.get("impl"),
            "bucket": last.get("bucket"),
            "measurement": "replayed:bench_last_tpu.json",
            "measured_at": last.get("e2e_date"),
            "fallback_run": run,
        })
    else:
        line.update(run)
        line["measurement"] = "live"
        line["measured_at"] = time.strftime("%Y-%m-%d")
    if last is not None:
        line["last_measured_tpu"] = last
    return line


# --selfcheck: schema contract for emitted records ---------------------------

REQUIRED_KEYS = ("metric", "value", "unit", "vs_baseline", "platform",
                 "measurement", "engine", "bucket")
ROUTE_REQUIRED_KEYS = ("metric", "value", "unit", "platform",
                       "measurement", "batch", "n_channels",
                       "host_baseline_rps", "speedup_vs_host")


def check_bench_line(line: dict) -> list[str]:
    """Return the list of schema violations in one emitted bench record
    (empty = ok).  Error/watchdog lines (an `error` key) only promise
    metric/value/unit and are exempt from the measurement contract.
    `route`/`mcf` workload records carry their own key set: the
    baseline is the measured serial host rate, not BASELINE_CPU_OPS."""
    if "error" in line:
        return [f"error line missing key: {k}" for k in
                ("metric", "value", "unit") if k not in line]
    if line.get("metric") in (ROUTE_METRIC, MCF_METRIC):
        problems = [f"missing/empty key: {k}" for k in ROUTE_REQUIRED_KEYS
                    if line.get(k) in (None, "")]
        v, hb, sp = (line.get("value"), line.get("host_baseline_rps"),
                     line.get("speedup_vs_host"))
        if all(isinstance(x, (int, float)) for x in (v, hb, sp)) and hb:
            if abs(sp - v / hb) > max(0.01, 0.01 * abs(sp)):
                problems.append(
                    "speedup_vs_host inconsistent with "
                    "value/host_baseline_rps")
        return problems
    problems = [f"missing/empty key: {k}" for k in REQUIRED_KEYS
                if line.get(k) in (None, "")]
    last = line.get("last_measured_tpu") or {}
    if (line.get("platform") == "cpu-fallback"
            and last.get("end_to_end_sig_verifies_per_sec")):
        problems.append(
            "hardware e2e numbers buried in last_measured_tpu under a "
            "cpu-fallback headline — promote them (compose_line)")
    if str(line.get("measurement", "")).startswith("replayed"):
        if not line.get("measured_at"):
            problems.append("replayed measurement without measured_at")
        if not isinstance(line.get("fallback_run"), dict):
            problems.append("replayed measurement without fallback_run")
    v, vb = line.get("value"), line.get("vs_baseline")
    if isinstance(v, (int, float)) and isinstance(vb, (int, float)) and v:
        if abs(vb - v / BASELINE_CPU_OPS) > 0.01:
            problems.append("vs_baseline inconsistent with value")
    return problems


def run_selfcheck(paths: list[str]) -> int:
    """`bench.py --selfcheck [BENCH_rNN.json | *.jsonl ...]` — validate
    driver artifacts against the schema contract; .jsonl paths validate
    as BENCH_HISTORY trajectories (every line through
    check_history_line).  With no paths, validates the line this bench
    WOULD emit on a cpu-fallback round (catching a headline-burial
    regression before any artifact is written) AND the history entry
    it would append — plus BENCH_HISTORY.jsonl itself when present."""
    rc = 0
    if not paths:
        line = compose_line(39.6, "cpu-fallback", engine="glv", bucket=64)
        probs = check_bench_line(line)
        tag = "hypothetical cpu-fallback line"
        print(f"{tag}: " + ("ok" if not probs else "; ".join(probs)))
        rc |= bool(probs)
        mline = compose_mcf_line(12.5, "cpu", batch=8, n_channels=2000,
                                 host_rps=20.0)
        probs = check_bench_line(mline)
        print("hypothetical mcf line: "
              + ("ok" if not probs else "; ".join(probs)))
        rc |= bool(probs)
        entry = {"v": HISTORY_VERSION,
                 "appended_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
                 "source": "bench.py", "record": line}
        probs = check_history_line(entry)
        print("hypothetical history entry: "
              + ("ok" if not probs else "; ".join(probs)))
        rc |= bool(probs)
        if os.path.exists(HISTORY_PATH):
            paths = [HISTORY_PATH]
    for p in paths:
        if p.endswith(".jsonl"):
            try:
                entries = load_history(p)
                print(f"{p}: ok ({len(entries)} entries)")
            except (ValueError, OSError) as e:
                print(f"{p}: {e}")
                rc = 1
            continue
        try:
            with open(p) as f:
                rec = json.load(f)
            # BENCH_rNN.json driver artifacts wrap the emitted line
            # under "parsed" (alongside cmd/rc/tail)
            if "metric" not in rec and "parsed" in rec:
                rec = rec["parsed"]
            if rec is None:
                probs = ["parsed is null (bench emitted no JSON line)"]
            else:
                probs = check_bench_line(rec)
        except Exception as e:
            probs = [f"unreadable: {type(e).__name__}: {e}"]
        print(f"{p}: " + ("ok" if not probs else "; ".join(probs)))
        rc |= bool(probs)
    return rc


def record_tpu_measurement(rec: dict) -> None:
    """Persist the honest accelerator numbers for future fallback runs.
    MERGES into the existing record (a kernel sweep and an e2e replay
    each own different keys; one must not clobber the other) and writes
    atomically (tmp + rename): a watchdog hard-exit mid-write must not
    destroy the previously persisted measurement."""
    try:
        merged = {}
        try:
            with open(LAST_TPU_PATH) as f:
                merged = json.load(f)
        except Exception:
            pass
        merged.update(rec)
        merged.pop("date", None)   # legacy unscoped key (pre-round-4.3)
        tmp = LAST_TPU_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(merged, f, indent=1)
        os.replace(tmp, LAST_TPU_PATH)
    except Exception:
        pass


def acquire_backend() -> str:
    """Initialize a usable jax backend, preferring the accelerator.

    Returns the backend platform name.  The accelerator is probed in a
    SUBPROCESS with a hard timeout first: the TPU here sits behind a
    network tunnel and its init has been observed both to raise (round-1
    BENCH failure) and to hang indefinitely — an in-process hang is
    unrecoverable (the backend lock stays held), a dead subprocess is
    trivially recoverable.  Only after the probe succeeds does the main
    process touch jax; otherwise it forces the CPU platform so the
    benchmark still produces an honest (labeled) number instead of
    nothing.
    """
    import subprocess

    from lightning_tpu.utils.jaxcfg import force_cpu

    probed = None
    if not os.environ.get("BENCH_FORCE_CPU"):
        import subprocess

        # the jax probe BLOCKS (the axon plugin retries internally)
        # whether the tunnel port is open or refused — measured on this
        # box: a probe against a closed port still hangs to its full
        # timeout.  One 270 s attempt therefore already spans a cold
        # tunnel bring-up (round-2 postmortem), and a wedged tunnel
        # stays wedged for hours, so 2 attempts is the budget: the
        # round-4 artifact lost 18 min to 4 hung probes.
        retries = int(os.environ.get("BENCH_PROBE_RETRIES", "2"))
        # the tunnel has been observed to take >2 min to come up cold —
        # round-2 postmortem: a 150s probe timeout wrote off a live TPU
        probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "270"))
        # PROBE_OK sentinel line: imports may print banners to stdout.
        probe_src = ("import jax; print('PROBE_OK', jax.default_backend(), "
                     "len(jax.devices()))")
        for attempt in range(retries):
            try:
                p = subprocess.run(
                    [sys.executable, "-c", probe_src],
                    capture_output=True, text=True, timeout=probe_timeout,
                )
                lines = [l for l in p.stdout.splitlines()
                         if l.startswith("PROBE_OK")]
                if p.returncode == 0 and lines:
                    _, platform, ndev = lines[-1].split()[:3]
                    print(f"bench: backend probe ok: {platform} x{ndev}",
                          file=sys.stderr, flush=True)
                    probed = platform
                    break
                # FULL stderr: truncating it hid the actual TPU init
                # error from the round-2 record (VERDICT Weak #1)
                print(f"bench: backend probe attempt {attempt + 1}/{retries} "
                      f"rc={p.returncode}:\n{p.stderr.strip()}",
                      file=sys.stderr, flush=True)
            except subprocess.TimeoutExpired:
                print(f"bench: backend probe attempt {attempt + 1}/{retries} "
                      f"hung >{probe_timeout}s", file=sys.stderr, flush=True)
            if attempt < retries - 1:
                time.sleep(2.0 * (attempt + 1))
    if probed is None:
        print("bench: accelerator unavailable; falling back to CPU",
              file=sys.stderr, flush=True)
    if probed is None or probed == "cpu":
        # Degraded mode either way: trade runtime for compile time (cold
        # CPU compiles of the EC programs take ~4 min each at full opt).
        force_cpu(cheap_compile=True)

    import jax

    jax.devices()  # raises if even CPU is broken — caught by main's guard
    return jax.default_backend()


def time_kernel_only(bucket: int, n_iters: int = 8,
                     impl_name: str | None = None) -> dict:
    """Steady-state throughput of the hash+verify kernel pair alone:
    one warm-up call (compile + page-in), then n_iters enqueued
    dispatches followed by a SINGLE host readback.  The readback is the
    only honest clock on the tunneled backend (block_until_ready returns
    immediately there); queue order serializes the dispatches.

    timing_scope: since round 5 the timed call includes the device-side
    z-row gather between the hash and verify phases (the production
    verify_items pipeline).  Pre-round-5 kernel_only numbers excluded
    it; `gather_ms_per_call` reports the gather's own cost so the two
    eras stay comparable (ADVICE.md round 5 / BENCH_NOTES.md)."""
    import numpy as np

    import jax.numpy as jnp

    from lightning_tpu.crypto import field as F
    from lightning_tpu.crypto import secp256k1 as S
    from lightning_tpu.gossip import synth, verify

    rng = np.random.default_rng(42)
    rows, nb, sigs, pubs = synth.make_signed_batch(bucket, rng)
    blocks = verify._bytes_to_blocks(rows, verify.MAX_BLOCKS)
    # the PRODUCTION pipeline program: ONE fused dispatch per bucket
    # (sha256d → local z gather → from-bytes EC verify), exactly what
    # verify_items enqueues.  donate=False: the timing loop reuses the
    # same device operands every iteration.
    args = (
        jnp.asarray(blocks), jnp.asarray(nb.astype(np.int32)),
        jnp.asarray(np.arange(bucket, dtype=np.int32)),
        jnp.asarray(sigs), jnp.asarray(pubs),
    )
    kern = verify._jit_fused_resolved(
        *S._resolve_engine_names(impl_name, None), False)

    def call():
        return kern(*args)

    ok = np.asarray(call())            # warm-up incl. compile + readback
    if not ok.all():
        raise AssertionError("kernel-only workload failed verification")
    t0 = time.perf_counter()
    for _ in range(n_iters):
        out = call()
    # ONE readback drains the queue — a plain statement, not an assert:
    # under `python -O` a stripped assert would skip the readback and
    # time enqueue-only dispatch (wildly inflated throughput)
    final_ok = bool(np.asarray(out).all())
    dt = time.perf_counter() - t0
    if not final_ok:
        raise AssertionError("kernel-only workload failed verification")

    # gather-only cost, same enqueue-N + one-readback clock: isolates
    # the inter-phase hop that round 5 folded into kernel_only
    z_dev = verify._jit_hash()(args[0], args[1])
    np.asarray(S._jit_gather_rows()(z_dev, args[2]))        # warm
    tg = time.perf_counter()
    for _ in range(n_iters):
        g = S._jit_gather_rows()(z_dev, args[2])
    np.asarray(g)
    dtg = time.perf_counter() - tg

    return {"bucket": bucket, "iters": n_iters,
            "throughput": round(bucket * n_iters / dt, 1),
            "ms_per_call": round(dt / n_iters * 1e3, 2),
            # since the fused-bucket pipeline landed this times the ONE
            # fused program; the pre-fusion rounds timed the 3-program
            # chain over the same phases, so the scope (and numbers)
            # stay comparable — gather_ms_per_call still isolates the
            # old standalone inter-phase gather for pre-round-5 eras
            "timing_scope": "fused:hash+gather+verify",
            "gather_ms_per_call": round(dtg / n_iters * 1e3, 3)}


def run_bench(platform: str) -> dict:
    from lightning_tpu.gossip import store as gstore
    from lightning_tpu.gossip import synth, verify

    on_accel = platform not in ("cpu",)
    # Big fixed bucket on the real accelerator: amortizes per-dispatch
    # latency (the TPU sits behind a network tunnel here) and keeps one
    # compiled program for any store size.  The CPU fallback gets a small
    # workload so the run finishes at all.
    if on_accel:
        n_channels = int(os.environ.get("BENCH_CHANNELS", "25000"))
        # 16384 is the measured sweet spot for the VMEM-resident fused
        # kernels (round-4 session-3 sweep: pallas_fb+pp 174.5k/s
        # @16384 vs 167.9k @8192; 32k batches regress on table HBM
        # residency)
        bucket = int(os.environ.get("BENCH_BUCKET", "16384"))
        # production engine on hardware = the sweep winner (in-kernel
        # table build + joint G/φG table + fused sqrt/inv prep,
        # 200.9k/s @16384 measured 2026-08-01); the CPU fallback keeps
        # the XLA scan (pallas interpret mode is orders of magnitude
        # slower than compiled XLA on CPU)
        os.environ.setdefault("LIGHTNING_TPU_DUAL_MUL", "pallas_fbj+pp")
    else:
        # bucket 64 = the unit-test bucket, warm in the persistent cache
        n_channels = int(os.environ.get("BENCH_CPU_CHANNELS", "200"))
        bucket = int(os.environ.get("BENCH_BUCKET", "64"))

    path = os.environ.get("BENCH_STORE")
    is_temp_store = not path or not os.path.exists(path)
    if is_temp_store:
        path = os.path.join(tempfile.gettempdir(), f"bench_store_{n_channels}.gs")
        if not os.path.exists(path):
            # write-then-rename: a run killed mid-synthesis must not leave
            # a truncated store that poisons every later run
            tmp = path + f".tmp.{os.getpid()}"
            synth.make_network_store(
                tmp, n_channels=n_channels, n_nodes=max(2, n_channels // 8),
                updates_per_channel=2,
                sign_bucket=(synth.SIGN_BUCKET if on_accel else 64),
            )
            os.replace(tmp, path)

    idx = gstore.load_store(path)
    crc_ok = idx.check_crcs()
    if not crc_ok.all():
        if is_temp_store:
            os.unlink(path)  # don't poison the next run
        raise AssertionError("store CRC failure")

    # Warm-up: compiles the kernel (cached persistently) and pages data in.
    res = verify.verify_store(idx, bucket=bucket)
    assert res.ca_valid.all() and res.cu_valid.all() and res.na_valid.all(), (
        "benchmark store failed verification — kernel bug"
    )

    # Timed replay: full host+device pipeline, fresh store scan included.
    t0 = time.perf_counter()
    idx2 = gstore.load_store(path)
    res2 = verify.verify_store(idx2, bucket=bucket)
    dt = time.perf_counter() - t0

    # Steady-state kernel-only number (separates device speed from
    # store-scan/host overhead; survives into the emitted metadata).
    try:
        kern = time_kernel_only(bucket, n_iters=8 if on_accel else 2)
    except Exception as e:
        kern = {"error": f"{type(e).__name__}: {e}"}

    out = {"n_sigs": res2.n_sigs, "seconds": dt,
           "throughput": res2.n_sigs / dt, "kernel_only": kern,
           "impl": os.environ.get("LIGHTNING_TPU_DUAL_MUL", "glv"),
           "bucket": bucket}
    if on_accel:
        # the date rides INSIDE the keys this writer owns — the merge
        # must not re-date a surviving sweep_best from another run
        record_tpu_measurement({
            "platform": platform,
            "e2e_date": time.strftime("%Y-%m-%d"),
            "end_to_end_sig_verifies_per_sec": round(out["throughput"], 1),
            "n_sigs": res2.n_sigs, "kernel_only": kern,
            "impl": out["impl"], "bucket": bucket,
        })
    return out


def compose_route_line(qps: float, platform: str, *, batch: int,
                       n_channels: int, host_rps: float,
                       extra: dict | None = None) -> dict:
    """Emitted record for the `route` workload.  Always a LIVE
    measurement (there is no replay store for this metric yet); the
    PR-2 convention for cpu-fallback rounds is a projection note in
    BENCH_NOTES.md, not a synthetic headline."""
    label = platform if platform not in ("cpu",) else "cpu-fallback"
    line = {"metric": ROUTE_METRIC, "unit": ROUTE_UNIT,
            "value": round(qps, 1), "platform": label,
            "measurement": "live",
            "measured_at": time.strftime("%Y-%m-%d"),
            "batch": batch, "n_channels": n_channels,
            "host_baseline_rps": round(host_rps, 2),
            "speedup_vs_host": round(qps / host_rps, 3) if host_rps
            else 0.0}
    line.update(extra or {})
    return line


def run_route_bench(platform: str) -> dict:
    """`bench.py route`: batched device pathfinding throughput over a
    synth gossmap vs the single-query host dijkstra baseline.

    Env knobs: BENCH_ROUTE_CHANNELS (default 10000), BENCH_ROUTE_BATCH
    (device query bucket, default 64), BENCH_ROUTE_BATCHES (timed
    device dispatches, default 4), BENCH_ROUTE_HOST_QUERIES (baseline
    sample, default 24)."""
    import numpy as np

    from lightning_tpu.gossip import gossmap as GM
    from lightning_tpu.gossip import store as gstore
    from lightning_tpu.gossip import synth
    from lightning_tpu.routing import device as RD
    from lightning_tpu.routing import dijkstra as DJ
    from lightning_tpu.routing.planes import RoutePlanes

    n_channels = int(os.environ.get("BENCH_ROUTE_CHANNELS", "10000"))
    batch = int(os.environ.get("BENCH_ROUTE_BATCH", "64"))
    n_batches = int(os.environ.get("BENCH_ROUTE_BATCHES", "4"))
    n_host = int(os.environ.get("BENCH_ROUTE_HOST_QUERIES", "24"))

    path = os.path.join(tempfile.gettempdir(),
                        f"bench_route_{n_channels}.gs")
    if not os.path.exists(path):
        tmp = path + f".tmp.{os.getpid()}"
        # sign=False: routing never verifies; zero-sig synthesis keeps
        # the workload graph-shaped instead of EC-bound
        synth.make_network_store(
            tmp, n_channels=n_channels, n_nodes=max(2, n_channels // 8),
            updates_per_channel=2, sign=False)
        os.replace(tmp, path)
    g = GM.from_store(gstore.load_store(path))

    rng = np.random.default_rng(11)
    amount = 1_000_000
    queries = []
    for _ in range(batch * (n_batches + 1)):
        a, b = rng.integers(0, g.n_nodes, 2)
        if a == b:
            b = (b + 1) % g.n_nodes
        queries.append(RD.RouteQuery(bytes(g.node_ids[a]),
                                     bytes(g.node_ids[b]), amount))

    # host baseline: the serial per-payment path this PR batches away
    t0 = time.perf_counter()
    host_done = 0
    for q in queries[:n_host]:
        try:
            DJ.getroute(g, q.source, q.destination, q.amount_msat)
        except DJ.NoRoute:
            pass
        host_done += 1
    host_rps = host_done / (time.perf_counter() - t0)

    planes = RoutePlanes.build(g)
    RD.solve_batch(planes, queries[:batch], batch=batch)  # compile+warm
    t0 = time.perf_counter()
    solved = fellback = 0
    for i in range(1, n_batches + 1):
        res = RD.solve_batch(planes, queries[i * batch:(i + 1) * batch],
                             batch=batch)
        # honest headline: only lanes the device actually ANSWERED
        # (route or proven-unreachable) count; fallback/error lanes
        # would need a host re-solve and must not inflate routes/s
        solved += sum(1 for r in res if r[0] in ("ok", "noroute"))
        fellback += sum(1 for r in res if r[0] not in ("ok", "noroute"))
    dt = time.perf_counter() - t0
    qps = solved / dt
    out = {"qps": qps, "host_rps": host_rps, "batch": batch,
           "n_channels": n_channels, "n_nodes": g.n_nodes,
           "queries": solved, "fallbacks": fellback, "seconds": dt,
           "planes": {"n_pad": planes.n_pad, "e_pad": planes.e_pad}}
    if platform not in ("cpu",):
        record_tpu_measurement({"route": {
            "routes_per_sec": round(qps, 1),
            "host_baseline_rps": round(host_rps, 2),
            "batch": batch, "n_channels": n_channels,
            "date": time.strftime("%Y-%m-%d")}})
    return out


def compose_mcf_line(sps: float, platform: str, *, batch: int,
                     n_channels: int, host_rps: float,
                     extra: dict | None = None) -> dict:
    """Emitted record for the `mcf` workload — the route-record key
    contract (check_bench_line validates both against the same set):
    always a LIVE measurement, host baseline = serial mcf.getroutes."""
    label = platform if platform not in ("cpu",) else "cpu-fallback"
    line = {"metric": MCF_METRIC, "unit": MCF_UNIT,
            "value": round(sps, 1), "platform": label,
            "measurement": "live",
            "measured_at": time.strftime("%Y-%m-%d"),
            "batch": batch, "n_channels": n_channels,
            "host_baseline_rps": round(host_rps, 2),
            "speedup_vs_host": round(sps / host_rps, 3) if host_rps
            else 0.0}
    line.update(extra or {})
    return line


def run_mcf_bench(platform: str) -> dict:
    """`bench.py mcf`: batched device min-cost-flow (MPP getroutes)
    throughput over a synth gossmap vs the serial host solver baseline.

    Env knobs: BENCH_MCF_CHANNELS (default 2000), BENCH_MCF_BATCH
    (device query bucket, default 8), BENCH_MCF_BATCHES (timed device
    dispatches, default 2), BENCH_MCF_HOST_QUERIES (baseline sample,
    default 8)."""
    import numpy as np

    from lightning_tpu.gossip import gossmap as GM
    from lightning_tpu.gossip import store as gstore
    from lightning_tpu.gossip import synth
    from lightning_tpu.routing import mcf as MCF
    from lightning_tpu.routing import mcf_device as MD

    n_channels = int(os.environ.get("BENCH_MCF_CHANNELS", "2000"))
    batch = int(os.environ.get("BENCH_MCF_BATCH", "8"))
    n_batches = int(os.environ.get("BENCH_MCF_BATCHES", "2"))
    n_host = int(os.environ.get("BENCH_MCF_HOST_QUERIES", "8"))

    path = os.path.join(tempfile.gettempdir(),
                        f"bench_mcf_{n_channels}.gs")
    if not os.path.exists(path):
        tmp = path + f".tmp.{os.getpid()}"
        synth.make_network_store(
            tmp, n_channels=n_channels, n_nodes=max(2, n_channels // 8),
            updates_per_channel=2, sign=False)
        os.replace(tmp, path)
    g = GM.from_store(gstore.load_store(path))

    rng = np.random.default_rng(13)
    # amounts big enough that some queries genuinely split (MPP), small
    # enough that most are routable — the realistic xpay mix
    queries = []
    for _ in range(batch * (n_batches + 1)):
        a, b = rng.integers(0, g.n_nodes, 2)
        if a == b:
            b = (b + 1) % g.n_nodes
        queries.append(MD.McfQuery(
            bytes(g.node_ids[a]), bytes(g.node_ids[b]),
            int(rng.integers(100_000, 50_000_000)), max_parts=8))

    # host baseline: the serial per-payment solver this engine batches
    t0 = time.perf_counter()
    host_done = 0
    for q in queries[:n_host]:
        try:
            MCF.getroutes(g, q.source, q.destination, q.amount_msat,
                          max_parts=q.max_parts)
        except MCF.McfError:
            pass
        host_done += 1
    host_rps = host_done / (time.perf_counter() - t0)

    planes = MD.McfPlanes.build(g)
    MD.solve_mcf_batch(planes, queries[:batch], batch=batch)  # warm
    t0 = time.perf_counter()
    solved = fellback = 0
    for i in range(1, n_batches + 1):
        res = MD.solve_mcf_batch(planes,
                                 queries[i * batch:(i + 1) * batch],
                                 batch=batch)
        # honest headline: only lanes the device ANSWERED (routes or
        # provably unroutable); fallback lanes need a host re-solve
        solved += sum(1 for r in res if r[0] in ("ok", "mcferr"))
        fellback += sum(1 for r in res if r[0] not in ("ok", "mcferr"))
    dt = time.perf_counter() - t0
    sps = solved / dt
    out = {"sps": sps, "host_rps": host_rps, "batch": batch,
           "n_channels": n_channels, "n_nodes": g.n_nodes,
           "queries": solved, "fallbacks": fellback, "seconds": dt,
           "planes": {"n_pad": planes.n_pad,
                      "a_fwd_pad": planes.a_fwd_pad}}
    if platform not in ("cpu",):
        record_tpu_measurement({"mcf": {
            "solves_per_sec": round(sps, 1),
            "host_baseline_rps": round(host_rps, 2),
            "batch": batch, "n_channels": n_channels,
            "date": time.strftime("%Y-%m-%d")}})
    return out


def run_sweep(platform: str) -> None:
    """Manual mode (`bench.py --sweep`): kernel-only throughput for each
    dual-mul implementation × bucket, printed as a table.  Used to pick
    the production impl/bucket on real hardware; results go in
    BENCH_NOTES.md."""
    impls = os.environ.get(
        "BENCH_IMPLS",
        "xla,glv,pallas,pallas_v2,pallas_glv,pallas_fb,pallas_fb+pp,"
        "pallas_fbj+pp",
    ).split(",")
    buckets = [int(b) for b in os.environ.get(
        "BENCH_BUCKETS", "4096,8192,16384").split(",")]
    print(f"# sweep on {platform}", flush=True)
    best = None
    for impl in impls:
        for b in buckets:
            try:
                k = time_kernel_only(b, n_iters=6, impl_name=impl)
                row = {"impl": impl, **k}
                if best is None or k["throughput"] > best["throughput"]:
                    best = row
                    # persist incrementally: the tunnel has died mid-sweep
                    # in two previous rounds, and a partial sweep is still
                    # a real hardware measurement
                    if platform not in ("cpu",):
                        record_tpu_measurement({
                            "platform": platform,
                            "sweep_best": {
                                **best,
                                "date": time.strftime("%Y-%m-%d")}})
            except Exception as e:
                row = {"impl": impl, "bucket": b,
                       "error": f"{type(e).__name__}: {e}"}
            print(json.dumps(row), flush=True)
    if best:
        print(f"# best: {json.dumps(best)}", flush=True)


def main():
    if "--selfcheck" in sys.argv:
        sys.exit(run_selfcheck(
            [a for a in sys.argv[1:] if not a.startswith("-")]))
    if "--seed-history" in sys.argv:
        sys.exit(seed_history(
            [a for a in sys.argv[1:] if not a.startswith("-")]))

    # A hang is not an Exception: if the tunnel drops after the probe, the
    # try/except below never fires.  The watchdog emits the JSON line and
    # hard-exits before the driver deadline so `parsed` is never null.
    import threading

    if "route" in sys.argv[1:]:
        # scope error/watchdog lines to the workload being measured
        _ACTIVE.update(metric=ROUTE_METRIC, unit=ROUTE_UNIT)
    elif "mcf" in sys.argv[1:]:
        _ACTIVE.update(metric=MCF_METRIC, unit=MCF_UNIT)

    t_start = time.monotonic()
    deadline = float(os.environ.get("BENCH_DEADLINE", "2400"))

    def _hang_guard():
        emit(0.0, 0.0, error=f"watchdog: exceeded {deadline}s deadline")
        os._exit(0)

    guard = threading.Timer(deadline, _hang_guard)
    guard.daemon = True
    guard.start()

    platform = None
    try:
        from lightning_tpu.utils.jaxcfg import setup_cache

        setup_cache()
        platform = acquire_backend()
        if "--sweep" in sys.argv:
            guard.cancel()
            run_sweep(platform)
            return
        if "route" in sys.argv[1:]:
            r = run_route_bench(platform)
            guard.cancel()
            rline = compose_route_line(
                r["qps"], platform, batch=r["batch"],
                n_channels=r["n_channels"], host_rps=r["host_rps"],
                extra={"n_nodes": r["n_nodes"], "queries": r["queries"],
                       "fallbacks": r["fallbacks"],
                       "seconds": round(r["seconds"], 3),
                       "planes": r["planes"]})
            append_history(rline)
            print(json.dumps(rline), flush=True)
            return
        if "mcf" in sys.argv[1:]:
            r = run_mcf_bench(platform)
            guard.cancel()
            mline = compose_mcf_line(
                r["sps"], platform, batch=r["batch"],
                n_channels=r["n_channels"], host_rps=r["host_rps"],
                extra={"n_nodes": r["n_nodes"], "queries": r["queries"],
                       "fallbacks": r["fallbacks"],
                       "seconds": round(r["seconds"], 3),
                       "planes": r["planes"]})
            append_history(mline)
            print(json.dumps(mline), flush=True)
            return
        # --metrics: bracket the run with obs snapshots and embed the
        # diff, so an offline bench round reports through the SAME
        # counters a live daemon exposes via getmetrics / GET /metrics
        metrics_mode = "--metrics" in sys.argv
        snap0 = None
        if metrics_mode:
            from lightning_tpu import obs

            obs.ensure_installed()
            snap0 = obs.snapshot()
        r = run_bench(platform)
        guard.cancel()
        extra = {}
        if metrics_mode:
            from lightning_tpu import obs

            from tools.obs_snapshot import diff_snapshots

            extra["metrics"] = diff_snapshots(snap0, obs.snapshot())
        label = platform if platform not in ("cpu",) else "cpu-fallback"
        line = compose_line(
            round(r["throughput"], 1), label,
            engine=r.get("impl"), bucket=r.get("bucket"),
            extra={"n_sigs": r["n_sigs"],
                   "seconds": round(r["seconds"], 3),
                   "kernel_only": r.get("kernel_only"), **extra})
        append_history(line)
        print(json.dumps(line), flush=True)
    except Exception as e:
        guard.cancel()
        traceback.print_exc()
        if (platform not in (None, "cpu")
                and not os.environ.get("BENCH_FORCE_CPU")):
            # Accelerator died AFTER a successful probe (tunnel drop
            # mid-run).  The in-process backend is wedged; re-exec on CPU
            # in a child so the run still yields a labeled number.
            import subprocess

            print("bench: accelerator failed mid-run; re-running on CPU",
                  file=sys.stderr, flush=True)
            # Child gets only the REMAINING budget so the total stays
            # inside the driver deadline the watchdog promises.
            remaining = deadline - (time.monotonic() - t_start) - 15
            if remaining > 60:
                try:
                    child = subprocess.run(
                        [sys.executable, os.path.abspath(__file__)]
                        + (["route"] if "route" in sys.argv[1:] else
                           ["mcf"] if "mcf" in sys.argv[1:] else []),
                        env=dict(os.environ, BENCH_FORCE_CPU="1",
                                 BENCH_DEADLINE=str(int(remaining))),
                        capture_output=True, text=True, timeout=remaining,
                    )
                    sys.stderr.write(child.stderr[-2000:])
                    jl = [l for l in child.stdout.splitlines()
                          if l.startswith("{")]
                    if child.returncode == 0 and jl:
                        print(jl[-1], flush=True)
                        sys.exit(0)
                except subprocess.TimeoutExpired:
                    pass
        emit(0.0, 0.0, error=f"{type(e).__name__}: {e}")
        sys.exit(0)  # the JSON line IS the result; don't mask it with rc!=0


if __name__ == "__main__":
    main()
