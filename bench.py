#!/usr/bin/env python
"""Headline benchmark: gossip_store replay signature throughput on TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "sig_verifies_per_sec", "vs_baseline": N}

Workload (BASELINE.md configs 2-3): a synthetic gossip_store in the
reference's on-disk format — channel_announcements (4 ECDSA sigs each,
matching gossipd/sigcheck.c:45-113's cost model), channel_updates and
node_announcements (1 sig each) — replay-verified end to end: load →
native scan → field gathers → chained sha256d+ECDSA batched kernels.

vs_baseline divides by BASELINE_CPU_OPS = 50k verifies/sec, the upper end
of single-core libsecp256k1 throughput cited in BASELINE.md (the library
itself cannot be built here: vendored submodule is empty and the image has
no network).  Using the upper end keeps the ratio conservative.

Robustness (round-1 postmortem: the TPU backend failed to init and the
whole run died with parsed=null): backend acquisition retries with
backoff, falls back to the CPU backend with a smaller workload if the
accelerator never comes up, and ANY error still emits the JSON line
(value 0 + error detail) so the driver always has a parseable record.

Env knobs: BENCH_CHANNELS (default 25000 → ~112k sigs), BENCH_BUCKET,
BENCH_STORE (reuse an existing store file), BENCH_CPU_CHANNELS (fallback
workload size, default 200), BENCH_FORCE_CPU=1 (skip the accelerator
probe entirely), BENCH_PROBE_TIMEOUT/RETRIES, BENCH_DEADLINE (watchdog
seconds before a guaranteed JSON line + exit).
"""
import json
import os
import sys
import time
import tempfile
import traceback

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_CPU_OPS = 50_000.0
METRIC = "gossip_store_replay_sig_verify_throughput"
UNIT = "sig_verifies_per_sec"


def emit(value: float, vs_baseline: float, **extra):
    line = {"metric": METRIC, "value": value, "unit": UNIT,
            "vs_baseline": vs_baseline}
    line.update(extra)
    print(json.dumps(line), flush=True)


def acquire_backend() -> str:
    """Initialize a usable jax backend, preferring the accelerator.

    Returns the backend platform name.  The accelerator is probed in a
    SUBPROCESS with a hard timeout first: the TPU here sits behind a
    network tunnel and its init has been observed both to raise (round-1
    BENCH failure) and to hang indefinitely — an in-process hang is
    unrecoverable (the backend lock stays held), a dead subprocess is
    trivially recoverable.  Only after the probe succeeds does the main
    process touch jax; otherwise it forces the CPU platform so the
    benchmark still produces an honest (labeled) number instead of
    nothing.
    """
    import subprocess

    from lightning_tpu.utils.jaxcfg import force_cpu

    probed = None
    if not os.environ.get("BENCH_FORCE_CPU"):
        import subprocess

        retries = int(os.environ.get("BENCH_PROBE_RETRIES", "4"))
        # the tunnel has been observed to take >2 min to come up cold —
        # round-2 postmortem: a 150s probe timeout wrote off a live TPU
        probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "270"))
        # PROBE_OK sentinel line: imports may print banners to stdout.
        probe_src = ("import jax; print('PROBE_OK', jax.default_backend(), "
                     "len(jax.devices()))")
        for attempt in range(retries):
            try:
                p = subprocess.run(
                    [sys.executable, "-c", probe_src],
                    capture_output=True, text=True, timeout=probe_timeout,
                )
                lines = [l for l in p.stdout.splitlines()
                         if l.startswith("PROBE_OK")]
                if p.returncode == 0 and lines:
                    _, platform, ndev = lines[-1].split()[:3]
                    print(f"bench: backend probe ok: {platform} x{ndev}",
                          file=sys.stderr, flush=True)
                    probed = platform
                    break
                # FULL stderr: truncating it hid the actual TPU init
                # error from the round-2 record (VERDICT Weak #1)
                print(f"bench: backend probe attempt {attempt + 1}/{retries} "
                      f"rc={p.returncode}:\n{p.stderr.strip()}",
                      file=sys.stderr, flush=True)
            except subprocess.TimeoutExpired:
                print(f"bench: backend probe attempt {attempt + 1}/{retries} "
                      f"hung >{probe_timeout}s", file=sys.stderr, flush=True)
            if attempt < retries - 1:
                time.sleep(2.0 * (attempt + 1))
    if probed is None:
        print("bench: accelerator unavailable; falling back to CPU",
              file=sys.stderr, flush=True)
    if probed is None or probed == "cpu":
        # Degraded mode either way: trade runtime for compile time (cold
        # CPU compiles of the EC programs take ~4 min each at full opt).
        force_cpu(cheap_compile=True)

    import jax

    jax.devices()  # raises if even CPU is broken — caught by main's guard
    return jax.default_backend()


def run_bench(platform: str) -> dict:
    from lightning_tpu.gossip import store as gstore
    from lightning_tpu.gossip import synth, verify

    on_accel = platform not in ("cpu",)
    # Big fixed bucket on the real accelerator: amortizes per-dispatch
    # latency (the TPU sits behind a network tunnel here) and keeps one
    # compiled program for any store size.  The CPU fallback gets a small
    # workload so the run finishes at all.
    if on_accel:
        n_channels = int(os.environ.get("BENCH_CHANNELS", "25000"))
        # 8192 is the measured throughput sweet spot on v5e: bigger
        # buckets spill the per-element window tables out of effective
        # cache (honest readback timing: 29.2k/s @8192, 19.5k @16384,
        # 11.9k @32768)
        bucket = int(os.environ.get("BENCH_BUCKET", "8192"))
    else:
        # bucket 64 = the unit-test bucket, warm in the persistent cache
        n_channels = int(os.environ.get("BENCH_CPU_CHANNELS", "200"))
        bucket = int(os.environ.get("BENCH_BUCKET", "64"))

    path = os.environ.get("BENCH_STORE")
    is_temp_store = not path or not os.path.exists(path)
    if is_temp_store:
        path = os.path.join(tempfile.gettempdir(), f"bench_store_{n_channels}.gs")
        if not os.path.exists(path):
            # write-then-rename: a run killed mid-synthesis must not leave
            # a truncated store that poisons every later run
            tmp = path + f".tmp.{os.getpid()}"
            synth.make_network_store(
                tmp, n_channels=n_channels, n_nodes=max(2, n_channels // 8),
                updates_per_channel=2,
                sign_bucket=(synth.SIGN_BUCKET if on_accel else 64),
            )
            os.replace(tmp, path)

    idx = gstore.load_store(path)
    crc_ok = idx.check_crcs()
    if not crc_ok.all():
        if is_temp_store:
            os.unlink(path)  # don't poison the next run
        raise AssertionError("store CRC failure")

    # Warm-up: compiles the kernel (cached persistently) and pages data in.
    res = verify.verify_store(idx, bucket=bucket)
    assert res.ca_valid.all() and res.cu_valid.all() and res.na_valid.all(), (
        "benchmark store failed verification — kernel bug"
    )

    # Timed replay: full host+device pipeline, fresh store scan included.
    t0 = time.perf_counter()
    idx2 = gstore.load_store(path)
    res2 = verify.verify_store(idx2, bucket=bucket)
    dt = time.perf_counter() - t0
    return {"n_sigs": res2.n_sigs, "seconds": dt,
            "throughput": res2.n_sigs / dt}


def main():
    # A hang is not an Exception: if the tunnel drops after the probe, the
    # try/except below never fires.  The watchdog emits the JSON line and
    # hard-exits before the driver deadline so `parsed` is never null.
    import threading

    t_start = time.monotonic()
    deadline = float(os.environ.get("BENCH_DEADLINE", "2400"))

    def _hang_guard():
        emit(0.0, 0.0, error=f"watchdog: exceeded {deadline}s deadline")
        os._exit(0)

    guard = threading.Timer(deadline, _hang_guard)
    guard.daemon = True
    guard.start()

    platform = None
    try:
        from lightning_tpu.utils.jaxcfg import setup_cache

        setup_cache()
        platform = acquire_backend()
        r = run_bench(platform)
        guard.cancel()
        label = platform if platform not in ("cpu",) else "cpu-fallback"
        emit(round(r["throughput"], 1),
             round(r["throughput"] / BASELINE_CPU_OPS, 3),
             n_sigs=r["n_sigs"], seconds=round(r["seconds"], 3),
             platform=label)
    except Exception as e:
        guard.cancel()
        traceback.print_exc()
        if (platform not in (None, "cpu")
                and not os.environ.get("BENCH_FORCE_CPU")):
            # Accelerator died AFTER a successful probe (tunnel drop
            # mid-run).  The in-process backend is wedged; re-exec on CPU
            # in a child so the run still yields a labeled number.
            import subprocess

            print("bench: accelerator failed mid-run; re-running on CPU",
                  file=sys.stderr, flush=True)
            # Child gets only the REMAINING budget so the total stays
            # inside the driver deadline the watchdog promises.
            remaining = deadline - (time.monotonic() - t_start) - 15
            if remaining > 60:
                try:
                    child = subprocess.run(
                        [sys.executable, os.path.abspath(__file__)],
                        env=dict(os.environ, BENCH_FORCE_CPU="1",
                                 BENCH_DEADLINE=str(int(remaining))),
                        capture_output=True, text=True, timeout=remaining,
                    )
                    sys.stderr.write(child.stderr[-2000:])
                    jl = [l for l in child.stdout.splitlines()
                          if l.startswith("{")]
                    if child.returncode == 0 and jl:
                        print(jl[-1], flush=True)
                        sys.exit(0)
                except subprocess.TimeoutExpired:
                    pass
        emit(0.0, 0.0, error=f"{type(e).__name__}: {e}")
        sys.exit(0)  # the JSON line IS the result; don't mask it with rc!=0


if __name__ == "__main__":
    main()
