#!/usr/bin/env python
"""Headline benchmark: gossip_store replay signature throughput on TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "sig_verifies_per_sec", "vs_baseline": N}

Workload (BASELINE.md configs 2-3): a synthetic gossip_store in the
reference's on-disk format — channel_announcements (4 ECDSA sigs each,
matching gossipd/sigcheck.c:45-113's cost model), channel_updates and
node_announcements (1 sig each) — replay-verified end to end: mmap →
native scan → field gathers → fused sha256d+ECDSA batched kernel.

vs_baseline divides by BASELINE_CPU_OPS = 50k verifies/sec, the upper end
of single-core libsecp256k1 throughput cited in BASELINE.md (the library
itself cannot be built here: vendored submodule is empty and the image has
no network).  Using the upper end keeps the ratio conservative.

Env knobs: BENCH_CHANNELS (default 25000 → ~112k sigs), BENCH_BUCKET,
BENCH_STORE (reuse an existing store file), BENCH_METRIC=replay|kernel.
"""
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_CPU_OPS = 50_000.0


def main():
    from lightning_tpu.utils.jaxcfg import setup_cache

    setup_cache()
    import numpy as np

    from lightning_tpu.gossip import store as gstore
    from lightning_tpu.gossip import synth, verify

    # Big fixed bucket on the real accelerator: amortizes per-dispatch
    # latency (the TPU sits behind a network tunnel here) and keeps one
    # compiled program for any store size.
    n_channels = int(os.environ.get("BENCH_CHANNELS", "25000"))
    bucket = int(os.environ.get("BENCH_BUCKET", "16384"))

    path = os.environ.get("BENCH_STORE")
    if not path or not os.path.exists(path):
        path = os.path.join(tempfile.gettempdir(), f"bench_store_{n_channels}.gs")
        if not os.path.exists(path):
            synth.make_network_store(
                path, n_channels=n_channels, n_nodes=max(2, n_channels // 8),
                updates_per_channel=2,
            )

    idx = gstore.load_store(path)
    crc_ok = idx.check_crcs()
    assert crc_ok.all(), "store CRC failure"

    # Warm-up: compiles the kernel (cached persistently) and pages data in.
    res = verify.verify_store(idx, bucket=bucket)
    assert res.ca_valid.all() and res.cu_valid.all() and res.na_valid.all(), (
        "benchmark store failed verification — kernel bug"
    )

    # Timed replay: full host+device pipeline, fresh store scan included.
    t0 = time.perf_counter()
    idx2 = gstore.load_store(path)
    res2 = verify.verify_store(idx2, bucket=bucket)
    dt = time.perf_counter() - t0
    n_sigs = res2.n_sigs
    throughput = n_sigs / dt

    print(json.dumps({
        "metric": "gossip_store_replay_sig_verify_throughput",
        "value": round(throughput, 1),
        "unit": "sig_verifies_per_sec",
        "vs_baseline": round(throughput / BASELINE_CPU_OPS, 3),
    }))


if __name__ == "__main__":
    main()
