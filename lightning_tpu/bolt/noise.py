"""BOLT#8 Noise_XK transport: handshake + AEAD message framing.

Functional equivalent of the reference's connectd/handshake.c (3-act
Noise_XK with secp256k1 / ChaChaPoly / SHA256) and common/cryptomsg.c
(length-prefixed AEAD framing with key rotation every 1000 messages).
Written from the BOLT#8 spec.

This is per-connection serial CPU work (SURVEY.md §2.4: not batchable
across the fleet boundary cheaply), so it uses the `cryptography` package
for ChaCha20-Poly1305 and exact-int host math for the handful of ECDH
point-multiplies per handshake.
"""
from __future__ import annotations

import hashlib
import hmac as hmac_mod
from dataclasses import dataclass, field

from cryptography.exceptions import InvalidTag
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

from ..crypto import ref_python as ref

PROTOCOL_NAME = b"Noise_XK_secp256k1_ChaChaPoly_SHA256"
PROLOGUE = b"lightning"
ACT_ONE_SIZE = 50
ACT_TWO_SIZE = 50
ACT_THREE_SIZE = 66
REKEY_INTERVAL = 1000


def sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def hkdf2(salt: bytes, ikm: bytes) -> tuple[bytes, bytes]:
    """HKDF-SHA256, zero info, 64 bytes out split in two (BOLT#8)."""
    prk = hmac_mod.new(salt, ikm, hashlib.sha256).digest()
    t1 = hmac_mod.new(prk, b"\x01", hashlib.sha256).digest()
    t2 = hmac_mod.new(prk, t1 + b"\x02", hashlib.sha256).digest()
    return t1, t2


def ecdh(privkey: int, pubkey: ref.Point) -> bytes:
    """BOLT#8 ECDH: sha256 of the compressed shared point."""
    return sha256(ref.pubkey_serialize(ref.point_mul(privkey, pubkey)))


def _nonce(n: int) -> bytes:
    return b"\x00" * 4 + n.to_bytes(8, "little")


def encrypt_with_ad(key: bytes, nonce: int, ad: bytes, plaintext: bytes) -> bytes:
    return ChaCha20Poly1305(key).encrypt(_nonce(nonce), plaintext, ad)


def decrypt_with_ad(key: bytes, nonce: int, ad: bytes, ciphertext: bytes) -> bytes:
    try:
        return ChaCha20Poly1305(key).decrypt(_nonce(nonce), ciphertext, ad)
    except InvalidTag:
        # normalize to our own error so transport/peer layers can handle
        # "bad bytes from the network" without importing cryptography
        raise HandshakeError("AEAD tag failure") from None


@dataclass
class Keypair:
    priv: int
    pub: ref.Point = None

    def __post_init__(self):
        if self.pub is None:
            self.pub = ref.pubkey_create(self.priv)

    @property
    def pub_bytes(self) -> bytes:
        return ref.pubkey_serialize(self.pub)


class HandshakeError(Exception):
    pass


class HandshakeState:
    """Symmetric+handshake state shared by both roles."""

    def __init__(self, responder_pub: ref.Point):
        self.ck = sha256(PROTOCOL_NAME)
        self.h = sha256(self.ck + PROLOGUE)
        self.mix_hash(ref.pubkey_serialize(responder_pub))
        self.temp_k2: bytes | None = None

    def mix_hash(self, data: bytes):
        self.h = sha256(self.h + data)

    def mix_key(self, ikm: bytes) -> bytes:
        self.ck, temp_k = hkdf2(self.ck, ikm)
        return temp_k


@dataclass
class TransportKeys:
    sk: bytes  # sending key
    rk: bytes  # receiving key
    ck: bytes  # chaining key for rotation
    remote_pub: ref.Point


def initiator_act1(hs: HandshakeState, e: Keypair, rs: ref.Point) -> bytes:
    hs.mix_hash(e.pub_bytes)
    temp_k1 = hs.mix_key(ecdh(e.priv, rs))
    c = encrypt_with_ad(temp_k1, 0, hs.h, b"")
    hs.mix_hash(c)
    return b"\x00" + e.pub_bytes + c


def responder_act1(hs: HandshakeState, s: Keypair, act1: bytes) -> ref.Point:
    if len(act1) != ACT_ONE_SIZE or act1[0] != 0:
        raise HandshakeError("bad act1")
    re_pub = ref.pubkey_parse(act1[1:34])
    hs.mix_hash(act1[1:34])
    temp_k1 = hs.mix_key(ecdh(s.priv, re_pub))
    decrypt_with_ad(temp_k1, 0, hs.h, act1[34:])  # raises on tag failure
    hs.mix_hash(act1[34:])
    return re_pub


def responder_act2(hs: HandshakeState, e: Keypair, re_pub: ref.Point) -> bytes:
    hs.mix_hash(e.pub_bytes)
    hs.temp_k2 = hs.mix_key(ecdh(e.priv, re_pub))
    c = encrypt_with_ad(hs.temp_k2, 0, hs.h, b"")
    hs.mix_hash(c)
    return b"\x00" + e.pub_bytes + c


def initiator_act2(hs: HandshakeState, e: Keypair, act2: bytes) -> ref.Point:
    if len(act2) != ACT_TWO_SIZE or act2[0] != 0:
        raise HandshakeError("bad act2")
    re_pub = ref.pubkey_parse(act2[1:34])
    hs.mix_hash(act2[1:34])
    hs.temp_k2 = hs.mix_key(ecdh(e.priv, re_pub))
    decrypt_with_ad(hs.temp_k2, 0, hs.h, act2[34:])
    hs.mix_hash(act2[34:])
    return re_pub


def initiator_act3(hs: HandshakeState, s: Keypair, re_pub: ref.Point) -> tuple[bytes, TransportKeys]:
    c = encrypt_with_ad(hs.temp_k2, 1, hs.h, s.pub_bytes)
    hs.mix_hash(c)
    temp_k3 = hs.mix_key(ecdh(s.priv, re_pub))
    t = encrypt_with_ad(temp_k3, 0, hs.h, b"")
    sk, rk = hkdf2(hs.ck, b"")
    return b"\x00" + c + t, TransportKeys(sk, rk, hs.ck, re_pub)


def responder_act3(hs: HandshakeState, e: Keypair, act3: bytes) -> TransportKeys:
    if len(act3) != ACT_THREE_SIZE or act3[0] != 0:
        raise HandshakeError("bad act3")
    c, t = act3[1:50], act3[50:]
    rs_bytes = decrypt_with_ad(hs.temp_k2, 1, hs.h, c)
    rs_pub = ref.pubkey_parse(rs_bytes)
    hs.mix_hash(c)
    temp_k3 = hs.mix_key(ecdh(e.priv, rs_pub))
    decrypt_with_ad(temp_k3, 0, hs.h, t)
    rk, sk = hkdf2(hs.ck, b"")
    return TransportKeys(sk, rk, hs.ck, rs_pub)


def initiator_handshake(s: Keypair, e: Keypair, responder_pub: ref.Point):
    """Returns (act1_bytes, continuation) — continuation(act2) → (act3, keys)."""
    hs = HandshakeState(responder_pub)
    act1 = initiator_act1(hs, e, responder_pub)

    def on_act2(act2: bytes):
        re_pub = initiator_act2(hs, e, act2)
        act3, keys = initiator_act3(hs, s, re_pub)
        # the peer's identity is its static key (known a priori in XK),
        # not the ephemeral used for act2
        keys.remote_pub = responder_pub
        return act3, keys

    return act1, on_act2


def responder_handshake(s: Keypair, e: Keypair):
    """Returns continuation(act1) → (act2, continuation2(act3) → keys)."""
    hs = HandshakeState(s.pub)

    def on_act1(act1: bytes):
        re_pub = responder_act1(hs, s, act1)
        act2 = responder_act2(hs, e, re_pub)

        def on_act3(act3: bytes):
            return responder_act3(hs, e, act3)

        return act2, on_act3

    return on_act1


class CryptoMsg:
    """Post-handshake AEAD framing (common/cryptomsg.c equivalent):
    2-byte big-endian length encrypted+tagged, then payload encrypted+
    tagged; independent nonce counters; rekey every 1000 messages."""

    def __init__(self, keys: TransportKeys):
        self.sk, self.rk, self.ck = keys.sk, keys.rk, keys.ck
        self.sck = self.rck = self.ck
        self.sn = self.rn = 0
        self.remote_pub = keys.remote_pub

    def _maybe_rotate_send(self):
        if self.sn == REKEY_INTERVAL:
            self.sck, self.sk = hkdf2(self.sck, self.sk)
            self.sn = 0

    def _maybe_rotate_recv(self):
        if self.rn == REKEY_INTERVAL:
            self.rck, self.rk = hkdf2(self.rck, self.rk)
            self.rn = 0

    def encrypt(self, msg: bytes) -> bytes:
        if len(msg) > 65535:
            raise ValueError("message too long")
        self._maybe_rotate_send()
        lc = encrypt_with_ad(self.sk, self.sn, b"", len(msg).to_bytes(2, "big"))
        self.sn += 1
        mc = encrypt_with_ad(self.sk, self.sn, b"", msg)
        self.sn += 1
        return lc + mc

    def decrypt_length(self, hdr: bytes) -> int:
        self._maybe_rotate_recv()
        ln = decrypt_with_ad(self.rk, self.rn, b"", hdr)
        self.rn += 1
        return int.from_bytes(ln, "big")

    def decrypt_body(self, body: bytes) -> bytes:
        msg = decrypt_with_ad(self.rk, self.rn, b"", body)
        self.rn += 1
        return msg

    def decrypt(self, frame: bytes) -> bytes:
        ln = self.decrypt_length(frame[:18])
        if len(frame) != 18 + ln + 16:
            raise ValueError("frame length mismatch")
        return self.decrypt_body(frame[18:])
