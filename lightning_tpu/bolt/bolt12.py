"""BOLT#12 offers: TLV models, merkle signatures, and bech32 strings.

Functional parity target: the reference's common/bolt12.c (decode/encode
:?), common/bolt12_merkle.c (signature merkle tree), and the lno1/lnr1/
lni1 string forms — re-implemented from the BOLT#12 spec text.

Strings are bech32-charset *without a checksum* (BOLT#12: the signature
already authenticates content), case-insensitive, and may contain `+`
(with optional whitespace) joining parts split for transport.

Signatures cover a tagged merkle root over the non-signature TLV fields:
each field leaf H("LnLeaf", tlv) is paired with a per-field nonce leaf
H("LnNonce"||first_tlv, bigsize(type)); pairs combine upward with
H("LnBranch", lesser||greater), an unpaired node promoting to the next
level.  The BIP340 signature is over
H("lightning" || messagename || fieldname, merkle_root).
"""
from __future__ import annotations

import re
import time
from dataclasses import dataclass, field

from ..crypto import ref_python as ref
from ..wire.codec import read_tlv_stream, write_bigsize, write_tlv_stream
from .bolt11 import CHARSET, _REV
from .blindedpath import BlindedPath, _tu as _tu_shared

SIGNATURE = 240

# offer fields (also embedded in invoice_request / invoice)
OFFER_CHAINS = 2
OFFER_METADATA = 4
OFFER_CURRENCY = 6
OFFER_AMOUNT = 8
OFFER_DESCRIPTION = 10
OFFER_FEATURES = 12
OFFER_ABSOLUTE_EXPIRY = 14
OFFER_PATHS = 16
OFFER_ISSUER = 18
OFFER_QUANTITY_MAX = 20
OFFER_ISSUER_ID = 22

INVREQ_METADATA = 0
INVREQ_CHAIN = 80
INVREQ_AMOUNT = 82
INVREQ_FEATURES = 84
INVREQ_QUANTITY = 86
INVREQ_PAYER_ID = 88
INVREQ_PAYER_NOTE = 89

INVOICE_PATHS = 160
INVOICE_BLINDEDPAY = 162
INVOICE_CREATED_AT = 164
INVOICE_RELATIVE_EXPIRY = 166
INVOICE_PAYMENT_HASH = 168
INVOICE_AMOUNT = 170
INVOICE_FALLBACKS = 172
INVOICE_FEATURES = 174
INVOICE_NODE_ID = 176

# BOLT#12 recurrence draft (wire numbers from the spec's experimental
# ranges; offer fields mirror into the invreq/invoice like the rest)
OFFER_RECURRENCE = 1000000025            # recurrence{time_unit, period}
OFFER_RECURRENCE_LIMIT = 1000000029      # max_period_index tu32
INVREQ_RECURRENCE_COUNTER = 2000000092   # tu32
INVREQ_RECURRENCE_START = 2000000093     # tu32 period offset
INVREQ_RECURRENCE_CANCEL = 2000000094    # presence = stop recurring
INVOICE_RECURRENCE_BASETIME = 3000000177  # tu64

# seconds per recurrence time_unit (draft: 0=seconds, 1=days,
# 2=months≈30d, 3=years≈365d — calendar math approximated)
RECURRENCE_UNIT_SECONDS = {0: 1, 1: 86_400, 2: 30 * 86_400,
                           3: 365 * 86_400}

DEFAULT_INVOICE_EXPIRY = 7200


class Bolt12Error(Exception):
    pass


# ---------------------------------------------------------------------------
# string form

def encode_string(hrp: str, tlv_bytes: bytes) -> str:
    acc, bits, data = 0, 0, []
    for b in tlv_bytes:
        acc = (acc << 8) | b
        bits += 8
        while bits >= 5:
            bits -= 5
            data.append((acc >> bits) & 31)
    if bits:
        data.append((acc << (5 - bits)) & 31)
    return hrp + "1" + "".join(CHARSET[d] for d in data)


def decode_string(s: str) -> tuple[str, bytes]:
    s = re.sub(r"\+\s*", "", s.strip())   # transport continuations
    if s.lower() != s and s.upper() != s:
        raise Bolt12Error("mixed case")
    s = s.lower()
    pos = s.rfind("1")
    if pos < 1:
        raise Bolt12Error("no hrp separator")
    hrp, rest = s[:pos], s[pos + 1:]
    try:
        data = [_REV[c] for c in rest]
    except KeyError as e:
        raise Bolt12Error(f"invalid character {e.args[0]!r}")
    acc, bits, out = 0, 0, bytearray()
    for v in data:
        acc = (acc << 5) | v
        bits += 5
        while bits >= 8:
            bits -= 8
            out.append((acc >> bits) & 0xFF)
    if bits and (acc & ((1 << bits) - 1)):
        raise Bolt12Error("non-zero padding")
    return hrp, bytes(out)


# ---------------------------------------------------------------------------
# merkle signature scheme (common/bolt12_merkle.c semantics, from spec)

def _H(tag: bytes, msg: bytes) -> bytes:
    import hashlib

    th = hashlib.sha256(tag).digest()
    return hashlib.sha256(th + th + msg).digest()


def _branch(a: bytes, b: bytes) -> bytes:
    lesser, greater = (a, b) if a < b else (b, a)
    return _H(b"LnBranch", lesser + greater)


def _tlv_entries(tlvs: dict[int, bytes]) -> list[tuple[int, bytes]]:
    return [(t, write_bigsize(t) + write_bigsize(len(v)) + v)
            for t, v in sorted(tlvs.items())]


def _leaf_level(tlvs: dict[int, bytes]) -> list[tuple[int, bytes,
                                                      bytes, bytes]]:
    """Shared leaf construction for merkle_root AND merkle_path (the
    derivation is spec-sensitive — one copy only): returns
    [(type, wire, nonce_hash, level0_node)] for every signed field."""
    entries = [(t, w) for t, w in _tlv_entries(tlvs)
               if not (SIGNATURE <= t <= 1000)]
    if not entries:
        raise Bolt12Error("no fields to sign")
    first_tlv = entries[0][1]
    out = []
    for t, wire in entries:
        leaf = _H(b"LnLeaf", wire)
        nonce = _H(b"LnNonce" + first_tlv, write_bigsize(t))
        out.append((t, wire, nonce, _branch(leaf, nonce)))
    return out


def merkle_root(tlvs: dict[int, bytes]) -> bytes:
    level = [node for _t, _w, _n, node in _leaf_level(tlvs)]
    while len(level) > 1:
        nxt = [_branch(level[i], level[i + 1])
               for i in range(0, len(level) - 1, 2)]
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
    return level[0]


def merkle_paths(tlvs: dict[int, bytes], field_types: list[int],
                 ) -> tuple[bytes, dict[int, tuple[bytes, bytes,
                                                   list[bytes]]]]:
    """Inclusion proofs for several TLVs from ONE tree construction
    (createproof's evidence format): returns (root, {field_type:
    (leaf_wire, nonce_hash, siblings)}).  A verifier recomputes
    fold(_branch(H(LnLeaf, leaf_wire), nonce_hash), siblings) and
    compares it to the root the invoice signature covers — proving the
    field value belongs to the signed invoice without revealing the
    other fields."""
    leaves = _leaf_level(tlvs)
    level = [node for _t, _w, _n, node in leaves]
    track: dict[int, dict] = {}
    for want in field_types:
        for i, (t, wire, nonce, _node) in enumerate(leaves):
            if t == want:
                track[want] = {"idx": i, "wire": wire,
                               "nonce": nonce, "sibs": []}
                break
        else:
            raise Bolt12Error(f"field {want} not present")
    while len(level) > 1:
        nxt = []
        positions = {w: tr["idx"] for w, tr in track.items()}
        for i in range(0, len(level) - 1, 2):
            for w, idx in positions.items():
                if idx in (i, i + 1):
                    track[w]["sibs"].append(
                        level[i + 1] if idx == i else level[i])
                    track[w]["idx"] = len(nxt)
            nxt.append(_branch(level[i], level[i + 1]))
        if len(level) % 2:
            for w, idx in positions.items():
                if idx == len(level) - 1:
                    track[w]["idx"] = len(nxt)
            nxt.append(level[-1])
        level = nxt
    return level[0], {w: (tr["wire"], tr["nonce"], tr["sibs"])
                      for w, tr in track.items()}


def merkle_path(tlvs: dict[int, bytes],
                field_type: int) -> tuple[bytes, bytes, list[bytes]]:
    """Single-field convenience wrapper over merkle_paths."""
    _root, paths = merkle_paths(tlvs, [field_type])
    return paths[field_type]


def verify_merkle_path(root: bytes, leaf_wire: bytes, nonce_hash: bytes,
                       siblings: list[bytes]) -> bool:
    """Check a merkle_path proof against the signed root.  _branch
    sorts its operands, so sibling ORDER along the path is all the
    proof needs to carry."""
    h = _branch(_H(b"LnLeaf", leaf_wire), nonce_hash)
    for s in siblings:
        h = _branch(h, s)
    return h == root


def sig_hash(messagename: str, fieldname: str, tlvs: dict[int, bytes]) -> bytes:
    tag = b"lightning" + messagename.encode() + fieldname.encode()
    return _H(tag, merkle_root(tlvs))


def sign(messagename: str, tlvs: dict[int, bytes], seckey: int) -> bytes:
    """BIP340 signature over the merkle sig-hash; stored as TLV 240."""
    return ref.schnorr_sign(sig_hash(messagename, "signature", tlvs), seckey)


def check_signature(messagename: str, tlvs: dict[int, bytes],
                    pubkey33_or_x: bytes) -> bool:
    sig = tlvs.get(SIGNATURE)
    if sig is None or len(sig) != 64:
        return False
    unsigned = {t: v for t, v in tlvs.items() if not (SIGNATURE <= t <= 1000)}
    h = sig_hash(messagename, "signature", unsigned)
    x = pubkey33_or_x[-32:] if len(pubkey33_or_x) == 33 else pubkey33_or_x
    return ref.schnorr_verify(h, int.from_bytes(x, "big"), sig)


# ---------------------------------------------------------------------------
# helpers shared by the three models

_tu = _tu_shared   # BOLT truncated uint; one impl (blindedpath.py)


def _tu_read(v: bytes) -> int:
    return int.from_bytes(v, "big")


def _paths_bytes(paths: list[BlindedPath]) -> bytes:
    return b"".join(p.serialize() for p in paths)


def _paths_parse(v: bytes) -> list[BlindedPath]:
    from .blindedpath import BlindedPathError

    out, off = [], 0
    try:
        while off < len(v):
            p, off = BlindedPath.parse(v, off)
            out.append(p)
    except (BlindedPathError, IndexError) as e:
        # attacker-controlled bytes: surface OUR error type, never the
        # path codec's (callers catch Bolt12Error; fuzz finding)
        raise Bolt12Error(f"bad blinded path: {e}") from None
    return out


@dataclass
class Offer:
    """lno1... — a reusable invitation to request invoices."""
    description: str | None = None
    amount_msat: int | None = None
    currency: str | None = None
    issuer: str | None = None
    issuer_id: bytes | None = None        # 33-byte pubkey
    chains: list[bytes] = field(default_factory=list)
    metadata: bytes | None = None
    features: bytes = b""
    absolute_expiry: int | None = None
    quantity_max: int | None = None
    paths: list[BlindedPath] = field(default_factory=list)
    # recurrence draft: (time_unit, period) makes the offer repeat
    recurrence: tuple[int, int] | None = None
    recurrence_limit: int | None = None   # last valid period index

    def tlvs(self) -> dict[int, bytes]:
        t: dict[int, bytes] = {}
        if self.chains:
            t[OFFER_CHAINS] = b"".join(self.chains)
        if self.metadata is not None:
            t[OFFER_METADATA] = self.metadata
        if self.currency is not None:
            t[OFFER_CURRENCY] = self.currency.encode()
            if self.amount_msat is None:
                raise Bolt12Error("currency requires amount")
        if self.amount_msat is not None:
            t[OFFER_AMOUNT] = _tu(self.amount_msat)
        if self.description is not None:
            t[OFFER_DESCRIPTION] = self.description.encode()
        if self.features:
            t[OFFER_FEATURES] = self.features
        if self.absolute_expiry is not None:
            t[OFFER_ABSOLUTE_EXPIRY] = _tu(self.absolute_expiry)
        if self.paths:
            t[OFFER_PATHS] = _paths_bytes(self.paths)
        if self.issuer is not None:
            t[OFFER_ISSUER] = self.issuer.encode()
        if self.quantity_max is not None:
            t[OFFER_QUANTITY_MAX] = _tu(self.quantity_max)
        if self.issuer_id is not None:
            t[OFFER_ISSUER_ID] = self.issuer_id
        if self.recurrence is not None:
            unit, period = self.recurrence
            t[OFFER_RECURRENCE] = bytes([unit]) + _tu(period)
        if self.recurrence_limit is not None:
            t[OFFER_RECURRENCE_LIMIT] = _tu(self.recurrence_limit)
        return t

    @classmethod
    def from_tlvs(cls, t: dict[int, bytes]) -> "Offer":
        o = cls()
        if OFFER_CHAINS in t:
            v = t[OFFER_CHAINS]
            o.chains = [v[i:i + 32] for i in range(0, len(v), 32)]
        o.metadata = t.get(OFFER_METADATA)
        if OFFER_CURRENCY in t:
            o.currency = t[OFFER_CURRENCY].decode()
        if OFFER_AMOUNT in t:
            o.amount_msat = _tu_read(t[OFFER_AMOUNT])
        if OFFER_DESCRIPTION in t:
            o.description = t[OFFER_DESCRIPTION].decode()
        o.features = t.get(OFFER_FEATURES, b"")
        if OFFER_ABSOLUTE_EXPIRY in t:
            o.absolute_expiry = _tu_read(t[OFFER_ABSOLUTE_EXPIRY])
        if OFFER_PATHS in t:
            o.paths = _paths_parse(t[OFFER_PATHS])
        if OFFER_ISSUER in t:
            o.issuer = t[OFFER_ISSUER].decode()
        if OFFER_QUANTITY_MAX in t:
            o.quantity_max = _tu_read(t[OFFER_QUANTITY_MAX])
        o.issuer_id = t.get(OFFER_ISSUER_ID)
        if OFFER_RECURRENCE in t:
            v = t[OFFER_RECURRENCE]
            if not v:
                raise Bolt12Error("empty recurrence")
            o.recurrence = (v[0], _tu_read(v[1:]))
        if OFFER_RECURRENCE_LIMIT in t:
            o.recurrence_limit = _tu_read(t[OFFER_RECURRENCE_LIMIT])
        return o

    def offer_id(self) -> bytes:
        """Merkle root of the offer fields — the stable dedup id."""
        return merkle_root(self.tlvs())

    def encode(self) -> str:
        t = self.tlvs()
        if self.issuer_id is None and not self.paths:
            raise Bolt12Error("offer needs issuer_id or paths")
        if self.description is None and self.amount_msat is not None:
            raise Bolt12Error("offer with amount needs description")
        return encode_string("lno", write_tlv_stream(t))

    @classmethod
    def decode(cls, s: str) -> "Offer":
        hrp, raw = decode_string(s)
        if hrp != "lno":
            raise Bolt12Error(f"not an offer: {hrp!r}")
        return cls.from_tlvs(read_tlv_stream(raw))


@dataclass
class InvoiceRequest:
    """lnr1... — a (signed) request for an invoice against an offer."""
    offer: Offer
    metadata: bytes = b""                 # payer-chosen key-binding blob
    payer_id: bytes = b""                 # 33-byte pubkey (signing key)
    chain: bytes | None = None
    amount_msat: int | None = None
    quantity: int | None = None
    payer_note: str | None = None
    features: bytes = b""
    signature: bytes | None = None
    # recurrence draft: which period this request pays for
    recurrence_counter: int | None = None
    recurrence_start: int | None = None
    recurrence_cancel: bool = False       # stop the recurrence instead

    def tlvs(self, with_sig: bool = True) -> dict[int, bytes]:
        t = self.offer.tlvs()
        t[INVREQ_METADATA] = self.metadata
        if self.chain is not None:
            t[INVREQ_CHAIN] = self.chain
        if self.amount_msat is not None:
            t[INVREQ_AMOUNT] = _tu(self.amount_msat)
        if self.features:
            t[INVREQ_FEATURES] = self.features
        if self.quantity is not None:
            t[INVREQ_QUANTITY] = _tu(self.quantity)
        t[INVREQ_PAYER_ID] = self.payer_id
        if self.payer_note is not None:
            t[INVREQ_PAYER_NOTE] = self.payer_note.encode()
        if self.recurrence_counter is not None:
            t[INVREQ_RECURRENCE_COUNTER] = _tu(self.recurrence_counter)
        if self.recurrence_start is not None:
            t[INVREQ_RECURRENCE_START] = _tu(self.recurrence_start)
        if self.recurrence_cancel:
            t[INVREQ_RECURRENCE_CANCEL] = b""
        if with_sig and self.signature is not None:
            t[SIGNATURE] = self.signature
        return t

    def sign(self, payer_seckey: int) -> None:
        self.signature = sign("invoice_request", self.tlvs(with_sig=False),
                              payer_seckey)

    def check_signature(self) -> bool:
        return check_signature("invoice_request", self.tlvs(), self.payer_id)

    def serialize(self) -> bytes:
        if self.signature is None:
            raise Bolt12Error("invoice_request must be signed")
        return write_tlv_stream(self.tlvs())

    def encode(self) -> str:
        return encode_string("lnr", self.serialize())

    @classmethod
    def from_tlvs(cls, t: dict[int, bytes]) -> "InvoiceRequest":
        offer = Offer.from_tlvs(
            {k: v for k, v in t.items()
             if 1 <= k <= 79
             or 1_000_000_000 <= k < 2_000_000_000})
        r = cls(offer=offer,
                metadata=t.get(INVREQ_METADATA, b""),
                payer_id=t.get(INVREQ_PAYER_ID, b""))
        r.chain = t.get(INVREQ_CHAIN)
        if INVREQ_AMOUNT in t:
            r.amount_msat = _tu_read(t[INVREQ_AMOUNT])
        r.features = t.get(INVREQ_FEATURES, b"")
        if INVREQ_QUANTITY in t:
            r.quantity = _tu_read(t[INVREQ_QUANTITY])
        if INVREQ_PAYER_NOTE in t:
            r.payer_note = t[INVREQ_PAYER_NOTE].decode()
        if INVREQ_RECURRENCE_COUNTER in t:
            r.recurrence_counter = _tu_read(t[INVREQ_RECURRENCE_COUNTER])
        if INVREQ_RECURRENCE_START in t:
            r.recurrence_start = _tu_read(t[INVREQ_RECURRENCE_START])
        r.recurrence_cancel = INVREQ_RECURRENCE_CANCEL in t
        r.signature = t.get(SIGNATURE)
        return r

    @classmethod
    def parse(cls, raw: bytes) -> "InvoiceRequest":
        return cls.from_tlvs(read_tlv_stream(raw))

    def validate_against(self, offer: Offer) -> None:
        """Recipient-side checks (reference: invoice_request handling in
        plugins/offers_invreq_hook.c semantics)."""
        if not self.payer_id or len(self.payer_id) != 33:
            raise Bolt12Error("missing invreq_payer_id")
        if not self.metadata:
            raise Bolt12Error("missing invreq_metadata")
        if not self.check_signature():
            raise Bolt12Error("bad invoice_request signature")
        if merkle_root(offer.tlvs()) != merkle_root(self.offer.tlvs()):
            raise Bolt12Error("invoice_request does not match offer")
        if offer.currency is not None:
            # offer_amount is in fiat minor units; without a converter
            # any msat comparison would be nonsense (reference rejects
            # unless the currencyrate plugin converts)
            raise Bolt12Error(
                f"cannot convert {offer.currency} amount")
        amt = self.amount_msat
        if offer.amount_msat is not None:
            expect = offer.amount_msat * (self.quantity or 1)
            if amt is not None and amt < expect:
                raise Bolt12Error("invreq_amount below offer amount")
        elif amt is None:
            raise Bolt12Error("offer has no amount; invreq must set one")
        if offer.quantity_max is not None:
            q = self.quantity or 0
            if not (1 <= q <= (offer.quantity_max or 2 ** 64)):
                raise Bolt12Error("bad quantity")
        elif self.quantity is not None:
            raise Bolt12Error("quantity not allowed")
        if (offer.absolute_expiry is not None
                and time.time() > offer.absolute_expiry):
            raise Bolt12Error("offer expired")
        # recurrence draft rules: a recurring offer demands a counter;
        # a non-recurring one forbids the recurrence fields entirely
        if offer.recurrence is not None:
            if self.recurrence_counter is None:
                raise Bolt12Error(
                    "recurring offer needs invreq_recurrence_counter")
            if offer.recurrence_limit is not None \
                    and self.recurrence_counter > offer.recurrence_limit:
                raise Bolt12Error("recurrence_counter past the limit")
        else:
            if (self.recurrence_counter is not None
                    or self.recurrence_start is not None
                    or self.recurrence_cancel):
                raise Bolt12Error(
                    "recurrence fields on a non-recurring offer")


@dataclass
class Invoice12:
    """lni1... — a BOLT#12 invoice answering an invoice_request."""
    invreq: InvoiceRequest
    payment_hash: bytes = b""
    amount_msat: int = 0
    node_id: bytes = b""                  # 33-byte signing key
    created_at: int = 0
    relative_expiry: int | None = None
    paths: list[BlindedPath] = field(default_factory=list)
    blindedpay: list[tuple[int, int, int, int, int, bytes]] = field(
        default_factory=list)  # (fee_base, ppm, cltv, htlc_min, htlc_max, feat)
    features: bytes = b""
    fallbacks: bytes | None = None
    signature: bytes | None = None
    # recurrence draft: anchors period arithmetic for the whole chain
    recurrence_basetime: int | None = None

    def tlvs(self, with_sig: bool = True) -> dict[int, bytes]:
        t = self.invreq.tlvs()             # includes invreq signature (240)?
        t.pop(SIGNATURE, None)             # no: sig is ours to add
        if self.paths:
            t[INVOICE_PATHS] = _paths_bytes(self.paths)
        if self.blindedpay:
            out = b""
            for base, ppm, cltv, hmin, hmax, feat in self.blindedpay:
                out += (base.to_bytes(4, "big") + ppm.to_bytes(4, "big")
                        + cltv.to_bytes(2, "big") + hmin.to_bytes(8, "big")
                        + hmax.to_bytes(8, "big")
                        + len(feat).to_bytes(2, "big") + feat)
            t[INVOICE_BLINDEDPAY] = out
        t[INVOICE_CREATED_AT] = _tu(self.created_at)
        if self.relative_expiry is not None:
            t[INVOICE_RELATIVE_EXPIRY] = _tu(self.relative_expiry)
        t[INVOICE_PAYMENT_HASH] = self.payment_hash
        t[INVOICE_AMOUNT] = _tu(self.amount_msat)
        if self.fallbacks is not None:
            t[INVOICE_FALLBACKS] = self.fallbacks
        if self.features:
            t[INVOICE_FEATURES] = self.features
        t[INVOICE_NODE_ID] = self.node_id
        if self.recurrence_basetime is not None:
            t[INVOICE_RECURRENCE_BASETIME] = _tu(self.recurrence_basetime)
        if with_sig and self.signature is not None:
            t[SIGNATURE] = self.signature
        return t

    def sign(self, node_seckey: int) -> None:
        self.signature = sign("invoice", self.tlvs(with_sig=False),
                              node_seckey)

    def check_signature(self) -> bool:
        return check_signature("invoice", self.tlvs(), self.node_id)

    def serialize(self) -> bytes:
        if self.signature is None:
            raise Bolt12Error("invoice must be signed")
        return write_tlv_stream(self.tlvs())

    def encode(self) -> str:
        return encode_string("lni", self.serialize())

    @property
    def expires_at(self) -> int:
        return self.created_at + (self.relative_expiry
                                  or DEFAULT_INVOICE_EXPIRY)

    @classmethod
    def from_tlvs(cls, t: dict[int, bytes]) -> "Invoice12":
        # invreq fields: the classic <160 range PLUS the experimental
        # offer (1e9) and invreq (2e9) ranges the recurrence draft uses
        invreq = InvoiceRequest.from_tlvs(
            {k: v for k, v in t.items()
             if k < 160 or 1_000_000_000 <= k < 3_000_000_000})
        inv = cls(invreq=invreq,
                  payment_hash=t.get(INVOICE_PAYMENT_HASH, b""),
                  amount_msat=_tu_read(t.get(INVOICE_AMOUNT, b"")),
                  node_id=t.get(INVOICE_NODE_ID, b""),
                  created_at=_tu_read(t.get(INVOICE_CREATED_AT, b"")))
        if INVOICE_RECURRENCE_BASETIME in t:
            inv.recurrence_basetime = _tu_read(
                t[INVOICE_RECURRENCE_BASETIME])
        if INVOICE_RELATIVE_EXPIRY in t:
            inv.relative_expiry = _tu_read(t[INVOICE_RELATIVE_EXPIRY])
        if INVOICE_PATHS in t:
            inv.paths = _paths_parse(t[INVOICE_PATHS])
        if INVOICE_BLINDEDPAY in t:
            v, off = t[INVOICE_BLINDEDPAY], 0
            while off + 28 <= len(v):
                base = int.from_bytes(v[off:off + 4], "big")
                ppm = int.from_bytes(v[off + 4:off + 8], "big")
                cltv = int.from_bytes(v[off + 8:off + 10], "big")
                hmin = int.from_bytes(v[off + 10:off + 18], "big")
                hmax = int.from_bytes(v[off + 18:off + 26], "big")
                fl = int.from_bytes(v[off + 26:off + 28], "big")
                feat = v[off + 28:off + 28 + fl]
                off += 28 + fl
                inv.blindedpay.append((base, ppm, cltv, hmin, hmax, feat))
        inv.features = t.get(INVOICE_FEATURES, b"")
        inv.fallbacks = t.get(INVOICE_FALLBACKS)
        inv.signature = t.get(SIGNATURE)
        return inv

    @classmethod
    def parse(cls, raw: bytes) -> "Invoice12":
        return cls.from_tlvs(read_tlv_stream(raw))

    @classmethod
    def decode(cls, s: str) -> "Invoice12":
        hrp, raw = decode_string(s)
        if hrp != "lni":
            raise Bolt12Error(f"not an invoice: {hrp!r}")
        return cls.parse(raw)

    def validate_against(self, invreq: InvoiceRequest) -> None:
        """Payer-side checks before paying (plugins/fetchinvoice.c
        semantics)."""
        if not self.check_signature():
            raise Bolt12Error("bad invoice signature")
        if len(self.payment_hash) != 32:
            raise Bolt12Error("bad payment_hash")
        mine = invreq.tlvs()
        mine.pop(SIGNATURE, None)
        theirs = {k: v for k, v in self.tlvs().items()
                  if k < 160 or 1_000_000_000 <= k < 3_000_000_000}
        theirs.pop(SIGNATURE, None)
        if mine != theirs:
            raise Bolt12Error("invoice does not mirror invoice_request")
        if invreq.offer.recurrence is not None \
                and self.recurrence_basetime is None:
            # BOLT-recurrence #12: period arithmetic is anchored here
            raise Bolt12Error("recurring invoice lacks basetime")
        offer = invreq.offer
        if offer.issuer_id is not None:
            # Invoice must be signed by the issuer key UNCONDITIONALLY —
            # invoice_paths are attacker-controlled, so they must never
            # relax the signer check (plugins/fetchinvoice.c:240-248).
            if self.node_id != offer.issuer_id:
                raise Bolt12Error("invoice node_id != offer issuer_id")
        else:
            # Blinded-only offer: the signer must be one of the offer's
            # path tips (the blinded id the invreq was delivered to).
            tips = {p.hops[-1].blinded_node_id for p in offer.paths if p.hops}
            if self.node_id not in tips:
                raise Bolt12Error("invoice node_id not an offer path tip")
        if offer.currency is not None:
            raise Bolt12Error(
                f"cannot verify {offer.currency}-denominated amount")
        want = invreq.amount_msat
        if want is None and offer.amount_msat is not None:
            want = offer.amount_msat * (invreq.quantity or 1)
        if want is not None and self.amount_msat > want:
            raise Bolt12Error("invoice amount exceeds request")
