"""BOLT#4 hop payload TLVs (the content inside each sphinx frame).

Parity targets: common/onion_encode.c / onion_decode.c — the TLV fields
every payment hop carries: amt_to_forward(2, tu64),
outgoing_cltv_value(4, tu32), short_channel_id(6) for forwards,
payment_data(8: 32-byte secret + tu64 total) for the final hop.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..wire.codec import (
    WireError, read_tlv_stream, read_tu, write_tlv_stream, write_tu,
)

TLV_AMT_TO_FORWARD = 2
TLV_OUTGOING_CLTV = 4
TLV_SHORT_CHANNEL_ID = 6
TLV_PAYMENT_DATA = 8
# route blinding (BOLT#4 tlv_payload for blinded hops): the recipient
# data ciphertext and the path key used to unblind it
TLV_ENCRYPTED_RECIPIENT_DATA = 10
TLV_CURRENT_PATH_KEY = 12
TLV_TOTAL_AMOUNT_MSAT = 18
# keysend (spontaneous payment): the preimage rides the final-hop onion
# (plugins/keysend.c; de-facto standard record type)
TLV_KEYSEND_PREIMAGE = 5482373484


class PayloadError(Exception):
    pass


@dataclass
class HopPayload:
    amt_to_forward_msat: int
    outgoing_cltv: int
    short_channel_id: int | None = None  # present ⇔ forwarding hop
    payment_secret: bytes | None = None  # final hop (payment_data)
    total_msat: int | None = None
    keysend_preimage: bytes | None = None
    # blinded hop (bolt12 payment): ciphertext + unblinding key
    encrypted_recipient_data: bytes | None = None
    path_key: bytes | None = None

    @property
    def is_final(self) -> bool:
        return self.short_channel_id is None

    def serialize(self) -> bytes:
        tlvs: dict[int, bytes] = {
            TLV_AMT_TO_FORWARD: write_tu(self.amt_to_forward_msat, 8),
            TLV_OUTGOING_CLTV: write_tu(self.outgoing_cltv, 4),
        }
        if self.short_channel_id is not None:
            tlvs[TLV_SHORT_CHANNEL_ID] = self.short_channel_id.to_bytes(8, "big")
        if self.payment_secret is not None:
            tlvs[TLV_PAYMENT_DATA] = (
                self.payment_secret + write_tu(self.total_msat or 0, 8)
            )
        if self.keysend_preimage is not None:
            tlvs[TLV_KEYSEND_PREIMAGE] = self.keysend_preimage
        if self.encrypted_recipient_data is not None:
            tlvs[TLV_ENCRYPTED_RECIPIENT_DATA] = self.encrypted_recipient_data
        if self.path_key is not None:
            tlvs[TLV_CURRENT_PATH_KEY] = self.path_key
        if self.total_msat is not None and self.payment_secret is None:
            tlvs[TLV_TOTAL_AMOUNT_MSAT] = write_tu(self.total_msat, 8)
        return write_tlv_stream(tlvs)

    KNOWN_TLVS = frozenset({TLV_AMT_TO_FORWARD, TLV_OUTGOING_CLTV,
                            TLV_SHORT_CHANNEL_ID, TLV_PAYMENT_DATA,
                            TLV_KEYSEND_PREIMAGE,
                            TLV_ENCRYPTED_RECIPIENT_DATA,
                            TLV_CURRENT_PATH_KEY, TLV_TOTAL_AMOUNT_MSAT})

    @classmethod
    def parse(cls, content: bytes) -> "HopPayload":
        try:
            tlvs = read_tlv_stream(content)
            if TLV_AMT_TO_FORWARD not in tlvs or TLV_OUTGOING_CLTV not in tlvs:
                raise PayloadError("hop payload missing amt/cltv")
            # BOLT#4 it's-OK-to-be-odd: an unknown EVEN type means the
            # sender relies on semantics we don't implement — MUST fail
            for t in tlvs:
                if t % 2 == 0 and t not in cls.KNOWN_TLVS:
                    raise PayloadError(f"unknown even TLV type {t}")
            scid = None
            if TLV_SHORT_CHANNEL_ID in tlvs:
                raw = tlvs[TLV_SHORT_CHANNEL_ID]
                if len(raw) != 8:
                    raise PayloadError("bad short_channel_id length")
                scid = int.from_bytes(raw, "big")
            secret = total = None
            if TLV_PAYMENT_DATA in tlvs:
                raw = tlvs[TLV_PAYMENT_DATA]
                if not 32 <= len(raw) <= 40:
                    raise PayloadError("bad payment_data length")
                secret = raw[:32]
                total = read_tu(raw[32:], 8)
            if TLV_TOTAL_AMOUNT_MSAT in tlvs:
                total = read_tu(tlvs[TLV_TOTAL_AMOUNT_MSAT], 8)
            return cls(
                amt_to_forward_msat=read_tu(tlvs[TLV_AMT_TO_FORWARD], 8),
                outgoing_cltv=read_tu(tlvs[TLV_OUTGOING_CLTV], 4),
                short_channel_id=scid,
                payment_secret=secret,
                total_msat=total,
                keysend_preimage=tlvs.get(TLV_KEYSEND_PREIMAGE),
                encrypted_recipient_data=tlvs.get(
                    TLV_ENCRYPTED_RECIPIENT_DATA),
                path_key=tlvs.get(TLV_CURRENT_PATH_KEY),
            )
        except WireError as e:
            raise PayloadError(f"bad hop payload: {e}") from None


def build_route_onion(hop_node_ids: list[bytes], payloads: list[HopPayload],
                      payment_hash: bytes, session_key: int):
    """Construct the payment onion for a route (xpay/pay's job in the
    reference).  Returns (onion_bytes_1366, shared_secrets)."""
    from . import sphinx

    framed = [sphinx.tlv_payload(p.serialize()) for p in payloads]
    pkt, secrets = sphinx.create_onion(
        hop_node_ids, framed, payment_hash, session_key
    )
    return pkt.serialize(), secrets


@dataclass
class PeeledHop:
    payload: HopPayload
    next_onion: bytes | None  # 1366 bytes for forwards, None at the end
    shared_secret: bytes


def peel_payment_onion(onion_bytes: bytes, payment_hash: bytes,
                       node_privkey: int) -> PeeledHop:
    """One node's view of an incoming payment onion (the core of
    lightningd/peer_htlcs.c:1451 peer_accepted_htlc)."""
    from . import sphinx

    pkt = sphinx.OnionPacket.parse(onion_bytes)
    peeled = sphinx.peel_onion(pkt, payment_hash, node_privkey)
    payload = HopPayload.parse(peeled.payload)
    if peeled.is_final != payload.is_final:
        raise PayloadError("hop position does not match payload shape")
    nxt = peeled.next_packet.serialize() if peeled.next_packet else None
    return PeeledHop(payload, nxt, peeled.shared_secret)
