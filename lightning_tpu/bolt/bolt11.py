"""BOLT#11 invoice encoding/decoding/signing.

Parity target: common/bolt11.c (decode :1003, encode/sign :1299 region)
and common/bech32.c — rewritten from the BOLT#11 spec text.  Invoices are
bech32 (no length limit, original non-m variant) over HRP
``ln{currency}{amount}{multiplier}`` plus a 5-bit data part:
timestamp(35 bits) | tagged fields | 65-byte recoverable signature.

The signature is ECDSA over sha256(hrp_utf8 || data_part_packed_to_bytes)
with a recovery id, so the payee node id can be omitted from the invoice
and recovered at decode time (common/bolt11.c uses
secp256k1_ecdsa_recoverable; here `recover_pubkey`).
"""
from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field

from ..crypto import ref_python as ref

CHARSET = "qpzry9x8gf2tvdw0s3jn54khce6mua7l"
_REV = {c: i for i, c in enumerate(CHARSET)}

# currency prefixes (chainparams.c: bip173_name per network)
CURRENCIES = ("lnbcrt", "lntbs", "lntb", "lnbc", "lnsb")
# msat per unit for each multiplier: amounts are `number × multiplier`
# BTC, 1 BTC = 10^11 msat; `p` (pico) is 0.1 msat so the digit string must
# end in 0 (BOLT#11: "If the `p` multiplier is used the last decimal of
# `amount` MUST be `0`.")
MULTIPLIERS = {"m": 10 ** 8, "u": 10 ** 5, "n": 10 ** 2}
DEFAULT_EXPIRY = 3600
DEFAULT_MIN_FINAL_CLTV = 18


class Bolt11Error(Exception):
    pass


# ---------------------------------------------------------------------------
# bech32 (BIP173 charset/checksum; BOLT#11 drops the 90-char length cap)

def _polymod(values):
    gen = (0x3B6A57B2, 0x26508E6D, 0x1EA119FA, 0x3D4233DD, 0x2A1462B3)
    chk = 1
    for v in values:
        top = chk >> 25
        chk = (chk & 0x1FFFFFF) << 5 ^ v
        for i in range(5):
            chk ^= gen[i] if ((top >> i) & 1) else 0
    return chk


def _hrp_expand(hrp: str):
    return [ord(x) >> 5 for x in hrp] + [0] + [ord(x) & 31 for x in hrp]


def bech32_encode(hrp: str, data: list[int]) -> str:
    values = _hrp_expand(hrp) + data
    polymod = _polymod(values + [0, 0, 0, 0, 0, 0]) ^ 1
    checksum = [(polymod >> 5 * (5 - i)) & 31 for i in range(6)]
    return hrp + "1" + "".join(CHARSET[d] for d in data + checksum)


def bech32_decode(s: str) -> tuple[str, list[int]]:
    if s.lower() != s and s.upper() != s:
        raise Bolt11Error("mixed case")
    s = s.lower()
    pos = s.rfind("1")
    if pos < 1 or pos + 7 > len(s):
        raise Bolt11Error("bad separator position")
    hrp, rest = s[:pos], s[pos + 1:]
    try:
        data = [_REV[c] for c in rest]
    except KeyError as e:
        raise Bolt11Error(f"invalid character {e.args[0]!r}")
    if _polymod(_hrp_expand(hrp) + data) != 1:
        raise Bolt11Error("bad checksum")
    return hrp, data[:-6]


def _to5(data: bytes, pad: bool = True) -> list[int]:
    out, acc, bits = [], 0, 0
    for b in data:
        acc = (acc << 8) | b
        bits += 8
        while bits >= 5:
            bits -= 5
            out.append((acc >> bits) & 31)
    if pad and bits:
        out.append((acc << (5 - bits)) & 31)
    return out


def _to8(data: list[int]) -> bytes:
    acc, bits, out = 0, 0, bytearray()
    for v in data:
        acc = (acc << 5) | v
        bits += 5
        while bits >= 8:
            bits -= 8
            out.append((acc >> bits) & 0xFF)
    # leftover bits must be zero padding
    if bits and (acc & ((1 << bits) - 1)):
        raise Bolt11Error("non-zero bech32 padding")
    return bytes(out)


# ---------------------------------------------------------------------------
# recoverable ECDSA (common/bolt11.c sign_invoice / pubkey recovery)

def sign_recoverable(msg_hash: bytes, seckey: int) -> tuple[bytes, int]:
    """Returns (64-byte compact sig, recovery id 0-3)."""
    r, s = ref.ecdsa_sign(msg_hash, seckey, grind_low_r=False)
    pub = ref.pubkey_create(seckey)
    sig = r.to_bytes(32, "big") + s.to_bytes(32, "big")
    for recid in range(4):
        try:
            if recover_pubkey(msg_hash, sig, recid) == ref.pubkey_serialize(pub):
                return sig, recid
        except Bolt11Error:
            continue
    raise Bolt11Error("could not determine recovery id")


def recover_pubkey(msg_hash: bytes, sig64: bytes, recid: int) -> bytes:
    """SEC1 4.1.6 public-key recovery; returns compressed pubkey."""
    r = int.from_bytes(sig64[:32], "big")
    s = int.from_bytes(sig64[32:], "big")
    if not (1 <= r < ref.N and 1 <= s < ref.N and 0 <= recid <= 3):
        raise Bolt11Error("bad signature")
    x = r + (ref.N if recid & 2 else 0)
    if x >= ref.P:
        raise Bolt11Error("r+n overflows field")
    ysq = (pow(x, 3, ref.P) + ref.B) % ref.P
    y = pow(ysq, (ref.P + 1) // 4, ref.P)
    if (y * y) % ref.P != ysq:
        raise Bolt11Error("point not on curve")
    if (y & 1) != (recid & 1):
        y = ref.P - y
    R = ref.Point(x, y)
    z = int.from_bytes(msg_hash, "big") % ref.N
    rinv = pow(r, -1, ref.N)
    # Q = r^-1 (s*R - z*G)
    q = ref.point_add(ref.point_mul((s * rinv) % ref.N, R),
                      ref.point_mul((-z * rinv) % ref.N, ref.G))
    if q.inf:
        raise Bolt11Error("recovered infinity")
    return ref.pubkey_serialize(q)


# ---------------------------------------------------------------------------
# invoice model

@dataclass
class RouteHint:
    pubkey: bytes          # 33
    scid: int
    fee_base_msat: int
    fee_ppm: int
    cltv_delta: int


@dataclass
class Invoice:
    currency: str = "bcrt"
    amount_msat: int | None = None
    timestamp: int = 0
    payment_hash: bytes = b""
    payment_secret: bytes | None = None
    description: str | None = None
    description_hash: bytes | None = None
    payee: bytes | None = None           # compressed pubkey (recovered)
    expiry: int = DEFAULT_EXPIRY
    min_final_cltv: int = DEFAULT_MIN_FINAL_CLTV
    features: bytes = b""
    route_hints: list[list[RouteHint]] = field(default_factory=list)
    signature: bytes = b""               # 64-byte compact
    metadata: bytes | None = None

    @property
    def expires_at(self) -> int:
        return self.timestamp + self.expiry


_PREFIX_FOR = {"bc": "lnbc", "tb": "lntb", "bcrt": "lnbcrt", "sb": "lnsb",
               "tbs": "lntbs"}


def _encode_amount(msat: int) -> str:
    # pick the largest multiplier that represents msat exactly
    if msat % (10 ** 11) == 0:
        return str(msat // (10 ** 11))
    for letter in "mun":
        scale = MULTIPLIERS[letter]
        if msat % scale == 0:
            return f"{msat // scale}{letter}"
    return f"{msat * 10}p"


def _decode_amount(s: str) -> int | None:
    if not s:
        return None
    if s[-1] == "p":
        num = s[:-1]
        _check_digits(num, s)
        if int(num) % 10:
            raise Bolt11Error("pico amount must end in 0 (sub-msat)")
        return int(num) // 10
    if s[-1] in MULTIPLIERS:
        num, scale = s[:-1], MULTIPLIERS[s[-1]]
    else:
        num, scale = s, 10 ** 11
    _check_digits(num, s)
    return int(num) * scale


def _check_digits(num: str, s: str) -> None:
    if not num.isdigit() or (len(num) > 1 and num[0] == "0"):
        raise Bolt11Error(f"bad amount {s!r}")


def _tagged(tag: str, data5: list[int]) -> list[int]:
    if len(data5) > 1023:
        raise Bolt11Error(f"field {tag} too long")
    return [_REV[tag], len(data5) >> 5, len(data5) & 31] + data5


def _int_to5(x: int, n: int | None = None) -> list[int]:
    out = []
    while x:
        out.append(x & 31)
        x >>= 5
    out.reverse()
    if n is not None:
        out = [0] * (n - len(out)) + out
    return out or ([0] * (n or 1))


def _sig_msg(hrp: str, data: list[int]) -> bytes:
    """The signed message: hrp utf8 bytes + data part (sans signature)
    packed 5→8 with zero bits padding the final partial byte."""
    acc, bits, out = 0, 0, bytearray()
    for v in data:
        acc = (acc << 5) | v
        bits += 5
        while bits >= 8:
            bits -= 8
            out.append((acc >> bits) & 0xFF)
    if bits:
        out.append((acc << (8 - bits)) & 0xFF)
    return hrp.encode("utf8") + bytes(out)


def _5_to_int(data5: list[int]) -> int:
    x = 0
    for v in data5:
        x = (x << 5) | v
    return x


def encode(inv: Invoice, seckey: int) -> str:
    """Serialize + sign an invoice with the node key."""
    prefix = _PREFIX_FOR.get(inv.currency)
    if prefix is None:
        raise Bolt11Error(f"unknown currency {inv.currency!r}")
    hrp = prefix + ("" if inv.amount_msat is None
                    else _encode_amount(inv.amount_msat))
    data: list[int] = _int_to5(inv.timestamp, 7)
    if len(data) > 7:
        raise Bolt11Error("timestamp overflow")
    if len(inv.payment_hash) != 32:
        raise Bolt11Error("payment_hash must be 32 bytes")
    data += _tagged("p", _to5(inv.payment_hash))
    if inv.payment_secret is not None:
        data += _tagged("s", _to5(inv.payment_secret))
    if inv.description is not None:
        data += _tagged("d", _to5(inv.description.encode("utf8")))
    elif inv.description_hash is not None:
        data += _tagged("h", _to5(inv.description_hash))
    else:
        raise Bolt11Error("need description or description_hash")
    if inv.metadata is not None:
        data += _tagged("m", _to5(inv.metadata))
    if inv.payee is not None:
        data += _tagged("n", _to5(inv.payee))
    if inv.expiry != DEFAULT_EXPIRY:
        data += _tagged("x", _int_to5(inv.expiry))
    if inv.min_final_cltv != DEFAULT_MIN_FINAL_CLTV:
        data += _tagged("c", _int_to5(inv.min_final_cltv))
    for hint in inv.route_hints:
        raw = b"".join(
            h.pubkey + h.scid.to_bytes(8, "big")
            + h.fee_base_msat.to_bytes(4, "big") + h.fee_ppm.to_bytes(4, "big")
            + h.cltv_delta.to_bytes(2, "big")
            for h in hint)
        data += _tagged("r", _to5(raw))
    if inv.features:
        feats = int.from_bytes(inv.features, "big")
        data += _tagged("9", _int_to5(feats) if feats else [0])
    sig, recid = sign_recoverable(
        hashlib.sha256(_sig_msg(hrp, data)).digest(), seckey)
    inv.signature = sig
    data += _to5(sig + bytes([recid]))
    return bech32_encode(hrp, data)


def decode(invstring: str, check_sig: bool = True) -> Invoice:
    invstring = invstring.strip()
    hrp, data = bech32_decode(invstring)
    prefix = next((p for p in CURRENCIES if hrp.startswith(p)), None)
    if prefix is None:
        raise Bolt11Error(f"bad prefix {hrp!r}")
    currency = prefix[2:]
    amount = _decode_amount(hrp[len(prefix):])
    if len(data) < 7 + 104:
        raise Bolt11Error("too short")
    sig5 = data[-104:]
    data = data[:-104]
    sigbytes = _to8(sig5)
    sig64, recid = sigbytes[:64], sigbytes[64]
    inv = Invoice(currency=currency, amount_msat=amount,
                  timestamp=_5_to_int(data[:7]), signature=sig64)
    i = 7
    while i < len(data):
        if i + 3 > len(data):
            raise Bolt11Error("truncated tagged field")
        tag = CHARSET[data[i]]
        ln = (data[i + 1] << 5) | data[i + 2]
        body = data[i + 3: i + 3 + ln]
        if len(body) != ln:
            raise Bolt11Error(f"truncated field {tag!r}")
        i += 3 + ln
        try:
            _parse_field(inv, tag, body)
        except Bolt11Error:
            raise
        except Exception:
            pass  # unknown/odd fields are ignored per spec
    if not inv.payment_hash:
        raise Bolt11Error("missing payment_hash")
    h = hashlib.sha256(_sig_msg(hrp, data)).digest()
    recovered = recover_pubkey(h, sig64, recid)
    if inv.payee is not None:
        if check_sig and recovered != inv.payee:
            # spec: if n field present, must validate sig against it
            r = int.from_bytes(sig64[:32], "big")
            s = int.from_bytes(sig64[32:], "big")
            if not ref.ecdsa_verify(h, r, s, ref.pubkey_parse(inv.payee)):
                raise Bolt11Error("signature does not match payee")
    else:
        inv.payee = recovered
    return inv


def _parse_field(inv: Invoice, tag: str, body: list[int]) -> None:
    if tag == "p":
        if len(body) != 52:
            return  # skip malformed-length p per spec
        inv.payment_hash = _field_bytes(body, 32)
    elif tag == "s":
        if len(body) == 52:
            inv.payment_secret = _field_bytes(body, 32)
    elif tag == "d":
        inv.description = _to8(body).decode("utf8")
    elif tag == "h":
        if len(body) == 52:
            inv.description_hash = _field_bytes(body, 32)
    elif tag == "n":
        if len(body) == 53:
            inv.payee = _field_bytes(body, 33)
    elif tag == "x":
        inv.expiry = _5_to_int(body)
    elif tag == "c":
        inv.min_final_cltv = _5_to_int(body)
    elif tag == "m":
        inv.metadata = _to8(body)
    elif tag == "9":
        bits = _5_to_int(body)
        inv.features = bits.to_bytes((bits.bit_length() + 7) // 8 or 1, "big")
    elif tag == "r":
        raw = _to8(body)
        hops = []
        while len(raw) >= 51:
            hops.append(RouteHint(
                pubkey=raw[:33],
                scid=int.from_bytes(raw[33:41], "big"),
                fee_base_msat=int.from_bytes(raw[41:45], "big"),
                fee_ppm=int.from_bytes(raw[45:49], "big"),
                cltv_delta=int.from_bytes(raw[49:51], "big"),
            ))
            raw = raw[51:]
        if hops:
            inv.route_hints.append(hops)


def _field_bytes(body: list[int], n: int) -> bytes:
    """Exact-size field: 5-bit data whose last partial bits are padding."""
    acc, bits, out = 0, 0, bytearray()
    for v in body:
        acc = (acc << 5) | v
        bits += 5
        while bits >= 8 and len(out) < n:
            bits -= 8
            out.append((acc >> bits) & 0xFF)
    if len(out) != n:
        raise Bolt11Error("short field")
    return bytes(out)


def new_invoice(seckey: int, payment_hash: bytes, amount_msat: int | None,
                description: str, currency: str = "bcrt",
                payment_secret: bytes | None = None,
                expiry: int = DEFAULT_EXPIRY,
                min_final_cltv: int = DEFAULT_MIN_FINAL_CLTV,
                features: bytes = b"\x02\x02\x41\x00",
                timestamp: int | None = None) -> tuple[str, Invoice]:
    """Convenience: build + sign, returning (bolt11 string, Invoice)."""
    inv = Invoice(
        currency=currency, amount_msat=amount_msat,
        timestamp=int(time.time()) if timestamp is None else timestamp,
        payment_hash=payment_hash, payment_secret=payment_secret,
        description=description, expiry=expiry,
        min_final_cltv=min_final_cltv, features=features,
    )
    return encode(inv, seckey), inv
