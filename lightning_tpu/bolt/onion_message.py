"""BOLT#4 onion messages: DoS-bounded, unreliable messaging over blinded
paths — the carrier for BOLT12 invoice_request / invoice flows.

Functional parity target: the reference's common/onion_message.c +
lightningd/onion_message.c (blinded-path unwrap and forward) — written
from the BOLT#4 "Onion Messages" spec text.

An onion_message (wire type 513) is a sphinx onion whose hops are the
*blinded* node ids of a blinded path; the clear-text `path_key` rides
alongside the onion so each hop can derive the tweak for its blinded
identity.  Payloads are `onionmsg_tlv` streams; only the final hop may
carry content fields (invoice_request etc.), relays see just their
encrypted_recipient_data.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..crypto import ref_python as ref
from ..wire import messages as M
from ..wire.codec import read_tlv_stream, write_tlv_stream
from . import blindedpath as BP
from . import sphinx

# onionmsg_tlv field numbers (BOLT#4)
REPLY_PATH = 2
ENCRYPTED_RECIPIENT_DATA = 4
INVOICE_REQUEST = 64
INVOICE = 66
INVOICE_ERROR = 68

# onion messages come in two fixed sizes so relays can't correlate by
# length (BOLT#4): the payment-onion 1300 and a jumbo 32768
SMALL_ROUTING = 1300
BIG_ROUTING = 32768


class OnionMessageError(Exception):
    pass


def create(path: BP.BlindedPath, final_tlvs: dict[int, bytes],
           session_key: int | None = None) -> M.OnionMessage:
    """Wrap `final_tlvs` (content for the path's recipient) in an onion
    over the blinded hops.  Returns the wire message to send to
    path.first_node_id."""
    payloads = []
    for i, hop in enumerate(path.hops):
        tlvs = {ENCRYPTED_RECIPIENT_DATA: hop.encrypted_recipient_data}
        if i == len(path.hops) - 1:
            tlvs.update(final_tlvs)
        payloads.append(sphinx.tlv_payload(write_tlv_stream(tlvs)))

    hop_ids = [h.blinded_node_id for h in path.hops]
    total = sum(len(p) + sphinx.HMAC_SIZE for p in payloads)
    routing = SMALL_ROUTING if total <= SMALL_ROUTING else BIG_ROUTING
    if total > BIG_ROUTING:
        raise OnionMessageError("onion message content too large")
    sk = session_key or sphinx.random_session_key()
    packet, _ = sphinx.create_onion(hop_ids, payloads, b"", sk,
                                    routing_size=routing)
    return M.OnionMessage(path_key=path.first_path_key,
                          onionmsg=packet.serialize())


@dataclass
class Forward:
    next_node_id: bytes | None   # from encrypted data (or scid-resolved)
    short_channel_id: int | None
    message: M.OnionMessage      # re-wrapped for the next hop


@dataclass
class Final:
    path_id: bytes | None        # recipient's secret cookie, if any
    tlvs: dict[int, bytes]       # content fields (invoice_request, ...)
    reply_path: BP.BlindedPath | None


def process(node_privkey: int, msg: M.OnionMessage) -> Forward | Final:
    """One hop's handling: unblind, peel, and either forward or deliver.

    Reference behavior split across connectd/onion_message handling and
    lightningd/onion_message.c:  relays MUST NOT see content fields;
    recipients get (path_id, tlvs, reply_path).
    """
    path_key = msg.path_key
    E = ref.pubkey_parse(path_key)
    ss = BP._ecdh(node_privkey, E)
    tweaked = (node_privkey * BP.blind_factor(ss)) % ref.N

    packet = sphinx.OnionPacket.parse(msg.onionmsg)
    try:
        peeled = sphinx.peel_onion(packet, b"", tweaked)
    except sphinx.SphinxError as e:
        raise OnionMessageError(f"onion peel failed: {e}") from None

    tlvs = read_tlv_stream(peeled.payload)
    enc = tlvs.get(ENCRYPTED_RECIPIENT_DATA)
    if enc is None:
        raise OnionMessageError("missing encrypted_recipient_data")
    rho = BP._hmac(b"rho", ss)
    data = BP.EncryptedData.parse(BP.decrypt_data(rho, enc))

    if peeled.is_final:
        reply = None
        if REPLY_PATH in tlvs:
            reply, _ = BP.BlindedPath.parse(tlvs[REPLY_PATH])
        content = {t: v for t, v in tlvs.items()
                   if t not in (REPLY_PATH, ENCRYPTED_RECIPIENT_DATA)}
        return Final(path_id=data.path_id, tlvs=content, reply_path=reply)

    # relay: spec forbids content fields for intermediate hops
    if any(t >= 64 for t in tlvs):
        raise OnionMessageError("content fields on non-final hop")
    if data.next_path_key_override is not None:
        next_key = data.next_path_key_override
    else:
        bf = int.from_bytes(BP._sha256(path_key + ss), "big") % ref.N
        next_key = ref.pubkey_serialize(ref.point_mul(bf, E))
    nxt = M.OnionMessage(path_key=next_key,
                         onionmsg=peeled.next_packet.serialize())
    return Forward(next_node_id=data.next_node_id,
                   short_channel_id=data.short_channel_id, message=nxt)


def reply_path_for(node_ids: list[bytes], path_id: bytes,
                   session_key: int | None = None) -> BP.BlindedPath:
    """Convenience: a blinded reply path ending at node_ids[-1] (us),
    whose final hop carries only our path_id cookie."""
    data = [BP.EncryptedData(next_node_id=node_ids[i + 1])
            for i in range(len(node_ids) - 1)]
    data.append(BP.EncryptedData(path_id=path_id))
    return BP.create_path(node_ids, data, session_key)
