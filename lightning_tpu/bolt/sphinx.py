"""BOLT#4 sphinx onion packets: construction, peeling, and error onions.

Functional parity target: the reference's common/sphinx.c:981
(create_onionpacket / process_onionpacket) and common/onionreply.c —
re-implemented from the public BOLT#4 spec and pinned by the official
BOLT#4 test vectors (tests/vectors/onion-test-v0.json,
onion-test-multi-frame.json, onion-error-test.json — public spec data
from the lightning/bolts repository).

This is per-packet serial CPU work like the Noise transport (one ECDH +
stream ciphers per hop); the batchable part — the ECDH point multiplies
for many simultaneous forwards — can ride the device kernels later via
hsmd's ecdh service.
"""
from __future__ import annotations

import hashlib
import hmac as hmac_mod
from dataclasses import dataclass

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms

from ..crypto import ref_python as ref
from ..wire.codec import read_bigsize, write_bigsize

VERSION = 0
ROUTING_INFO_SIZE = 1300
HMAC_SIZE = 32
ONION_PACKET_SIZE = 1 + 33 + ROUTING_INFO_SIZE + HMAC_SIZE  # 1366
MAX_ERROR_MSG = 256


class SphinxError(Exception):
    pass


def _sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def _hmac(key: bytes, msg: bytes) -> bytes:
    return hmac_mod.new(key, msg, hashlib.sha256).digest()


def generate_key(key_type: bytes, secret: bytes) -> bytes:
    """BOLT#4: HMAC-SHA256 keyed by the ascii key-type string."""
    return _hmac(key_type, secret)


def random_session_key() -> int:
    """A fresh sphinx session scalar (shared by every onion builder)."""
    import os

    return int.from_bytes(os.urandom(32), "big") % (2 ** 252) + 1


def cipher_stream(key: bytes, length: int) -> bytes:
    """ChaCha20 keystream with a zero 96-bit nonce from counter 0."""
    c = Cipher(algorithms.ChaCha20(key, b"\x00" * 16), mode=None)
    return c.encryptor().update(b"\x00" * length)


def _xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


def ecdh(privkey: int, pubkey: ref.Point) -> bytes:
    return _sha256(ref.pubkey_serialize(ref.point_mul(privkey, pubkey)))


def _blind(eph_priv: int, eph_pub: ref.Point, ss: bytes) -> int:
    """Next ephemeral key: e' = e * sha256(eph_pub || ss)."""
    bf = int.from_bytes(_sha256(ref.pubkey_serialize(eph_pub) + ss), "big")
    return (eph_priv * bf) % ref.N


def compute_shared_secrets(session_key: int,
                           hop_pubkeys: list[bytes]) -> list[bytes]:
    """Per-hop ECDH shared secrets with ephemeral key blinding."""
    secrets = []
    e = session_key
    for pk in hop_pubkeys:
        pub = ref.pubkey_parse(pk)
        eph_pub = ref.pubkey_create(e)
        ss = ecdh(e, pub)
        secrets.append(ss)
        e = _blind(e, eph_pub, ss)
    return secrets


def tlv_payload(content: bytes) -> bytes:
    """Frame TLV hop content with its bigsize length (modern BOLT#4)."""
    return write_bigsize(len(content)) + content


def legacy_payload(data: bytes) -> bytes:
    """Frame a legacy realm-0 hop payload (fixed 32 bytes, zero-padded)."""
    assert len(data) <= 32
    return b"\x00" + data + b"\x00" * (32 - len(data))


def _frame_size(framed_payload: bytes) -> int:
    return len(framed_payload) + HMAC_SIZE


def _generate_filler(key_type: bytes, payloads: list[bytes],
                     shared_secrets: list[bytes],
                     routing_size: int = ROUTING_INFO_SIZE) -> bytes:
    """BOLT#4 filler: the overflow bytes that successive shifts push past
    the end of the routing info, pre-XORed with each hop's stream so the
    final hop's HMAC verifies."""
    filler = b""
    prev = 0  # bytes consumed by earlier hops' frames
    for payload, ss in zip(payloads[:-1], shared_secrets[:-1]):
        fsize = _frame_size(payload)
        filler += b"\x00" * fsize
        key = generate_key(key_type, ss)
        # this hop's stream covers [0, ROUTING+fsize); the filler region
        # it touches starts where earlier frames pushed it: offset
        # ROUTING - prev, length prev + fsize
        stream = cipher_stream(key, routing_size + fsize)
        filler = _xor(filler, stream[routing_size - prev:])
        prev += fsize
    return filler


@dataclass
class OnionPacket:
    version: int
    eph_pub: bytes  # 33
    routing_info: bytes  # 1300 for payments; variable for onion messages
    hmac: bytes  # 32

    def serialize(self) -> bytes:
        return (bytes([self.version]) + self.eph_pub + self.routing_info
                + self.hmac)

    @classmethod
    def parse(cls, data: bytes) -> "OnionPacket":
        # routing-info length is inferred: onion messages permit sizes
        # other than the payment onion's 1300 (BOLT#4 onion_message_packet)
        if len(data) < 1 + 33 + 1 + HMAC_SIZE:
            raise SphinxError(f"bad onion size {len(data)}")
        if data[0] != VERSION:
            raise SphinxError(f"bad onion version {data[0]}")
        return cls(data[0], data[1:34], data[34:-32], data[-32:])


def create_onion(hop_pubkeys: list[bytes], payloads: list[bytes],
                 assoc_data: bytes, session_key: int,
                 pad_stream: bool = True,
                 routing_size: int = ROUTING_INFO_SIZE,
                 ) -> tuple[OnionPacket, list[bytes]]:
    """Build the onion for a route (sphinx.c create_onionpacket).
    `payloads` are ALREADY-FRAMED hop payloads — use tlv_payload() /
    legacy_payload() — mirroring the reference's raw_payload convention.
    Returns (packet, per-hop shared secrets — the origin keeps these to
    decrypt a returned error onion).

    pad_stream: initialize the unused region with the "pad"-keyed
    ChaCha20 stream (current BOLT#4: hides route length).  The official
    test vectors predate this change and zero-pad; the choice is
    constructor-local — it never affects peers, who only peel."""
    assert len(hop_pubkeys) == len(payloads) > 0
    total = sum(_frame_size(p) for p in payloads)
    if total > routing_size:
        raise SphinxError("route payloads exceed onion capacity")
    secrets = compute_shared_secrets(session_key, hop_pubkeys)
    filler = _generate_filler(b"rho", payloads, secrets, routing_size)

    if pad_stream:
        pad_key = generate_key(b"pad", session_key.to_bytes(32, "big"))
        routing = cipher_stream(pad_key, routing_size)
    else:
        routing = b"\x00" * routing_size
    next_hmac = b"\x00" * HMAC_SIZE

    for i in range(len(payloads) - 1, -1, -1):
        ss = secrets[i]
        rho = generate_key(b"rho", ss)
        mu = generate_key(b"mu", ss)
        frame = payloads[i] + next_hmac
        routing = frame + routing[: routing_size - len(frame)]
        routing = _xor(routing, cipher_stream(rho, routing_size))
        if i == len(payloads) - 1 and filler:
            routing = routing[: routing_size - len(filler)] + filler
        next_hmac = _hmac(mu, routing + assoc_data)

    eph_pub = ref.pubkey_serialize(ref.pubkey_create(session_key))
    return OnionPacket(VERSION, eph_pub, routing, next_hmac), secrets


@dataclass
class PeeledOnion:
    payload: bytes  # this hop's payload (without realm/length framing)
    hmac: bytes  # next hop's hmac (zeros ⇔ we are the final hop)
    next_packet: OnionPacket | None
    shared_secret: bytes

    @property
    def is_final(self) -> bool:
        return self.hmac == b"\x00" * HMAC_SIZE


def peel_onion(packet: OnionPacket, assoc_data: bytes,
               privkey: int) -> PeeledOnion:
    """One hop's processing (sphinx.c process_onionpacket)."""
    try:
        eph = ref.pubkey_parse(packet.eph_pub)
    except ValueError as e:
        raise SphinxError(f"bad ephemeral key: {e}") from None
    ss = ecdh(privkey, eph)
    routing_size = len(packet.routing_info)
    mu = generate_key(b"mu", ss)
    expect = _hmac(mu, packet.routing_info + assoc_data)
    if expect != packet.hmac:
        raise SphinxError("onion hmac mismatch")

    rho = generate_key(b"rho", ss)
    stream = cipher_stream(rho, 2 * routing_size)
    padded = packet.routing_info + b"\x00" * routing_size
    clear = _xor(padded, stream)

    # parse this hop's frame (content returned without framing)
    if clear[0] == 0:  # legacy realm 0: 32-byte payload
        payload = clear[1:33]
        consumed = 33
    else:
        try:
            ln, off = read_bigsize(clear, 0)
        except Exception as e:
            raise SphinxError(f"bad frame length: {e}") from None
        if off + ln + HMAC_SIZE > routing_size:
            raise SphinxError("hop frame exceeds routing info")
        payload = clear[off : off + ln]
        consumed = off + ln
    next_hmac = clear[consumed : consumed + HMAC_SIZE]
    consumed += HMAC_SIZE
    next_routing = clear[consumed : consumed + routing_size]

    next_packet = None
    if next_hmac != b"\x00" * HMAC_SIZE:
        bf = int.from_bytes(
            _sha256(packet.eph_pub + ss), "big"
        )
        next_eph = ref.point_mul(bf, eph)
        next_packet = OnionPacket(
            VERSION, ref.pubkey_serialize(next_eph), next_routing, next_hmac
        )
    return PeeledOnion(payload, next_hmac, next_packet, ss)


# ---------------------------------------------------------------------------
# Error onions (BOLT#4 "Returning Errors"; common/onionreply.c)


def create_error_onion(shared_secret: bytes, failure_msg: bytes) -> bytes:
    """Build the erring node's failure packet and apply its first ammag
    obfuscation layer."""
    if len(failure_msg) > MAX_ERROR_MSG:
        raise SphinxError("failure message too long")
    um = generate_key(b"um", shared_secret)
    pad_len = MAX_ERROR_MSG - len(failure_msg)
    body = (
        len(failure_msg).to_bytes(2, "big") + failure_msg
        + pad_len.to_bytes(2, "big") + b"\x00" * pad_len
    )
    packet = _hmac(um, body) + body
    return wrap_error_onion(shared_secret, packet)


def wrap_error_onion(shared_secret: bytes, error_onion: bytes) -> bytes:
    """Each hop on the return path XORs its ammag stream over the blob."""
    ammag = generate_key(b"ammag", shared_secret)
    return _xor(error_onion, cipher_stream(ammag, len(error_onion)))


def unwrap_error_onion(shared_secrets: list[bytes],
                       error_onion: bytes) -> tuple[int, bytes]:
    """Origin-side decryption: peel ammag layers in route order until a
    valid um-HMAC appears.  Returns (erring_hop_index, failure_msg)."""
    blob = error_onion
    for i, ss in enumerate(shared_secrets):
        blob = wrap_error_onion(ss, blob)  # XOR is its own inverse
        um = generate_key(b"um", ss)
        if _hmac(um, blob[32:]) == blob[:32]:
            msg_len = int.from_bytes(blob[32:34], "big")
            return i, blob[34 : 34 + msg_len]
    raise SphinxError("error onion matches no hop")
