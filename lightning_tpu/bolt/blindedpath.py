"""BOLT#4 route blinding: blinded paths for onion messages and payments.

Functional parity target: the reference's common/blindedpath.c (path
construction + unblinding) and common/blindedpay.c — re-implemented from
the BOLT#4 "Route Blinding" spec text.

Construction: the builder picks a path-key scalar e0 and, walking the
route, derives per-hop shared secrets ss_i = H(e_i * P_i).  Each hop's
real node id P_i is tweaked into a blinded id
B_i = HMAC("blinded_node_id", ss_i) * P_i, its per-hop routing payload is
sealed with ChaCha20-Poly1305 under rho_i = HMAC("rho", ss_i), and the
path key evolves as e_{i+1} = H(E_i || ss_i) * e_i.  A relaying node,
handed E_i alongside the onion, recovers ss_i with its own node key,
decrypts its payload, tweaks its privkey by the blinded_node_id factor to
peel the onion addressed to B_i, and forwards E_{i+1}.
"""
from __future__ import annotations

import hashlib
import hmac as hmac_mod
import os
from dataclasses import dataclass, field

from cryptography.exceptions import InvalidTag
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

from ..crypto import ref_python as ref
from ..wire.codec import read_tlv_stream, write_bigsize, write_tlv_stream


class BlindedPathError(Exception):
    pass


def _sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def _hmac(key: bytes, msg: bytes) -> bytes:
    return hmac_mod.new(key, msg, hashlib.sha256).digest()


def _ecdh(scalar: int, point: ref.Point) -> bytes:
    return _sha256(ref.pubkey_serialize(ref.point_mul(scalar, point)))


# ---------------------------------------------------------------------------
# encrypted_data TLV (BOLT#4 tlv_encrypted_data_tlv)

PADDING = 1
SHORT_CHANNEL_ID = 2
NEXT_NODE_ID = 4
PATH_ID = 6
NEXT_PATH_KEY_OVERRIDE = 8
PAYMENT_RELAY = 10
PAYMENT_CONSTRAINTS = 12
ALLOWED_FEATURES = 14


@dataclass
class EncryptedData:
    """One hop's recipient data inside a blinded path."""
    short_channel_id: int | None = None
    next_node_id: bytes | None = None     # 33-byte compressed pubkey
    path_id: bytes | None = None          # recipient-only secret cookie
    next_path_key_override: bytes | None = None
    payment_relay: tuple[int, int, int] | None = None  # (cltv_delta, ppm, base)
    payment_constraints: tuple[int, int] | None = None  # (max_cltv, htlc_min)
    allowed_features: bytes | None = None
    padding: int = 0

    def serialize(self) -> bytes:
        tlvs: dict[int, bytes] = {}
        if self.padding:
            tlvs[PADDING] = b"\x00" * self.padding
        if self.short_channel_id is not None:
            tlvs[SHORT_CHANNEL_ID] = self.short_channel_id.to_bytes(8, "big")
        if self.next_node_id is not None:
            tlvs[NEXT_NODE_ID] = self.next_node_id
        if self.path_id is not None:
            tlvs[PATH_ID] = self.path_id
        if self.next_path_key_override is not None:
            tlvs[NEXT_PATH_KEY_OVERRIDE] = self.next_path_key_override
        if self.payment_relay is not None:
            cltv, ppm, base = self.payment_relay
            v = cltv.to_bytes(2, "big") + ppm.to_bytes(4, "big")
            v += _tu(base)
            tlvs[PAYMENT_RELAY] = v
        if self.payment_constraints is not None:
            max_cltv, htlc_min = self.payment_constraints
            tlvs[PAYMENT_CONSTRAINTS] = max_cltv.to_bytes(4, "big") + _tu(htlc_min)
        if self.allowed_features is not None:
            tlvs[ALLOWED_FEATURES] = self.allowed_features
        return write_tlv_stream(tlvs)

    @classmethod
    def parse(cls, data: bytes) -> "EncryptedData":
        tlvs = read_tlv_stream(data)
        ed = cls()
        if SHORT_CHANNEL_ID in tlvs:
            ed.short_channel_id = int.from_bytes(tlvs[SHORT_CHANNEL_ID], "big")
        if NEXT_NODE_ID in tlvs:
            ed.next_node_id = tlvs[NEXT_NODE_ID]
        if PATH_ID in tlvs:
            ed.path_id = tlvs[PATH_ID]
        if NEXT_PATH_KEY_OVERRIDE in tlvs:
            ed.next_path_key_override = tlvs[NEXT_PATH_KEY_OVERRIDE]
        if PAYMENT_RELAY in tlvs:
            v = tlvs[PAYMENT_RELAY]
            ed.payment_relay = (int.from_bytes(v[:2], "big"),
                                int.from_bytes(v[2:6], "big"),
                                int.from_bytes(v[6:], "big"))
        if PAYMENT_CONSTRAINTS in tlvs:
            v = tlvs[PAYMENT_CONSTRAINTS]
            ed.payment_constraints = (int.from_bytes(v[:4], "big"),
                                      int.from_bytes(v[4:], "big"))
        if ALLOWED_FEATURES in tlvs:
            ed.allowed_features = tlvs[ALLOWED_FEATURES]
        return ed


def _tu(n: int) -> bytes:
    """Truncated big-endian uint (no leading zero bytes)."""
    out = n.to_bytes(8, "big").lstrip(b"\x00")
    return out


# ---------------------------------------------------------------------------
# the path object (BOLT#4 blinded_path subtype)


@dataclass
class BlindedHop:
    blinded_node_id: bytes     # 33
    encrypted_recipient_data: bytes


@dataclass
class BlindedPath:
    first_node_id: bytes       # 33 — real id of the introduction point
    first_path_key: bytes      # 33 — E_0
    hops: list[BlindedHop] = field(default_factory=list)

    def serialize(self) -> bytes:
        out = [self.first_node_id, self.first_path_key,
               bytes([len(self.hops)])]
        for h in self.hops:
            out.append(h.blinded_node_id)
            out.append(len(h.encrypted_recipient_data).to_bytes(2, "big"))
            out.append(h.encrypted_recipient_data)
        return b"".join(out)

    @classmethod
    def parse(cls, data: bytes, off: int = 0) -> tuple["BlindedPath", int]:
        if len(data) - off < 67:
            raise BlindedPathError("short blinded path")
        first = data[off:off + 33]
        pk = data[off + 33:off + 66]
        n = data[off + 66]
        off += 67
        hops = []
        for _ in range(n):
            bid = data[off:off + 33]
            ln = int.from_bytes(data[off + 33:off + 35], "big")
            enc = data[off + 35:off + 35 + ln]
            if len(bid) != 33 or len(enc) != ln:
                raise BlindedPathError("truncated blinded hop")
            off += 35 + ln
            hops.append(BlindedHop(bid, enc))
        return cls(first, pk, hops), off


def blind_factor(ss: bytes) -> int:
    return int.from_bytes(_hmac(b"blinded_node_id", ss), "big") % ref.N


def encrypt_data(rho: bytes, plaintext: bytes) -> bytes:
    return ChaCha20Poly1305(rho).encrypt(b"\x00" * 12, plaintext, b"")


def decrypt_data(rho: bytes, ciphertext: bytes) -> bytes:
    try:
        return ChaCha20Poly1305(rho).decrypt(b"\x00" * 12, ciphertext, b"")
    except InvalidTag:
        raise BlindedPathError("encrypted_data AEAD failure") from None


def create_path(node_ids: list[bytes], data: list[EncryptedData],
                session_key: int | None = None) -> BlindedPath:
    """Blind a route: node_ids[i] gets data[i]; the last entry is the
    recipient (usually carrying only a path_id)."""
    assert len(node_ids) == len(data) > 0
    e = session_key or (int.from_bytes(os.urandom(32), "big") % ref.N or 1)
    first_key = ref.pubkey_serialize(ref.pubkey_create(e))
    hops = []
    for pk, d in zip(node_ids, data):
        point = ref.pubkey_parse(pk)
        eph_pub = ref.pubkey_create(e)
        ss = _ecdh(e, point)
        blinded = ref.point_mul(blind_factor(ss), point)
        rho = _hmac(b"rho", ss)
        hops.append(BlindedHop(ref.pubkey_serialize(blinded),
                               encrypt_data(rho, d.serialize())))
        bf = int.from_bytes(
            _sha256(ref.pubkey_serialize(eph_pub) + ss), "big") % ref.N
        e = (e * bf) % ref.N
    return BlindedPath(node_ids[0], first_key, hops)


@dataclass
class UnblindedHop:
    data: EncryptedData        # this hop's decrypted recipient data
    onion_privkey: int         # tweaked key that peels the onion for B_i
    next_path_key: bytes       # E_{i+1} to hand to the next hop


def unblind_hop(node_privkey: int, path_key: bytes,
                encrypted_recipient_data: bytes) -> UnblindedHop:
    """A relaying/receiving node's processing of one blinded hop."""
    E = ref.pubkey_parse(path_key)
    ss = _ecdh(node_privkey, E)
    rho = _hmac(b"rho", ss)
    data = EncryptedData.parse(decrypt_data(rho, encrypted_recipient_data))
    tweaked = (node_privkey * blind_factor(ss)) % ref.N
    if data.next_path_key_override is not None:
        next_key = data.next_path_key_override
    else:
        bf = int.from_bytes(_sha256(path_key + ss), "big") % ref.N
        next_key = ref.pubkey_serialize(ref.point_mul(bf, E))
    return UnblindedHop(data, tweaked, next_key)
