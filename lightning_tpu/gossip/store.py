"""gossip_store file I/O, format-compatible with the reference.

On-disk format (see /root/reference/common/gossip_store.h:15-50 — studied
for interop, re-implemented here):
  byte 0: version (major in top 3 bits — must be 0; minor in low 5)
  then records: be16 flags | be16 len | be32 crc | be32 timestamp | msg
  crc = crc32c(timestamp, msg) (gossipd/gossip_store.c:67)
  flags: DELETED 0x8000 | PUSH 0x4000 | RATELIMIT 0x2000 | DYING 0x0800.

The reader is built for the replay benchmark: one mmap + native scan into
flat numpy arrays; no per-record Python objects anywhere.
"""
from __future__ import annotations

import logging
import mmap
import os
from dataclasses import dataclass, field

import numpy as np

from ..obs import families as _f
from ..resilience import faultinject as _fault
from ..resilience import quarantine as _quarantine
from ..utils import native

log = logging.getLogger("lightning_tpu.gossip.store")

VERSION_BYTE = 0x10  # major 0, minor 16
# flag bits per the reference's common/gossip_store.h
FLAG_DELETED = 0x8000
FLAG_PUSH = 0x4000  # stream to peers even before timestamp filter
FLAG_RATELIMIT = 0x2000  # spam-flagged: kept but not relayed
FLAG_DYING = 0x0800  # funding spent; removed after 12 blocks


@dataclass
class StoreIndex:
    """Flat view of a scanned store: numpy arrays, one row per record."""

    buf: np.ndarray  # uint8 view of the whole file
    offsets: np.ndarray  # uint64, start of each message body
    lengths: np.ndarray  # uint32
    flags: np.ndarray  # uint16
    timestamps: np.ndarray  # uint32
    crcs: np.ndarray  # uint32
    types: np.ndarray  # uint16

    def alive(self) -> np.ndarray:
        return (self.flags & FLAG_DELETED) == 0

    def select(self, mask: np.ndarray) -> "StoreIndex":
        return StoreIndex(
            self.buf, self.offsets[mask], self.lengths[mask],
            self.flags[mask], self.timestamps[mask], self.crcs[mask],
            self.types[mask],
        )

    def check_crcs(self) -> np.ndarray:
        """crc32c(timestamp-seeded) over each message; True = intact."""
        got = native.crc32c_batch(self.buf, self.offsets, self.lengths,
                                  self.timestamps)
        return got == self.crcs

    def message(self, i: int) -> bytes:
        o, l = int(self.offsets[i]), int(self.lengths[i])
        return bytes(self.buf[o : o + l])

    def __len__(self):
        return len(self.offsets)


def _empty_index() -> StoreIndex:
    """A zero-record StoreIndex (the fresh-daemon bootstrap view)."""
    return StoreIndex(
        np.frombuffer(bytes([VERSION_BYTE]), dtype=np.uint8),
        np.zeros(0, np.uint64), np.zeros(0, np.uint32),
        np.zeros(0, np.uint16), np.zeros(0, np.uint32),
        np.zeros(0, np.uint32), np.zeros(0, np.uint16))


def load_store(path: str) -> StoreIndex:
    """mmap the store (zero-copy — at the 1M-record scale the file is
    hundreds of MB) and scan it natively.  The mmap stays alive as long
    as the returned StoreIndex's buf does.

    A missing or empty store (or the 1-byte version header only) is the
    fresh-daemon bootstrap case and loads as a zero-record index; a
    TORN store (partial record at EOF) still raises — callers that must
    survive a crash mid-append go through recover_store(), which
    truncates the torn tail CLN-style and re-loads."""
    if not os.path.exists(path):
        return _empty_index()
    with open(path, "rb") as f:
        size = os.fstat(f.fileno()).st_size
        if size < 1:
            return _empty_index()
        mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    buf = np.frombuffer(mm, dtype=np.uint8)
    ver = int(buf[0])
    if ver >> 5 != 0:
        raise ValueError(f"incompatible gossip store major version {ver >> 5}")
    d = native.gossip_store_scan(buf, start_off=1)
    return StoreIndex(buf, **d)


def scan_valid_prefix(path: str) -> int:
    """Length in bytes of the longest prefix holding only COMPLETE
    records (record walk off the be16 length fields; == file size when
    the store is intact).  Pure Python, used only on the recovery path:
    the native scanner reports THAT a store is torn, not where."""
    with open(path, "rb") as f:
        data = f.read()
    size = len(data)
    if size < 1:
        return 0
    off = 1
    while off + 12 <= size:
        ln = int.from_bytes(data[off + 2 : off + 4], "big")
        if off + 12 + ln > size:
            break
        off += 12 + ln
    return off


@dataclass
class StoreRecovery:
    """What recover_store() found and did (doc/recovery.md)."""

    path: str
    bootstrapped: bool = False     # store was missing/empty, created fresh
    size: int = 0                  # byte size after recovery
    truncated_bytes: int = 0       # torn tail dropped (0 = tail was clean)
    crc_bad: int = 0               # rows that failed check_crcs()
    requalified: int = 0           # crc-bad rows the host re-check kept
    dropped: int = 0               # crc-bad rows flagged deleted
    records: int = 0               # records in the recovered index
    dropped_rows: list = field(default_factory=list)


def recover_store(path: str, *, check_sigs=None,
                  check_crc: bool = True) -> tuple[StoreIndex, StoreRecovery]:
    """Load a store that may have been torn by a crash.

    CLN's gossip_store load truncates at the first bad record and
    carries on; this is that, with the write-then-rename discipline
    compact_store() documents (never truncate in place — loaded
    StoreIndexes are live mmaps) and the PR-4 quarantine accounting:

    * missing/empty store → created fresh (bootstrap);
    * partial record at EOF (crash mid-append) → the torn tail is
      truncated via tmp-file + fsync + os.replace, logged and metered;
    * rows failing check_crcs() are NOT silently trusted: each is
      diverted through quarantine accounting and host re-checked via
      ``check_sigs(msgs) -> [bool]`` (daemon/recovery.py injects a
      pure-host signature oracle); rows that fail get FLAG_DELETED
      flipped in place, rows that pass are kept (the crc covers
      timestamp+msg, so a corrupt timestamp can fail crc while the
      self-authenticating signature still proves the message).
      ``check_sigs=None`` drops every crc-bad row.

    Returns (index, StoreRecovery).  Raises only on an incompatible
    version byte — there is nothing safe to salvage behind that."""
    rep = StoreRecovery(path=path)
    if not os.path.exists(path) or os.path.getsize(path) == 0:
        with open(path, "wb") as f:
            f.write(bytes([VERSION_BYTE]))
            f.flush()
            os.fsync(f.fileno())
        rep.bootstrapped = True
        rep.size = 1
        log.info("gossip store %s missing/empty: bootstrapped fresh", path)
        return _empty_index(), rep

    size = os.path.getsize(path)
    valid_end = scan_valid_prefix(path)
    if valid_end < size:
        # torn tail: crash mid-append.  Write-then-rename, never
        # truncate in place (live mmaps of the old inode stay valid).
        with open(path, "rb") as f:
            good = f.read(valid_end)
        tmp = path + f".recover.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(good)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        rep.truncated_bytes = size - valid_end
        _f.RECOVERY_STORE_TRUNCATED_BYTES.inc(rep.truncated_bytes)
        log.warning("gossip store %s: torn tail (%d bytes past offset %d) "
                    "truncated", path, rep.truncated_bytes, valid_end)

    idx = load_store(path)
    rep.records = len(idx)
    rep.size = os.path.getsize(path)
    if not check_crc or len(idx) == 0:
        return idx, rep

    ok = idx.check_crcs()
    bad = np.flatnonzero(~ok)
    if len(bad) == 0:
        return idx, rep
    rep.crc_bad = int(len(bad))
    _quarantine.note("store", "crc_mismatch", rep.crc_bad)
    keep = np.zeros(len(bad), bool)
    if check_sigs is not None:
        msgs = [idx.message(int(i)) for i in bad]
        try:
            keep = np.asarray(check_sigs(msgs), bool)
        except Exception:
            log.exception("host re-check of crc-bad rows failed; "
                          "dropping all %d", rep.crc_bad)
            keep = np.zeros(len(bad), bool)
    rep.requalified = int(keep.sum())
    drop = bad[~keep]
    rep.dropped = int(len(drop))
    rep.dropped_rows = [int(i) for i in drop]
    if rep.requalified:
        _f.RECOVERY_STORE_ROWS.labels("requalified").inc(rep.requalified)
    if rep.dropped:
        _f.RECOVERY_STORE_ROWS.labels("dropped").inc(rep.dropped)
        # flag-flip in place (the mark_deleted discipline: the crc
        # covers timestamp+msg only, so flag writes never tear records)
        with open(path, "r+b") as f:
            for i in drop:
                f.seek(int(idx.offsets[i]) - 12)
                f.write((int(idx.flags[i]) | FLAG_DELETED)
                        .to_bytes(2, "big"))
            f.flush()
            os.fsync(f.fileno())
        idx.flags[drop] |= FLAG_DELETED
    log.warning("gossip store %s: %d crc-bad row(s) — %d requalified by "
                "host re-check, %d dropped", path, rep.crc_bad,
                rep.requalified, rep.dropped)
    return idx, rep


class StoreWriter:
    """Append-only store writer (used by gossipd-equivalent + test/bench
    synthesis)."""

    def __init__(self, path: str):
        self.path = path
        fresh = not os.path.exists(path) or os.path.getsize(path) == 0
        self.f = open(path, "ab")
        if fresh:
            self.f.write(bytes([VERSION_BYTE]))

    def _write(self, blob: bytes) -> None:
        """One seam-instrumented store write.  When a crash fault is
        armed at the append seam, the write is split so the kill lands
        MID-record — modelling the real torn-append window a SIGKILL
        leaves (recover_store truncates it on the next boot); for
        raise/hang actions the seam fires before any byte is written,
        so an injected error never corrupts the store."""
        if blob and _fault.crash_armed("append", "store"):
            half = max(1, len(blob) // 2)
            self.f.write(blob[:half])
            self.f.flush()
            _fault.fire("append", "store")
            self.f.write(blob[half:])
        else:
            _fault.fire("append", "store")
            self.f.write(blob)

    def append(self, msg: bytes, timestamp: int = 0, flags: int = 0,
               sync: bool = False):
        """Append one record.  sync=True makes the record durable before
        returning — the live ingest path uses this (the reference fsyncs
        before gossip is acked/relayed); bulk synthesis leaves it off."""
        crc = native.crc32c(timestamp, msg)
        hdr = (
            int(flags).to_bytes(2, "big")
            + len(msg).to_bytes(2, "big")
            + crc.to_bytes(4, "big")
            + int(timestamp).to_bytes(4, "big")
        )
        self._write(hdr + msg)
        if sync:
            self.sync()

    def sync(self):
        self.f.flush()
        os.fsync(self.f.fileno())

    def append_many(self, msgs, timestamps=None, sync: bool = False):
        """Append a batch as ONE contiguous write.

        Same durability contract as append(): sync=True makes the whole
        batch durable before returning.  Ordering guarantee: records
        reach the file in argument order within one write(2)-sized
        burst, so a crash can only lose a SUFFIX of the batch (plus, if
        it lands mid-write, one torn record at the cut that
        recover_store() truncates) — it can never persist record i+1
        without record i, and never reorders records."""
        parts = []
        for i, msg in enumerate(msgs):
            ts = int(timestamps[i]) if timestamps is not None else 0
            crc = native.crc32c(ts, msg)
            parts.append(
                (0).to_bytes(2, "big") + len(msg).to_bytes(2, "big")
                + crc.to_bytes(4, "big") + ts.to_bytes(4, "big") + msg
            )
        self._write(b"".join(parts))
        if sync:
            self.sync()

    def close(self):
        self.f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def mark_deleted(path: str, scids: set[int]) -> int:
    """Set FLAG_DELETED in place on every channel_announcement /
    channel_update whose scid is in `scids` (the reference's
    gossip_store_del flag flip, gossipd/gossip_store.c).  The crc covers
    (timestamp, msg) only, so flag flips never invalidate records.
    Returns the number of records flagged."""
    from . import wire as gwire

    idx = load_store(path)
    n = 0
    with open(path, "r+b") as f:
        for i in range(len(idx)):
            if idx.flags[i] & FLAG_DELETED:
                continue
            if idx.types[i] not in (gwire.MSG_CHANNEL_ANNOUNCEMENT,
                                    gwire.MSG_CHANNEL_UPDATE):
                continue
            try:
                p = gwire.parse_gossip(idx.message(i))
            except Exception:
                continue
            if p.short_channel_id in scids:
                f.seek(int(idx.offsets[i]) - 12)
                f.write((int(idx.flags[i])
                         | FLAG_DELETED).to_bytes(2, "big"))
                n += 1
        f.flush()
        os.fsync(f.fileno())
    return n


def compact_store(src: str, dst: str) -> int:
    """Rewrite the store dropping deleted records (the reference runs this
    as a dedicated subdaemon, gossipd/compactd.c).  Returns record count."""
    idx = load_store(src)
    keep = idx.select(idx.alive())
    out = []
    for i in range(len(keep)):
        o, l = int(keep.offsets[i]), int(keep.lengths[i])
        hdr = (
            int(keep.flags[i]).to_bytes(2, "big")
            + l.to_bytes(2, "big")
            + int(keep.crcs[i]).to_bytes(4, "big")
            + int(keep.timestamps[i]).to_bytes(4, "big")
        )
        out.append(hdr + bytes(keep.buf[o : o + l]))
    # write-then-rename: never truncate dst in place — loaded StoreIndexes
    # are live mmaps of it, and rewriting the mapped inode would SIGBUS
    # them.  rename swaps the directory entry; old maps keep the old inode.
    tmp = dst + f".compact.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(bytes([VERSION_BYTE]) + b"".join(out))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, dst)
    return len(keep)
