"""gossip_store file I/O, format-compatible with the reference.

On-disk format (see /root/reference/common/gossip_store.h:15-50 — studied
for interop, re-implemented here):
  byte 0: version (major in top 3 bits — must be 0; minor in low 5)
  then records: be16 flags | be16 len | be32 crc | be32 timestamp | msg
  crc = crc32c(timestamp, msg) (gossipd/gossip_store.c:67)
  flags: DELETED 0x8000 | PUSH 0x4000 | RATELIMIT 0x2000 | DYING 0x0800.

The reader is built for the replay benchmark: one mmap + native scan into
flat numpy arrays; no per-record Python objects anywhere.
"""
from __future__ import annotations

import mmap
import os
from dataclasses import dataclass

import numpy as np

from ..utils import native

VERSION_BYTE = 0x10  # major 0, minor 16
# flag bits per the reference's common/gossip_store.h
FLAG_DELETED = 0x8000
FLAG_PUSH = 0x4000  # stream to peers even before timestamp filter
FLAG_RATELIMIT = 0x2000  # spam-flagged: kept but not relayed
FLAG_DYING = 0x0800  # funding spent; removed after 12 blocks


@dataclass
class StoreIndex:
    """Flat view of a scanned store: numpy arrays, one row per record."""

    buf: np.ndarray  # uint8 view of the whole file
    offsets: np.ndarray  # uint64, start of each message body
    lengths: np.ndarray  # uint32
    flags: np.ndarray  # uint16
    timestamps: np.ndarray  # uint32
    crcs: np.ndarray  # uint32
    types: np.ndarray  # uint16

    def alive(self) -> np.ndarray:
        return (self.flags & FLAG_DELETED) == 0

    def select(self, mask: np.ndarray) -> "StoreIndex":
        return StoreIndex(
            self.buf, self.offsets[mask], self.lengths[mask],
            self.flags[mask], self.timestamps[mask], self.crcs[mask],
            self.types[mask],
        )

    def check_crcs(self) -> np.ndarray:
        """crc32c(timestamp-seeded) over each message; True = intact."""
        got = native.crc32c_batch(self.buf, self.offsets, self.lengths,
                                  self.timestamps)
        return got == self.crcs

    def message(self, i: int) -> bytes:
        o, l = int(self.offsets[i]), int(self.lengths[i])
        return bytes(self.buf[o : o + l])

    def __len__(self):
        return len(self.offsets)


def load_store(path: str) -> StoreIndex:
    """mmap the store (zero-copy — at the 1M-record scale the file is
    hundreds of MB) and scan it natively.  The mmap stays alive as long
    as the returned StoreIndex's buf does."""
    with open(path, "rb") as f:
        size = os.fstat(f.fileno()).st_size
        if size < 1:
            raise ValueError("empty gossip store")
        mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    buf = np.frombuffer(mm, dtype=np.uint8)
    ver = int(buf[0])
    if ver >> 5 != 0:
        raise ValueError(f"incompatible gossip store major version {ver >> 5}")
    d = native.gossip_store_scan(buf, start_off=1)
    return StoreIndex(buf, **d)


class StoreWriter:
    """Append-only store writer (used by gossipd-equivalent + test/bench
    synthesis)."""

    def __init__(self, path: str):
        self.path = path
        fresh = not os.path.exists(path) or os.path.getsize(path) == 0
        self.f = open(path, "ab")
        if fresh:
            self.f.write(bytes([VERSION_BYTE]))

    def append(self, msg: bytes, timestamp: int = 0, flags: int = 0,
               sync: bool = False):
        """Append one record.  sync=True makes the record durable before
        returning — the live ingest path uses this (the reference fsyncs
        before gossip is acked/relayed); bulk synthesis leaves it off."""
        crc = native.crc32c(timestamp, msg)
        hdr = (
            int(flags).to_bytes(2, "big")
            + len(msg).to_bytes(2, "big")
            + crc.to_bytes(4, "big")
            + int(timestamp).to_bytes(4, "big")
        )
        self.f.write(hdr + msg)
        if sync:
            self.sync()

    def sync(self):
        self.f.flush()
        os.fsync(self.f.fileno())

    def append_many(self, msgs, timestamps=None):
        parts = []
        for i, msg in enumerate(msgs):
            ts = int(timestamps[i]) if timestamps is not None else 0
            crc = native.crc32c(ts, msg)
            parts.append(
                (0).to_bytes(2, "big") + len(msg).to_bytes(2, "big")
                + crc.to_bytes(4, "big") + ts.to_bytes(4, "big") + msg
            )
        self.f.write(b"".join(parts))

    def close(self):
        self.f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def mark_deleted(path: str, scids: set[int]) -> int:
    """Set FLAG_DELETED in place on every channel_announcement /
    channel_update whose scid is in `scids` (the reference's
    gossip_store_del flag flip, gossipd/gossip_store.c).  The crc covers
    (timestamp, msg) only, so flag flips never invalidate records.
    Returns the number of records flagged."""
    from . import wire as gwire

    idx = load_store(path)
    n = 0
    with open(path, "r+b") as f:
        for i in range(len(idx)):
            if idx.flags[i] & FLAG_DELETED:
                continue
            if idx.types[i] not in (gwire.MSG_CHANNEL_ANNOUNCEMENT,
                                    gwire.MSG_CHANNEL_UPDATE):
                continue
            try:
                p = gwire.parse_gossip(idx.message(i))
            except Exception:
                continue
            if p.short_channel_id in scids:
                f.seek(int(idx.offsets[i]) - 12)
                f.write((int(idx.flags[i])
                         | FLAG_DELETED).to_bytes(2, "big"))
                n += 1
        f.flush()
        os.fsync(f.fileno())
    return n


def compact_store(src: str, dst: str) -> int:
    """Rewrite the store dropping deleted records (the reference runs this
    as a dedicated subdaemon, gossipd/compactd.c).  Returns record count."""
    idx = load_store(src)
    keep = idx.select(idx.alive())
    out = []
    for i in range(len(keep)):
        o, l = int(keep.offsets[i]), int(keep.lengths[i])
        hdr = (
            int(keep.flags[i]).to_bytes(2, "big")
            + l.to_bytes(2, "big")
            + int(keep.crcs[i]).to_bytes(4, "big")
            + int(keep.timestamps[i]).to_bytes(4, "big")
        )
        out.append(hdr + bytes(keep.buf[o : o + l]))
    # write-then-rename: never truncate dst in place — loaded StoreIndexes
    # are live mmaps of it, and rewriting the mapped inode would SIGBUS
    # them.  rename swaps the directory entry; old maps keep the old inode.
    tmp = dst + f".compact.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(bytes([VERSION_BYTE]) + b"".join(out))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, dst)
    return len(keep)
