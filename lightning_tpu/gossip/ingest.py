"""Live gossip ingest: dedup/pending machinery in front of the batched
verify kernels, feeding the gossip_store and the routing graph.

Parity target: gossipd/gossmap_manage.c:35-115 (pending maps, dedup),
:620-683 (channel_announcement checks), :687/:924/:1217 (the sigcheck
call sites — replaced here by one batched device flush), plus the
ratelimit/stale-update rules of BOLT#7.  The TPU-first delta (SURVEY
§3.4): instead of one serial `check_signed_hash` per signature, messages
queue into a `VerifyItems` batch that is flushed to the chained
sha256d+ECDSA kernels when it reaches `flush_size` signatures or
`flush_ms` of latency budget — SURVEY §7.3's occupancy/latency policy.

The ingest object is transport-agnostic: daemons push raw gossip
messages via `submit()`; accepted messages are appended to the store
(write-ahead, fsync'd) and handed to `on_accept` for peer streaming.
"""
from __future__ import annotations

import asyncio
import logging
import os
import time
from dataclasses import dataclass, field

import numpy as np

from .. import obs
from ..obs import families as _families
from ..obs import journey as _journey
from ..resilience import deadline as _deadline
from ..resilience import overload as _overload
from ..utils import events, native, trace
from . import store as gstore
from . import verify as gverify
from . import wire

log = logging.getLogger("lightning_tpu.gossip.ingest")

# bounded-queue watermarks, in SIGNATURES (doc/overload.md): the queue
# sheds by priority at the high watermark and transport backpressure
# releases below the low one.  LOW_WM=0 means "half of high".
INGEST_HIGH_WM = int(os.environ.get("LIGHTNING_TPU_INGEST_HIGH_WM",
                                    "4096"))
INGEST_LOW_WM = (int(os.environ.get("LIGHTNING_TPU_INGEST_LOW_WM", "0"))
                 or INGEST_HIGH_WM // 2)
# pending-map bound (messages HELD for a missing channel, not queued):
# an adversarial storm of orphan updates must not grow memory either
PENDING_CAP = max(1024, INGEST_HIGH_WM)

_M_FLUSH_SECONDS = obs.histogram(
    "clntpu_gossip_flush_seconds",
    "End-to-end wall time of one ingest flush "
    "(build + device verify + apply + store append)")
_M_FLUSH_SIGS = obs.histogram(
    "clntpu_gossip_flush_sigs",
    "Signatures per ingest flush", buckets=obs.SIZE_BUCKETS)
_M_ACCEPTED = obs.counter(
    "clntpu_gossip_accepted_total", "Gossip messages accepted")
_M_DROPPED = obs.counter(
    "clntpu_gossip_dropped_total",
    "Gossip messages dropped/held before acceptance, by reason",
    labelnames=("reason",))
_M_QUEUE = obs.gauge(
    "clntpu_gossip_queue_sigs",
    "Signatures currently queued awaiting a verify flush")
_M_BACKLOG = _families.INGEST_BACKLOG
_M_FLUSH_ERRORS = _families.INGEST_FLUSH_ERRORS

# Drop reasons (observable in tests/metrics).
R_DUP = "duplicate"
R_STALE = "stale_timestamp"
R_BADSIG = "bad_signature"
R_NO_CHANNEL = "pending_no_channel"   # queued, not dropped
R_NO_UTXO = "utxo_check_failed"
R_RATELIMIT = "ratelimited"
R_MALFORMED = "malformed"
R_FLUSH_ERROR = "flush_error"         # batch lost to a flush exception
R_SHED = "shed_overload"              # priority-shed at the watermark
                                      # (metered in clntpu_shed_total +
                                      # the shed ring, doc/overload.md)

# BOLT#7 suggests limiting spammy channel_updates; the reference tracks
# per-channel tokens.  We allow a burst then 1 update per interval.
RATELIMIT_BURST = 4
RATELIMIT_INTERVAL = 300.0


@dataclass
class _QItem:
    kind: int                  # wire msg type
    parsed: object
    raw: bytes
    source: object             # opaque peer handle (None = local/store)
    n_sigs: int
    # correlation carrier minted at submit time (trace.new_corr): links
    # this message's enqueue span to the flush/dispatch spans that
    # eventually verify it, across the to_thread hop (doc/tracing.md)
    corr: object = None
    # enqueue time (self.now() at admission): the per-item queue-wait
    # anchor for the journey verify hop (doc/journeys.md §semantics)
    t_enq: float = 0.0


def _journey_entity(kind: int, parsed) -> tuple[str, object]:
    """The journey entity a gossip message narrates: channel messages
    key on their scid, node announcements on the node id."""
    if kind == wire.MSG_NODE_ANNOUNCEMENT:
        return "node", parsed.node_id
    return "channel", int(parsed.short_channel_id)


def _shed_key(kind: int, parsed) -> dict:
    """Message identity recorded with every shed (doc/overload.md):
    the re-request key — a shed scid can be re-fetched later via
    query_short_channel_ids, a node id via its next announcement —
    and the exact-subset key loadgen's replay-parity check matches on."""
    if kind == wire.MSG_CHANNEL_ANNOUNCEMENT:
        return {"kind": "channel_announcement",
                "scid": int(parsed.short_channel_id)}
    if kind == wire.MSG_CHANNEL_UPDATE:
        return {"kind": "channel_update",
                "scid": int(parsed.short_channel_id),
                "direction": int(parsed.direction),
                "timestamp": int(parsed.timestamp)}
    return {"kind": "node_announcement",
            "node_id": parsed.node_id.hex(),
            "timestamp": int(parsed.timestamp)}


@dataclass
class IngestStats:
    accepted: int = 0
    dropped: dict = field(default_factory=dict)
    flushes: int = 0
    batched_sigs: int = 0
    max_batch: int = 0

    def drop(self, reason: str) -> None:
        self.dropped[reason] = self.dropped.get(reason, 0) + 1
        _M_DROPPED.labels(reason).inc()


class GossipIngest:
    """Dedup + pending + batched-verify + store-append pipeline."""

    def __init__(self, store_path: str, *, utxo_check=None,
                 flush_size: int = 256, flush_ms: float = 2.0,
                 bucket: int = gverify.DEFAULT_BUCKET,
                 replay_depth: int | None = None,
                 on_accept=None, now=time.monotonic,
                 own_node_id: bytes | None = None,
                 high_wm: int | None = None, low_wm: int | None = None,
                 pending_cap: int | None = None):
        self.writer = gstore.StoreWriter(store_path)
        self.utxo_check = utxo_check      # async (scid)->sat|None, or None
        self.flush_size = flush_size
        self.flush_ms = flush_ms
        self.bucket = bucket
        # overload control (doc/overload.md): bounded queue with
        # priority shedding; own-node/own-channel traffic (keyed on
        # own_node_id) sheds last.  The breaker family is "verify" —
        # an open verify breaker slows the drain, so the retry hints
        # and the ladder snapshot consult it.
        self.own_node_id = own_node_id
        self.pending_cap = PENDING_CAP if pending_cap is None \
            else pending_cap
        self.overload = _overload.controller(
            "ingest",
            high_wm if high_wm is not None else INGEST_HIGH_WM,
            low_wm if low_wm is not None else INGEST_LOW_WM,
            breaker_family="verify", now=now)
        # prepared-bucket pipeline depth for the verify flush (None =
        # verify_items' default double-buffering; catch-up syncs whose
        # flushes span many buckets overlap host pack with device
        # compute, single-bucket live flushes are unaffected)
        self.replay_depth = replay_depth
        self.on_accept = on_accept        # callback(raw, source)
        self.now = now
        self.stats = IngestStats()

        # accepted-state tables (gossmap_manage's in-memory view)
        self.channels: dict[int, tuple[bytes, bytes]] = {}  # scid -> nodes
        self.updates: dict[tuple[int, int], int] = {}   # (scid,dir) -> ts
        self.nodes: dict[bytes, int] = {}               # node_id -> ts
        self._channeled_nodes: set[bytes] = set()       # O(1) NA gate
        self._accepted: list[_QItem] = []               # staged this flush
        # pending (messages that arrived before their channel)
        self.pending_updates: dict[int, dict[int, _QItem]] = {}
        self.pending_nodes: dict[bytes, _QItem] = {}
        # ratelimit token state per (scid, direction)
        self._tokens: dict[tuple[int, int], tuple[float, float]] = {}

        self._queue: list[_QItem] = []
        self._queued_sigs = 0
        self._inflight_sigs = 0          # popped batch being verified
        self._pending_held = 0           # entries across both pending maps
        self._flush_due: float | None = None
        self._wakeup = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._closed = False
        self._flushing = False

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def warmup(self) -> None:
        """Pre-compile the fused verify program at this ingest's bucket
        (see verify.warmup: a cold compile inside a live flush stalls
        acceptance for minutes).  Daemons call this at startup; safe to
        skip for pure-CPU library use where the caller prefers lazy
        compilation."""
        await asyncio.to_thread(gverify.warmup, self.bucket)

    async def close(self) -> None:
        self._closed = True
        self._wakeup.set()
        if self._task is not None:
            await self._task
        self.writer.close()

    # -- submission -------------------------------------------------------

    async def wait_capacity(self, max_wait: float | None = None) -> float:
        """Transport-side backpressure point (doc/overload.md): while
        the ingest backlog is saturated, pause the calling read pump —
        bounded per message, every waiter released together once the
        backlog drains below the low watermark.  Gossipd awaits this
        before submitting each peer message, which stops that peer's
        socket reads and lets TCP push back on the remote."""
        return await self.overload.wait_capacity(max_wait)

    async def submit(self, raw: bytes, source=None) -> None:
        """Queue one raw gossip message for verification.  The submit
        span is the message's enqueue point: the correlation carrier
        minted here rides the queue item into the flush, so the
        exported timeline draws a flow arrow from this span to the
        device dispatch that verified the message."""
        with trace.span("gossip/submit"):
            try:
                parsed = wire.parse_gossip(raw)
            except Exception:
                self.stats.drop(R_MALFORMED)
                return
            if parsed is None:
                self.stats.drop(R_MALFORMED)
                return
            kind = wire.msg_type(raw)
            if not self._precheck(kind, parsed, raw, source):
                return
            # overload admission, deliberately BEFORE the ratelimiter:
            # a shed message must not spend a ratelimit token, or an
            # unthrottled replay of the non-shed subset would see a
            # different token state and accept a different set — the
            # bit-identical-replay contract tools/loadgen.py asserts
            prio = self._priority(kind, parsed)
            n_sigs = 4 if kind == wire.MSG_CHANNEL_ANNOUNCEMENT else 1
            if not self.overload.admit(prio, n_sigs):
                self.stats.drop(R_SHED)
                self.overload.shed(prio, "queue_full",
                                   **_shed_key(kind, parsed))
                jk, jkey = _journey_entity(kind, parsed)
                _journey.hop("shed", jk, jkey, outcome=R_SHED,
                             reason="queue_full")
                return
            if kind == wire.MSG_CHANNEL_UPDATE and not self._ratelimit_ok(
                    (parsed.short_channel_id, parsed.direction)):
                self.stats.drop(R_RATELIMIT)
                jk, jkey = _journey_entity(kind, parsed)
                _journey.hop("drop", jk, jkey, outcome=R_RATELIMIT)
                return
            it = _QItem(kind, parsed, raw, source, n_sigs,
                        corr=trace.new_corr(), t_enq=self.now())
            self._queue.append(it)
            self._queued_sigs += n_sigs
            jk, jkey = _journey_entity(kind, parsed)
            _journey.hop("admit", jk, jkey, outcome="ok",
                         corr_id=it.corr.corr_id,
                         queued_sigs=self._queued_sigs)
        self._note_backlog()
        if self._flush_due is None:
            # adaptive flush window: the latency budget stretches as
            # pressure rises (throughput over latency under load)
            self._flush_due = self.now() + self.overload.window_s(
                self.flush_ms)
            # the loop may be parked on an indefinite wait — rearm it so
            # it recomputes its timeout against the new deadline
            self._wakeup.set()
        if self._queued_sigs >= self._flush_threshold():
            self._wakeup.set()

    def _flush_threshold(self) -> int:
        """Adaptive size trigger: flush_size when calm, widening toward
        flush_size * LIGHTNING_TPU_FLUSH_WIDEN as the backlog climbs —
        bigger batches amortize dispatch overhead exactly when the
        storm makes overhead matter (doc/overload.md)."""
        return self.overload.flush_target(self.flush_size)

    def _note_backlog(self) -> None:
        _M_QUEUE.set(self._queued_sigs)
        _M_BACKLOG.set(self._queued_sigs + self._inflight_sigs)
        self.overload.update(self._queued_sigs, self._inflight_sigs)

    def _priority(self, kind: int, parsed) -> int:
        """Shed-priority classes (doc/overload.md): own-node/own-channel
        traffic sheds last, fresh third-party channel data next, node
        announcements first."""
        own = self.own_node_id
        if kind == wire.MSG_CHANNEL_ANNOUNCEMENT:
            if own is not None and own in (parsed.node_id_1,
                                           parsed.node_id_2):
                return _overload.PRIO_OWN
            return _overload.PRIO_FRESH
        if kind == wire.MSG_CHANNEL_UPDATE:
            if own is not None and own in self.channels.get(
                    parsed.short_channel_id, ()):
                return _overload.PRIO_OWN
            return _overload.PRIO_FRESH
        # node_announcement
        if own is not None and parsed.node_id == own:
            return _overload.PRIO_OWN
        return _overload.PRIO_BULK

    def _precheck(self, kind: int, parsed, raw: bytes, source) -> bool:
        """Cheap host-side dedup BEFORE paying for signature checks
        (gossmap_manage.c does the same ordering).  Stateful gates
        (ratelimit, overload admission) live in submit(), after this
        purely content-keyed screen."""
        if kind == wire.MSG_CHANNEL_ANNOUNCEMENT:
            if parsed.short_channel_id in self.channels:
                self.stats.drop(R_DUP)
                _journey.hop("drop", "channel",
                             parsed.short_channel_id, outcome=R_DUP)
                return False
        elif kind == wire.MSG_CHANNEL_UPDATE:
            key = (parsed.short_channel_id, parsed.direction)
            if self.updates.get(key, -1) >= parsed.timestamp:
                self.stats.drop(R_STALE)
                _journey.hop("drop", "channel",
                             parsed.short_channel_id, outcome=R_STALE)
                return False
            if parsed.short_channel_id not in self.channels:
                # can't verify yet — the signer is node[direction] of a
                # channel we don't know.  Hold latest per direction
                # (gossmap_manage's pending_cupdates), re-submitted when
                # the channel_announcement lands.  The pending maps are
                # bounded too: past the cap, NEW keys shed (metered)
                # instead of growing without limit.
                held = self.pending_updates.get(parsed.short_channel_id)
                prev = held.get(parsed.direction) if held else None
                if prev is None:
                    if self._pending_held >= self.pending_cap:
                        # classify honestly for the shed record.  (An
                        # own-channel update is indistinguishable here —
                        # the channel's endpoints are exactly what we
                        # don't know yet — so it classifies "fresh";
                        # the shed ring still makes it re-requestable.)
                        self.stats.drop(R_SHED)
                        self.overload.shed(self._priority(kind, parsed),
                                           "pending_cap",
                                           **_shed_key(kind, parsed))
                        _journey.hop("shed", "channel",
                                     parsed.short_channel_id,
                                     outcome=R_SHED,
                                     reason="pending_cap")
                        return False
                    self.pending_updates.setdefault(
                        parsed.short_channel_id, {})[parsed.direction] = \
                        _QItem(kind, parsed, raw, source, 1)
                    self._pending_held += 1
                elif prev.parsed.timestamp < parsed.timestamp:
                    held[parsed.direction] = _QItem(
                        kind, parsed, raw, source, 1)
                self.stats.drop(R_NO_CHANNEL)
                return False
        elif kind == wire.MSG_NODE_ANNOUNCEMENT:
            if self.nodes.get(parsed.node_id, -1) >= parsed.timestamp:
                self.stats.drop(R_STALE)
                _journey.hop("drop", "node", parsed.node_id,
                             outcome=R_STALE)
                return False
        else:
            self.stats.drop(R_MALFORMED)
            return False
        return True

    def _ratelimit_ok(self, key) -> bool:
        tokens, last = self._tokens.get(key, (float(RATELIMIT_BURST), 0.0))
        t = self.now()
        tokens = min(RATELIMIT_BURST,
                     tokens + (t - last) / RATELIMIT_INTERVAL)
        if tokens < 1.0:
            self._tokens[key] = (tokens, t)
            return False
        self._tokens[key] = (tokens - 1.0, t)
        return True

    # -- the flush loop ---------------------------------------------------

    async def _run(self) -> None:
        """Supervised flush loop: an exception escaping a flush used to
        kill this task SILENTLY — every later submit queued forever
        with no signal.  Now the error is metered
        (clntpu_ingest_flush_errors_total), emitted on the events bus
        (topic `ingest_flush_error`), and the loop restarts with capped
        exponential backoff."""
        backoff = _deadline.RestartBackoff()
        while not self._closed:
            try:
                await self._step()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                delay = backoff.next()
                _M_FLUSH_ERRORS.inc()
                _deadline.note_restart("ingest_flush", e, delay)
                events.emit("ingest_flush_error",
                            {"error": repr(e),
                             "restart_delay_s": round(delay, 3)})
                await asyncio.sleep(delay)
            else:
                backoff.reset()
        if self._queue:
            try:
                await self.flush()
            except Exception as e:  # shutting down: surface, don't retry
                _M_FLUSH_ERRORS.inc()
                events.emit("ingest_flush_error",
                            {"error": repr(e), "restart_delay_s": 0.0})
                log.exception("final ingest flush failed on close")

    async def _step(self) -> None:
        """One flush-loop iteration (wait for a deadline/size trigger,
        flush if due)."""
        if self._flush_due is None:
            await self._wakeup.wait()
            self._wakeup.clear()
            return
        timeout = self._flush_due - self.now()
        if timeout > 0 and self._queued_sigs < self._flush_threshold():
            try:
                await asyncio.wait_for(self._wakeup.wait(), timeout)
            except asyncio.TimeoutError:
                pass
            self._wakeup.clear()
            return  # re-evaluate: deadline, size, or shutdown
        if self._queue:
            await self.flush()

    async def drain(self) -> None:
        """Wait until every submitted message has been flushed+applied
        (including pending resubmissions triggered by those flushes)."""
        while self._queue or self._flushing:
            await asyncio.sleep(0.005)

    async def flush(self) -> None:
        """Verify everything queued in one batched device dispatch, then
        apply accepted messages in arrival order."""
        batch, self._queue = self._queue, []
        n_sigs = self._queued_sigs
        self._queued_sigs = 0
        self._flush_due = None
        self._inflight_sigs = n_sigs
        self._note_backlog()
        if not batch:
            self._inflight_sigs = 0
            return
        self._flushing = True
        t0 = time.perf_counter()
        try:
            await self._flush_batch(batch)
        except BaseException:
            # the batch was already popped; account for the loss so a
            # scrape can tell "dropped by policy" from "lost to a crash"
            # (application may have partially happened — approximate)
            for _ in batch:
                self.stats.drop(R_FLUSH_ERROR)
            raise
        finally:
            self._flushing = False
            dt = time.perf_counter() - t0
            _M_FLUSH_SECONDS.observe(dt)
            self._inflight_sigs = 0
            # drain-rate feedback for the overload retry hints, then
            # publish the post-flush backlog (wakes backpressure
            # waiters if we fell below the low watermark)
            self.overload.note_drain(n_sigs, dt)
            self._note_backlog()

    async def _flush_batch(self, batch: list[_QItem]) -> None:
        corrs = [it.corr for it in batch if it.corr is not None]
        items = self._build_items(batch)
        self.stats.flushes += 1
        self.stats.batched_sigs += len(items)
        self.stats.max_batch = max(self.stats.max_batch, len(items))
        _M_FLUSH_SIGS.observe(len(items))
        # dispatch deadline (LIGHTNING_TPU_DEADLINE_INGEST_S, off by
        # default): a hung verify worker surfaces as a metered
        # DeadlineExceeded — handled by _run's restart supervision —
        # instead of wedging the loop forever.  The guard bounds ONLY
        # the (pure) verify dispatch: a blown deadline here cancels
        # nothing stateful, so apply + durable store append below can
        # never be split by the timeout.  The batch's corr carriers
        # cross the to_thread hop explicitly (contextvars won't), so
        # every bucket dispatched for this flush flows back to the
        # submit spans in the exported timeline.
        # per-item provenance (doc/journeys.md): dispatch_map receives,
        # per signature, the dispatch_id of the flight record whose
        # bucket verified it; the batch-side queue-wait counter sums
        # (flush_start − enqueue) over EVERY queued item so the sampled
        # journeys' waits reconcile against it within ε
        jw = _journey.enabled()
        dmap = np.full(len(items), -1, np.int64) if jw else None
        t_flush0 = self.now()
        if jw:
            _journey.note_batch_wait(
                "verify", sum(max(0.0, t_flush0 - it.t_enq)
                              for it in batch if it.t_enq))
        t_verify0 = time.perf_counter()
        with trace.span("gossip/flush", corr=corrs, sigs=len(items)):
            ok = await _deadline.guard(
                asyncio.to_thread(gverify.verify_items, items,
                                  self.bucket, depth=self.replay_depth,
                                  corr=corrs, dispatch_map=dmap),
                family="ingest", seam="flush")
        verify_dt = time.perf_counter() - t_verify0
        # fold per-sig results to per-message (CAs have 4 sigs)
        sig_ok: list[bool] = []
        first_sig: list[int] = []
        pos = 0
        for it in batch:
            sig_ok.append(bool(ok[pos: pos + it.n_sigs].all()))
            first_sig.append(pos)
            pos += it.n_sigs
        if jw:
            for it, good, fs in zip(batch, sig_ok, first_sig):
                jk, jkey = _journey_entity(it.kind, it.parsed)
                did = int(dmap[fs]) if dmap is not None \
                    and fs < len(dmap) and dmap[fs] >= 0 else None
                _journey.hop(
                    "verify", jk, jkey,
                    outcome="ok" if good else R_BADSIG,
                    wait_s=max(0.0, t_flush0 - it.t_enq)
                    if it.t_enq else 0.0,
                    service_s=verify_dt, dispatch_id=did,
                    corr_id=it.corr.corr_id
                    if it.corr is not None else None)
        self._accepted = []
        for it, good in zip(batch, sig_ok):
            if not good:
                self.stats.drop(R_BADSIG)
                if jw:
                    jk, jkey = _journey_entity(it.kind, it.parsed)
                    _journey.hop("drop", jk, jkey, outcome=R_BADSIG)
                continue
            await self._apply(it)
        if self._accepted:
            # write-ahead: ONE append_many + fsync for the whole batch,
            # then stream — nothing reaches peers before it is durable
            t_store0 = time.perf_counter()
            self.writer.append_many(
                [it.raw for it in self._accepted],
                [getattr(it.parsed, "timestamp", 0)
                 for it in self._accepted], sync=True)
            store_dt = time.perf_counter() - t_store0
            if jw:
                for it in self._accepted:
                    jk, jkey = _journey_entity(it.kind, it.parsed)
                    _journey.hop(
                        "store", jk, jkey, outcome="ok",
                        service_s=store_dt,
                        corr_id=it.corr.corr_id
                        if it.corr is not None else None)
            self.stats.accepted += len(self._accepted)
            _M_ACCEPTED.inc(len(self._accepted))
            if self.on_accept is not None:
                for it in self._accepted:
                    self.on_accept(it.raw, it.source)
            self._accepted = []

    async def _apply(self, it: _QItem) -> None:
        """Post-signature acceptance: state tables + store + streaming."""
        kind, p = it.kind, it.parsed
        if kind == wire.MSG_CHANNEL_ANNOUNCEMENT:
            scid = p.short_channel_id
            if scid in self.channels:       # raced within one batch
                self.stats.drop(R_DUP)
                _journey.hop("drop", "channel", scid, outcome=R_DUP)
                return
            if self.utxo_check is not None:
                sat = await self.utxo_check(scid)
                if sat is None:
                    self.stats.drop(R_NO_UTXO)
                    _journey.hop("drop", "channel", scid,
                                 outcome=R_NO_UTXO)
                    return
            self.channels[scid] = (p.node_id_1, p.node_id_2)
            self._channeled_nodes.update((p.node_id_1, p.node_id_2))
            self._accept(it)
            # drain pendings now satisfiable
            drained = self.pending_updates.pop(scid, {})
            self._pending_held -= len(drained)
            for q in drained.values():
                await self.submit(q.raw, q.source)
            for nid in (p.node_id_1, p.node_id_2):
                q = self.pending_nodes.pop(nid, None)
                if q is not None:
                    self._pending_held -= 1
                    await self.submit(q.raw, q.source)
        elif kind == wire.MSG_CHANNEL_UPDATE:
            scid, d = p.short_channel_id, p.direction
            if self.updates.get((scid, d), -1) >= p.timestamp:
                self.stats.drop(R_STALE)   # raced within one batch
                _journey.hop("drop", "channel", scid, outcome=R_STALE)
                return
            self.updates[(scid, d)] = p.timestamp
            self._accept(it)
        elif kind == wire.MSG_NODE_ANNOUNCEMENT:
            nid = p.node_id
            if nid not in self._channeled_nodes:
                prev = self.pending_nodes.get(nid)
                if prev is None:
                    # held-map bound, same contract as pending_updates
                    # (this one post-verify: the signature was real, but
                    # an orphan-NA flood must still not grow memory).
                    # OWN node announcements are exempt: they are
                    # intrinsically bounded (one node) and the
                    # own-sheds-last contract must hold here too.
                    prio = self._priority(kind, p)
                    if prio != _overload.PRIO_OWN and \
                            self._pending_held >= self.pending_cap:
                        self.stats.drop(R_SHED)
                        self.overload.shed(prio, "pending_cap",
                                           **_shed_key(kind, p))
                        return
                    self.pending_nodes[nid] = it
                    self._pending_held += 1
                elif prev.parsed.timestamp < p.timestamp:
                    self.pending_nodes[nid] = it
                self.stats.drop(R_NO_CHANNEL)
                return
            if self.nodes.get(nid, -1) >= p.timestamp:
                self.stats.drop(R_STALE)
                _journey.hop("drop", "node", nid, outcome=R_STALE)
                return
            self.nodes[nid] = p.timestamp
            self._accept(it)

    def _accept(self, it: _QItem) -> None:
        """Stage for the per-flush store write (one append_many + fsync
        per batch, not per message)."""
        self._accepted.append(it)


    def _build_items(self, batch: list[_QItem]) -> gverify.VerifyItems:
        """Flatten queued messages into one VerifyItems workload: ONE
        hashed row per message, with row_of_item fanning the 4
        channel_announcement signatures onto their shared row (same
        layout as the store-replay extractor)."""
        regions: list[bytes] = []
        sigs: list[bytes] = []
        keys: list[bytes] = []
        midx: list[int] = []
        roi: list[int] = []
        for i, it in enumerate(batch):
            p = it.parsed
            row = len(regions)
            regions.append(p.signed_region())
            if it.kind == wire.MSG_CHANNEL_ANNOUNCEMENT:
                for sig, key in p.signature_tuples():
                    sigs.append(sig)
                    keys.append(key)
                    midx.append(i)
                    roi.append(row)
            elif it.kind == wire.MSG_CHANNEL_UPDATE:
                # _precheck guarantees the channel is known by now; the
                # signer is the channel endpoint for this direction, so
                # identity and signature are checked in one kernel pass.
                sigs.append(p.signature)
                keys.append(self.channels[p.short_channel_id][p.direction])
                midx.append(i)
                roi.append(row)
            else:  # node_announcement (self-signed)
                sigs.append(p.signature)
                keys.append(p.node_id)
                midx.append(i)
                roi.append(row)
        buf = np.frombuffer(b"".join(regions), np.uint8)
        lengths = np.array([len(r) for r in regions], np.int64)
        offsets = np.concatenate(
            [[0], np.cumsum(lengths)[:-1]]).astype(np.int64)
        rows, nb = native.sha256_pack(buf, offsets, lengths,
                                      gverify.MAX_BLOCKS)
        z_host = gverify._host_hash_oversized(buf, offsets, lengths, nb)
        return gverify.VerifyItems(
            rows, nb,
            np.frombuffer(b"".join(sigs), np.uint8).reshape(-1, 64),
            np.frombuffer(b"".join(k.ljust(33, b"\0") for k in keys),
                          np.uint8).reshape(-1, 33),
            np.array(midx, np.int64), z_host,
            np.array(roi, np.int64),
        )
