"""Live gossip ingest: dedup/pending machinery in front of the batched
verify kernels, feeding the gossip_store and the routing graph.

Parity target: gossipd/gossmap_manage.c:35-115 (pending maps, dedup),
:620-683 (channel_announcement checks), :687/:924/:1217 (the sigcheck
call sites — replaced here by one batched device flush), plus the
ratelimit/stale-update rules of BOLT#7.  The TPU-first delta (SURVEY
§3.4): instead of one serial `check_signed_hash` per signature, messages
queue into a `VerifyItems` batch that is flushed to the chained
sha256d+ECDSA kernels when it reaches `flush_size` signatures or
`flush_ms` of latency budget — SURVEY §7.3's occupancy/latency policy.

The ingest object is transport-agnostic: daemons push raw gossip
messages via `submit()`; accepted messages are appended to the store
(write-ahead, fsync'd) and handed to `on_accept` for peer streaming.
"""
from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field

import numpy as np

from .. import obs
from ..obs import families as _families
from ..resilience import deadline as _deadline
from ..utils import events, native, trace
from . import store as gstore
from . import verify as gverify
from . import wire

log = logging.getLogger("lightning_tpu.gossip.ingest")

_M_FLUSH_SECONDS = obs.histogram(
    "clntpu_gossip_flush_seconds",
    "End-to-end wall time of one ingest flush "
    "(build + device verify + apply + store append)")
_M_FLUSH_SIGS = obs.histogram(
    "clntpu_gossip_flush_sigs",
    "Signatures per ingest flush", buckets=obs.SIZE_BUCKETS)
_M_ACCEPTED = obs.counter(
    "clntpu_gossip_accepted_total", "Gossip messages accepted")
_M_DROPPED = obs.counter(
    "clntpu_gossip_dropped_total",
    "Gossip messages dropped/held before acceptance, by reason",
    labelnames=("reason",))
_M_QUEUE = obs.gauge(
    "clntpu_gossip_queue_sigs",
    "Signatures currently queued awaiting a verify flush")
_M_FLUSH_ERRORS = _families.INGEST_FLUSH_ERRORS

# Drop reasons (observable in tests/metrics).
R_DUP = "duplicate"
R_STALE = "stale_timestamp"
R_BADSIG = "bad_signature"
R_NO_CHANNEL = "pending_no_channel"   # queued, not dropped
R_NO_UTXO = "utxo_check_failed"
R_RATELIMIT = "ratelimited"
R_MALFORMED = "malformed"
R_FLUSH_ERROR = "flush_error"         # batch lost to a flush exception

# BOLT#7 suggests limiting spammy channel_updates; the reference tracks
# per-channel tokens.  We allow a burst then 1 update per interval.
RATELIMIT_BURST = 4
RATELIMIT_INTERVAL = 300.0


@dataclass
class _QItem:
    kind: int                  # wire msg type
    parsed: object
    raw: bytes
    source: object             # opaque peer handle (None = local/store)
    n_sigs: int
    # correlation carrier minted at submit time (trace.new_corr): links
    # this message's enqueue span to the flush/dispatch spans that
    # eventually verify it, across the to_thread hop (doc/tracing.md)
    corr: object = None


@dataclass
class IngestStats:
    accepted: int = 0
    dropped: dict = field(default_factory=dict)
    flushes: int = 0
    batched_sigs: int = 0
    max_batch: int = 0

    def drop(self, reason: str) -> None:
        self.dropped[reason] = self.dropped.get(reason, 0) + 1
        _M_DROPPED.labels(reason).inc()


class GossipIngest:
    """Dedup + pending + batched-verify + store-append pipeline."""

    def __init__(self, store_path: str, *, utxo_check=None,
                 flush_size: int = 256, flush_ms: float = 2.0,
                 bucket: int = gverify.DEFAULT_BUCKET,
                 replay_depth: int | None = None,
                 on_accept=None, now=time.monotonic):
        self.writer = gstore.StoreWriter(store_path)
        self.utxo_check = utxo_check      # async (scid)->sat|None, or None
        self.flush_size = flush_size
        self.flush_ms = flush_ms
        self.bucket = bucket
        # prepared-bucket pipeline depth for the verify flush (None =
        # verify_items' default double-buffering; catch-up syncs whose
        # flushes span many buckets overlap host pack with device
        # compute, single-bucket live flushes are unaffected)
        self.replay_depth = replay_depth
        self.on_accept = on_accept        # callback(raw, source)
        self.now = now
        self.stats = IngestStats()

        # accepted-state tables (gossmap_manage's in-memory view)
        self.channels: dict[int, tuple[bytes, bytes]] = {}  # scid -> nodes
        self.updates: dict[tuple[int, int], int] = {}   # (scid,dir) -> ts
        self.nodes: dict[bytes, int] = {}               # node_id -> ts
        self._channeled_nodes: set[bytes] = set()       # O(1) NA gate
        self._accepted: list[_QItem] = []               # staged this flush
        # pending (messages that arrived before their channel)
        self.pending_updates: dict[int, dict[int, _QItem]] = {}
        self.pending_nodes: dict[bytes, _QItem] = {}
        # ratelimit token state per (scid, direction)
        self._tokens: dict[tuple[int, int], tuple[float, float]] = {}

        self._queue: list[_QItem] = []
        self._queued_sigs = 0
        self._flush_due: float | None = None
        self._wakeup = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._closed = False
        self._flushing = False

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def warmup(self) -> None:
        """Pre-compile the fused verify program at this ingest's bucket
        (see verify.warmup: a cold compile inside a live flush stalls
        acceptance for minutes).  Daemons call this at startup; safe to
        skip for pure-CPU library use where the caller prefers lazy
        compilation."""
        await asyncio.to_thread(gverify.warmup, self.bucket)

    async def close(self) -> None:
        self._closed = True
        self._wakeup.set()
        if self._task is not None:
            await self._task
        self.writer.close()

    # -- submission -------------------------------------------------------

    async def submit(self, raw: bytes, source=None) -> None:
        """Queue one raw gossip message for verification.  The submit
        span is the message's enqueue point: the correlation carrier
        minted here rides the queue item into the flush, so the
        exported timeline draws a flow arrow from this span to the
        device dispatch that verified the message."""
        with trace.span("gossip/submit"):
            try:
                parsed = wire.parse_gossip(raw)
            except Exception:
                self.stats.drop(R_MALFORMED)
                return
            if parsed is None:
                self.stats.drop(R_MALFORMED)
                return
            kind = wire.msg_type(raw)
            if not self._precheck(kind, parsed, raw, source):
                return
            n_sigs = 4 if kind == wire.MSG_CHANNEL_ANNOUNCEMENT else 1
            self._queue.append(_QItem(kind, parsed, raw, source, n_sigs,
                                      corr=trace.new_corr()))
            self._queued_sigs += n_sigs
        _M_QUEUE.set(self._queued_sigs)
        if self._flush_due is None:
            self._flush_due = self.now() + self.flush_ms / 1000.0
            # the loop may be parked on an indefinite wait — rearm it so
            # it recomputes its timeout against the new deadline
            self._wakeup.set()
        if self._queued_sigs >= self.flush_size:
            self._wakeup.set()

    def _precheck(self, kind: int, parsed, raw: bytes, source) -> bool:
        """Cheap host-side dedup BEFORE paying for signature checks
        (gossmap_manage.c does the same ordering)."""
        if kind == wire.MSG_CHANNEL_ANNOUNCEMENT:
            if parsed.short_channel_id in self.channels:
                self.stats.drop(R_DUP)
                return False
        elif kind == wire.MSG_CHANNEL_UPDATE:
            key = (parsed.short_channel_id, parsed.direction)
            if self.updates.get(key, -1) >= parsed.timestamp:
                self.stats.drop(R_STALE)
                return False
            if parsed.short_channel_id not in self.channels:
                # can't verify yet — the signer is node[direction] of a
                # channel we don't know.  Hold latest per direction
                # (gossmap_manage's pending_cupdates), re-submitted when
                # the channel_announcement lands.
                held = self.pending_updates.setdefault(
                    parsed.short_channel_id, {})
                prev = held.get(parsed.direction)
                if prev is None or prev.parsed.timestamp < parsed.timestamp:
                    held[parsed.direction] = _QItem(
                        kind, parsed, raw, source, 1)
                self.stats.drop(R_NO_CHANNEL)
                return False
            if not self._ratelimit_ok(key):
                self.stats.drop(R_RATELIMIT)
                return False
        elif kind == wire.MSG_NODE_ANNOUNCEMENT:
            if self.nodes.get(parsed.node_id, -1) >= parsed.timestamp:
                self.stats.drop(R_STALE)
                return False
        else:
            self.stats.drop(R_MALFORMED)
            return False
        return True

    def _ratelimit_ok(self, key) -> bool:
        tokens, last = self._tokens.get(key, (float(RATELIMIT_BURST), 0.0))
        t = self.now()
        tokens = min(RATELIMIT_BURST,
                     tokens + (t - last) / RATELIMIT_INTERVAL)
        if tokens < 1.0:
            self._tokens[key] = (tokens, t)
            return False
        self._tokens[key] = (tokens - 1.0, t)
        return True

    # -- the flush loop ---------------------------------------------------

    async def _run(self) -> None:
        """Supervised flush loop: an exception escaping a flush used to
        kill this task SILENTLY — every later submit queued forever
        with no signal.  Now the error is metered
        (clntpu_ingest_flush_errors_total), emitted on the events bus
        (topic `ingest_flush_error`), and the loop restarts with capped
        exponential backoff."""
        backoff = _deadline.RestartBackoff()
        while not self._closed:
            try:
                await self._step()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                delay = backoff.next()
                _M_FLUSH_ERRORS.inc()
                _deadline.note_restart("ingest_flush", e, delay)
                events.emit("ingest_flush_error",
                            {"error": repr(e),
                             "restart_delay_s": round(delay, 3)})
                await asyncio.sleep(delay)
            else:
                backoff.reset()
        if self._queue:
            try:
                await self.flush()
            except Exception as e:  # shutting down: surface, don't retry
                _M_FLUSH_ERRORS.inc()
                events.emit("ingest_flush_error",
                            {"error": repr(e), "restart_delay_s": 0.0})
                log.exception("final ingest flush failed on close")

    async def _step(self) -> None:
        """One flush-loop iteration (wait for a deadline/size trigger,
        flush if due)."""
        if self._flush_due is None:
            await self._wakeup.wait()
            self._wakeup.clear()
            return
        timeout = self._flush_due - self.now()
        if timeout > 0 and self._queued_sigs < self.flush_size:
            try:
                await asyncio.wait_for(self._wakeup.wait(), timeout)
            except asyncio.TimeoutError:
                pass
            self._wakeup.clear()
            return  # re-evaluate: deadline, size, or shutdown
        if self._queue:
            await self.flush()

    async def drain(self) -> None:
        """Wait until every submitted message has been flushed+applied
        (including pending resubmissions triggered by those flushes)."""
        while self._queue or self._flushing:
            await asyncio.sleep(0.005)

    async def flush(self) -> None:
        """Verify everything queued in one batched device dispatch, then
        apply accepted messages in arrival order."""
        batch, self._queue = self._queue, []
        self._queued_sigs = 0
        self._flush_due = None
        _M_QUEUE.set(0)
        if not batch:
            return
        self._flushing = True
        t0 = time.perf_counter()
        try:
            await self._flush_batch(batch)
        except BaseException:
            # the batch was already popped; account for the loss so a
            # scrape can tell "dropped by policy" from "lost to a crash"
            # (application may have partially happened — approximate)
            for _ in batch:
                self.stats.drop(R_FLUSH_ERROR)
            raise
        finally:
            self._flushing = False
            _M_FLUSH_SECONDS.observe(time.perf_counter() - t0)

    async def _flush_batch(self, batch: list[_QItem]) -> None:
        corrs = [it.corr for it in batch if it.corr is not None]
        items = self._build_items(batch)
        self.stats.flushes += 1
        self.stats.batched_sigs += len(items)
        self.stats.max_batch = max(self.stats.max_batch, len(items))
        _M_FLUSH_SIGS.observe(len(items))
        # dispatch deadline (LIGHTNING_TPU_DEADLINE_INGEST_S, off by
        # default): a hung verify worker surfaces as a metered
        # DeadlineExceeded — handled by _run's restart supervision —
        # instead of wedging the loop forever.  The guard bounds ONLY
        # the (pure) verify dispatch: a blown deadline here cancels
        # nothing stateful, so apply + durable store append below can
        # never be split by the timeout.  The batch's corr carriers
        # cross the to_thread hop explicitly (contextvars won't), so
        # every bucket dispatched for this flush flows back to the
        # submit spans in the exported timeline.
        with trace.span("gossip/flush", corr=corrs, sigs=len(items)):
            ok = await _deadline.guard(
                asyncio.to_thread(gverify.verify_items, items,
                                  self.bucket, depth=self.replay_depth,
                                  corr=corrs),
                family="ingest", seam="flush")
        # fold per-sig results to per-message (CAs have 4 sigs)
        sig_ok: list[bool] = []
        pos = 0
        for it in batch:
            sig_ok.append(bool(ok[pos: pos + it.n_sigs].all()))
            pos += it.n_sigs
        self._accepted = []
        for it, good in zip(batch, sig_ok):
            if not good:
                self.stats.drop(R_BADSIG)
                continue
            await self._apply(it)
        if self._accepted:
            # write-ahead: ONE append_many + fsync for the whole batch,
            # then stream — nothing reaches peers before it is durable
            self.writer.append_many(
                [it.raw for it in self._accepted],
                [getattr(it.parsed, "timestamp", 0)
                 for it in self._accepted])
            self.writer.sync()
            self.stats.accepted += len(self._accepted)
            _M_ACCEPTED.inc(len(self._accepted))
            if self.on_accept is not None:
                for it in self._accepted:
                    self.on_accept(it.raw, it.source)
            self._accepted = []

    async def _apply(self, it: _QItem) -> None:
        """Post-signature acceptance: state tables + store + streaming."""
        kind, p = it.kind, it.parsed
        if kind == wire.MSG_CHANNEL_ANNOUNCEMENT:
            scid = p.short_channel_id
            if scid in self.channels:       # raced within one batch
                self.stats.drop(R_DUP)
                return
            if self.utxo_check is not None:
                sat = await self.utxo_check(scid)
                if sat is None:
                    self.stats.drop(R_NO_UTXO)
                    return
            self.channels[scid] = (p.node_id_1, p.node_id_2)
            self._channeled_nodes.update((p.node_id_1, p.node_id_2))
            self._accept(it)
            # drain pendings now satisfiable
            for q in self.pending_updates.pop(scid, {}).values():
                await self.submit(q.raw, q.source)
            for nid in (p.node_id_1, p.node_id_2):
                q = self.pending_nodes.pop(nid, None)
                if q is not None:
                    await self.submit(q.raw, q.source)
        elif kind == wire.MSG_CHANNEL_UPDATE:
            scid, d = p.short_channel_id, p.direction
            if self.updates.get((scid, d), -1) >= p.timestamp:
                self.stats.drop(R_STALE)   # raced within one batch
                return
            self.updates[(scid, d)] = p.timestamp
            self._accept(it)
        elif kind == wire.MSG_NODE_ANNOUNCEMENT:
            nid = p.node_id
            if nid not in self._channeled_nodes:
                prev = self.pending_nodes.get(nid)
                if prev is None or prev.parsed.timestamp < p.timestamp:
                    self.pending_nodes[nid] = it
                self.stats.drop(R_NO_CHANNEL)
                return
            if self.nodes.get(nid, -1) >= p.timestamp:
                self.stats.drop(R_STALE)
                return
            self.nodes[nid] = p.timestamp
            self._accept(it)

    def _accept(self, it: _QItem) -> None:
        """Stage for the per-flush store write (one append_many + fsync
        per batch, not per message)."""
        self._accepted.append(it)


    def _build_items(self, batch: list[_QItem]) -> gverify.VerifyItems:
        """Flatten queued messages into one VerifyItems workload: ONE
        hashed row per message, with row_of_item fanning the 4
        channel_announcement signatures onto their shared row (same
        layout as the store-replay extractor)."""
        regions: list[bytes] = []
        sigs: list[bytes] = []
        keys: list[bytes] = []
        midx: list[int] = []
        roi: list[int] = []
        for i, it in enumerate(batch):
            p = it.parsed
            row = len(regions)
            regions.append(p.signed_region())
            if it.kind == wire.MSG_CHANNEL_ANNOUNCEMENT:
                for sig, key in p.signature_tuples():
                    sigs.append(sig)
                    keys.append(key)
                    midx.append(i)
                    roi.append(row)
            elif it.kind == wire.MSG_CHANNEL_UPDATE:
                # _precheck guarantees the channel is known by now; the
                # signer is the channel endpoint for this direction, so
                # identity and signature are checked in one kernel pass.
                sigs.append(p.signature)
                keys.append(self.channels[p.short_channel_id][p.direction])
                midx.append(i)
                roi.append(row)
            else:  # node_announcement (self-signed)
                sigs.append(p.signature)
                keys.append(p.node_id)
                midx.append(i)
                roi.append(row)
        buf = np.frombuffer(b"".join(regions), np.uint8)
        lengths = np.array([len(r) for r in regions], np.int64)
        offsets = np.concatenate(
            [[0], np.cumsum(lengths)[:-1]]).astype(np.int64)
        rows, nb = native.sha256_pack(buf, offsets, lengths,
                                      gverify.MAX_BLOCKS)
        z_host = gverify._host_hash_oversized(buf, offsets, lengths, nb)
        return gverify.VerifyItems(
            rows, nb,
            np.frombuffer(b"".join(sigs), np.uint8).reshape(-1, 64),
            np.frombuffer(b"".join(k.ljust(33, b"\0") for k in keys),
                          np.uint8).reshape(-1, 33),
            np.array(midx, np.int64), z_host,
            np.array(roi, np.int64),
        )
