"""Autonomous gossip seeker: keep the network view current with no
operator action.

Parity target: gossipd/seeker.c:28-100 — a periodic state machine that
(1) full-syncs from a peer when starting up or provably far behind,
(2) otherwise probes random scid ranges against rotating peers to find
gaps, escalating to a full sync when a probe uncovers too many unknown
channels, (3) backs off exponentially while the view stays current, and
(4) prunes channels whose newest channel_update went stale (the
reference's 2-week prune, gossipd.c).

The wire work is delegated to Gossipd.sync_with (timestamp filter +
query_channel_range + query_short_channel_ids); the seeker only decides
WHEN, from WHOM, and WHAT RANGE.
"""
from __future__ import annotations

import asyncio
import logging
import random
import time

log = logging.getLogger("lightning_tpu.seeker")

# seeker.c cadence: startup sync immediately, then probe every minute,
# backing off ×2 (cap 8×) while nothing new turns up
PROBE_INTERVAL = 60.0
BACKOFF_CAP = 8
# a probe that uncovers this many unknown scids means we are behind
FULL_SYNC_THRESHOLD = 16
PROBE_BLOCKS = 2016          # one retarget period per gap probe
PRUNE_AGE = 14 * 24 * 3600   # BOLT#7 stale-channel prune


class Seeker:
    def __init__(self, gossipd, interval: float = PROBE_INTERVAL,
                 rng: random.Random | None = None,
                 clock=time.time):
        self.g = gossipd
        self.interval = interval
        self.rng = rng or random.Random()
        self.clock = clock
        self.state = "startup"
        self.backoff = 1
        self._rotation = 0
        self._task: asyncio.Task | None = None
        self.stats = {"ticks": 0, "full_syncs": 0, "probes": 0,
                      "found": 0, "pruned": 0}

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _run(self) -> None:
        while True:
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("seeker tick failed; continuing")
            await asyncio.sleep(self.interval * self.backoff)

    # -- the state machine ------------------------------------------------

    def _pick_peer(self):
        """Rotate through connected peers (seeker.c peer rotation: never
        keep asking the same peer, its view may be stale/partial)."""
        peers = [p for p in self.g.node.peers.values()
                 if getattr(p, "connected", False)]
        if not peers:
            return None
        peer = peers[self._rotation % len(peers)]
        self._rotation += 1
        return peer

    def _known_block_span(self) -> tuple[int, int]:
        scids = self.g.ingest.channels
        if not scids:
            return (0, 0)
        blocks = [s >> 40 for s in scids]
        return (min(blocks), max(blocks))

    async def tick(self) -> None:
        """One seeker step; factored out so tests drive it directly
        instead of sleeping through the cadence."""
        self.stats["ticks"] += 1
        peer = self._pick_peer()
        if peer is None:
            return
        if self.state == "startup":
            found = await self._full_sync(peer)
            self.state = "probing"
            self.backoff = 1 if found else 2
        else:
            found = await self._probe(peer)
            if found >= FULL_SYNC_THRESHOLD:
                # the gap was not an isolated miss: we are behind
                self.state = "startup"
                self.backoff = 1
            elif found:
                self.backoff = 1
            else:
                self.backoff = min(self.backoff * 2, BACKOFF_CAP)
        self.prune_stale()

    async def _ingested_delta(self, do_sync) -> int:
        """Run a sync and count channels that actually SURVIVED
        verification+ingest — sync_with's return is merely the number
        REQUESTED, which a peer advertising bogus scids could inflate
        forever (it would pin backoff at 1 and force a full sync every
        tick)."""
        before = len(self.g.ingest.channels)
        await do_sync()
        await self.g.ingest.drain()
        return max(0, len(self.g.ingest.channels) - before)

    async def _full_sync(self, peer) -> int:
        self.stats["full_syncs"] += 1
        try:
            n = await self._ingested_delta(
                lambda: self.g.sync_with(peer, timeout=30.0))
        except (asyncio.TimeoutError, ConnectionError) as e:
            log.info("full sync from %s failed: %s",
                     peer.node_id.hex()[:16], e)
            return 0
        self.stats["found"] += n
        log.info("seeker: full sync from %s found %d new channel(s)",
                 peer.node_id.hex()[:16], n)
        return n

    async def _probe(self, peer) -> int:
        """Ask one peer about a random block window and fetch unknown
        scids (seeker.c probe_some_random_scids role)."""
        self.stats["probes"] += 1
        lo, hi = self._known_block_span()
        span_end = max(hi + PROBE_BLOCKS, lo + PROBE_BLOCKS)
        first = self.rng.randrange(lo, span_end + 1) if span_end > lo \
            else lo
        try:
            n = await self._ingested_delta(
                lambda: self.g.sync_with(peer, first_blocknum=first,
                                         number_of_blocks=PROBE_BLOCKS,
                                         timeout=15.0))
        except (asyncio.TimeoutError, ConnectionError) as e:
            log.info("probe of %s failed: %s", peer.node_id.hex()[:16], e)
            return 0
        self.stats["found"] += n
        return n

    def prune_stale(self, now: float | None = None) -> int:
        """Drop channels whose NEWEST update is older than PRUNE_AGE
        (gossipd gossip_time-based prune).  Channels with no update at
        all are kept — their announcement may simply predate our first
        update sighting."""
        now = now if now is not None else self.clock()
        cutoff = now - PRUNE_AGE
        ing = self.g.ingest
        stale = []
        for scid in list(ing.channels):
            stamps = [ing.updates[k] for k in
                      ((scid, 0), (scid, 1)) if k in ing.updates]
            if stamps and max(stamps) < cutoff:
                stale.append(scid)
        for scid in stale:
            ing.channels.pop(scid, None)
            ing.updates.pop((scid, 0), None)
            ing.updates.pop((scid, 1), None)
            self.g.msgs.pop(scid, None)
        if stale:
            # durable: flip FLAG_DELETED in the store so a restart's
            # load_existing does not resurrect them, and compaction can
            # reclaim the bytes.  The flagging scans the WHOLE store
            # (mmap + per-record parse) — at the 1M-record scale that
            # is seconds of work, so it runs off the event loop.
            from . import store as gstore

            def _flag(path=ing.writer.path, scids=set(stale)):
                try:
                    gstore.mark_deleted(path, scids)
                except Exception:
                    log.exception("store prune flagging failed")

            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                _flag()               # sync caller (tests)
            else:
                t = loop.create_task(asyncio.to_thread(_flag))
                self._flag_tasks = getattr(self, "_flag_tasks", set())
                self._flag_tasks.add(t)
                t.add_done_callback(self._flag_tasks.discard)
            self.stats["pruned"] += len(stale)
            log.info("seeker: pruned %d stale channel(s)", len(stale))
        return len(stale)
