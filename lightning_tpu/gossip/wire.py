"""BOLT#7 gossip message codecs (channel_announcement / node_announcement /
channel_update), written from the public spec.

Functional parity targets in the reference: message layouts as generated
from wire/peer_wire.csv, and the signed-hash rule used by
gossipd/sigcheck.c:9-164 — every gossip signature covers
sha256d(message after its last signature field).

The parse/serialize here is the slow, per-message path (tests, tools,
single-message ingest).  The batch path used for store replay extracts
fields with vectorized gathers instead — see gossip/verify.py.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field

MSG_CHANNEL_ANNOUNCEMENT = 256
MSG_NODE_ANNOUNCEMENT = 257
MSG_CHANNEL_UPDATE = 258

# Regtest/mainnet chain hashes (block 0 hash, little-endian as used on the
# wire).  Mainnet genesis: 000000000019d6689c085ae165831e934ff763ae46a2a6c172b3f1b60a8ce26f
MAINNET_CHAIN_HASH = bytes.fromhex(
    "6fe28c0ab6f1b372c1a6a246ae63f74f931e8365e15a089c68d6190000000000"
)


@dataclass
class ChannelAnnouncement:
    node_signature_1: bytes = b"\x00" * 64
    node_signature_2: bytes = b"\x00" * 64
    bitcoin_signature_1: bytes = b"\x00" * 64
    bitcoin_signature_2: bytes = b"\x00" * 64
    features: bytes = b""
    chain_hash: bytes = MAINNET_CHAIN_HASH
    short_channel_id: int = 0
    node_id_1: bytes = b"\x02" + b"\x00" * 32
    node_id_2: bytes = b"\x02" + b"\x00" * 32
    bitcoin_key_1: bytes = b"\x02" + b"\x00" * 32
    bitcoin_key_2: bytes = b"\x02" + b"\x00" * 32

    TYPE = MSG_CHANNEL_ANNOUNCEMENT

    def serialize(self) -> bytes:
        return (
            struct.pack(">H", self.TYPE)
            + self.node_signature_1
            + self.node_signature_2
            + self.bitcoin_signature_1
            + self.bitcoin_signature_2
            + struct.pack(">H", len(self.features))
            + self.features
            + self.chain_hash
            + struct.pack(">Q", self.short_channel_id)
            + self.node_id_1
            + self.node_id_2
            + self.bitcoin_key_1
            + self.bitcoin_key_2
        )

    @classmethod
    def parse(cls, msg: bytes) -> "ChannelAnnouncement":
        (t,) = struct.unpack_from(">H", msg, 0)
        assert t == cls.TYPE
        sigs = [msg[2 + 64 * i : 2 + 64 * (i + 1)] for i in range(4)]
        (flen,) = struct.unpack_from(">H", msg, 258)
        o = 260
        features = msg[o : o + flen]
        o += flen
        chain_hash = msg[o : o + 32]
        o += 32
        (scid,) = struct.unpack_from(">Q", msg, o)
        o += 8
        keys = [msg[o + 33 * i : o + 33 * (i + 1)] for i in range(4)]
        return cls(*sigs, features, chain_hash, scid, *keys)

    def signed_region(self) -> bytes:
        """Everything after the last signature (spec: sigs cover
        sha256d of the remainder)."""
        return self.serialize()[258:]

    def signature_tuples(self):
        """[(sig, signer_pubkey)] in wire order."""
        return [
            (self.node_signature_1, self.node_id_1),
            (self.node_signature_2, self.node_id_2),
            (self.bitcoin_signature_1, self.bitcoin_key_1),
            (self.bitcoin_signature_2, self.bitcoin_key_2),
        ]


# Byte offsets of fixed-position fields inside a channel_announcement
# (valid for any features length for the sigs; key offsets add flen).
CA_SIG_OFFSETS = (2, 66, 130, 194)
CA_FLEN_OFFSET = 258
CA_SIGNED_OFFSET = 258  # signed region starts at the features length field


@dataclass
class NodeAnnouncement:
    signature: bytes = b"\x00" * 64
    features: bytes = b""
    timestamp: int = 0
    node_id: bytes = b"\x02" + b"\x00" * 32
    rgb_color: bytes = b"\x00\x00\x00"
    alias: bytes = b"\x00" * 32
    addresses: bytes = b""

    TYPE = MSG_NODE_ANNOUNCEMENT

    def serialize(self) -> bytes:
        return (
            struct.pack(">H", self.TYPE)
            + self.signature
            + struct.pack(">H", len(self.features))
            + self.features
            + struct.pack(">I", self.timestamp)
            + self.node_id
            + self.rgb_color
            + self.alias
            + struct.pack(">H", len(self.addresses))
            + self.addresses
        )

    @classmethod
    def parse(cls, msg: bytes) -> "NodeAnnouncement":
        (t,) = struct.unpack_from(">H", msg, 0)
        assert t == cls.TYPE
        sig = msg[2:66]
        (flen,) = struct.unpack_from(">H", msg, 66)
        o = 68
        features = msg[o : o + flen]
        o += flen
        (ts,) = struct.unpack_from(">I", msg, o)
        o += 4
        node_id = msg[o : o + 33]
        o += 33
        rgb = msg[o : o + 3]
        o += 3
        alias = msg[o : o + 32]
        o += 32
        (alen,) = struct.unpack_from(">H", msg, o)
        o += 2
        return cls(sig, features, ts, node_id, rgb, alias, msg[o : o + alen])

    def signed_region(self) -> bytes:
        return self.serialize()[66:]


NA_SIG_OFFSET = 2
NA_SIGNED_OFFSET = 66


@dataclass
class ChannelUpdate:
    signature: bytes = b"\x00" * 64
    chain_hash: bytes = MAINNET_CHAIN_HASH
    short_channel_id: int = 0
    timestamp: int = 0
    message_flags: int = 1  # bit0: htlc_maximum_msat present (always, today)
    channel_flags: int = 0  # bit0: direction, bit1: disabled
    cltv_expiry_delta: int = 6
    htlc_minimum_msat: int = 0
    fee_base_msat: int = 1000
    fee_proportional_millionths: int = 1
    htlc_maximum_msat: int = 0

    TYPE = MSG_CHANNEL_UPDATE

    def serialize(self) -> bytes:
        return (
            struct.pack(">H", self.TYPE)
            + self.signature
            + self.chain_hash
            + struct.pack(
                ">QIBBHQIIQ",
                self.short_channel_id,
                self.timestamp,
                self.message_flags,
                self.channel_flags,
                self.cltv_expiry_delta,
                self.htlc_minimum_msat,
                self.fee_base_msat,
                self.fee_proportional_millionths,
                self.htlc_maximum_msat,
            )
        )

    @classmethod
    def parse(cls, msg: bytes) -> "ChannelUpdate":
        (t,) = struct.unpack_from(">H", msg, 0)
        assert t == cls.TYPE
        sig = msg[2:66]
        chain_hash = msg[66:98]
        vals = struct.unpack_from(">QIBBHQIIQ", msg, 98)
        return cls(sig, chain_hash, *vals)

    @property
    def direction(self) -> int:
        return self.channel_flags & 1

    def signed_region(self) -> bytes:
        return self.serialize()[66:]


CU_SIG_OFFSET = 2
CU_SIGNED_OFFSET = 66
CU_SCID_OFFSET = 98
CU_FLAGS_OFFSET = 110  # message_flags, channel_flags


def msg_type(msg: bytes) -> int:
    (t,) = struct.unpack_from(">H", msg, 0)
    return t


def parse_gossip(msg: bytes):
    (t,) = struct.unpack_from(">H", msg, 0)
    if t == MSG_CHANNEL_ANNOUNCEMENT:
        return ChannelAnnouncement.parse(msg)
    if t == MSG_NODE_ANNOUNCEMENT:
        return NodeAnnouncement.parse(msg)
    if t == MSG_CHANNEL_UPDATE:
        return ChannelUpdate.parse(msg)
    raise ValueError(f"unknown gossip type {t}")
