"""Batched gossip-signature verification: the primary TPU offload.

The reference verifies each gossip message inline and serially as it is
processed (gossipd/sigcheck.c:45 sigcheck_channel_announcement does 4
ECDSA verifies per channel_announcement; :9 and :118 do one each for
channel_update / node_announcement; each preceded by a sha256d).  Here the
whole store (or any batch of messages) becomes flat arrays:

  host:   mmap store → native scan → vectorized field gathers
  device: fused sha256d + batched ECDSA verify (one jit program)

The fused kernel means message bytes are uploaded once and only booleans
come back — hashes never round-trip to the host.
"""
from __future__ import annotations

import functools
import time
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..crypto import field as F
from ..crypto import secp256k1 as S
from ..crypto import sha256 as H
from ..utils import native
from . import wire
from .store import StoreIndex

# Default verify bucket: fixed batch shape so one compiled program serves
# any store size (remainder padded with dummy always-False rows that are
# masked out host-side).  Overridable for big-batch TPU runs via
# LIGHTNING_TPU_VERIFY_BUCKET.
import os as _os

DEFAULT_BUCKET = int(_os.environ.get("LIGHTNING_TPU_VERIFY_BUCKET", str(S.VERIFY_BUCKET)))
MAX_BLOCKS = 8  # 512-byte signed regions cover all standard gossip msgs

# -- observability (doc/observability.md) ----------------------------------
_M_FLUSH_SECONDS = obs.histogram(
    "clntpu_verify_flush_seconds",
    "Wall time of one verify_items dispatch (hash + verify phases)")
_M_BATCH_SIGS = obs.histogram(
    "clntpu_verify_batch_sigs",
    "Signatures per verify_items call", buckets=obs.SIZE_BUCKETS)
_M_OCCUPANCY = obs.histogram(
    "clntpu_verify_batch_occupancy_ratio",
    "Real lanes / padded lanes per verify_items call "
    "(1.0 = no bucket padding waste)", buckets=obs.RATIO_BUCKETS)
_M_LANES = obs.counter(
    "clntpu_verify_lanes_total",
    "Device lanes dispatched (real + pad), by kind",
    labelnames=("kind",))
_M_DEVICE_BYTES = obs.counter(
    "clntpu_verify_device_bytes_total",
    "Host->device bytes staged for verify dispatches")
_M_OVERSIZED = obs.counter(
    "clntpu_verify_oversized_host_total",
    "Oversized rows (n_blocks == 0) verified on the host fallback path")
_M_COMPILE = obs.counter(
    "clntpu_verify_compile_events_total",
    "New program shapes compiled (warmup or live), by program",
    labelnames=("program",))

# every (program, shape) jax compiles exactly once per process; tracking
# first-sights here turns "did the live path hit a compile stall?" into
# a scrape (warmup pre-populates the expected shapes, so a LIVE
# increment means a flush paid a compile)
_seen_shapes: set = set()


def _note_shape(program: str, key: tuple) -> None:
    if (program, key) not in _seen_shapes:
        _seen_shapes.add((program, key))
        _M_COMPILE.labels(program).inc()


def gossip_hash_kernel(blocks, n_blocks):
    """sha256d(signed region) → z limbs.  Kept as a separate jit program
    from the EC verify: one fused program is beyond what XLA:CPU compiles
    in reasonable time.  The digest handoff to the verify phase is
    device-resident (verify_items concatenates the padded z buckets on
    device and S._jit_gather_rows gathers rows device-side)."""
    digest = H.sha256d_blocks(blocks, n_blocks)
    return H.digest_words_to_limbs(digest)


@functools.lru_cache(maxsize=2)
def _jit_hash():
    return jax.jit(gossip_hash_kernel)


def warmup(bucket: int = DEFAULT_BUCKET) -> None:
    """Compile (or load from the persistent cache) the hash + verify
    programs at the given bucket, off the live path.  A cold XLA:CPU
    compile of the EC verify program takes minutes; a daemon that
    first compiles it inside a live flush stalls gossip acceptance far
    past peer/test timeouts (found via test_gossip_origination on a
    fresh cache).  Call from startup — idempotent and cheap once the
    jit caches are warm.

    Residual per-K compile: the z-row gather's operand shape scales
    with K = ceil(M / bucket) hash buckets, so each distinct K compiles
    its own (tiny, sub-second) gather program on first sight.  We warm
    K=1 and K=2 here (single- and multi-bucket flushes); a live flush
    with K > 2 still pays one small gather compile, surfaced by the
    ``clntpu_verify_compile_events_total{program="gather"}`` counter —
    a LIVE increment after warmup means a flush hit a compile stall."""
    blocks = jnp.zeros((bucket, MAX_BLOCKS, 16), jnp.uint32)
    nb = jnp.ones((bucket,), jnp.int32)
    _note_shape("hash", (bucket, MAX_BLOCKS))
    z = _jit_hash()(blocks, nb)
    _note_shape("hash", (bucket, 4))
    _jit_hash()(blocks[:, :4], nb)   # the quantized small-row shape
    idx = jnp.zeros((bucket,), jnp.int32)
    _note_shape("gather", (int(z.shape[0]), bucket))
    z = S._jit_gather_rows()(z, idx)
    # multi-bucket flushes (M > bucket) gather from a K·bucket z plane;
    # warm the K=2 shape so the first such live flush doesn't compile
    z2 = jnp.concatenate([z, z])
    _note_shape("gather", (int(z2.shape[0]), bucket))
    S._jit_gather_rows()(z2, idx)
    sigs = jnp.zeros((bucket, 64), jnp.uint8)
    pubs = jnp.zeros((bucket, 33), jnp.uint8)
    _note_shape("verify", (bucket,))
    np.asarray(S._jit_verify_from_bytes()(z, sigs, pubs))


def _bytes_to_blocks(rows: np.ndarray, max_blocks: int) -> np.ndarray:
    """(B, max_blocks*64) uint8 → (B, max_blocks, 16) uint32 big-endian."""
    B = rows.shape[0]
    w = rows.reshape(B, max_blocks, 16, 4).astype(np.uint32)
    return (w[..., 0] << 24) | (w[..., 1] << 16) | (w[..., 2] << 8) | w[..., 3]


@dataclass
class VerifyItems:
    """One flat signature-check workload (possibly many sigs per message).

    ``rows``/``n_blocks``/``z_host`` are per unique MESSAGE (M rows);
    sigs/pubkeys are per SIGNATURE (N items).  ``row_of_item`` maps each
    signature to its message row — None means 1:1 (M == N).  Hashing per
    unique row instead of per signature matters: channel_announcements
    carry 4 signatures over ONE signed region, so the per-item layout
    hashed (and uploaded) every CA region 4×."""

    rows: np.ndarray  # (M, MAX_BLOCKS*64) uint8 pre-padded signed regions
    n_blocks: np.ndarray  # (M,) uint32; 0 = oversized, hashed host-side
    sigs: np.ndarray  # (N, 64) uint8
    pubkeys: np.ndarray  # (N, 33) uint8
    msg_index: np.ndarray  # (N,) int64 — row in the originating batch
    z_host: np.ndarray | None = None  # (M, 32) host sha256d where n_blocks==0
    row_of_item: np.ndarray | None = None  # (N,) int64; None = identity

    @staticmethod
    def concat(items: list["VerifyItems"]) -> "VerifyItems":
        if any(x.z_host is not None for x in items):
            zh = np.concatenate([
                x.z_host if x.z_host is not None
                else np.zeros((x.rows.shape[0], 32), np.uint8)
                for x in items
            ])
        else:
            zh = None
        rois, base = [], 0
        for x in items:
            roi = (np.arange(len(x), dtype=np.int64)
                   if x.row_of_item is None else x.row_of_item)
            rois.append(roi + base)
            base += x.rows.shape[0]
        return VerifyItems(
            np.concatenate([x.rows for x in items]),
            np.concatenate([x.n_blocks for x in items]),
            np.concatenate([x.sigs for x in items]),
            np.concatenate([x.pubkeys for x in items]),
            np.concatenate([x.msg_index for x in items]),
            zh,
            np.concatenate(rois),
        )

    def __len__(self):
        return len(self.sigs)


def _host_hash_oversized(buf: np.ndarray, offsets: np.ndarray,
                         lengths: np.ndarray, nb: np.ndarray):
    """sha256d for rows the packer flagged oversized (n_blocks == 0).
    Rare (long node_announcements) — returns None when there are none, so
    the common-case 1M-record replay allocates nothing here."""
    import hashlib

    which = np.nonzero(nb == 0)[0]
    if len(which) == 0:
        return None
    z = np.zeros((len(nb), 32), np.uint8)
    for i in which:
        o, l = int(offsets[i]), int(lengths[i])
        d = hashlib.sha256(
            hashlib.sha256(buf[o : o + l].tobytes()).digest()
        ).digest()
        z[i] = np.frombuffer(d, np.uint8)
    return z


def extract_channel_announcements(idx: StoreIndex) -> VerifyItems:
    """4 (sig, key) pairs per channel_announcement (sigcheck.c:45-113)."""
    n = len(idx)
    if n == 0:
        return _empty_items()
    off = idx.offsets
    sr_off = off + wire.CA_SIGNED_OFFSET
    sr_len = idx.lengths - wire.CA_SIGNED_OFFSET
    rows, nb = native.sha256_pack(idx.buf, sr_off, sr_len, MAX_BLOCKS)
    z_host = _host_hash_oversized(idx.buf, sr_off, sr_len, nb)
    flen_raw = native.gather_fields(idx.buf, off, wire.CA_FLEN_OFFSET, 2)
    flen = (flen_raw[:, 0].astype(np.uint64) << 8) | flen_raw[:, 1]
    key_base = wire.CA_FLEN_OFFSET + 2 + flen + 32 + 8
    sigs, keys = [], []
    for i, sig_off in enumerate(wire.CA_SIG_OFFSETS):
        sigs.append(native.gather_fields(idx.buf, off, sig_off, 64))
        keys.append(native.gather_fields(idx.buf, off + key_base, 33 * i, 33))
    # rows stay per-MESSAGE: the 4 signatures share one signed region,
    # and row_of_item maps them back — tiling the 512-byte rows 4× made
    # the hash phase (and its upload) 4× bigger for nothing
    return VerifyItems(
        rows,
        nb,
        np.concatenate(sigs),
        np.concatenate(keys),
        np.tile(np.arange(n, dtype=np.int64), 4),
        z_host,
        np.tile(np.arange(n, dtype=np.int64), 4),
    )


def extract_node_announcements(idx: StoreIndex) -> VerifyItems:
    n = len(idx)
    if n == 0:
        return _empty_items()
    off = idx.offsets
    sr_off = off + wire.NA_SIGNED_OFFSET
    sr_len = idx.lengths - wire.NA_SIGNED_OFFSET
    rows, nb = native.sha256_pack(idx.buf, sr_off, sr_len, MAX_BLOCKS)
    z_host = _host_hash_oversized(idx.buf, sr_off, sr_len, nb)
    flen_raw = native.gather_fields(idx.buf, off, 66, 2)
    flen = (flen_raw[:, 0].astype(np.uint64) << 8) | flen_raw[:, 1]
    sigs = native.gather_fields(idx.buf, off, wire.NA_SIG_OFFSET, 64)
    keys = native.gather_fields(idx.buf, off + flen, 68 + 4, 33)
    return VerifyItems(rows, nb, sigs, keys, np.arange(n, dtype=np.int64),
                       z_host)


def extract_channel_updates(idx: StoreIndex, scid_to_nodes) -> VerifyItems:
    """channel_update is signed by the direction-selected channel node
    (sigcheck.c:9-43); scid_to_nodes maps scid → (node_id_1, node_id_2)."""
    n = len(idx)
    if n == 0:
        return _empty_items()
    off = idx.offsets
    sr_off = off + wire.CU_SIGNED_OFFSET
    sr_len = idx.lengths - wire.CU_SIGNED_OFFSET
    rows, nb = native.sha256_pack(idx.buf, sr_off, sr_len, MAX_BLOCKS)
    z_host = _host_hash_oversized(idx.buf, sr_off, sr_len, nb)
    sigs = native.gather_fields(idx.buf, off, wire.CU_SIG_OFFSET, 64)
    scid_raw = native.gather_fields(idx.buf, off, wire.CU_SCID_OFFSET, 8)
    scids = scid_raw.astype(np.uint64)
    scid = np.zeros(n, np.uint64)
    for b in range(8):
        scid = (scid << np.uint64(8)) | scids[:, b]
    chan_flags = native.gather_fields(idx.buf, off, wire.CU_FLAGS_OFFSET + 1, 1)[:, 0]
    direction = chan_flags & 1
    keys = scid_to_nodes(scid, direction)  # (n, 33) uint8
    return VerifyItems(rows, nb, sigs, keys, np.arange(n, dtype=np.int64),
                       z_host)


def _empty_items() -> VerifyItems:
    return VerifyItems(
        np.zeros((0, MAX_BLOCKS * 64), np.uint8), np.zeros(0, np.uint32),
        np.zeros((0, 64), np.uint8), np.zeros((0, 33), np.uint8),
        np.zeros(0, np.int64),
    )


def make_scid_map(ca_idx: StoreIndex):
    """Vectorized scid → (node_id_1 | node_id_2) resolver built from the
    channel_announcement batch (sorted array + searchsorted)."""
    n = len(ca_idx)
    if n == 0:
        # no announcements: every update resolves to the zero key, which
        # fails verification (as it must)
        return lambda scids, direction: np.zeros((len(scids), 33), np.uint8)
    off = ca_idx.offsets
    flen_raw = native.gather_fields(ca_idx.buf, off, wire.CA_FLEN_OFFSET, 2)
    flen = (flen_raw[:, 0].astype(np.uint64) << 8) | flen_raw[:, 1]
    scid_raw = native.gather_fields(
        ca_idx.buf, off + flen, wire.CA_FLEN_OFFSET + 2 + 32, 8
    ).astype(np.uint64)
    scid = np.zeros(n, np.uint64)
    for b in range(8):
        scid = (scid << np.uint64(8)) | scid_raw[:, b]
    key_base = wire.CA_FLEN_OFFSET + 2 + flen + 40
    node1 = native.gather_fields(ca_idx.buf, off + key_base, 0, 33)
    node2 = native.gather_fields(ca_idx.buf, off + key_base, 33, 33)
    order = np.argsort(scid, kind="stable")
    scid_sorted = scid[order]
    node1s, node2s = node1[order], node2[order]

    def lookup(scids: np.ndarray, direction: np.ndarray) -> np.ndarray:
        pos = np.searchsorted(scid_sorted, scids)
        pos_c = np.clip(pos, 0, max(0, n - 1))
        found = (pos < n) & (scid_sorted[pos_c] == scids) if n else np.zeros(len(scids), bool)
        keys = np.where(
            (direction == 0)[:, None], node1s[pos_c], node2s[pos_c]
        )
        # unknown scid → zero key (fails verification, as it must)
        keys[~found] = 0
        return keys

    return lookup


def verify_items(items: VerifyItems, bucket: int = DEFAULT_BUCKET) -> np.ndarray:
    """Two bucketed device phases with a DEVICE-RESIDENT handoff:
    sha256d per unique MESSAGE row, then ECDSA verify per SIGNATURE
    with the hash gathered by row_of_item ON DEVICE
    (S._jit_gather_rows) and sig/pubkey bytes unpacked on-device.

    The z plane never visits the host: each padded hash bucket covers
    rows [k·bucket, (k+1)·bucket), so concatenating the padded outputs
    preserves global row indices and the verify phase gathers straight
    from the concatenated device array (S._jit_gather_rows — a separate
    tiny program so the shape-static EC program never recompiles).  The
    whole replay is therefore one enqueue stream with a SINGLE readback
    at the end — the previous z readback + re-upload between the phases
    was a full sync point and ~30% of the measured 25k-store e2e wall
    clock.  Oversized rows (n_blocks == 0, hashed host-side at
    extraction) are re-checked on the host afterward.
    Returns bool (N,)."""
    N = len(items)
    if N == 0:
        return np.zeros(0, bool)
    t_start = time.perf_counter()
    roi = items.row_of_item
    if roi is None:
        roi = np.arange(N, dtype=np.int64)
    M = items.rows.shape[0]
    tag_ok = (items.pubkeys[:, 0] == 2) | (items.pubkeys[:, 0] == 3)

    # --- hash phase (per unique row); z stays on device
    zs = []
    staged_bytes = 0
    for start in range(0, M, bucket):
        end = min(start + bucket, M)
        sl = slice(start, end)
        # rows arrive type-sorted (CA | NA | CU), so most buckets need
        # far fewer SHA blocks than the 8-block pad: channel_updates
        # fit in 3, node_announcements usually in 4.  Slicing the block
        # axis per bucket halves the host→device bytes for those
        # buckets; quantizing to {4, MAX_BLOCKS} bounds the number of
        # hash-program shapes at two (both precompiled by warmup).
        mb = int(items.n_blocks[sl].max(initial=0))
        mb = 4 if 0 < mb <= 4 else MAX_BLOCKS
        blocks = _bytes_to_blocks(
            S._pad_rows(items.rows[sl], bucket)[:, :mb * 64], mb)
        _note_shape("hash", (bucket, mb))
        staged_bytes += blocks.nbytes + bucket * 4
        zs.append(_jit_hash()(
            jnp.asarray(blocks),
            jnp.asarray(S._pad_rows(items.n_blocks[sl],
                                    bucket).astype(np.int32)),
        ))
    z_rows = zs[0] if len(zs) == 1 else jnp.concatenate(zs)

    # --- verify phase (per signature), z gathered device-side
    out = np.zeros(N, bool)
    gather = S._jit_gather_rows()
    kern = S._jit_verify_from_bytes()
    _note_shape("gather", (int(z_rows.shape[0]), bucket))
    _note_shape("verify", (bucket,))
    pending = []
    for start in range(0, N, bucket):
        end = min(start + bucket, N)
        sl = slice(start, end)
        z = gather(z_rows,
                   jnp.asarray(S._pad_rows(roi[sl].astype(np.int32),
                                           bucket)))
        ok = kern(
            z,
            jnp.asarray(S._pad_rows(items.sigs[sl], bucket)),
            jnp.asarray(S._pad_rows(items.pubkeys[sl], bucket)),
        )
        staged_bytes += bucket * (4 + 64 + 33)
        pending.append((sl, end - start, ok))
    for sl, n_real, ok in pending:
        out[sl] = np.asarray(ok)[:n_real]

    # oversized rows: the device hashed garbage for them; their host
    # sha256d was computed at extraction — verify those few serially.
    # A builder that marks rows oversized MUST supply z_host, or valid
    # signatures would silently verify as False off the garbage hash.
    # An explicit raise, not assert: the contract must survive
    # `python -O` (stripped asserts made this fail as an incidental
    # TypeError on the None subscript).
    ovs = items.n_blocks[roi] == 0
    if ovs.any():
        if items.z_host is None:
            raise ValueError(
                "oversized rows (n_blocks == 0) require z_host")
        _M_OVERSIZED.inc(int(ovs.sum()))
        out[ovs] = S._host_verify(items.z_host[roi[ovs]],
                                  items.sigs[ovs], items.pubkeys[ovs])

    verify_lanes = ((N + bucket - 1) // bucket) * bucket
    hash_lanes = ((M + bucket - 1) // bucket) * bucket
    _M_BATCH_SIGS.observe(N)
    _M_OCCUPANCY.observe(N / verify_lanes)
    _M_LANES.labels("verify").inc(verify_lanes)
    _M_LANES.labels("hash").inc(hash_lanes)
    _M_DEVICE_BYTES.inc(staged_bytes)
    _M_FLUSH_SECONDS.observe(time.perf_counter() - t_start)
    return out & tag_ok


@dataclass
class StoreVerifyResult:
    n_records: int
    n_sigs: int
    ca_valid: np.ndarray  # per channel_announcement (all 4 sigs)
    cu_valid: np.ndarray
    na_valid: np.ndarray


def verify_store(idx: StoreIndex, bucket: int = DEFAULT_BUCKET) -> StoreVerifyResult:
    """Replay-verify a full store: every signature on every alive gossip
    message (the reference's store *load* skips re-verification; its
    *ingest* path verifies serially — this is the ingest cost model run at
    load scale, the BASELINE.md target workload)."""
    from ..utils import trace

    alive = idx.select(idx.alive())
    ca = alive.select(alive.types == wire.MSG_CHANNEL_ANNOUNCEMENT)
    na = alive.select(alive.types == wire.MSG_NODE_ANNOUNCEMENT)
    cu = alive.select(alive.types == wire.MSG_CHANNEL_UPDATE)
    with trace.span("gossip/extract", records=int(len(alive.types))):
        items_ca = extract_channel_announcements(ca)
        items_na = extract_node_announcements(na)
        items_cu = extract_channel_updates(cu, make_scid_map(ca))
        all_items = VerifyItems.concat([items_ca, items_na, items_cu])
    with trace.span("gossip/verify", sigs=int(len(all_items.sigs))):
        ok = verify_items(all_items, bucket)
    n_ca, n_na, n_cu = len(items_ca), len(items_na), len(items_cu)
    ca_ok = ok[:n_ca].reshape(4, -1).all(axis=0) if n_ca else np.zeros(0, bool)
    na_ok = ok[n_ca : n_ca + n_na]
    cu_ok = ok[n_ca + n_na :]
    return StoreVerifyResult(
        n_records=len(alive), n_sigs=len(all_items),
        ca_valid=ca_ok, cu_valid=cu_ok, na_valid=na_ok,
    )
