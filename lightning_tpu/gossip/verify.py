"""Batched gossip-signature verification: the primary TPU offload.

The reference verifies each gossip message inline and serially as it is
processed (gossipd/sigcheck.c:45 sigcheck_channel_announcement does 4
ECDSA verifies per channel_announcement; :9 and :118 do one each for
channel_update / node_announcement; each preceded by a sha256d).  Here the
whole store (or any batch of messages) becomes flat arrays:

  host:   mmap store → native scan → vectorized field gathers
  device: fused sha256d + z-gather + batched ECDSA verify
          (ONE jit program per bucket)

The replay is a streaming bucket pipeline (doc/replay_pipeline.md):
signatures are sorted by message row and cut into self-contained
buckets (a bucket's signatures reference only the bucket's own rows),
so each bucket is one fused device dispatch with no inter-bucket data
flow.  Host-side bucket prep (extraction slice, byte→block pack, pad)
runs on a producer thread ahead of the dispatch loop — while bucket i
verifies on device, bucket i+1 is being packed — and the only
device→host transfer of the whole replay is the final boolean
readback.  With >1 device the EC stage routes through the
parallel/mesh.py batch sharding (sharded_verify_fn).
"""
from __future__ import annotations

import functools
import logging
import queue as _queue
import threading
import time
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..crypto import field as F
from ..crypto import secp256k1 as S
from ..crypto import sha256 as H
from ..obs import attribution as _attr
from ..obs import families as _families
from ..obs import flight as _flight
from ..resilience import breaker as _breaker
from ..resilience import deadline as _deadline
from ..resilience import faultinject as _fault
from ..resilience import quarantine as _quarantine
from ..utils import native, trace
from . import wire
from .store import StoreIndex

log = logging.getLogger("lightning_tpu.gossip.verify")

# Default verify bucket: fixed batch shape so one compiled program serves
# any store size (remainder padded with dummy always-False rows that are
# masked out host-side).  Overridable for big-batch TPU runs via
# LIGHTNING_TPU_VERIFY_BUCKET.
import os as _os

DEFAULT_BUCKET = int(_os.environ.get("LIGHTNING_TPU_VERIFY_BUCKET", str(S.VERIFY_BUCKET)))
MAX_BLOCKS = 8  # 512-byte signed regions cover all standard gossip msgs

# -- observability (doc/observability.md) ----------------------------------
_M_FLUSH_SECONDS = obs.histogram(
    "clntpu_verify_flush_seconds",
    "End-to-end wall time of one verify_items replay (plan + stream + "
    "readback + host fallback)")
_M_BATCH_SIGS = obs.histogram(
    "clntpu_verify_batch_sigs",
    "Signatures per verify_items call", buckets=obs.SIZE_BUCKETS)
_M_OCCUPANCY = obs.histogram(
    "clntpu_verify_batch_occupancy_ratio",
    "Real lanes / padded lanes per verify_items call "
    "(1.0 = no bucket padding waste)", buckets=obs.RATIO_BUCKETS)
_M_LANES = obs.counter(
    "clntpu_verify_lanes_total",
    "Device lanes dispatched (real + pad), by kind",
    labelnames=("kind",))
_M_DEVICE_BYTES = obs.counter(
    "clntpu_verify_device_bytes_total",
    "Host->device bytes staged for verify dispatches")
_M_OVERSIZED = obs.counter(
    "clntpu_verify_oversized_host_total",
    "Oversized rows (n_blocks == 0) verified on the host fallback path")
_M_COMPILE = obs.counter(
    "clntpu_verify_compile_events_total",
    "New program shapes compiled (warmup or live), by program",
    labelnames=("program",))

# -- streaming-replay pipeline stages (doc/replay_pipeline.md) -------------
# Vocabulary: "prep" is host bucket build (extraction slice + byte→block
# pack + pad), "stall" is the slice of prep that was VISIBLE on the
# dispatch thread's critical path (waiting on the prepared-bucket queue;
# in serial mode stall == prep by definition), "dispatch" is upload +
# program enqueue, "readback" is the single end-of-replay block on the
# device booleans.  overlap_ratio = 1 - stall/prep: the fraction of host
# prep wall time hidden behind device compute.  Families are DECLARED
# in obs/families.py (jax-free) so the attribution model and capture
# tools see them without this module's crypto-stack import.
_M_R_PREP = _families.REPLAY_PREP
_M_R_STALL = _families.REPLAY_STALL
_M_R_DISPATCH = _families.REPLAY_DISPATCH
_M_R_READBACK = _families.REPLAY_READBACK
_M_R_OVERLAP = _families.REPLAY_OVERLAP
_M_R_QDEPTH = _families.REPLAY_QDEPTH
_M_R_BUCKETS = _families.REPLAY_BUCKETS
_M_TRANSFER = _families.TRANSFER_BYTES

# every (program, shape) jax compiles exactly once per process; tracking
# first-sights here turns "did the live path hit a compile stall?" into
# a scrape (warmup pre-populates the expected shapes, so a LIVE
# increment means a flush paid a compile)
_seen_shapes: set = set()


def _note_shape(program: str, key: tuple) -> None:
    if (program, key) not in _seen_shapes:
        _seen_shapes.add((program, key))
        _M_COMPILE.labels(program).inc()
        # the retrace detector (obs/attribution.py): once warmup() has
        # completed, a first-sight here means a LIVE flush paid a
        # compile — clntpu_retrace_total fires + the `retrace` topic
        _attr.note_program(program, key)


def gossip_hash_kernel(blocks, n_blocks):
    """sha256d(signed region) → z limbs.  Still a standalone jit program
    for the unfused fallback path (LIGHTNING_TPU_REPLAY_FUSED=0), the
    mesh hash stage, and bench isolation; the default replay path runs
    the fused bucket program below instead."""
    digest = H.sha256d_blocks(blocks, n_blocks)
    return H.digest_words_to_limbs(digest)


@functools.lru_cache(maxsize=2)
def _jit_hash():
    return jax.jit(gossip_hash_kernel)


def fused_verify_kernel(blocks, n_blocks, roi, sig_bytes, pub_bytes,
                        dual_mul_impl=None, prep_impl=None):
    """ONE device program per bucket: sha256d(signed regions) → z-row
    gather by local row index → byte→limb unpack → batched ECDSA verify.

    Replaces the previous 3-program chain (_jit_hash → _jit_gather_rows
    → _jit_verify_from_bytes) on the default path.  Fusing became
    possible once buckets were made self-contained (a bucket's
    signatures reference only the bucket's own rows, so the gather's
    operand shape is the static (bucket, NLIMBS) — the old chain kept
    the gather separate precisely because its z plane scaled with the
    GLOBAL hash-bucket count K and would have recompiled the
    multi-minute EC program per K).  A cold XLA:CPU compile of this
    program takes ~4 min at full opt — warmup() covers both quantized
    block widths, and the persistent cache serves every later process.
    """
    z_rows = H.digest_words_to_limbs(H.sha256d_blocks(blocks, n_blocks))
    z = jnp.take(z_rows, roi, axis=0)
    r = F.from_bytes_be_dev(sig_bytes[:, :32])
    s = F.from_bytes_be_dev(sig_bytes[:, 32:])
    qx = F.from_bytes_be_dev(pub_bytes[:, 1:])
    parity = (pub_bytes[:, 0] & 1).astype(jnp.uint32)
    return S.ecdsa_verify_kernel(z, r, s, qx, parity,
                                 dual_mul_impl=dual_mul_impl,
                                 prep_impl=prep_impl)


@functools.lru_cache(maxsize=8)
def _jit_fused_resolved(impl_name: str, prep_name: str, donate: bool):
    impl = S.resolve_dual_mul(impl_name)
    prep = S.resolve_prep(prep_name)
    kern = functools.partial(fused_verify_kernel,
                             dual_mul_impl=impl, prep_impl=prep)
    # donate the big upload buffers (blocks/sigs/pubs) so the device
    # runtime can reuse their memory inside the program; donation is a
    # no-op (plus a per-call warning) on the CPU backend, so only ask
    # for it where it does something
    return jax.jit(kern, donate_argnums=(0, 3, 4) if donate else ())


def _jit_fused():
    donate = jax.default_backend() not in ("cpu",)
    return _jit_fused_resolved(*S._resolve_engine_names(None, None), donate)


def warmup(bucket: int = DEFAULT_BUCKET) -> None:
    """Compile (or load from the persistent cache) the replay programs
    at the given bucket, off the live path.  A cold XLA:CPU compile of
    an EC program takes minutes; a daemon that first compiles one
    inside a live flush stalls gossip acceptance far past peer/test
    timeouts (found via test_gossip_origination on a fresh cache).
    Call from startup — idempotent and cheap once the jit caches are
    warm.

    The default path needs exactly TWO programs per bucket: the fused
    sha256d+gather+verify program at both quantized SHA block widths
    (the bucket planner guarantees those are the only live shapes).
    The unfused 3-program chain is warmed only when the fallback is
    selected (LIGHTNING_TPU_REPLAY_FUSED=0) — eagerly tracing programs
    the process will never dispatch costs seconds per warmup call.

    Runs inside attribution.warmup_scope(): the shapes compiled here
    are EXPECTED first-sights, and the scope's exit arms the retrace
    detector — any program-shape first-sight after this call is a live
    compile stall and fires clntpu_retrace_total (doc/perf.md)."""
    with _attr.warmup_scope():
        _warmup_inner(bucket)


def _warmup_inner(bucket: int) -> None:
    nb = jnp.ones((bucket,), jnp.int32)
    idx = jnp.zeros((bucket,), jnp.int32)
    fused_on = _os.environ.get("LIGHTNING_TPU_REPLAY_FUSED", "1") != "0"
    if fused_on:
        for mb in (4, MAX_BLOCKS):
            _note_shape("fused", (bucket, mb))
            # fresh operand arrays EVERY call: the production program
            # donates blocks/sigs/pubs on accelerators, so a reused
            # array would be a deleted buffer on the second iteration
            np.asarray(_jit_fused()(
                jnp.zeros((bucket, mb, 16), jnp.uint32), nb, idx,
                jnp.zeros((bucket, 64), jnp.uint8),
                jnp.zeros((bucket, 33), jnp.uint8)))
    else:
        # the fallback 3-program chain — selected precisely to AVOID
        # the fused program's compile, so don't warm the fused one
        blocks = jnp.zeros((bucket, MAX_BLOCKS, 16), jnp.uint32)
        _note_shape("hash", (bucket, MAX_BLOCKS))
        z = _jit_hash()(blocks, nb)
        _note_shape("hash", (bucket, 4))
        _jit_hash()(blocks[:, :4], nb)   # the quantized small-row shape
        _note_shape("gather", (int(z.shape[0]), bucket))
        z = S._jit_gather_rows()(z, idx)
        # multi-bucket flushes (M > bucket) gather from a K·bucket z
        # plane; warm K=2 so the first such live flush doesn't compile
        z2 = jnp.concatenate([z, z])
        _note_shape("gather", (int(z2.shape[0]), bucket))
        S._jit_gather_rows()(z2, idx)
        _note_shape("verify", (bucket,))
        np.asarray(S._jit_verify_from_bytes()(
            z, jnp.zeros((bucket, 64), jnp.uint8),
            jnp.zeros((bucket, 33), jnp.uint8)))
    # if flushes would route through the mesh (>1 usable device and not
    # opted out), warm THAT path's programs too — hash at both widths,
    # the local z gather, and the sharded EC program — by pushing dummy
    # prepared buckets through the real dispatcher (metrics suppressed:
    # warmup buckets are not replay dispatches); otherwise the first
    # multi-device flush pays the multi-minute cold compile this
    # function exists to keep off the live path.  The unfused fallback
    # never reaches the mesh (verify_items routes it first), so skip.
    if (fused_on
            and _os.environ.get("LIGHTNING_TPU_MESH_VERIFY", "auto")
            != "off"):
        mesh_fn = _mesh_device_fn(bucket, count_metrics=False)
        if mesh_fn is not None:
            for mb in (4, MAX_BLOCKS):
                np.asarray(mesh_fn(_PreparedBucket(
                    sel=np.arange(bucket), n_real=bucket, mb=mb,
                    blocks=np.zeros((bucket, mb, 16), np.uint32),
                    n_blocks=np.ones(bucket, np.int32),
                    roi_local=np.zeros(bucket, np.int32),
                    sigs=np.zeros((bucket, 64), np.uint8),
                    pubkeys=np.zeros((bucket, 33), np.uint8),
                    staged_bytes=0, prep_seconds=0.0)))
    # the hsmd batched-sign path (sign_htlc_batch / sign_withdrawal)
    # shares the startup warmup: one grinding-sign compile per process
    # at the production SIGN_BUCKET, so a channel's first commitment
    # fan-out never pays a cold EC compile mid-dance
    one = F.int_to_limbs(1).astype(np.uint32)
    zb = np.tile(one, (S.SIGN_BUCKET, 1))
    kb = np.tile(one, (S.SIGN_BUCKET, S.GRIND_CANDIDATES, 1))
    _note_shape("sign", (S.SIGN_BUCKET,))
    np.asarray(S._jit_sign()(
        jnp.asarray(zb), jnp.asarray(zb), jnp.asarray(kb))[0])


def _bytes_to_blocks(rows: np.ndarray, max_blocks: int) -> np.ndarray:
    """(B, max_blocks*64) uint8 → (B, max_blocks, 16) uint32 big-endian."""
    B = rows.shape[0]
    w = rows.reshape(B, max_blocks, 16, 4).astype(np.uint32)
    return (w[..., 0] << 24) | (w[..., 1] << 16) | (w[..., 2] << 8) | w[..., 3]


@dataclass
class VerifyItems:
    """One flat signature-check workload (possibly many sigs per message).

    ``rows``/``n_blocks``/``z_host`` are per unique MESSAGE (M rows);
    sigs/pubkeys are per SIGNATURE (N items).  ``row_of_item`` maps each
    signature to its message row — None means 1:1 (M == N).  Hashing per
    unique row instead of per signature matters: channel_announcements
    carry 4 signatures over ONE signed region, so the per-item layout
    hashed (and uploaded) every CA region 4×."""

    rows: np.ndarray  # (M, MAX_BLOCKS*64) uint8 pre-padded signed regions
    n_blocks: np.ndarray  # (M,) uint32; 0 = oversized, hashed host-side
    sigs: np.ndarray  # (N, 64) uint8
    pubkeys: np.ndarray  # (N, 33) uint8
    msg_index: np.ndarray  # (N,) int64 — row in the originating batch
    z_host: np.ndarray | None = None  # (M, 32) host sha256d where n_blocks==0
    row_of_item: np.ndarray | None = None  # (N,) int64; None = identity

    @staticmethod
    def concat(items: list["VerifyItems"]) -> "VerifyItems":
        if any(x.z_host is not None for x in items):
            zh = np.concatenate([
                x.z_host if x.z_host is not None
                else np.zeros((x.rows.shape[0], 32), np.uint8)
                for x in items
            ])
        else:
            zh = None
        rois, base = [], 0
        for x in items:
            roi = (np.arange(len(x), dtype=np.int64)
                   if x.row_of_item is None else x.row_of_item)
            rois.append(roi + base)
            base += x.rows.shape[0]
        return VerifyItems(
            np.concatenate([x.rows for x in items]),
            np.concatenate([x.n_blocks for x in items]),
            np.concatenate([x.sigs for x in items]),
            np.concatenate([x.pubkeys for x in items]),
            np.concatenate([x.msg_index for x in items]),
            zh,
            np.concatenate(rois),
        )

    def __len__(self):
        return len(self.sigs)


def _host_hash_oversized(buf: np.ndarray, offsets: np.ndarray,
                         lengths: np.ndarray, nb: np.ndarray):
    """sha256d for rows the packer flagged oversized (n_blocks == 0).
    Rare (long node_announcements) — returns None when there are none, so
    the common-case 1M-record replay allocates nothing here."""
    import hashlib

    which = np.nonzero(nb == 0)[0]
    if len(which) == 0:
        return None
    z = np.zeros((len(nb), 32), np.uint8)
    for i in which:
        o, l = int(offsets[i]), int(lengths[i])
        d = hashlib.sha256(
            hashlib.sha256(buf[o : o + l].tobytes()).digest()
        ).digest()
        z[i] = np.frombuffer(d, np.uint8)
    return z


def extract_channel_announcements(idx: StoreIndex) -> VerifyItems:
    """4 (sig, key) pairs per channel_announcement (sigcheck.c:45-113)."""
    n = len(idx)
    if n == 0:
        return _empty_items()
    off = idx.offsets
    sr_off = off + wire.CA_SIGNED_OFFSET
    sr_len = idx.lengths - wire.CA_SIGNED_OFFSET
    rows, nb = native.sha256_pack(idx.buf, sr_off, sr_len, MAX_BLOCKS)
    z_host = _host_hash_oversized(idx.buf, sr_off, sr_len, nb)
    flen_raw = native.gather_fields(idx.buf, off, wire.CA_FLEN_OFFSET, 2)
    flen = (flen_raw[:, 0].astype(np.uint64) << 8) | flen_raw[:, 1]
    key_base = wire.CA_FLEN_OFFSET + 2 + flen + 32 + 8
    sigs, keys = [], []
    for i, sig_off in enumerate(wire.CA_SIG_OFFSETS):
        sigs.append(native.gather_fields(idx.buf, off, sig_off, 64))
        keys.append(native.gather_fields(idx.buf, off + key_base, 33 * i, 33))
    # rows stay per-MESSAGE: the 4 signatures share one signed region,
    # and row_of_item maps them back — tiling the 512-byte rows 4× made
    # the hash phase (and its upload) 4× bigger for nothing
    return VerifyItems(
        rows,
        nb,
        np.concatenate(sigs),
        np.concatenate(keys),
        np.tile(np.arange(n, dtype=np.int64), 4),
        z_host,
        np.tile(np.arange(n, dtype=np.int64), 4),
    )


def extract_node_announcements(idx: StoreIndex) -> VerifyItems:
    n = len(idx)
    if n == 0:
        return _empty_items()
    off = idx.offsets
    sr_off = off + wire.NA_SIGNED_OFFSET
    sr_len = idx.lengths - wire.NA_SIGNED_OFFSET
    rows, nb = native.sha256_pack(idx.buf, sr_off, sr_len, MAX_BLOCKS)
    z_host = _host_hash_oversized(idx.buf, sr_off, sr_len, nb)
    flen_raw = native.gather_fields(idx.buf, off, 66, 2)
    flen = (flen_raw[:, 0].astype(np.uint64) << 8) | flen_raw[:, 1]
    sigs = native.gather_fields(idx.buf, off, wire.NA_SIG_OFFSET, 64)
    keys = native.gather_fields(idx.buf, off + flen, 68 + 4, 33)
    return VerifyItems(rows, nb, sigs, keys, np.arange(n, dtype=np.int64),
                       z_host)


def extract_channel_updates(idx: StoreIndex, scid_to_nodes) -> VerifyItems:
    """channel_update is signed by the direction-selected channel node
    (sigcheck.c:9-43); scid_to_nodes maps scid → (node_id_1, node_id_2)."""
    n = len(idx)
    if n == 0:
        return _empty_items()
    off = idx.offsets
    sr_off = off + wire.CU_SIGNED_OFFSET
    sr_len = idx.lengths - wire.CU_SIGNED_OFFSET
    rows, nb = native.sha256_pack(idx.buf, sr_off, sr_len, MAX_BLOCKS)
    z_host = _host_hash_oversized(idx.buf, sr_off, sr_len, nb)
    sigs = native.gather_fields(idx.buf, off, wire.CU_SIG_OFFSET, 64)
    scid_raw = native.gather_fields(idx.buf, off, wire.CU_SCID_OFFSET, 8)
    scids = scid_raw.astype(np.uint64)
    scid = np.zeros(n, np.uint64)
    for b in range(8):
        scid = (scid << np.uint64(8)) | scids[:, b]
    chan_flags = native.gather_fields(idx.buf, off, wire.CU_FLAGS_OFFSET + 1, 1)[:, 0]
    direction = chan_flags & 1
    keys = scid_to_nodes(scid, direction)  # (n, 33) uint8
    return VerifyItems(rows, nb, sigs, keys, np.arange(n, dtype=np.int64),
                       z_host)


def _empty_items() -> VerifyItems:
    return VerifyItems(
        np.zeros((0, MAX_BLOCKS * 64), np.uint8), np.zeros(0, np.uint32),
        np.zeros((0, 64), np.uint8), np.zeros((0, 33), np.uint8),
        np.zeros(0, np.int64),
    )


def make_scid_map(ca_idx: StoreIndex):
    """Vectorized scid → (node_id_1 | node_id_2) resolver built from the
    channel_announcement batch (sorted array + searchsorted)."""
    n = len(ca_idx)
    if n == 0:
        # no announcements: every update resolves to the zero key, which
        # fails verification (as it must)
        return lambda scids, direction: np.zeros((len(scids), 33), np.uint8)
    off = ca_idx.offsets
    flen_raw = native.gather_fields(ca_idx.buf, off, wire.CA_FLEN_OFFSET, 2)
    flen = (flen_raw[:, 0].astype(np.uint64) << 8) | flen_raw[:, 1]
    scid_raw = native.gather_fields(
        ca_idx.buf, off + flen, wire.CA_FLEN_OFFSET + 2 + 32, 8
    ).astype(np.uint64)
    scid = np.zeros(n, np.uint64)
    for b in range(8):
        scid = (scid << np.uint64(8)) | scid_raw[:, b]
    key_base = wire.CA_FLEN_OFFSET + 2 + flen + 40
    node1 = native.gather_fields(ca_idx.buf, off + key_base, 0, 33)
    node2 = native.gather_fields(ca_idx.buf, off + key_base, 33, 33)
    order = np.argsort(scid, kind="stable")
    scid_sorted = scid[order]
    node1s, node2s = node1[order], node2[order]

    def lookup(scids: np.ndarray, direction: np.ndarray) -> np.ndarray:
        pos = np.searchsorted(scid_sorted, scids)
        pos_c = np.clip(pos, 0, max(0, n - 1))
        found = (pos < n) & (scid_sorted[pos_c] == scids) if n else np.zeros(len(scids), bool)
        keys = np.where(
            (direction == 0)[:, None], node1s[pos_c], node2s[pos_c]
        )
        # unknown scid → zero key (fails verification, as it must)
        keys[~found] = 0
        return keys

    return lookup


# ---------------------------------------------------------------------------
# The streaming bucket pipeline (doc/replay_pipeline.md)


@dataclass
class _PreparedBucket:
    """One self-contained, fully host-prepped bucket: hash rows, local
    row indices and signature operands, all padded to the bucket."""

    sel: np.ndarray        # (n_real,) item indices, dispatch order
    n_real: int
    mb: int                # quantized SHA block width (4 or MAX_BLOCKS)
    blocks: np.ndarray     # (bucket, mb, 16) uint32
    n_blocks: np.ndarray   # (bucket,) int32
    roi_local: np.ndarray  # (bucket,) int32 — row index WITHIN the bucket
    sigs: np.ndarray       # (bucket, 64) uint8
    pubkeys: np.ndarray    # (bucket, 33) uint8
    staged_bytes: int
    prep_seconds: float


def _plan_buckets(roi_sorted: np.ndarray, bucket: int) -> list[tuple]:
    """Cut the row-sorted signature stream into self-contained buckets:
    ≤ bucket signatures AND ≤ bucket distinct rows each, so every
    bucket's fused program sees static (bucket, ·) shapes.  A message
    row whose signatures straddle a cut is simply hashed by both
    buckets (≤ 3 duplicate rows per cut — CAs carry 4 sigs/row).
    Returns [(sig_start, sig_end, row_start, row_end), ...]."""
    N = len(roi_sorted)
    out = []
    start = 0
    while start < N:
        cap = min(start + bucket, N)
        r0 = int(roi_sorted[start])
        # signatures referencing rows beyond r0 + bucket can't gather
        # from this bucket's (bucket,)-shaped z plane; cut before them
        end = start + int(np.searchsorted(roi_sorted[start:cap],
                                          r0 + bucket, side="left"))
        out.append((start, end, r0, int(roi_sorted[end - 1]) + 1))
        start = end
    return out


def _prep_bucket(items: VerifyItems, order: np.ndarray,
                 roi_sorted: np.ndarray, bucket: int,
                 chunk: tuple, corrs=()) -> _PreparedBucket:
    """Host side of one bucket: slice rows, byte→block pack, pad.  Runs
    on the producer thread in the overlapped pipeline (the corr
    carriers keep its spans causally linked to the enqueue point —
    contextvars don't follow us onto that thread)."""
    with trace.span("replay/prep", corr=corrs):
        return _prep_bucket_inner(items, order, roi_sorted, bucket, chunk)


def _prep_bucket_inner(items: VerifyItems, order: np.ndarray,
                       roi_sorted: np.ndarray, bucket: int,
                       chunk: tuple) -> _PreparedBucket:
    start, end, r0, r1 = chunk
    t0 = time.perf_counter()
    _fault.fire("prep", "verify")
    sel = order[start:end]
    nb = items.n_blocks[r0:r1]
    # rows arrive type-sorted (CA | NA | CU), so most buckets need far
    # fewer SHA blocks than the 8-block pad: channel_updates fit in 3,
    # node_announcements usually in 4.  Slicing the block axis halves
    # the host→device bytes for those buckets; quantizing to
    # {4, MAX_BLOCKS} bounds the fused-program shapes at two (both
    # precompiled by warmup).
    mbv = int(nb.max(initial=0))
    mb = 4 if 0 < mbv <= 4 else MAX_BLOCKS
    blocks = _bytes_to_blocks(
        S._pad_rows(items.rows[r0:r1], bucket)[:, :mb * 64], mb)
    nb_p = S._pad_rows(nb, bucket).astype(np.int32)
    roi_l = S._pad_rows((roi_sorted[start:end] - r0).astype(np.int32),
                        bucket)
    sigs = S._pad_rows(items.sigs[sel], bucket)
    pubs = S._pad_rows(items.pubkeys[sel], bucket)
    staged = (blocks.nbytes + nb_p.nbytes + roi_l.nbytes
              + sigs.nbytes + pubs.nbytes)
    return _PreparedBucket(sel, end - start, mb, blocks, nb_p, roi_l,
                           sigs, pubs, staged,
                           time.perf_counter() - t0)


def _fused_device_fn(bucket: int):
    """Default device path: one fused program per prepared bucket."""
    kern = _jit_fused()

    def dispatch(pb: _PreparedBucket):
        _note_shape("fused", (bucket, pb.mb))
        _M_R_BUCKETS.labels("fused").inc()
        return kern(jnp.asarray(pb.blocks), jnp.asarray(pb.n_blocks),
                    jnp.asarray(pb.roi_local), jnp.asarray(pb.sigs),
                    jnp.asarray(pb.pubkeys))

    return dispatch


@functools.lru_cache(maxsize=2)
def _cached_mesh(n_devices: int):
    from ..parallel import mesh as pmesh

    return pmesh.make_mesh(jax.devices()[:n_devices])


def _mesh_compiler_opts() -> tuple:
    """Compiler options for the sharded EC program.  Defaults to cheap
    LLVM options on the CPU backend (a virtual CPU mesh is a sharding
    rig, not a perf rig; full opt quadruples its multi-minute compile)
    and full optimization elsewhere.  LIGHTNING_TPU_MESH_COMPILE=
    cheap|full overrides."""
    from ..utils.jaxcfg import CHEAP_COMPILE_OPTS

    mode = _os.environ.get("LIGHTNING_TPU_MESH_COMPILE", "")
    if not mode:
        mode = "cheap" if jax.default_backend() == "cpu" else "full"
    return tuple(sorted(CHEAP_COMPILE_OPTS.items())) if mode == "cheap" \
        else ()


def _mesh_device_fn(bucket: int, count_metrics: bool = True):
    """Multi-device path: hash + local z gather stay single-device jit
    programs, the EC verify — ~99% of the device FLOPs — runs batch-
    sharded over the mesh via parallel/mesh.py sharded_verify_fn (the
    psum valid-count collective included).  Host converts sig/pubkey
    bytes to limbs (the sharded program's operand contract); the z
    plane moves device→mesh as a resharding device_put, never through
    numpy.  Returns None when no usable mesh exists (then the caller
    falls back to the fused single-device path).  count_metrics=False
    suppresses the bucket counter (warmup's dummy dispatches are not
    replay buckets; compile-event first-sights still record)."""
    from ..parallel import mesh as pmesh

    limit = _os.environ.get("LIGHTNING_TPU_MESH_DEVICES")
    n = pmesh.usable_device_count(bucket,
                                  int(limit) if limit else None)
    if n < 2:
        return None
    mesh = _cached_mesh(n)
    vfn = pmesh.sharded_verify_fn(mesh, _mesh_compiler_opts())

    def mesh_dispatch(pb: _PreparedBucket):
        _note_shape("hash", (bucket, pb.mb))
        _note_shape("gather", (bucket, bucket))
        _note_shape("mesh_verify", (bucket, n))
        if count_metrics:
            _M_R_BUCKETS.labels("mesh").inc()
        z_rows = _jit_hash()(jnp.asarray(pb.blocks),
                             jnp.asarray(pb.n_blocks))
        z = S._jit_gather_rows()(z_rows, jnp.asarray(pb.roi_local))
        r = F.from_bytes_be(pb.sigs[:, :32])
        s = F.from_bytes_be(pb.sigs[:, 32:])
        qx = F.from_bytes_be(pb.pubkeys[:, 1:])
        parity = (pb.pubkeys[:, 0] & 1).astype(np.uint32)
        zs, rs, ss, qxs, ps = pmesh.shard_batch(mesh, z, r, s, qx, parity)
        ok, _count = vfn(zs, rs, ss, qxs, ps)
        return ok

    # supervision: the mesh is an OPTIMIZATION over the fused
    # single-device program, so its breaker degrades mesh→fused (the
    # outer "verify" breaker still guards fused→host).  A failing
    # collective or dead mesh device trips this after N consecutive
    # failures and the replay keeps streaming on one device.
    fused = _fused_device_fn(bucket)

    def _supervised(pb: _PreparedBucket, rec: dict):
        brk = _breaker.get("mesh")
        rec["breaker_state"] = brk.state
        if not brk.allow():
            # mesh's fallback is the fused single-device program, not
            # the host; breaker_state="open" records the cause
            rec["outcome"] = "fused"
            return fused(pb)
        try:
            ok = mesh_dispatch(pb)
        except Exception as e:
            brk.record_failure()
            rec["outcome"] = "fused"
            rec["error"] = type(e).__name__
            log.warning("mesh-sharded verify failed (%s); this bucket "
                        "runs on the fused single-device program", e)
            return fused(pb)
        brk.record_success()
        rec["outcome"] = "ok"
        return ok

    def dispatch(pb: _PreparedBucket):
        if not count_metrics:      # warmup's dummy buckets: no records
            return _supervised(pb, {})
        # a nested flight record: the mesh shard links to its parent
        # verify dispatch via parent_dispatch_id (thread-local nesting)
        with _flight.dispatch("mesh", shape=(bucket, pb.mb),
                              n_real=pb.n_real, lanes=bucket) as rec:
            with trace.span("mesh/dispatch",
                            dispatch_id=rec["dispatch_id"]):
                with trace.annotation("mesh/dispatch"):
                    return _supervised(pb, rec)

    return dispatch


def _select_device_fn(bucket: int, n_sigs: int):
    """Route buckets to the mesh-sharded EC stage when the process has
    >1 device and the batch is worth sharding; LIGHTNING_TPU_MESH_VERIFY
    = auto (default) | on | off.  The auto threshold
    (LIGHTNING_TPU_MESH_MIN_SIGS, default one full bucket) keeps
    protocol-path one-off checks on the single-device program."""
    mode = _os.environ.get("LIGHTNING_TPU_MESH_VERIFY", "auto")
    if mode != "off":
        try:
            ndev = len(jax.devices())
        except Exception:
            ndev = 1
        if ndev > 1:
            min_sigs = int(_os.environ.get("LIGHTNING_TPU_MESH_MIN_SIGS",
                                           str(bucket)))
            if mode == "on" or n_sigs >= min_sigs:
                fn = _mesh_device_fn(bucket)
                if fn is not None:
                    return fn
    return _fused_device_fn(bucket)


def _host_device_fn(items: "VerifyItems", roi: np.ndarray, bucket: int):
    """LIGHTNING_TPU_VERIFY_DEVICE=off: a bucket dispatcher that routes
    straight to the host oracle — the FULL pipeline still runs (producer
    overlap, breaker/quarantine supervision, fault seams, flight
    records), but no device program is ever compiled or dispatched.
    Bit-identical to the device path by the oracle's construction.

    For CPU-only daemons and subprocess harnesses (tools/crashmatrix.py
    children) where a one-core jax compile would stall startup for
    minutes; the kill-seam coverage of the verify pipeline depends on
    the real pipeline machinery running, which a verify_items() stub
    would bypass."""

    def dispatch(pb: "_PreparedBucket") -> np.ndarray:
        _M_R_BUCKETS.labels("host_off").inc()
        ok = np.zeros(bucket, bool)
        if pb.n_real:
            ok[:pb.n_real] = _host_verify_selected(
                items, roi, pb.sel[: pb.n_real])
        return ok

    return dispatch


_DONE = object()


def _host_verify_selected(items: VerifyItems, roi: np.ndarray,
                          idx: np.ndarray) -> np.ndarray:
    """The trustworthy host escape hatch: sha256d + exact-int ECDSA for
    the given signature indices, straight off the packed host rows.

    The packer (native.sha256_pack) stores standard SHA-256 padding —
    0x80, zeros, 64-bit big-endian bit length closing block n_blocks-1
    — so the original signed region is recoverable from the row itself
    and no extraction-time buffer needs to be retained.  Rows flagged
    oversized (n_blocks == 0) hash to zero here; verify_items re-checks
    those against items.z_host afterward, exactly as it does for the
    device result.  Bit-identical to the device path by construction
    (S._host_verify mirrors the kernel's low-S/tag semantics)."""
    import hashlib

    idx = np.asarray(idx, np.int64)
    z = np.zeros((len(idx), 32), np.uint8)
    cache: dict[int, bytes] = {}
    for j, r in enumerate(roi[idx]):
        r = int(r)
        d = cache.get(r)
        if d is None:
            nbr = int(items.n_blocks[r])
            if nbr == 0:
                d = b"\0" * 32
            else:
                row = items.rows[r]
                bitlen = int.from_bytes(
                    row[nbr * 64 - 8: nbr * 64].tobytes(), "big")
                msg = row[: bitlen // 8].tobytes()
                d = hashlib.sha256(hashlib.sha256(msg).digest()).digest()
            cache[r] = d
        z[j] = np.frombuffer(d, np.uint8)
    return S._host_verify(z, items.sigs[idx], items.pubkeys[idx])


def _subbucket(pb: _PreparedBucket, lanes: np.ndarray,
               bucket: int) -> _PreparedBucket:
    """Re-pad a subset of a prepared bucket's signature lanes into a
    dispatchable bucket (same static shapes, so no new compile).  The
    hash-row planes are shared — only the per-signature operands and
    their row indices narrow."""
    return _PreparedBucket(
        sel=pb.sel[lanes], n_real=len(lanes), mb=pb.mb,
        blocks=pb.blocks, n_blocks=pb.n_blocks,
        roi_local=S._pad_rows(pb.roi_local[lanes], bucket),
        sigs=S._pad_rows(pb.sigs[lanes], bucket),
        pubkeys=S._pad_rows(pb.pubkeys[lanes], bucket),
        staged_bytes=0, prep_seconds=0.0)


def _wrap_resilient(device_fn, items: VerifyItems, roi: np.ndarray,
                    bucket: int, corrs=(), sink: list | None = None,
                    dispatch_map: np.ndarray | None = None):
    """Supervise one bucket dispatcher with the "verify" circuit
    breaker and poisoned-batch quarantine (doc/resilience.md):

    * breaker open → the whole bucket verifies on the host oracle
      (metered as a `host_breaker` bucket), bit-identical results;
    * dispatch raises → breaker records the failure and the bucket
      bisects: clean halves complete on the device, isolated rows are
      quarantined + re-checked host-side.  The replay completes either
      way — a single poisoned row no longer fails the whole store.

    Every call is one flight-recorded dispatch (obs/flight.py): the
    record lands in ``sink`` (dispatch order) and its span carries the
    replay's corr carriers, so each bucket shows up once in the
    exported timeline with a flow arrow back to the enqueue span.
    Records of successful dispatches are NOT sealed here — the
    readback at end-of-replay decides the final outcome (a failed
    readback is ``readback_host``), so sealing/metering waits for it
    (flight.defer); only a raising dispatch seals immediately.
    """
    brk = _breaker.get("verify")
    corr_ids = _flight.corr_ids(corrs)

    def host_lanes(pb: _PreparedBucket, lanes: np.ndarray) -> np.ndarray:
        return _host_verify_selected(items, roi, pb.sel[lanes])

    def _dispatch_inner(pb: _PreparedBucket, rec: dict):
        if not brk.allow():
            rec["outcome"] = "host_breaker"
            _M_R_BUCKETS.labels("host_breaker").inc()
            ok = np.zeros(bucket, bool)
            if pb.n_real:
                ok[:pb.n_real] = host_lanes(pb, np.arange(pb.n_real))
            return ok
        try:
            _fault.fire("dispatch", "verify")
            # operand upload happens inside device_fn (jnp.asarray on
            # the packed planes): account the staged bytes against THIS
            # dispatch only when a device dispatch is actually attempted
            rec["h2d_bytes"] = pb.staged_bytes
            _M_TRANSFER.labels("verify", "h2d").inc(pb.staged_bytes)
            ok = device_fn(pb)
        except Exception as e:
            brk.record_failure()
            rec["outcome"] = "bisect"
            rec["error"] = type(e).__name__
            log.warning("verify bucket dispatch failed (%s); bisecting "
                        "%d lanes", e, pb.n_real)
            out = np.zeros(bucket, bool)
            parts, bad = _quarantine.bisect(
                np.arange(pb.n_real),
                lambda lanes: np.asarray(
                    device_fn(_subbucket(pb, lanes, bucket)))[:len(lanes)],
                family="verify")
            for lanes, res in parts:
                out[lanes] = res
            if bad:
                lanes = np.asarray(bad, np.int64)
                out[lanes] = host_lanes(pb, lanes)
            return out
        brk.record_success()
        rec["outcome"] = "ok"
        return ok

    def dispatch(pb: _PreparedBucket, queue_wait: float = 0.0):
        rec = _flight.begin(
            "verify", corr_ids=corr_ids, shape=(bucket, pb.mb),
            n_real=pb.n_real, lanes=bucket,
            queue_wait_ms=queue_wait * 1e3,
            prep_ms=pb.prep_seconds * 1e3, breaker_state=brk.state)
        if sink is not None:
            sink.append(rec)
        if dispatch_map is not None:
            # per-item provenance (doc/journeys.md): pb.sel holds the
            # ORIGINAL signature indices this bucket carries, so the
            # caller learns which flight record verified each item
            dispatch_map[pb.sel[:pb.n_real]] = rec["dispatch_id"]
        t0 = time.perf_counter()
        try:
            with trace.span("verify/dispatch", corr=corrs,
                            dispatch_id=rec["dispatch_id"]):
                with trace.annotation("verify/dispatch"):
                    ok = _dispatch_inner(pb, rec)
        except BaseException as e:
            if rec["outcome"] is None:
                rec["outcome"] = "error"
            _flight.finish(rec,
                           dispatch_ms=(time.perf_counter() - t0) * 1e3,
                           error=type(e).__name__)
            raise
        rec["dispatch_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        _flight.defer(rec)
        return ok

    return dispatch


def _run_pipeline(items: VerifyItems, roi: np.ndarray, bucket: int,
                  depth: int | None, device_fn,
                  corrs=(), dispatch_map: np.ndarray | None = None,
                  ) -> tuple[np.ndarray, int]:
    """Sort signatures by row, cut self-contained buckets, and stream
    them: a producer thread preps bucket i+1 while bucket i's fused
    program runs on device.  depth bounds the prepared-bucket queue
    (HBM staging for ~depth in-flight buckets); depth 0 = serial
    (prep inline on the dispatch thread — the measured baseline the
    overlap metrics are asserted against).  Returns (out, n_buckets)."""
    N = len(items)
    order = np.argsort(roi, kind="stable")
    roi_sorted = roi[order]
    chunks = _plan_buckets(roi_sorted, bucket)
    if depth is None:
        depth = int(_os.environ.get("LIGHTNING_TPU_REPLAY_DEPTH", "2"))
    if device_fn is None:
        if _os.environ.get("LIGHTNING_TPU_VERIFY_DEVICE", "auto") == "off":
            device_fn = _host_device_fn(items, roi, bucket)
        else:
            device_fn = _select_device_fn(bucket, N)
    # every bucket dispatch (injected test doubles included) runs under
    # the verify breaker + quarantine supervision, and each is one
    # flight-recorded dispatch whose record lands in `flight_recs`
    # (dispatch order, so the readback loop below can set late fields)
    flight_recs: list[dict] = []
    device_fn = _wrap_resilient(device_fn, items, roi, bucket,
                                corrs=corrs, sink=flight_recs,
                                dispatch_map=dispatch_map)
    prep = functools.partial(_prep_bucket, items, order, roi_sorted,
                             bucket, corrs=corrs)

    out = np.zeros(N, bool)
    # pending holds only (sel, n_real, device_ok): keeping the whole
    # _PreparedBucket would pin every bucket's packed host arrays (≈ the
    # re-packed store) in memory until the final readback
    pending: list[tuple[np.ndarray, int, object]] = []
    t_prep = t_stall = t_dispatch = 0.0
    staged_bytes = 0
    # dispatch-deadline on the prepared-bucket queue: a producer that
    # hangs (or dies without surfacing) must not park the replay forever
    prod_deadline = _deadline.deadline_for("verify")
    n_done = 0          # buckets dispatched from the producer stream
    timed_out = False

    if depth > 0 and len(chunks) > 1:
        q: _queue.Queue = _queue.Queue(maxsize=depth)
        stop = threading.Event()  # dispatch failed: stop prepping

        def _put(item) -> bool:
            # stop-aware put: a producer abandoned by the deadline path
            # (or raced by a dispatch failure) must never block forever
            # on a full queue nobody drains — at ANY depth
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except _queue.Full:
                    pass
            return False

        def _producer():
            try:
                for c in chunks:
                    if stop.is_set():
                        return
                    _fault.fire("producer", "verify")
                    if not _put(prep(c)):
                        return
                _put(_DONE)
            except BaseException as e:  # surface on the dispatch thread
                _put(e)

        th = threading.Thread(target=_producer, name="replay-prep",
                              daemon=True)
        th.start()
        try:
            while True:
                t0 = time.perf_counter()
                try:
                    pb = q.get(timeout=prod_deadline)
                except _queue.Empty:
                    _deadline.note_exceeded("verify", "producer",
                                            prod_deadline)
                    timed_out = True
                    break
                wait = time.perf_counter() - t0
                t_stall += wait
                if pb is _DONE:
                    break
                if isinstance(pb, BaseException):
                    raise pb
                _M_R_QDEPTH.observe(q.qsize() + 1)
                t0 = time.perf_counter()
                ok = device_fn(pb, queue_wait=wait)
                t_dispatch += time.perf_counter() - t0
                t_prep += pb.prep_seconds
                staged_bytes += pb.staged_bytes
                pending.append((pb.sel, pb.n_real, ok))
                n_done += 1
        finally:
            # the producer may be parked on a full queue if the
            # dispatch loop raised — tell it to stop and drain until it
            # exits (its puts are stop-aware, so it unparks on its
            # own).  A HUNG producer (deadline path) is abandoned
            # instead — a daemon thread stuck in prep that the join
            # below would wait on; when (if) its prep ever returns, the
            # stop-aware put lets it exit without a consumer.
            stop.set()
            while th.is_alive() and not timed_out:
                try:
                    q.get_nowait()
                except _queue.Empty:
                    pass
                th.join(timeout=0.005)
            if timed_out:
                while True:
                    try:
                        q.get_nowait()
                    except _queue.Empty:
                        break
    else:
        for c in chunks:
            pb = prep(c)
            t_prep += pb.prep_seconds
            t_stall += pb.prep_seconds  # serial: all prep is visible
            t0 = time.perf_counter()
            ok = device_fn(pb)
            t_dispatch += time.perf_counter() - t0
            staged_bytes += pb.staged_bytes
            pending.append((pb.sel, pb.n_real, ok))
            n_done += 1

    if timed_out:
        # restart semantics for the replay: abandon the wedged producer
        # and prep the remaining buckets inline on this thread.  A
        # bucket the producer managed to deliver concurrently is simply
        # verified twice (idempotent) — never skipped, never hung.
        log.warning("replay producer missed its %.3fs deadline after "
                    "%d/%d buckets; prepping the rest inline",
                    prod_deadline, n_done, len(chunks))
        for c in chunks[n_done:]:
            pb = prep(c)
            t_prep += pb.prep_seconds
            t_stall += pb.prep_seconds
            t0 = time.perf_counter()
            ok = device_fn(pb)
            t_dispatch += time.perf_counter() - t0
            staged_bytes += pb.staged_bytes
            pending.append((pb.sel, pb.n_real, ok))

    # the ONLY device→host transfer of the replay: drain the enqueued
    # booleans in dispatch order.  A readback failure (an enqueued
    # program that died after dispatch) diverts just that bucket's rows
    # to the host oracle instead of failing the replay.
    t0 = time.perf_counter()
    brk = _breaker.get("verify")
    try:
        with trace.span("replay/readback", corr=corrs,
                        buckets=len(pending)):
            for (sel, n_real, ok), rec in zip(pending, flight_recs):
                idx = sel[:n_real]
                t0b = time.perf_counter()
                try:
                    _fault.fire("readback", "verify")
                    ok_host = np.asarray(ok)
                    out[idx] = ok_host[:n_real]
                    if rec["outcome"] in ("ok", "bisect"):
                        # the replay's only device→host transfer: the
                        # boolean plane this bucket read back
                        rec["d2h_bytes"] = ok_host.nbytes
                        _M_TRANSFER.labels("verify",
                                           "d2h").inc(ok_host.nbytes)
                except Exception as e:
                    brk.record_failure()
                    _quarantine.note("verify", "readback", n_real)
                    rec["outcome"] = "readback_host"
                    rec["error"] = type(e).__name__
                    rec["quarantined"] += n_real
                    log.warning("replay readback failed (%s); re-checking "
                                "%d rows on the host", e, n_real)
                    out[idx] = _host_verify_selected(items, roi, idx)
                rec["readback_ms"] = round(
                    (time.perf_counter() - t0b) * 1e3, 3)
                # the deferred seal: the final outcome (ok / bisect /
                # host_breaker from dispatch, or readback_host above) is
                # only known now, so the ring insert + counter + watchdog
                # all see it — listdispatches and clntpu_dispatches_total
                # reconcile even on readback failures
                _flight.finish(rec)
    finally:
        # a raising host re-check must not leave the remaining deferred
        # records unsealed and invisible to the ring (finish() is
        # idempotent, so already-sealed ones are untouched)
        for rec in flight_recs:
            _flight.finish(rec)
    _M_R_READBACK.inc(time.perf_counter() - t0)

    _M_R_PREP.inc(t_prep)
    _M_R_STALL.inc(t_stall)
    _M_R_DISPATCH.inc(t_dispatch)
    if t_prep > 0:
        _M_R_OVERLAP.observe(max(0.0, 1.0 - t_stall / t_prep))
    lanes = len(chunks) * bucket
    _M_LANES.labels("verify").inc(lanes)
    _M_LANES.labels("hash").inc(lanes)
    _M_DEVICE_BYTES.inc(staged_bytes)
    return out, len(chunks)


def _verify_items_unfused(items: VerifyItems, roi: np.ndarray,
                          bucket: int) -> tuple[np.ndarray, int]:
    """The pre-pipeline 3-program chain (hash buckets → device-resident
    z concat → per-signature gather + verify).  Kept as the
    LIGHTNING_TPU_REPLAY_FUSED=0 fallback: it needs no fused-program
    compile, which matters on a backend whose persistent cache has only
    the old programs.  Same device-resident z handoff, same single
    readback."""
    N, M = len(items), items.rows.shape[0]
    zs = []
    staged_bytes = 0
    for start in range(0, M, bucket):
        end = min(start + bucket, M)
        sl = slice(start, end)
        mb = int(items.n_blocks[sl].max(initial=0))
        mb = 4 if 0 < mb <= 4 else MAX_BLOCKS
        blocks = _bytes_to_blocks(
            S._pad_rows(items.rows[sl], bucket)[:, :mb * 64], mb)
        _note_shape("hash", (bucket, mb))
        staged_bytes += blocks.nbytes + bucket * 4
        zs.append(_jit_hash()(
            jnp.asarray(blocks),
            jnp.asarray(S._pad_rows(items.n_blocks[sl],
                                    bucket).astype(np.int32)),
        ))
    z_rows = zs[0] if len(zs) == 1 else jnp.concatenate(zs)

    out = np.zeros(N, bool)
    gather = S._jit_gather_rows()
    kern = S._jit_verify_from_bytes()
    _note_shape("gather", (int(z_rows.shape[0]), bucket))
    _note_shape("verify", (bucket,))
    pending = []
    for start in range(0, N, bucket):
        end = min(start + bucket, N)
        sl = slice(start, end)
        z = gather(z_rows,
                   jnp.asarray(S._pad_rows(roi[sl].astype(np.int32),
                                           bucket)))
        ok = kern(
            z,
            jnp.asarray(S._pad_rows(items.sigs[sl], bucket)),
            jnp.asarray(S._pad_rows(items.pubkeys[sl], bucket)),
        )
        staged_bytes += bucket * (4 + 64 + 33)
        _M_R_BUCKETS.labels("unfused").inc()
        pending.append((sl, end - start, ok))
    for sl, n_real, ok in pending:
        out[sl] = np.asarray(ok)[:n_real]

    verify_lanes = ((N + bucket - 1) // bucket) * bucket
    hash_lanes = ((M + bucket - 1) // bucket) * bucket
    _M_LANES.labels("verify").inc(verify_lanes)
    _M_LANES.labels("hash").inc(hash_lanes)
    _M_DEVICE_BYTES.inc(staged_bytes)
    return out, (N + bucket - 1) // bucket


def verify_items(items: VerifyItems, bucket: int = DEFAULT_BUCKET, *,
                 depth: int | None = None, device_fn=None,
                 corr=None,
                 dispatch_map: np.ndarray | None = None) -> np.ndarray:
    """Streaming fused-bucket replay (doc/replay_pipeline.md).

    Signatures are sorted by message row and cut into self-contained
    buckets; each bucket is ONE fused device program (sha256d → local
    z gather → ECDSA verify — sig/pubkey bytes unpack on-device), so
    the z plane never leaves the device and the whole replay is one
    enqueue stream with a SINGLE boolean readback at the end.  Host
    bucket prep runs on a producer thread `depth` buckets ahead of the
    dispatch loop (double-buffered by default), overlapping pack/pad
    work with device compute — observable via the clntpu_replay_*
    stage counters.  With >1 device, buckets route the EC stage
    through parallel/mesh.py batch sharding (LIGHTNING_TPU_MESH_VERIFY).

    Oversized rows (n_blocks == 0, hashed host-side at extraction) are
    re-checked on the host afterward.  `device_fn` injects a bucket
    dispatcher (tests); `depth` overrides LIGHTNING_TPU_REPLAY_DEPTH
    (0 = serial prep, the overlap baseline).  Returns bool (N,).

    Every bucket dispatch runs supervised (doc/resilience.md): the
    "verify" circuit breaker short-circuits to the host oracle when the
    device path is flapping, a raising dispatch bisects to quarantine
    the poisoned rows and complete the rest, readback failures re-check
    just their bucket host-side, and a hung producer thread trips the
    LIGHTNING_TPU_DEADLINE_VERIFY_S deadline into inline prep — so a
    replay COMPLETES, bit-identically, under any single-path failure.
    (The LIGHTNING_TPU_REPLAY_FUSED=0 legacy chain is supervised
    coarsely: breaker-open or a raising chain re-checks the whole
    replay on the host oracle, without per-bucket bisection.)

    ``corr`` (a trace.Carrier or list of them, minted at the enqueue
    point — ingest submit, the store-replay span) rides every prep /
    dispatch / readback span and flight record of this replay, so the
    exported timeline links each bucket back to its enqueue span
    across the producer/dispatch threads (doc/tracing.md).  When
    LIGHTNING_TPU_PROFILE=<dir> is set the whole replay runs inside a
    jax.profiler session with per-dispatch TraceAnnotations.

    ``dispatch_map`` (caller-allocated int64 (N,), conventionally
    filled with -1) receives, per SIGNATURE index, the dispatch_id of
    the flight record whose bucket verified it — the per-item
    provenance link doc/journeys.md stitches journeys with.  The
    legacy unfused chain has one coarse record covering the whole
    replay, so every lane maps to it."""
    N = len(items)
    if N == 0:
        return np.zeros(0, bool)
    corrs = trace.as_carriers(corr)
    t_start = time.perf_counter()
    roi = items.row_of_item
    if roi is None:
        roi = np.arange(N, dtype=np.int64)
    tag_ok = (items.pubkeys[:, 0] == 2) | (items.pubkeys[:, 0] == 3)

    with trace.profile_session():
        if (device_fn is None
                and _os.environ.get("LIGHTNING_TPU_REPLAY_FUSED",
                                    "1") == "0"):
            # the legacy chain has no per-bucket dispatcher to wrap, so
            # its supervision is coarse: breaker-open short-circuits the
            # whole replay to the host oracle, and a raising chain falls
            # back the same way (no bisect — all rows re-check host-side)
            # — one coarse flight record covers the whole replay
            n_buckets = (N + bucket - 1) // bucket
            brk = _breaker.get("verify")
            with _flight.dispatch(
                    "verify", corr_ids=_flight.corr_ids(corrs),
                    shape=(bucket, MAX_BLOCKS), n_real=N,
                    lanes=n_buckets * bucket,
                    breaker_state=brk.state) as frec:
                if dispatch_map is not None:
                    dispatch_map[:] = frec["dispatch_id"]
                with trace.span("verify/dispatch", corr=corrs,
                                dispatch_id=frec["dispatch_id"]):
                    if not brk.allow():
                        frec["outcome"] = "host_breaker"
                        _M_R_BUCKETS.labels("host_breaker").inc(n_buckets)
                        out = _host_verify_selected(items, roi,
                                                    np.arange(N))
                    else:
                        try:
                            _fault.fire("dispatch", "verify")
                            out, n_buckets = _verify_items_unfused(
                                items, roi, bucket)
                        except Exception as e:
                            brk.record_failure()
                            _quarantine.note("verify", type(e).__name__, N)
                            # recovered on the host oracle — "error" is
                            # reserved for unrecovered failures
                            frec["outcome"] = "host"
                            frec["error"] = type(e).__name__
                            log.warning(
                                "unfused verify chain failed (%s); "
                                "re-checking all %d rows on the host",
                                e, N)
                            out = _host_verify_selected(items, roi,
                                                        np.arange(N))
                        else:
                            brk.record_success()
                            frec["outcome"] = "ok"
        else:
            out, n_buckets = _run_pipeline(items, roi, bucket, depth,
                                           device_fn, corrs=corrs,
                                           dispatch_map=dispatch_map)

    # oversized rows: the device hashed garbage for them; their host
    # sha256d was computed at extraction — verify those few serially.
    # A builder that marks rows oversized MUST supply z_host, or valid
    # signatures would silently verify as False off the garbage hash.
    # An explicit raise, not assert: the contract must survive
    # `python -O` (stripped asserts made this fail as an incidental
    # TypeError on the None subscript).
    ovs = items.n_blocks[roi] == 0
    if ovs.any():
        if items.z_host is None:
            raise ValueError(
                "oversized rows (n_blocks == 0) require z_host")
        _M_OVERSIZED.inc(int(ovs.sum()))
        out[ovs] = S._host_verify(items.z_host[roi[ovs]],
                                  items.sigs[ovs], items.pubkeys[ovs])

    _M_BATCH_SIGS.observe(N)
    _M_OCCUPANCY.observe(N / (n_buckets * bucket))
    _M_FLUSH_SECONDS.observe(time.perf_counter() - t_start)
    return out & tag_ok


@dataclass
class StoreVerifyResult:
    n_records: int
    n_sigs: int
    ca_valid: np.ndarray  # per channel_announcement (all 4 sigs)
    cu_valid: np.ndarray
    na_valid: np.ndarray


def verify_store(idx: StoreIndex, bucket: int = DEFAULT_BUCKET) -> StoreVerifyResult:
    """Replay-verify a full store: every signature on every alive gossip
    message (the reference's store *load* skips re-verification; its
    *ingest* path verifies serially — this is the ingest cost model run at
    load scale, the BASELINE.md target workload)."""
    alive = idx.select(idx.alive())
    ca = alive.select(alive.types == wire.MSG_CHANNEL_ANNOUNCEMENT)
    na = alive.select(alive.types == wire.MSG_NODE_ANNOUNCEMENT)
    cu = alive.select(alive.types == wire.MSG_CHANNEL_UPDATE)
    with trace.span("gossip/extract", records=int(len(alive.types))):
        items_ca = extract_channel_announcements(ca)
        items_na = extract_node_announcements(na)
        items_cu = extract_channel_updates(cu, make_scid_map(ca))
        all_items = VerifyItems.concat([items_ca, items_na, items_cu])
    with trace.span("gossip/verify", sigs=int(len(all_items.sigs))):
        # the replay's enqueue point: every bucket's prep/dispatch/
        # readback span flows back here in the exported timeline
        corr = trace.new_corr()
        ok = verify_items(all_items, bucket, corr=corr)
    n_ca, n_na, n_cu = len(items_ca), len(items_na), len(items_cu)
    ca_ok = ok[:n_ca].reshape(4, -1).all(axis=0) if n_ca else np.zeros(0, bool)
    na_ok = ok[n_ca : n_ca + n_na]
    cu_ok = ok[n_ca + n_na :]
    return StoreVerifyResult(
        n_records=len(alive), n_sigs=len(all_items),
        ca_valid=ca_ok, cu_valid=cu_ok, na_valid=na_ok,
    )
