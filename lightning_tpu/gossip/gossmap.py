"""Gossmap: the routing graph as structure-of-arrays.

Parity target: common/gossmap.c:55 (the reference's mmap'd SoA graph
with fp16-compressed capacities) + plugins/topology.c's listchannels /
listnodes views.  Here the graph IS flat numpy arrays from the start —
built with the same vectorized native gathers as the verify pipeline, no
per-record Python objects — so it can later be dropped onto the device
wholesale (SURVEY §5's long-context mapping).

Layout:
  nodes:    node_ids (N,33) uint8, sorted-unique
  channels: scids (C,) u64 sorted; node1/node2 (C,) int32 into nodes;
            per-direction update arrays (2,C): enabled, cltv_delta,
            htlc_min/max_msat, fee_base_msat, fee_ppm, timestamp
  adjacency: CSR over directed edges — adj_off (N+1,), adj_chan, adj_dst
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..utils import native
from . import wire
from .store import StoreIndex

# bounded (channel, direction) change log capacity: past this the log
# halves (oldest entries dropped) and consumers whose cursor fell off
# the base do one full parameter refresh instead of a patch
_PARAM_LOG_CAP = 4096


def scid_str(scid: int) -> str:
    """Display form BLOCKxTXxOUT (the reference's short_channel_id fmt)."""
    return f"{scid >> 40}x{(scid >> 16) & 0xFFFFFF}x{scid & 0xFFFF}"


def scid_parse(s) -> int:
    if isinstance(s, int):
        return s
    b, t, o = s.split("x")
    return (int(b) << 40) | (int(t) << 16) | int(o)


@dataclass
class Gossmap:
    node_ids: np.ndarray  # (N, 33) uint8
    scids: np.ndarray  # (C,) uint64, sorted
    node1: np.ndarray  # (C,) int32
    node2: np.ndarray  # (C,) int32
    capacity_sat: np.ndarray  # (C,) float32 (fp16-compressible)
    # per-direction (2, C): direction d = from node_{d+1}'s side
    enabled: np.ndarray  # bool
    cltv_delta: np.ndarray  # uint16
    htlc_min_msat: np.ndarray  # uint64
    htlc_max_msat: np.ndarray  # uint64
    fee_base_msat: np.ndarray  # uint32
    fee_ppm: np.ndarray  # uint32
    timestamps: np.ndarray  # uint32
    # CSR adjacency over directed, update-bearing edges, keyed by
    # DESTINATION node: routing runs backward from the destination, so
    # the scan "edges INTO v" must see every direction that has an
    # update, including channels updated in only one direction
    adj_off: np.ndarray = field(default=None)  # (N+1,) by dst node
    adj_chan: np.ndarray = field(default=None)  # (E,) int32 channel index
    adj_dir: np.ndarray = field(default=None)  # (E,) int8 direction
    adj_src: np.ndarray = field(default=None)  # (E,) int32 source node
    # version counters (routing.planes freshness gate): params bumps on
    # any accepted update's field change, topology on edge-set changes
    topology_version: int = 0
    params_version: int = 0
    # set instead of rebuilding eagerly: a gossip-sync burst of
    # first-in-direction updates would otherwise pay one O(E log E)
    # _build_adjacency per message on the event loop — readers call
    # ensure_adjacency() and the batch costs ONE rebuild
    _adjacency_dirty: bool = False
    # bounded (channel_index, direction) log of accepted updates since
    # construction: RoutePlanes consumers keep a cursor into it and
    # patch ONLY the touched edge lanes on a params bump instead of
    # re-deriving (and re-uploading) every plane — the incremental
    # maintenance path for channel_update bursts (doc/overload.md)
    _param_log: list = field(default_factory=list)
    _param_log_base: int = 0

    @property
    def n_nodes(self) -> int:
        return len(self.node_ids)

    @property
    def n_channels(self) -> int:
        return len(self.scids)

    def node_index(self, node_id: bytes) -> int:
        ids = self.node_ids.view([("k", "V33")]).reshape(-1)
        key = np.frombuffer(node_id, np.uint8).view([("k", "V33")])
        i = np.searchsorted(ids, key[0])
        if i >= len(ids) or ids[i] != key[0]:
            raise KeyError(f"unknown node {node_id.hex()[:16]}")
        return int(i)

    def channel_index(self, scid: int) -> int:
        i = int(np.searchsorted(self.scids, scid))
        if i >= len(self.scids) or self.scids[i] != scid:
            raise KeyError(f"unknown scid {scid}")
        return i

    def _build_adjacency(self) -> None:
        # directed edge exists where direction d has an update;
        # source of (chan c, dir d) is node1 if d==0 else node2
        srcs, chans, dirs, dsts = [], [], [], []
        for d in (0, 1):
            idx = np.nonzero(self.timestamps[d] > 0)[0]
            src = self.node1[idx] if d == 0 else self.node2[idx]
            dst = self.node2[idx] if d == 0 else self.node1[idx]
            srcs.append(src)
            dsts.append(dst)
            chans.append(idx)
            dirs.append(np.full(len(idx), d, np.int8))
        dst = np.concatenate(dsts)
        order = np.argsort(dst, kind="stable")
        dst = dst[order]
        self.adj_chan = np.concatenate(chans)[order].astype(np.int32)
        self.adj_dir = np.concatenate(dirs)[order]
        self.adj_src = np.concatenate(srcs)[order].astype(np.int32)
        counts = np.bincount(dst, minlength=self.n_nodes)
        self.adj_off = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        self.topology_version += 1
        self.params_version += 1
        self._adjacency_dirty = False

    def ensure_adjacency(self) -> None:
        """Rebuild the CSR if updates marked it dirty (or it was never
        built).  Every adjacency reader — dijkstra, RoutePlanes.build —
        enters through here."""
        if self._adjacency_dirty or self.adj_off is None:
            self._build_adjacency()

    def apply_channel_update(self, scid: int, direction: int, *,
                             timestamp: int, disabled: bool,
                             cltv_delta: int, htlc_min_msat: int,
                             htlc_max_msat: int, fee_base_msat: int,
                             fee_ppm: int) -> bool:
        """Fold one ACCEPTED (signature-verified) channel_update into
        the live graph, bumping the version counters consumers key on
        (routing.planes re-uploads parameter planes on params bumps and
        rebuilds on topology bumps).

        Returns False for stale timestamps and for scids this graph
        does not carry.  The latter includes channels ANNOUNCED after
        the graph was built: the SoA arrays are fixed-size, so new
        channels only enter through a map rebuild (`loadgossip` /
        `from_store`) — until then their updates are durably in the
        store but invisible to routing.  Live announcement folding
        (growing node/channel arrays in place) is an open follow-on."""
        try:
            c = self.channel_index(scid)
        except KeyError:
            return False
        d = int(direction) & 1
        if timestamp <= int(self.timestamps[d, c]):
            return False
        first_update = self.timestamps[d, c] == 0
        self.timestamps[d, c] = timestamp
        self.enabled[d, c] = not disabled
        self.cltv_delta[d, c] = cltv_delta
        self.htlc_min_msat[d, c] = htlc_min_msat
        self.htlc_max_msat[d, c] = htlc_max_msat
        self.fee_base_msat[d, c] = fee_base_msat
        self.fee_ppm[d, c] = fee_ppm
        if first_update:
            # a direction gained its first update: new directed edge.
            # Mark dirty (readers rebuild once per batch, not per msg);
            # bump the topology counter NOW so planes snapshots taken
            # before the rebuild are already invalidated.
            self._adjacency_dirty = True
            self.topology_version += 1
        self.params_version += 1
        # change log for incremental plane patching; bounded — on
        # overflow the oldest half drops and stale cursors fall back
        # to a full refresh (param_entries_since returns None)
        self._param_log.append((c, d))
        if len(self._param_log) > _PARAM_LOG_CAP:
            drop = len(self._param_log) - _PARAM_LOG_CAP // 2
            del self._param_log[:drop]
            self._param_log_base += drop
        return True

    @property
    def param_log_pos(self) -> int:
        """Cursor value covering every update logged so far."""
        return self._param_log_base + len(self._param_log)

    def param_entries_since(self, pos: int) -> list | None:
        """(channel_index, direction) pairs accepted since cursor
        `pos`, or None when the log no longer reaches back that far
        (the caller must do a full parameter refresh)."""
        if pos < self._param_log_base:
            return None
        return self._param_log[pos - self._param_log_base:]

    # -- views (plugins/topology.c:270 listchannels / :408 listnodes) -----

    def listnodes(self) -> list[dict]:
        return [{"nodeid": bytes(self.node_ids[i]).hex()}
                for i in range(self.n_nodes)]

    def listchannels(self) -> list[dict]:
        out = []
        for c in range(self.n_channels):
            for d in (0, 1):
                if self.timestamps[d, c] == 0:
                    continue
                src = self.node1[c] if d == 0 else self.node2[c]
                dst = self.node2[c] if d == 0 else self.node1[c]
                out.append({
                    "source": bytes(self.node_ids[src]).hex(),
                    "destination": bytes(self.node_ids[dst]).hex(),
                    "short_channel_id": scid_str(int(self.scids[c])),
                    "direction": d,
                    "active": bool(self.enabled[d, c]),
                    "base_fee_millisatoshi": int(self.fee_base_msat[d, c]),
                    "fee_per_millionth": int(self.fee_ppm[d, c]),
                    "delay": int(self.cltv_delta[d, c]),
                    "htlc_minimum_msat": int(self.htlc_min_msat[d, c]),
                    "htlc_maximum_msat": int(self.htlc_max_msat[d, c]),
                })
        return out


def _scids_from(buf, off, scid_off) -> np.ndarray:
    raw = native.gather_fields(buf, off, scid_off, 8).astype(np.uint64)
    scid = np.zeros(len(off), np.uint64)
    for b in range(8):
        scid = (scid << np.uint64(8)) | raw[:, b]
    return scid


def from_store(idx: StoreIndex, default_capacity_sat: int = 0) -> Gossmap:
    """Build the graph from a (verified) store in one vectorized pass.
    The reference rebuilds its gossmap by mmap-scanning the same file
    (common/gossmap.c:749); capacities come from the chain backend there —
    until ours lands, default_capacity_sat (0 = unknown) is used."""
    alive = idx.select(idx.alive())
    ca = alive.select(alive.types == wire.MSG_CHANNEL_ANNOUNCEMENT)
    cu = alive.select(alive.types == wire.MSG_CHANNEL_UPDATE)

    # --- channels + nodes from announcements
    n = len(ca)
    off = ca.offsets
    flen_raw = native.gather_fields(ca.buf, off, wire.CA_FLEN_OFFSET, 2)
    flen = (flen_raw[:, 0].astype(np.uint64) << 8) | flen_raw[:, 1]
    scids = _scids_from(ca.buf, off + flen, wire.CA_FLEN_OFFSET + 2 + 32)
    key_base = wire.CA_FLEN_OFFSET + 2 + flen + 40
    node1_ids = native.gather_fields(ca.buf, off + key_base, 0, 33)
    node2_ids = native.gather_fields(ca.buf, off + key_base, 33, 33)

    order = np.argsort(scids, kind="stable")
    scids, node1_ids, node2_ids = scids[order], node1_ids[order], node2_ids[order]
    # deduplicate scids (later records win — store append order)
    keep = np.ones(n, bool)
    if n:
        keep[:-1] = scids[:-1] != scids[1:]
    scids, node1_ids, node2_ids = scids[keep], node1_ids[keep], node2_ids[keep]
    n = len(scids)

    all_ids = np.concatenate([node1_ids, node2_ids]) if n else \
        np.zeros((0, 33), np.uint8)
    uniq, inverse = np.unique(all_ids.view([("k", "V33")]).reshape(-1),
                              return_inverse=True)
    node_ids = uniq.view(np.uint8).reshape(-1, 33)
    node1 = inverse[:n].astype(np.int32)
    node2 = inverse[n:].astype(np.int32)

    # --- per-direction updates
    enabled = np.zeros((2, n), bool)
    cltv = np.zeros((2, n), np.uint16)
    hmin = np.zeros((2, n), np.uint64)
    hmax = np.zeros((2, n), np.uint64)
    base = np.zeros((2, n), np.uint32)
    ppm = np.zeros((2, n), np.uint32)
    ts = np.zeros((2, n), np.uint32)
    m = len(cu)
    if m:
        offu = cu.offsets
        u_scid = _scids_from(cu.buf, offu, wire.CU_SCID_OFFSET)
        u_ts = native.gather_fields(cu.buf, offu, wire.CU_SCID_OFFSET + 8, 4)
        u_ts = ((u_ts[:, 0].astype(np.uint32) << 24)
                | (u_ts[:, 1].astype(np.uint32) << 16)
                | (u_ts[:, 2].astype(np.uint32) << 8) | u_ts[:, 3])
        fl = native.gather_fields(cu.buf, offu, wire.CU_FLAGS_OFFSET, 2)
        mflags, cflags = fl[:, 0], fl[:, 1]
        direction = (cflags & 1).astype(np.int8)
        disabled = (cflags & 2) != 0
        body = native.gather_fields(cu.buf, offu, wire.CU_FLAGS_OFFSET + 2, 18)

        def be(a, o, w):
            v = np.zeros(len(a), np.uint64)
            for b in range(w):
                v = (v << np.uint64(8)) | a[:, o + b]
            return v

        u_cltv = be(body, 0, 2)
        u_hmin = be(body, 2, 8)
        u_base = be(body, 10, 4)
        u_ppm = be(body, 14, 4)
        # htlc_maximum_msat is optional (message_flags bit 0 + length);
        # gathering it unconditionally would read past short legacy
        # records (gather_fields is an unchecked memcpy)
        u_hmax = np.zeros(m, np.uint64)
        has_max = ((mflags & 1) != 0) & (
            cu.lengths >= wire.CU_FLAGS_OFFSET + 2 + 26)
        li = np.nonzero(has_max)[0]
        if len(li):
            maxb = native.gather_fields(
                cu.buf, offu[li], wire.CU_FLAGS_OFFSET + 2 + 18, 8)
            u_hmax[li] = be(maxb, 0, 8)

        pos = np.searchsorted(scids, u_scid)
        pos_c = np.clip(pos, 0, max(0, n - 1))
        found = (pos < n) & (scids[pos_c] == u_scid) if n else \
            np.zeros(m, bool)
        # keep the NEWEST update per (channel, direction) — vectorized:
        # sort by (chan, dir, ts) and take the last row of each group
        fi = np.nonzero(found)[0]
        if len(fi):
            key = pos_c[fi].astype(np.int64) * 2 + direction[fi]
            order = np.lexsort((u_ts[fi], key))
            ordered, okey = fi[order], key[order]
            last = np.ones(len(ordered), bool)
            last[:-1] = okey[:-1] != okey[1:]
            sel = ordered[last]
            c, d = pos_c[sel], direction[sel]
            ts[d, c] = u_ts[sel]
            enabled[d, c] = ~disabled[sel]
            cltv[d, c] = u_cltv[sel]
            hmin[d, c] = u_hmin[sel]
            hmax[d, c] = u_hmax[sel]
            base[d, c] = u_base[sel]
            ppm[d, c] = u_ppm[sel]

    g = Gossmap(
        node_ids=node_ids, scids=scids, node1=node1, node2=node2,
        capacity_sat=np.full(n, default_capacity_sat, np.float32),
        enabled=enabled, cltv_delta=cltv, htlc_min_msat=hmin,
        htlc_max_msat=hmax, fee_base_msat=base, fee_ppm=ppm, timestamps=ts,
    )
    g._build_adjacency()
    return g
