"""Synthetic gossip-network generator (tests + benchmarks + loadgen).

Produces a spec-valid gossip_store with n_channels channel_announcements
(4 real ECDSA sigs each), 2 channel_updates per channel and one
node_announcement per node — the same shape of workload as the reference's
"million channels project" store used by tools/bench-gossipd.sh.

Signing runs on-device in bulk (ecdsa_sign_simple_kernel); hashing at
generation time uses hashlib so test data is independent of the JAX SHA
kernel under test.

Mainnet scale: generation STREAMS — messages are built, signed, and
appended to the store in bounded chunks (``chunk`` messages at a time),
so memory stays flat no matter the graph size.  The CLI's ``--mainnet``
preset generates a ~60k-node / ~250k-channel store (the LN topology
snapshot scale the GNN-benchmarking literature works from); ``--scale``
cuts a proportional slice of the preset for smoke tests::

    python -m lightning_tpu.gossip.synth /tmp/mainnet.gs --mainnet
    python -m lightning_tpu.gossip.synth /tmp/slice.gs --mainnet --scale 0.01

The heavyweight crypto imports (jax, the sign kernels) load lazily, so
``sign=False`` graph generation — routing/topology workloads — never
pays them.
"""
from __future__ import annotations

import hashlib

import numpy as np

from . import wire
from .store import StoreWriter

SIGN_BUCKET = 1 << 12  # production/bench default; tests pass a small one

# the --mainnet preset: current-mainnet-shaped topology scale
MAINNET_CHANNELS = 250_000
MAINNET_NODES = 60_000
DEFAULT_CHUNK = 16384


def _sha256d(b: bytes) -> bytes:
    return hashlib.sha256(hashlib.sha256(b).digest()).digest()


def _rand_scalars(rng: np.random.Generator, n: int) -> list[int]:
    from ..crypto import ref_python as ref

    return [int.from_bytes(rng.bytes(32), "big") % (ref.N - 1) + 1
            for _ in range(n)]


def _sign_bulk(hashes: list[bytes], keys: list[int], rng,
               bucket: int = SIGN_BUCKET) -> np.ndarray:
    """Batched device sign → (N, 64) compact sigs."""
    import jax.numpy as jnp

    from ..crypto import field as F
    from ..crypto import secp256k1 as S

    N = len(hashes)
    out = np.empty((N, 64), np.uint8)
    kern = S._jit_sign_simple()   # cached: re-wrapping loses the traces
    for start in range(0, N, bucket):
        end = min(start + bucket, N)
        B = bucket
        zs = np.zeros((B, F.NLIMBS), np.uint32)
        ds = np.zeros((B, F.NLIMBS), np.uint32)
        ks = np.zeros((B, F.NLIMBS), np.uint32)
        for i in range(start, end):
            zs[i - start] = F.int_to_limbs(int.from_bytes(hashes[i], "big"))
            ds[i - start] = F.int_to_limbs(keys[i])
            ks[i - start] = F.int_to_limbs(int.from_bytes(rng.bytes(32), "big") % (F.N_INT - 1) + 1)
        r, s, ok = kern(jnp.asarray(zs), jnp.asarray(ds), jnp.asarray(ks))
        assert bool(np.asarray(ok)[: end - start].all())
        out[start:end, :32] = F.to_bytes_be(np.asarray(r))[: end - start]
        out[start:end, 32:] = F.to_bytes_be(np.asarray(s))[: end - start]
    return out


def make_signed_batch(n: int, rng: np.random.Generator | None = None):
    """n signed channel_update-sized messages for kernel-only benches.
    Returns (rows, n_blocks, sigs, pubs): rows are sha-padded signed
    regions in the (n, MAX_BLOCKS*64) layout verify_items consumes."""
    from ..crypto import field as F
    from ..crypto import secp256k1 as S
    from ..utils import native
    from .verify import MAX_BLOCKS

    rng = rng or np.random.default_rng(0)
    keys = _rand_scalars(rng, n)
    pubs = S.derive_pubkeys(
        np.stack([F.int_to_limbs(k) for k in keys]).astype(np.uint32))
    msg_len = 130           # typical channel_update signed-region size
    raw = rng.integers(0, 256, n * msg_len).astype(np.uint8)
    offs = (np.arange(n, dtype=np.int64) * msg_len)
    lens = np.full(n, msg_len, np.int64)
    rows, nb = native.sha256_pack(raw, offs, lens, MAX_BLOCKS)
    hashes = [_sha256d(raw[i * msg_len:(i + 1) * msg_len].tobytes())
              for i in range(n)]
    sigs = _sign_bulk(hashes, keys, rng, min(SIGN_BUCKET, max(64, n)))
    return rows, nb, sigs, np.asarray(pubs)


def _scid_for(i: int) -> int:
    return (500000 + i // 2016) << 40 | (i % 2016) << 16 | 0


def make_network_store(
    path: str,
    n_channels: int,
    n_nodes: int | None = None,
    updates_per_channel: int = 2,
    node_announcements: bool = True,
    seed: int = 7,
    sign_bucket: int = SIGN_BUCKET,
    sign: bool = True,
    chunk: int = DEFAULT_CHUNK,
):
    """Generate and write a synthetic gossip store; returns counts.

    Streaming: messages are built, signed, and appended in chunks of
    ``chunk`` messages, so peak memory is O(chunk + n_nodes) no matter
    the graph size — a --mainnet store generates flat at a few tens of
    MB instead of materializing ~700k message buffers.

    sign=False writes zero signatures and derives pubkeys host-side —
    right for graph/routing tests and topology benches that never verify
    (no device kernels touched at all)."""
    from ..crypto import ref_python as ref

    rng = np.random.default_rng(seed)
    n_nodes = n_nodes or max(2, n_channels // 8)
    seckeys = _rand_scalars(rng, n_nodes)
    if sign:
        from ..crypto import field as F
        from ..crypto import secp256k1 as S

        pubs = S.derive_pubkeys(
            np.stack([F.int_to_limbs(k) for k in seckeys]).astype(np.uint32)
        )
        pub_bytes = [bytes(p) for p in pubs]
    else:
        pub_bytes = [ref.pubkey_serialize(ref.pubkey_create(k))
                     for k in seckeys]

    # channel endpoints; BOLT7: node_id_1 is the lexically lesser key
    a = rng.integers(0, n_nodes, n_channels)
    b = (a + 1 + rng.integers(0, n_nodes - 1, n_channels)) % n_nodes
    swap = np.array([pub_bytes[x] > pub_bytes[y] for x, y in zip(a, b)])
    n1 = np.where(swap, b, a)
    n2 = np.where(swap, a, b)
    chunk = max(1, chunk)

    n_cu = 0
    n_na = 0
    with StoreWriter(path) as w:

        def _write(msgs: list, ts0: int) -> None:
            w.append_many([bytes(m) for m in msgs],
                          [ts0 + k for k in range(len(msgs))])

        # --- channel_announcements: build, hash, bulk-sign, patch,
        # append — one bounded chunk at a time
        for start in range(0, n_channels, chunk):
            end = min(start + chunk, n_channels)
            ca_msgs = []
            for i in range(start, end):
                ca = wire.ChannelAnnouncement(
                    short_channel_id=_scid_for(i),
                    node_id_1=pub_bytes[n1[i]],
                    node_id_2=pub_bytes[n2[i]],
                    bitcoin_key_1=pub_bytes[n1[i]],
                    bitcoin_key_2=pub_bytes[n2[i]],
                )
                ca_msgs.append(bytearray(ca.serialize()))
            if sign:
                ca_hashes = [_sha256d(bytes(m[wire.CA_SIGNED_OFFSET:]))
                             for m in ca_msgs]
                sig_jobs_h, sig_jobs_k, patch = [], [], []
                for i in range(start, end):
                    for j, signer in enumerate((n1[i], n2[i],
                                                n1[i], n2[i])):
                        sig_jobs_h.append(ca_hashes[i - start])
                        sig_jobs_k.append(seckeys[signer])
                        patch.append((i - start, wire.CA_SIG_OFFSETS[j]))
                sigs = _sign_bulk(sig_jobs_h, sig_jobs_k, rng, sign_bucket)
                for (i, off), sig in zip(patch, sigs):
                    ca_msgs[i][off: off + 64] = bytes(sig)
            _write(ca_msgs, 1700000000 + start)

        # --- channel_updates, chunked over messages
        cu_msgs, cu_hashes, cu_keys = [], [], []

        def _flush_cu() -> None:
            nonlocal cu_msgs, cu_hashes, cu_keys, n_cu
            if not cu_msgs:
                return
            if sign:
                sigs = _sign_bulk(cu_hashes, cu_keys, rng, sign_bucket)
                for m, sig in zip(cu_msgs, sigs):
                    m[wire.CU_SIG_OFFSET: wire.CU_SIG_OFFSET + 64] = \
                        bytes(sig)
            _write(cu_msgs, 1700000000 + n_cu)
            n_cu += len(cu_msgs)
            cu_msgs, cu_hashes, cu_keys = [], [], []

        for i in range(n_channels):
            for d in range(updates_per_channel):
                direction = d % 2
                cu = wire.ChannelUpdate(
                    short_channel_id=_scid_for(i),
                    timestamp=1700000000 + i,
                    channel_flags=direction,
                    htlc_maximum_msat=int(rng.integers(1, 1 << 40)),
                    fee_base_msat=int(rng.integers(0, 5000)),
                    fee_proportional_millionths=int(rng.integers(0, 10000)),
                )
                m = bytearray(cu.serialize())
                cu_msgs.append(m)
                if sign:
                    cu_hashes.append(
                        _sha256d(bytes(m[wire.CU_SIGNED_OFFSET:])))
                    cu_keys.append(
                        seckeys[(n1 if direction == 0 else n2)[i]])
            if len(cu_msgs) >= chunk:
                _flush_cu()
        _flush_cu()

        # --- node_announcements, chunked over messages
        if node_announcements:
            for start in range(0, n_nodes, chunk):
                end = min(start + chunk, n_nodes)
                na_msgs, na_hashes, na_keys = [], [], []
                for i in range(start, end):
                    na = wire.NodeAnnouncement(
                        timestamp=1700000000 + i,
                        node_id=pub_bytes[i],
                        alias=(b"tpu-node-%06d" % i).ljust(32, b"\x00"),
                    )
                    m = bytearray(na.serialize())
                    na_msgs.append(m)
                    if sign:
                        na_hashes.append(
                            _sha256d(bytes(m[wire.NA_SIGNED_OFFSET:])))
                        na_keys.append(seckeys[i])
                if sign:
                    sigs = _sign_bulk(na_hashes, na_keys, rng, sign_bucket)
                    for m, sig in zip(na_msgs, sigs):
                        m[wire.NA_SIG_OFFSET: wire.NA_SIG_OFFSET + 64] = \
                            bytes(sig)
                _write(na_msgs, 1700000000 + start)
                n_na += len(na_msgs)

    return {
        "channels": n_channels,
        "nodes": n_nodes,
        "channel_updates": n_cu,
        "node_announcements": n_na,
        "sigs": 4 * n_channels + n_cu + n_na,
        "seckeys": seckeys,
    }


def main(argv=None) -> int:
    """CLI front-end: stream a synthetic gossip_store to disk."""
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python -m lightning_tpu.gossip.synth",
        description="Generate a synthetic (spec-valid) gossip_store. "
        "Generation streams in bounded chunks, so --mainnet-sized "
        "stores build with flat memory.")
    ap.add_argument("path", help="output gossip_store file")
    ap.add_argument("--channels", type=int, default=1000)
    ap.add_argument("--nodes", type=int, default=0,
                    help="0 = channels // 8")
    ap.add_argument("--updates-per-channel", type=int, default=2)
    ap.add_argument("--mainnet", action="store_true",
                    help=f"preset: ~{MAINNET_NODES} nodes / "
                    f"~{MAINNET_CHANNELS} channels")
    ap.add_argument("--scale", type=float, default=1.0,
                    help="scale factor applied to the --mainnet preset "
                    "(smoke-test slices, e.g. --scale 0.01)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--no-sign", action="store_true",
                    help="zero signatures, host-derived pubkeys (no jax)")
    ap.add_argument("--sign-bucket", type=int, default=SIGN_BUCKET)
    ap.add_argument("--chunk", type=int, default=DEFAULT_CHUNK,
                    help="messages generated+written per streamed chunk")
    args = ap.parse_args(argv)
    channels, nodes = args.channels, args.nodes or None
    if args.mainnet:
        channels = max(1, int(MAINNET_CHANNELS * args.scale))
        nodes = max(2, int(MAINNET_NODES * args.scale))
    info = make_network_store(
        args.path, channels, nodes,
        updates_per_channel=args.updates_per_channel, seed=args.seed,
        sign=not args.no_sign, sign_bucket=args.sign_bucket,
        chunk=args.chunk)
    info.pop("seckeys")
    print(json.dumps(info))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
