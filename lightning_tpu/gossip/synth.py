"""Synthetic gossip-network generator (tests + benchmarks).

Produces a spec-valid gossip_store with n_channels channel_announcements
(4 real ECDSA sigs each), 2 channel_updates per channel and one
node_announcement per node — the same shape of workload as the reference's
"million channels project" store used by tools/bench-gossipd.sh.

Signing runs on-device in bulk (ecdsa_sign_simple_kernel); hashing at
generation time uses hashlib so test data is independent of the JAX SHA
kernel under test.
"""
from __future__ import annotations

import hashlib

import numpy as np

import jax.numpy as jnp

from ..crypto import field as F
from ..crypto import secp256k1 as S
from . import wire
from .store import StoreWriter

SIGN_BUCKET = 1 << 12  # production/bench default; tests pass a small one


def _sha256d(b: bytes) -> bytes:
    return hashlib.sha256(hashlib.sha256(b).digest()).digest()


def _rand_scalars(rng: np.random.Generator, n: int) -> list[int]:
    return [int.from_bytes(rng.bytes(32), "big") % (F.N_INT - 1) + 1 for _ in range(n)]


def _sign_bulk(hashes: list[bytes], keys: list[int], rng,
               bucket: int = SIGN_BUCKET) -> np.ndarray:
    """Batched device sign → (N, 64) compact sigs."""
    N = len(hashes)
    out = np.empty((N, 64), np.uint8)
    kern = S._jit_sign_simple()   # cached: re-wrapping loses the traces
    for start in range(0, N, bucket):
        end = min(start + bucket, N)
        B = bucket
        zs = np.zeros((B, F.NLIMBS), np.uint32)
        ds = np.zeros((B, F.NLIMBS), np.uint32)
        ks = np.zeros((B, F.NLIMBS), np.uint32)
        for i in range(start, end):
            zs[i - start] = F.int_to_limbs(int.from_bytes(hashes[i], "big"))
            ds[i - start] = F.int_to_limbs(keys[i])
            ks[i - start] = F.int_to_limbs(int.from_bytes(rng.bytes(32), "big") % (F.N_INT - 1) + 1)
        r, s, ok = kern(jnp.asarray(zs), jnp.asarray(ds), jnp.asarray(ks))
        assert bool(np.asarray(ok)[: end - start].all())
        out[start:end, :32] = F.to_bytes_be(np.asarray(r))[: end - start]
        out[start:end, 32:] = F.to_bytes_be(np.asarray(s))[: end - start]
    return out


def make_signed_batch(n: int, rng: np.random.Generator | None = None):
    """n signed channel_update-sized messages for kernel-only benches.
    Returns (rows, n_blocks, sigs, pubs): rows are sha-padded signed
    regions in the (n, MAX_BLOCKS*64) layout verify_items consumes."""
    from ..utils import native
    from .verify import MAX_BLOCKS

    rng = rng or np.random.default_rng(0)
    keys = _rand_scalars(rng, n)
    pubs = S.derive_pubkeys(
        np.stack([F.int_to_limbs(k) for k in keys]).astype(np.uint32))
    msg_len = 130           # typical channel_update signed-region size
    raw = rng.integers(0, 256, n * msg_len).astype(np.uint8)
    offs = (np.arange(n, dtype=np.int64) * msg_len)
    lens = np.full(n, msg_len, np.int64)
    rows, nb = native.sha256_pack(raw, offs, lens, MAX_BLOCKS)
    hashes = [_sha256d(raw[i * msg_len:(i + 1) * msg_len].tobytes())
              for i in range(n)]
    sigs = _sign_bulk(hashes, keys, rng, min(SIGN_BUCKET, max(64, n)))
    return rows, nb, sigs, np.asarray(pubs)


def make_network_store(
    path: str,
    n_channels: int,
    n_nodes: int | None = None,
    updates_per_channel: int = 2,
    node_announcements: bool = True,
    seed: int = 7,
    sign_bucket: int = SIGN_BUCKET,
    sign: bool = True,
):
    """Generate and write a synthetic gossip store; returns counts.

    sign=False writes zero signatures and derives pubkeys host-side —
    right for graph/routing tests and topology benches that never verify
    (no device kernels touched at all)."""
    from ..crypto import ref_python as ref

    rng = np.random.default_rng(seed)
    n_nodes = n_nodes or max(2, n_channels // 8)
    seckeys = _rand_scalars(rng, n_nodes)
    if sign:
        pubs = S.derive_pubkeys(
            np.stack([F.int_to_limbs(k) for k in seckeys]).astype(np.uint32)
        )
        pub_bytes = [bytes(p) for p in pubs]
    else:
        pub_bytes = [ref.pubkey_serialize(ref.pubkey_create(k))
                     for k in seckeys]

    # channel endpoints; BOLT7: node_id_1 is the lexically lesser key
    a = rng.integers(0, n_nodes, n_channels)
    b = (a + 1 + rng.integers(0, n_nodes - 1, n_channels)) % n_nodes
    swap = np.array([pub_bytes[x] > pub_bytes[y] for x, y in zip(a, b)])
    n1 = np.where(swap, b, a)
    n2 = np.where(swap, a, b)

    # --- channel_announcements: build unsigned, hash, bulk-sign, patch
    ca_msgs = []
    for i in range(n_channels):
        scid = (500000 + i // 2016) << 40 | (i % 2016) << 16 | 0
        ca = wire.ChannelAnnouncement(
            short_channel_id=int(scid),
            node_id_1=pub_bytes[n1[i]],
            node_id_2=pub_bytes[n2[i]],
            bitcoin_key_1=pub_bytes[n1[i]],
            bitcoin_key_2=pub_bytes[n2[i]],
        )
        ca_msgs.append(bytearray(ca.serialize()))
    if sign:
        ca_hashes = [_sha256d(bytes(m[wire.CA_SIGNED_OFFSET:]))
                     for m in ca_msgs]
        sig_jobs_h, sig_jobs_k, patch = [], [], []
        for i in range(n_channels):
            for j, signer in enumerate((n1[i], n2[i], n1[i], n2[i])):
                sig_jobs_h.append(ca_hashes[i])
                sig_jobs_k.append(seckeys[signer])
                patch.append((i, wire.CA_SIG_OFFSETS[j]))
        sigs = _sign_bulk(sig_jobs_h, sig_jobs_k, rng, sign_bucket)
        for (i, off), sig in zip(patch, sigs):
            ca_msgs[i][off : off + 64] = bytes(sig)

    # --- channel_updates
    cu_msgs, cu_hashes, cu_keys = [], [], []
    for i in range(n_channels):
        for d in range(updates_per_channel):
            direction = d % 2
            cu = wire.ChannelUpdate(
                short_channel_id=int((500000 + i // 2016) << 40 | (i % 2016) << 16),
                timestamp=1700000000 + i,
                channel_flags=direction,
                htlc_maximum_msat=int(rng.integers(1, 1 << 40)),
                fee_base_msat=int(rng.integers(0, 5000)),
                fee_proportional_millionths=int(rng.integers(0, 10000)),
            )
            m = bytearray(cu.serialize())
            cu_msgs.append(m)
            cu_hashes.append(_sha256d(bytes(m[wire.CU_SIGNED_OFFSET:])))
            cu_keys.append(seckeys[(n1 if direction == 0 else n2)[i]])
    if cu_msgs and sign:
        sigs = _sign_bulk(cu_hashes, cu_keys, rng, sign_bucket)
        for m, sig in zip(cu_msgs, sigs):
            m[wire.CU_SIG_OFFSET : wire.CU_SIG_OFFSET + 64] = bytes(sig)

    # --- node_announcements
    na_msgs = []
    if node_announcements:
        na_hashes, na_keys = [], []
        for i in range(n_nodes):
            na = wire.NodeAnnouncement(
                timestamp=1700000000 + i,
                node_id=pub_bytes[i],
                alias=(b"tpu-node-%06d" % i).ljust(32, b"\x00"),
            )
            m = bytearray(na.serialize())
            na_msgs.append(m)
            na_hashes.append(_sha256d(bytes(m[wire.NA_SIGNED_OFFSET:])))
            na_keys.append(seckeys[i])
        if sign:
            sigs = _sign_bulk(na_hashes, na_keys, rng, sign_bucket)
            for m, sig in zip(na_msgs, sigs):
                m[wire.NA_SIG_OFFSET : wire.NA_SIG_OFFSET + 64] = bytes(sig)

    with StoreWriter(path) as w:
        w.append_many([bytes(m) for m in ca_msgs],
                      [1700000000 + i for i in range(len(ca_msgs))])
        w.append_many([bytes(m) for m in cu_msgs],
                      [1700000000 + i for i in range(len(cu_msgs))])
        w.append_many([bytes(m) for m in na_msgs],
                      [1700000000 + i for i in range(len(na_msgs))])
    return {
        "channels": n_channels,
        "nodes": n_nodes,
        "channel_updates": len(cu_msgs),
        "node_announcements": len(na_msgs),
        "sigs": 4 * n_channels + len(cu_msgs) + len(na_msgs),
        "seckeys": seckeys,
    }
