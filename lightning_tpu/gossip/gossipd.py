"""The gossipd service: live peer gossip in/out around the batched
verifier.

Parity targets:
 - connectd/multiplex.c:829 `handle_gossip_in` (peer bytes → gossipd)
   and :599 `wake_gossip` (store → every peer, filtered) — here the
   ingest's on_accept fan-out plus gossip_timestamp_filter state.
 - gossipd/queries.c + connectd/queries.c: query_channel_range /
   query_short_channel_ids / reply handling (BOLT#7 encoding type 0).
 - gossipd/seeker.c:28: the catch-up state machine a fresh node runs
   against its first peer (filter → range query → scid query → ingest).

The crypto-heavy part stays in GossipIngest (batched TPU kernels);
this module is the host-side shell that makes it a daemon.
"""
from __future__ import annotations

import asyncio
import logging
import struct
import time

from ..obs import journey as _journey
from ..wire import messages as M
from . import wire as gwire
from .ingest import GossipIngest, _journey_entity

log = logging.getLogger("lightning_tpu.gossipd")

ENC_UNCOMPRESSED = 0


def encode_scids(scids: list[int]) -> bytes:
    return bytes([ENC_UNCOMPRESSED]) + b"".join(
        s.to_bytes(8, "big") for s in sorted(scids))


def decode_scids(blob: bytes) -> list[int]:
    if not blob:
        return []
    if blob[0] != ENC_UNCOMPRESSED:
        raise ValueError(f"unsupported scid encoding {blob[0]}")
    body = blob[1:]
    if len(body) % 8:
        raise ValueError("ragged encoded_short_ids")
    return [int.from_bytes(body[i:i + 8], "big")
            for i in range(0, len(body), 8)]


def scid_block(scid: int) -> int:
    return scid >> 40


class Gossipd:
    """Attach to a LightningNode: ingest, answer queries, stream out."""

    def __init__(self, node, store_path: str,
                 chain_hash: bytes = gwire.MAINNET_CHAIN_HASH,
                 utxo_check=None, flush_ms: float = 2.0,
                 flush_size: int = 256, bucket: int | None = None,
                 gossmap_ref: dict | None = None):
        from . import verify as _gv

        bucket = bucket if bucket is not None else _gv.DEFAULT_BUCKET
        self.node = node
        self.chain_hash = chain_hash
        # mutable {'map': Gossmap|None} holder (the daemon's routing
        # view): accepted channel_updates are folded into it live so
        # the route planes refresh instead of waiting for a reload
        self.gossmap_ref = gossmap_ref or {}
        self.ingest = GossipIngest(
            store_path, utxo_check=utxo_check, flush_ms=flush_ms,
            flush_size=flush_size, bucket=bucket,
            on_accept=self._on_accept,
            # own-node/own-channel gossip sheds LAST under overload
            # (doc/overload.md priority classes)
            own_node_id=getattr(node, "node_id", None))
        # raw message cache for query replies (the store is the durable
        # copy; this is the reference's gossmap offset index role)
        self.msgs: dict[int, dict] = {}       # scid -> {ca, cu0, cu1}
        self.node_msgs: dict[bytes, bytes] = {}  # node_id -> na raw
        self.filters: dict[bytes, tuple[int, int]] = {}  # peer -> (t0, dt)
        self._synced: dict[bytes, asyncio.Event] = {}
        # we sent THEM a filter — keyed by the Peer OBJECT (WeakSet):
        # filter state is per-connection (BOLT#7), so a reconnect's new
        # Peer must get a fresh filter or the remote streams us nothing
        import weakref

        self._filter_sent = weakref.WeakSet()

        for t in (gwire.MSG_CHANNEL_ANNOUNCEMENT,
                  gwire.MSG_NODE_ANNOUNCEMENT, gwire.MSG_CHANNEL_UPDATE):
            node.raw_handlers[t] = self._on_gossip
        node.register(M.QueryChannelRange, self._on_query_range)
        node.register(M.ReplyChannelRange, self._on_reply_range)
        node.register(M.QueryShortChannelIds, self._on_query_scids)
        node.register(M.ReplyShortChannelIdsEnd, self._on_scids_end)
        node.register(M.GossipTimestampFilter, self._on_filter)

    def load_existing(self, store_path: str, verify: bool = False,
                      idx=None) -> int:
        """Rebuild the in-memory view from an existing store (restart
        path; common/gossmap.c:749's load role).  verify=True replays
        every signature through the batched kernels first
        (tools/bench-gossipd.sh's store_load workload).  idx: an
        already-loaded StoreIndex for this path (saves the second scan
        when the daemon also built a Gossmap from the same file)."""
        import os

        from . import store as gstore

        if idx is None:
            if not os.path.exists(store_path):
                return 0
            if os.path.getsize(store_path) <= 1:
                return 0  # fresh store: version byte only (just created)
            idx = gstore.load_store(store_path)  # corrupt store DOES raise
        alive = idx.select(idx.alive())
        if verify:
            from . import verify as gverify

            res = gverify.verify_store(alive)
            if not (res.ca_valid.all() and res.cu_valid.all()
                    and res.na_valid.all()):
                raise ValueError("store failed replay verification")
        n = 0
        for i in range(len(alive)):
            raw = alive.message(i)
            try:
                p = gwire.parse_gossip(raw)
            except Exception:
                continue
            t = gwire.msg_type(raw)
            ing = self.ingest
            if t == gwire.MSG_CHANNEL_ANNOUNCEMENT:
                ing.channels[p.short_channel_id] = (p.node_id_1, p.node_id_2)
                ing._channeled_nodes.update((p.node_id_1, p.node_id_2))
                self.msgs.setdefault(p.short_channel_id, {})["ca"] = raw
            elif t == gwire.MSG_CHANNEL_UPDATE:
                key = (p.short_channel_id, p.direction)
                if ing.updates.get(key, -1) < p.timestamp:
                    ing.updates[key] = p.timestamp
                    self.msgs.setdefault(p.short_channel_id, {})[
                        f"cu{p.direction}"] = raw
            else:
                if ing.nodes.get(p.node_id, -1) < p.timestamp:
                    ing.nodes[p.node_id] = p.timestamp
                    self.node_msgs[p.node_id] = raw
            n += 1
        return n

    def start(self) -> None:
        self.ingest.start()

    async def close(self) -> None:
        await self.ingest.close()

    # -- ingest + fan-out -------------------------------------------------

    async def _on_gossip(self, peer, raw: bytes) -> None:
        # backpressure propagation (doc/overload.md): while the ingest
        # backlog is saturated this await pauses THIS peer's read pump
        # (the pump awaits its raw handler), so we stop draining the
        # socket and TCP pushes back on the sender instead of us
        # buffering its storm.  Bounded per message and released for
        # every peer together when the backlog drains — no peer
        # starves, and messages that still arrive saturated are shed
        # by priority inside submit(), metered, never silently lost.
        if _journey.enabled():
            # the journey's first hop: the raw bytes reached gossipd
            # from a peer.  Parse only when sampling is on — the hop
            # must not tax the disabled-by-default hot path.
            try:
                p = gwire.parse_gossip(raw)
            except Exception:
                p = None
            if p is not None:
                jk, jkey = _journey_entity(gwire.msg_type(raw), p)
                _journey.hop("recv", jk, jkey, outcome="ok")
        await self.ingest.wait_capacity()
        await self.ingest.submit(raw, source=peer.node_id)

    def _on_accept(self, raw: bytes, source) -> None:
        t = gwire.msg_type(raw)
        p = gwire.parse_gossip(raw)
        if t == gwire.MSG_CHANNEL_ANNOUNCEMENT:
            self.msgs.setdefault(p.short_channel_id, {})["ca"] = raw
        elif t == gwire.MSG_CHANNEL_UPDATE:
            self.msgs.setdefault(p.short_channel_id, {})[
                f"cu{p.direction}"] = raw
            g = self.gossmap_ref.get("map")
            if g is not None:
                t0 = time.perf_counter()
                g.apply_channel_update(
                    p.short_channel_id, p.direction,
                    timestamp=p.timestamp,
                    disabled=bool(p.channel_flags & 2),
                    cltv_delta=p.cltv_expiry_delta,
                    htlc_min_msat=p.htlc_minimum_msat,
                    htlc_max_msat=p.htlc_maximum_msat,
                    fee_base_msat=p.fee_base_msat,
                    fee_ppm=p.fee_proportional_millionths)
                _journey.hop("fold", "channel", p.short_channel_id,
                             outcome="ok",
                             service_s=time.perf_counter() - t0,
                             direction=int(p.direction))
        else:
            self.node_msgs[p.node_id] = raw
        ts = getattr(p, "timestamp", int(time.time()))
        loop = asyncio.get_event_loop()
        for peer in list(self.node.peers.values()):
            if peer.node_id == source or not peer.connected:
                continue
            flt = self.filters.get(peer.node_id)
            if flt is None:
                continue      # peer never asked for gossip
            t0, dt = flt
            if t == gwire.MSG_CHANNEL_ANNOUNCEMENT or t0 <= ts < t0 + dt:
                loop.create_task(peer.send_raw(raw))

    # -- query answering (gossipd/queries.c) ------------------------------

    async def _on_query_range(self, peer, msg: M.QueryChannelRange) -> None:
        lo = msg.first_blocknum
        hi = lo + msg.number_of_blocks
        scids = [s for s in self.ingest.channels
                 if lo <= scid_block(s) < hi]
        await peer.send(M.ReplyChannelRange(
            chain_hash=msg.chain_hash, first_blocknum=lo,
            number_of_blocks=msg.number_of_blocks, sync_complete=1,
            encoded_short_ids=encode_scids(scids)))

    async def _on_query_scids(self, peer,
                              msg: M.QueryShortChannelIds) -> None:
        try:
            scids = decode_scids(msg.encoded_short_ids)
        except ValueError:
            await peer.send(M.ReplyShortChannelIdsEnd(
                chain_hash=msg.chain_hash, full_information=0))
            return
        full = 1
        sent_nodes: set[bytes] = set()
        for s in scids:
            entry = self.msgs.get(s)
            if entry is None or "ca" not in entry:
                full = 0
                continue
            await peer.send_raw(entry["ca"])
            for k in ("cu0", "cu1"):
                if k in entry:
                    await peer.send_raw(entry[k])
            for nid in self.ingest.channels.get(s, ()):
                na = self.node_msgs.get(nid)
                if na is not None and nid not in sent_nodes:
                    sent_nodes.add(nid)
                    await peer.send_raw(na)
        await peer.send(M.ReplyShortChannelIdsEnd(
            chain_hash=msg.chain_hash, full_information=full))

    async def _on_filter(self, peer, msg: M.GossipTimestampFilter) -> None:
        self.filters[peer.node_id] = (msg.first_timestamp,
                                      msg.timestamp_range)
        # backfill everything already accepted that matches (connectd's
        # store-streaming role, simplified to the in-memory index)
        t0, dt = msg.first_timestamp, msg.timestamp_range
        for entry in list(self.msgs.values()):
            ca = entry.get("ca")
            if ca is not None:
                await peer.send_raw(ca)
            for k in ("cu0", "cu1"):
                raw = entry.get(k)
                if raw is None:
                    continue
                ts = gwire.parse_gossip(raw).timestamp
                if t0 <= ts < t0 + dt:
                    await peer.send_raw(raw)
        for raw in list(self.node_msgs.values()):
            ts = gwire.parse_gossip(raw).timestamp
            if t0 <= ts < t0 + dt:
                await peer.send_raw(raw)

    # -- seeker (gossipd/seeker.c) ----------------------------------------

    async def sync_with(self, peer, first_blocknum: int = 0,
                        number_of_blocks: int = 0xFFFFFFFF,
                        backfill_from: int = 0,
                        timeout: float = 30.0) -> int:
        """Catch up from one peer: set a timestamp filter, learn its scid
        set, fetch the ones we don't know.  Returns #scids requested.

        The filter is sent once per peer connection: re-sending it makes
        the peer re-backfill its whole store (our _on_filter streams the
        full backlog), which a periodic seeker probe must not trigger."""
        evt = asyncio.Event()
        self._synced[peer.node_id] = evt
        self._requested = 0
        if peer not in self._filter_sent:
            self._filter_sent.add(peer)
            await peer.send(M.GossipTimestampFilter(
                chain_hash=self.chain_hash, first_timestamp=backfill_from,
                timestamp_range=0xFFFFFFFF))
        await peer.send(M.QueryChannelRange(
            chain_hash=self.chain_hash, first_blocknum=first_blocknum,
            number_of_blocks=number_of_blocks))
        await asyncio.wait_for(evt.wait(), timeout)
        return self._requested

    async def _on_reply_range(self, peer, msg: M.ReplyChannelRange) -> None:
        try:
            theirs = decode_scids(msg.encoded_short_ids)
        except ValueError:
            return
        missing = [s for s in theirs if s not in self.ingest.channels]
        self._requested = len(missing)
        if missing:
            await peer.send(M.QueryShortChannelIds(
                chain_hash=msg.chain_hash,
                encoded_short_ids=encode_scids(missing)))
        elif msg.sync_complete:
            evt = self._synced.get(peer.node_id)
            if evt is not None:
                evt.set()

    async def _on_scids_end(self, peer,
                            msg: M.ReplyShortChannelIdsEnd) -> None:
        evt = self._synced.get(peer.node_id)
        if evt is not None:
            evt.set()
