"""BOLT#12 offers service: offer registry, invoice_request handling, and
the payer-side fetchinvoice flow — all over onion messages.

Functional parity targets: plugins/offers.c (offer bookkeeping +
onion-message subscriptions), plugins/offers_invreq_hook.c (validate an
incoming invoice_request, mint the bolt12 invoice), and
plugins/fetchinvoice.c (send invoice_request, await invoice over the
reply path) — re-designed as in-loop services on LightningNode rather
than separate plugin processes.
"""
from __future__ import annotations

import asyncio
import hashlib
import hmac
import logging
import os
import time

from ..bolt import blindedpath as BP
from ..bolt import bolt12 as B12
from ..bolt import onion_message as OM
from ..crypto import ref_python as ref
from ..wire import messages as M

log = logging.getLogger("lightning_tpu.offers")


class OffersError(Exception):
    pass


class OnionMessenger:
    """Per-node onion-message router (lightningd/onion_message.c role).

    Relays Forward results to the connected peer named by the encrypted
    data; delivers Final results to content handlers registered by
    services (offers, fetchinvoice, ...).
    """

    def __init__(self, node, privkey: int):
        self.node = node
        self.privkey = privkey
        self.handlers: dict[int, object] = {}   # content tlv -> async fn
        node.register(M.OnionMessage, self._on_message)

    def register_content(self, tlv_type: int, handler) -> None:
        """async handler(final: OM.Final) for messages whose content
        includes tlv_type."""
        self.handlers[tlv_type] = handler

    async def _on_message(self, peer, msg: M.OnionMessage) -> None:
        try:
            result = OM.process(self.privkey, msg)
        except Exception as e:
            # onion messages are fire-and-forget: drop, never error back
            log.debug("onion message dropped: %s", e)
            return
        if isinstance(result, OM.Forward):
            nxt = None
            if result.next_node_id is not None:
                nxt = self.node.peers.get(result.next_node_id)
            if nxt is None:
                log.debug("onion message: next hop not connected")
                return
            await nxt.send(result.message)
            return
        for t, v in result.tlvs.items():
            h = self.handlers.get(t)
            if h is not None:
                try:
                    await h(result)
                except Exception:
                    # a malformed content field must not tear down the
                    # peer connection that happened to carry it
                    log.exception("onion message handler failed")
                return
        log.debug("onion message final had no handled content")

    async def send(self, path: BP.BlindedPath,
                   content: dict[int, bytes]) -> bool:
        """Send an onion message along `path`; the first hop must be a
        connected peer (or us — then we self-process the peel)."""
        msg = OM.create(path, content)
        first = path.first_node_id
        if first == self.node.node_id:
            # we are the introduction point (reply paths often start at
            # the recipient's own peer): peel our hop and forward
            result = OM.process(self.privkey, msg)
            if isinstance(result, OM.Final):
                for t in result.tlvs:
                    h = self.handlers.get(t)
                    if h is not None:
                        await h(result)
                        return True
                return False
            nxt = self.node.peers.get(result.next_node_id)
            if nxt is None:
                return False
            await nxt.send(result.message)
            return True
        peer = self.node.peers.get(first)
        if peer is None:
            return False
        await peer.send(msg)
        return True


class OfferRegistry:
    """Our published offers (wallet/wallet.c offers table semantics)."""

    def __init__(self, db=None):
        self.db = db
        self.offers: dict[bytes, dict] = {}   # offer_id -> row
        if db is not None:
            for r in db.conn.execute(
                    "SELECT offer_id, label, bolt12, status, single_use"
                    " FROM offers").fetchall():
                self.offers[bytes(r[0])] = {
                    "offer_id": bytes(r[0]), "label": r[1], "bolt12": r[2],
                    "status": r[3], "single_use": bool(r[4])}

    def add(self, offer: B12.Offer, label: str = "",
            single_use: bool = False) -> dict:
        oid = offer.offer_id()
        if oid in self.offers:
            return self.offers[oid]
        row = {"offer_id": oid, "label": label, "bolt12": offer.encode(),
               "status": "active", "single_use": single_use}
        self.offers[oid] = row
        if self.db is not None:
            with self.db.transaction():
                self.db.conn.execute(
                    "INSERT OR IGNORE INTO offers"
                    " (offer_id, label, bolt12, status, single_use)"
                    " VALUES (?,?,?,?,?)",
                    (oid, label, row["bolt12"], "active", int(single_use)))
        return row

    def disable(self, offer_id: bytes) -> None:
        self._set_status(offer_id, "disabled")

    def enable(self, offer_id: bytes) -> None:
        """Re-arm a disabled offer (json_enableoffer; a used single-use
        offer stays used)."""
        row = self.offers.get(offer_id)
        if row is not None and row["status"] == "used":
            raise OffersError("single-use offer was already paid")
        self._set_status(offer_id, "active")

    def _set_status(self, offer_id: bytes, status: str) -> None:
        row = self.offers.get(offer_id)
        if row is None:
            raise OffersError("unknown offer")
        row["status"] = status
        if self.db is not None:
            with self.db.transaction():
                self.db.conn.execute(
                    "UPDATE offers SET status=? WHERE offer_id=?",
                    (status, offer_id))

    def active(self, offer_id: bytes) -> B12.Offer | None:
        row = self.offers.get(offer_id)
        if row is None or row["status"] != "active":
            return None
        return B12.Offer.decode(row["bolt12"])

    def listoffers(self) -> list[dict]:
        return [{**r, "offer_id": r["offer_id"].hex()}
                for r in self.offers.values()]


class OffersService:
    """Issuer side: answer invoice_requests against our offers."""

    def __init__(self, messenger: OnionMessenger, registry: OfferRegistry,
                 invoices, node_seckey: int):
        self.messenger = messenger
        self.registry = registry
        self.invoices = invoices            # InvoiceRegistry
        self.node_seckey = node_seckey
        # recurrence draft: (offer_id, payer_id) -> {"next": counter,
        # "basetime": unix} — one chain per payer per recurring offer,
        # persisted beside the invoices so a restart cannot strand a
        # subscription mid-chain
        self._recurrences: dict[tuple[bytes, bytes], dict] = \
            self._load_recurrences()
        messenger.register_content(OM.INVOICE_REQUEST, self._on_invreq)
        invoices.on_bolt12_paid = self.on_invoice_paid

    def _load_recurrences(self) -> dict:
        import json

        db = getattr(self.invoices, "db", None)
        if db is None:
            return {}
        raw = db.get_var("bolt12_recurrences")
        if not raw:
            return {}
        return {(bytes.fromhex(i["offer_id"]),
                 bytes.fromhex(i["payer_id"])):
                {"next": i["next"], "basetime": i["basetime"]}
                for i in json.loads(raw)}

    def _save_recurrences(self) -> None:
        import json

        db = getattr(self.invoices, "db", None)
        if db is None:
            return
        db.set_var("bolt12_recurrences", json.dumps(
            [{"offer_id": oid.hex(), "payer_id": pid.hex(),
              "next": st["next"], "basetime": st["basetime"]}
             for (oid, pid), st in self._recurrences.items()]))

    def _drop_recurrence(self, key: tuple[bytes, bytes]) -> None:
        self._recurrences.pop(key, None)
        self._save_recurrences()

    def create_offer(self, description: str, amount_msat: int | None = None,
                     issuer: str | None = None, label: str = "",
                     quantity_max: int | None = None,
                     absolute_expiry: int | None = None,
                     single_use: bool = False,
                     recurrence: tuple[int, int] | None = None,
                     recurrence_limit: int | None = None) -> dict:
        offer = B12.Offer(
            description=description, amount_msat=amount_msat, issuer=issuer,
            recurrence=recurrence, recurrence_limit=recurrence_limit,
            issuer_id=ref.pubkey_serialize(
                ref.pubkey_create(self.node_seckey)),
            quantity_max=quantity_max, absolute_expiry=absolute_expiry)
        return self.registry.add(offer, label=label, single_use=single_use)

    async def _on_invreq(self, final: OM.Final) -> None:
        raw = final.tlvs[OM.INVOICE_REQUEST]
        try:
            invreq = B12.InvoiceRequest.parse(raw)
        except Exception:
            return
        if final.reply_path is None:
            return                          # nowhere to answer
        if invreq.recurrence_cancel:
            # payer stops the recurrence.  The cancel must be held to
            # the SAME bar as a mint: a valid signature binds it to
            # payer_id (else anyone could kill a victim's chain with
            # an unsigned invreq), and the offer must be a known
            # recurring one.  Ack = the EXACT sentinel the payer
            # matches on.
            from ..wire.codec import write_tlv_stream

            async def _reply(text: bytes) -> None:
                await self.messenger.send(
                    final.reply_path,
                    {OM.INVOICE_ERROR: write_tlv_stream({5: text})})

            if not invreq.check_signature():
                await _reply(b"bad invoice_request signature")
                return
            offer = self.registry.active(invreq.offer.offer_id())
            if offer is None or offer.recurrence is None:
                await _reply(b"unknown or non-recurring offer")
                return
            key = (invreq.offer.offer_id(), invreq.payer_id)
            self._drop_recurrence(key)
            await _reply(b"recurrence cancelled")
            return
        try:
            inv = self.make_invoice(invreq)
            await self.messenger.send(
                final.reply_path, {OM.INVOICE: inv.serialize()})
        except B12.Bolt12Error as e:
            from ..wire.codec import write_tlv_stream

            # tlv_invoice_error: 5 = error (utf8), 1 = erroneous_field
            err = write_tlv_stream({5: str(e).encode()})
            await self.messenger.send(
                final.reply_path, {OM.INVOICE_ERROR: err})

    def make_invoice(self, invreq: B12.InvoiceRequest) -> B12.Invoice12:
        offer = self.registry.active(invreq.offer.offer_id())
        if offer is None:
            raise B12.Bolt12Error("unknown or inactive offer")
        invreq.validate_against(offer)
        amount = invreq.amount_msat
        if amount is None:
            amount = (offer.amount_msat or 0) * (invreq.quantity or 1)
        basetime = None
        if offer.recurrence is not None:
            # one monotone chain per payer: the counter must be exactly
            # the next expected one (BOLT-recurrence #12 semantics,
            # paywindow arithmetic simplified to strict succession)
            key = (offer.offer_id(), invreq.payer_id)
            st = self._recurrences.get(key)
            expect = st["next"] if st is not None else 0
            # accept the NEXT period, or a RETRY of the last minted
            # one — the reply can be lost in flight, and without retry
            # idempotence one dropped onion message would wedge the
            # chain forever (payer stuck at N, issuer at N+1)
            if invreq.recurrence_counter not in (expect,
                                                 max(expect - 1, 0)):
                raise B12.Bolt12Error(
                    f"expected recurrence_counter {expect}")
            if st is None:
                st = {"next": 0, "basetime": int(time.time())}
                self._recurrences[key] = st
            st["next"] = max(st["next"], invreq.recurrence_counter + 1)
            self._save_recurrences()
            basetime = st["basetime"]
        return self.mint_for_invreq(invreq, amount,
                                    local_offer_id=invreq.offer.offer_id(),
                                    recurrence_basetime=basetime)

    def mint_for_invreq(self, invreq: B12.InvoiceRequest, amount: int,
                        label: str | None = None,
                        local_offer_id: bytes | None = None,
                        recurrence_basetime: int | None = None
                        ) -> B12.Invoice12:
        """Mint + register a bolt12 invoice answering an invoice_request
        — shared by the onion-message responder (make_invoice, offer
        known+validated) and `sendinvoice` (out-of-band invreq with no
        published offer; lightningd/invoicerequest.c json_sendinvoice)."""
        preimage = os.urandom(32)
        payment_hash = hashlib.sha256(preimage).digest()
        node_id = ref.pubkey_serialize(ref.pubkey_create(self.node_seckey))
        # BOLT#12 has no payment_secret TLV; the secret that stops an
        # on-route node from claiming the preimage is the blinded path's
        # path_id — a cookie only we can derive (lightningd/invoice.c
        # invoice_path_id semantics).  Even a direct payment rides a
        # 1-hop blinded path whose introduction point is us.
        cookie = self.invoice_path_id(payment_hash)
        path = BP.create_path([node_id], [BP.EncryptedData(path_id=cookie)])
        inv = B12.Invoice12(
            invreq=invreq, payment_hash=payment_hash, amount_msat=amount,
            node_id=node_id, created_at=int(time.time()),
            recurrence_basetime=recurrence_basetime,
            paths=[path],
            blindedpay=[(0, 0, self.invoices.min_final_cltv, 0,
                         21_000_000 * 100_000_000 * 1000, b"")])
        inv.sign(self.node_seckey)
        label = label or f"bolt12-{payment_hash[:8].hex()}"
        self.invoices.create_bolt12(label, amount, payment_hash, preimage,
                                    inv.encode(), local_offer_id,
                                    payment_secret=cookie)
        return inv

    def invoice_path_id(self, payment_hash: bytes) -> bytes:
        """Deterministic path_id cookie for a bolt12 invoice we mint."""
        key = self.node_seckey.to_bytes(32, "big")
        return hmac.new(key, b"bolt12-invoice-path" + payment_hash,
                        hashlib.sha256).digest()

    def on_invoice_paid(self, local_offer_id: bytes) -> None:
        """Called when a bolt12 invoice settles: single-use offers are
        spent by PAYMENT, not by the (costless) invoice_request."""
        row = self.registry.offers.get(local_offer_id)
        if row is not None and row["single_use"] \
                and row["status"] == "active":
            # 'used' is terminal — distinguishable from an operator
            # disable so enableoffer can never re-arm a spent offer
            self.registry._set_status(local_offer_id, "used")


class RecurrenceCancelled(Exception):
    """The issuer confirmed a recurrence_cancel (expected outcome of
    cancelrecurringinvoice — not a failure)."""


class FetchInvoice:
    """Payer side: request an invoice for an offer and await it."""

    def __init__(self, messenger: OnionMessenger, node_seckey: int,
                 db=None):
        self.messenger = messenger
        self.node_seckey = node_seckey
        self.db = db
        self.pending: dict[bytes, asyncio.Future] = {}  # path_id cookie
        # recurrence draft: label -> {"payer_key", "next", "start"} —
        # successive periods must reuse ONE payer_id so the issuer can
        # link them into a chain; persisted so a restart can continue
        # (or cancel) a subscription
        self.recurrences: dict[str, dict] = {}
        if db is not None:
            import json

            raw = db.get_var("bolt12_payer_recurrences")
            if raw:
                self.recurrences = {
                    lb: {"payer_key": int(st["payer_key"], 16),
                         "next": st["next"], "start": st["start"]}
                    for lb, st in json.loads(raw).items()}
        messenger.register_content(OM.INVOICE, self._on_invoice)
        messenger.register_content(OM.INVOICE_ERROR, self._on_error)

    def _persist_recurrences(self) -> None:
        if self.db is None:
            return
        import json

        self.db.set_var("bolt12_payer_recurrences", json.dumps(
            {lb: {"payer_key": format(st["payer_key"], "x"),
                  "next": st["next"], "start": st["start"]}
             for lb, st in self.recurrences.items()}))

    async def fetch(self, offer: B12.Offer, amount_msat: int | None = None,
                    quantity: int | None = None,
                    payer_note: str | None = None,
                    timeout: float = 30.0,
                    recurrence_counter: int | None = None,
                    recurrence_start: int | None = None,
                    recurrence_label: str | None = None,
                    recurrence_cancel: bool = False) -> B12.Invoice12:
        if offer.currency is not None:
            # no fiat converter on board (reference: currencyrate plugin)
            raise OffersError(
                f"offer denominated in {offer.currency}: unsupported")
        if not offer.paths and offer.issuer_id is None:
            raise OffersError("offer names no issuer_id and no paths")
        if offer.recurrence is not None and recurrence_counter is None \
                and not recurrence_cancel:
            raise OffersError(
                "recurring offer: pass recurrence_counter + "
                "recurrence_label")
        if recurrence_counter is not None and recurrence_label is None:
            raise OffersError("recurrence_counter needs recurrence_label")
        if recurrence_label is not None:
            # ONE payer key per label, across every period of the chain
            st = self.recurrences.get(recurrence_label)
            if st is None and recurrence_cancel:
                # a cancel under a fresh random payer_id would hit
                # a chain the issuer has never seen — and falsely
                # report success while the real chain lives on
                raise OffersError(
                    f"unknown recurrence_label "
                    f"{recurrence_label!r}: nothing to cancel")
            expected = st["next"] if st is not None else 0
            # next period or a retry of the last one (lost replies)
            if recurrence_counter is not None and not recurrence_cancel \
                    and recurrence_counter not in (expected,
                                                   max(expected - 1, 0)):
                raise OffersError(
                    f"label {recurrence_label!r} expects "
                    f"recurrence_counter {expected}")
            if st is None:
                # state exists in memory from here; persisted only once
                # a fetch SUCCEEDS, so a failed first attempt leaves no
                # phantom label whose cancel would falsely succeed
                st = {"payer_key":
                      int.from_bytes(os.urandom(32), "big") % ref.N or 1,
                      "next": 0, "start": recurrence_start}
                self.recurrences[recurrence_label] = st
            if recurrence_start is None:
                recurrence_start = st.get("start")
            payer_key = st["payer_key"]
        else:
            payer_key = int.from_bytes(os.urandom(32), "big") % ref.N or 1
        invreq = B12.InvoiceRequest(
            offer=offer, metadata=os.urandom(16),
            payer_id=ref.pubkey_serialize(ref.pubkey_create(payer_key)),
            amount_msat=amount_msat, quantity=quantity,
            payer_note=payer_note,
            recurrence_counter=recurrence_counter,
            recurrence_start=recurrence_start,
            recurrence_cancel=recurrence_cancel)
        invreq.sign(payer_key)

        dest = offer.paths[0] if offer.paths else _direct_path(
            offer.issuer_id)
        cookie = os.urandom(32)
        reply = OM.reply_path_for(
            [_reply_intro(offer, dest), self.messenger.node.node_id], cookie)
        fut = asyncio.get_running_loop().create_future()
        self.pending[cookie] = fut
        try:
            ok = await self.messenger.send(
                dest, {OM.INVOICE_REQUEST: invreq.serialize(),
                       OM.REPLY_PATH: reply.serialize()})
            if not ok:
                raise OffersError("issuer not reachable")
            result = await asyncio.wait_for(fut, timeout)
        finally:
            self.pending.pop(cookie, None)
        if isinstance(result, bytes):
            text = result.decode(errors='replace')
            if recurrence_cancel and text == "recurrence cancelled":
                # the issuer's ack for a recurrence_cancel IS an
                # invoice_error (no invoice exists to return) — exact
                # sentinel match, so no other failure text can pass
                self.recurrences.pop(recurrence_label or "", None)
                self._persist_recurrences()
                raise RecurrenceCancelled(text)
            raise OffersError(f"invoice_error: {text}")
        inv: B12.Invoice12 = result
        inv.validate_against(invreq)
        if recurrence_label is not None and recurrence_counter is not None:
            st = self.recurrences[recurrence_label]
            st["next"] = max(st["next"], recurrence_counter + 1)
            self._persist_recurrences()
        return inv

    async def _on_invoice(self, final: OM.Final) -> None:
        fut = self.pending.get(final.path_id or b"")
        if fut is None or fut.done():
            return
        try:
            fut.set_result(B12.Invoice12.parse(final.tlvs[OM.INVOICE]))
        except Exception as e:
            fut.set_exception(OffersError(f"bad invoice: {e}"))

    async def _on_error(self, final: OM.Final) -> None:
        fut = self.pending.get(final.path_id or b"")
        if fut is None or fut.done():
            return
        from ..wire.codec import read_tlv_stream

        tlvs = read_tlv_stream(final.tlvs[OM.INVOICE_ERROR])
        fut.set_result(tlvs.get(5, b"unknown error"))


def attach_offers_commands(rpc, service: OffersService,
                           fetcher: FetchInvoice, registry: OfferRegistry,
                           invoices) -> None:
    """RPC surface: offer/listoffers/disableoffer/fetchinvoice plus the
    bolt11 invoice/listinvoices/decode commands (doc/schemas names)."""

    async def offer(amount: str | int, description: str,
                    issuer: str | None = None, label: str = "",
                    quantity_max: int | None = None,
                    single_use: bool = False,
                    recurrence: str | None = None,
                    recurrence_limit: int | None = None) -> dict:
        amt = None if amount in ("any", None) else int(amount)
        rec = None
        if recurrence is not None:
            # reference syntax: "<number><unit>" with unit in
            # seconds/days/months/years (e.g. "1month", "12H" unsupported)
            import re as _re

            m = _re.fullmatch(r"(\d+)\s*(second|day|month|year)s?",
                              str(recurrence).strip().lower())
            if not m:
                raise OffersError(
                    f"unparseable recurrence {recurrence!r} "
                    "(use e.g. '1month', '2weeks'→'14days')")
            unit = {"second": 0, "day": 1, "month": 2,
                    "year": 3}[m.group(2)]
            rec = (unit, int(m.group(1)))
        row = service.create_offer(
            description, amount_msat=amt, issuer=issuer, label=label,
            quantity_max=quantity_max, single_use=single_use,
            recurrence=rec, recurrence_limit=recurrence_limit)
        return {"offer_id": row["offer_id"].hex(), "bolt12": row["bolt12"],
                "active": row["status"] == "active",
                "single_use": row["single_use"], "used": False}

    async def listoffers() -> dict:
        return {"offers": registry.listoffers()}

    async def disableoffer(offer_id: str) -> dict:
        registry.disable(bytes.fromhex(offer_id))
        return {"offer_id": offer_id, "active": False}

    async def enableoffer(offer_id: str) -> dict:
        registry.enable(bytes.fromhex(offer_id))
        return {"offer_id": offer_id, "active": True}

    async def fetchinvoice(offer: str, amount_msat: int | None = None,
                           quantity: int | None = None,
                           payer_note: str | None = None,
                           timeout: float = 30.0,
                           recurrence_counter: int | None = None,
                           recurrence_start: int | None = None,
                           recurrence_label: str | None = None) -> dict:
        if "@" in offer and not offer.startswith("lno1"):
            # BIP-353 payment address: resolve user@domain → lno offer
            # (reference: fetchinvoice's bip353 path)
            from ..utils import bip353

            uri = await bip353.resolve(offer)
            if "lno" not in uri:
                raise OffersError(
                    f"{offer} resolves to no BOLT#12 offer "
                    f"(has: {sorted(set(uri) - {'dns_name'})})")
            offer = uri["lno"]
        o = B12.Offer.decode(offer)
        inv = await fetcher.fetch(
            o, amount_msat=amount_msat, quantity=quantity,
            payer_note=payer_note, timeout=timeout,
            recurrence_counter=recurrence_counter,
            recurrence_start=recurrence_start,
            recurrence_label=recurrence_label)
        out = {"invoice": inv.encode(),
               "amount_msat": inv.amount_msat,
               "payment_hash": inv.payment_hash.hex(),
               "expires_at": inv.expires_at}
        if inv.recurrence_basetime is not None and o.recurrence is not None:
            # period index = start offset + counter (draft semantics:
            # recurrence_start shifts which period the chain began at)
            nxt = (recurrence_counter or 0) + 1
            out["next_period"] = {
                "counter": nxt,
                "starttime": inv.recurrence_basetime
                + ((recurrence_start or 0) + nxt)
                * B12.RECURRENCE_UNIT_SECONDS.get(
                    o.recurrence[0], 1) * o.recurrence[1]}
        return out

    async def invoice(amount_msat, label: str, description: str,
                      expiry: int = 3600) -> dict:
        amt = None if amount_msat in ("any", None) else int(amount_msat)
        rec = invoices.create(label, amt, description, expiry=expiry)
        return {"bolt11": rec.bolt11,
                "payment_hash": rec.payment_hash.hex(),
                "payment_secret": rec.payment_secret.hex(),
                "expires_at": rec.expires_at}

    async def listinvoices(label: str | None = None) -> dict:
        return {"invoices": invoices.listinvoices(label)}

    async def waitinvoice(label: str, timeout: int = 600) -> dict:
        rec = await invoices.wait_for_label(label, timeout=timeout)
        return rec.to_rpc()

    async def waitanyinvoice(lastpay_index: int = 0,
                             timeout: int = 600) -> dict:
        rec = await invoices.wait_any(int(lastpay_index),
                                      timeout=timeout)
        return rec.to_rpc()

    async def delinvoice(label: str, status: str) -> dict:
        # status is required: an unguarded delete races concurrent
        # payment and could erase a just-paid record (invoices.c)
        return invoices.delete(label, status)

    async def decode(string: str) -> dict:
        """bolt11 / bolt12 decoder (plugins/offers.c decode command)."""
        from ..bolt import bolt11 as B11

        s = string.strip()
        if s.startswith("lno1"):
            o = B12.Offer.decode(s)
            return {"type": "bolt12 offer", "valid": True,
                    "offer_id": o.offer_id().hex(),
                    "offer_description": o.description,
                    "offer_amount_msat": o.amount_msat,
                    "offer_issuer_id":
                        o.issuer_id.hex() if o.issuer_id else None}
        if s.startswith("lni1"):
            inv = B12.Invoice12.decode(s)
            return {"type": "bolt12 invoice", "valid": True,
                    "invoice_payment_hash": inv.payment_hash.hex(),
                    "invoice_amount_msat": inv.amount_msat,
                    "invoice_created_at": inv.created_at}
        inv11 = B11.decode(s, check_sig=True)
        return {"type": "bolt11 invoice", "valid": True,
                "currency": inv11.currency,
                "payee": inv11.payee.hex() if inv11.payee else None,
                "amount_msat": inv11.amount_msat,
                "description": inv11.description,
                "payment_hash": inv11.payment_hash.hex(),
                "min_final_cltv_expiry": inv11.min_final_cltv}

    async def decodepay(bolt11: str) -> dict:
        """Deprecated alias kept for pre-`decode` tooling."""
        return await decode(bolt11)

    async def createinvoice(invstring: str, label: str,
                            preimage: str) -> dict:
        """Sign a caller-constructed BOLT11 with the node key and save
        it under `label` with the caller's preimage
        (lightningd/invoice.c json_createinvoice)."""
        import hashlib as _h

        from ..bolt import bolt11 as B11

        pre = bytes.fromhex(preimage)
        inv = B11.decode(invstring, check_sig=False)
        if inv.payment_hash != _h.sha256(pre).digest():
            raise ValueError("preimage does not match payment_hash")
        signed = B11.encode(inv, invoices.node_seckey)
        rec = invoices.create_bolt12(
            label, inv.amount_msat, inv.payment_hash, pre, signed,
            payment_secret=inv.payment_secret or b"",
            expiry=max(1, inv.expires_at - int(__import__("time").time())))
        return rec.to_rpc()

    async def signinvoice(invstring: str) -> dict:
        """Re-sign someone else's BOLT11 with OUR node key
        (lightningd/invoice.c json_signinvoice)."""
        from ..bolt import bolt11 as B11

        inv = B11.decode(invstring, check_sig=False)
        inv.payee = None   # recovered from the new signature
        return {"bolt11": B11.encode(inv, invoices.node_seckey)}

    # -- invoice_request family (reference: lightningd/invoicerequest.c
    #    + plugins/offers: withdraw/refund flows) ------------------------
    _invreqs: dict[bytes, dict] = {}

    async def invoicerequest(amount_msat: int, description: str,
                             issuer: str | None = None,
                             label: str | None = None,
                             single_use: bool = True) -> dict:
        import hashlib as _h
        import os as _os

        from ..crypto import ref_python as _ref

        payer_key = invoices.node_seckey
        o = B12.Offer(description=description, issuer=issuer)
        r = B12.InvoiceRequest(
            offer=o, metadata=_os.urandom(16),
            payer_id=_ref.pubkey_serialize(_ref.pubkey_create(payer_key)),
            amount_msat=int(amount_msat))
        r.sign(payer_key)
        bolt12 = r.encode()
        invreq_id = _h.sha256(r.serialize()).digest()
        _invreqs[invreq_id] = {
            "invreq_id": invreq_id.hex(), "bolt12": bolt12,
            "active": True, "single_use": bool(single_use),
            "used": False, "label": label}
        return dict(_invreqs[invreq_id])

    async def listinvoicerequests(invreq_id: str | None = None) -> dict:
        rows = list(_invreqs.values())
        if invreq_id is not None:
            rows = [r for r in rows if r["invreq_id"] == invreq_id]
        return {"invoicerequests": rows}

    async def disableinvoicerequest(invreq_id: str) -> dict:
        row = _invreqs.get(bytes.fromhex(invreq_id))
        if row is None:
            raise KeyError(f"unknown invoice_request {invreq_id}")
        row["active"] = False
        return dict(row)

    async def sendinvoice(invreq: str, label: str,
                          amount_msat: int | None = None) -> dict:
        """Answer an out-of-band invoice_request with a freshly minted
        BOLT12 invoice registered under `label` (the reference also
        pushes it over onion messaging when the invreq carries a reply
        path; an out-of-band string has none)."""
        _hrp, raw = B12.decode_string(invreq)
        req = B12.InvoiceRequest.parse(raw)
        if not req.check_signature():
            raise B12.Bolt12Error("bad invoice_request signature")
        amount = int(amount_msat) if amount_msat is not None \
            else req.amount_msat
        if amount is None:
            raise B12.Bolt12Error("invoice_request carries no amount")
        inv12 = service.mint_for_invreq(req, amount, label=label)
        return {"bolt12": inv12.encode(),
                "payment_hash": inv12.payment_hash.hex(),
                "amount_msat": inv12.amount_msat, "label": label}

    async def cancelrecurringinvoice(offer: str, recurrence_counter: int,
                                     recurrence_label: str,
                                     recurrence_start: int | None = None,
                                     payer_note: str | None = None,
                                     timeout: float = 30.0) -> dict:
        """Stop a recurrence: sends invreq_recurrence_cancel in place
        of an invoice_request (cancelrecurringinvoice.json); the
        issuer's confirmation arrives as a recognizable invoice_error
        and the label's chain state is dropped."""
        o = B12.Offer.decode(offer)
        try:
            await fetcher.fetch(
                o, payer_note=payer_note, timeout=timeout,
                recurrence_counter=int(recurrence_counter),
                recurrence_start=recurrence_start,
                recurrence_label=recurrence_label,
                recurrence_cancel=True)
        except RecurrenceCancelled as e:
            return {"cancelled": True, "detail": str(e)}
        raise OffersError(
            "issuer answered the cancel with an invoice, not an ack")

    async def injectonionmessage(message: str, path_key: str) -> dict:
        """Process a fully-built onion message as if it had arrived
        from a peer (lightningd/onion_message.c
        json_injectonionmessage — the xpay/BOLT12 dispatch door)."""
        msg = M.OnionMessage(path_key=bytes.fromhex(path_key),
                             onionmsg=bytes.fromhex(message))
        await service.messenger._on_message(None, msg)
        return {}

    async def sendonionmessage(node_ids: list,
                               content: dict | None = None) -> dict:
        """Send an onion message along a path of node ids; the first
        must be a connected peer (lightningd/onion_message.c
        json_sendonionmessage/injectonionmessage role)."""
        path_nodes = [bytes.fromhex(n) for n in node_ids]
        bp = BP.create_path(path_nodes,
                            [BP.EncryptedData() for _ in path_nodes])
        tlvs = {int(k): bytes.fromhex(v)
                for k, v in (content or {}).items()}
        ok = await service.messenger.send(bp, tlvs)
        if not ok:
            raise OffersError("first hop not connected")
        return {"sent": True}

    for fn in (offer, listoffers, disableoffer, enableoffer,
               fetchinvoice, invoice,
               listinvoices, waitinvoice, waitanyinvoice, delinvoice,
               decode, createinvoice, signinvoice, invoicerequest,
               listinvoicerequests, disableinvoicerequest, sendinvoice,
               sendonionmessage, injectonionmessage,
               cancelrecurringinvoice):
        rpc.register(fn.__name__, fn)
    rpc.register("decodepay", decodepay, deprecated=True)


def _direct_path(issuer_id: bytes) -> BP.BlindedPath:
    """A single-hop 'blinded' path to a known issuer — used when the
    offer names an issuer_id rather than carrying blinded paths."""
    return BP.create_path([issuer_id], [BP.EncryptedData()])


def _reply_intro(offer: B12.Offer, dest: BP.BlindedPath) -> bytes:
    """The reply path's introduction node: the issuer itself (direct
    offers) — blinded-path offers would use the path's last real node,
    which only the issuer knows; it replaces the reply intro itself."""
    return offer.issuer_id if offer.issuer_id is not None \
        else dest.first_node_id
