"""MPP receive: HTLC sets that accumulate partial payments.

Functional parity target: lightningd/htlc_set.c — final-hop HTLCs
sharing a payment_hash whose onion claims total_msat > this part's
amount are HELD (not fulfilled, not failed) until the set sums to
total_msat, then ALL fulfill with the invoice preimage; a set that
does not complete within MPP_TIMEOUT fails every held part with
mpp_timeout (BOLT#4 failure code 23).

The registry is node-wide: parts may arrive over different channels.
Each held part carries async callbacks (fulfill/fail) supplied by the
channel loop that owns the HTLC, so completion can fan out to every
involved channel from whichever task completed the set.
"""
from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field

from .. import obs
from ..obs import journey as _journey

log = logging.getLogger("lightning_tpu.htlc_set")

_M_PARTS = obs.counter(
    "clntpu_htlc_set_parts_total",
    "MPP parts offered to the accumulator, by outcome",
    labelnames=("result",))
_M_TIMEOUTS = obs.counter(
    "clntpu_htlc_set_timeouts_total",
    "MPP sets that timed out before completing")
_M_OPEN = obs.gauge(
    "clntpu_htlc_set_open", "MPP sets currently accumulating parts")

MPP_TIMEOUT_SECONDS = 60
MPP_TIMEOUT = 23   # BOLT#4 mpp_timeout failure code (0x17)


@dataclass
class _Part:
    amount_msat: int
    fulfill: object       # async fn(preimage)
    fail: object          # async fn(failure_code)


@dataclass
class _Set:
    total_msat: int
    deadline: float
    parts: list = field(default_factory=list)

    @property
    def received(self) -> int:
        return sum(p.amount_msat for p in self.parts)


class HtlcSets:
    """Node-wide MPP accumulator tied to an InvoiceRegistry."""

    def __init__(self, invoices, timeout: float = MPP_TIMEOUT_SECONDS):
        self.invoices = invoices
        self.timeout = timeout
        self.sets: dict[bytes, _Set] = {}
        self._sweeper: asyncio.Task | None = None

    def _ensure_sweeper(self) -> None:
        if self._sweeper is None or self._sweeper.done():
            self._sweeper = asyncio.get_running_loop().create_task(
                self._sweep())

    async def _sweep(self) -> None:
        while self.sets:
            now = time.monotonic()
            for ph in [ph for ph, s in self.sets.items()
                       if now >= s.deadline]:
                await self._fail_set(ph)
            await asyncio.sleep(1.0)

    async def _fail_set(self, payment_hash: bytes) -> None:
        s = self.sets.pop(payment_hash, None)
        if s is None:
            return
        _M_TIMEOUTS.inc()
        _M_OPEN.set(len(self.sets))
        log.info("MPP set %s timed out with %d/%d msat",
                 payment_hash.hex()[:16], s.received, s.total_msat)
        for p in s.parts:
            try:
                await p.fail(MPP_TIMEOUT)
            except Exception:
                log.exception("failing MPP part")

    async def add_part(self, payment_hash: bytes, amount_msat: int,
                       payment_secret: bytes | None, total_msat: int,
                       fulfill, fail) -> str:
        """Register one partial HTLC.  Returns:
          "held"     — valid part, waiting for the rest
          "complete" — this part completed the set; every part's
                       fulfill callback (including this one's) has run
          "reject"   — not a valid part; caller fails the HTLC itself
        """
        result = await self._add_part(payment_hash, amount_msat,
                                      payment_secret, total_msat,
                                      fulfill, fail)
        _M_PARTS.labels(result).inc()
        _M_OPEN.set(len(self.sets))
        _journey.hop("htlc_part", "payment", payment_hash,
                     outcome=result, amount_msat=int(amount_msat),
                     total_msat=int(total_msat))
        return result

    async def _add_part(self, payment_hash: bytes, amount_msat: int,
                        payment_secret: bytes | None, total_msat: int,
                        fulfill, fail) -> str:
        rec = self.invoices.by_hash.get(payment_hash)
        if rec is None or rec.status != "unpaid":
            return "reject"
        if time.time() > rec.expires_at:
            return "reject"
        if rec.payment_secret and payment_secret != rec.payment_secret:
            return "reject"
        # BOLT#4: total_msat replaces amt for the invoice amount rules
        if rec.amount_msat is not None and not (
                rec.amount_msat <= total_msat <= 2 * rec.amount_msat):
            return "reject"

        s = self.sets.get(payment_hash)
        if s is None:
            s = _Set(total_msat=total_msat,
                     deadline=time.monotonic() + self.timeout)
            self.sets[payment_hash] = s
        elif s.total_msat != total_msat:
            return "reject"   # parts must agree on the total
        s.parts.append(_Part(amount_msat, fulfill, fail))

        if s.received >= s.total_msat:
            del self.sets[payment_hash]
            for p in s.parts:
                try:
                    await p.fulfill(rec.preimage)
                except Exception:
                    log.exception("fulfilling MPP part")
            self.invoices.settle(payment_hash, s.received)
            return "complete"
        self._ensure_sweeper()
        return "held"
