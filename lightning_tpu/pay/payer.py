"""The payment engine: decode → route → onion → HTLC → settle.

Parity target: the modern xpay path (plugins/xpay/xpay.c: route query →
onion build → injectpaymentonion, lightningd/pay.c:1074
send_payment_core) plus error-onion attribution
(common/onion_message parsing of BOLT#4 failure messages) and the
payments table (wallet_payment records, listpays surface).

The route source is pluggable: direct channel (single hop), an explicit
hop list, or a Gossmap+dijkstra query.  Failures unwrap the returned
error onion with the per-hop shared secrets so the erring node is
attributed (pay.c's payment_result path).
"""
from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field

from ..bolt import bolt11 as B11
from ..bolt import onion_payload as OP
from ..bolt import sphinx as SX
from ..wire import messages as M

log = logging.getLogger("lightning_tpu.pay")

# BOLT#4 failure codes we name in errors (subset; PERM=0x4000,
# NODE=0x2000, UPDATE=0x1000, BADONION=0x8000)
FAILURE_NAMES = {
    0x400F: "incorrect_or_unknown_payment_details",   # PERM|15
    0x1007: "temporary_channel_failure",              # UPDATE|7
    0x400A: "unknown_next_peer",                      # PERM|10
    0x4016: "invalid_onion_payload",                  # PERM|22
    0x2002: "temporary_node_failure",                 # NODE|2
}


class PayError(Exception):
    def __init__(self, message: str, code: int | None = None,
                 erring_index: int | None = None):
        super().__init__(message)
        self.code = code
        self.erring_index = erring_index


@dataclass
class PayResult:
    payment_hash: bytes
    preimage: bytes
    amount_msat: int
    amount_sent_msat: int
    parts: int = 1
    status: str = "complete"

    def to_rpc(self) -> dict:
        return {
            "payment_hash": self.payment_hash.hex(),
            "payment_preimage": self.preimage.hex(),
            "amount_msat": self.amount_msat,
            "amount_sent_msat": self.amount_sent_msat,
            "parts": self.parts,
            "status": self.status,
        }


@dataclass
class RouteStep:
    """One hop of a payment route: forward over `scid` to `node_id`,
    delivering amount_msat with cltv `delay` at that hop."""
    node_id: bytes
    scid: int
    amount_msat: int
    delay: int


def _steps_from_hops(hops, src_amount: int, src_delay: int,
                     blockheight: int) \
        -> tuple[list[RouteStep], int, int]:
    steps = [RouteStep(h.node_id, h.scid, h.amount_msat,
                       blockheight + h.delay) for h in hops]
    return steps, src_amount, blockheight + src_delay


def route_from_gossmap(g, source: bytes, dest: bytes, amount_msat: int,
                       final_cltv: int, blockheight: int = 0) \
        -> tuple[list[RouteStep], int, int]:
    """Route from `source` (our channel peer) to dest; also returns what
    we must deliver TO source (amount, cltv) so its own fee and delta
    are funded."""
    from ..routing import dijkstra as DJ

    hops, (src_amount, src_delay) = DJ.getroute(
        g, source, dest, amount_msat, final_cltv=final_cltv,
        with_source=True)
    return _steps_from_hops(hops, src_amount, src_delay, blockheight)


async def route_via(g, source: bytes, dest: bytes, amount_msat: int,
                    final_cltv: int, blockheight: int = 0, router=None) \
        -> tuple[list[RouteStep], int, int]:
    """route_from_gossmap, optionally through a batching RouteService
    (routing.device): concurrent payment route queries then coalesce
    into one device dispatch instead of serial host dijkstra runs."""
    if router is None:
        return route_from_gossmap(g, source, dest, amount_msat,
                                  final_cltv, blockheight)
    hops, (src_amount, src_delay) = await router.getroute(
        source, dest, amount_msat, final_cltv=final_cltv,
        with_source=True)
    return _steps_from_hops(hops, src_amount, src_delay, blockheight)


def build_payment_onion(route: list[RouteStep], payment_hash: bytes,
                        payment_secret: bytes | None, total_msat: int,
                        session_key: int):
    """Per-hop payloads: forwards carry the NEXT hop's amount/cltv/scid;
    the final hop carries payment_data (BOLT#4 payload semantics)."""
    payloads = []
    for i, step in enumerate(route):
        if i + 1 < len(route):
            nxt = route[i + 1]
            payloads.append(OP.HopPayload(
                nxt.amount_msat, nxt.delay,
                short_channel_id=nxt.scid))
        else:
            payloads.append(OP.HopPayload(
                step.amount_msat, step.delay,
                payment_secret=payment_secret,
                total_msat=total_msat))
    return OP.build_route_onion(
        [s.node_id for s in route], payloads, payment_hash,
        session_key=session_key)


def bolt12_final_payload(inv12, amount_msat: int, cltv: int,
                         total_msat: int | None = None):
    """Final-hop payload for paying a BOLT#12 invoice over its blinded
    path.  The invoice carries ≥1 blinded path whose tip is the payee;
    the payer copies the tip hop's ciphertext + the path key into the
    final onion payload so the recipient can recover its path_id cookie
    (which plays payment_secret's role — BOLT#4 blinded payments)."""
    if not inv12.paths or not inv12.paths[0].hops:
        raise PayError("bolt12 invoice has no blinded path")
    path = inv12.paths[0]
    if len(path.hops) != 1:
        # multi-hop blinded tails need in-flight path-key evolution at
        # each blinded hop; we pay the 1-hop (intro-point-is-payee)
        # shape every make_invoice mints
        raise PayError("only 1-hop blinded paths supported")
    return OP.HopPayload(
        amount_msat, cltv,
        encrypted_recipient_data=path.hops[0].encrypted_recipient_data,
        path_key=path.first_path_key,
        total_msat=total_msat or amount_msat)


async def pay_over_channel(ch, invoice_str: str, *,
                           amount_msat: int | None = None,
                           gossmap=None, source_node_id: bytes | None = None,
                           blockheight: int = 0, wallet=None,
                           session_key: int | None = None) -> PayResult:
    """Pay a BOLT#11 invoice whose first hop is the given Channeld.

    Route selection: direct if the channel peer IS the payee, else a
    gossmap query from the channel peer to the payee (we prepend the
    first hop ourselves since our own channel is not in the public map).
    """
    inv = B11.decode(invoice_str)
    if inv.amount_msat is None and amount_msat is None:
        raise PayError("invoice has no amount; amount_msat required")
    if inv.amount_msat is not None and amount_msat is not None \
            and amount_msat != inv.amount_msat:
        raise PayError("amount_msat conflicts with invoice amount")
    amount = inv.amount_msat or amount_msat
    if time.time() > inv.expires_at:
        raise PayError("invoice expired")

    final_cltv = blockheight + inv.min_final_cltv
    if ch.peer.node_id == inv.payee:
        route = [RouteStep(inv.payee, 0, amount, final_cltv)]
        amount_sent, first_cltv = amount, final_cltv
    else:
        if gossmap is None:
            raise PayError(f"no route: payee {inv.payee.hex()[:16]} is not "
                           "a direct peer and no gossip graph is loaded",
                           code=205)
        try:
            tail, src_amount, src_cltv = route_from_gossmap(
                gossmap, ch.peer.node_id, inv.payee, amount,
                inv.min_final_cltv, blockheight)
        except KeyError as e:
            raise PayError(f"no route: {e.args[0] if e.args else e}",
                           code=205) from e
        except Exception as e:
            from ..routing.dijkstra import NoRoute

            if isinstance(e, NoRoute):
                raise PayError(f"no route: {e}", code=205) from e
            raise
        # hop 0 of the onion is ch.peer itself (our unannounced channel
        # feeds the public route); we must deliver src_amount/src_cltv to
        # it so its forwarding fee and cltv_delta are funded
        route = [RouteStep(ch.peer.node_id, 0, src_amount, src_cltv)] + tail
        amount_sent, first_cltv = src_amount, src_cltv

    if session_key is None:
        session_key = SX.random_session_key()
    onion, secrets = build_payment_onion(
        route, inv.payment_hash, inv.payment_secret, amount, session_key)

    created = int(time.time())
    pay_id = _record_payment(wallet, inv, invoice_str, amount, amount_sent,
                             created)
    # ANY exit below must resolve the payments row — a row stuck at
    # 'pending' is the reference's cardinal sin (wallet_payment states
    # are the restart-recovery source of truth)
    try:
        await ch.offer_htlc(amount_sent, inv.payment_hash, first_cltv,
                            onion=onion)
        await ch.commit()
        await ch.handle_commit()
        upd = await ch.recv_update()
        await ch.handle_commit()
        await ch.commit()
    except Exception as e:
        _fail_payment(wallet, pay_id, f"{type(e).__name__}: {e}")
        raise PayError(f"payment dance failed: {e}") from e

    if isinstance(upd, M.UpdateFulfillHtlc):
        _settle_payment(wallet, pay_id, upd.payment_preimage,
                        amount_msat=amount, amount_sent_msat=amount_sent,
                        payment_hash=inv.payment_hash)
        return PayResult(inv.payment_hash, upd.payment_preimage,
                         amount, amount_sent)
    if isinstance(upd, M.UpdateFailHtlc):
        try:
            idx, failmsg = SX.unwrap_error_onion(secrets, upd.reason)
        except SX.SphinxError as e:
            _fail_payment(wallet, pay_id, "unparseable error onion")
            raise PayError(f"failed with unparseable error onion: {e}") \
                from e
        code = int.from_bytes(failmsg[:2], "big") if len(failmsg) >= 2 \
            else None
        name = FAILURE_NAMES.get(code, f"code {code:#x}" if code else "?")
        _fail_payment(wallet, pay_id, name)
        raise PayError(f"payment failed at hop {idx}: {name}",
                       code=code, erring_index=idx)
    _fail_payment(wallet, pay_id, f"unexpected {type(upd).__name__}")
    raise PayError(f"unexpected update {type(upd).__name__}")


async def pay_mpp_direct(ch, invoice_str: str, parts: int = 2,
                         blockheight: int = 0) -> PayResult:
    """Multi-part payment to a DIRECT peer over one channel: the amount
    splits into `parts` HTLCs, each onion claiming total_msat = full
    amount, so the payee's htlc_set holds them until the set completes
    (lightningd/pay.c MPP send ∘ htlc_set.c receive).  One commitment
    dance locks in every part; the payee fulfills them together."""
    inv = B11.decode(invoice_str)
    if inv.amount_msat is None:
        raise PayError("MPP needs an invoice amount")
    if inv.payment_secret is None:
        raise PayError("MPP needs a payment_secret")
    if ch.peer.node_id != inv.payee:
        raise PayError("pay_mpp_direct: payee is not the channel peer")
    amount = inv.amount_msat
    final_cltv = blockheight + inv.min_final_cltv

    split = [amount // parts] * parts
    split[-1] += amount - sum(split)
    for part_amt in split:
        route = [RouteStep(inv.payee, 0, part_amt, final_cltv)]
        onion, _ = build_payment_onion(
            route, inv.payment_hash, inv.payment_secret, amount,
            SX.random_session_key())
        await ch.offer_htlc(part_amt, inv.payment_hash, final_cltv,
                            onion=onion)
    await ch.commit()
    await ch.handle_commit()

    preimage = None
    got = 0
    while got < parts:
        upd = await ch.recv_update()
        if isinstance(upd, M.UpdateFulfillHtlc):
            preimage = upd.payment_preimage
            got += 1
        elif isinstance(upd, M.UpdateFailHtlc):
            raise PayError("MPP part failed")
    await ch.handle_commit()
    await ch.commit()
    return PayResult(inv.payment_hash, preimage, amount, amount)


def _record_payment(wallet, inv, bolt11_str, amount, amount_sent,
                    created) -> int | None:
    from ..utils import events

    events.emit("sendpay_created", {
        "payment_hash": inv.payment_hash.hex(), "amount_msat": amount})
    if wallet is None:
        return None
    with wallet.db.transaction():
        cur = wallet.db.conn.execute(
            "INSERT INTO payments (payment_hash, destination, amount_msat,"
            " amount_sent_msat, bolt11, status, created_at)"
            " VALUES (?,?,?,?,?,'pending',?)",
            (inv.payment_hash, inv.payee, amount, amount_sent,
             bolt11_str, created))
    return cur.lastrowid


def _settle_payment(wallet, pay_id, preimage: bytes,
                    amount_msat: int | None = None,
                    amount_sent_msat: int | None = None,
                    payment_hash: bytes | None = None) -> None:
    if amount_msat is not None:
        from ..utils import events

        ref_hex = payment_hash.hex() if payment_hash else None
        events.emit("coin_movement", {
            "account": "channel", "tag": "payment",
            "debit_msat": amount_msat, "reference": ref_hex})
        fee = (amount_sent_msat or amount_msat) - amount_msat
        if fee > 0:
            events.emit("coin_movement", {
                "account": "channel", "tag": "invoice_fee",
                "debit_msat": fee, "reference": ref_hex})
    from ..utils import events

    events.emit("sendpay_success", {
        "payment_hash": payment_hash.hex() if payment_hash else None,
        "amount_msat": amount_msat, "amount_sent_msat": amount_sent_msat,
        "status": "complete"})
    if wallet is None or pay_id is None:
        return
    with wallet.db.transaction():
        wallet.db.conn.execute(
            "UPDATE payments SET status='complete', preimage=?,"
            " completed_at=? WHERE id=?",
            (preimage, int(time.time()), pay_id))


def _fail_payment(wallet, pay_id, why: str) -> None:
    from ..utils import events

    events.emit("sendpay_failure", {"status": "failed", "failure": why})
    if wallet is None or pay_id is None:
        return
    with wallet.db.transaction():
        wallet.db.conn.execute(
            "UPDATE payments SET status='failed', failure=?,"
            " completed_at=? WHERE id=?",
            (why, int(time.time()), pay_id))


def listpays(wallet) -> list[dict]:
    rows = wallet.db.conn.execute(
        "SELECT payment_hash, destination, amount_msat, amount_sent_msat,"
        " status, preimage, created_at, failure FROM payments"
        " ORDER BY id").fetchall()
    out = []
    for r in rows:
        d = {"payment_hash": bytes(r[0]).hex(),
             "amount_msat": r[2], "amount_sent_msat": r[3],
             "status": r[4], "created_at": r[6]}
        if r[1] is not None:
            d["destination"] = bytes(r[1]).hex()
        if r[5] is not None:
            d["preimage"] = bytes(r[5]).hex()
        if r[7] is not None:
            d["failure"] = r[7]
        out.append(d)
    return out
