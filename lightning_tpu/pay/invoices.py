"""Invoice registry: create/lookup/settle/expire BOLT#11 invoices.

Parity target: lightningd/invoice.c + wallet/invoices.c (the invoices
table, pay_index monotone counter for waitanyinvoice, expiry handling)
with our bolt11 codec doing the encoding/signing.
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass

from ..bolt import bolt11


class InvoiceError(Exception):
    pass


@dataclass
class InvoiceRecord:
    label: str
    payment_hash: bytes
    preimage: bytes
    amount_msat: int | None
    bolt11: str
    description: str
    status: str               # unpaid | paid | expired
    expires_at: int
    payment_secret: bytes
    pay_index: int | None = None
    paid_at: int | None = None
    received_msat: int | None = None
    local_offer_id: bytes | None = None   # bolt12: the offer it answers

    def to_rpc(self) -> dict:
        out = {
            "label": self.label,
            "payment_hash": self.payment_hash.hex(),
            "bolt11": self.bolt11,
            "status": self.status,
            "description": self.description,
            "expires_at": self.expires_at,
        }
        if self.amount_msat is not None:
            out["amount_msat"] = self.amount_msat
        if self.status == "paid":
            out.update(pay_index=self.pay_index, paid_at=self.paid_at,
                       amount_received_msat=self.received_msat,
                       payment_preimage=self.preimage.hex())
        return out


class InvoiceRegistry:
    """In-memory registry with write-through to the wallet db (if any)."""

    def __init__(self, node_seckey: int, db=None, currency: str = "bcrt",
                 min_final_cltv: int = 18):
        self.node_seckey = node_seckey
        self.db = db
        self.currency = currency
        self.min_final_cltv = min_final_cltv
        self.by_hash: dict[bytes, InvoiceRecord] = {}
        self.by_label: dict[str, InvoiceRecord] = {}
        self._next_pay_index = 1
        # offers service hook: fn(local_offer_id) once a bolt12 invoice
        # settles (single-use offers are spent by payment)
        self.on_bolt12_paid = None
        # waitinvoice/waitanyinvoice wake signal: waiters re-check their
        # own condition on every registry change (settle/delete/expire),
        # so cursors and deletions are always honored
        # (invoices.c wait machinery + the pay_index cursor)
        self._change_ev = None
        if db is not None:
            self._load()

    def _signal(self) -> None:
        ev = self._change_ev
        if ev is not None:
            ev.set()
            self._change_ev = None

    def _change_event(self):
        import asyncio

        if self._change_ev is None:
            self._change_ev = asyncio.Event()
        return self._change_ev

    # -- persistence ------------------------------------------------------

    def _load(self) -> None:
        rows = self.db.conn.execute(
            "SELECT label, payment_hash, preimage, amount_msat, bolt11,"
            " description, status, expires_at, pay_index, paid_at,"
            " received_msat, payment_secret, local_offer_id"
            " FROM invoices").fetchall()
        for r in rows:
            if r[11] is not None:
                secret = bytes(r[11])
            else:
                # pre-migration-8 row: fall back to decoding the invoice
                inv = bolt11.decode(r[4], check_sig=False)
                secret = inv.payment_secret or b""
            rec = InvoiceRecord(
                label=r[0], payment_hash=bytes(r[1]), preimage=bytes(r[2]),
                amount_msat=r[3], bolt11=r[4], description=r[5] or "",
                status=r[6], expires_at=r[7],
                payment_secret=secret,
                pay_index=r[8], paid_at=r[9], received_msat=r[10],
                local_offer_id=bytes(r[12]) if r[12] is not None else None)
            self.by_hash[rec.payment_hash] = rec
            self.by_label[rec.label] = rec
            if rec.pay_index is not None:
                self._next_pay_index = max(self._next_pay_index,
                                           rec.pay_index + 1)

    def _save(self, rec: InvoiceRecord) -> None:
        if self.db is None:
            return
        with self.db.transaction():
            self.db.conn.execute(
                "INSERT INTO invoices (label, payment_hash, preimage,"
                " amount_msat, bolt11, description, status, expires_at,"
                " pay_index, paid_at, received_msat, payment_secret)"
                " VALUES (?,?,?,?,?,?,?,?,?,?,?,?)"
                " ON CONFLICT(label) DO UPDATE SET status=excluded.status,"
                " pay_index=excluded.pay_index, paid_at=excluded.paid_at,"
                " received_msat=excluded.received_msat",
                (rec.label, rec.payment_hash, rec.preimage, rec.amount_msat,
                 rec.bolt11, rec.description, rec.status, rec.expires_at,
                 rec.pay_index, rec.paid_at, rec.received_msat,
                 rec.payment_secret))

    # -- creation ---------------------------------------------------------

    def create(self, label: str, amount_msat: int | None, description: str,
               expiry: int = 3600) -> InvoiceRecord:
        if label in self.by_label:
            raise InvoiceError(f"duplicate label {label!r}")
        preimage = os.urandom(32)
        import hashlib

        payment_hash = hashlib.sha256(preimage).digest()
        payment_secret = os.urandom(32)
        s, inv = bolt11.new_invoice(
            self.node_seckey, payment_hash, amount_msat, description,
            currency=self.currency, payment_secret=payment_secret,
            expiry=expiry, min_final_cltv=self.min_final_cltv)
        rec = InvoiceRecord(
            label=label, payment_hash=payment_hash, preimage=preimage,
            amount_msat=amount_msat, bolt11=s, description=description,
            status="unpaid", expires_at=inv.expires_at,
            payment_secret=payment_secret)
        self.by_hash[payment_hash] = rec
        self.by_label[label] = rec
        self._save(rec)
        from ..utils import events

        events.emit("invoice_creation", {
            "label": label, "amount_msat": amount_msat,
            "payment_hash": payment_hash.hex()})
        return rec

    def create_bolt12(self, label: str, amount_msat: int,
                      payment_hash: bytes, preimage: bytes, bolt12: str,
                      local_offer_id: bytes | None = None,
                      expiry: int = 7200,
                      payment_secret: bytes = b"") -> InvoiceRecord:
        """Register a BOLT#12 invoice we just minted for an
        invoice_request (plugins/offers_invreq_hook.c → invoice
        creation).  BOLT#12 has no payment_secret TLV — the blinded-path
        path_id cookie plays that role, so the caller passes it here and
        resolve_htlc demands it like any bolt11 secret (without it, any
        on-route node that sees the payment_hash could claim the
        preimage directly)."""
        if label in self.by_label:
            raise InvoiceError(f"duplicate label {label!r}")
        rec = InvoiceRecord(
            label=label, payment_hash=payment_hash, preimage=preimage,
            amount_msat=amount_msat, bolt11=bolt12, description="",
            status="unpaid", expires_at=int(time.time()) + expiry,
            payment_secret=payment_secret, local_offer_id=local_offer_id)
        self.by_hash[payment_hash] = rec
        self.by_label[label] = rec
        self._save(rec)
        if self.db is not None and local_offer_id is not None:
            with self.db.transaction():
                self.db.conn.execute(
                    "UPDATE invoices SET local_offer_id=? WHERE label=?",
                    (local_offer_id, label))
        return rec

    # -- resolution (the htlc_accepted / invoice_payment path) ------------

    def resolve_htlc(self, payment_hash: bytes, amount_msat: int,
                     payment_secret: bytes | None,
                     total_msat: int | None = None,
                     now: float | None = None) -> bytes | None:
        """Decide whether an incoming final-hop HTLC pays one of our
        invoices.  Returns the preimage to fulfill with, or None
        (caller fails the HTLC).  Mirrors invoice.c's checks: known
        hash, not expired, secret matches, delivered amount in
        [amount, 2*amount] (BOLT#4 overpayment rule).

        READ-ONLY w.r.t. payment state: classification can run more
        than once for the same HTLC (the fulfill may not be committable
        yet); callers mark the invoice paid via `settle()` only after
        the fulfill is actually sent.  Until MPP sets land, a single
        HTLC must deliver the whole amount: a payload claiming
        total_msat beyond what this HTLC carries is rejected (the
        reference holds such HTLCs in an htlc_set; paying out the
        preimage for a partial delivery would forfeit the invoice)."""
        rec = self.by_hash.get(payment_hash)
        if rec is None:
            return None
        t = int(now if now is not None else time.time())
        if rec.status == "paid":
            # idempotent re-classification of the same fulfill
            return rec.preimage if amount_msat == rec.received_msat \
                else None
        if t > rec.expires_at:
            rec.status = "expired"
            self._save(rec)
            return None
        if rec.payment_secret and payment_secret != rec.payment_secret:
            return None
        if total_msat is not None and total_msat > amount_msat:
            return None   # partial HTLC of a multi-part payment
        if rec.amount_msat is not None and not (
                rec.amount_msat <= amount_msat <= 2 * rec.amount_msat):
            return None
        return rec.preimage

    def settle(self, payment_hash: bytes, amount_msat: int,
               now: float | None = None) -> None:
        """Mark paid — called once the fulfill_htlc was actually sent.
        Idempotent."""
        rec = self.by_hash.get(payment_hash)
        if rec is None or rec.status == "paid":
            return
        rec.status = "paid"
        rec.paid_at = int(now if now is not None else time.time())
        rec.received_msat = amount_msat
        rec.pay_index = self._next_pay_index
        self._next_pay_index += 1
        self._save(rec)
        from ..utils import events

        # bkpr feed (common/coin_mvt.c new_coin_channel_credit: invoice
        # income; account granularity is node-wide here, not per-channel)
        events.emit("coin_movement", {
            "account": "channel", "tag": "invoice",
            "credit_msat": amount_msat,
            "reference": payment_hash.hex(), "timestamp": rec.paid_at})
        events.emit("invoice_payment", {
            "label": rec.label, "msat": amount_msat,
            "payment_hash": payment_hash.hex(),
            "preimage": rec.preimage.hex()})
        if rec.local_offer_id is not None and self.on_bolt12_paid:
            self.on_bolt12_paid(rec.local_offer_id)
        self._signal()

    # -- waiting (invoices.c waitany/waitinvoice) -------------------------

    async def _await_change(self, deadline) -> None:
        import asyncio

        ev = self._change_event()
        if deadline is None:
            await ev.wait()
            return
        remaining = deadline - asyncio.get_running_loop().time()
        if remaining <= 0:
            raise asyncio.TimeoutError
        await asyncio.wait_for(ev.wait(), remaining)

    def _deadline(self, timeout):
        import asyncio

        return None if timeout is None else \
            asyncio.get_running_loop().time() + timeout

    async def wait_any(self, lastpay_index: int = 0,
                       timeout: float | None = None) -> InvoiceRecord:
        """Resolve with the next invoice whose pay_index exceeds the
        cursor (already-paid ones resolve immediately).  The condition
        is re-checked on every registry change, so a cursor beyond the
        current counter keeps waiting (never returns a stale index),
        and the timeout is a DEADLINE across wakeups."""
        deadline = self._deadline(timeout)
        while True:
            paid = [r for r in self.by_label.values()
                    if r.pay_index is not None
                    and r.pay_index > lastpay_index]
            if paid:
                return min(paid, key=lambda r: r.pay_index)
            await self._await_change(deadline)

    async def wait_for_label(self, label: str,
                             timeout: float | None = None
                             ) -> InvoiceRecord:
        import time as _time

        if label not in self.by_label:
            raise InvoiceError(f"unknown invoice {label!r}")
        deadline = self._deadline(timeout)
        while True:
            rec = self.by_label.get(label)
            if rec is None:
                raise InvoiceError(f"invoice {label!r} was deleted")
            if rec.status == "paid":
                return rec
            if rec.status == "expired" or _time.time() > rec.expires_at:
                raise InvoiceError(f"invoice {label!r} expired")
            await self._await_change(deadline)

    def delete(self, label: str, status: str) -> dict:
        """status is REQUIRED (invoices.c): deleting without asserting
        the expected state races a concurrent payment and could destroy
        a just-paid record."""
        rec = self.by_label.get(label)
        if rec is None:
            raise InvoiceError(f"unknown invoice {label!r}")
        if rec.status != status:
            raise InvoiceError(
                f"invoice is {rec.status}, not {status}")
        del self.by_label[label]
        self.by_hash.pop(rec.payment_hash, None)
        if self.db is not None:
            with self.db.transaction() as c:
                c.execute("DELETE FROM invoices WHERE label=?", (label,))
        from ..utils import events

        events.emit("invoice_deleted", {
            "label": label, "payment_hash": rec.payment_hash.hex()})
        self._signal()   # wake waiters so they see the deletion
        return rec.to_rpc()

    # -- queries ----------------------------------------------------------

    def listinvoices(self, label: str | None = None) -> list[dict]:
        self._expire_now()
        if label is not None:
            rec = self.by_label.get(label)
            return [rec.to_rpc()] if rec else []
        return [r.to_rpc() for r in self.by_label.values()]

    def _expire_now(self) -> None:
        t = time.time()
        changed = False
        for rec in self.by_label.values():
            if rec.status == "unpaid" and t > rec.expires_at:
                rec.status = "expired"
                self._save(rec)
                changed = True
        if changed:
            self._signal()
