"""xpay: the modern payment engine — MCF routes + multi-part sends.

Functional parity target: plugins/xpay/xpay.c (asks askrene for
`getroutes`, splits into parts, injects each part's onion, retries with
the failing channel disabled) — here the solver is routing.mcf and the
injection path is our own channel driver.

Flow: decode invoice → mcf.getroutes from our direct peer to the payee
(our unannounced channel is prepended to every part) → build one onion
per part with payment_secret + total_msat → offer all parts, one
commitment dance → collect fulfills/fails.  On a part failure the
erring channel is disabled in the layers and the WHOLE payment retries
(up to `retries` times), matching xpay's "disable and re-ask" loop.
"""
from __future__ import annotations

import logging
import time

from ..bolt import bolt11 as B11
from ..bolt import sphinx as SX
from ..obs import journey as _journey
from ..routing import mcf
from ..wire import messages as M
from .payer import (FAILURE_NAMES, PayError, PayResult, RouteStep,
                    _fail_payment, _record_payment, _settle_payment,
                    build_payment_onion)

log = logging.getLogger("lightning_tpu.xpay")


async def xpay(ch, invoice_str: str, gossmap, *,
               amount_msat: int | None = None,
               maxfee_msat: int | None = None,
               layers: mcf.Layers | None = None,
               max_parts: int = 8, retries: int = 2,
               blockheight: int = 0, wallet=None,
               mcf_service=None, inv=None) -> PayResult:
    """Pay a BOLT#11 invoice over `ch` using min-cost-flow routing.

    ``mcf_service`` is an optional routing.mcf_device.McfService:
    the per-attempt getroutes then coalesces with every other
    concurrent payer's into one batched device solve (mcf.getroutes
    stays the bit-identical host fallback — breaker-open, oversized
    amounts, inexpressible layers all land there).  ``inv`` lets a
    caller that already decoded ``invoice_str`` (manager.xpay screens
    on payee/payment_secret) skip the second signature recovery."""
    inv = inv if inv is not None else B11.decode(invoice_str)
    amount = inv.amount_msat or amount_msat
    if amount is None:
        raise PayError("invoice has no amount; amount_msat required")
    if inv.payment_secret is None:
        raise PayError("xpay requires a payment_secret (MPP)")
    if time.time() > inv.expires_at:
        raise PayError("invoice expired")
    final_cltv = blockheight + inv.min_final_cltv
    layers = layers or mcf.Layers()

    created = int(time.time())
    pay_id = _record_payment(wallet, inv, invoice_str, amount, amount,
                             created)
    last_err: PayError | None = None
    try:
        for attempt in range(retries + 1):
            try:
                result = await _attempt(ch, inv, gossmap, amount,
                                        layers, maxfee_msat, max_parts,
                                        final_cltv,
                                        mcf_service=mcf_service)
                _settle_payment(wallet, pay_id, result.preimage,
                                amount_msat=amount,
                                amount_sent_msat=result.amount_sent_msat,
                                payment_hash=inv.payment_hash)
                return result
            except _PartFailure as pf:
                last_err = pf.err
                if pf.erring_scid is not None:
                    layers.disabled.add(pf.erring_scid)
                    log.info("xpay: disabled %s after failure, "
                             "retrying", pf.erring_scid)
                else:
                    break
            except mcf.McfError as e:
                last_err = PayError(f"no route: {e}", code=205)
                break
    except Exception as e:
        # everything else — Overloaded admission (no part was ever
        # offered; the RPC layer maps the re-raise to TRY_AGAIN),
        # KeyError for a graph-unknown node, a stopped/failed service,
        # a protocol error mid-dance — must still resolve the recorded
        # payment row: a pending-forever phantom in listpays is worse
        # than a conservatively-failed row
        _fail_payment(wallet, pay_id, str(e) or repr(e))
        raise
    _fail_payment(wallet, pay_id, str(last_err))
    raise last_err


class _PartFailure(Exception):
    def __init__(self, err: PayError, erring_scid: int | None):
        self.err = err
        self.erring_scid = erring_scid


async def _attempt(ch, inv, gossmap, amount: int, layers,
                   maxfee_msat, max_parts: int,
                   final_cltv: int, mcf_service=None) -> PayResult:
    if ch.peer.node_id == inv.payee:
        routes = [{"source_amount_msat": amount,
                   "source_delay": final_cltv, "path": [],
                   "amount_msat": amount}]
    else:
        if mcf_service is not None:
            # batched device MPP solve: concurrent payers coalesce into
            # one dispatch; the service owns the host-oracle fallback.
            # payment_hash rides along as the journey key so the
            # enqueue/mcf_flush/parts hops land on this payment's
            # journey (doc/journeys.md)
            res = await mcf_service.getroutes(
                ch.peer.node_id, inv.payee, amount, layers=layers,
                maxfee_msat=maxfee_msat, final_cltv=final_cltv,
                max_parts=max_parts, journey_key=inv.payment_hash)
        else:
            res = mcf.getroutes(gossmap, ch.peer.node_id, inv.payee,
                                amount, layers=layers,
                                maxfee_msat=maxfee_msat,
                                final_cltv=final_cltv,
                                max_parts=max_parts)
        routes = []
        for r in res["routes"]:
            routes.append({
                "source_amount_msat": r["source_amount_msat"],
                "source_delay": r["source_delay"],
                "amount_msat": r["amount_msat"],
                "path": [(bytes.fromhex(h["next_node_id"]),
                          h["short_channel_id"], h["amount_msat"],
                          h["delay"]) for h in r["path"]],
            })

    # the WHOLE premium we pay includes the source peer's own
    # forwarding fee (mcf's fee excludes the source hop, since a
    # source doesn't charge itself) — enforce maxfee on it up front
    total_sent = sum(r["source_amount_msat"] for r in routes)
    if maxfee_msat is not None and total_sent - amount > maxfee_msat:
        raise mcf.McfError(
            f"fee {total_sent - amount} msat exceeds maxfee "
            f"{maxfee_msat}")

    # build + offer every part, then one dance
    parts_by_hid = {}   # hid -> (route_scids, sphinx secrets)
    sent = 0
    for r in routes:
        steps = [RouteStep(ch.peer.node_id, 0, r["source_amount_msat"],
                           r["source_delay"])]
        steps += [RouteStep(n, s, a, d) for n, s, a, d in r["path"]]
        onion, secrets = build_payment_onion(
            steps, inv.payment_hash, inv.payment_secret, amount,
            SX.random_session_key())
        hid = await ch.offer_htlc(r["source_amount_msat"],
                                  inv.payment_hash,
                                  r["source_delay"], onion=onion)
        _journey.hop("htlc_add", "payment", inv.payment_hash,
                     outcome="ok", htlc_id=int(hid),
                     amount_msat=int(r["source_amount_msat"]))
        parts_by_hid[hid] = ([0] + [s for _, s, _, _ in r["path"]],
                             secrets)
        sent += r["source_amount_msat"]
    await ch.commit()
    await ch.handle_commit()

    # collect a resolution for EVERY part before touching the dance:
    # raising on the first failure would leave sibling fails queued and
    # desync our commitment view from the peer's
    preimage = None
    first_failure: tuple[PayError, int | None] | None = None
    for _ in range(len(routes)):
        upd = await ch.recv_update()
        if isinstance(upd, M.UpdateFulfillHtlc):
            preimage = upd.payment_preimage
            _journey.hop("htlc_settle", "payment", inv.payment_hash,
                         outcome="ok", htlc_id=int(upd.id))
            continue
        if isinstance(upd, M.UpdateFailMalformedHtlc):
            _journey.hop("htlc_fail", "payment", inv.payment_hash,
                         outcome="malformed", htlc_id=int(upd.id))
            if first_failure is None:
                first_failure = (PayError(
                    f"part failed: malformed onion "
                    f"({upd.failure_code:#x})",
                    code=upd.failure_code, erring_index=0), None)
            continue
        if isinstance(upd, M.UpdateFailHtlc):
            scids, secrets = parts_by_hid.get(upd.id, (None, None))
            hop_idx = code = None
            if secrets is not None:
                try:
                    hop_idx, failmsg = SX.unwrap_error_onion(secrets,
                                                             upd.reason)
                    code = int.from_bytes(failmsg[:2], "big") \
                        if len(failmsg) >= 2 else None
                except SX.SphinxError:
                    pass
            name = FAILURE_NAMES.get(code,
                                     f"code {code:#x}" if code else "?")
            _journey.hop("htlc_fail", "payment", inv.payment_hash,
                         outcome=name, htlc_id=int(upd.id),
                         erring_hop=hop_idx)
            err = PayError(f"part failed at hop {hop_idx}: {name}",
                           code=code, erring_index=hop_idx)
            # disable the erring node's OUTGOING channel (xpay's
            # "disable and re-ask"); hop 0 is our own unannounced hop
            erring_scid = None
            if scids and hop_idx is not None:
                if hop_idx + 1 < len(scids) and scids[hop_idx + 1]:
                    erring_scid = scids[hop_idx + 1]
                elif 0 <= hop_idx < len(scids) and scids[hop_idx]:
                    erring_scid = scids[hop_idx]
            if first_failure is None:
                first_failure = (err, erring_scid)
    await ch.handle_commit()
    await ch.commit()
    if first_failure is not None:
        raise _PartFailure(*first_failure)
    if preimage is None:
        raise PayError("no part fulfilled and no failure reported")
    return PayResult(inv.payment_hash, preimage, amount, sent,
                     parts=len(routes))
