/* Native host-side helpers for the gossip_store → TPU verify pipeline.
 *
 * Store record layout matches the reference's on-disk format
 * (common/gossip_store.h:44-50): version byte, then records of
 *   be16 flags | be16 len | be32 crc | be32 timestamp | msg[len]
 * where msg starts with the be16 wire message type.
 *
 * These scanners exist so a ~1M-record replay spends host time at memcpy
 * speed: the Python layer gets flat numpy arrays (offsets/lengths/types)
 * and slices signature/pubkey fields with vectorized gathers, while the
 * signed regions are packed (with SHA256 padding pre-applied) straight
 * into the pinned staging buffer the device hashes from.
 */
#include <stddef.h>
#include <stdint.h>
#include <string.h>

static inline uint16_t rd_be16(const uint8_t *p) {
    return (uint16_t)((p[0] << 8) | p[1]);
}
static inline uint32_t rd_be32(const uint8_t *p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | p[3];
}

/* Scan records from `off` to end of buffer.  Returns record count, or -1 if
 * a record header/body would run past the end (truncated store).  Arrays
 * must have capacity for (size - off) / 12 entries. */
int64_t gossip_store_scan(const uint8_t *buf, uint64_t size, uint64_t off,
                          uint64_t *offsets, uint32_t *lengths,
                          uint16_t *flags, uint32_t *timestamps,
                          uint32_t *crcs, uint16_t *types) {
    int64_t n = 0;
    while (off < size) {
        if (off + 12 > size) return -1;
        uint16_t f = rd_be16(buf + off);
        uint16_t len = rd_be16(buf + off + 2);
        if (off + 12 + len > size) return -1;
        offsets[n] = off + 12;
        lengths[n] = len;
        flags[n] = f;
        crcs[n] = rd_be32(buf + off + 4);
        timestamps[n] = rd_be32(buf + off + 8);
        types[n] = len >= 2 ? rd_be16(buf + off + 12) : 0xFFFF;
        n++;
        off += 12 + (uint64_t)len;
    }
    return n;
}

/* Pack variable-length signed regions into fixed-size SHA256 block rows.
 *
 * For record i: copies buf[offsets[i] .. offsets[i]+lengths[i]) into
 * out + i*row_bytes, applies SHA256 padding (0x80, zeros, 64-bit bit
 * length), zero-fills the rest, and writes the number of 64-byte blocks
 * to n_blocks[i].
 *
 * A region that does not fit row_bytes is NOT an error: BOLT#7 messages
 * are legal up to 64 KiB (long node_announcement address/feature vectors
 * occur on the real network), and one oversized message must not abort a
 * whole-store replay.  Such rows get n_blocks[i] = 0 (impossible for a
 * real region — padding makes every region >= 1 block) and a zeroed row;
 * the caller hashes them host-side.  Returns the oversized count. */
int64_t sha256_pack(const uint8_t *buf, const uint64_t *offsets,
                    const uint32_t *lengths, size_t n, uint8_t *out,
                    uint64_t row_bytes, uint32_t *n_blocks) {
    int64_t oversized = 0;
    for (size_t i = 0; i < n; i++) {
        uint32_t len = lengths[i];
        uint64_t padded = ((uint64_t)len + 1 + 8 + 63) & ~63ull;
        uint8_t *row = out + i * row_bytes;
        if (padded > row_bytes) {
            memset(row, 0, row_bytes);
            n_blocks[i] = 0;
            oversized++;
            continue;
        }
        memcpy(row, buf + offsets[i], len);
        row[len] = 0x80;
        memset(row + len + 1, 0, padded - len - 1 - 8);
        uint64_t bits = (uint64_t)len * 8;
        for (int b = 0; b < 8; b++)
            row[padded - 1 - b] = (uint8_t)(bits >> (8 * b));
        if (padded < row_bytes)
            memset(row + padded, 0, row_bytes - padded);
        n_blocks[i] = (uint32_t)(padded / 64);
    }
    return oversized;
}

/* Gather fixed-size fields at per-record offsets: out[i] = buf[offsets[i]
 * + field_off .. +field_len).  Bounds are the caller's responsibility. */
void gather_fields(const uint8_t *buf, const uint64_t *offsets, size_t n,
                   uint64_t field_off, uint32_t field_len, uint8_t *out) {
    for (size_t i = 0; i < n; i++)
        memcpy(out + i * field_len, buf + offsets[i] + field_off, field_len);
}
