/* crc32c (Castagnoli) — slice-by-4 software implementation.
 *
 * The gossip store on-disk format (compatible with the reference's
 * common/gossip_store.h:44-50 record header) checksums each record with
 * crc32c seeded by the record timestamp (gossipd/gossip_store.c:67).
 * This native module exists because a 1M-record store replay needs CRC
 * validation at GB/s on the host while the TPU verifies signatures.
 *
 * Exposes plain C symbols for ctypes; no Python.h dependency.
 */
#include <stddef.h>
#include <stdint.h>

static uint32_t table[4][256];
static int initialized = 0;

static void init_tables(void) {
    const uint32_t poly = 0x82F63B78u; /* reflected CRC-32C */
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (poly ^ (c >> 1)) : (c >> 1);
        table[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = table[0][i];
        for (int t = 1; t < 4; t++) {
            c = table[0][c & 0xFF] ^ (c >> 8);
            table[t][i] = c;
        }
    }
    initialized = 1;
}

uint32_t crc32c(uint32_t seed, const uint8_t *buf, size_t len) {
    if (!initialized) init_tables();
    uint32_t crc = ~seed;
    while (len && ((uintptr_t)buf & 3)) {
        crc = table[0][(crc ^ *buf++) & 0xFF] ^ (crc >> 8);
        len--;
    }
    while (len >= 4) {
        uint32_t w;
        __builtin_memcpy(&w, buf, 4);
        crc ^= w;
        crc = table[3][crc & 0xFF] ^ table[2][(crc >> 8) & 0xFF] ^
              table[1][(crc >> 16) & 0xFF] ^ table[0][crc >> 24];
        buf += 4;
        len -= 4;
    }
    while (len--) crc = table[0][(crc ^ *buf++) & 0xFF] ^ (crc >> 8);
    return ~crc;
}

/* Batched variant over a contiguous buffer with per-record offsets:
 * out[i] = crc32c(seeds[i], buf + offsets[i], lengths[i]). */
void crc32c_batch(const uint8_t *buf, const uint64_t *offsets,
                  const uint32_t *lengths, const uint32_t *seeds,
                  uint32_t *out, size_t n) {
    for (size_t i = 0; i < n; i++)
        out[i] = crc32c(seeds[i], buf + offsets[i], lengths[i]);
}
