"""Static channel backups (SCB) + peer_storage distribution.

Functional parity targets: plugins/chanbackup.c (the encrypted
`emergency.recover` blob: one static record per channel, re-encrypted
and re-distributed on every channel change) and the BOLT `peer_storage`
/`peer_storage_retrieval` messages (wire/peer_wire.csv:30-34) that let
peers hold our blob for us; lightningd's recover flow
(lightningd/lightningd.c:1434, plugins/recover.c) restores from it.

The SCB deliberately holds only STATIC data: enough to identify the
channel, reconnect to the peer, and run channel_reestablish so the
peer force-closes to us (we cannot reconstruct HTLC state — that is the
wallet db's job; the SCB is the disaster floor, not a checkpoint).

Encryption: ChaCha20-Poly1305, key = sha256("scb secret" || hsm_secret),
random 12-byte nonce prepended.  Version byte leads the plaintext.
"""
from __future__ import annotations

import hashlib
import logging
import os
import struct

from cryptography.exceptions import InvalidTag
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

log = logging.getLogger("lightning_tpu.chanbackup")

SCB_VERSION = 1
MAX_PEER_STORAGE = 65531   # BOLT#1 peer_storage blob cap


class ScbError(Exception):
    pass


def scb_key(hsm_secret: bytes) -> bytes:
    return hashlib.sha256(b"scb secret" + hsm_secret).digest()


def _pack_chan(row: dict) -> bytes:
    """One channel's static record from its wallet row."""
    addr = row.get("peer_addr", "").encode()
    return struct.pack(
        ">B33s32s32sHQB H", SCB_VERSION, row["peer_node_id"],
        row["channel_id"], row["funding_txid"], row["funding_outidx"],
        row["funding_sat"], int(bool(row["opener_is_local"])), len(addr),
    ) + addr


_FIXED = struct.calcsize(">B33s32s32sHQB H")


def _unpack_chan(raw: bytes, off: int) -> tuple[dict, int]:
    if off + _FIXED > len(raw):
        raise ScbError("truncated channel record")
    (ver, node_id, cid, txid, outidx, sat, opener,
     alen) = struct.unpack_from(">B33s32s32sHQB H", raw, off)
    if ver != SCB_VERSION:
        raise ScbError(f"unknown SCB record version {ver}")
    off += _FIXED
    addr = raw[off:off + alen].decode(errors="replace")
    off += alen
    return {
        "peer_node_id": node_id, "channel_id": cid, "funding_txid": txid,
        "funding_outidx": outidx, "funding_sat": sat,
        "opener_is_local": bool(opener), "peer_addr": addr,
    }, off


def serialize(channels: list[dict]) -> bytes:
    out = [struct.pack(">BH", SCB_VERSION, len(channels))]
    out += [_pack_chan(c) for c in channels]
    return b"".join(out)


def parse(raw: bytes) -> list[dict]:
    if len(raw) < 3:
        raise ScbError("short SCB")
    ver, n = struct.unpack_from(">BH", raw, 0)
    if ver != SCB_VERSION:
        raise ScbError(f"unknown SCB version {ver}")
    off, chans = 3, []
    for _ in range(n):
        c, off = _unpack_chan(raw, off)
        chans.append(c)
    return chans


def encrypt(hsm_secret: bytes, channels: list[dict]) -> bytes:
    nonce = os.urandom(12)
    ct = ChaCha20Poly1305(scb_key(hsm_secret)).encrypt(
        nonce, serialize(channels), b"")
    blob = nonce + ct
    if len(blob) > MAX_PEER_STORAGE:
        raise ScbError("SCB exceeds peer_storage size cap")
    return blob


def decrypt(hsm_secret: bytes, blob: bytes) -> list[dict]:
    if len(blob) < 12 + 16:
        raise ScbError("short SCB blob")
    try:
        pt = ChaCha20Poly1305(scb_key(hsm_secret)).decrypt(
            blob[:12], blob[12:], b"")
    except InvalidTag:
        raise ScbError("SCB decryption failed (wrong secret or tampered)") \
            from None
    return parse(pt)


class PeerStorageService:
    """Both halves of the peer_storage protocol on one node.

    - we SEND our encrypted SCB to every peer on connect and whenever a
      channel changes (chanbackup.c send_to_peers)
    - we STORE up to one blob per peer (BOLT#1: nodes SHOULD store if
      they have a channel with the sender) and echo it back with
      peer_storage_retrieval on reconnect
    """

    def __init__(self, node, hsm_secret: bytes, wallet=None):
        from ..wire import messages as M

        self.node = node
        self.hsm_secret = hsm_secret
        self.wallet = wallet
        self.stored: dict[bytes, bytes] = {}     # peer -> their blob
        self.retrieved: bytes | None = None      # our blob, echoed back
        self._table_ready = False
        node.register(M.PeerStorage, self._on_storage)
        node.register(M.PeerStorageRetrieval, self._on_retrieval)
        if wallet is not None:
            self._ensure_table()
            for r in wallet.db.conn.execute(
                    "SELECT peer_id, blob FROM peer_storage").fetchall():
                self.stored[bytes(r[0])] = bytes(r[1])

    def _ensure_table(self) -> None:
        with self.wallet.db.transaction():
            self.wallet.db.conn.execute(
                """CREATE TABLE IF NOT EXISTS peer_storage (
                    peer_id BLOB PRIMARY KEY, blob BLOB NOT NULL)""")
        self._table_ready = True

    # -- our backup -------------------------------------------------------

    def our_blob(self) -> bytes | None:
        if self.wallet is None:
            return None
        rows = self.wallet.list_channels()
        live = [r for r in rows if r["state"] not in
                ("closingd_complete", "onchain", "closed")]
        if not live:
            return None
        return encrypt(self.hsm_secret, live)

    async def distribute(self) -> int:
        """Send our current SCB to every connected peer."""
        from ..wire import messages as M

        blob = self.our_blob()
        if blob is None:
            return 0
        n = 0
        for peer in list(self.node.peers.values()):
            try:
                await peer.send(M.PeerStorage(blob=blob))
                n += 1
            except (ConnectionError, OSError):
                pass
        return n

    async def send_ours_to(self, peer) -> None:
        from ..wire import messages as M

        blob = self.our_blob()
        if blob is not None:
            await peer.send(M.PeerStorage(blob=blob))

    # -- storing for peers ------------------------------------------------

    async def _on_storage(self, peer, msg) -> None:
        if len(msg.blob) > MAX_PEER_STORAGE:
            return
        self.stored[peer.node_id] = msg.blob
        if self.wallet is not None:
            with self.wallet.db.transaction():
                self.wallet.db.conn.execute(
                    "INSERT INTO peer_storage (peer_id, blob) VALUES (?,?)"
                    " ON CONFLICT(peer_id) DO UPDATE SET blob=excluded.blob",
                    (peer.node_id, msg.blob))

    async def _on_retrieval(self, peer, msg) -> None:
        self.retrieved = msg.blob
        log.info("peer %s returned our %d-byte backup",
                 peer.node_id.hex()[:16], len(msg.blob))

    async def echo_back(self, peer) -> bool:
        """On reconnect, return the peer's stored blob (BOLT#1: a node
        storing peer data MUST send peer_storage_retrieval on
        reconnection)."""
        from ..wire import messages as M

        blob = self.stored.get(peer.node_id)
        if blob is None:
            return False
        await peer.send(M.PeerStorageRetrieval(blob=blob))
        return True

    # -- recovery ---------------------------------------------------------

    def emergencyrecover(self, blob: bytes | None = None) -> list[dict]:
        """Decrypt an SCB (ours from a peer echo, or supplied hex) and
        re-register channel stubs so reestablish can trigger the peer's
        unilateral close (plugins/recover.c flow)."""
        raw = blob if blob is not None else self.retrieved
        if raw is None:
            raise ScbError("no backup available to recover from")
        chans = decrypt(self.hsm_secret, raw)
        if self.wallet is not None:
            for c in chans:
                self._restore_stub(c)
        return chans

    def _restore_stub(self, c: dict) -> None:
        """Insert a minimal 'recover' channel row unless one exists."""
        db = self.wallet.db
        row = db.conn.execute(
            "SELECT id FROM channels WHERE channel_id=?",
            (c["channel_id"],)).fetchone()
        if row is not None:
            return
        with db.transaction():
            db.conn.execute(
                "INSERT INTO channels (peer_node_id, hsm_dbid, funder,"
                " channel_id, funding_txid, funding_outidx, funding_sat,"
                " state, to_local_msat, to_remote_msat, feerate_per_kw,"
                " opener_is_local, anchors, reserve_local_msat,"
                " reserve_remote_msat, next_local_commit,"
                " next_remote_commit, delay_on_local, delay_on_remote,"
                " their_dust_limit, their_funding_pub, their_basepoints,"
                " their_points, their_last_secret)"
                " VALUES (?,?,?,?,?,?,?,'recover',0,0,253,?,1,0,0,0,0,"
                " 144,144,546,x'',x'',x'',x'')",
                (c["peer_node_id"], 0, int(c["opener_is_local"]),
                 c["channel_id"], c["funding_txid"], c["funding_outidx"],
                 c["funding_sat"], int(c["opener_is_local"])))


def attach_backup_commands(rpc, svc: PeerStorageService) -> None:
    """staticbackup / emergencyrecover RPC surface."""

    async def staticbackup() -> dict:
        blob = svc.our_blob()
        return {"scb": blob.hex() if blob else None,
                "peers_holding": len(svc.stored)}

    async def emergencyrecover(scb: str | None = None) -> dict:
        chans = svc.emergencyrecover(bytes.fromhex(scb) if scb else None)
        return {"stubs": [{
            "channel_id": c["channel_id"].hex(),
            "peer_id": c["peer_node_id"].hex(),
            "funding_txid": c["funding_txid"].hex(),
            "funding_sat": c["funding_sat"],
        } for c in chans]}

    async def getemergencyrecoverdata() -> dict:
        """The raw encrypted SCB blob, as the chanbackup plugin's
        getemergencyrecoverdata returns it."""
        blob = svc.our_blob()
        return {"filedata": blob.hex() if blob else ""}

    async def recoverchannel(scb: list) -> dict:
        """Restore channel stubs from individual UNENCRYPTED scb
        entries (json_recoverchannel: each element is one channel's
        packed backup hex, as `staticbackup` lists them)."""
        stubs = []
        for entry in scb:
            c, _ = _unpack_chan(bytes.fromhex(entry), 0)
            if svc.wallet is not None:
                svc._restore_stub(c)
            stubs.append(c["channel_id"].hex())
        return {"stubs": stubs}

    rpc.register("staticbackup", staticbackup)
    rpc.register("emergencyrecover", emergencyrecover)
    rpc.register("getemergencyrecoverdata", getemergencyrecoverdata)
    rpc.register("recoverchannel", recoverchannel)
