"""On-chain UTXO wallet: deposits, reservations, funding, withdraw.

Parity targets: wallet/wallet.c (outputs table, wallet_add_utxo /
wallet_confirm_tx paths), wallet/txfilter.c (block-scan for our
scriptpubkeys), wallet/reservation.c (UTXO reservations expiring at
height+72), wallet/walletrpc.c (newaddr / listfunds / withdraw /
fundpsbt) and lightningd/chaintopology.c's deposit flow.

Keys are BIP32 m/0/keyindex P2WPKH, derived from the hsm's bip32 seed
(hsmd/hsmd.c hands lightningd the base at init); the wallet only ever
sees public material — signing rides the hsm's CAP_SIGN_ONCHAIN door
(`sign_withdrawal`), which signs every wallet input of a PSBT-shaped tx
in one batched device call (vs the reference's per-input loop inside
hsmd's sign_withdrawal handler).
"""
from __future__ import annotations

from dataclasses import dataclass

from ..btc import address as ADDR
from ..btc import script as SCRIPT
from ..btc.bip32 import ExtKey
from ..btc.tx import SIGHASH_ALL, Tx, TxInput, TxOutput

# reservation lifetime in blocks (wallet/reservation.c RESERVATION_INC)
RESERVATION_BLOCKS = 72
DUST_LIMIT_SAT = 546


class WalletError(Exception):
    pass


class KeyManager:
    """Derives wallet keys/addresses; persists the high-water keyindex."""

    def __init__(self, base: ExtKey, db, hrp: str = "bcrt"):
        self.base = base.ckd(0)          # external chain m/0
        self.db = db
        self.hrp = hrp
        self._cache: dict[int, ExtKey] = {}

    def key(self, index: int) -> ExtKey:
        k = self._cache.get(index)
        if k is None:
            k = self._cache[index] = self.base.ckd(index)
        return k

    def pubkey(self, index: int) -> bytes:
        return self.key(index).pubkey

    def scriptpubkey(self, index: int) -> bytes:
        return SCRIPT.p2wpkh(self.pubkey(index))

    def address(self, index: int) -> str:
        return ADDR.p2wpkh(self.pubkey(index), self.hrp)

    @property
    def max_index(self) -> int:
        v = self.db.get_var("bip32_max_index")
        return int(v) if v is not None else -1

    def fresh_index(self) -> int:
        nxt = self.max_index + 1
        self.db.set_var("bip32_max_index", nxt)
        return nxt


class TxFilter:
    """scriptpubkey → keyindex lookup for block scanning
    (wallet/txfilter.c:1)."""

    def __init__(self):
        self._by_spk: dict[bytes, int] = {}

    def add(self, scriptpubkey: bytes, keyindex: int) -> None:
        self._by_spk[scriptpubkey] = keyindex

    def match(self, tx: Tx) -> list[tuple[int, int, bytes, int]]:
        """[(vout, amount_sat, scriptpubkey, keyindex)] of ours in tx."""
        out = []
        for i, o in enumerate(tx.outputs):
            idx = self._by_spk.get(o.script_pubkey)
            if idx is not None:
                out.append((i, o.amount_sat, o.script_pubkey, idx))
        return out


@dataclass
class Utxo:
    txid: bytes
    vout: int
    amount_sat: int
    scriptpubkey: bytes
    keyindex: int
    status: str                      # available | reserved | spent
    reserved_til: int | None
    confirmation_height: int | None

    @property
    def outpoint(self) -> tuple[bytes, int]:
        return (self.txid, self.vout)


class OnchainWallet:
    """The node's coins.  All mutations are write-ahead into the db."""

    def __init__(self, db, keyman: KeyManager):
        self.db = db
        self.keyman = keyman
        self.filter = TxFilter()
        # every address ever issued watches forever (reference loads the
        # whole scriptpubkeys set into its txfilter at startup)
        for i in range(self.keyman.max_index + 1):
            self.filter.add(self.keyman.scriptpubkey(i), i)
        self.height = 0

    # -- address issuance -------------------------------------------------

    def newaddr(self) -> dict:
        idx = self.keyman.fresh_index()
        spk = self.keyman.scriptpubkey(idx)
        self.filter.add(spk, idx)
        return {"bech32": self.keyman.address(idx), "keyindex": idx}

    def listaddresses(self) -> list[dict]:
        return [{"keyindex": i, "bech32": self.keyman.address(i)}
                for i in range(self.keyman.max_index + 1)]

    # -- chain feed (wire into ChainTopology) -----------------------------

    def attach(self, topology) -> None:
        topology.on_block(self.on_block)
        topology.on_reorg(self.on_reorg)

    def on_block(self, height: int, block) -> None:
        self.height = height
        with self.db.transaction() as c:
            for tx in block.txs:
                txid = tx.txid()
                # deposits: outputs paying one of our scriptpubkeys
                for vout, amount, spk, keyindex in self.filter.match(tx):
                    c.execute(
                        "INSERT INTO outputs (txid, vout, amount_sat,"
                        " scriptpubkey, keyindex, status,"
                        " confirmation_height) VALUES (?,?,?,?,?,?,?)"
                        " ON CONFLICT(txid, vout) DO UPDATE SET"
                        " confirmation_height=excluded.confirmation_height",
                        (txid, vout, amount, spk, keyindex, "available",
                         height))
                # spends of our outputs (any tx, ours or not)
                for vin in tx.inputs:
                    c.execute(
                        "UPDATE outputs SET status='spent', spent_height=?,"
                        " spending_txid=? WHERE txid=? AND vout=?",
                        (height, txid, vin.txid, vin.vout))
            # reservation expiry (reservation.c: height-based timeout)
            c.execute(
                "UPDATE outputs SET status='available', reserved_til=NULL"
                " WHERE status='reserved' AND reserved_til IS NOT NULL"
                " AND reserved_til <= ?", (height,))

    def on_reorg(self, new_height: int) -> None:
        self.height = min(self.height, new_height)
        with self.db.transaction() as c:
            c.execute(
                "UPDATE outputs SET confirmation_height=NULL"
                " WHERE confirmation_height > ?", (new_height,))
            c.execute(
                "UPDATE outputs SET status='available', spent_height=NULL,"
                " spending_txid=NULL"
                " WHERE status='spent' AND spent_height > ?", (new_height,))

    # -- queries ----------------------------------------------------------

    def _rows(self, where: str = "", args: tuple = ()) -> list[Utxo]:
        cur = self.db.conn.execute(
            "SELECT txid, vout, amount_sat, scriptpubkey, keyindex,"
            f" status, reserved_til, confirmation_height FROM outputs {where}",
            args)
        return [Utxo(bytes(r[0]), r[1], r[2], bytes(r[3]), r[4], r[5],
                     r[6], r[7]) for r in cur.fetchall()]

    def utxos(self, include_reserved: bool = False) -> list[Utxo]:
        if include_reserved:
            return self._rows("WHERE status != 'spent'")
        return self._rows("WHERE status = 'available'")

    def balance_sat(self) -> int:
        return sum(u.amount_sat for u in self.utxos())

    def listfunds(self) -> list[dict]:
        out = []
        for u in self.utxos(include_reserved=True):
            out.append({
                "txid": u.txid.hex(), "output": u.vout,
                "amount_msat": u.amount_sat * 1000,
                "scriptpubkey": u.scriptpubkey.hex(),
                "address": ADDR.from_scriptpubkey(u.scriptpubkey,
                                                  self.keyman.hrp),
                "status": ("confirmed" if u.confirmation_height is not None
                           else "unconfirmed"),
                "reserved": u.status == "reserved",
                **({"blockheight": u.confirmation_height}
                   if u.confirmation_height is not None else {}),
            })
        return out

    # -- reservations (wallet/reservation.c) ------------------------------

    def reserve(self, outpoints: list[tuple[bytes, int]],
                blocks: int = RESERVATION_BLOCKS) -> None:
        til = self.height + blocks
        with self.db.transaction() as c:
            for txid, vout in outpoints:
                cur = c.execute(
                    "UPDATE outputs SET status='reserved', reserved_til=?"
                    " WHERE txid=? AND vout=? AND status='available'",
                    (til, txid, vout))
                if cur.rowcount != 1:
                    raise WalletError(
                        f"cannot reserve {txid.hex()}:{vout} (missing or"
                        " not available)")

    def unreserve(self, outpoints: list[tuple[bytes, int]]) -> None:
        with self.db.transaction() as c:
            for txid, vout in outpoints:
                c.execute(
                    "UPDATE outputs SET status='available',"
                    " reserved_til=NULL WHERE txid=? AND vout=?"
                    " AND status='reserved'", (txid, vout))

    def mark_spent(self, outpoints: list[tuple[bytes, int]],
                   spending_txid: bytes) -> None:
        """Inputs of a tx we just broadcast: spent immediately (the
        confirmation scan is idempotent on them)."""
        with self.db.transaction() as c:
            for txid, vout in outpoints:
                c.execute(
                    "UPDATE outputs SET status='spent', spending_txid=?"
                    " WHERE txid=? AND vout=?", (spending_txid, txid, vout))

    def add_unconfirmed_change(self, tx: Tx) -> None:
        """Track our own outputs of a tx we broadcast before any block
        confirms it (spendable immediately, like the reference)."""
        txid = tx.txid()
        with self.db.transaction() as c:
            for vout, amount, spk, keyindex in self.filter.match(tx):
                c.execute(
                    "INSERT OR IGNORE INTO outputs (txid, vout, amount_sat,"
                    " scriptpubkey, keyindex, status) VALUES (?,?,?,?,?,?)",
                    (txid, vout, amount, spk, keyindex, "available"))

    # -- coin selection + tx building -------------------------------------

    @staticmethod
    def _input_weight() -> int:
        # P2WPKH input: 36 outpoint + 1 scriptlen + 4 sequence = 41 vbytes
        # base, witness ~(73 sig + 34 key + 2) / 4 ≈ 27.25 → 273 WU total
        return 41 * 4 + 109

    def select_coins(self, amount_sat: int, feerate_per_kw: int,
                     base_weight: int, confirmed_only: bool = False,
                     min_conf: int = 0) -> tuple[list[Utxo], int, int]:
        """Largest-first selection (the reference delegates to
        bitcoind-style knapsack; largest-first keeps change counts low
        and is deterministic for tests).  Returns (picked, fee, change).
        """
        cands = [u for u in self.utxos()
                 if not confirmed_only or u.confirmation_height is not None]
        if min_conf:
            cands = [u for u in cands
                     if u.confirmation_height is not None
                     and self.height - u.confirmation_height + 1 >= min_conf]
        cands.sort(key=lambda u: -u.amount_sat)
        picked: list[Utxo] = []
        total = 0
        weight = base_weight
        for u in cands:
            picked.append(u)
            total += u.amount_sat
            weight += self._input_weight()
            fee = feerate_per_kw * weight // 1000
            if total >= amount_sat + fee:
                # change output adds 31 vbytes = 124 WU
                change_fee = feerate_per_kw * (weight + 124) // 1000
                change = total - amount_sat - change_fee
                if change < DUST_LIMIT_SAT:
                    return picked, total - amount_sat, 0
                return picked, change_fee, change
        raise WalletError(
            f"insufficient funds: need {amount_sat} sat + fee,"
            f" have {total} sat across {len(picked)} utxos")

    def fund_tx(self, outputs: list[TxOutput], feerate_per_kw: int,
                confirmed_only: bool = False, reserve: bool = True,
                extra_weight: int = 0, reserve_blocks: int =
                RESERVATION_BLOCKS) -> tuple[Tx, list[Utxo], int | None]:
        """Build a funded tx paying `outputs`, adding inputs + change.
        Returns (tx, picked_utxos, change_vout|None).  Inputs are
        reserved (fundpsbt semantics) so concurrent fundings don't
        double-spend each other.  extra_weight: caller-supplied weight
        (fundpsbt startweight) the fee must also cover."""
        amount = sum(o.amount_sat for o in outputs)
        base_weight = (4 + 1 + 1 + 4 + 2) * 4 + extra_weight \
            + sum(len(o.serialize()) for o in outputs) * 4
        picked, fee, change = self.select_coins(
            amount, feerate_per_kw, base_weight, confirmed_only)
        tx = Tx(version=2)
        for u in picked:
            tx.inputs.append(TxInput(u.txid, u.vout, sequence=0xFFFFFFFD))
        tx.outputs = list(outputs)
        change_vout = None
        if change > 0:
            idx = self.keyman.fresh_index()
            spk = self.keyman.scriptpubkey(idx)
            self.filter.add(spk, idx)
            change_vout = len(tx.outputs)
            tx.outputs.append(TxOutput(change, spk))
        if reserve:
            self.reserve([u.outpoint for u in picked],
                         blocks=reserve_blocks)
        return tx, picked, change_vout

    def utxo_meta(self, tx: Tx) -> list[tuple[int, int] | None]:
        """Per-input (amount_sat, keyindex) for OUR inputs, None for
        foreign ones — the shape hsm.sign_withdrawal consumes."""
        meta: list[tuple[int, int] | None] = []
        for vin in tx.inputs:
            row = self.db.conn.execute(
                "SELECT amount_sat, keyindex FROM outputs"
                " WHERE txid=? AND vout=?", (vin.txid, vin.vout)).fetchone()
            meta.append((row[0], row[1]) if row is not None else None)
        return meta


def wallet_input_digests(tx: Tx, meta, key_for_index):
    """Per wallet input: (input_index, sighash_digest, privkey, pubkey).
    key_for_index: keyindex → ExtKey.  The single source of the P2WPKH
    scriptCode/sighash recipe (used by both the standalone signer below
    and Hsm.sign_withdrawal — keep it in one place so a sighash change
    can never drift between them)."""
    items = []
    for i, m in enumerate(meta):
        if m is None:
            continue
        amount_sat, keyindex = m
        key = key_for_index(keyindex)
        pub = key.pubkey
        # BIP143 P2WPKH scriptCode: the implied P2PKH script (the length
        # varint is written by sighash_segwit itself)
        code = b"\x76\xa9\x14" + SCRIPT.hash160(pub) + b"\x88\xac"
        items.append((i, tx.sighash_segwit(i, code, amount_sat,
                                           SIGHASH_ALL), key.key, pub))
    return items


def sign_wallet_inputs(tx: Tx, meta, keyman: KeyManager) -> Tx:
    """Fill P2WPKH witnesses for every input with (amount, keyindex)
    metadata.  Standalone (non-hsm) variant used by tests; the daemon
    path goes through Hsm.sign_withdrawal which adds the capability
    check + batched low-R device signing."""
    from ..btc.tx import sig_to_der
    from ..crypto import ref_python as ref

    for i, digest, priv, pub in wallet_input_digests(tx, meta, keyman.key):
        r, s = ref.ecdsa_sign(digest, priv)
        tx.inputs[i].witness = [sig_to_der(r, s), pub]
    return tx
