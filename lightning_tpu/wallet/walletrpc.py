"""Wallet RPC commands: newaddr / listfunds / withdraw / fundpsbt /
reserveinputs / unreserveinputs.

Parity target: wallet/walletrpc.c (json_newaddr :?, json_listfunds,
json_withdraw, json_fundpsbt/json_utxopsbt) and wallet/reservation.c's
reserve RPC trio, over our OnchainWallet.
"""
from __future__ import annotations

import base64

from ..btc import address as ADDR
from ..btc.psbt import Psbt, PsbtInput
from ..btc.tx import Tx, TxOutput
from .onchain import OnchainWallet, WalletError


def _parse_outpoints(items: list[str]) -> list[tuple[bytes, int]]:
    out = []
    for it in items:
        txid_hex, vout = it.split(":")
        out.append((bytes.fromhex(txid_hex), int(vout)))
    return out


def _feerate_per_kw(feerate, topology) -> int:
    # topology.feerate() is sat/kVB (FeeEstimates contract) — per-kw
    # is a quarter of that (4 WU per vbyte)
    if feerate is None or feerate == "normal":
        return topology.feerate(12) // 4 if topology is not None else 1250
    if feerate == "urgent":
        return topology.feerate(2) // 4 if topology is not None else 1875
    if feerate == "slow":
        return topology.feerate(100) // 4 if topology is not None else 253
    s = str(feerate)
    if s.endswith("perkw"):
        return int(s[:-5])
    if s.endswith("perkb"):
        return int(s[:-5]) // 4
    return int(s)


def _to_psbt(tx: Tx, wallet: OnchainWallet) -> str:
    p = Psbt.from_tx(Tx(tx.version, [
        # strip witnesses: a PSBT's unsigned tx must be witness-free
        type(i)(i.txid, i.vout, b"", i.sequence) for i in tx.inputs
    ], list(tx.outputs), tx.locktime))
    for i, vin in enumerate(tx.inputs):
        row = wallet.db.conn.execute(
            "SELECT amount_sat, scriptpubkey FROM outputs"
            " WHERE txid=? AND vout=?", (vin.txid, vin.vout)).fetchone()
        if row is not None:
            p.inputs[i].witness_utxo = TxOutput(row[0], bytes(row[1]))
    return base64.b64encode(p.serialize()).decode()


def attach_wallet_commands(rpc, wallet: OnchainWallet, hsm=None,
                           hsm_client=None, backend=None,
                           topology=None) -> None:
    async def newaddr(addresstype: str = "bech32") -> dict:
        if addresstype not in ("bech32", "all"):
            raise ValueError(f"unsupported addresstype {addresstype!r}")
        return {"bech32": wallet.newaddr()["bech32"]}

    async def listaddresses() -> dict:
        return {"addresses": wallet.listaddresses()}

    async def listfunds(spent: bool = False) -> dict:
        return {"outputs": wallet.listfunds(), "channels": []}

    async def fundpsbt(satoshi, feerate=None, startweight: int = 0,
                       reserve: int = 72, min_witness_weight: int = 0,
                       excess_as_change: bool = False) -> dict:
        """Reserve inputs summing past `satoshi` + fee; return the
        funding skeleton as a PSBT (walletrpc.c json_fundpsbt).
        startweight: weight of the outputs the CALLER will add — it is
        part of the fee the selection must cover.  excess_msat already
        has the fee deducted (lightningd contract)."""
        per_kw = _feerate_per_kw(feerate, topology)
        from ..btc.tx import TxInput
        from .onchain import OnchainWallet as _W

        if satoshi == "all":
            utxos = wallet.utxos()
            if not utxos:
                raise WalletError("no available utxos")
            tx = Tx(version=2)
            for u in utxos:
                tx.inputs.append(TxInput(u.txid, u.vout,
                                         sequence=0xFFFFFFFD))
            weight = (4 + 1 + 1 + 4 + 2) * 4 + startweight \
                + len(utxos) * _W._input_weight()
            fee = per_kw * weight // 1000
            total = sum(u.amount_sat for u in utxos)
            if total <= fee:
                raise WalletError("available funds would not cover the fee")
            wallet.reserve([u.outpoint for u in utxos], blocks=reserve)
            picked, change_vout = utxos, None
            excess = total - fee
        else:
            amount = int(satoshi)
            tx, picked, change_vout = wallet.fund_tx(
                [TxOutput(amount, b"\x00" * 22)], per_kw,
                extra_weight=startweight, reserve_blocks=reserve)
            # fundpsbt returns inputs + change only; the caller adds
            # its own outputs (the placeholder primary output is ours
            # to drop)
            tx.outputs.pop(0)
            if change_vout is not None:
                change_vout = 0
            excess = amount
        return {
            "psbt": _to_psbt(tx, wallet),
            "feerate_per_kw": per_kw,
            "reservations": [
                {"txid": u.txid.hex(), "vout": u.vout, "reserved": True}
                for u in picked],
            "excess_msat": excess * 1000,
            **({"change_outnum": change_vout}
               if change_vout is not None else {}),
        }

    async def reserveinputs(psbt: str = None, outpoints: list = None,
                            exclusive: bool = True,
                            reserve: int = 72) -> dict:
        pts = _parse_outpoints(outpoints or [])
        wallet.reserve(pts, blocks=reserve)
        return {"reservations": [
            {"txid": t.hex(), "vout": v, "reserved": True}
            for t, v in pts]}

    async def unreserveinputs(psbt: str = None,
                              outpoints: list = None) -> dict:
        pts = _parse_outpoints(outpoints or [])
        wallet.unreserve(pts)
        return {"reservations": [
            {"txid": t.hex(), "vout": v, "reserved": False}
            for t, v in pts]}

    async def withdraw(destination: str, satoshi, feerate=None,
                       minconf: int = 0) -> dict:
        per_kw = _feerate_per_kw(feerate, topology)
        spk = ADDR.to_scriptpubkey(destination, wallet.keyman.hrp)
        if satoshi == "all":
            utxos = [u for u in wallet.utxos()
                     if not minconf or (
                         u.confirmation_height is not None
                         and wallet.height - u.confirmation_height + 1
                         >= minconf)]
            if not utxos:
                raise WalletError("no available utxos")
            from ..btc.tx import TxInput

            tx = Tx(version=2)
            for u in utxos:
                tx.inputs.append(TxInput(u.txid, u.vout,
                                         sequence=0xFFFFFFFD))
            tx.outputs = [TxOutput(0, spk)]
            weight = tx.weight() + len(utxos) * 109  # witness-to-come
            fee = per_kw * weight // 1000
            total = sum(u.amount_sat for u in utxos)
            if total <= fee:
                raise WalletError("funds would not cover the fee")
            tx.outputs[0].amount_sat = total - fee
            picked = utxos
            # reserve BEFORE the awaited broadcast: a concurrent
            # fundpsbt/withdraw task must not see these as available
            wallet.reserve([u.outpoint for u in picked])
        else:
            tx, picked, _ = wallet.fund_tx(
                [TxOutput(int(satoshi), spk)], per_kw,
                confirmed_only=bool(minconf))
        meta = wallet.utxo_meta(tx)
        if hsm is not None:
            hsm.sign_withdrawal(hsm_client, tx, meta)
        else:
            from .onchain import sign_wallet_inputs

            sign_wallet_inputs(tx, meta, wallet.keyman)
        raw = tx.serialize()
        if backend is not None:
            ok, err = await backend.sendrawtransaction(raw)
            if not ok:
                wallet.unreserve([u.outpoint for u in picked])
                raise WalletError(f"sendrawtransaction failed: {err}")
        txid = tx.txid()
        wallet.mark_spent([u.outpoint for u in picked], txid)
        wallet.add_unconfirmed_change(tx)
        return {"tx": raw.hex(), "txid": txid.hex()}

    async def signpsbt(psbt: str, signonly: list | None = None) -> dict:
        """Sign every PSBT input the wallet owns (walletrpc.c
        json_signpsbt; the HSM signs when attached)."""
        p = Psbt.parse(base64.b64decode(psbt))
        tx = p.tx
        meta = wallet.utxo_meta(tx)
        if signonly is not None:
            meta = [m if i in signonly else None
                    for i, m in enumerate(meta)]
        if not any(m is not None for m in meta):
            raise WalletError("no wallet inputs to sign")
        if hsm is not None:
            hsm.sign_withdrawal(hsm_client, tx, meta)
        else:
            from .onchain import sign_wallet_inputs

            sign_wallet_inputs(tx, meta, wallet.keyman)
        for i, vin in enumerate(tx.inputs):
            if vin.witness:
                p.inputs[i].final_witness = list(vin.witness)
                vin.witness = []
        return {"signed_psbt": base64.b64encode(p.serialize()).decode()}

    async def sendpsbt(psbt: str, reserve: bool = False) -> dict:
        """Finalize + extract + broadcast (walletrpc.c json_sendpsbt)."""
        p = Psbt.parse(base64.b64decode(psbt))
        p.finalize()
        tx = p.extract()
        raw = tx.serialize()
        if backend is not None:
            ok, err = await backend.sendrawtransaction(raw)
            if not ok:
                raise WalletError(f"sendrawtransaction failed: {err}")
        txid = tx.txid()
        ours = [i for i, m in enumerate(wallet.utxo_meta(tx))
                if m is not None]
        if ours:
            wallet.mark_spent(
                [(tx.inputs[i].txid, tx.inputs[i].vout) for i in ours],
                txid)
        wallet.add_unconfirmed_change(tx)
        return {"tx": raw.hex(), "txid": txid.hex()}

    async def utxopsbt(satoshi, feerate=None, startweight: int = 0,
                       utxos: list | None = None, reserve: int = 72,
                       reservedok: bool = False) -> dict:
        """fundpsbt from CALLER-CHOSEN utxos (walletrpc.c
        json_utxopsbt)."""
        from ..btc.tx import TxInput
        from .onchain import OnchainWallet as _W

        per_kw = _feerate_per_kw(feerate, topology)
        pts = _parse_outpoints(utxos or [])
        if not pts:
            raise WalletError("utxos required")
        rows = []
        for t, v in pts:
            row = wallet.db.conn.execute(
                "SELECT amount_sat, status FROM outputs"
                " WHERE txid=? AND vout=?", (t, v)).fetchone()
            if row is None:
                raise WalletError(f"unknown utxo {t.hex()}:{v}")
            if row[1] != "available" and not reservedok:
                raise WalletError(f"utxo {t.hex()}:{v} is {row[1]}")
            rows.append(row[0])
        tx = Tx(version=2)
        for t, v in pts:
            tx.inputs.append(TxInput(t, v, sequence=0xFFFFFFFD))
        weight = (4 + 1 + 1 + 4 + 2) * 4 + startweight \
            + len(pts) * _W._input_weight()
        fee = per_kw * weight // 1000
        total = sum(rows)
        want = 0 if satoshi == "all" else int(satoshi)
        if total < want + fee:
            raise WalletError(
                f"utxos total {total} < amount {want} + fee {fee}")
        wallet.reserve(pts, blocks=reserve)
        excess = total - fee if satoshi == "all" else total - want - fee
        return {"psbt": _to_psbt(tx, wallet), "feerate_per_kw": per_kw,
                "excess_msat": excess * 1000,
                "reservations": [
                    {"txid": t.hex(), "vout": v, "reserved": True}
                    for t, v in pts]}

    async def addpsbtoutput(satoshi: int, psbt: str | None = None,
                            destination: str | None = None) -> dict:
        """Append an output paying us (or `destination`) to a PSBT,
        creating one if absent (walletrpc.c json_addpsbtoutput)."""
        if psbt is not None:
            p = Psbt.parse(base64.b64decode(psbt))
        else:
            p = Psbt.from_tx(Tx(version=2))
        if destination is not None:
            spk = ADDR.to_scriptpubkey(destination, wallet.keyman.hrp)
        else:
            addr = wallet.newaddr()
            spk = ADDR.to_scriptpubkey(addr["bech32"], wallet.keyman.hrp)
        p.tx.outputs.append(TxOutput(int(satoshi), spk))
        p.outputs.append({})
        return {"psbt": base64.b64encode(p.serialize()).decode(),
                "outnum": len(p.tx.outputs) - 1,
                "estimated_added_weight": (8 + 1 + len(spk)) * 4}

    async def listtransactions() -> dict:
        """Wallet-relevant transactions from the outputs table
        (walletrpc.c json_listtransactions scope)."""
        txs: dict[bytes, dict] = {}
        for r in wallet.db.conn.execute(
                "SELECT txid, vout, amount_sat, confirmation_height,"
                " spending_txid, spent_height FROM outputs"):
            txid = bytes(r[0])
            e = txs.setdefault(txid, {
                "hash": txid.hex(),
                "blockheight": r[3] or 0, "outputs": []})
            e["outputs"].append({"index": r[1], "amount_msat": r[2] * 1000})
            if r[4] is not None:
                txs.setdefault(bytes(r[4]), {
                    "hash": bytes(r[4]).hex(),
                    "blockheight": r[5] or 0, "outputs": []})
        return {"transactions": sorted(txs.values(),
                                       key=lambda t: t["blockheight"])}

    async def signmessagewithkey(message: str, address: str) -> dict:
        """BIP137 recoverable signature with the key behind one of OUR
        wallet addresses (reference signmessagewithkey; header 39+recid
        marks a bech32 p2wpkh signer)."""
        import hashlib

        from ..crypto import ref_python as ref
        from ..utils import zbase32 as Z

        idx = None
        for a in wallet.listaddresses():
            if a["bech32"] == address:
                idx = a["keyindex"]
                break
        if idx is None:
            raise WalletError(f"address {address} is not from this "
                              "wallet")
        key = wallet.keyman.key(idx)
        from ..btc.tx import write_varint

        msg = message.encode()
        payload = (write_varint(len(b"Bitcoin Signed Message:\n"))
                   + b"Bitcoin Signed Message:\n"
                   + write_varint(len(msg)) + msg)
        digest = hashlib.sha256(
            hashlib.sha256(payload).digest()).digest()
        r, s = ref.ecdsa_sign(digest, key.key)
        z = int.from_bytes(digest, "big")
        pub = ref.pubkey_create(key.key)
        recid = next(c for c in range(4)
                     if (q := Z._recover(z, r, s, c)) is not None
                     and q.x == pub.x and q.y == pub.y)
        sig65 = bytes([39 + recid]) + r.to_bytes(32, "big") \
            + s.to_bytes(32, "big")
        return {"address": address, "pubkey": key.pubkey.hex(),
                "signature": base64.b64encode(sig65).decode()}

    async def setpsbtversion(psbt: str, version: int) -> dict:
        """Convert a PSBT between v0 (BIP174) and v2 (BIP370)
        (walletrpc setpsbtversion)."""
        p = Psbt.parse(base64.b64decode(psbt))
        if int(version) == 0:
            raw = p.serialize_v0()
        elif int(version) == 2:
            raw = p.serialize_v2()
        else:
            raise WalletError(f"unsupported psbt version {version}")
        return {"psbt": base64.b64encode(raw).decode()}

    rpc.register("setpsbtversion", setpsbtversion)
    rpc.register("signmessagewithkey", signmessagewithkey)
    rpc.register("signpsbt", signpsbt)
    rpc.register("sendpsbt", sendpsbt)
    rpc.register("utxopsbt", utxopsbt)
    rpc.register("addpsbtoutput", addpsbtoutput)
    rpc.register("listtransactions", listtransactions)
    rpc.register("newaddr", newaddr)
    rpc.register("listaddresses", listaddresses)
    rpc.register("listfunds", listfunds)
    rpc.register("fundpsbt", fundpsbt)
    rpc.register("reserveinputs", reserveinputs)
    rpc.register("unreserveinputs", unreserveinputs)
    rpc.register("withdraw", withdraw)

    if backend is not None and hasattr(backend, "generate"):
        # regtest-in-a-box controls (pyln-testing's bitcoind.generate /
        # faucet role) — only exist on the FakeBitcoind backend
        from ..btc.tx import TxInput

        async def dev_generate(blocks: int = 1) -> dict:
            backend.generate(int(blocks))
            if topology is not None:
                await topology.sync_once()
            return {"blockheight": topology.height
                    if topology is not None else None}

        async def dev_faucet(satoshi: int) -> dict:
            """Mint a deposit to a fresh wallet address and confirm it."""
            addr = wallet.newaddr()["bech32"]
            tx = Tx(inputs=[TxInput(b"\x00" * 32, 0xFFFFFFFF)],
                    outputs=[TxOutput(int(satoshi),
                                      ADDR.to_scriptpubkey(addr))])
            backend.mempool[tx.txid()] = tx
            backend.generate(1)
            if topology is not None:
                await topology.sync_once()
            return {"txid": tx.txid().hex(), "address": addr}

        rpc.register("dev-generate", dev_generate)
        rpc.register("dev-faucet", dev_faucet)
