"""Channel persistence: save/load the full protocol state of a channel.

Parity target: wallet/wallet.c's channels + channel_htlcs + shachains
tables.  The save path is called by channeld BEFORE every wire ack
(write-ahead semantics, SURVEY §5); the load path reconstructs a
Channeld after restart, ready for channel_reestablish.
"""
from __future__ import annotations

import json

from ..btc import keys as K
from ..channel.commitment import Htlc
from ..channel.state import ChannelCore, ChannelState, HtlcState, LiveHtlc
from ..crypto import ref_python as ref
from .db import Db


def _pack_basepoints(bp: K.Basepoints) -> bytes:
    ser = ref.pubkey_serialize
    return b"".join([ser(bp.funding_pubkey), ser(bp.revocation),
                     ser(bp.payment), ser(bp.delayed_payment), ser(bp.htlc)])


def _unpack_basepoints(raw: bytes) -> K.Basepoints:
    ks = [ref.pubkey_parse(raw[i * 33:(i + 1) * 33]) for i in range(5)]
    return K.Basepoints(*ks)


def _pack_retransmit(sealed: bool, msgs: list[bytes]) -> bytes:
    out = [b"\x01" if sealed else b"\x00"]
    for m in msgs:
        out.append(len(m).to_bytes(4, "big"))
        out.append(m)
    return b"".join(out)


def _unpack_retransmit(raw: bytes) -> tuple[bool, list[bytes]]:
    if not raw:
        return False, []
    sealed = raw[0] == 1
    msgs, off = [], 1
    while off < len(raw):
        ln = int.from_bytes(raw[off:off + 4], "big")
        msgs.append(bytes(raw[off + 4:off + 4 + ln]))
        off += 4 + ln
    return sealed, msgs


class Wallet:
    def __init__(self, db: Db):
        self.db = db

    # -- channels ---------------------------------------------------------

    def save_channel(self, ch, peer_node_id: bytes, hsm_dbid: int) -> int:
        """Insert-or-update the complete state of a Channeld.  Returns the
        channel's db id (stable across saves via ch.wallet_id)."""
        core = ch.core
        points = json.dumps(
            {str(n): ref.pubkey_serialize(p).hex()
             for n, p in ch.their_points.items()}
        )
        fields = dict(
            peer_node_id=peer_node_id, hsm_dbid=hsm_dbid,
            funder=int(ch.funder), channel_id=ch.channel_id,
            funding_txid=ch.funding_txid, funding_outidx=ch.funding_outidx,
            funding_sat=ch.funding_sat, state=core.state.value,
            to_local_msat=core.to_local_msat,
            to_remote_msat=core.to_remote_msat,
            feerate_per_kw=core.feerate_per_kw,
            opener_is_local=int(core.opener_is_local),
            anchors=int(core.anchors),
            reserve_local_msat=core.reserve_local_msat,
            reserve_remote_msat=core.reserve_remote_msat,
            next_local_commit=ch.next_local_commit,
            next_remote_commit=ch.next_remote_commit,
            next_htlc_id_ours=core.next_htlc_id[True],
            next_htlc_id_theirs=core.next_htlc_id[False],
            delay_on_local=ch.delay_on_local,
            delay_on_remote=ch.delay_on_remote,
            their_dust_limit=ch.their_dust_limit,
            their_funding_pub=ch.their_funding_pub,
            their_basepoints=_pack_basepoints(ch.their_base),
            their_points=points,
            their_last_secret=ch.their_last_secret,
            our_shutdown_script=ch.our_shutdown_script,
            their_shutdown_script=ch.their_shutdown_script,
            retransmit=_pack_retransmit(ch.retransmit_sealed,
                                        ch.retransmit),
            inflight=(json.dumps(ch.inflight).encode()
                      if getattr(ch, "inflight", None) else b""),
            announce=int(getattr(ch, "announce", False)),
        )
        with self.db.transaction() as c:
            if getattr(ch, "wallet_id", None) is None:
                cols = ", ".join(fields)
                ph = ", ".join("?" * len(fields))
                cur = c.execute(
                    f"INSERT INTO channels ({cols}) VALUES ({ph})",
                    tuple(fields.values()),
                )
                ch.wallet_id = cur.lastrowid
            else:
                sets = ", ".join(f"{k}=?" for k in fields)
                c.execute(
                    f"UPDATE channels SET {sets} WHERE id=?",
                    (*fields.values(), ch.wallet_id),
                )
            # htlcs + shachain are replaced wholesale inside the SAME
            # transaction — the commit point makes the snapshot atomic
            c.execute("DELETE FROM htlcs WHERE channel_ref=?", (ch.wallet_id,))
            for (by_us, hid), lh in core.htlcs.items():
                c.execute(
                    "INSERT INTO htlcs VALUES (?,?,?,?,?,?,?,?,?,?)",
                    (ch.wallet_id, int(by_us), hid, lh.htlc.amount_msat,
                     lh.htlc.payment_hash, lh.htlc.cltv_expiry,
                     lh.state.name, lh.preimage, lh.fail_reason, lh.onion),
                )
            c.execute("DELETE FROM shachain_slots WHERE channel_ref=?",
                      (ch.wallet_id,))
            for slot, entry in enumerate(ch.their_secrets.known):
                if entry is not None:
                    c.execute(
                        "INSERT INTO shachain_slots VALUES (?,?,?,?)",
                        (ch.wallet_id, slot, entry[0], entry[1]),
                    )
        return ch.wallet_id

    def list_channels(self) -> list[dict]:
        cur = self.db.conn.execute("SELECT * FROM channels")
        names = [d[0] for d in cur.description]
        return [dict(zip(names, row)) for row in cur.fetchall()]

    def load_channel_state(self, wallet_id: int) -> dict:
        cur = self.db.conn.execute("SELECT * FROM channels WHERE id=?",
                                   (wallet_id,))
        row = cur.fetchone()
        if row is None:
            raise KeyError(f"no channel {wallet_id}")
        names = [d[0] for d in cur.description]
        return dict(zip(names, row))

    def restore_into(self, ch, row: dict) -> None:
        """Rebuild a Channeld's protocol state from a channels row (the
        inverse of save_channel; caller provides a fresh Channeld with
        hsm/client/peer wired)."""
        ch.wallet_id = row["id"]
        ch.channel_id = row["channel_id"]
        ch.funding_txid = row["funding_txid"]
        ch.funding_outidx = row["funding_outidx"]
        ch.funding_sat = row["funding_sat"]
        ch.funder = bool(row["funder"])
        ch.delay_on_local = row["delay_on_local"]
        ch.delay_on_remote = row["delay_on_remote"]
        ch.their_dust_limit = row["their_dust_limit"]
        ch.their_funding_pub = row["their_funding_pub"]
        ch.their_base = _unpack_basepoints(row["their_basepoints"])
        ch.their_points = {
            int(n): ref.pubkey_parse(bytes.fromhex(h))
            for n, h in json.loads(row["their_points"]).items()
        }
        ch.their_last_secret = row["their_last_secret"]
        ch.next_local_commit = row["next_local_commit"]
        ch.next_remote_commit = row["next_remote_commit"]
        ch.our_shutdown_script = row["our_shutdown_script"]
        ch.their_shutdown_script = row["their_shutdown_script"]
        ch.retransmit_sealed, ch.retransmit = _unpack_retransmit(
            row.get("retransmit") or b"")
        raw_inflight = row.get("inflight") or b""
        ch.inflight = json.loads(raw_inflight) if raw_inflight else None
        ch.announce = bool(row.get("announce", 0))
        ch.core = ChannelCore(
            funding_sat=row["funding_sat"],
            to_local_msat=row["to_local_msat"],
            to_remote_msat=row["to_remote_msat"],
            reserve_local_msat=row["reserve_local_msat"],
            reserve_remote_msat=row["reserve_remote_msat"],
            feerate_per_kw=row["feerate_per_kw"],
            opener_is_local=bool(row["opener_is_local"]),
            anchors=bool(row["anchors"]),
            state=ChannelState(row["state"]),
        )
        ch.core.next_htlc_id = {True: row["next_htlc_id_ours"],
                                False: row["next_htlc_id_theirs"]}
        ch.core.notify_tag = row["channel_id"].hex()
        for h in self.db.conn.execute(
            "SELECT offered_by_us, htlc_id, amount_msat, payment_hash, "
            "cltv_expiry, hstate, preimage, fail_reason, onion FROM htlcs "
            "WHERE channel_ref=?", (ch.wallet_id,)
        ):
            by_us = bool(h[0])
            ch.core.htlcs[(by_us, h[1])] = LiveHtlc(
                Htlc(by_us, h[2], h[3], h[4], id=h[1]),
                HtlcState[h[5]], preimage=h[6], fail_reason=h[7], onion=h[8],
            )
        ch.their_secrets = K.ShachainReceiver()
        for slot, idx, secret in self.db.conn.execute(
            "SELECT slot, idx, secret FROM shachain_slots WHERE channel_ref=?",
            (ch.wallet_id,)
        ):
            ch.their_secrets.known[slot] = (idx, secret)
            m = ch.their_secrets.max_index
            ch.their_secrets.max_index = idx if m is None else min(m, idx)
