"""PostgreSQL driver behind the Db API.

Parity target: /root/reference/db/db_postgres.c (1-331) plus the
build-time dialect rewriting of devtools/sql-rewrite.py.  All call
sites (wallet.py, channeld, invoices, ...) write statements ONCE in the
sqlite-ish dialect; this driver rewrites them per-backend at execute
time, exactly the reference's approach of maintaining one query table
with per-driver translations.

Rewrite rules (db_postgres.c / sql-rewrite.py):
  ?                     → $1..$N positional parameters
  BLOB                  → BYTEA
  INTEGER PRIMARY KEY   → BIGSERIAL PRIMARY KEY
  x'<hex>'              → decode('<hex>', 'hex')
  PRAGMA ...            → dropped (sqlite-only)

Backends:
  * psycopg2, when installed ($N → %s placeholder mapping);
  * EmulatedPostgres otherwise — an in-process backend that accepts
    ONLY the postgres dialect (it refuses `?`, BLOB, x'' literals) and
    executes via sqlite after reverse-mapping.  THE LIMITATION, stated
    plainly: this environment ships neither a postgres server nor
    psycopg2, so the driver is proven against the emulation — the
    rewriter and driver logic are fully exercised; live-server behavior
    (types, concurrency) is not.
"""
from __future__ import annotations

import re
import sqlite3
import threading
from contextlib import contextmanager

from .db import MIGRATIONS


class DbUnavailable(Exception):
    pass


# -- the dialect rewriter ----------------------------------------------------


def rewrite(sql: str) -> str:
    """sqlite-dialect statement → postgres dialect."""
    s = sql.strip()
    if s.upper().startswith("PRAGMA"):
        return ""
    out = []
    i = 0
    argn = 0
    while i < len(s):
        c = s[i]
        if c == "'":                      # string literal: copy verbatim
            j = i + 1
            while j < len(s):
                if s[j] == "'" and not (j + 1 < len(s) and s[j + 1] == "'"):
                    break
                j += 2 if s[j] == "'" else 1
            out.append(s[i:j + 1])
            i = j + 1
            continue
        if c == "?":
            argn += 1
            out.append(f"${argn}")
            i += 1
            continue
        if c in "xX" and i + 1 < len(s) and s[i + 1] == "'":
            j = s.index("'", i + 2)
            out.append(f"decode('{s[i + 2:j]}', 'hex')")
            i = j + 1
            continue
        out.append(c)
        i += 1
    s = "".join(out)
    s = re.sub(r"\bINTEGER PRIMARY KEY\b", "BIGSERIAL PRIMARY KEY", s,
               flags=re.IGNORECASE)
    s = re.sub(r"\bBLOB\b", "BYTEA", s, flags=re.IGNORECASE)
    return s


# -- backends ---------------------------------------------------------------


class EmulatedPostgres:
    """Accepts the POSTGRES dialect only; executes via sqlite.  The
    in-process stand-in that proves the rewriter + driver pipeline when
    no server exists (documented limitation above)."""

    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)

    def execute(self, sql: str, params=()):
        if "?" in re.sub(r"'[^']*'", "", sql):
            raise DbUnavailable(
                "postgres backend received a sqlite placeholder — the "
                "rewriter was bypassed")
        if re.search(r"\bBLOB\b", sql, flags=re.IGNORECASE):
            raise DbUnavailable("postgres backend received BLOB")
        back = re.sub(r"\$\d+", "?", sql)
        back = re.sub(r"\bBYTEA\b", "BLOB", back, flags=re.IGNORECASE)
        back = re.sub(r"\bBIGSERIAL PRIMARY KEY\b", "INTEGER PRIMARY KEY",
                      back, flags=re.IGNORECASE)
        back = re.sub(r"decode\('([0-9a-fA-F]*)', 'hex'\)", r"x'\1'", back)
        return self._conn.execute(back, params)

    def commit(self):
        self._conn.commit()

    def rollback(self):
        self._conn.rollback()

    def close(self):
        self._conn.close()


class _Psycopg2Backend:
    def __init__(self, dsn: str):
        try:
            import psycopg2
        except ImportError as e:     # pragma: no cover — env lacks it
            raise DbUnavailable(
                "psycopg2 not installed in this environment") from e
        self._conn = psycopg2.connect(dsn)
        self._conn.autocommit = False

    def execute(self, sql: str, params=()):    # pragma: no cover
        cur = self._conn.cursor()
        cur.execute(re.sub(r"\$\d+", "%s", sql), params)
        return cur

    def commit(self):                          # pragma: no cover
        self._conn.commit()

    def rollback(self):                        # pragma: no cover
        self._conn.rollback()

    def close(self):                           # pragma: no cover
        self._conn.close()


class _RewritingCursor:
    """The `.conn` facade: call sites keep their sqlite-dialect SQL."""

    def __init__(self, db: "PostgresDb"):
        self._db = db

    def execute(self, sql: str, params=()):
        pg = rewrite(sql)
        if not pg:
            return _EmptyCursor()
        self._db._trace(sql)
        return self._db.backend.execute(pg, params)

    def set_trace_callback(self, cb):
        pass                     # tracing handled in execute


class _EmptyCursor:
    def fetchone(self):
        return None

    def fetchall(self):
        return []

    description = []


class PostgresDb:
    """Drop-in for wallet.db.Db on a postgres backend: same migration
    table, same transaction()/get_var/set_var/db_write-hook surface."""

    def __init__(self, dsn: str = "", backend=None):
        self.backend = backend if backend is not None \
            else _Psycopg2Backend(dsn)
        self._local = threading.local()
        self.db_write_hook = None
        self._version_lock = threading.Lock()
        self._facade = _RewritingCursor(self)
        self._migrate()
        v = self.get_var("data_version")
        self._data_version = int(v) if v is not None else 0

    @property
    def conn(self):
        return self._facade

    def set_db_write_hook(self, hook) -> None:
        self.db_write_hook = hook

    _MUTATING = ("INSERT", "UPDATE", "DELETE", "REPLACE", "CREATE",
                 "ALTER", "DROP")

    def _trace(self, sql: str) -> None:
        if self.db_write_hook is None:
            return
        if sql.lstrip()[:7].upper().startswith(self._MUTATING):
            pend = getattr(self._local, "pending_writes", None)
            if pend is None:
                pend = self._local.pending_writes = []
            pend.append((sql, None))

    def _flush_writes(self) -> None:
        pend = getattr(self._local, "pending_writes", None)
        if not pend:
            return
        with self._version_lock:
            version = self._data_version + 1
            self._facade.execute(
                "INSERT INTO vars (name, val) VALUES ('data_version', ?) "
                "ON CONFLICT(name) DO UPDATE SET val=excluded.val",
                (str(version),))
            batch = list(self._local.pending_writes)
            self._local.pending_writes = []
            self._data_version = version
        try:
            self.db_write_hook(version, batch)
        except BaseException:
            with self._version_lock:
                if self._data_version == version:
                    self._data_version = version - 1
            raise

    def _migrate(self) -> None:
        self._facade.execute(
            "CREATE TABLE IF NOT EXISTS db_version"
            " (version INTEGER NOT NULL)")
        row = self._facade.execute(
            "SELECT version FROM db_version").fetchone()
        version = row[0] if row else 0
        for i in range(version, len(MIGRATIONS)):
            if MIGRATIONS[i]:
                self._facade.execute(MIGRATIONS[i])
        if row:
            self._facade.execute("UPDATE db_version SET version=?",
                                 (len(MIGRATIONS),))
        else:
            self._facade.execute("INSERT INTO db_version VALUES (?)",
                                 (len(MIGRATIONS),))
        self.backend.commit()

    @contextmanager
    def transaction(self):
        try:
            yield self._facade
            if self.db_write_hook is not None:
                self._flush_writes()
            self.backend.commit()
        except BaseException:
            self.backend.rollback()
            if getattr(self._local, "pending_writes", None):
                self._local.pending_writes = []
            raise

    def get_var(self, name: str, default=None):
        row = self._facade.execute(
            "SELECT val FROM vars WHERE name=?", (name,)).fetchone()
        return row[0] if row else default

    def set_var(self, name: str, val) -> None:
        with self.transaction() as c:
            c.execute(
                "INSERT INTO vars (name, val) VALUES (?, ?) "
                "ON CONFLICT(name) DO UPDATE SET val=excluded.val",
                (name, val))

    def close(self) -> None:
        self.backend.close()
