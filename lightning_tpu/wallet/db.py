"""SQLite persistence core: versioned migrations + transactions.

Parity targets: wallet/db.c + db/db_sqlite3.c and the migration-array
pattern of wallet/migrations.c (the reference carries 261 entries; ours
grows the same way — append-only, never edit an entry that shipped).

The durability invariant is the reference's checkpoint/resume design
(SURVEY §5): every protocol-visible state change is committed HERE
before the wire message that acknowledges it is sent.  The db IS the
checkpoint; there is no other state.
"""
from __future__ import annotations

import json
import logging
import os
import sqlite3
import threading
from contextlib import contextmanager

from ..resilience import faultinject as _fault

log = logging.getLogger("lightning_tpu.wallet.db")

# Append-only migration list (wallet/migrations.c pattern).
MIGRATIONS: list[str] = [
    # 1: schema bookkeeping
    "CREATE TABLE vars (name TEXT PRIMARY KEY, val BLOB)",
    # 2: channels — everything needed to reconstruct a Channeld
    """CREATE TABLE channels (
        id INTEGER PRIMARY KEY,
        peer_node_id BLOB NOT NULL,
        hsm_dbid INTEGER NOT NULL,
        funder INTEGER NOT NULL,
        channel_id BLOB NOT NULL,
        funding_txid BLOB NOT NULL,
        funding_outidx INTEGER NOT NULL,
        funding_sat INTEGER NOT NULL,
        state TEXT NOT NULL,
        to_local_msat INTEGER NOT NULL,
        to_remote_msat INTEGER NOT NULL,
        feerate_per_kw INTEGER NOT NULL,
        opener_is_local INTEGER NOT NULL,
        anchors INTEGER NOT NULL,
        reserve_local_msat INTEGER NOT NULL,
        reserve_remote_msat INTEGER NOT NULL,
        next_local_commit INTEGER NOT NULL,
        next_remote_commit INTEGER NOT NULL,
        next_htlc_id_ours INTEGER NOT NULL DEFAULT 0,
        next_htlc_id_theirs INTEGER NOT NULL DEFAULT 0,
        delay_on_local INTEGER NOT NULL,
        delay_on_remote INTEGER NOT NULL,
        their_dust_limit INTEGER NOT NULL,
        their_funding_pub BLOB NOT NULL,
        their_basepoints BLOB NOT NULL,
        their_points BLOB NOT NULL,
        their_last_secret BLOB NOT NULL,
        our_shutdown_script BLOB NOT NULL DEFAULT x'',
        their_shutdown_script BLOB NOT NULL DEFAULT x''
    )""",
    # 3: live HTLCs (channel_htlcs table equivalent)
    """CREATE TABLE htlcs (
        channel_ref INTEGER NOT NULL REFERENCES channels(id),
        offered_by_us INTEGER NOT NULL,
        htlc_id INTEGER NOT NULL,
        amount_msat INTEGER NOT NULL,
        payment_hash BLOB NOT NULL,
        cltv_expiry INTEGER NOT NULL,
        hstate TEXT NOT NULL,
        preimage BLOB,
        fail_reason BLOB,
        onion BLOB,
        PRIMARY KEY (channel_ref, offered_by_us, htlc_id)
    )""",
    # 4: peer's revealed per-commitment secrets (shachains table)
    """CREATE TABLE shachain_slots (
        channel_ref INTEGER NOT NULL REFERENCES channels(id),
        slot INTEGER NOT NULL,
        idx INTEGER NOT NULL,
        secret BLOB NOT NULL,
        PRIMARY KEY (channel_ref, slot)
    )""",
    # 5: gossip store high-water mark + misc node state live in vars
    # (placeholder entry: the migration loop skips falsy entries, keeping
    # comment numbers == db_version values)
    "",
    # 6: invoices (wallet/invoices.c table equivalent)
    """CREATE TABLE invoices (
        id INTEGER PRIMARY KEY,
        label TEXT NOT NULL UNIQUE,
        payment_hash BLOB NOT NULL UNIQUE,
        preimage BLOB NOT NULL,
        amount_msat INTEGER,
        bolt11 TEXT NOT NULL,
        description TEXT,
        status TEXT NOT NULL DEFAULT 'unpaid',
        expires_at INTEGER NOT NULL,
        pay_index INTEGER,
        paid_at INTEGER,
        received_msat INTEGER
    )""",
    # 7: outgoing payments (wallet_payment / listpays store)
    """CREATE TABLE payments (
        id INTEGER PRIMARY KEY,
        payment_hash BLOB NOT NULL,
        destination BLOB,
        amount_msat INTEGER NOT NULL,
        amount_sent_msat INTEGER NOT NULL,
        bolt11 TEXT,
        status TEXT NOT NULL DEFAULT 'pending',
        preimage BLOB,
        created_at INTEGER NOT NULL,
        completed_at INTEGER,
        failure TEXT
    )""",
    # 8: store the payment_secret directly (re-deriving it by decoding
    # the bolt11 string on load was costly and fragile)
    "ALTER TABLE invoices ADD COLUMN payment_secret BLOB",
    # 9: BOLT#12 offers we publish (wallet/wallet.c offers table role)
    """CREATE TABLE offers (
        offer_id BLOB PRIMARY KEY,
        label TEXT,
        bolt12 TEXT NOT NULL,
        status TEXT NOT NULL DEFAULT 'active',
        single_use INTEGER NOT NULL DEFAULT 0
    )""",
    # 10: bolt12 invoices reference the offer they answered
    "ALTER TABLE invoices ADD COLUMN local_offer_id BLOB",
    # 11: on-chain UTXOs (wallet/migrations.c:59 outputs table role)
    """CREATE TABLE outputs (
        txid BLOB NOT NULL,
        vout INTEGER NOT NULL,
        amount_sat INTEGER NOT NULL,
        scriptpubkey BLOB NOT NULL,
        keyindex INTEGER NOT NULL,
        status TEXT NOT NULL DEFAULT 'available',
        reserved_til INTEGER,
        confirmation_height INTEGER,
        spent_height INTEGER,
        spending_txid BLOB,
        PRIMARY KEY (txid, vout)
    )""",
    # 12: retransmission journal — the exact update_*/commitment_signed
    # bytes in flight, replayed at channel_reestablish (BOLT#2
    # retransmission; channeld.c peer_reconnect).  Format: 1 sealed
    # byte + repeated [u32-be length][raw wire msg].
    "ALTER TABLE channels ADD COLUMN retransmit BLOB NOT NULL DEFAULT x''",
    # 13: splice inflight — persisted BEFORE our tx_signatures leave, so
    # a crash between signature exchange and splice_locked can never
    # lose the new funding outpoint or the peer's inflight commitment
    # signature (the reference's channel_funding_inflights table,
    # wallet/wallet.c wallet_channel_insert_inflight).  JSON blob; empty
    # = no inflight.
    "ALTER TABLE channels ADD COLUMN inflight BLOB NOT NULL DEFAULT x''",
    # 14: BOLT#2 announce_channel bit — a restored channel must keep its
    # public/private nature (re-announcing a private channel on restart
    # would leak it; forgetting a public one breaks re-announcement)
    "ALTER TABLE channels ADD COLUMN announce INTEGER NOT NULL DEFAULT 0",
]


class Db:
    """One node's database.  sqlite3 in WAL mode; every mutation goes
    through transaction() so a crash can never observe a torn write.

    db_write hook (the reference's special-cased synchronous plugin
    hook, lightningd/plugin_hook.c): when set, EVERY data-modifying
    statement is streamed to the hook BEFORE the transaction commits —
    a raising hook vetoes the commit (rollback), so the replica can
    never be missing a transaction the primary has durably applied; it
    may only be AHEAD by one (crash between hook and commit), which a
    replayer resolves via the monotone data_version.  data_version
    itself is persisted in vars (the reference does the same) so it
    survives restart, and the statement updating it rides the streamed
    batch, keeping the replica's counter in lock-step."""

    def __init__(self, path: str):
        self.path = path
        self._local = threading.local()
        self.db_write_hook = None    # fn(data_version, [(sql, None)])
        self._batching = False       # `batching` RPC: defer commits
        self._version_lock = threading.Lock()
        self._data_version = 0   # provisional: transaction() reads it
        self._migrate()
        v = self.get_var("data_version")
        self._data_version = int(v) if v is not None else 0

    @property
    def conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.path)
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA synchronous=FULL")
            conn.execute("PRAGMA foreign_keys=ON")
            # trace is ALWAYS installed (cheap no-op while no hook is
            # set) so a hook installed later covers every thread's
            # already-open connection
            conn.set_trace_callback(self._trace)
            self._local.conn = conn
        return conn

    def set_db_write_hook(self, hook) -> None:
        """hook(data_version, [(sql, None)]): called with the statement
        batch of each transaction before it commits.  (sqlite's trace
        callback delivers the EXPANDED sql — params already substituted
        — which is exactly what a replica needs to re-execute.)"""
        self.db_write_hook = hook

    _MUTATING = ("INSERT", "UPDATE", "DELETE", "REPLACE", "CREATE",
                 "ALTER", "DROP")

    def _trace(self, sql: str) -> None:
        if self.db_write_hook is None:
            return
        s = sql.lstrip()
        if s[:7].upper().startswith(self._MUTATING):
            pend = getattr(self._local, "pending_writes", None)
            if pend is None:
                pend = self._local.pending_writes = []
            pend.append((sql, None))

    def _flush_writes(self, conn) -> None:
        """Stream this transaction's batch (pre-commit).  The version
        bump is written INSIDE the transaction so the stream carries it
        and the replica's counter stays in lock-step."""
        pend = getattr(self._local, "pending_writes", None)
        if not pend:
            return
        # Version accounting happens under the lock; hook DELIVERY does
        # not — a bridged hook may need the event loop, and the loop
        # thread takes this lock for its own commits (holding it here
        # was a 30s deadlock).  Concurrent write transactions are
        # already serialized by sqlite's single-writer locking, so
        # delivery order still follows version order in practice.
        with self._version_lock:
            version = self._data_version + 1
            conn.execute(
                "INSERT INTO vars (name, val) VALUES ('data_version', ?) "
                "ON CONFLICT(name) DO UPDATE SET val=excluded.val",
                (str(version),))
            batch = list(self._local.pending_writes)
            self._local.pending_writes = []
            self._data_version = version
        try:
            self.db_write_hook(version, batch)
        except BaseException:
            # veto: the transaction (incl. the vars row) rolls back, so
            # the counter must give this number back — the next commit
            # reuses it, keeping the replica's lock-step monotone.
            raced = False
            with self._version_lock:
                if self._data_version == version:
                    self._data_version = version - 1
                else:   # pragma: no cover — needs interleaved writers
                    raced = True
            if raced:   # pragma: no cover — log OUTSIDE the version
                # lock: handlers are pluggable (graftlint lock-order)
                import logging

                logging.getLogger("lightning_tpu.db").warning(
                    "db_write veto raced a concurrent commit; "
                    "replication stream may skip version %d", version)
            raise

    def _migrate(self) -> None:
        c = self.conn
        with self.transaction():
            c.execute("""CREATE TABLE IF NOT EXISTS db_version
                         (version INTEGER NOT NULL)""")
            row = c.execute("SELECT version FROM db_version").fetchone()
            version = row[0] if row else 0
            for i in range(version, len(MIGRATIONS)):
                if MIGRATIONS[i]:
                    c.execute(MIGRATIONS[i])
            if row:
                c.execute("UPDATE db_version SET version=?", (len(MIGRATIONS),))
            else:
                c.execute("INSERT INTO db_version VALUES (?)",
                          (len(MIGRATIONS),))

    @contextmanager
    def transaction(self):
        c = self.conn
        if self._batching:
            # batched mode: each transaction is a SAVEPOINT so a later
            # failure rolls back ONLY itself, never the acknowledged
            # writes accumulated before it
            c.execute("SAVEPOINT batched_txn")
            try:
                yield c
                if self.db_write_hook is not None:
                    self._flush_writes(c)
                c.execute("RELEASE batched_txn")
            except BaseException:
                c.execute("ROLLBACK TO batched_txn")
                c.execute("RELEASE batched_txn")
                if getattr(self._local, "pending_writes", None):
                    self._local.pending_writes = []
                raise
            return
        try:
            yield c
            v_before = self._data_version
            if self.db_write_hook is not None:
                self._flush_writes(c)   # pre-commit: hook can veto
            # the commit fault seam sits in the hook-replica
            # "ahead by one" window (hook delivered, COMMIT not yet
            # durable) — a crash armed here is exactly the case the
            # boot reconciliation resolves (doc/recovery.md)
            try:
                _fault.fire("commit", "db")
            except BaseException:
                # an injected pre-commit failure must give the version
                # number back, same as a hook veto, or the replica
                # stream would skip a version
                with self._version_lock:
                    if self._data_version == v_before + 1:
                        self._data_version = v_before
                raise
            c.commit()
        except BaseException:
            c.rollback()
            if getattr(self._local, "pending_writes", None):
                self._local.pending_writes = []
            raise

    def set_batching(self, enable: bool) -> None:
        """Defer COMMITs while enabled (jsonrpc.c `batching`): many
        writes ride one fsync.  Disabling (or rpc connection close)
        commits whatever accumulated — the documented crash-window
        tradeoff."""
        enable = bool(enable)
        if enable and not self._batching:
            # hold an explicit enclosing transaction: a SAVEPOINT
            # released OUTSIDE a transaction would commit on its own
            # (sqlite outermost-savepoint rule), defeating the batch
            self.conn.commit()
            self.conn.execute("BEGIN")
        elif not enable and self._batching:
            self.conn.commit()
        self._batching = enable

    def get_var(self, name: str, default=None):
        row = self.conn.execute(
            "SELECT val FROM vars WHERE name=?", (name,)
        ).fetchone()
        return row[0] if row else default

    def set_var(self, name: str, val) -> None:
        with self.transaction() as c:
            c.execute(
                "INSERT INTO vars (name, val) VALUES (?, ?) "
                "ON CONFLICT(name) DO UPDATE SET val=excluded.val",
                (name, val),
            )

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None

    def reconcile_replica(self, replica_version: int | None) -> str:
        """Classify a db_write-hook replica's last-seen data_version
        against the primary's durable one (the docstring's monotone
        lock-step contract).  Pure classification — the caller applies
        the fix; reconcile_file_replica() is the boot-time driver.

        * ``empty``        — replica has seen nothing yet (fresh);
        * ``in_sync``      — versions match;
        * ``ahead_by_one`` — the documented crash window: the hook
          streamed a transaction whose COMMIT never became durable.
          The replica must DROP its tail record;
        * ``behind``       — the replica missed transactions (only
          possible if it was attached late or lost data; needs a
          full resync, not a tail fix);
        * ``diverged``     — ahead by more than one: impossible under
          the hook contract, so something rewrote history."""
        if replica_version is None:
            return "empty"
        rv, dv = int(replica_version), self._data_version
        if rv == dv:
            return "in_sync"
        if rv == dv + 1:
            return "ahead_by_one"
        if rv < dv:
            return "behind"
        return "diverged"


class FileReplica:
    """Durable db_write-hook consumer: a line-JSON journal of every
    streamed transaction batch (``{"v": data_version, "writes":
    [sql...]}``), fsynced BEFORE the primary's COMMIT returns — the
    tested stand-in for the reference's backup plugin.

    Because the hook streams pre-commit, a crash inside the commit
    window leaves this journal AHEAD of the primary by exactly one
    record (Db docstring); a crash mid-journal-append leaves a torn
    last LINE instead, which the reader ignores.  Both cases resolve on
    boot via reconcile_file_replica(): the unacknowledged tail record
    is dropped write-then-rename, never truncated in place."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f = open(path, "ab")

    def __call__(self, version: int, batch) -> None:
        line = json.dumps(
            {"v": int(version), "writes": [sql for sql, _ in batch]},
            separators=(",", ":")) + "\n"
        with self._lock:
            self._f.write(line.encode())
            self._f.flush()
            os.fsync(self._f.fileno())

    def records(self) -> list[dict]:
        """Parsed journal records; a torn/partial last line (crash
        mid-append) is dropped silently — it was never acknowledged."""
        try:
            with open(self.path, "rb") as f:
                data = f.read()
        except OSError:
            return []
        out = []
        for ln in data.split(b"\n"):
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except ValueError:
                break   # torn tail: everything after it is garbage
            if not isinstance(rec, dict) or "v" not in rec:
                break
            out.append(rec)
        return out

    def last_version(self) -> int | None:
        recs = self.records()
        return int(recs[-1]["v"]) if recs else None

    def drop_last(self) -> None:
        """Drop the newest complete record (write-then-rename)."""
        recs = self.records()
        if not recs:
            return
        blob = b"".join(
            json.dumps(r, separators=(",", ":")).encode() + b"\n"
            for r in recs[:-1])
        tmp = self.path + f".reconcile.{os.getpid()}"
        with self._lock:
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self._f.close()
            self._f = open(self.path, "ab")

    def close(self) -> None:
        with self._lock:
            self._f.close()


def reconcile_file_replica(db: Db, replica: FileReplica) -> str:
    """Boot-time replica reconciliation (doc/recovery.md): classify via
    Db.reconcile_replica and resolve the one self-healable verdict —
    ahead-by-one drops the replica's unacknowledged tail record.
    Returns the verdict ("dropped_ahead" when a tail was dropped)."""
    verdict = db.reconcile_replica(replica.last_version())
    if verdict == "ahead_by_one":
        replica.drop_last()
        log.warning("db replica %s was ahead by one (crash between "
                    "db_write hook and commit); dropped its tail record",
                    replica.path)
        return "dropped_ahead"
    if verdict in ("behind", "diverged"):
        log.error("db replica %s is %s the primary (replica v%s, "
                  "primary v%d): needs a full resync",
                  replica.path, verdict, replica.last_version(),
                  db._data_version)
    return verdict
