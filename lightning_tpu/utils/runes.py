"""Runes: add-only bearer tokens authorizing (restricted) RPC access.

Functional parity target: the reference's ccan/ccan/rune +
lightningd/runes.c (createrune/checkrune/showrunes; used by commando and
clnrest) — re-implemented from the public rune scheme.

A rune is base64url(authcode32 || restriction-string).  The authcode is
a SHA-256 *midstate*: the issuer hashes its secret (padded to a block),
then each restriction (padded to a block) in turn.  Anyone holding a
rune can add further restrictions by continuing the hash — but nobody
can remove one without the secret, because SHA-256 midstates can't be
rewound.  Verification recomputes the chain from the secret.

Restrictions: '&'-joined; each is '|'-joined alternatives; an
alternative is field + operator + value with '\\' escaping for
[\\|&].  Operators: = (equal), / (not equal), ^ (starts with),
$ (ends with), ~ (contains), < (int less), > (int greater),
{ (lexicographic before), } (after), # (comment, always passes),
! (field must be absent).
"""
from __future__ import annotations

import base64
import hmac
import struct
import time


class RuneError(Exception):
    pass


# ---------------------------------------------------------------------------
# SHA-256 with an exposed midstate (needed for the add-only property)

_K = [
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
    0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
    0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
    0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
    0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
    0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
]
_IV = (0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
       0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19)
_M32 = 0xFFFFFFFF


def _rotr(x: int, n: int) -> int:
    return ((x >> n) | (x << (32 - n))) & _M32


def _compress(state: tuple, block: bytes) -> tuple:
    w = list(struct.unpack(">16I", block))
    for i in range(16, 64):
        s0 = _rotr(w[i - 15], 7) ^ _rotr(w[i - 15], 18) ^ (w[i - 15] >> 3)
        s1 = _rotr(w[i - 2], 17) ^ _rotr(w[i - 2], 19) ^ (w[i - 2] >> 10)
        w.append((w[i - 16] + s0 + w[i - 7] + s1) & _M32)
    a, b, c, d, e, f, g, h = state
    for i in range(64):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = (h + s1 + ch + _K[i] + w[i]) & _M32
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = (s0 + maj) & _M32
        h, g, f, e, d, c, b, a = (g, f, e, (d + t1) & _M32,
                                  c, b, a, (t1 + t2) & _M32)
    return tuple((x + y) & _M32 for x, y in zip(state, (a, b, c, d, e, f, g, h)))


def _pad_to_block(data: bytes, total_len: int) -> bytes:
    """SHA-2 end-padding as if the whole message so far were total_len
    bytes, rounded out to a 64-byte boundary.  (total_len ≡ len(data)
    mod 64 because every earlier absorption ended on a block boundary.)"""
    padlen = (55 - total_len) % 64
    return data + b"\x80" + b"\x00" * padlen + struct.pack(
        ">Q", total_len * 8)


def _absorb(state: tuple, data: bytes, total_len: int) -> tuple:
    buf = _pad_to_block(data, total_len)
    assert len(buf) % 64 == 0
    for i in range(0, len(buf), 64):
        state = _compress(state, buf[i:i + 64])
    return state


def _state_bytes(state: tuple) -> bytes:
    return struct.pack(">8I", *state)


def _state_from(b: bytes) -> tuple:
    return struct.unpack(">8I", b)


# ---------------------------------------------------------------------------
# restriction model

OPS = "=/^$~<>{}#!"


def _escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace("|", "\\|").replace("&", "\\&")


def _split_unescaped(s: str, sep: str) -> list[str]:
    """Split at unescaped separators, PRESERVING escapes (they are only
    consumed at the innermost parse so '&' then '|' splits compose)."""
    out, cur, i = [], [], 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            cur.append(c)
            cur.append(s[i + 1])
            i += 2
            continue
        if c == sep:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def _unescape(s: str) -> str:
    out, i = [], 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            out.append(s[i + 1])
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


class Alternative:
    def __init__(self, field: str, op: str, value: str):
        if op not in OPS:
            raise RuneError(f"unknown operator {op!r}")
        self.field, self.op, self.value = field, op, value

    def encode(self) -> str:
        return _escape(self.field) + self.op + _escape(self.value)

    @classmethod
    def parse(cls, s: str) -> "Alternative":
        # find the first unescaped operator character
        i, esc = 0, False
        while i < len(s):
            if esc:
                esc = False
            elif s[i] == "\\":
                esc = True
            elif s[i] in OPS:
                break
            i += 1
        else:
            raise RuneError(f"no operator in alternative {s!r}")
        return cls(_unescape(s[:i]), s[i], _unescape(s[i + 1:]))

    def test(self, values: dict) -> str | None:
        """None if satisfied, else a reason string."""
        if self.op == "#":
            return None
        present = self.field in values
        if self.op == "!":
            return None if not present else f"{self.field} is present"
        if not present:
            return f"{self.field} not present"
        v = values[self.field]
        if callable(v):
            return v(self)
        sval = str(v)
        if self.op == "=":
            return None if sval == self.value else \
                f"{self.field} != {self.value}"
        if self.op == "/":
            return None if sval != self.value else \
                f"{self.field} = {self.value}"
        if self.op == "^":
            return None if sval.startswith(self.value) else "no prefix match"
        if self.op == "$":
            return None if sval.endswith(self.value) else "no suffix match"
        if self.op == "~":
            return None if self.value in sval else "no substring match"
        if self.op in "<>":
            try:
                a, b = int(sval), int(self.value)
            except ValueError:
                return "not an integer"
            ok = a < b if self.op == "<" else a > b
            return None if ok else f"{a} not {self.op} {b}"
        if self.op == "{":
            return None if sval < self.value else "not lexicographically before"
        if self.op == "}":
            return None if sval > self.value else "not lexicographically after"
        raise RuneError(f"unhandled op {self.op}")


class Restriction:
    def __init__(self, alternatives: list[Alternative]):
        if not alternatives:
            raise RuneError("empty restriction")
        self.alternatives = alternatives

    def encode(self) -> str:
        return "|".join(a.encode() for a in self.alternatives)

    @classmethod
    def parse(cls, s: str) -> "Restriction":
        return cls([Alternative.parse(a) for a in _split_unescaped(s, "|")])

    @classmethod
    def from_str(cls, s: str) -> "Restriction":
        return cls.parse(s)

    def test(self, values: dict) -> str | None:
        reasons = []
        for alt in self.alternatives:
            r = alt.test(values)
            if r is None:
                return None
            reasons.append(r)
        return " AND ".join(reasons)


class Rune:
    def __init__(self, authcode: bytes, restrictions: list[Restriction],
                 total_len: int):
        self.authcode = authcode          # 32-byte midstate
        self.restrictions = restrictions
        self._total_len = total_len       # bytes absorbed so far

    # -- construction -----------------------------------------------------

    @classmethod
    def from_secret(cls, secret: bytes,
                    restrictions: list[Restriction] = ()) -> "Rune":
        if len(secret) + 1 + 8 > 64:
            raise RuneError("secret too long for one block")
        state = _absorb(_IV, secret, len(secret))
        rune = cls(_state_bytes(state), [], 64)
        for r in restrictions:
            rune.add_restriction(r)
        return rune

    def add_restriction(self, r: Restriction) -> None:
        data = r.encode().encode()
        state = _state_from(self.authcode)
        # continue the hash: absorb the restriction padded to a block
        buf = _pad_to_block(data, self._total_len + len(data))
        for i in range(0, len(buf), 64):
            state = _compress(state, buf[i:i + 64])
        self.authcode = _state_bytes(state)
        self._total_len += len(buf)
        self.restrictions.append(r)

    # -- wire form --------------------------------------------------------

    def encode(self) -> str:
        body = "&".join(r.encode() for r in self.restrictions)
        return base64.urlsafe_b64encode(
            self.authcode + body.encode()).decode().rstrip("=")

    @classmethod
    def decode(cls, s: str) -> "Rune":
        pad = "=" * (-len(s) % 4)
        try:
            raw = base64.urlsafe_b64decode(s + pad)
        except Exception as e:
            raise RuneError(f"bad base64: {e}")
        if len(raw) < 32:
            raise RuneError("rune too short")
        try:
            body = raw[32:].decode()
        except UnicodeDecodeError:
            raise RuneError("restrictions not utf8") from None
        restrictions = []
        if body:
            restrictions = [Restriction.parse(p)
                            for p in _split_unescaped(body, "&")]
        total = 64
        for r in restrictions:
            enc = r.encode().encode()
            total += len(_pad_to_block(enc, total + len(enc)))
        return cls(raw[:32], restrictions, total)

    # -- verification -----------------------------------------------------

    def is_authorized(self, secret: bytes) -> bool:
        expect = Rune.from_secret(secret, self.restrictions)
        # constant-time: runes gate network-reachable surfaces (commando,
        # REST), so the compare must not leak a byte-position oracle
        return hmac.compare_digest(expect.authcode, self.authcode)

    def check(self, secret: bytes, values: dict) -> str | None:
        """None if the rune is valid AND every restriction passes."""
        if not self.is_authorized(secret):
            return "invalid rune authcode"
        for r in self.restrictions:
            reason = r.test(values)
            if reason is not None:
                return reason
        return None


def standard_values(method: str | None = None, rune_id: str | None = None,
                    now: float | None = None, **extra) -> dict:
    """The field set lightningd/runes.c exposes to checkrune: method,
    time, id/unique_id plus caller params as pname<param>/parr<idx>."""
    values = {"time": int(now if now is not None else time.time())}
    if method is not None:
        values["method"] = method
    if rune_id is not None:
        values["id"] = rune_id
    for k, v in extra.items():
        values[k] = v
    return values
