"""In-memory ring-buffer log with per-subsystem level filters + getlog.

Parity target: lightningd/log.c — a bounded ring of structured entries
(the reference prunes at 10M bytes), per-subsystem level overrides
(`--log-level=debug:gossipd`), and the `getlog` RPC that replays the
ring.  Implemented as a logging.Handler so every module's stdlib logger
feeds the same ring the RPC reads.
"""
from __future__ import annotations

import collections
import logging
import time
from dataclasses import dataclass

LEVELS = {"io": 5, "debug": logging.DEBUG, "info": logging.INFO,
          "unusual": logging.WARNING, "broken": logging.ERROR}
_LEVEL_NAMES = {5: "IO", logging.DEBUG: "DEBUG", logging.INFO: "INFO",
                logging.WARNING: "UNUSUAL", logging.ERROR: "BROKEN",
                logging.CRITICAL: "BROKEN"}

logging.addLevelName(5, "IO")


def level_name(levelno: int) -> str:
    for threshold in (logging.CRITICAL, logging.ERROR, logging.WARNING,
                      logging.INFO, logging.DEBUG, 5):
        if levelno >= threshold:
            return _LEVEL_NAMES[threshold]
    return "IO"


@dataclass
class LogEntry:
    ts: float
    levelno: int
    subsystem: str
    message: str
    node_id: str | None = None


class LogRing(logging.Handler):
    """Bounded structured log sink with per-subsystem filtering."""

    def __init__(self, max_entries: int = 100_000,
                 default_level: str = "info"):
        super().__init__(level=1)
        self.entries: collections.deque[LogEntry] = collections.deque(
            maxlen=max_entries)
        self.default_level = LEVELS[default_level]
        self.overrides: dict[str, int] = {}   # subsystem prefix -> levelno
        self.n_skipped = 0
        # total records accepted per level name, monotone — the ring
        # itself is bounded, so the obs collector reads emit rates here
        self.n_emitted: dict[str, int] = {}

    # -- configuration ----------------------------------------------------

    def set_level(self, spec: str) -> None:
        """'debug' or 'debug:gossipd' (reference --log-level syntax)."""
        level, _, subsys = spec.partition(":")
        if level not in LEVELS:
            raise ValueError(f"unknown log level {level!r}")
        if subsys:
            self.overrides[subsys] = LEVELS[level]
        else:
            self.default_level = LEVELS[level]

    def threshold_for(self, subsystem: str) -> int:
        for prefix, lv in self.overrides.items():
            if prefix in subsystem:
                return lv
        return self.default_level

    # -- logging.Handler --------------------------------------------------

    def emit(self, record: logging.LogRecord) -> None:
        sub = record.name.removeprefix("lightning_tpu.")
        if record.levelno < self.threshold_for(sub):
            self.n_skipped += 1
            return
        try:
            msg = record.getMessage()
        except Exception:
            msg = str(record.msg)
        self._count(record.levelno)
        self.entries.append(LogEntry(record.created, record.levelno,
                                     sub, msg))

    def add(self, subsystem: str, message: str,
            level: str = "info") -> None:
        """Direct structured append (non-stdlib paths)."""
        if LEVELS[level] >= self.threshold_for(subsystem):
            self._count(LEVELS[level])
            self.entries.append(LogEntry(time.time(), LEVELS[level],
                                         subsystem, message))

    def _count(self, levelno: int) -> None:
        name = level_name(levelno)
        self.n_emitted[name] = self.n_emitted.get(name, 0) + 1

    # -- RPC surface ------------------------------------------------------

    def getlog(self, level: str = "info") -> dict:
        """doc/schemas/lightning-getlog.json shape."""
        threshold = LEVELS.get(level)
        if threshold is None:
            raise ValueError(f"unknown log level {level!r}")
        first = self.entries[0].ts if self.entries else time.time()
        out = [
            {"type": level_name(e.levelno),
             "time": f"{e.ts - first:.9f}",
             "source": e.subsystem,
             "log": e.message}
            for e in self.entries if e.levelno >= threshold
        ]
        return {"created_at": f"{first:.9f}",
                "bytes_used": sum(len(e.message) for e in self.entries),
                "bytes_max": self.entries.maxlen or 0,
                "log": out}


def install(ring: LogRing, root: str = "lightning_tpu") -> None:
    """Attach the ring to the package's root logger."""
    lg = logging.getLogger(root)
    lg.addHandler(ring)
    lg.setLevel(1)
