"""zbase32 codec + recoverable message signatures.

Parity targets: common/bech32_util? no — the reference's signmessage
plugin uses zbase32 (plugins/... via common/utils; see
doc/schemas/lightning-signmessage.json): sign
sha256d("Lightning Signed Message:" || msg) with a RECOVERABLE compact
signature (65 bytes: recid+31 || r || s) and emit it zbase32-encoded.
checkmessage recovers the public key and compares.
"""
from __future__ import annotations

import hashlib

from ..crypto import ref_python as ref

_ALPHA = "ybndrfg8ejkmcpqxot1uwisza345h769"
_REV = {c: i for i, c in enumerate(_ALPHA)}

MSG_PREFIX = b"Lightning Signed Message:"


def encode(data: bytes) -> str:
    out = []
    bits = 0
    acc = 0
    for b in data:
        acc = (acc << 8) | b
        bits += 8
        while bits >= 5:
            bits -= 5
            out.append(_ALPHA[(acc >> bits) & 31])
    if bits:
        out.append(_ALPHA[(acc << (5 - bits)) & 31])
    return "".join(out)


def decode(s: str) -> bytes:
    acc = 0
    bits = 0
    out = bytearray()
    for c in s:
        if c not in _REV:
            raise ValueError(f"invalid zbase32 char {c!r}")
        acc = (acc << 5) | _REV[c]
        bits += 5
        if bits >= 8:
            bits -= 8
            out.append((acc >> bits) & 0xFF)
    return bytes(out)


def _msg_hash(message: str) -> bytes:
    h = hashlib.sha256(MSG_PREFIX + message.encode()).digest()
    return hashlib.sha256(h).digest()


def _recover(z: int, r: int, s: int, recid: int) -> ref.Point | None:
    """Standard ECDSA public-key recovery (SEC1 4.1.6)."""
    if not (1 <= r < ref.N and 1 <= s < ref.N):
        return None
    x = r + (recid >> 1) * ref.N
    if x >= ref.P:
        return None
    # lift x to a curve point with y parity = recid & 1
    y2 = (pow(x, 3, ref.P) + 7) % ref.P
    y = pow(y2, (ref.P + 1) // 4, ref.P)
    if y * y % ref.P != y2:
        return None
    if (y & 1) != (recid & 1):
        y = ref.P - y
    R = ref.Point(x, y)
    rinv = ref.fe_inv(r, ref.N)
    # Q = r^-1 (sR - zG)
    sR = ref.point_mul(s, R)
    zG = ref.point_mul(z % ref.N, ref.G)
    neg_zG = ref.Point(zG.x, (ref.P - zG.y) % ref.P) \
        if not zG.inf else zG
    Q = ref.point_mul(rinv, ref.point_add(sR, neg_zG))
    if Q.inf:
        return None
    return Q


def sign_message(message: str, seckey: int) -> tuple[str, bytes, bytes]:
    """Returns (zbase, signature65, recid_byte) for the given node key."""
    h = _msg_hash(message)
    r, s = ref.ecdsa_sign(h, seckey)
    z = int.from_bytes(h, "big")
    pub = ref.pubkey_create(seckey)
    recid = None
    for cand in range(4):
        q = _recover(z, r, s, cand)
        if q is not None and q.x == pub.x and q.y == pub.y:
            recid = cand
            break
    assert recid is not None, "unrecoverable signature"
    sig65 = bytes([recid + 31]) + r.to_bytes(32, "big") + s.to_bytes(32, "big")
    return encode(sig65), sig65, bytes([recid + 31])


def check_message(message: str, zbase: str) -> bytes | None:
    """Recover the signer's compressed pubkey, or None if invalid."""
    try:
        sig = decode(zbase)
    except ValueError:
        return None
    if len(sig) != 65 or not 31 <= sig[0] <= 34:
        return None
    recid = sig[0] - 31
    r = int.from_bytes(sig[1:33], "big")
    s = int.from_bytes(sig[33:], "big")
    h = _msg_hash(message)
    q = _recover(int.from_bytes(h, "big"), r, s, recid)
    if q is None or not ref.ecdsa_verify(h, r, s, q):
        return None
    return ref.pubkey_serialize(q)
