"""Build-on-demand loader for the native (C) helpers.

The runtime around the TPU compute path is native where it matters
(checksums, codecs, IO) — mirroring the reference's C runtime — but built
lazily with the system toolchain so the package stays pip-less.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC_DIR = os.path.join(_ROOT, "native")
_LIB_PATH = os.path.join(_SRC_DIR, "_lightning_native.so")
_SOURCES = ["crc32c.c", "gossip_native.c"]
_lock = threading.Lock()
_lib = None


def _build() -> str:
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if not os.path.exists(_LIB_PATH) or os.path.getmtime(_LIB_PATH) < newest_src:
        cmd = ["cc", "-O3", "-shared", "-fPIC", "-o", _LIB_PATH, *srcs]
        subprocess.run(cmd, check=True, capture_output=True)
    return _LIB_PATH


def get_lib() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is None:
            lib = ctypes.CDLL(_build())
            lib.crc32c.restype = ctypes.c_uint32
            lib.crc32c.argtypes = [ctypes.c_uint32, ctypes.c_char_p, ctypes.c_size_t]
            lib.crc32c_batch.restype = None
            lib.crc32c_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
            ]
            lib.gossip_store_scan.restype = ctypes.c_int64
            lib.gossip_store_scan.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ]
            lib.sha256_pack.restype = ctypes.c_int64
            lib.sha256_pack.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_size_t, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_void_p,
            ]
            lib.gather_fields.restype = None
            lib.gather_fields.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_uint64, ctypes.c_uint32, ctypes.c_void_p,
            ]
            _lib = lib
    return _lib


def crc32c(seed: int, data: bytes) -> int:
    return get_lib().crc32c(seed & 0xFFFFFFFF, data, len(data))


def crc32c_batch(buf: np.ndarray, offsets: np.ndarray, lengths: np.ndarray,
                 seeds: np.ndarray) -> np.ndarray:
    """Vectorized crc32c over records inside one contiguous uint8 buffer."""
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.uint64)
    lengths = np.ascontiguousarray(lengths, dtype=np.uint32)
    seeds = np.ascontiguousarray(seeds, dtype=np.uint32)
    out = np.empty(len(offsets), dtype=np.uint32)
    get_lib().crc32c_batch(
        buf.ctypes.data, offsets.ctypes.data, lengths.ctypes.data,
        seeds.ctypes.data, out.ctypes.data, len(offsets),
    )
    return out


def gossip_store_scan(buf: np.ndarray, start_off: int = 1):
    """Scan store records. Returns dict of numpy arrays (offsets point at
    each record's message body; lengths exclude the 12-byte header)."""
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    cap = max(1, (len(buf) - start_off) // 12 + 1)
    offsets = np.empty(cap, np.uint64)
    lengths = np.empty(cap, np.uint32)
    flags = np.empty(cap, np.uint16)
    timestamps = np.empty(cap, np.uint32)
    crcs = np.empty(cap, np.uint32)
    types = np.empty(cap, np.uint16)
    n = get_lib().gossip_store_scan(
        buf.ctypes.data, len(buf), start_off,
        offsets.ctypes.data, lengths.ctypes.data, flags.ctypes.data,
        timestamps.ctypes.data, crcs.ctypes.data, types.ctypes.data,
    )
    if n < 0:
        raise ValueError("truncated gossip store")
    sl = slice(0, n)
    return {
        "offsets": offsets[sl], "lengths": lengths[sl], "flags": flags[sl],
        "timestamps": timestamps[sl], "crcs": crcs[sl], "types": types[sl],
    }


def sha256_pack(buf: np.ndarray, offsets: np.ndarray, lengths: np.ndarray,
                max_blocks: int):
    """Pack signed regions into pre-padded SHA256 rows.
    Returns (rows (n, max_blocks*64) uint8, n_blocks (n,) uint32).
    Oversized regions (legal per BOLT#7, up to 64 KiB) get n_blocks == 0
    and a zeroed row — callers route those to a host-side hash."""
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.uint64)
    lengths = np.ascontiguousarray(lengths, dtype=np.uint32)
    n = len(offsets)
    row_bytes = max_blocks * 64
    out = np.empty((n, row_bytes), np.uint8)
    n_blocks = np.empty(n, np.uint32)
    get_lib().sha256_pack(
        buf.ctypes.data, offsets.ctypes.data, lengths.ctypes.data, n,
        out.ctypes.data, row_bytes, n_blocks.ctypes.data,
    )
    return out, n_blocks


def gather_fields(buf: np.ndarray, offsets: np.ndarray, field_off: int,
                  field_len: int) -> np.ndarray:
    """out[i] = buf[offsets[i]+field_off : +field_len] as (n, field_len)."""
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.uint64)
    out = np.empty((len(offsets), field_len), np.uint8)
    get_lib().gather_fields(
        buf.ctypes.data, offsets.ctypes.data, len(offsets),
        field_off, field_len, out.ctypes.data,
    )
    return out
