"""Shared JAX configuration: persistent compilation cache.

The crypto kernels are scan-heavy (256-step field inversions, 64-step
windowed point multiplies); a cold compile takes minutes on a small host.
The persistent cache makes every process after the first start instantly,
which matters for the subdaemon architecture (each daemon process jits the
same kernels) and for repeated bench/test runs.
"""
import os

import jax

_DEFAULT_CACHE = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), ".jax_cache")


def setup_cache(path: str | None = None) -> None:
    path = path or os.environ.get("LIGHTNING_TPU_JAX_CACHE", _DEFAULT_CACHE)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
