"""Shared JAX configuration: persistent compilation cache.

The crypto kernels are scan-heavy (256-step field inversions, 64-step
windowed point multiplies); a cold compile takes minutes on a small host.
The persistent cache makes every process after the first start instantly,
which matters for the subdaemon architecture (each daemon process jits the
same kernels) and for repeated bench/test runs.
"""
import os
import re

import jax

_DEFAULT_CACHE = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), ".jax_cache")

# Compile-time-over-runtime XLA options: ~50 s instead of ~250 s per cold
# EC-kernel compile on this host's CPU backend, at the cost of slower
# generated code.  Right for dry-runs/tests/fallbacks, wrong for benches.
CHEAP_COMPILE_OPTS = {
    "xla_llvm_disable_expensive_passes": True,
    "xla_backend_optimization_level": 0,
}


def setup_cache(path: str | None = None) -> None:
    """Point jax at the persistent compilation cache.

    LIGHTNING_TPU_JAX_CACHE_MODE gates how the process uses it:
      rw (default) — read + write (daemons, benches, warmup scripts)
      ro           — read-only: warm programs still load instantly,
                     but nothing new is serialized.  The suite runs in
                     this mode (tests/conftest.py): the cache-WRITE
                     path (executable serialization on a box this
                     loaded) is where the long-standing 1-in-2 pytest
                     SIGSEGV fired, and a test run has no business
                     mutating the shared cache anyway — new programs
                     are warmed into it once, out-of-band, via
                     `python -c "from lightning_tpu.gossip.verify
                     import warmup; warmup(8)"`.
      off          — no persistent cache at all (cold compiles every
                     process; only for debugging the cache itself).
    """
    mode = os.environ.get("LIGHTNING_TPU_JAX_CACHE_MODE", "rw")
    if mode == "off":
        return
    path = path or os.environ.get("LIGHTNING_TPU_JAX_CACHE", _DEFAULT_CACHE)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # read-only: an absurd write threshold keeps every lookup live but
    # makes no compile ever eligible for serialization
    min_secs = 1.0 if mode != "ro" else 1e9
    jax.config.update("jax_persistent_cache_min_compile_time_secs", min_secs)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def force_cpu(n_devices: int | None = None, cheap_compile: bool = False) -> None:
    """Force the CPU platform, with >= n_devices virtual devices if given.

    Must run BEFORE any jax backend initializes: the environment preloads
    an `axon` TPU platform from sitecustomize, so both the env vars AND
    jax.config must be overridden (env alone loses once jax is imported).
    Used by tests/conftest.py, __graft_entry__.dryrun_multichip, and
    bench.py's CPU fallback — keep the dance in this one place.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if n_devices:
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
        if m is None:
            flags = (
                flags + f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()
        elif int(m.group(1)) < n_devices:
            flags = flags.replace(
                m.group(0), f"--xla_force_host_platform_device_count={n_devices}"
            )
    if cheap_compile and "--xla_llvm_disable_expensive_passes" not in flags:
        cheap = " ".join(
            f"--{k}={str(v).lower() if isinstance(v, bool) else v}"
            for k, v in CHEAP_COMPILE_OPTS.items()
        )
        flags = (flags + " " + cheap).strip()
    os.environ["XLA_FLAGS"] = flags
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already up; callers assert on default_backend()
