"""Layered configuration engine with provenance tracking.

Parity target: common/configvar.c (layered sources + provenance),
lightningd/options.c (typed option registry, `clnopt_*` sites) and the
`listconfigs`/`setconfig` RPC surface (lightningd/configs.c).

Sources layer in increasing precedence:
    default < config file < network config file < cmdline < setconfig
Each value remembers where it came from (`listconfigs` shows it), and
only options registered `dynamic=True` may be changed at runtime
(setconfig), matching the reference's dynamic-option gating.

Config file format is the reference's: one `name=value` per line,
`name` alone for flags, `#` comments, and `include <file>`.
"""
from __future__ import annotations

import os
import shlex
from dataclasses import dataclass, field

SOURCES = ("default", "file", "network_file", "cmdline", "setconfig")


class ConfigError(Exception):
    pass


def _parse_bool(v: str) -> bool:
    if v in ("true", "True", "1", "yes"):
        return True
    if v in ("false", "False", "0", "no"):
        return False
    raise ConfigError(f"not a boolean: {v!r}")


_PARSERS = {
    "string": str,
    "int": int,
    "bool": _parse_bool,
    "flag": lambda v: True,
    "msat": lambda v: int(v[:-4]) if v.endswith("msat") else int(v),
    "sat": lambda v: int(v[:-3]) if v.endswith("sat") else int(v),
    "float": float,
}


@dataclass
class OptSpec:
    name: str
    type: str = "string"          # key into _PARSERS
    default: object = None
    description: str = ""
    dynamic: bool = False         # settable via setconfig at runtime
    multi: bool = False           # repeatable (collects a list)
    dev_only: bool = False

    def parse(self, value: str | None):
        if self.type == "flag":
            return True
        if value is None:
            raise ConfigError(f"--{self.name} requires a value")
        try:
            return _PARSERS[self.type](value)
        except (ValueError, KeyError) as e:
            raise ConfigError(f"--{self.name}: {e}")


@dataclass
class _Entry:
    value: object
    source: str
    file: str | None = None
    line: int | None = None


class Config:
    """Option registry + layered values."""

    def __init__(self, developer: bool = False):
        self.specs: dict[str, OptSpec] = {}
        self.values: dict[str, _Entry] = {}
        self.multi_values: dict[str, list[_Entry]] = {}
        self.developer = developer
        self.on_change: dict[str, object] = {}   # name -> callback(value)

    # -- registry ---------------------------------------------------------

    def register(self, *specs: OptSpec) -> None:
        for s in specs:
            if s.name in self.specs:
                raise ConfigError(f"option {s.name} registered twice")
            self.specs[s.name] = s

    def _spec(self, name: str) -> OptSpec:
        s = self.specs.get(name)
        if s is None:
            raise ConfigError(f"unknown option {name!r}")
        if s.dev_only and not self.developer:
            raise ConfigError(f"{name} requires --developer")
        return s

    # -- setting ----------------------------------------------------------

    def _set(self, name: str, raw: str | None, source: str,
             file: str | None = None, line: int | None = None) -> None:
        s = self._spec(name)
        val = s.parse(raw)
        e = _Entry(val, source, file, line)
        if s.multi:
            self.multi_values.setdefault(name, []).append(e)
        else:
            prev = self.values.get(name)
            # higher- or equal-precedence sources win (later file lines
            # override earlier ones; cmdline overrides files)
            if prev is None or SOURCES.index(source) >= SOURCES.index(
                    prev.source):
                self.values[name] = e

    def load_file(self, path: str, source: str = "file",
                  missing_ok: bool = True, _depth: int = 0) -> None:
        """Reference config-file syntax (common/configdir.c)."""
        if _depth > 10:
            raise ConfigError("include depth exceeded")
        if not os.path.exists(path):
            if missing_ok:
                return
            raise ConfigError(f"config file {path} not found")
        with open(path) as f:
            for ln, rawline in enumerate(f, 1):
                s = rawline.strip()
                if not s or s.startswith("#"):
                    continue
                if s.startswith("include "):
                    inc = shlex.split(s[len("include "):])[0]
                    if not os.path.isabs(inc):
                        inc = os.path.join(os.path.dirname(path), inc)
                    self.load_file(inc, source, missing_ok=False,
                                   _depth=_depth + 1)
                    continue
                name, sep, value = s.partition("=")
                self._set(name.strip(),
                          value.strip() if sep else None,
                          source, file=path, line=ln)

    def parse_argv(self, argv: list[str]) -> list[str]:
        """Consume --name[=value] style args; returns non-option rest."""
        rest, i = [], 0
        while i < len(argv):
            a = argv[i]
            if not a.startswith("--"):
                rest.append(a)
                i += 1
                continue
            name, sep, value = a[2:].partition("=")
            spec = self._spec(name)
            if not sep and spec.type != "flag":
                i += 1
                if i >= len(argv):
                    raise ConfigError(f"--{name} requires a value")
                value = argv[i]
            self._set(name, value if (sep or spec.type != "flag") else None,
                      "cmdline")
            i += 1
        return rest

    def setconfig(self, name: str, value: str | None) -> dict:
        """Runtime change (RPC `setconfig`); dynamic options only."""
        s = self._spec(name)
        if not s.dynamic:
            raise ConfigError(f"{name} is not a dynamic option")
        self._set(name, value, "setconfig")
        cb = self.on_change.get(name)
        if cb is not None:
            cb(self.get(name))
        return {"config": self._describe(name)}

    # -- reading ----------------------------------------------------------

    def get(self, name: str):
        s = self.specs[name]
        if s.multi:
            entries = self.multi_values.get(name)
            return [e.value for e in entries] if entries else (s.default or [])
        e = self.values.get(name)
        return e.value if e is not None else s.default

    def __getitem__(self, name: str):
        return self.get(name)

    def _describe(self, name: str) -> dict:
        s = self.specs[name]
        out = {"value_" + ("int" if s.type in ("int", "msat", "sat")
                           else "bool" if s.type in ("bool", "flag")
                           else "str"): self.get(name),
               "source": "default"}
        e = self.values.get(name)
        if e is not None:
            out["source"] = e.source if e.file is None else \
                f"{e.file}:{e.line}"
        if s.dynamic:
            out["dynamic"] = True
        return out

    def listconfigs(self) -> dict:
        """RPC `listconfigs` shape: {configs: {name: {value_*, source}}}"""
        return {"configs": {
            name: self._describe(name)
            for name, s in sorted(self.specs.items())
            if not (s.dev_only and not self.developer)
        }}


# ---------------------------------------------------------------------------
# The node's option registry (subset of lightningd/options.c's 80 clnopt_*
# registrations, growing as subsystems land).

def node_options() -> Config:
    cfg = Config()
    cfg.register(
        OptSpec("network", "string", "regtest", "chain network name"),
        OptSpec("alias", "string", None, "node alias (up to 32 bytes)",
                dynamic=True),
        OptSpec("rgb", "string", "0377ff", "node color"),
        OptSpec("bind-addr", "string", "127.0.0.1", "listen address"),
        OptSpec("addr", "string", None, "public address", multi=True),
        OptSpec("port", "int", 19846, "listen port"),
        OptSpec("rpc-file", "string", None, "JSON-RPC unix socket path"),
        OptSpec("lightning-dir", "string", None, "data directory"),
        OptSpec("log-level", "string", "info", "minimum log level",
                dynamic=True),
        OptSpec("log-file", "string", None, "log to this file", multi=True),
        OptSpec("fee-base", "int", 1000, "routing base fee msat",
                dynamic=True),
        OptSpec("fee-per-satoshi", "int", 10, "routing ppm fee",
                dynamic=True),
        OptSpec("cltv-delta", "int", 34, "forwarding cltv delta",
                dynamic=True),
        OptSpec("cltv-final", "int", 18, "final hop cltv"),
        OptSpec("max-concurrent-htlcs", "int", 30,
                "HTLC slots offered per channel (options.c:979)"),
        OptSpec("min-capacity-sat", "int", 10000,
                "reject channels smaller than this", dynamic=True),
        OptSpec("funding-confirms", "int", 3, "depth before channel_ready"),
        OptSpec("watchtime-blocks", "int", 144, "to_self_delay we demand"),
        OptSpec("gossip-store-file", "string", None, "gossip store path"),
        OptSpec("offline", "flag", False, "do not listen or reconnect"),
        OptSpec("developer", "flag", False, "enable dev options"),
        OptSpec("dev-fast-gossip", "flag", False, "short gossip timers",
                dev_only=True),
        OptSpec("verify-batch-size", "int", 256,
                "signature batch flush threshold (TPU occupancy)",
                dynamic=True),
        OptSpec("verify-batch-ms", "float", 2.0,
                "signature batch flush deadline ms", dynamic=True),
    )
    return cfg
