"""Random-mutation fuzz harnesses for the attack-surface parsers.

Parity target: the reference's libFuzzer pack (82 targets in
tests/fuzz/fuzz-*.c with seed corpora; runner tests/fuzz/check-fuzz.sh)
— the codecs and the Noise handshake are exactly the byte surfaces a
remote attacker controls.  We fuzz the same way libFuzzer's default
mutator does in spirit: start from valid seeds, apply bit flips, byte
splices, truncations, duplications, and magic-value injections, and
assert the parser either succeeds or raises its DECLARED error type —
any other exception is a finding.

Deterministic by seed, so the CI smoke run (tests/test_fuzz_smoke.py)
is reproducible; crank iterations via fuzz_all(n=...) for longer local
campaigns.
"""
from __future__ import annotations

import hashlib
import random

MAGIC = [b"\x00", b"\xff", b"\x7f", b"\x80", b"\x00\x00\x00\x00",
         b"\xff\xff\xff\xff", b"\xfd\x00\xfd", b"\xfe", b"\x01" * 9]


def mutate(rng: random.Random, seed: bytes) -> bytes:
    """One libFuzzer-ish mutation of a seed input."""
    data = bytearray(seed)
    for _ in range(rng.randint(1, 8)):
        op = rng.randrange(6)
        if not data:
            data = bytearray(rng.randbytes(rng.randint(1, 64)))
            continue
        if op == 0:      # bit flip
            i = rng.randrange(len(data))
            data[i] ^= 1 << rng.randrange(8)
        elif op == 1:    # byte overwrite
            data[rng.randrange(len(data))] = rng.randrange(256)
        elif op == 2:    # truncate
            data = data[:rng.randrange(len(data) + 1)]
        elif op == 3:    # insert random chunk
            i = rng.randrange(len(data) + 1)
            data[i:i] = rng.randbytes(rng.randint(1, 16))
        elif op == 4:    # splice magic value
            i = rng.randrange(len(data) + 1)
            m = rng.choice(MAGIC)
            data[i:i + len(m)] = m
        else:            # duplicate a slice
            if len(data) >= 2:
                a = rng.randrange(len(data) - 1)
                b = rng.randrange(a + 1, len(data))
                data[b:b] = data[a:b]
    return bytes(data)


class FuzzFinding(AssertionError):
    pass


def run_target(name: str, fn, seeds: list[bytes], allowed: tuple,
               n: int = 2000, seed: int = 1337) -> int:
    """fn(data) must return or raise one of `allowed`.  Returns the
    number of executions.  Raises FuzzFinding on any other exception."""
    rng = random.Random(f"{name}:{seed}")
    execs = 0
    for s in seeds:       # seeds themselves must not crash either
        _exec_one(name, fn, s, allowed)
        execs += 1
    for i in range(n):
        data = mutate(rng, rng.choice(seeds))
        _exec_one(name, fn, data, allowed)
        execs += 1
    return execs


def _exec_one(name, fn, data, allowed):
    try:
        fn(data)
    except allowed:
        pass
    except Exception as e:   # noqa: BLE001 — the whole point
        raise FuzzFinding(
            f"[{name}] {type(e).__name__}: {e} on input "
            f"{data[:64].hex()}... (len {len(data)}, "
            f"sha256 {hashlib.sha256(data).hexdigest()[:16]})") from e


# ---------------------------------------------------------------------------
# Targets (each returns (fn, seeds, allowed_exceptions))


def target_wire_codec():
    """Peer-message parse: every registered BOLT#1/2/7 message type."""
    from ..wire import codec
    from ..wire import messages as M

    seeds = [
        M.Init(globalfeatures=b"", features=b"\x02\xaa").serialize(),
        M.Ping(num_pong_bytes=8, ignored=b"\x00" * 4).serialize(),
        M.UpdateAddHtlc(channel_id=b"\x11" * 32, id=7,
                        amount_msat=10_000, payment_hash=b"\x22" * 32,
                        cltv_expiry=500_000,
                        onion_routing_packet=b"\x03" * 1366).serialize(),
        M.ChannelReestablish(
            channel_id=b"\x11" * 32, next_commitment_number=2,
            next_revocation_number=1,
            your_last_per_commitment_secret=b"\x04" * 32,
            my_current_per_commitment_point=b"\x02" + b"\x05" * 32,
        ).serialize(),
        M.Shutdown(channel_id=b"\x11" * 32,
                   scriptpubkey=b"\x00\x14" + b"\x33" * 20).serialize(),
    ]

    def fn(data: bytes):
        t = codec.msg_type(data)
        cls = codec.MessageMeta.registry.get(t)
        if cls is not None:
            cls.parse(data)

    return fn, seeds, (codec.WireError,)


def target_tlv_stream():
    from ..wire import codec

    seeds = [
        codec.write_tlv_stream({1: b"\x01", 3: b"abc", 7: b"\xff" * 8}),
        b"",
        codec.write_tlv_stream({2: (500).to_bytes(2, "big")}),
    ]
    return (lambda d: codec.read_tlv_stream(d)), seeds, (codec.WireError,)


def target_noise_acts():
    """Noise_XK responder driving acts 1+3 from attacker bytes
    (fuzz-connectd-handshake-act{1,3}.c role)."""
    from ..bolt import noise

    rs = noise.Keypair(7)        # responder static
    ri = noise.Keypair(9)        # initiator static
    ei = noise.Keypair(11)
    er = noise.Keypair(13)

    # valid act1/act3 seeds from a real handshake
    act1_seed, on_act2 = noise.initiator_handshake(ri, ei, rs.pub)
    hr = noise.HandshakeState(rs.pub)
    noise.responder_act1(hr, rs, act1_seed)
    act2 = noise.responder_act2(hr, er, ei.pub)
    act3_seed, _keys = on_act2(act2)

    def fn(data: bytes):
        # attacker act1 against a fresh responder
        h1 = noise.HandshakeState(rs.pub)
        try:
            re_pub = noise.responder_act1(h1, rs, data)
            noise.responder_act2(h1, er, re_pub)
        except noise.HandshakeError:
            pass
        # attacker act3 against a valid post-act2 responder state
        h2 = noise.HandshakeState(rs.pub)
        noise.responder_act1(h2, rs, act1_seed)
        noise.responder_act2(h2, er, ei.pub)
        noise.responder_act3(h2, er, data)

    return fn, [act1_seed, act3_seed], (noise.HandshakeError, ValueError)


def target_sphinx_peel():
    from ..bolt import onion_payload as OP
    from ..bolt import sphinx as SX
    from ..crypto import ref_python as ref

    payment_hash = b"\x21" * 32
    node_key = 0x4242
    onion, _ = OP.build_route_onion(
        [ref.pubkey_serialize(ref.pubkey_create(node_key))],
        [OP.HopPayload(1000, 100)], payment_hash, session_key=0x99)

    def fn(data: bytes):
        pkt = SX.OnionPacket.parse(data)
        SX.peel_onion(pkt, payment_hash, node_key)

    return fn, [onion], (SX.SphinxError, ValueError)


def target_bolt11():
    from ..bolt import bolt11

    seeds = [
        bolt11.new_invoice(0x1234, b"\x11" * 32, 12345, "fuzz seed",
                           payment_secret=b"\x22" * 32)[0].encode(),
        b"lnbc1invalid",
    ]

    def fn(data: bytes):
        try:
            s = data.decode("ascii")
        except UnicodeDecodeError:
            return
        bolt11.decode(s)

    return fn, seeds, (bolt11.Bolt11Error, ValueError)


def target_bolt12():
    from ..wire.codec import WireError, read_tlv_stream, write_tlv_stream
    from ..bolt import bolt12 as B12

    offer = B12.Offer(description="fuzz", amount_msat=5,
                      issuer_id=b"\x02" + b"\x11" * 32)
    seeds = [offer.encode().encode(),
             write_tlv_stream(offer.tlvs())]

    def fn(data: bytes):
        try:
            s = data.decode("ascii")
            B12.Offer.decode(s)
            return
        except UnicodeDecodeError:
            pass
        B12.Offer.from_tlvs(read_tlv_stream(data))

    return fn, seeds, (B12.Bolt12Error, ValueError, WireError)


def target_gossip_store():
    import os
    import tempfile

    from ..gossip import store as gstore
    from ..gossip import synth

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "seed.gs")
        synth.make_network_store(path, n_channels=3, n_nodes=2, sign=False)
        seed = open(path, "rb").read()

    def fn(data: bytes):
        import io

        with tempfile.NamedTemporaryFile() as f:
            f.write(data)
            f.flush()
            try:
                idx = gstore.load_store(f.name)
                idx.check_crcs()
            except (ValueError, EOFError):
                pass

    return fn, [seed], (ValueError, EOFError)


def target_onion_payload():
    from ..bolt import onion_payload as OP

    seeds = [
        OP.HopPayload(1000, 100, short_channel_id=42).serialize(),
        OP.HopPayload(1000, 100, payment_secret=b"\x01" * 32,
                      total_msat=5000).serialize(),
        OP.HopPayload(1000, 100, encrypted_recipient_data=b"\x02" * 50,
                      path_key=b"\x03" * 33).serialize(),
    ]
    return (lambda d: OP.HopPayload.parse(d)), seeds, (OP.PayloadError,)


TARGETS = {
    "wire_codec": target_wire_codec,
    "tlv_stream": target_tlv_stream,
    "noise_acts": target_noise_acts,
    "sphinx_peel": target_sphinx_peel,
    "bolt11": target_bolt11,
    "bolt12": target_bolt12,
    "gossip_store": target_gossip_store,
    "onion_payload": target_onion_payload,
}


def fuzz_all(n: int = 2000, seed: int = 1337) -> dict[str, int]:
    out = {}
    for name, mk in TARGETS.items():
        fn, seeds, allowed = mk()
        out[name] = run_target(name, fn, seeds, allowed, n=n, seed=seed)
    return out


if __name__ == "__main__":
    import sys

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 20000
    for name, execs in fuzz_all(n=n).items():
        print(f"{name}: {execs} execs, no findings")
