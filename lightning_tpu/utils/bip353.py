"""BIP-353 DNS payment instructions: ₿user@domain → BOLT#12 offer.

Parity target: the reference's bip353 resolution inside its fetchinvoice
path (plugins/fetchinvoice + the bundled dnssec-prover): a payment
address `user@domain` resolves the TXT record at
`user.user._bitcoin-payment.domain`, whose concatenated strings form a
`bitcoin:` URI carrying an `lno=` offer (and/or on-chain fallbacks).

This implementation includes a small RFC1035 DNS client (UDP, TXT
queries, TCP-sized answers out of scope) with a PLUGGABLE resolver so
tests inject records and deployments can route through a trusted
resolver.  DNSSEC proof verification — the reference vendors a prover —
is NOT implemented; stated plainly: resolution here trusts the
configured resolver, so treat results accordingly.
"""
from __future__ import annotations

import asyncio
import os
import re
import secrets

TXT = 16
CLASS_IN = 1


class Bip353Error(Exception):
    pass


def parse_address(addr: str) -> tuple[str, str]:
    """`₿user@domain` (the ₿ prefix is optional per BIP-353)."""
    addr = addr.strip()
    if addr.startswith("₿"):
        addr = addr[1:]
    m = re.fullmatch(r"([a-zA-Z0-9._~!$&'()*+,;=:-]+)@"
                     r"([a-zA-Z0-9.-]+)", addr)
    if m is None:
        raise Bip353Error(f"not a BIP-353 address: {addr!r}")
    return m.group(1), m.group(2)


def query_name(user: str, domain: str) -> str:
    return f"{user}.user._bitcoin-payment.{domain}"


def _encode_name(name: str) -> bytes:
    out = bytearray()
    for label in name.rstrip(".").split("."):
        raw = label.encode("idna") if not label.isascii() \
            else label.encode()
        if not 0 < len(raw) < 64:
            raise Bip353Error(f"bad DNS label {label!r}")
        out.append(len(raw))
        out += raw
    out.append(0)
    return bytes(out)


def build_txt_query(name: str, txid: int) -> bytes:
    hdr = txid.to_bytes(2, "big") + b"\x01\x00" + b"\x00\x01" \
        + b"\x00\x00" * 3
    return hdr + _encode_name(name) + TXT.to_bytes(2, "big") \
        + CLASS_IN.to_bytes(2, "big")


def _skip_name(buf: bytes, off: int) -> int:
    while True:
        ln = buf[off]
        if ln == 0:
            return off + 1
        if ln & 0xC0 == 0xC0:      # compression pointer
            return off + 2
        off += 1 + ln


def parse_txt_response(buf: bytes, txid: int) -> list[bytes]:
    """All TXT rdata strings (concatenated per record, RFC7208-style)."""
    if len(buf) < 12 or int.from_bytes(buf[:2], "big") != txid:
        raise Bip353Error("DNS response id mismatch")
    if buf[3] & 0x0F != 0:
        raise Bip353Error(f"DNS rcode {buf[3] & 0x0F}")
    qd = int.from_bytes(buf[4:6], "big")
    an = int.from_bytes(buf[6:8], "big")
    off = 12
    for _ in range(qd):
        off = _skip_name(buf, off) + 4
    out = []
    for _ in range(an):
        off = _skip_name(buf, off)
        rtype = int.from_bytes(buf[off:off + 2], "big")
        rdlen = int.from_bytes(buf[off + 8:off + 10], "big")
        rdata = buf[off + 10:off + 10 + rdlen]
        off += 10 + rdlen
        if rtype != TXT:
            continue
        parts, p = [], 0
        while p < len(rdata):
            ln = rdata[p]
            parts.append(rdata[p + 1:p + 1 + ln])
            p += 1 + ln
        out.append(b"".join(parts))
    return out


async def udp_txt_resolver(name: str,
                           server: str | None = None,
                           timeout: float = 5.0) -> list[bytes]:
    """Minimal RFC1035 TXT query over UDP (the pluggable default)."""
    server = server or os.environ.get("LIGHTNING_TPU_DNS", "127.0.0.53")
    port = 53
    if ":" in server:
        server, _, p = server.rpartition(":")
        port = int(p)
    txid = secrets.randbits(16)
    query = build_txt_query(name, txid)
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    class _Proto(asyncio.DatagramProtocol):
        def datagram_received(self, data, addr):
            if not fut.done():
                fut.set_result(data)

        def error_received(self, exc):
            if not fut.done():
                fut.set_exception(exc)

    transport, _ = await loop.create_datagram_endpoint(
        _Proto, remote_addr=(server, port))
    try:
        transport.sendto(query)
        data = await asyncio.wait_for(fut, timeout)
    finally:
        transport.close()
    return parse_txt_response(data, txid)


def parse_bitcoin_uri(txt: str) -> dict:
    """bitcoin:[address]?key=value&... → {address?, lno?, sp?, ...}."""
    if not txt.lower().startswith("bitcoin:"):
        raise Bip353Error("TXT record is not a bitcoin: URI")
    rest = txt[len("bitcoin:"):]
    addr, _, qs = rest.partition("?")
    out: dict = {}
    if addr:
        out["address"] = addr
    for kv in qs.split("&"):
        if not kv:
            continue
        k, _, v = kv.partition("=")
        out[k.lower()] = v
    return out


async def resolve(address: str, resolver=None) -> dict:
    """user@domain → parsed payment instructions.  resolver:
    async (dns_name) -> list[bytes] (default: udp_txt_resolver)."""
    user, domain = parse_address(address)
    name = query_name(user, domain)
    resolver = resolver or udp_txt_resolver
    records = await resolver(name)
    for rec in records:
        try:
            uri = parse_bitcoin_uri(rec.decode("utf-8", "strict"))
        except (Bip353Error, UnicodeDecodeError):
            continue
        if "lno" in uri or "address" in uri or "sp" in uri:
            uri["dns_name"] = name
            return uri
    raise Bip353Error(f"no payment instructions at {name}")
