"""Tiny synchronous notification bus.

Functional parity target: lightningd/notification.c's topics
(REGISTER_NOTIFICATION sites) — in-process subscribers instead of
plugin-process fan-out; the PluginHost bridges topics to external
plugins, the bookkeeper consumes `coin_movement` directly.

Emission never raises: a broken subscriber must not break a payment.
"""
from __future__ import annotations

import logging

log = logging.getLogger("lightning_tpu.events")

_subscribers: dict[str, list] = {}
_wildcard: list = []


def subscribe(topic: str, fn) -> None:
    _subscribers.setdefault(topic, []).append(fn)


def subscribe_all(fn) -> None:
    """fn(topic, payload) for EVERY emission — the PluginHost bridge
    (notification.c fan-out to plugin subscriptions)."""
    _wildcard.append(fn)


def unsubscribe_all(fn) -> None:
    if fn in _wildcard:
        _wildcard.remove(fn)


def unsubscribe(topic: str, fn) -> None:
    lst = _subscribers.get(topic, [])
    if fn in lst:
        lst.remove(fn)


def emit(topic: str, payload: dict) -> None:
    for fn in list(_subscribers.get(topic, [])):
        try:
            fn(payload)
        except Exception:
            log.exception("subscriber for %s failed", topic)
    for fn in list(_wildcard):
        try:
            fn(topic, payload)
        except Exception:
            log.exception("wildcard subscriber failed on %s", topic)


def reset() -> None:
    """Test isolation helper."""
    _subscribers.clear()
    _wildcard.clear()
