"""Tracing spans: lightweight structured profiling of daemon phases.

Functional parity target: common/trace.c (trace_span_start/end/
suspend/resume emitting USDT probes consumed by contrib/cln-tracer) —
re-targeted: spans emit JSON lines (one object per completed span) to a
sink, and — the TPU twist — a span can wrap a `jax.profiler` trace so
host-side phases correlate with the device timeline.

Usage:
    from lightning_tpu.utils import trace
    with trace.span("gossip/verify", batch=4096):
        ...
    trace.set_sink(path_or_callable)   # default: in-memory ring

Spans nest via a contextvar; each record carries its parent's name so a
flame view can be reconstructed.  Suspend/resume (for spans crossing an
await) are modeled by `span()` measuring wall time only between enter
and exit — matching trace.c's span lifetime semantics.
"""
from __future__ import annotations

import contextvars
import json
import os
import time
from contextlib import contextmanager

_current = contextvars.ContextVar("trace_span", default=None)

_records: list[dict] = []
_MAX_RECORDS = 10_000
_sink = None          # None → ring buffer; else callable(record)
_file = None
# taps see EVERY record regardless of the sink (the obs collector feeds
# span-duration histograms from here; a tap must never raise into the
# traced code path)
_taps: list = []


def set_sink(sink) -> None:
    """sink: a path (append JSON lines) or a callable(record) or None
    (in-memory ring, default)."""
    global _sink, _file
    if _file is not None:
        _file.close()
        _file = None
    if isinstance(sink, str):
        _file = open(sink, "a")
        _sink = lambda rec: (_file.write(json.dumps(rec) + "\n"),
                             _file.flush())
    else:
        _sink = sink


def add_tap(fn) -> None:
    """Register fn(record) to observe every completed span, independent
    of (and in addition to) the configured sink."""
    if fn not in _taps:
        _taps.append(fn)


def remove_tap(fn) -> None:
    if fn in _taps:
        _taps.remove(fn)


def records() -> list[dict]:
    return list(_records)


def reset() -> None:
    _records.clear()


def _emit(rec: dict) -> None:
    for tap in list(_taps):
        try:
            tap(rec)
        except Exception:
            pass
    if _sink is not None:
        _sink(rec)
        return
    _records.append(rec)
    if len(_records) > _MAX_RECORDS:
        del _records[: _MAX_RECORDS // 2]


@contextmanager
def span(name: str, **attributes):
    """Measure one phase; attaches to the enclosing span as parent."""
    parent = _current.get()
    token = _current.set(name)
    t0 = time.monotonic_ns()
    err = None
    try:
        yield
    except BaseException as e:
        err = type(e).__name__
        raise
    finally:
        _current.reset(token)
        rec = {
            "name": name,
            "parent": parent,
            "start_ns": t0,
            "duration_ns": time.monotonic_ns() - t0,
        }
        if attributes:
            rec["attributes"] = attributes
        if err is not None:
            rec["error"] = err
        _emit(rec)


@contextmanager
def device_span(name: str, **attributes):
    """A span that also captures the XLA device timeline when
    LIGHTNING_TPU_PROFILE_DIR is set (jax.profiler trace) — the
    correlation hook cln-tracer gets from USDT probes."""
    profile_dir = os.environ.get("LIGHTNING_TPU_PROFILE_DIR")
    if profile_dir:
        import jax

        with jax.profiler.trace(profile_dir):
            with span(name, profiled=True, **attributes):
                yield
    else:
        with span(name, **attributes):
            yield


def summarize() -> dict:
    """Aggregate by span name: count + total/mean duration (the quick
    operator view `getlog`-style)."""
    agg: dict[str, list[int]] = {}
    for r in _records:
        agg.setdefault(r["name"], []).append(r["duration_ns"])
    return {
        name: {
            "count": len(ds),
            "total_ms": sum(ds) / 1e6,
            "mean_ms": sum(ds) / len(ds) / 1e6,
        }
        for name, ds in agg.items()
    }
