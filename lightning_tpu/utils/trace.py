"""Tracing spans: lightweight structured profiling of daemon phases.

Functional parity target: common/trace.c (trace_span_start/end/
suspend/resume emitting USDT probes consumed by contrib/cln-tracer) —
re-targeted: spans emit JSON lines (one object per completed span) to a
sink, and — the TPU twist — a span can wrap a `jax.profiler` trace so
host-side phases correlate with the device timeline.

Usage:
    from lightning_tpu.utils import trace
    with trace.span("gossip/verify", batch=4096):
        ...
    trace.set_sink(path_or_callable)   # default: in-memory ring

Spans nest via a contextvar; each record carries its parent's name (and
span id) so a flame view can be reconstructed.  Suspend/resume (for
spans crossing an await) are modeled by `span()` measuring wall time
only between enter and exit — matching trace.c's span lifetime
semantics.

Cross-thread correlation (doc/tracing.md): contextvars do not follow
work onto producer threads or flush loops, so causality is carried by
an EXPLICIT ``Carrier`` object instead.  ``new_corr()`` mints one
inside the enqueue span (stamping that span with the correlation id);
the carrier rides the queue item / batch to wherever the work is
dispatched, and every downstream span opened with ``corr=carrier``
shares the id.  The exporter (obs/traceexport.py) turns each
correlation id into a Perfetto flow arrow chain, linking the enqueue
span to its prep/dispatch/readback spans across threads.  Every record
carries ``span_id``/``parent_id``/``tid``/``thread``; spans on a
dispatch path additionally carry ``corr_ids`` (plus ``corr_id``, the
first) and ``dispatch_id``.
"""
from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
from contextlib import contextmanager

_current = contextvars.ContextVar("trace_span", default=None)

_records: list[dict] = []        # guarded-by: _lock
_MAX_RECORDS = 10_000
_sink = None          # guarded-by: _lock  (None → ring; else callable)
_file = None          # guarded-by: _lock
# taps see EVERY record regardless of the sink (the obs collector feeds
# span-duration histograms from here; a tap must never raise into the
# traced code path)
_taps: list = []                 # guarded-by: _lock
# one lock for ring + taps + sink swaps: flush loops, the replay
# producer thread, and the main thread all emit concurrently, and a
# bare list append/prune pair is a lost-update race under free threading
_lock = threading.RLock()

_span_ids = itertools.count(1)
_corr_ids = itertools.count(1)

# spans on a big coalesced dispatch can carry hundreds of carriers; cap
# what a single record stores so the ring stays bounded (the flow chain
# for capped-out carriers simply starts at the flush span)
CORR_CAP = 32


class Carrier:
    """Explicit correlation context — the cross-thread causality token.

    Mint with ``new_corr()`` at the enqueue point; pass by reference to
    the thread/loop doing the work; open downstream spans with
    ``corr=carrier``.  Deliberately NOT a contextvar: the whole point
    is to survive hops contextvars cannot follow."""

    __slots__ = ("corr_id", "span_id")

    def __init__(self, corr_id: int, span_id: int):
        self.corr_id = corr_id
        self.span_id = span_id

    def __repr__(self):
        return f"Carrier(corr_id={self.corr_id}, span_id={self.span_id})"


class _Span:
    __slots__ = ("name", "span_id", "corr_ids")

    def __init__(self, name: str, span_id: int):
        self.name = name
        self.span_id = span_id
        self.corr_ids: list[int] = []


def new_corr() -> Carrier:
    """Mint a correlation carrier at the CURRENT span (the enqueue
    point).  The enclosing span's record gains the correlation id, so
    exported flow arrows start there; with no enclosing span the
    carrier still correlates every downstream span that adopts it."""
    cur = _current.get()
    c = Carrier(next(_corr_ids), cur.span_id if cur is not None else 0)
    if cur is not None and len(cur.corr_ids) < CORR_CAP:
        cur.corr_ids.append(c.corr_id)
    return c


def as_carriers(corr) -> tuple:
    """Normalize a ``corr=`` argument: None, one Carrier, or an
    iterable of Carriers → tuple of Carriers."""
    if corr is None:
        return ()
    if isinstance(corr, Carrier):
        return (corr,)
    return tuple(c for c in corr if isinstance(c, Carrier))


def set_sink(sink) -> None:
    """sink: a path (append JSON lines) or a callable(record) or None
    (in-memory ring, default).  Crash-safe: the previous file sink is
    closed even when opening the new one fails (in which case records
    fall back to the in-memory ring)."""
    global _sink, _file
    with _lock:
        old, _file = _file, None
        _sink = None
        try:
            if isinstance(sink, str):
                f = open(sink, "a")
                _file = f
                _sink = lambda rec: (f.write(json.dumps(rec) + "\n"),
                                     f.flush())
            else:
                _sink = sink
        finally:
            if old is not None:
                try:
                    old.close()
                except OSError:
                    pass


def add_tap(fn) -> None:
    """Register fn(record) to observe every completed span, independent
    of (and in addition to) the configured sink."""
    with _lock:
        if fn not in _taps:
            _taps.append(fn)


def remove_tap(fn) -> None:
    with _lock:
        if fn in _taps:
            _taps.remove(fn)


def records() -> list[dict]:
    with _lock:
        return list(_records)


def reset() -> None:
    with _lock:
        _records.clear()


def _emit(rec: dict) -> None:
    with _lock:
        taps = list(_taps)
    for tap in taps:
        try:
            tap(rec)
        except Exception:
            pass
    # the sink runs UNDER the lock: set_sink closes the old file under
    # the same lock, so a rotation can never close the file out from
    # under a concurrent write (and two threads' JSONL lines can't
    # interleave)
    with _lock:
        if _sink is not None:
            _sink(rec)
            return
        _records.append(rec)
        if len(_records) > _MAX_RECORDS:
            del _records[: _MAX_RECORDS // 2]


@contextmanager
def span(name: str, corr=None, dispatch_id: int | None = None,
         **attributes):
    """Measure one phase; attaches to the enclosing span as parent.

    ``corr`` (a Carrier or iterable of Carriers) stamps the record with
    the correlation ids so the exporter can draw cross-thread flow
    arrows; ``dispatch_id`` ties the span to its flight-recorder
    DispatchRecord (obs/flight.py)."""
    parent = _current.get()
    sp = _Span(name, next(_span_ids))
    for c in as_carriers(corr):
        if len(sp.corr_ids) >= CORR_CAP:
            break
        sp.corr_ids.append(c.corr_id)
    token = _current.set(sp)
    t0 = time.monotonic_ns()
    err = None
    try:
        yield sp
    except BaseException as e:
        err = type(e).__name__
        raise
    finally:
        _current.reset(token)
        rec = {
            "name": name,
            "parent": parent.name if parent is not None else None,
            "span_id": sp.span_id,
            "parent_id": parent.span_id if parent is not None else None,
            "tid": threading.get_native_id(),
            "thread": threading.current_thread().name,
            "start_ns": t0,
            "duration_ns": time.monotonic_ns() - t0,
        }
        if sp.corr_ids:
            rec["corr_ids"] = list(sp.corr_ids)
            rec["corr_id"] = sp.corr_ids[0]
        if dispatch_id is not None:
            rec["dispatch_id"] = dispatch_id
        if attributes:
            rec["attributes"] = attributes
        if err is not None:
            rec["error"] = err
        _emit(rec)


@contextmanager
def device_span(name: str, **attributes):
    """A span that also captures the XLA device timeline when
    LIGHTNING_TPU_PROFILE_DIR is set (jax.profiler trace) — the
    correlation hook cln-tracer gets from USDT probes."""
    profile_dir = os.environ.get("LIGHTNING_TPU_PROFILE_DIR")
    if profile_dir:
        import jax

        with jax.profiler.trace(profile_dir):
            with span(name, profiled=True, **attributes):
                yield
    else:
        with span(name, **attributes):
            yield


# -- dispatch profiling (LIGHTNING_TPU_PROFILE, doc/tracing.md) ------------
# One jax.profiler session brackets a whole workload (a replay, a bench
# round) and every dispatch inside annotates itself, so the host lanes
# of our Chrome-trace export line up with the XLA device timeline in
# the same Perfetto UI.  Both are strict no-ops unless the env knob is
# set AND a session is active — the live path never imports jax.profiler.

_profile_active = False          # guarded-by: _lock


@contextmanager
def profile_session():
    """Bracket a workload with jax.profiler start/stop when
    LIGHTNING_TPU_PROFILE=<dir> is set; nested or concurrent sessions
    no-op (the flag flips under the module lock — two replays racing
    here must not both call start_trace, which would raise into the
    second one's verify path)."""
    global _profile_active
    profile_dir = os.environ.get("LIGHTNING_TPU_PROFILE")
    if not profile_dir:
        yield
        return
    with _lock:
        owner = not _profile_active
        if owner:
            _profile_active = True
    if not owner:
        yield
        return
    import jax

    try:
        jax.profiler.start_trace(profile_dir)
    except BaseException:
        with _lock:
            _profile_active = False
        raise
    try:
        yield
    finally:
        with _lock:
            _profile_active = False
        jax.profiler.stop_trace()


@contextmanager
def annotation(name: str):
    """jax.profiler.TraceAnnotation around one dispatch — visible as a
    host-lane slice in the XLA profile; no-op outside a session."""
    if not _profile_active:
        yield
        return
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def summarize() -> dict:
    """Aggregate by span name: count + total/mean duration (the quick
    operator view `getlog`-style)."""
    agg: dict[str, list[int]] = {}
    for r in records():
        agg.setdefault(r["name"], []).append(r["duration_ns"])
    return {
        name: {
            "count": len(ds),
            "total_ms": sum(ds) / 1e6,
            "mean_ms": sum(ds) / len(ds) / 1e6,
        }
        for name, ds in agg.items()
    }
