"""Per-item journeys: entity-level provenance through the batched
dispatch pipeline (doc/journeys.md).

Every observability layer before this one is dispatch-centric — the
flight ring, perf attribution, health SLOs and incident bundles all
key on the *batch* — so none can answer "why is this scid's
channel_update not in my route planes?" or "where did part 3 of this
payment spend 400 ms?".  This module keys on the WORK ITEM: a sampled
entity (a channel's scid, a node id, a payment hash) accumulates one
bounded journey of hop records as it moves through the pipeline, each
hop carrying the ``dispatch_id``/``corr_id`` of the batch that carried
it, so a journey stitches into the flight ring and the trace timeline.

Sampling is DETERMINISTIC and entity-keyed: ``crc32(kind/key) %
LIGHTNING_TPU_JOURNEY_SAMPLE == 0``.  The same entity is therefore
sampled at every hop in every thread and every process with no
coordination — the classic trace-sampling trick, applied to scids.
``0`` disables (the default: zero table growth, one int compare per
item), ``1`` samples everything (tests, smoke drives).

Queue-wait vs service (the batching tax, doc/journeys.md §semantics):
a hop's ``wait_ms`` is time the ITEM spent queued before its batch
dispatched (flush_start − enqueue), ``service_ms`` is the batch's
execution time it shared.  Per-item waits are reconcilable against the
batch-side ``clntpu_journey_batch_wait_seconds_total`` stage counter,
which dispatch sites increment for ALL items (sampled or not) — the
cross-check tools/perf_report.py-style selfchecks and the e2e stitch
test assert within ε.

Deliberately jax-free (the obs-package rule) and lock-cheap: the
unsampled fast path is one cached-int compare; sampled hops take one
short critical section on ``_lock``.
"""
from __future__ import annotations

import collections
import itertools
import os
import threading
import time
import zlib

from . import families as _f

# entity classes (bounded label vocabulary)
KINDS = ("channel", "node", "payment")

# The FIXED hop vocabulary.  Call sites must pass one of these as a
# string literal — the graftlint spans pass checks both the literal-ness
# and the membership (analysis/passes/spans.py), so the set cannot grow
# by interpolation and the per-hop histograms stay bounded.
HOPS = (
    # gossip-message journey (ingest → planes)
    "recv",        # peer bytes reached gossipd
    "admit",       # passed precheck + overload admission, queued
    "shed",        # overload/pending-cap shed (terminal)
    "drop",        # dedup/stale/ratelimit/badsig/utxo drop (terminal)
    "verify",      # signature checked inside a batched verify dispatch
    "store",       # durable gossip_store append (write-ahead fsync)
    "fold",        # folded into the live gossmap arrays
    "planes",      # route-planes parameter patch picked the update up
    "mcf_planes",  # MCF planes refreshed over the update
    # payment journey (xpay → HTLC resolution)
    "enqueue",     # getroutes query entered the mcf flush queue
    "mcf_flush",   # solved inside a batched mcf dispatch
    "parts",       # flow decomposed into MPP parts
    "htlc_add",    # one part's HTLC offered on a channel
    "htlc_part",   # receiver-side MPP accumulator verdict
    "htlc_settle",  # part fulfilled (terminal)
    "htlc_fail",   # part failed (terminal)
)
HOP_SET = frozenset(HOPS)
TERMINAL_HOPS = frozenset(("shed", "drop", "htlc_settle", "htlc_fail"))

# batch-side reconciliation stages (clntpu_journey_batch_wait label set)
STAGES = ("verify", "mcf")

_WINDOW = 256        # per-hop (wait, service) window for p50/p99
_E2E_WINDOW = 512    # rolling end-to-end latencies of finished journeys

_lock = threading.Lock()
_ids = itertools.count(1)            # thread-safe without the lock
# (kind, key) -> journey dict, LRU order    guarded-by: _lock
_table: "collections.OrderedDict[tuple, dict]" = collections.OrderedDict()
_hop_wait: dict[str, collections.deque] = {}      # guarded-by: _lock
_hop_service: dict[str, collections.deque] = {}   # guarded-by: _lock
_e2e_ms: collections.deque = collections.deque(maxlen=_E2E_WINDOW)
                                                  # guarded-by: _lock
_evicted = 0                                      # guarded-by: _lock


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _refresh() -> None:
    """(Re)read the LIGHTNING_TPU_JOURNEY_* knobs.  Called at import
    and from reset_for_tests(); daemons configure via the environment
    at process start."""
    global _SAMPLE, _MAX, _HOPCAP
    _SAMPLE = _env_int("LIGHTNING_TPU_JOURNEY_SAMPLE", 0)
    _MAX = max(1, _env_int("LIGHTNING_TPU_JOURNEY_MAX", 512))
    _HOPCAP = max(1, _env_int("LIGHTNING_TPU_JOURNEY_HOPS", 64))


_refresh()


# -- sampling ---------------------------------------------------------------


def canon_key(kind: str, key) -> object:
    """Canonical table key: int for channels (scid), lowercase hex for
    node ids / payment hashes (bytes accepted)."""
    if kind == "channel":
        return int(key)
    if isinstance(key, (bytes, bytearray, memoryview)):
        return bytes(key).hex()
    return str(key).lower()


def _key_bytes(kind: str, key) -> bytes:
    if kind == "channel":
        return int(key).to_bytes(8, "big", signed=False)
    k = canon_key(kind, key)
    try:
        return bytes.fromhex(k)
    except ValueError:
        return k.encode()


def enabled() -> bool:
    """True when sampling is configured at all — the cheap pre-gate
    dispatch sites consult before doing any per-item bookkeeping."""
    return _SAMPLE > 0


def sampled(kind: str, key) -> bool:
    """Deterministic entity-keyed sampling decision.  Stable across
    threads, processes, and restarts: every hop of a sampled entity is
    recorded with no coordination, and an unsampled entity costs one
    int compare here."""
    n = _SAMPLE
    if n <= 0:
        return False
    if n == 1:
        return True
    h = zlib.crc32(kind.encode() + b"/" + _key_bytes(kind, key))
    return h % n == 0


# -- recording --------------------------------------------------------------


def hop(name: str, kind: str, key, *, outcome: str = "ok",
        wait_s: float = 0.0, service_s: float = 0.0,
        dispatch_id: int | None = None, corr_id: int | None = None,
        t_ns: int | None = None, **attrs) -> bool:
    """Record one hop on an entity's journey (no-op unless sampled).

    ``name`` must be a HOPS literal at the call site (lint-enforced).
    ``wait_s``/``service_s`` split the batching tax per doc/journeys.md;
    ``dispatch_id`` links the hop to the flight-ring record of the
    batch that carried the item, ``corr_id`` to its trace flow chain.
    Returns True when the hop was recorded."""
    if name not in HOP_SET:
        raise ValueError(f"unknown journey hop {name!r}")
    if kind not in KINDS:
        raise ValueError(f"unknown journey kind {kind!r}")
    if not sampled(kind, key):
        return False
    now = time.monotonic_ns() if t_ns is None else int(t_ns)
    rec = {
        "hop": name,
        "t_ns": now,
        "outcome": str(outcome),
        "wait_ms": round(float(wait_s) * 1e3, 3),
        "service_ms": round(float(service_s) * 1e3, 3),
        "dispatch_id": None if dispatch_id is None else int(dispatch_id),
        "corr_id": None if corr_id is None else int(corr_id),
    }
    if attrs:
        rec["attrs"] = {k: v for k, v in attrs.items() if v is not None}
    k = (kind, canon_key(kind, key))
    created = False
    with _lock:
        j = _table.get(k)
        if j is None:
            created = True
            j = {
                "seq": next(_ids),
                "kind": kind,
                "key": k[1],
                "first_ns": now,
                "last_ns": now,
                "done": False,
                "truncated": 0,
                "hops": [],
            }
            _table[k] = j
            global _evicted
            while len(_table) > _MAX:
                _table.popitem(last=False)
                _evicted += 1
        else:
            _table.move_to_end(k)
        if len(j["hops"]) < _HOPCAP:
            j["hops"].append(rec)
        else:
            j["truncated"] += 1
        j["last_ns"] = max(j["last_ns"], now)
        terminal = name in TERMINAL_HOPS
        if terminal:
            j["done"] = True
            _e2e_ms.append((j["last_ns"] - j["first_ns"]) / 1e6)
        w = _hop_wait.get(name)
        if w is None:
            w = _hop_wait[name] = collections.deque(maxlen=_WINDOW)
            _hop_service[name] = collections.deque(maxlen=_WINDOW)
        w.append(rec["wait_ms"])
        _hop_service[name].append(rec["service_ms"])
        table_size = len(_table)
    if created:
        _f.JOURNEY_SAMPLED.labels(kind).inc()
    _f.JOURNEY_TABLE.set(table_size)
    _f.JOURNEY_HOP_WAIT.labels(name).observe(float(wait_s))
    _f.JOURNEY_HOP_SERVICE.labels(name).observe(float(service_s))
    return True


def note_batch_wait(stage: str, wait_s: float) -> None:
    """Batch-side queue-wait accounting, incremented by dispatch sites
    for EVERY item (sampled or not): Σ(flush_start − enqueue) over the
    batch.  The per-item journey waits must reconcile against this
    counter within ε when sampling is 1 — the stitch test's invariant."""
    if stage not in STAGES:
        raise ValueError(f"unknown journey stage {stage!r}")
    _f.JOURNEY_BATCH_WAIT.labels(stage).inc(max(0.0, float(wait_s)))


# -- exposition -------------------------------------------------------------


def _copy(j: dict) -> dict:
    out = dict(j)
    out["hops"] = [dict(h) for h in j["hops"]]
    out["e2e_ms"] = round((j["last_ns"] - j["first_ns"]) / 1e6, 3)
    return out


def lookup(kind: str, key) -> dict | None:
    """One entity's journey (a copy), or None when never sampled."""
    with _lock:
        j = _table.get((kind, canon_key(kind, key)))
        return None if j is None else _copy(j)


def recent(limit: int = 20) -> list[dict]:
    """The most recently touched journeys, newest first (copies)."""
    with _lock:
        js = sorted(_table.values(), key=lambda j: j["last_ns"],
                    reverse=True)
        if limit is not None and limit > 0:
            js = js[:limit]
        return [_copy(j) for j in js]


def _quantile(vals: list[float], q: float) -> float | None:
    if not vals:
        return None
    s = sorted(vals)
    return s[min(len(s) - 1, int(q * len(s)))]


def e2e_p99_ms() -> float | None:
    """Rolling p99 of finished journeys' end-to-end latency (the
    obs_snapshot --watch SLOW JOURNEY threshold)."""
    with _lock:
        return _quantile(list(_e2e_ms), 0.99)


def summary() -> dict:
    """The journeys section of getjourney / obs snapshots: sampling
    config, table occupancy, per-hop queue-vs-service quantiles, the
    rolling e2e tail, and the slowest finished journey."""
    with _lock:
        by_hop = {}
        for name, w in _hop_wait.items():
            sv = list(_hop_service[name])
            wv = list(w)
            by_hop[name] = {
                "count": len(wv),
                "wait_ms_p50": _quantile(wv, 0.50),
                "wait_ms_p99": _quantile(wv, 0.99),
                "service_ms_p50": _quantile(sv, 0.50),
                "service_ms_p99": _quantile(sv, 0.99),
            }
        slowest = None
        for j in _table.values():
            if not j["done"]:
                continue
            if slowest is None or (j["last_ns"] - j["first_ns"]) > (
                    slowest["last_ns"] - slowest["first_ns"]):
                slowest = j
        e2e = list(_e2e_ms)
        return {
            "enabled": _SAMPLE > 0,
            "sample": _SAMPLE,
            "max_entities": _MAX,
            "entities": len(_table),
            "finished": sum(1 for j in _table.values() if j["done"]),
            "evicted": _evicted,
            "by_hop": by_hop,
            "e2e_ms_p50": _quantile(e2e, 0.50),
            "e2e_ms_p99": _quantile(e2e, 0.99),
            "slowest": None if slowest is None else _copy(slowest),
        }


# Chrome-trace splice: journey hops render as X slices on synthetic
# per-journey tracks (tid base 1 << 29, below the flight-ring band at
# 1 << 30) whose corr_ids hook them into the existing flow-arrow
# chains — obs/traceexport.chrome_trace treats these exactly like live
# span records (doc/journeys.md §perfetto).
JOURNEY_TID_BASE = 1 << 29


def journey_span_records(limit: int | None = None) -> list[dict]:
    """Span-record-shaped dicts (one per hop) for chrome_trace():
    every field trace.py spans carry that the exporter reads — name,
    start/duration, a synthetic per-journey tid, span_id (flow sort
    key), and the hop's corr_id for flow splicing."""
    out = []
    for j in recent(limit=limit or 0):
        tid = JOURNEY_TID_BASE + j["seq"]
        for i, h in enumerate(j["hops"]):
            busy_ns = int((h["wait_ms"] + h["service_ms"]) * 1e6)
            out.append({
                "name": "journey/" + h["hop"],
                "start_ns": h["t_ns"] - max(busy_ns, 1_000),
                "duration_ns": max(busy_ns, 1_000),
                "tid": tid,
                "thread": "journey:" + j["kind"],
                "span_id": -(j["seq"] * 1_000 + i),
                "corr_ids": ([h["corr_id"]]
                             if h["corr_id"] is not None else []),
                "attributes": {
                    "kind": j["kind"], "key": str(j["key"]),
                    "outcome": h["outcome"],
                    "dispatch_id": h["dispatch_id"],
                },
            })
    return out


def reset_for_tests() -> None:
    global _evicted
    with _lock:
        _table.clear()
        _hop_wait.clear()
        _hop_service.clear()
        _e2e_ms.clear()
        _evicted = 0
    _refresh()
