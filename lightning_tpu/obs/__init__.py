"""Unified observability: metrics registry + silo collector.

One process-wide default registry; hot-path modules create their
instruments at import time and mutate them lock-cheaply:

    from lightning_tpu import obs
    _FLUSHES = obs.counter("clntpu_gossip_flushes_total", "...")
    _FLUSHES.inc()

Exposition (all three read the same registry):
  * ``getmetrics`` JSON-RPC command (daemon/jsonrpc.py);
  * Prometheus text at ``GET /metrics`` on the REST server;
  * ``tools/obs_snapshot.py`` capture/diff CLI (benches).

``ensure_installed()`` attaches the trace/events/logring collector;
it is idempotent and safe to call from every exposition path (tests
call ``events.reset()``, which would otherwise silently detach the
events tap).
"""
from __future__ import annotations

from .collector import Collector
from .registry import (DURATION_BUCKETS, OVERFLOW_LABEL, RATIO_BUCKETS,
                       SIZE_BUCKETS, Registry, log2_buckets)

REGISTRY = Registry()
_collector = Collector(REGISTRY)


def counter(name: str, help: str = "", labelnames=(), **kw):
    return REGISTRY.counter(name, help, labelnames, **kw)


def gauge(name: str, help: str = "", labelnames=(), **kw):
    return REGISTRY.gauge(name, help, labelnames, **kw)


def histogram(name: str, help: str = "", labelnames=(),
              buckets=DURATION_BUCKETS, **kw):
    return REGISTRY.histogram(name, help, labelnames, buckets=buckets, **kw)


def ensure_installed(ring=None) -> None:
    """Attach (or re-attach) the span/events/logring collector."""
    _collector.install(ring=ring)


def snapshot() -> dict:
    ensure_installed()
    return REGISTRY.snapshot()


def render_prometheus() -> str:
    ensure_installed()
    return REGISTRY.render_prometheus()


def reset_for_tests() -> None:
    """Drop every family and re-create the collector's own metrics.
    Instruments held by other modules at import time keep working but
    become invisible until re-registered — tests that assert on them
    should re-import or use fresh registries instead."""
    global _collector
    _collector.uninstall()
    REGISTRY.reset()
    _collector = Collector(REGISTRY)
