"""Well-known instrument families whose hot-path owners are heavyweight
imports.

`routing/device.py` and `daemon/hsmd.py` pull in the full jax/crypto
stack at import time; declaring their metric families HERE (the obs
package imports nothing heavy) lets lightweight consumers — the
`tools/obs_snapshot.py` capture CLI, exposition-only processes — make
the series present-at-zero without paying those imports.  The registry
re-registers same-name families to the same object, so owner modules
import these instruments rather than re-declaring them.
"""
from __future__ import annotations

from . import registry as _r
from .registry import SIZE_BUCKETS

from . import REGISTRY

RATIO_BUCKETS = _r.RATIO_BUCKETS
DURATION_BUCKETS = _r.DURATION_BUCKETS

# -- routing/device.py: the batched route solver (doc/routing.md) ----------
ROUTE_FLUSH_SECONDS = REGISTRY.histogram(
    "clntpu_route_flush_seconds",
    "End-to-end wall time of one route flush (plane refresh + solve + "
    "reconstruct, device and host paths together)",
    buckets=DURATION_BUCKETS)
ROUTE_BATCH_QUERIES = REGISTRY.histogram(
    "clntpu_route_batch_queries",
    "Route queries coalesced per flush", buckets=SIZE_BUCKETS)
ROUTE_OCCUPANCY = REGISTRY.histogram(
    "clntpu_route_batch_occupancy_ratio",
    "Real queries / padded device lanes per dispatch",
    buckets=RATIO_BUCKETS)
ROUTE_QUERIES = REGISTRY.counter(
    "clntpu_route_queries_total",
    "Route queries solved, by execution path and outcome",
    labelnames=("path", "outcome"))
ROUTE_FALLBACK = REGISTRY.counter(
    "clntpu_route_fallback_total",
    "Queries diverted from the device solver to host dijkstra, by reason",
    labelnames=("reason",))
ROUTE_QUEUE = REGISTRY.gauge(
    "clntpu_route_queue_queries",
    "Route queries currently queued awaiting a flush")

# -- daemon/hsmd.py: the batched-sign paths --------------------------------
SIGN_BATCH_SIGS = REGISTRY.histogram(
    "clntpu_sign_batch_sigs",
    "Signatures per hsmd batched-sign call, by operation",
    labelnames=("op",), buckets=SIZE_BUCKETS)
SIGN_CALLS = REGISTRY.counter(
    "clntpu_sign_total",
    "hsmd batched-sign calls, by operation and host/device path",
    labelnames=("op", "path"))
