"""Well-known instrument families whose hot-path owners are heavyweight
imports.

`routing/device.py` and `daemon/hsmd.py` pull in the full jax/crypto
stack at import time; declaring their metric families HERE (the obs
package imports nothing heavy) lets lightweight consumers — the
`tools/obs_snapshot.py` capture CLI, exposition-only processes — make
the series present-at-zero without paying those imports.  The registry
re-registers same-name families to the same object, so owner modules
import these instruments rather than re-declaring them.
"""
from __future__ import annotations

from . import registry as _r
from .registry import SIZE_BUCKETS

from . import REGISTRY

RATIO_BUCKETS = _r.RATIO_BUCKETS
DURATION_BUCKETS = _r.DURATION_BUCKETS

# -- routing/device.py: the batched route solver (doc/routing.md) ----------
ROUTE_FLUSH_SECONDS = REGISTRY.histogram(
    "clntpu_route_flush_seconds",
    "End-to-end wall time of one route flush (plane refresh + solve + "
    "reconstruct, device and host paths together)",
    buckets=DURATION_BUCKETS)
ROUTE_BATCH_QUERIES = REGISTRY.histogram(
    "clntpu_route_batch_queries",
    "Route queries coalesced per flush", buckets=SIZE_BUCKETS)
ROUTE_OCCUPANCY = REGISTRY.histogram(
    "clntpu_route_batch_occupancy_ratio",
    "Real queries / padded device lanes per dispatch",
    buckets=RATIO_BUCKETS)
ROUTE_QUERIES = REGISTRY.counter(
    "clntpu_route_queries_total",
    "Route queries solved, by execution path and outcome",
    labelnames=("path", "outcome"))
ROUTE_FALLBACK = REGISTRY.counter(
    "clntpu_route_fallback_total",
    "Queries diverted from the device solver to host dijkstra, by reason",
    labelnames=("reason",))
ROUTE_QUEUE = REGISTRY.gauge(
    "clntpu_route_queue_queries",
    "Route queries currently queued awaiting a flush")
# owner: daemon/jsonrpc.py's getroute command.  ANSWERED queries only
# (ok or no-route) — TRY_AGAIN admission rejections are excluded, so
# this is the same population tools/loadgen.py's post-hoc p99 and the
# health engine's route_p99 SLO judge (clntpu_rpc_latency_seconds
# counts every call, and under storm the fast 429s would drag the
# tail estimate down exactly when it matters).
ROUTE_ANSWER_SECONDS = REGISTRY.histogram(
    "clntpu_route_answer_seconds",
    "getroute RPC latency for answered queries (ok or no-route; "
    "TRY_AGAIN rejections excluded)",
    buckets=DURATION_BUCKETS)

# -- routing/mcf_device.py: the batched min-cost-flow payment engine -------
# (doc/routing.md §MCF/MPP; the askrene-parity MPP solver's dispatch
# family — declared here so jax-free consumers see the series at zero.)
MCF_FLUSH_SECONDS = REGISTRY.histogram(
    "clntpu_mcf_flush_seconds",
    "End-to-end wall time of one mcf flush (lane prep + batched solve + "
    "flow decomposition, device and host paths together)",
    buckets=DURATION_BUCKETS)
MCF_BATCH_QUERIES = REGISTRY.histogram(
    "clntpu_mcf_batch_queries",
    "getroutes/xpay queries coalesced per mcf flush", buckets=SIZE_BUCKETS)
MCF_OCCUPANCY = REGISTRY.histogram(
    "clntpu_mcf_batch_occupancy_ratio",
    "Real mcf queries / padded device lanes per dispatch",
    buckets=RATIO_BUCKETS)
MCF_QUERIES = REGISTRY.counter(
    "clntpu_mcf_queries_total",
    "Min-cost-flow queries solved, by execution path and outcome",
    labelnames=("path", "outcome"))
MCF_FALLBACK = REGISTRY.counter(
    "clntpu_mcf_fallback_total",
    "Queries diverted from the device mcf solver to the host oracle, "
    "by reason",
    labelnames=("reason",))
MCF_QUEUE = REGISTRY.gauge(
    "clntpu_mcf_queue_queries",
    "Min-cost-flow queries currently queued awaiting a flush")
MCF_PARTS = REGISTRY.histogram(
    "clntpu_mcf_parts_per_query",
    "Route parts per successfully solved mcf query (MPP split width)",
    buckets=SIZE_BUCKETS)

# -- daemon/hsmd.py: the batched-sign paths --------------------------------
SIGN_BATCH_SIGS = REGISTRY.histogram(
    "clntpu_sign_batch_sigs",
    "Signatures per hsmd batched-sign call, by operation",
    labelnames=("op",), buckets=SIZE_BUCKETS)
SIGN_CALLS = REGISTRY.counter(
    "clntpu_sign_total",
    "hsmd batched-sign calls, by operation and host/device path",
    labelnames=("op", "path"))

# -- resilience/: the device-path supervision layer (doc/resilience.md) ----
BREAKER_STATE = REGISTRY.gauge(
    "clntpu_breaker_state",
    "Circuit-breaker state per dispatch family "
    "(0 = closed, 1 = open, 2 = half-open)",
    labelnames=("family",))
BREAKER_TRANSITIONS = REGISTRY.counter(
    "clntpu_breaker_transitions_total",
    "Circuit-breaker state transitions, by family and target state",
    labelnames=("family", "to"))
BREAKER_FAILURES = REGISTRY.counter(
    "clntpu_breaker_failures_total",
    "Device dispatch failures recorded against a breaker",
    labelnames=("family",))
BREAKER_SHORT_CIRCUITS = REGISTRY.counter(
    "clntpu_breaker_short_circuits_total",
    "Dispatches diverted to the host fallback because the breaker was "
    "open (or a half-open probe was already in flight)",
    labelnames=("family",))
QUARANTINE = REGISTRY.counter(
    "clntpu_quarantine_total",
    "Rows diverted off a failing device dispatch (bisect-isolated or "
    "readback-lost) and re-checked host-side, by family and reason",
    labelnames=("family", "reason"))
FAULT_INJECTED = REGISTRY.counter(
    "clntpu_fault_injected_total",
    "Faults fired by the LIGHTNING_TPU_FAULT injection harness",
    labelnames=("seam", "family", "action"))
DEADLINE_EXCEEDED = REGISTRY.counter(
    "clntpu_deadline_exceeded_total",
    "Dispatch deadlines blown (a hung/slow worker surfaced instead of a "
    "silent stall), by family and seam",
    labelnames=("family", "seam"))
LOOP_RESTARTS = REGISTRY.counter(
    "clntpu_loop_restarts_total",
    "Supervised flush/producer loop restarts after an escaped exception",
    labelnames=("loop",))
INGEST_FLUSH_ERRORS = REGISTRY.counter(
    "clntpu_ingest_flush_errors_total",
    "GossipIngest flush-loop iterations that raised (the loop restarts "
    "with backoff instead of dying silently)")

# -- resilience/overload.py: overload control (doc/overload.md) ------------
SHED = REGISTRY.counter(
    "clntpu_shed_total",
    "Messages/queries shed by the overload controller, by family, "
    "priority class, and reason (every shed is also recorded in the "
    "shed ring — never silently dropped)",
    labelnames=("family", "priority", "reason"))
OVERLOAD_STATE = REGISTRY.gauge(
    "clntpu_overload_state",
    "Degradation-ladder state per dispatch family "
    "(0 = normal, 1 = elevated, 2 = saturated)",
    labelnames=("family",))
OVERLOAD_TRANSITIONS = REGISTRY.counter(
    "clntpu_overload_transitions_total",
    "Degradation-ladder transitions, by family and target state",
    labelnames=("family", "to"))
BACKPRESSURE_WAITS = REGISTRY.counter(
    "clntpu_backpressure_waits_total",
    "Transport read pauses taken because the family was saturated "
    "(one per paused message, bounded per wait)",
    labelnames=("family",))
BACKPRESSURE_WAIT_SECONDS = REGISTRY.histogram(
    "clntpu_backpressure_wait_seconds",
    "Seconds a saturated family paused one transport read",
    labelnames=("family",), buckets=DURATION_BUCKETS)
INGEST_BACKLOG = REGISTRY.gauge(
    "clntpu_ingest_backlog_sigs",
    "Total unverified ingest backlog: queued signatures plus the "
    "in-flight flush batch (the queue gauge counts only queued)")

# -- gossip/verify.py: streaming-replay pipeline stages --------------------
# (doc/replay_pipeline.md owns the timing vocabulary; declared here so
# jax-free consumers — tools/obs_snapshot.py capture, the attribution
# model in obs/attribution.py, perf_report --selfcheck — see the series
# present-at-zero and can drive them synthetically without the crypto
# stack.)  "prep" is host bucket build (slice + pack + pad), "stall" is
# the slice of prep VISIBLE on the dispatch thread's critical path,
# "dispatch" is upload + program enqueue, "readback" is the single
# end-of-replay block on the device booleans.
REPLAY_PREP = REGISTRY.counter(
    "clntpu_replay_prep_seconds_total",
    "Host bucket-prep busy time (slice + pack + pad), all buckets")
REPLAY_STALL = REGISTRY.counter(
    "clntpu_replay_prep_stall_seconds_total",
    "Prep time visible on the dispatch critical path (queue-empty waits; "
    "== prep time when the pipeline is serial/depth 0)")
REPLAY_DISPATCH = REGISTRY.counter(
    "clntpu_replay_dispatch_seconds_total",
    "Dispatch-thread time spent uploading + enqueueing bucket programs")
REPLAY_READBACK = REGISTRY.counter(
    "clntpu_replay_readback_seconds_total",
    "Time blocked on the single end-of-replay device readback")
REPLAY_OVERLAP = REGISTRY.histogram(
    "clntpu_replay_overlap_ratio",
    "Per-replay fraction of host prep hidden behind device compute "
    "(1 - stall/prep; serial pipelines observe 0)",
    buckets=RATIO_BUCKETS)
REPLAY_QDEPTH = REGISTRY.histogram(
    "clntpu_replay_queue_depth",
    "Prepared-bucket queue depth sampled at each dispatch",
    buckets=_r.log2_buckets(1.0, 16.0))
REPLAY_BUCKETS = REGISTRY.counter(
    "clntpu_replay_buckets_total",
    "Fused bucket dispatches, by device path",
    labelnames=("path",))

# -- obs/attribution.py: the perf observatory (doc/perf.md) ----------------
TRANSFER_BYTES = REGISTRY.counter(
    "clntpu_transfer_bytes_total",
    "Host<->device bytes staged for batched dispatches, by family and "
    "direction (h2d = operand upload, d2h = result readback; "
    "operand-size accounting, not a PCIe counter)",
    labelnames=("family", "direction"))
RETRACE = REGISTRY.counter(
    "clntpu_retrace_total",
    "Program-shape compile first-sights AFTER warmup() completed — "
    "every increment is an anomaly (a live dispatch paid a compile the "
    "warmup contract promises it never does), by program",
    labelnames=("program",))
DEVICE_MEMORY = REGISTRY.gauge(
    "clntpu_device_memory_bytes",
    "Live device-memory statistics where the backend exposes "
    "memory_stats() (TPU does; CPU reports nothing), by device and stat",
    labelnames=("device", "stat"))

# -- obs/health.py: the always-on health engine (doc/health.md) ------------
HEALTH_STATE = REGISTRY.gauge(
    "clntpu_health_state",
    "Rolled-up daemon health from the continuous SLO evaluator "
    "(0 = healthy, 1 = degraded, 2 = unhealthy)")
SLO_BREACH = REGISTRY.counter(
    "clntpu_slo_breach_total",
    "SLO breach ENTRIES recorded by the health engine (one increment "
    "per transition into breach, not per breached tick), by SLO name",
    labelnames=("slo",))

# -- obs/incident.py: the black-box flight recorder (doc/incidents.md) -----
INCIDENTS = REGISTRY.counter(
    "clntpu_incidents_total",
    "Incident bundles frozen to disk by the black-box recorder, by the "
    "trigger class that names the bundle (escalations re-count under "
    "the new class)",
    labelnames=("trigger",))
INCIDENT_TRIGGERS = REGISTRY.counter(
    "clntpu_incident_triggers_total",
    "Incident triggers observed, by class and what the episode "
    "debouncer did with them (capture = opened a bundle, escalate = "
    "re-froze the open bundle under a higher-severity class, absorb = "
    "suppressed inside the cooldown window)",
    labelnames=("trigger", "action"))
INCIDENT_BYTES = REGISTRY.gauge(
    "clntpu_incident_store_bytes",
    "Total bytes of incident bundles on disk (bounded by "
    "LIGHTNING_TPU_INCIDENT_MAX_BYTES with oldest-first rotation)")

# -- daemon/recovery.py + gossip/store.py: crash-consistent restart --------
# (doc/recovery.md owns the semantics: the clean-shutdown marker, the
# torn-tail truncation rules, and the boot reconciliation sweep.)
RECOVERY_BOOTS = REGISTRY.counter(
    "clntpu_recovery_boots_total",
    "Daemon boots by what the clean-shutdown marker said about the "
    "previous run (first_boot = no marker, clean = orderly shutdown, "
    "crash = the marker still said running)",
    labelnames=("state",))
RECOVERY_STORE_ROWS = REGISTRY.counter(
    "clntpu_recovery_store_rows_total",
    "Store records handled by the recovery scan, by action "
    "(requalified = crc-bad but host re-check passed, dropped = "
    "crc-bad and failed the re-check, flagged deleted)",
    labelnames=("action",))
RECOVERY_STORE_TRUNCATED_BYTES = REGISTRY.counter(
    "clntpu_recovery_store_truncated_bytes_total",
    "Torn-tail bytes truncated off the gossip store at recovery "
    "(a crash mid-append leaves at most one partial record at EOF)")
RECOVERY_DB_FIXUPS = REGISTRY.counter(
    "clntpu_recovery_db_fixups_total",
    "Rows fixed by the boot db reconciliation sweep, by kind "
    "(payment_failed = pending payment older than the crash marked "
    "retryable-failed, retransmit_reset / inflight_reset = journal "
    "blob invalid against channel state, replica_dropped = hook "
    "replica was ahead by one and its tail record was dropped)",
    labelnames=("kind",))
RECOVERY_INCIDENTS_FOUND = REGISTRY.counter(
    "clntpu_recovery_incidents_found_total",
    "Incident bundles from the previous (crashed) run discovered and "
    "logged during boot recovery")
RECOVERY_SECONDS = REGISTRY.histogram(
    "clntpu_recovery_seconds",
    "Wall time of the whole boot recovery phase (marker check + store "
    "scan + optional verify replay + db reconciliation)",
    buckets=DURATION_BUCKETS)

# -- obs/flight.py: the dispatch flight recorder (doc/tracing.md) ----------
DISPATCHES = REGISTRY.counter(
    "clntpu_dispatches_total",
    "Flight-recorded dispatches, by family and outcome (the aggregate "
    "view of the listdispatches ring)",
    labelnames=("family", "outcome"))
SLOW_DISPATCH = REGISTRY.counter(
    "clntpu_slow_dispatch_total",
    "Dispatches flagged by the slow-dispatch watchdog (over "
    "LIGHTNING_TPU_SLOW_DISPATCH_S, or the rolling per-family p99)",
    labelnames=("family",))

# -- obs/journey.py: per-item journeys (doc/journeys.md) -------------------
JOURNEY_SAMPLED = REGISTRY.counter(
    "clntpu_journey_sampled_total",
    "Entities admitted to the journey table by the deterministic "
    "sampler (one per entity, not per hop), by entity kind",
    labelnames=("kind",))
JOURNEY_TABLE = REGISTRY.gauge(
    "clntpu_journey_table_size",
    "Journeys currently held in the bounded per-entity table "
    "(LRU-rotated at LIGHTNING_TPU_JOURNEY_MAX)")
JOURNEY_HOP_WAIT = REGISTRY.histogram(
    "clntpu_journey_hop_wait_seconds",
    "Per-ITEM queue-induced wait at each journey hop (time the item "
    "sat queued before its batch dispatched — the batching tax, split "
    "from service time per doc/journeys.md)",
    labelnames=("hop",), buckets=DURATION_BUCKETS)
JOURNEY_HOP_SERVICE = REGISTRY.histogram(
    "clntpu_journey_hop_service_seconds",
    "Per-ITEM service time at each journey hop (the batch execution "
    "the item shared, split from queue wait per doc/journeys.md)",
    labelnames=("hop",), buckets=DURATION_BUCKETS)
JOURNEY_BATCH_WAIT = REGISTRY.counter(
    "clntpu_journey_batch_wait_seconds_total",
    "Batch-side queue-wait accounting by pipeline stage: "
    "Σ(flush_start − enqueue) over EVERY item of every batch, sampled "
    "or not — the reconciliation target the summed per-item journey "
    "waits must match within ε when sampling is 1",
    labelnames=("stage",))
