"""Perf observatory: automated stage attribution for the batched
dispatch pipelines (doc/perf.md).

ROADMAP open item #1 names a 4.4x kernel-vs-e2e gap and asks for the
gap to be ATTRIBUTED across queue-wait / prep / dispatch / readback per
dispatch.  PRs 1 and 5 built the raw instruments — the clntpu_replay_*
stage counters and the per-dispatch flight rings (obs/flight.py) — but
nothing consumed them.  This module is the consumer: a critical-path
pipeline model that turns those numbers into, per dispatch family,

  * the stage breakdown (queue_wait / prep / stall / dispatch /
    readback seconds) and which stages sit ON the critical path;
  * overlap efficiency (how much host prep the producer pipeline
    actually hid behind device compute);
  * the named bottleneck stage and a speedup-if-removed projection
    for every critical stage (Amdahl over the critical path);
  * achieved throughput vs a measured kernel roofline — the exact
    "where did the 4.4x go" report.

Consumers: the ``getperf`` RPC and the ``perf`` section of
``getmetrics`` (daemon/jsonrpc.py), tools/perf_report.py (live over
RPC, offline over a saved obs_snapshot capture, and a synthetic
``--selfcheck``), and tools/obs_snapshot.py diffs.

Also here (it is the runtime twin of graftlint's static jit-hygiene
pass): the post-warmup RETRACE DETECTOR.  warmup() functions wrap
their bodies in ``warmup_scope()``; once any warmup has completed, a
program-shape first-sight reported via ``note_program()`` is an
anomaly — the live path paid a compile warmup promised it never would
— and fires ``clntpu_retrace_total{program}`` plus a ``retrace``
events-bus topic with the offending (program, shape).

Deliberately jax-free (the obs-package rule): the model runs in
exposition-only processes and perf_report --selfcheck without paying
the crypto-stack import.  ``sample_device_memory()`` reads jax device
memory stats ONLY when jax is already loaded in the process
(sys.modules peek — importing jax here could hang a tool process on
the accelerator probe).
"""
from __future__ import annotations

import logging
import sys
import threading
import time
from contextlib import contextmanager

from ..utils import events
from . import families as _f

log = logging.getLogger("lightning_tpu.obs.attribution")

# the five-stage vocabulary (doc/perf.md; matches the flight-record
# fields and the clntpu_replay_* counter family)
STAGES = ("queue_wait", "prep", "stall", "dispatch", "readback")

# reconciliation tolerance: ring sums and the clntpu_replay_* counters
# measure the same quantities through different code paths; relative
# disagreement beyond this is unattributed wall time and the report
# says so instead of papering over it.  Disagreement under ABS_FLOOR_S
# per dispatch is timer placement overhead (the counter's stopwatch
# wraps the record's) and never counts against the epsilon — without
# the floor, a µs-scale stub workload reads as 80% "unattributed".
EPSILON = 0.05
ABS_FLOOR_S = 1e-3

_RETRACE_RING = 64

_lock = threading.Lock()
_seen: set = set()           # guarded-by: _lock
_warmup_depth = 0            # guarded-by: _lock
_armed = False                # guarded-by: _lock
_retraces: list = []          # guarded-by: _lock
_retrace_count = 0            # guarded-by: _lock (monotonic; the ring
#                               above keeps only the recent 64)


# ---------------------------------------------------------------------------
# The retrace detector


def note_program(program: str, key=()) -> bool:
    """Record a program-shape first-sight.  Call from every jit
    dispatch site (gossip/verify._note_shape, routing/device's route
    program) with the program name and its static shape key.  Returns
    True when the sighting fired the retrace anomaly: first sight of
    this (program, key), outside any warmup_scope, after at least one
    warmup completed."""
    global _retrace_count
    k = (str(program), tuple(key) if isinstance(key, (list, tuple))
         else (key,))
    with _lock:
        if k in _seen:
            return False
        _seen.add(k)
        fire = _armed and _warmup_depth == 0
        if fire:
            ev = {"program": k[0], "key": list(k[1]),
                  "ts": round(time.time(), 3)}
            _retraces.append(ev)
            del _retraces[:-_RETRACE_RING]
            _retrace_count += 1
    if fire:
        _f.RETRACE.labels(k[0]).inc()
        log.warning(
            "post-warmup retrace: program %r compiled a new shape %r "
            "on the live path — warmup() coverage is incomplete "
            "(doc/perf.md)", k[0], k[1])
        events.emit("retrace", ev)
    return fire


@contextmanager
def warmup_scope():
    """Bracket a warmup body: first-sights inside the scope are
    expected (they ARE the warmup) and never fire the anomaly; the
    first scope to EXIT arms the detector for the rest of the process
    lifetime.  Re-entrant and thread-safe (RouteService.warmup runs
    in a worker thread while verify.warmup may already have run)."""
    global _warmup_depth, _armed
    with _lock:
        _warmup_depth += 1
    try:
        yield
    finally:
        with _lock:
            _warmup_depth -= 1
            _armed = True


def retrace_state() -> dict:
    """The ``retraces`` section of the perf report.  ``total`` is the
    monotonic lifetime count (it must agree with clntpu_retrace_total);
    ``recent`` is the bounded ring of the last few events."""
    with _lock:
        return {"armed": _armed, "in_warmup": _warmup_depth > 0,
                "known_programs": len(_seen), "total": _retrace_count,
                "recent": [dict(r) for r in _retraces]}


# ---------------------------------------------------------------------------
# Device memory


def sample_device_memory() -> dict:
    """Per-device memory stats where the backend exposes them, set on
    the clntpu_device_memory_bytes gauge and returned as a dict.
    Samples ONLY when jax is already imported in this process — a
    jax-free tool process must never trigger the accelerator probe
    just to report memory it cannot have."""
    jax = sys.modules.get("jax")
    if jax is None:
        return {}
    try:
        devices = jax.devices()
    except Exception:
        return {}
    out: dict = {}
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        dev = f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', 0)}"
        stats = {}
        for stat in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                     "bytes_reserved"):
            v = ms.get(stat)
            if v is not None:
                stats[stat] = int(v)
                _f.DEVICE_MEMORY.labels(dev, stat).set(float(v))
        if stats:
            out[dev] = stats
    return out


# ---------------------------------------------------------------------------
# The critical-path pipeline model


def _ring_sums(records: list[dict]) -> dict:
    """Per-stage second totals (and byte/item tallies) over a list of
    flight DispatchRecords."""
    out = {"queue_wait_s": 0.0, "prep_s": 0.0, "dispatch_s": 0.0,
           "readback_s": 0.0, "h2d_bytes": 0, "d2h_bytes": 0,
           "items": 0, "lanes": 0, "quarantined": 0}
    first_ns = last_ns = None
    outcomes: dict = {}
    for r in records:
        out["queue_wait_s"] += (r.get("queue_wait_ms") or 0.0) / 1e3
        out["prep_s"] += (r.get("prep_ms") or 0.0) / 1e3
        out["dispatch_s"] += (r.get("dispatch_ms") or 0.0) / 1e3
        out["readback_s"] += (r.get("readback_ms") or 0.0) / 1e3
        out["h2d_bytes"] += int(r.get("h2d_bytes") or 0)
        out["d2h_bytes"] += int(r.get("d2h_bytes") or 0)
        out["items"] += int(r.get("n_real") or 0)
        out["lanes"] += int(r.get("lanes") or 0)
        out["quarantined"] += int(r.get("quarantined") or 0)
        oc = r.get("outcome") or "?"
        outcomes[oc] = outcomes.get(oc, 0) + 1
        ns = r.get("ts_ns")
        if ns is not None:
            first_ns = ns if first_ns is None else min(first_ns, ns)
            last_ns = ns if last_ns is None else max(last_ns, ns)
    out["outcomes"] = outcomes
    if first_ns is not None and last_ns is not None and records:
        # span start -> last record start + its own duration.  prep is
        # included so a serial family's span is never SMALLER than its
        # critical path (an internally inconsistent report); for the
        # overlapped replay this overstates by at most the last
        # bucket's hidden prep — bounded, and errs toward reporting
        # idle time rather than hiding it.
        last = max(records, key=lambda r: r.get("ts_ns") or 0)
        tail_s = ((last.get("queue_wait_ms") or 0.0)
                  + (last.get("prep_ms") or 0.0)
                  + (last.get("dispatch_ms") or 0.0)
                  + (last.get("readback_ms") or 0.0)) / 1e3
        out["wall_span_s"] = (last_ns - first_ns) / 1e9 + tail_s
    else:
        out["wall_span_s"] = 0.0
    return out


def _speedup(critical_s: float, stage_s: float) -> float | None:
    """Amdahl over the critical path: end-to-end speedup if this stage
    cost nothing (None when the stage IS the whole path)."""
    if critical_s <= 0 or stage_s <= 0:
        return 1.0
    rest = critical_s - stage_s
    if rest <= 0:
        return None
    return round(critical_s / rest, 4)


def attribute_family(family: str, records: list[dict], *,
                     stage_totals_s: dict | None = None,
                     ring_complete: bool = True,
                     kernel_rate: float | None = None,
                     epsilon: float = EPSILON) -> dict:
    """Attribute one dispatch family's wall time across the pipeline
    stages and name the bottleneck.

    ``records`` are the family's flight DispatchRecords (ring order).
    ``stage_totals_s`` — when given (the verify family passes the
    clntpu_replay_* counter totals: keys prep/stall/dispatch/readback)
    — is the authoritative OVERLAPPED-pipeline timing source: prep runs
    on a producer thread and only its ``stall`` share is visible on the
    critical path, so critical = stall + dispatch + readback.  Without
    it the family is modeled serial (route flushes, sign batches):
    every stage is on the critical path and critical = queue_wait +
    prep + dispatch + readback.

    Returns the per-family report section (doc/perf.md for the shape):
    stages, critical-path membership, overlap ratio, bottleneck,
    per-stage speedup-if-removed, throughput, transfer rates, an
    optional roofline comparison, and — when both sources cover the
    same dispatches (``ring_complete``) — a reconciliation block
    asserting the two agree within ``epsilon``."""
    ring = _ring_sums(records)
    overlapped = stage_totals_s is not None
    if overlapped:
        stages = {
            "queue_wait_s": round(ring["queue_wait_s"], 6),
            "prep_s": round(stage_totals_s.get("prep", 0.0), 6),
            "stall_s": round(stage_totals_s.get("stall", 0.0), 6),
            "dispatch_s": round(stage_totals_s.get("dispatch", 0.0), 6),
            "readback_s": round(stage_totals_s.get("readback", 0.0), 6),
        }
        critical = {"stall": stages["stall_s"],
                    "dispatch": stages["dispatch_s"],
                    "readback": stages["readback_s"]}
        prep = stages["prep_s"]
        overlap = (max(0.0, 1.0 - stages["stall_s"] / prep)
                   if prep > 0 else None)
    else:
        stages = {
            "queue_wait_s": round(ring["queue_wait_s"], 6),
            "prep_s": round(ring["prep_s"], 6),
            "stall_s": round(ring["prep_s"], 6),  # serial: all visible
            "dispatch_s": round(ring["dispatch_s"], 6),
            "readback_s": round(ring["readback_s"], 6),
        }
        critical = {"queue_wait": stages["queue_wait_s"],
                    "prep": stages["prep_s"],
                    "dispatch": stages["dispatch_s"],
                    "readback": stages["readback_s"]}
        overlap = 0.0 if stages["prep_s"] > 0 else None
    critical_s = sum(critical.values())
    bottleneck = (max(critical, key=lambda s: critical[s])
                  if critical_s > 0 else None)
    # Rates divide RING-scoped items/bytes, so they must divide by
    # RING-scoped seconds too: the stage counters are process-lifetime
    # while the ring is bounded, and mixing the two understates every
    # rate by (lifetime/ring) once the ring wraps.  The ring's stall
    # share is the recorded queue waits — or inline prep when the
    # replay ran serial (depth 0 records no queue waits at all).
    if overlapped:
        stall_ring = ring["queue_wait_s"] or ring["prep_s"]
        window_s = stall_ring + ring["dispatch_s"] + ring["readback_s"]
    else:
        window_s = critical_s
    section = {
        "family": family,
        "dispatches": len(records),
        "items": ring["items"],
        "lanes": ring["lanes"],
        "occupancy": (round(ring["items"] / ring["lanes"], 4)
                      if ring["lanes"] else None),
        "outcomes": ring["outcomes"],
        "quarantined": ring["quarantined"],
        "pipeline": "overlapped" if overlapped else "serial",
        "stages": stages,
        "critical_path": sorted(critical),
        "critical_path_s": round(critical_s, 6),
        "window_s": round(window_s, 6),
        "hidden_prep_s": round(max(0.0, stages["prep_s"]
                                   - stages["stall_s"]), 6),
        "overlap_ratio": (round(overlap, 4)
                          if overlap is not None else None),
        "wall_span_s": round(ring["wall_span_s"], 6),
        "idle_s": round(max(0.0, ring["wall_span_s"] - critical_s), 6),
        "bottleneck": bottleneck,
        "speedup_if_removed": {s: _speedup(critical_s, v)
                               for s, v in critical.items()},
        "transfer": {
            "h2d_bytes": ring["h2d_bytes"],
            "d2h_bytes": ring["d2h_bytes"],
            "h2d_bytes_per_s": (round(ring["h2d_bytes"] / window_s, 1)
                                if window_s > 0 else None),
        },
    }
    if window_s > 0 and ring["items"]:
        achieved = ring["items"] / window_s
        section["throughput_per_s"] = round(achieved, 1)
        if kernel_rate:
            section["roofline"] = {
                "kernel_items_per_s": round(float(kernel_rate), 1),
                "achieved_items_per_s": round(achieved, 1),
                "fraction_of_roofline": round(achieved / kernel_rate, 4),
                "gap_x": round(kernel_rate / achieved, 2),
            }
    else:
        section["throughput_per_s"] = None
    if overlapped:
        # the two timing sources must agree on the same dispatches:
        # counters are process-lifetime, the ring is bounded, so only a
        # ring that still holds every dispatch can be reconciled
        recon = {"checked": bool(ring_complete), "epsilon": epsilon}
        if ring_complete:
            floor = ABS_FLOOR_S * max(1, len(records))

            def rel(a: float, b: float) -> float:
                if abs(a - b) <= floor:
                    return 0.0
                scale = max(abs(a), abs(b))
                return (round(abs(a - b) / scale, 6)
                        if scale > 1e-9 else 0.0)

            # which ring quantity the stall counter measured depends on
            # pipeline depth: a STREAMED replay surfaces stall as the
            # per-record producer-queue wait, a SERIAL one (depth 0)
            # as inline prep (stall == prep by definition).  Reconcile
            # against whichever interpretation the ring supports.
            stall_vs_qw = rel(ring["queue_wait_s"], stages["stall_s"])
            stall_vs_prep = rel(ring["prep_s"], stages["stall_s"])
            stall_ring = (ring["queue_wait_s"]
                          if stall_vs_qw <= stall_vs_prep
                          else ring["prep_s"])
            errs = {
                "prep": rel(ring["prep_s"], stages["prep_s"]),
                "stall": min(stall_vs_qw, stall_vs_prep),
                "dispatch": rel(ring["dispatch_s"],
                                stages["dispatch_s"]),
                "readback": rel(ring["readback_s"],
                                stages["readback_s"]),
            }
            recon["rel_err"] = errs
            recon["max_rel_err"] = max(errs.values())
            recon["ok"] = recon["max_rel_err"] <= epsilon
            recon["unattributed_s"] = round(
                abs(stall_ring + ring["dispatch_s"]
                    + ring["readback_s"] - critical_s), 6)
        section["reconciliation"] = recon
    return section


def _counter_value(metrics: dict, name: str) -> float:
    fam = metrics.get(name)
    if not fam:
        return 0.0
    return float(sum(s.get("value", 0.0) for s in fam.get("samples", ())))


def replay_stage_totals(metrics: dict) -> dict | None:
    """Extract the clntpu_replay_* stage totals (seconds) from a
    metrics snapshot; None when the pipeline has not run (all zero), so
    the verify family falls back to the serial ring model instead of
    reconciling against nothing."""
    totals = {
        "prep": _counter_value(metrics, "clntpu_replay_prep_seconds_total"),
        "stall": _counter_value(
            metrics, "clntpu_replay_prep_stall_seconds_total"),
        "dispatch": _counter_value(
            metrics, "clntpu_replay_dispatch_seconds_total"),
        "readback": _counter_value(
            metrics, "clntpu_replay_readback_seconds_total"),
    }
    if not any(v > 0 for v in totals.values()):
        return None
    return totals


def report_local(kernel_rate: float | None = None,
                 families: list[str] | None = None,
                 metrics: dict | None = None,
                 flight_summary: dict | None = None) -> dict:
    """The full perf report off THIS process's live registry + flight
    rings — what the ``getperf`` RPC and the getmetrics ``perf``
    section serve (doc/perf.md for the format).  Callers that already
    hold a registry snapshot / flight summary (getmetrics builds both
    for its own sections) pass them in to avoid a second full walk."""
    from . import REGISTRY, flight

    if metrics is None:
        metrics = REGISTRY.snapshot()["metrics"]
    summ = (flight_summary if flight_summary is not None
            else flight.summary())["families"]
    report: dict = {
        "generated_at": round(time.time(), 3),
        "epsilon": EPSILON,
        "kernel_rate": kernel_rate,
        "families": {},
        "retraces": retrace_state(),
        "device_memory": sample_device_memory(),
    }
    for fam in sorted(summ):
        if families is not None and fam not in families:
            continue
        records = flight.recent(fam)
        totals = replay_stage_totals(metrics) if fam == "verify" else None
        report["families"][fam] = attribute_family(
            fam, records, stage_totals_s=totals,
            ring_complete=summ[fam]["total"] == len(records),
            kernel_rate=kernel_rate if fam == "verify" else None)
    return report


def report_from_snapshot(snap: dict,
                         kernel_rate: float | None = None) -> dict:
    """The same report computed OFFLINE from a saved getmetrics-shaped
    capture that includes a ``dispatch_log`` (tools/obs_snapshot.py
    capture --dispatches N).  Ring completeness cannot be judged from a
    capture, so reconciliation is only attempted when the log holds at
    least as many dispatches as the lifetime counter reports."""
    metrics = snap.get("metrics", {})
    by_family: dict[str, list] = {}
    for rec in snap.get("dispatch_log", ()):  # capture --dispatches N
        by_family.setdefault(rec.get("family", "?"), []).append(rec)
    totals_fam = (snap.get("dispatches", {}) or {}).get("families", {})
    report: dict = {
        "generated_at": round(time.time(), 3),
        "epsilon": EPSILON,
        "kernel_rate": kernel_rate,
        "families": {},
        "retraces": snap.get("perf", {}).get("retraces", {}),
        "device_memory": snap.get("perf", {}).get("device_memory", {}),
    }
    for fam in sorted(by_family):
        records = by_family[fam]
        lifetime = (totals_fam.get(fam) or {}).get("total", len(records))
        totals = replay_stage_totals(metrics) if fam == "verify" else None
        report["families"][fam] = attribute_family(
            fam, records, stage_totals_s=totals,
            ring_complete=len(records) >= lifetime,
            kernel_rate=kernel_rate if fam == "verify" else None)
    return report


def compact(report: dict) -> dict:
    """The one-line-per-family view tools/obs_snapshot.py folds into
    diffs: bottleneck + critical path + throughput, no sub-tables."""
    fams = {}
    for fam, sec in report.get("families", {}).items():
        fams[fam] = {
            "bottleneck": sec.get("bottleneck"),
            "critical_path_s": sec.get("critical_path_s"),
            "throughput_per_s": sec.get("throughput_per_s"),
            "overlap_ratio": sec.get("overlap_ratio"),
        }
    return {"families": fams,
            "retraces": report.get("retraces", {}).get("total", 0)}


def reset_for_tests() -> None:
    global _warmup_depth, _armed, _retrace_count
    with _lock:
        _seen.clear()
        _retraces.clear()
        _warmup_depth = 0
        _armed = False
        _retrace_count = 0
