"""Dispatch flight recorder: a bounded per-family ring of
DispatchRecords for post-mortem of the batched device paths.

Aggregate counters (clntpu_replay_* and friends) answer "how much";
they cannot answer "WHICH dispatch blew the p99, and what did the
breaker/quarantine machinery see at that moment".  This module gives
every batched device dispatch — verify bucket, route flush, sign
batch, mesh shard — a process-monotonic ``dispatch_id`` and a
JSON-able record of its shape, timing split (queue-wait / prep /
dispatch / readback), the breaker state it dispatched under, the
faults it hit, and its outcome.  The last N records per family survive
in a ring exposed via the ``listdispatches`` RPC, the ``dispatches``
section of ``getmetrics``, and the Chrome-trace export
(obs/traceexport.py).

Deliberately jax-free (the obs-package rule): hot paths call
``dispatch()``/``begin()``/``finish()``, exposition-only consumers
(tools/obs_snapshot.py) read ``recent()``/``summary()`` without paying
the crypto-stack import.

The slow-dispatch watchdog rides ``finish()``: a dispatch whose total
(queue-wait + prep + dispatch) exceeds LIGHTNING_TPU_SLOW_DISPATCH_S —
or, with no threshold configured, the rolling per-family p99 — is
logged, metered (``clntpu_slow_dispatch_total{family}``), and emitted
on the events bus (topic ``slow_dispatch``) with the full record
attached, so the operator sees the offending dispatch, not just a
counter tick.

Outcome vocabulary (fixed — the cardinality lint, tools/lint_spans.py,
holds label values to declared constants):

    ok             device dispatch completed
    host           the host path ran by design (micro-batch, disabled)
    host_breaker   breaker open → host fallback
    bisect         dispatch raised → quarantine bisect completed it
    readback_host  readback failed → rows re-checked host-side
    fused          mesh shard degraded to the fused single-device path
    deadline       dispatch deadline blown → host fallback
    error          dispatch failed with no recovery path
"""
from __future__ import annotations

import collections
import itertools
import logging
import os
import threading
import time
from contextlib import contextmanager

from ..utils import events, trace as _trace
from . import families as _f

log = logging.getLogger("lightning_tpu.obs.flight")

OUTCOMES = ("ok", "host", "host_breaker", "bisect", "readback_host",
            "fused", "deadline", "error")

# carriers stored per record are capped like span corr ids — a 10k-sig
# ingest flush must not pin 10k ints per ring slot.  One constant for
# both layers: records and flow chains cap at the same width.
CORR_CAP = _trace.CORR_CAP


def corr_ids(carriers) -> list:
    """The capped corr-id list a DispatchRecord stores for an iterable
    of trace.Carrier (the one idiom every dispatch site needs)."""
    return [c.corr_id for c in carriers][:CORR_CAP]

_RING_DEFAULT = 256
_WATCH_WINDOW = 128      # rolling per-family duration window (p99 source)
_P99_MIN_SAMPLES = 32    # no p99 verdicts before the window has history
_P99_FLOOR_S = 0.005     # p99 mode ignores sub-5ms dispatches (noise)

_lock = threading.Lock()
_ids = itertools.count(1)        # thread-safe without the lock (CPython)
_rings: dict[str, collections.deque] = {}       # guarded-by: _lock
_counts: dict[str, int] = {}                    # guarded-by: _lock
_windows: dict[str, collections.deque] = {}     # guarded-by: _lock
_tls = threading.local()


def _ring_size() -> int:
    try:
        return max(1, int(os.environ.get("LIGHTNING_TPU_FLIGHT_RING",
                                         str(_RING_DEFAULT))))
    except ValueError:
        return _RING_DEFAULT


def _slow_threshold_s() -> float | None:
    raw = os.environ.get("LIGHTNING_TPU_SLOW_DISPATCH_S")
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current() -> dict | None:
    """The in-flight record on THIS thread (faultinject/quarantine
    annotate it), or None outside a dispatch."""
    st = _stack()
    return st[-1] if st else None


def begin(family: str, *, corr_ids=(), shape=None, n_real: int = 0,
          lanes: int = 0, queue_wait_ms: float = 0.0,
          prep_ms: float = 0.0, breaker_state: str | None = None) -> dict:
    """Open a DispatchRecord and make it the thread's current one.
    Callers set ``rec["outcome"]`` as the dispatch resolves and must
    pair with ``finish()`` (or use the ``dispatch()`` context manager,
    which does both)."""
    rec = {
        "dispatch_id": next(_ids),
        "family": family,
        "ts": time.time(),
        "ts_ns": time.monotonic_ns(),
        "tid": threading.get_native_id(),
        "thread": threading.current_thread().name,
        "shape": list(shape) if shape is not None else None,
        "n_real": int(n_real),
        "lanes": int(lanes),
        "occupancy": round(n_real / lanes, 4) if lanes else None,
        "queue_wait_ms": round(float(queue_wait_ms), 3),
        "prep_ms": round(float(prep_ms), 3),
        "dispatch_ms": None,
        "readback_ms": None,
        "breaker_state": breaker_state,
        # host<->device operand bytes staged for THIS dispatch (the
        # perf-attribution model's transfer accounting, doc/perf.md);
        # dispatch sites fill them in when a device path actually runs
        "h2d_bytes": 0,
        "d2h_bytes": 0,
        "faults": [],
        "quarantined": 0,
        "outcome": None,
        "corr_ids": list(corr_ids)[:CORR_CAP],
        "_open": True,
    }
    parent = current()
    if parent is not None:
        rec["parent_dispatch_id"] = parent["dispatch_id"]
    _stack().append(rec)
    return rec


def defer(rec: dict) -> None:
    """Pop a record off the thread's dispatch stack WITHOUT sealing it
    — for pipelines whose outcome is only known at a later readback
    (the streaming replay).  The caller owns calling finish() exactly
    once afterwards; finish() is idempotent, so a blanket
    seal-everything finally block is safe."""
    st = _stack()
    if rec in st:
        st.remove(rec)


def finish(rec: dict, outcome: str | None = None, *,
           dispatch_ms: float | None = None,
           error: str | None = None) -> None:
    """Seal a record into its family ring, meter it, and run the
    slow-dispatch watchdog.  Idempotent: a record already sealed is
    left alone (deferred pipeline records are finished from a finally
    block that cannot know which ones an error path sealed early)."""
    if not rec.pop("_open", False):
        return
    st = _stack()
    if rec in st:
        st.remove(rec)
    if outcome is not None:
        rec["outcome"] = outcome
    if rec["outcome"] is None:
        rec["outcome"] = "ok"
    if dispatch_ms is not None:
        rec["dispatch_ms"] = round(float(dispatch_ms), 3)
    if error is not None:
        rec["error"] = error
    family = rec["family"]
    with _lock:
        ring = _rings.get(family)
        if ring is None or ring.maxlen != _ring_size():
            ring = collections.deque(ring or (), maxlen=_ring_size())
            _rings[family] = ring
        ring.append(rec)
        _counts[family] = _counts.get(family, 0) + 1
    _f.DISPATCHES.labels(family, rec["outcome"]).inc()
    _watchdog(rec)


@contextmanager
def dispatch(family: str, **fields):
    """One supervised dispatch: begin() on enter, finish() on exit with
    dispatch wall time measured; an escaping exception seals the record
    with outcome ``error`` (unless the body already resolved it) and
    re-raises."""
    rec = begin(family, **fields)
    t0 = time.perf_counter()
    try:
        yield rec
    except BaseException as e:
        if rec["outcome"] is None:
            rec["outcome"] = "error"
        finish(rec, dispatch_ms=(time.perf_counter() - t0) * 1e3,
               error=type(e).__name__)
        raise
    finish(rec, dispatch_ms=(time.perf_counter() - t0) * 1e3)


def note_fault(seam: str, family: str) -> None:
    """faultinject.fire() hook: stamp the injected fault onto the
    in-flight record so a post-mortem shows WHICH dispatch ate it."""
    rec = current()
    if rec is not None and len(rec["faults"]) < 16:
        rec["faults"].append(seam + ":" + family)


def note_quarantine(rows: int) -> None:
    """quarantine hook: rows diverted off the in-flight dispatch."""
    rec = current()
    if rec is not None:
        rec["quarantined"] += int(rows)


# -- the slow-dispatch watchdog --------------------------------------------


def _quantile(sorted_vals: list[float], q: float) -> float:
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


def _watchdog(rec: dict) -> None:
    total_s = (rec["queue_wait_ms"] + rec["prep_ms"]
               + (rec["dispatch_ms"] or 0.0)) / 1e3
    family = rec["family"]
    thr = _slow_threshold_s()
    with _lock:
        win = _windows.get(family)
        if win is None:
            win = _windows[family] = collections.deque(
                maxlen=_WATCH_WINDOW)
        history = sorted(win)
        win.append(total_s)
    slow = thr is not None and total_s > thr
    if (not slow and thr is None and len(history) >= _P99_MIN_SAMPLES
            and total_s >= _P99_FLOOR_S):
        slow = total_s > _quantile(history, 0.99)
    if not slow:
        return
    rec["slow"] = True
    _f.SLOW_DISPATCH.labels(family).inc()
    log.warning(
        "slow dispatch %d (%s): %.1f ms total (wait %.1f + prep %.1f "
        "+ dispatch %.1f), outcome %s",
        rec["dispatch_id"], family, total_s * 1e3, rec["queue_wait_ms"],
        rec["prep_ms"], rec["dispatch_ms"] or 0.0, rec["outcome"])
    events.emit("slow_dispatch", dict(rec))


# -- exposition -------------------------------------------------------------


def recent(family: str | None = None, limit: int | None = None) -> list[dict]:
    """The last ``limit`` flight records (all families merged in
    dispatch order when family is None).  Returns copies — callers may
    serialize while dispatches continue."""
    with _lock:
        if family is not None:
            recs = list(_rings.get(family, ()))
        else:
            recs = sorted(
                (r for ring in _rings.values() for r in ring),
                key=lambda r: r["dispatch_id"])
        if limit is not None:
            recs = recs[-limit:] if limit > 0 else []
        return [dict(r) for r in recs]


def summary() -> dict:
    """The ``dispatches`` section of getmetrics: per-family lifetime
    counts, ring occupancy, and the latest record."""
    with _lock:
        fams = {
            fam: {
                "total": _counts.get(fam, 0),
                "ring": len(ring),
                "last": dict(ring[-1]) if ring else None,
            }
            for fam, ring in _rings.items()
        }
    return {"ring_size": _ring_size(), "families": fams}


def reset_for_tests() -> None:
    with _lock:
        _rings.clear()
        _counts.clear()
        _windows.clear()
    _tls.stack = []
