"""Dependency-free metrics registry: counters, gauges, histograms.

The reference exposes operational state through per-command RPCs
(listforwards, bkpr reports) and leaves rate/latency aggregation to
external tooling; a batched-verification pipeline lives or dies on
amortization factors (occupancy, flush latency, compile stalls) that
must be measurable on the LIVE daemon, so this registry is first-class.

Design constraints:
  * zero third-party deps (the container has no prometheus_client);
  * cheap enough for hot paths: one dict hit + a locked float add;
  * safe under the daemon's single-loop + to_thread model — verify
    flushes run in worker threads, so every mutation takes the
    instrument's lock (a bare `+=` is a read-modify-write race);
  * bounded label cardinality: a flapping peer set must not grow the
    registry forever, so each family folds overflow label sets into a
    single ``<other>`` child once it reaches its cap.

Naming scheme (doc/observability.md): ``clntpu_<area>_<name>``, with
Prometheus conventions for suffixes (``_total`` counters, ``_seconds``
histograms).  Histograms use FIXED log-scale buckets so two snapshots
taken days apart diff cleanly (tools/obs_snapshot.py).
"""
from __future__ import annotations

import math
import re
import threading

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# One child per distinct label-value tuple; past the cap everything
# folds into this sentinel so the registry stays bounded.
OVERFLOW_LABEL = "<other>"
DEFAULT_MAX_LABEL_SETS = 64


def log2_buckets(lo: float, hi: float) -> tuple[float, ...]:
    """Powers of two spanning [lo, hi] — the fixed log-scale ladder.
    Fixed boundaries (not adaptive) so snapshots diff bucket-by-bucket."""
    e0 = math.floor(math.log2(lo))
    e1 = math.ceil(math.log2(hi))
    return tuple(2.0 ** e for e in range(e0, e1 + 1))


# 1 µs .. ~128 s in powers of two: wide enough for both a single kernel
# dispatch and a cold-compile stall, 28 buckets.
DURATION_BUCKETS = log2_buckets(1e-6, 128.0)
# batch/occupancy-style size ladder: 1 .. 1Mi
SIZE_BUCKETS = log2_buckets(1.0, float(1 << 20))
# ratios in (0, 1]: 1/256 .. 1
RATIO_BUCKETS = log2_buckets(1.0 / 256.0, 1.0)


class Counter:
    """Monotone float counter."""

    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += n

    def sample(self):
        return self.value


class Gauge:
    """Point-in-time value."""

    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self.value -= n

    def sample(self):
        return self.value


class Histogram:
    """Cumulative histogram with fixed upper bounds (Prometheus ``le``
    semantics: a bucket counts observations <= its bound; +Inf implied)."""

    __slots__ = ("_lock", "bounds", "counts", "sum", "count")

    def __init__(self, bounds: tuple[float, ...]):
        self._lock = threading.Lock()
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        # linear scan beats bisect for <32 buckets in CPython; bounded
        i = 0
        for i, b in enumerate(self.bounds):
            if v <= b:
                break
        else:
            i = len(self.bounds)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def sample(self):
        with self._lock:
            # cumulative counts per Prometheus exposition
            cum, out = 0, []
            for b, c in zip(self.bounds, self.counts):
                cum += c
                out.append((b, cum))
            return {"buckets": out, "sum": self.sum,
                    "count": self.count}


_KINDS = {"counter": Counter, "gauge": Gauge}


class Family:
    """One named metric with 0+ label dimensions; children are created
    lazily per label-value tuple and folded into ``<other>`` at the cap."""

    def __init__(self, kind: str, name: str, help: str = "",
                 labelnames: tuple[str, ...] = (),
                 buckets: tuple[float, ...] = DURATION_BUCKETS,
                 max_label_sets: int = DEFAULT_MAX_LABEL_SETS):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.kind = kind
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(buckets)
        self.max_label_sets = max_label_sets
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], object] = {}
        if not self.labelnames:
            self._children[()] = self._make()

    def _make(self):
        if self.kind == "histogram":
            return Histogram(self.buckets)
        return _KINDS[self.kind]()

    def labels(self, *values, **kv):
        """Child instrument for one label-value set; positional values
        follow labelnames order, keywords may name them explicitly."""
        if kv:
            if values:
                raise ValueError("positional and keyword labels mixed")
            values = tuple(str(kv[n]) for n in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {values}")
        child = self._children.get(values)
        if child is not None:
            return child
        with self._lock:
            child = self._children.get(values)
            if child is not None:
                return child
            if len(self._children) >= self.max_label_sets:
                values = (OVERFLOW_LABEL,) * len(self.labelnames)
                child = self._children.get(values)
                if child is not None:
                    return child
            child = self._make()
            self._children[values] = child
            return child

    # unlabeled conveniences: family IS the instrument when labelnames=()
    def _solo(self):
        if self.labelnames:
            raise ValueError(f"{self.name} has labels {self.labelnames}; "
                             "use .labels(...)")
        return self._children[()]

    def inc(self, n: float = 1.0) -> None:
        self._solo().inc(n)

    def set(self, v: float) -> None:
        self._solo().set(v)

    def dec(self, n: float = 1.0) -> None:
        self._solo().dec(n)

    def observe(self, v: float) -> None:
        self._solo().observe(v)

    def collect(self) -> list[tuple[tuple[str, ...], object]]:
        with self._lock:
            items = list(self._children.items())
        return [(lv, child.sample()) for lv, child in items]


class Registry:
    """Named family table + collect/exposition surface.

    ``on_collect`` hooks run before every snapshot/render so pull-style
    sources (logring depth, queue sizes) publish fresh gauges without a
    push call on their own hot path.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, Family] = {}
        self._on_collect: list = []

    def _family(self, kind: str, name: str, help: str,
                labelnames, **kw) -> Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.kind}")
                if fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered with labels "
                        f"{fam.labelnames}, not {tuple(labelnames)}")
                buckets = kw.get("buckets")
                if buckets is not None and fam.buckets != tuple(buckets):
                    raise ValueError(
                        f"metric {name!r} already registered with "
                        "different buckets")
                return fam
            fam = Family(kind, name, help, tuple(labelnames), **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames=(), **kw) -> Family:
        return self._family("counter", name, help, labelnames, **kw)

    def gauge(self, name: str, help: str = "",
              labelnames=(), **kw) -> Family:
        return self._family("gauge", name, help, labelnames, **kw)

    def histogram(self, name: str, help: str = "", labelnames=(),
                  buckets: tuple[float, ...] = DURATION_BUCKETS,
                  **kw) -> Family:
        return self._family("histogram", name, help, labelnames,
                            buckets=tuple(buckets), **kw)

    def on_collect(self, fn) -> None:
        if fn not in self._on_collect:
            self._on_collect.append(fn)

    def _run_hooks(self) -> None:
        for fn in list(self._on_collect):
            try:
                fn()
            except Exception:
                pass  # a broken gauge source must not break exposition

    def snapshot(self) -> dict:
        """JSON-able view: the `getmetrics` RPC result and the
        tools/obs_snapshot.py interchange format."""
        self._run_hooks()
        out = {}
        with self._lock:
            fams = list(self._families.values())
        for fam in sorted(fams, key=lambda f: f.name):
            samples = []
            for lv, val in fam.collect():
                rec = {"labels": dict(zip(fam.labelnames, lv))}
                if fam.kind == "histogram":
                    rec.update(val)
                else:
                    rec["value"] = val
                samples.append(rec)
            out[fam.name] = {"kind": fam.kind, "help": fam.help,
                             "samples": samples}
        return {"metrics": out}

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        self._run_hooks()
        lines: list[str] = []
        with self._lock:
            fams = list(self._families.values())
        for fam in sorted(fams, key=lambda f: f.name):
            if fam.help:
                lines.append(f"# HELP {fam.name} {_esc_help(fam.help)}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for lv, val in fam.collect():
                base = _labelstr(fam.labelnames, lv)
                if fam.kind == "histogram":
                    for b, cum in val["buckets"]:
                        lines.append(
                            f"{fam.name}_bucket"
                            f"{_labelstr(fam.labelnames + ('le',), lv + (_fmt(b),))}"
                            f" {cum}")
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_labelstr(fam.labelnames + ('le',), lv + ('+Inf',))}"
                        f" {val['count']}")
                    lines.append(f"{fam.name}_sum{base} {_fmt(val['sum'])}")
                    lines.append(f"{fam.name}_count{base} {val['count']}")
                else:
                    lines.append(f"{fam.name}{base} {_fmt(val)}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Test isolation: drop every family and hook."""
        with self._lock:
            self._families.clear()
            self._on_collect.clear()


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _esc_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _esc_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _labelstr(names: tuple[str, ...], values: tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_esc_label(v)}"'
                     for n, v in zip(names, values))
    return "{" + inner + "}"
