"""Always-on health engine: time-series telemetry rings + continuous
SLO evaluation over the live metrics registry (doc/health.md).

Everything observability built so far answers "what is happening right
now": the registry is point-in-time, the flight rings hold the last N
dispatches, and the only SLO evaluation in the tree was a post-hoc
assertion inside tools/loadgen.py.  An orchestrator (the ROADMAP's
hardware campaign and multi-tenant fleet) needs the daemon to watch
*itself* over time.  This module is that instrument:

* **Sampler.**  A periodic in-process daemon thread (one tick every
  ``LIGHTNING_TPU_HEALTH_INTERVAL_S`` seconds) snapshots the metrics
  registry plus the flight/overload/breaker state.  The registry walk
  happens ONLY inside the tick — hot paths never pay for it — and the
  tick also refreshes ``clntpu_device_memory_bytes`` (previously only
  sampled at getperf/capture time).

* **Time-series rings.**  Every registry series folds into a bounded
  fixed-step ring of ``LIGHTNING_TPU_HEALTH_RING`` points: counter
  deltas become rates (normalized by the ACTUAL elapsed time of the
  tick, so a late sampler does not inflate a rate), gauges keep their
  last value, and log2-bucket histograms become per-window estimated
  p50/p99 (log-interpolated inside the containing bucket) plus an
  observation rate.

* **SLO engine.**  Declarative ``SloSpec``s — route p99, ingest accept
  floor, shed ratio, breaker open-time, deadline exceedances, retrace
  count — are evaluated every tick against short and long windows into
  per-SLO ok/warn/breach with error-budget burn rates
  (violated-fraction / (1 - objective), the SRE burn-rate shape).
  ``DEFAULT_SLO`` (previously tools/loadgen.py's post-hoc table) lives
  here and seeds the thresholds; loadgen imports it back and asserts
  its own post-hoc verdict AGREES with this live evaluator.

* **State machine.**  Per-SLO statuses roll up into
  healthy -> degraded -> unhealthy with the PR-7 ladder's hysteresis:
  escalation is immediate, de-escalation requires
  ``LIGHTNING_TPU_HEALTH_RECOVER_TICKS`` consecutive clean ticks.
  Transitions emit the ``health_state`` events topic and set
  ``clntpu_health_state``; each transition INTO breach increments
  ``clntpu_slo_breach_total{slo}``.

Consumers: the ``gethealth`` RPC and REST ``GET /health``
(daemon/jsonrpc.py, daemon/rest.py), tools/dashboard.py (live terminal
dashboard), tools/obs_snapshot.py ``--watch`` (window rates from the
rings), and tools/health_smoke.py (the suite's fault-driven
degrade/recover drive).

Deliberately jax-free (the obs-package rule): the engine runs in
exposition-only processes; device memory is sampled via
attribution.sample_device_memory()'s sys.modules peek, never a jax
import.
"""
from __future__ import annotations

import logging
import math
import os
import threading
import time
import traceback
from collections import deque
from dataclasses import dataclass, field

from ..utils import events
from . import REGISTRY
from . import attribution as _attribution
from . import families as _f
from . import flight as _flight

log = logging.getLogger("lightning_tpu.obs.health")

# -- the harness-level SLO table (moved from tools/loadgen.py, which
#    imports it back; doc/overload.md documents the report format) ---------
DEFAULT_SLO = {
    # p99 latency of ANSWERED getroute RPCs (ok or noroute; TRY_AGAIN
    # retries excluded — they are the mechanism that protects this)
    "route_p99_s": 2.0,
    # verified-signature throughput floor while storming (CPU stub is
    # the selfcheck target; TPU deployments declare their own)
    "min_accept_sigs_per_s": 20.0,
    # at least this many getroute answers must land during the storm
    # (a harness-level liveness floor — not evaluable as a live
    # windowed SLO, so the health engine does not carry it)
    "min_route_answers": 20,
}

# -- rolled-up states (clntpu_health_state; ladder-style hysteresis) -------
HEALTHY, DEGRADED, UNHEALTHY = 0, 1, 2
STATE_NAMES = ("healthy", "degraded", "unhealthy")

# per-SLO statuses
OK, WARN, BREACH = "ok", "warn", "breach"

# headline window rates served in every report (the dashboard's
# sparkline sources and the obs_snapshot --watch fold): display name ->
# (family, histogram?-sum) — curated so a report stays small
HEADLINE_RATES = {
    "gossip_accepted_per_s": "clntpu_gossip_accepted_total",
    "verify_sigs_per_s": "clntpu_gossip_flush_sigs",
    "route_queries_per_s": "clntpu_route_queries_total",
    "rpc_requests_per_s": "clntpu_rpc_requests_total",
    "sheds_per_s": "clntpu_shed_total",
    "dispatches_per_s": "clntpu_dispatches_total",
}


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# log2-histogram quantile estimation


def estimate_quantile(bounds, bucket_counts, overflow: float,
                      q: float) -> float | None:
    """Estimate the q-quantile of a windowed histogram given its
    per-bucket (NON-cumulative) counts aligned to ``bounds`` plus the
    +Inf ``overflow`` count.

    The estimate is the smallest value v with P(X <= v) >= q,
    log-interpolated inside the containing bucket (the registry's
    ladders are powers of two, so log interpolation is the natural
    within-bucket model: ``lo * (hi/lo)**frac``).  The first bucket
    extends the ladder downward (lo = bound/2); observations in the
    overflow bucket clamp to the top finite bound (Prometheus
    histogram_quantile semantics).  Returns None for an empty window.
    """
    total = sum(bucket_counts) + overflow
    if total <= 0:
        return None
    q = min(1.0, max(0.0, q))
    rank = max(1.0, math.ceil(q * total))
    cum = 0.0
    for i, n in enumerate(bucket_counts):
        if n <= 0:
            continue
        if cum + n >= rank:
            hi = bounds[i]
            lo = bounds[i - 1] if i > 0 else bounds[0] / 2.0
            frac = (rank - cum) / n
            return lo * (hi / lo) ** frac
        cum += n
    return float(bounds[-1])


def window_buckets(prev: dict, cur: dict) -> tuple[list, float]:
    """Per-bucket non-cumulative counts between two registry histogram
    samples (each ``{"buckets": [(bound, cum), ...], "count": N}``),
    plus the +Inf overflow delta."""
    pb = {b: c for b, c in prev.get("buckets", ())}
    counts, last = [], 0.0
    for bound, cum in cur.get("buckets", ()):
        cum_d = cum - pb.get(bound, 0.0)
        counts.append(cum_d - last)
        last = cum_d
    overflow = (cur.get("count", 0) - prev.get("count", 0)) - last
    return counts, max(0.0, overflow)


# ---------------------------------------------------------------------------
# SLO specs


@dataclass
class SloSpec:
    """One declarative SLO evaluated every sampler tick.

    kind (doc/health.md for the full semantics):
      quantile_max  estimated q-quantile of `family` over the window
                    must stay <= `max`
      rate_min      rate of `family` (counter value, or histogram sum)
                    must stay >= `min` — but ONLY while any `active`
                    family saw traffic in the window (an idle daemon
                    is not in breach of a throughput floor)
      ratio_max     rate(`num`) / (rate(`num`) + sum(rate(d) for den))
                    must stay <= `max` (the shed-ratio shape)
      saturated     no sample of gauge `family` may sit at/above
                    `level` (the overload ladder's SATURATED)
      breaker_open  no circuit breaker may stay continuously open
                    longer than `max_open_s`
      increase_max  `family` may grow by at most `max` over the window
                    (0 = any increase is a breach: retraces, deadline
                    exceedances)

    `window` picks the evaluation span: "short" reacts fast (the
    degradation signals), "long" approximates a whole-run verdict (the
    customer-facing SLOs loadgen cross-checks post-hoc).  `severity`
    feeds the roll-up: only a "major" breach whose long burn rate
    exhausted the budget escalates to unhealthy.
    """

    name: str
    kind: str
    params: dict = field(default_factory=dict)
    window: str = "short"              # "short" | "long"
    severity: str = "minor"            # "minor" | "major"
    objective: float = 0.9             # good-tick target; budget = 1 - obj
    description: str = ""


def default_slo_specs(slo: dict | None = None) -> list[SloSpec]:
    """The stock SLO set, thresholds seeded from DEFAULT_SLO (callers
    pass loadgen's possibly-overridden table to stay in agreement with
    the harness's post-hoc assertions)."""
    t = dict(DEFAULT_SLO)
    if slo:
        t.update({k: v for k, v in slo.items() if k in DEFAULT_SLO})
    return [
        SloSpec(
            "route_p99", "quantile_max",
            # answered queries ONLY (clntpu_route_answer_seconds omits
            # TRY_AGAIN rejections): the same population loadgen's
            # post-hoc p99 judges — fast 429s must not dilute the tail
            {"family": "clntpu_route_answer_seconds", "q": 0.99,
             "max": float(t["route_p99_s"])},
            window="long", severity="major",
            description="p99 of answered getroute RPCs"),
        SloSpec(
            "ingest_accept", "rate_min",
            {"family": "clntpu_gossip_flush_sigs",
             "min": float(t["min_accept_sigs_per_s"]),
             "active": ["clntpu_gossip_accepted_total",
                        "clntpu_gossip_dropped_total",
                        "clntpu_gossip_flush_sigs"]},
            severity="major",
            description="verified-signature throughput floor while "
                        "gossip is flowing (short window: reacts to a "
                        "stalled pipeline, goes inactive when idle)"),
        SloSpec(
            "shed_ratio", "ratio_max",
            {"num": "clntpu_shed_total",
             "den": ["clntpu_gossip_accepted_total",
                     "clntpu_route_queries_total"],
             "max": 0.01},
            description="load shed vs. work admitted"),
        SloSpec(
            "overload_saturated", "saturated",
            {"family": "clntpu_overload_state", "level": 2.0},
            description="a dispatch family's backlog is past its high "
                        "watermark"),
        SloSpec(
            "breaker_open", "breaker_open", {"max_open_s": 5.0},
            severity="major",
            description="a circuit breaker stayed open (host-fallback "
                        "mode) beyond the grace period"),
        SloSpec(
            "deadline_rate", "increase_max",
            {"family": "clntpu_deadline_exceeded_total", "max": 0.0},
            severity="major",
            description="dispatch deadlines blown in the window"),
        SloSpec(
            "retrace", "increase_max",
            {"family": "clntpu_retrace_total", "max": 0.0},
            severity="major",
            description="post-warmup compile on the live path"),
    ]


# ---------------------------------------------------------------------------
# the engine


def _series_key(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return name + "{" + inner + "}"


def _labels_match(labels: dict, want: dict | None) -> bool:
    if not want:
        return True
    return all(labels.get(k) == v for k, v in want.items())


class HealthEngine:
    """Periodic sampler + SLO evaluator + health state machine.

    Construct one per process (``ensure_engine()`` / ``install()``
    manage the singleton the RPC/REST surfaces read), ``start()`` the
    daemon thread, ``stop()`` on shutdown.  ``tick()`` is public so
    tests and harnesses drive the engine deterministically with an
    injected clock.
    """

    def __init__(self, interval_s: float | None = None,
                 ring: int | None = None,
                 slos: list[SloSpec] | None = None,
                 short_ticks: int | None = None,
                 long_ticks: int | None = None,
                 recover_ticks: int | None = None,
                 registry=None, now=time.monotonic):
        self.interval_s = max(0.05, float(
            interval_s if interval_s is not None
            else _env_float("LIGHTNING_TPU_HEALTH_INTERVAL_S", 5.0)))
        self.ring = max(8, ring if ring is not None
                        else _env_int("LIGHTNING_TPU_HEALTH_RING", 240))
        self.short_ticks = max(1, short_ticks if short_ticks is not None
                               else _env_int(
                                   "LIGHTNING_TPU_HEALTH_SHORT_TICKS", 6))
        self.long_ticks = max(
            self.short_ticks,
            long_ticks if long_ticks is not None
            else _env_int("LIGHTNING_TPU_HEALTH_LONG_TICKS", 60))
        self.recover_ticks = max(
            1, recover_ticks if recover_ticks is not None
            else _env_int("LIGHTNING_TPU_HEALTH_RECOVER_TICKS", 3))
        self.slos = list(slos) if slos is not None else default_slo_specs()
        self._registry = registry if registry is not None else REGISTRY
        self._now = now
        self._lock = threading.Lock()
        self._stop_ev = threading.Event()
        self._thread: threading.Thread | None = None
        # series key -> {"kind", "raw": deque[(ts, raw)], "points": deque}
        self._series: dict[str, dict] = {}
        # SLO name -> evaluation state
        self._slo_state: dict[str, dict] = {
            s.name: {"violated": deque(maxlen=self.long_ticks),
                     "observed": deque(maxlen=self.ring),
                     "status": OK, "was_violated": False,
                     "breaches_total": 0, "burn_short": 0.0,
                     "burn_long": 0.0, "value": None}
            for s in self.slos}
        self._ticks = 0
        self._last_mono: float | None = None
        self._last_wall: float | None = None
        self._state = HEALTHY
        self._state_since = time.time()
        self._recover_run = 0
        self._transitions = 0
        # breaker family -> monotonic ts it was first seen open
        self._open_since: dict[str, float] = {}
        self._breaker_view: dict[str, dict] = {}
        self._overload_view: dict[str, str] = {}
        self._flight_view: dict[str, dict] = {}
        _f.HEALTH_STATE.set(float(HEALTHY))

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_ev.clear()
        self._thread = threading.Thread(
            target=self._run, name="health-sampler", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop_ev.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        self._thread = None

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def _run(self) -> None:
        while not self._stop_ev.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # a broken evaluator must never kill the sampler; the
                # next tick retries with fresh state
                log.exception("health tick failed")

    # -- sampling ----------------------------------------------------------

    def tick(self) -> None:
        """One sample + evaluate cycle (the ONLY place the registry is
        walked)."""
        # refresh the device-memory gauge first so this tick's snapshot
        # carries it (continuous sampling — previously getperf-only)
        try:
            _attribution.sample_device_memory()
        except Exception:
            pass
        snap = self._registry.snapshot()["metrics"]
        # tap the breaker/overload/flight subsystems BEFORE taking the
        # engine lock — each tap takes its subsystem's own lock
        taps = self._collect_taps()
        now = self._now()
        eval_errors = ()
        breach_entries = ()
        with self._lock:
            self._ticks += 1
            elapsed = (now - self._last_mono
                       if self._last_mono is not None else None)
            self._last_mono = now
            self._last_wall = time.time()
            self._fold(snap, now, elapsed)
            self._sample_taps(now, taps)
            transition = None
            if elapsed is not None:
                eval_errors, breach_entries = self._evaluate(now)
                transition = self._roll_up()
        # the events bus runs arbitrary subscriber callbacks
        # synchronously — emitting OUTSIDE the lock keeps a subscriber
        # that calls back into report()/state_name() (or is just slow)
        # from deadlocking the sampler and every gethealth caller;
        # same for logging, whose handlers are pluggable
        for name, tb in eval_errors:
            log.error("SLO %s evaluation failed:\n%s", name, tb)
        for entry in breach_entries:
            # one emission per transition INTO breach (the same edge
            # clntpu_slo_breach_total meters) — the incident recorder's
            # slo_breach trigger surface (doc/incidents.md)
            events.emit("slo_breach", entry)
        if transition is not None:
            state, breached = transition
            log.log(logging.WARNING if state != HEALTHY else logging.INFO,
                    "health state -> %s (breached: %s)",
                    STATE_NAMES[state], ",".join(breached) or "none")
            events.emit("health_state",
                        {"state": STATE_NAMES[state],
                         "breached": breached,
                         "ts": round(self._state_since, 3)})

    def _fold(self, snap: dict, now: float, elapsed: float | None) -> None:
        for name, fam in snap.items():
            kind = fam.get("kind")
            for s in fam.get("samples", ()):
                labels = s.get("labels", {})
                key = _series_key(name, labels)
                ser = self._series.get(key)
                if ser is None:
                    ser = self._series[key] = {
                        "kind": kind, "family": name, "labels": labels,
                        "raw": deque(maxlen=self.long_ticks + 1),
                        "points": deque(maxlen=self.ring),
                    }
                    # a monotone series born mid-run (a labeled child's
                    # first increment creates it) baselines at zero one
                    # tick back — otherwise the very event that created
                    # it escapes every window (the first deadline
                    # exceedance / retrace would never breach)
                    if elapsed is not None and kind == "counter":
                        ser["raw"].append((now - elapsed, 0.0))
                    elif elapsed is not None and kind == "histogram":
                        ser["raw"].append((now - elapsed, {
                            "buckets": [(b, 0.0) for b, _
                                        in s.get("buckets", ())],
                            "sum": 0.0, "count": 0}))
                if kind == "histogram":
                    raw = {"buckets": [(b, c) for b, c
                                       in s.get("buckets", ())],
                           "sum": s.get("sum", 0.0),
                           "count": s.get("count", 0)}
                    point = None
                    if ser["raw"] and elapsed:
                        prev_ts, prev = ser["raw"][-1]
                        span = max(now - prev_ts, 1e-9)
                        counts, over = window_buckets(prev, raw)
                        bounds = [b for b, _ in raw["buckets"]]
                        point = (
                            round((raw["count"] - prev["count"])
                                  / span, 6),
                            estimate_quantile(bounds, counts, over, 0.5),
                            estimate_quantile(bounds, counts, over, 0.99),
                        )
                    ser["raw"].append((now, raw))
                    ser["points"].append(point)
                elif kind == "counter":
                    v = float(s.get("value", 0.0))
                    point = None
                    if ser["raw"] and elapsed:
                        prev_ts, prev = ser["raw"][-1]
                        span = max(now - prev_ts, 1e-9)
                        # a reset registry (tests) must not produce a
                        # negative rate
                        point = round(max(0.0, v - prev) / span, 6)
                    ser["raw"].append((now, v))
                    ser["points"].append(point)
                else:  # gauge: last value IS the point
                    v = float(s.get("value", 0.0))
                    ser["raw"].append((now, v))
                    ser["points"].append(v)

    def _collect_taps(self):
        """Breaker / overload / flight state, gathered OUTSIDE the
        engine lock (graftlint lock-order): every call here takes the
        tapped subsystem's own lock — breaker.get/snapshot, overload
        snapshot, the flight-ring summary — and holding ours across
        theirs builds acquisition edges into code we don't control.
        (Jax-free imports; lazy so obs.health never forces the
        resilience package on importers that only want the quantile
        math.)  Returns (breakers, overload_view, flight_view) or
        None."""
        try:
            from ..resilience import FAMILIES, breaker as _breaker
            from ..resilience import overload as _overload
        except Exception:
            return None
        breakers = {}
        for fam in FAMILIES:
            brk = _breaker.get(fam)
            breakers[fam] = (brk.state, brk.trips)
        overload_view = {
            f: c.snapshot()["state"]
            for f, c in sorted(getattr(_overload, "_controllers",
                                       {}).items())}
        try:
            summ = _flight.summary()["families"]
            flight_view = {f: {"total": v["total"],
                               "ring": v["ring"]}
                           for f, v in summ.items()}
        except Exception:
            flight_view = {}
        return breakers, overload_view, flight_view

    def _sample_taps(self, now: float, taps) -> None:
        """Fold pre-collected tap state into the engine's views (lock
        held; pure bookkeeping, no calls out)."""
        if taps is None:
            return
        breakers, self._overload_view, self._flight_view = taps
        view = {}
        for fam, (state, trips) in breakers.items():
            if state == "open":
                self._open_since.setdefault(fam, now)
                open_s = now - self._open_since[fam]
            else:
                self._open_since.pop(fam, None)
                open_s = 0.0
            view[fam] = {"state": state, "open_s": round(open_s, 3),
                         "trips": trips}
        self._breaker_view = view

    # -- windowed reads (lock held) ----------------------------------------

    def _window(self, spec_window: str) -> int:
        return (self.short_ticks if spec_window == "short"
                else self.long_ticks)

    def _matching(self, family: str, labels: dict | None):
        for ser in self._series.values():
            if ser["family"] == family and _labels_match(
                    ser["labels"], labels):
                yield ser

    @staticmethod
    def _span(ser: dict, k: int):
        """(prev, cur, seconds) raw endpoints over the last k ticks (or
        the series' whole history when shorter)."""
        raw = ser["raw"]
        if len(raw) < 2:
            return None
        idx = max(0, len(raw) - 1 - k)
        t0, a = raw[idx]
        t1, b = raw[-1]
        if t1 <= t0:
            return None
        return a, b, t1 - t0

    def _rate(self, family: str, k: int,
              labels: dict | None = None) -> float | None:
        """Summed window rate for a counter family (histogram families
        contribute their `sum` — e.g. sigs/s off a batch-size
        histogram).  None when no series has two points yet."""
        total, span, seen = 0.0, 0.0, False
        for ser in self._matching(family, labels):
            got = self._span(ser, k)
            if got is None:
                continue
            a, b, s = got
            if ser["kind"] == "histogram":
                total += max(0.0, b["sum"] - a["sum"])
            else:
                total += max(0.0, b - a)
            span = max(span, s)
            seen = True
        if not seen or span <= 0:
            return None
        return total / span

    def _increase(self, family: str, k: int,
                  labels: dict | None = None) -> float | None:
        total, seen = 0.0, False
        for ser in self._matching(family, labels):
            got = self._span(ser, k)
            if got is None:
                continue
            a, b, _ = got
            if ser["kind"] == "histogram":
                total += max(0.0, b["count"] - a["count"])
            else:
                total += max(0.0, b - a)
            seen = True
        return total if seen else None

    def _quantile(self, family: str, k: int, q: float,
                  labels: dict | None = None) -> float | None:
        """Windowed quantile estimate over the merged bucket deltas of
        every matching histogram series."""
        merged: dict[float, float] = {}
        overflow = 0.0
        bounds: list[float] | None = None
        for ser in self._matching(family, labels):
            if ser["kind"] != "histogram":
                continue
            got = self._span(ser, k)
            if got is None:
                continue
            a, b, _ = got
            counts, over = window_buckets(a, b)
            bs = [bd for bd, _ in b["buckets"]]
            bounds = bounds or bs
            for bd, n in zip(bs, counts):
                merged[bd] = merged.get(bd, 0.0) + n
            overflow += over
        if bounds is None:
            return None
        return estimate_quantile(
            bounds, [merged.get(bd, 0.0) for bd in bounds], overflow, q)

    def _gauge_peak(self, family: str,
                    labels: dict | None = None) -> float | None:
        peak, seen = 0.0, False
        for ser in self._matching(family, labels):
            if not ser["raw"]:
                continue
            peak = max(peak, ser["raw"][-1][1])
            seen = True
        return peak if seen else None

    # -- SLO evaluation (lock held) ----------------------------------------

    def _evaluate_spec(self, spec: SloSpec):
        """-> (violated: bool | None, observed value).  None = no data
        / inactive this window (counts as good for the burn rate)."""
        p = spec.params
        k = self._window(spec.window)
        if spec.kind == "quantile_max":
            est = self._quantile(p["family"], k, p.get("q", 0.99),
                                 p.get("labels"))
            if est is None:
                return None, None
            return est > p["max"], round(est, 6)
        if spec.kind == "rate_min":
            active = False
            for fam in p.get("active", (p["family"],)):
                inc = self._increase(fam, k)
                if inc:
                    active = True
                    break
            if not active:
                return None, None
            rate = self._rate(p["family"], k) or 0.0
            return rate < p["min"], round(rate, 3)
        if spec.kind == "ratio_max":
            num = self._rate(p["num"], k)
            if num is None:
                return None, None
            den = num + sum(self._rate(d, k) or 0.0 for d in p["den"])
            if den <= 0:
                return None, None
            ratio = num / den
            return ratio > p["max"], round(ratio, 6)
        if spec.kind == "saturated":
            peak = self._gauge_peak(p["family"], p.get("labels"))
            if peak is None:
                return None, None
            return peak >= p.get("level", 2.0), peak
        if spec.kind == "breaker_open":
            worst = 0.0
            for st in self._breaker_view.values():
                worst = max(worst, st.get("open_s", 0.0))
            return worst > p.get("max_open_s", 5.0), round(worst, 3)
        if spec.kind == "increase_max":
            inc = self._increase(p["family"], k, p.get("labels"))
            if inc is None:
                return None, None
            return inc > p.get("max", 0.0), inc
        raise ValueError(f"unknown SLO kind {spec.kind!r}")

    def _evaluate(self, now: float) -> tuple[list, list]:
        errors: list = []
        entries: list = []
        for spec in self.slos:
            st = self._slo_state[spec.name]
            try:
                violated, observed = self._evaluate_spec(spec)
            except Exception:
                # runs under the engine lock: collect, let tick() log
                # after release (handlers are pluggable — lock-order)
                errors.append((spec.name, traceback.format_exc()))
                violated, observed = None, None
            st["violated"].append(1 if violated else 0)
            st["observed"].append(observed)
            st["value"] = observed
            budget = max(1e-6, 1.0 - spec.objective)
            ring = st["violated"]
            short = list(ring)[-self.short_ticks:]
            st["burn_short"] = round(
                (sum(short) / len(short)) / budget, 3) if short else 0.0
            st["burn_long"] = round(
                (sum(ring) / len(ring)) / budget, 3) if ring else 0.0
            if violated:
                st["status"] = BREACH
                if not st["was_violated"]:
                    st["breaches_total"] += 1
                    _f.SLO_BREACH.labels(spec.name).inc()
                    entries.append({
                        "slo": spec.name, "kind": spec.kind,
                        "window": spec.window,
                        "severity": spec.severity,
                        "observed": observed,
                        "breaches_total": st["breaches_total"],
                    })
            elif st["burn_short"] > 1.0:
                st["status"] = WARN
            else:
                st["status"] = OK
            st["was_violated"] = bool(violated)
        return errors, entries

    # -- roll-up state machine (lock held) ---------------------------------

    def _breached(self) -> list[str]:
        return [s.name for s in self.slos
                if self._slo_state[s.name]["status"] == BREACH]

    def _roll_up(self) -> tuple[int, list[str]] | None:
        """Advance the state machine; returns the (state, breached)
        transition for the caller to emit OUTSIDE the lock, or None."""
        breached = self._breached()
        target = HEALTHY
        if breached:
            target = DEGRADED
            for spec in self.slos:
                st = self._slo_state[spec.name]
                if (spec.severity == "major" and st["status"] == BREACH
                        and st["burn_long"] > 1.0):
                    target = UNHEALTHY
                    break
        if target >= self._state:
            # escalation (or holding steady) is immediate — the
            # PR-7 ladder's hysteresis shape
            self._recover_run = 0
            if target > self._state:
                return self._set_state(target, breached)
        else:
            self._recover_run += 1
            if self._recover_run >= self.recover_ticks:
                self._recover_run = 0
                return self._set_state(target, breached)
        return None

    def _set_state(self, state: int,
                   breached: list[str]) -> tuple[int, list[str]]:
        self._state = state
        self._state_since = time.time()
        self._transitions += 1
        _f.HEALTH_STATE.set(float(state))
        return (state, breached)

    # -- exposition --------------------------------------------------------

    def state_name(self) -> str:
        with self._lock:
            return STATE_NAMES[self._state] if self._ticks else "unknown"

    def report(self, series=None, points=None) -> dict:
        """The gethealth RPC result (doc/health.md for the shape).
        ``series``: family names whose time-series rings to extract;
        ``points`` caps ring length in the reply."""
        with self._lock:
            slos = {}
            for spec in self.slos:
                st = self._slo_state[spec.name]
                slos[spec.name] = {
                    "status": st["status"],
                    "violated": st["was_violated"],
                    "kind": spec.kind,
                    "window": spec.window,
                    "severity": spec.severity,
                    "objective": spec.objective,
                    "burn_short": st["burn_short"],
                    "burn_long": st["burn_long"],
                    "breaches_total": st["breaches_total"],
                    "observed": st["value"],
                    "threshold": next(
                        (spec.params[k] for k in
                         ("max", "min", "max_open_s", "level")
                         if k in spec.params), None),
                    "description": spec.description,
                    # a bounded tail of the per-tick observed values —
                    # the SLO panel's sparkline source
                    "recent": list(st["observed"])[-16:],
                }
            rates = {}
            for label, fam in HEADLINE_RATES.items():
                r = self._rate(fam, self.short_ticks)
                rates[label] = round(r, 3) if r is not None else None
            out = {
                "running": self.running,
                "state": (STATE_NAMES[self._state] if self._ticks
                          else "unknown"),
                "state_code": self._state,
                "since": round(self._state_since, 3),
                "ticks": self._ticks,
                "transitions": self._transitions,
                "interval_s": self.interval_s,
                "ring_points": self.ring,
                "short_ticks": self.short_ticks,
                "long_ticks": self.long_ticks,
                "recover_ticks": self.recover_ticks,
                "last_tick_at": self._last_wall,
                "breached": self._breached(),
                "slos": slos,
                "rates": rates,
                "breakers": dict(self._breaker_view),
                "overload": dict(self._overload_view),
                "flight": dict(self._flight_view),
            }
            if series:
                want = set(series)
                rings: dict[str, dict] = {}
                for key, ser in self._series.items():
                    if ser["family"] not in want:
                        continue
                    pts = list(ser["points"])
                    if points is not None and points > 0:
                        pts = pts[-points:]
                    rings[key] = {"kind": ser["kind"], "points": pts}
                out["rings"] = rings
            return out


def compact(report: dict) -> dict:
    """The bounded view tools/obs_snapshot.py folds into --watch ticks
    (window rates come from the engine's rings, so watch output and the
    dashboard agree on the same numbers)."""
    return {
        "state": report.get("state"),
        "breached": report.get("breached", []),
        "slos": {n: s.get("status")
                 for n, s in report.get("slos", {}).items()},
        "rates": report.get("rates", {}),
    }


def empty_report() -> dict:
    """gethealth's answer when no engine was ever installed (a
    harness-embedded daemon that did not opt in)."""
    return {"running": False, "state": "unknown", "state_code": -1,
            "ticks": 0, "breached": [], "slos": {}, "rates": {}}


# ---------------------------------------------------------------------------
# process singleton (the RPC / REST surfaces read this)

_engine: HealthEngine | None = None
_engine_lock = threading.Lock()


def current() -> HealthEngine | None:
    return _engine


def install(engine: HealthEngine | None) -> HealthEngine | None:
    """Make `engine` the process's health engine (harnesses install
    their own fast-tick engine; None uninstalls)."""
    global _engine
    with _engine_lock:
        _engine = engine
    return engine


def ensure_engine(**kw) -> HealthEngine:
    """The daemon entry point's accessor: create the singleton from the
    env knobs on first use."""
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = HealthEngine(**kw)
        return _engine


def reset_for_tests() -> None:
    global _engine
    with _engine_lock:
        if _engine is not None:
            _engine.stop(timeout=1.0)
        _engine = None
