"""Chrome trace-event export: span ring + flight ring → a
Perfetto-loadable JSON timeline (doc/tracing.md).

The reference's cln-tracer turns USDT probes into a timeline an
operator can scrub; our equivalent serializes the trace-span ring
(utils/trace.py) and the dispatch flight ring (obs/flight.py) into the
Chrome trace-event format — `{"traceEvents": [...]}` — which both
chrome://tracing and https://ui.perfetto.dev open directly:

* every completed span is a complete ("X") event on its thread's lane
  (``tid`` from the record; lanes are named via "M" metadata events);
* every correlation id (trace.Carrier) becomes a flow-arrow chain —
  "s"/"t"/"f" events threading the enqueue span to its prep, dispatch,
  and readback spans across threads;
* every flight record is an "X" event on a synthetic per-family
  ``flight:<family>`` lane, its args carrying the full DispatchRecord
  (breaker state, faults, quarantine, timing split).

Spans and flight records share one clock (time.monotonic_ns), so the
lanes line up.  ``validate()`` checks the schema Perfetto actually
enforces — a malformed export fails loudly in tests
(tools/trace_export.py --selfcheck, wired into tools/run_suite.sh)
instead of silently rendering an empty timeline.

Deliberately jax-free (the obs-package rule); the ``gettrace`` RPC and
the tools/trace_export.py CLI are thin callers.
"""
from __future__ import annotations

PID = 1
# synthetic lanes for flight records sit far above real native tids
FLIGHT_TID_BASE = 1 << 30

_FLOW_NAME = "corr"
_FLOW_CAT = "flow"


def _span_event(rec: dict, pid: int) -> dict:
    args = dict(rec.get("attributes", ()))
    for k in ("span_id", "parent_id", "corr_ids", "dispatch_id", "error"):
        if rec.get(k) is not None:
            args[k] = rec[k]
    return {
        "ph": "X",
        "name": rec["name"],
        "cat": "span",
        "ts": rec["start_ns"] / 1e3,
        "dur": rec["duration_ns"] / 1e3,
        "pid": pid,
        "tid": rec.get("tid", 0),
        "args": args,
    }


def _flight_event(rec: dict, tid: int, pid: int) -> dict:
    dur_ms = (rec.get("dispatch_ms") or 0.0) + (rec.get("readback_ms")
                                                or 0.0)
    return {
        "ph": "X",
        "name": "dispatch/" + rec["family"],
        "cat": "dispatch",
        "ts": rec["ts_ns"] / 1e3,
        "dur": dur_ms * 1e3,
        "pid": pid,
        "tid": tid,
        "args": {k: v for k, v in rec.items()
                 if k not in ("ts_ns",) and v is not None},
    }


def chrome_trace(span_records, flight_records=(), *, pid: int = PID) -> dict:
    """Build the Chrome trace-event object.  Deterministic for a given
    input (the golden-file test relies on it): events appear as
    metadata, then spans in input order, then flow chains in corr-id
    order, then flight lanes in input order."""
    span_records = [r for r in span_records if "start_ns" in r]
    events: list[dict] = []
    tid_names: dict[int, str] = {}
    for rec in span_records:
        tid = rec.get("tid", 0)
        if tid not in tid_names:
            tid_names[tid] = rec.get("thread") or f"tid-{tid}"

    fam_tids: dict[str, int] = {}
    flight_events = []
    for rec in flight_records:
        fam = rec["family"]
        tid = fam_tids.get(fam)
        if tid is None:
            tid = fam_tids[fam] = FLIGHT_TID_BASE + len(fam_tids)
            tid_names[tid] = "flight:" + fam
        flight_events.append(_flight_event(rec, tid, pid))

    events.append({"ph": "M", "name": "process_name", "pid": pid,
                   "args": {"name": "lightning_tpu"}})
    for tid in sorted(tid_names):
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": tid_names[tid]}})

    events.extend(_span_event(r, pid) for r in span_records)

    # flow arrows: one chain per correlation id, hop after hop in span
    # start order — the enqueue span starts the chain, every later span
    # carrying the id is a step, the last is the binding finish
    by_corr: dict[int, list[dict]] = {}
    for rec in span_records:
        for cid in rec.get("corr_ids", ()):
            by_corr.setdefault(cid, []).append(rec)
    for cid in sorted(by_corr):
        chain = sorted(by_corr[cid],
                       key=lambda r: (r["start_ns"], r["span_id"]))
        if len(chain) < 2:
            continue
        last = len(chain) - 1
        for i, rec in enumerate(chain):
            ev = {
                "ph": "s" if i == 0 else ("f" if i == last else "t"),
                "name": _FLOW_NAME,
                "cat": _FLOW_CAT,
                "id": cid,
                "ts": rec["start_ns"] / 1e3,
                "pid": pid,
                "tid": rec.get("tid", 0),
            }
            if i == last:
                ev["bp"] = "e"   # bind to the enclosing slice
            events.append(ev)

    events.extend(flight_events)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def validate(trace_obj: dict) -> list[str]:
    """Schema check for the fields Perfetto/chrome://tracing require;
    returns a list of problems (empty == valid).  Checked per event:

    * "M" metadata: name + args.name;
    * "X" complete: name, numeric ts, numeric dur >= 0, pid, tid;
    * "s"/"t"/"f" flow: id, name, numeric ts, pid, tid; "f" needs
      bp="e"; every flow id must have exactly one "s" and one "f", and
      each flow event must bind INSIDE an "X" slice on its tid (the
      rule Perfetto enforces when attaching arrows).
    """
    errs: list[str] = []
    if not isinstance(trace_obj, dict):
        return ["top-level value is not an object"]
    evs = trace_obj.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents missing or not a list"]
    slices: dict[object, list[tuple[float, float]]] = {}
    flows: dict[object, dict[str, int]] = {}
    for i, ev in enumerate(evs):
        where = f"event[{i}]"
        if not isinstance(ev, dict):
            errs.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph == "M":
            if not ev.get("name") or "name" not in ev.get("args", {}):
                errs.append(f"{where}: metadata needs name + args.name")
            continue
        if ph not in ("X", "s", "t", "f"):
            errs.append(f"{where}: unknown ph {ph!r}")
            continue
        for key in ("ts", "pid", "tid"):
            if not isinstance(ev.get(key), (int, float)):
                errs.append(f"{where}: {key} missing/non-numeric")
        if not isinstance(ev.get("name"), str) or not ev.get("name"):
            errs.append(f"{where}: name missing")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errs.append(f"{where}: X event needs dur >= 0")
            elif isinstance(ev.get("ts"), (int, float)):
                slices.setdefault(ev.get("tid"), []).append(
                    (ev["ts"], ev["ts"] + dur))
        else:
            if "id" not in ev:
                errs.append(f"{where}: flow event needs id")
            if ph == "f" and ev.get("bp") != "e":
                errs.append(f"{where}: flow finish needs bp='e'")
            counts = flows.setdefault(ev.get("id"), {"s": 0, "f": 0})
            if ph in counts:
                counts[ph] += 1
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict) or ev.get("ph") not in ("s", "t", "f"):
            continue
        ts, tid = ev.get("ts"), ev.get("tid")
        if not isinstance(ts, (int, float)):
            continue
        if not any(a <= ts <= b for a, b in slices.get(tid, ())):
            errs.append(f"event[{i}]: flow event at ts={ts} binds no "
                        f"slice on tid={tid}")
    for fid, counts in flows.items():
        if counts["s"] != 1 or counts["f"] != 1:
            errs.append(f"flow id {fid!r}: needs exactly one start and "
                        f"one finish (got s={counts['s']}, "
                        f"f={counts['f']})")
    return errs
