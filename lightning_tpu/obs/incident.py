"""Black-box flight recorder: automatic incident capture and forensic
bundles (doc/incidents.md).

Everything observability built so far is LIVE state: the registry is
point-in-time, the 256-record flight rings wrap within seconds of an
incident, and a daemon crash loses all of it.  The ROADMAP's unattended
hardware campaign runs behind a tunnel that has already died
mid-session twice; when a breaker trips or the process dies at 3am
with nobody watching tools/dashboard.py, there must be a durable,
correlated evidence bundle on disk.  This module is that instrument.

**Triggers.**  The recorder subscribes to the trigger surfaces the rest
of the stack already emits — no hot path gains a new call site:

  * ``health_state``        engine transitions to degraded/unhealthy;
  * ``slo_breach``          SLO breach ENTRIES (obs/health.py emits one
                            per transition into breach);
  * ``breaker_transition``  a circuit breaker OPENING (to="open");
  * ``slow_dispatch``       the flight-recorder watchdog;
  * ``deadline_exceeded``   a dispatch deadline blown;
  * ``quarantine``          rows bisect-isolated off a poisoned batch;
  * ``sys.excepthook`` / ``threading.excepthook``  unhandled crashes
    (the bundle is frozen BEFORE the interpreter unwinds);
  * a ``faulthandler`` dump file armed in the bundle directory, so a
    hard crash (SIGSEGV in a jax extension — the suite's known cache
    failure mode) leaves native tracebacks next to the bundles.

**Episodes.**  Triggers are debounced per episode: the first trigger
opens an episode and freezes a bundle; for ``LIGHTNING_TPU_INCIDENT_``
``COOLDOWN_S`` further triggers are absorbed into the same episode — a
strictly higher-severity trigger RE-freezes the bundle under its own
name (a verify fault storm quarantines rows first and opens the breaker
seconds later; the one resulting bundle is named ``breaker_open``, with
the quarantine triggers in its history), everything else only counts.
Per-class counts live in the manifest, so "the cooldown suppressed N
duplicates" is an assertable fact.  At most one bundle exists per
episode, which is what makes the acceptance drive ("exactly one bundle
per cooldown window") deterministic.

**Bundles.**  One directory per episode holding the correlated frozen
state as separate JSON artifacts: the full metrics snapshot, every
per-family flight ring, the recent trace spans as a validated
Chrome-trace export, the gethealth report with its SLO rings, the
breaker/overload/shed state, the resolved knob registry, and a
``manifest.json`` naming the trigger with its correlation id.  Bundles
are bounded by count and total bytes (oldest-first rotation; the open
episode's bundle is never rotated away).

**Hot-path contract.**  Subscriber callbacks only classify the trigger
under the recorder's own lock and enqueue; ALL capture I/O runs on a
dedicated worker thread, never under any subsystem lock (the graftrace
lock-order pass stays clean).  Crash hooks block on the worker draining
— the dying interpreter waits for its own black box to flush.

Surfaces: ``listincidents``/``getincident`` RPCs (daemon/jsonrpc.py),
tools/incident_report.py (render/--diff/--validate/--selfcheck),
tools/dashboard.py (incidents panel), tools/obs_snapshot.py (capture
fold + --watch new-incident lines).  Deliberately jax-free (the
obs-package rule).
"""
from __future__ import annotations

import faulthandler
import json
import logging
import os
import queue
import re
import shutil
import sys
import threading
import time
import traceback

from ..utils import events, trace as _trace
from . import REGISTRY, ensure_installed
from . import families as _f

log = logging.getLogger("lightning_tpu.obs.incident")

MANIFEST_SCHEMA = 1

# trigger class -> severity (higher wins an episode's name; the ladder
# ranks forensic SPECIFICITY: a crash or an open breaker names a root
# cause, a health roll-up is a symptom of one)
SEVERITY = {
    "slow_dispatch": 20,
    "quarantine": 30,
    "slo_breach": 40,
    "health_degraded": 45,
    "health_unhealthy": 50,
    "deadline": 60,
    "breaker_open": 70,
    "thread_crash": 80,
    "crash": 90,
}
TRIGGER_CLASSES = tuple(sorted(SEVERITY))

# events-bus topic -> trigger class (payload-conditional mappings are
# resolved in _classify)
_TOPIC_CLASSES = {
    "breaker_transition": "breaker_open",
    "health_state": "health_degraded",
    "slo_breach": "slo_breach",
    "slow_dispatch": "slow_dispatch",
    "deadline_exceeded": "deadline",
    "quarantine": "quarantine",
}

# artifact file names inside a bundle directory (manifest.json rides
# beside them); getincident validates requested names against this
ARTIFACTS = ("metrics.json", "flight.json", "trace.json", "health.json",
             "resilience.json", "knobs.json", "journeys.json")

_ID_RE = re.compile(r"^inc-[0-9]+-[0-9]+$")
_REDACT_RE = re.compile(r"PASSPHRASE|SECRET|TOKEN|PASSWORD")

# bound the trigger payload stored in the manifest (a slow_dispatch
# payload is a full DispatchRecord — fine; an adversarially huge one
# must not balloon the manifest)
_PAYLOAD_CAP = 32 << 10


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _jsonable(obj):
    """Round-trip through json with a lossy fallback so an artifact
    write can never raise on an exotic payload value."""
    return json.loads(json.dumps(obj, default=repr))


def _classify(topic: str, payload: dict) -> str | None:
    """Map a bus emission to a trigger class, or None when the emission
    is not incident-shaped (breaker closing, health recovering)."""
    cls = _TOPIC_CLASSES.get(topic)
    if cls is None:
        return None
    if topic == "breaker_transition":
        return "breaker_open" if payload.get("to") == "open" else None
    if topic == "health_state":
        state = payload.get("state")
        if state == "unhealthy":
            return "health_unhealthy"
        if state == "degraded":
            return "health_degraded"
        return None
    return cls


def _correlation(cls: str, payload: dict) -> dict:
    """The bounded correlation block the manifest carries: whatever
    identity the trigger payload offers (dispatch family, corr ids,
    SLO name, breaker seq) plus the class itself."""
    out: dict = {"class": cls}
    for k in ("family", "slo", "seam", "loop", "dispatch_id",
              "corr_ids", "seq", "state", "reason", "row", "thread",
              "exception"):
        if isinstance(payload, dict) and payload.get(k) is not None:
            out[k] = payload[k]
    return _jsonable(out)


def resolve_knobs() -> dict:
    """The resolved LIGHTNING_TPU_* knob registry: every knob named in
    the generated doc/knobs.md (when the repo layout is present) with
    its effective value and source, plus any set env knob the table
    does not know yet.  Secret-shaped knobs are redacted."""
    knobs: dict[str, dict] = {}
    doc = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "doc", "knobs.md")
    try:
        with open(doc, encoding="utf8") as f:
            for line in f:
                m = re.match(r"\| `(LIGHTNING_TPU_[A-Z0-9_]+)` \| (.+?) \|",
                             line)
                if m:
                    knobs[m.group(1)] = {"default": m.group(2).strip(),
                                         "value": None,
                                         "source": "default"}
    except OSError:
        pass
    for name, value in os.environ.items():
        if not name.startswith("LIGHTNING_TPU_"):
            continue
        entry = knobs.setdefault(name, {"default": None, "value": None,
                                        "source": "default"})
        entry["value"] = ("<redacted>" if _REDACT_RE.search(name)
                          else value)
        entry["source"] = "env"
    return knobs


class IncidentRecorder:
    """The black-box recorder: classify triggers cheaply on the
    emitter's thread, capture bundles on a dedicated worker.

    Construct one per process (``install_from_env()`` manages the
    singleton the RPC surface reads), ``start()``/``stop()`` bracket
    its lifetime.  ``now=`` injects a clock for deterministic cooldown
    tests; ``drain()`` blocks until queued captures are on disk.
    """

    def __init__(self, directory: str, *,
                 max_bundles: int | None = None,
                 max_bytes: int | None = None,
                 cooldown_s: float | None = None,
                 triggers=None,
                 disabled: bool | None = None,
                 process_hooks: bool = False,
                 now=time.monotonic):
        self.directory = os.path.abspath(directory)
        self.max_bundles = max(1, max_bundles if max_bundles is not None
                               else _env_int(
                                   "LIGHTNING_TPU_INCIDENT_MAX_BUNDLES",
                                   16))
        self.max_bytes = max(1 << 12, max_bytes if max_bytes is not None
                             else _env_int(
                                 "LIGHTNING_TPU_INCIDENT_MAX_BYTES",
                                 67108864))    # 64 MiB
        self.cooldown_s = max(0.0, cooldown_s if cooldown_s is not None
                              else _env_float(
                                  "LIGHTNING_TPU_INCIDENT_COOLDOWN_S",
                                  60.0))
        self.triggers = frozenset(triggers if triggers is not None
                                  else TRIGGER_CLASSES)
        self.disabled = (disabled if disabled is not None else
                         os.environ.get("LIGHTNING_TPU_INCIDENT_DISABLE")
                         == "1")
        self.process_hooks = process_hooks
        self._now = now
        self._lock = threading.Lock()
        self._episode: dict | None = None       # guarded-by: self._lock
        self._ep_seq = 0                        # guarded-by: self._lock
        self._queue: queue.Queue = queue.Queue()
        self._cond = threading.Condition()
        self._pending = 0                       # guarded-by: self._cond
        self._thread: threading.Thread | None = None
        self._stop_ev = threading.Event()
        self._subscribed: list = []
        self._prev_sys_hook = None
        self._prev_thread_hook = None
        self._fault_file = None
        self._faulthandler_armed = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Subscribe the trigger surfaces, spawn the capture worker,
        and (with process_hooks) arm the crash hooks + faulthandler."""
        if self.disabled:
            return
        if self._thread is not None and self._thread.is_alive():
            return
        os.makedirs(self.directory, exist_ok=True)
        self._stop_ev.clear()
        self._thread = threading.Thread(
            target=self._run, name="incident-recorder", daemon=True)
        self._thread.start()
        for topic in sorted(set(_TOPIC_CLASSES)):
            fn = self._make_subscriber(topic)
            events.subscribe(topic, fn)
            self._subscribed.append((topic, fn))
        if self.process_hooks:
            self._install_process_hooks()
        log.info("incident recorder armed: dir=%s cooldown=%.1fs "
                 "max_bundles=%d max_bytes=%d triggers=%s",
                 self.directory, self.cooldown_s, self.max_bundles,
                 self.max_bytes, ",".join(sorted(self.triggers)))

    def stop(self, timeout: float = 10.0) -> None:
        """Unsubscribe, flush the worker (pending captures complete),
        finalize the open episode's manifest, restore crash hooks."""
        for topic, fn in self._subscribed:
            events.unsubscribe(topic, fn)
        self._subscribed.clear()
        self._restore_process_hooks()
        t = self._thread
        if t is not None and t.is_alive():
            self.drain(timeout)
            self._stop_ev.set()
            self._queue.put(None)
            t.join(timeout)
        self._thread = None
        # final manifest refresh so absorbed-trigger counts recorded
        # since the last capture are durable
        with self._lock:
            ep = self._episode
            snap = self._manifest_view(ep) if (
                ep is not None and ep.get("captured_at")) else None
        if snap is not None:
            try:
                self._write_json(
                    os.path.join(snap["_dir"], "manifest.json"),
                    {k: v for k, v in snap.items()
                     if not k.startswith("_")})
            except OSError:
                log.exception("incident manifest finalize failed")

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until every queued capture has been processed (tests
        and the crash hooks use this); False on timeout."""
        with self._cond:
            return self._cond.wait_for(lambda: self._pending == 0,
                                       timeout)

    def note_crash(self, reason: str, extra: dict | None = None,
                   timeout: float = 10.0) -> bool:
        """Synchronously freeze a crash bundle: trigger + drain.

        The excepthook path captures interpreter-unwinding crashes, but
        a deliberate ``os._exit`` (the fault grammar's crash action)
        skips every hook — callers about to hard-exit use this to make
        sure the bundle the next boot will look for is on disk first.
        Returns False when the capture did not flush within timeout."""
        self._trigger("crash", {"reason": reason,
                                "thread": threading.current_thread().name,
                                **(extra or {})})
        return self.drain(timeout)

    # -- trigger intake (emitter threads; must stay cheap) -----------------

    def _make_subscriber(self, topic: str):
        def _on_event(payload: dict, _topic=topic) -> None:
            try:
                cls = _classify(_topic, payload
                                if isinstance(payload, dict) else {})
                if cls is not None:
                    self._trigger(cls, payload)
            except Exception:
                log.exception("incident trigger intake failed (%s)",
                              _topic)
        return _on_event

    def _trigger(self, cls: str, payload) -> None:
        """Classify against the open episode and enqueue capture work.
        Returns after a dict update — capture I/O never runs on the
        emitter's thread."""
        if self.disabled or cls not in self.triggers:
            return
        if not isinstance(payload, dict):
            payload = {"payload": payload}
        sev = SEVERITY.get(cls, 0)
        now = self._now()
        wall = time.time()
        with self._lock:
            ep = self._episode
            if ep is None or (now - ep["opened_mono"]) > self.cooldown_s:
                self._ep_seq += 1
                ep = self._episode = {
                    "id": f"inc-{int(wall * 1000)}-{self._ep_seq}",
                    "seq": self._ep_seq,
                    "opened_mono": now,
                    "opened_at": wall,
                    "severity": sev,
                    "trigger_class": cls,
                    "trigger_payload": payload,
                    "trigger_at": wall,
                    "history": [{"class": cls, "at": round(wall, 3),
                                 "action": "capture"}],
                    "suppressed": {},
                    "captured_at": None,
                    "recaptures": 0,
                    "capture_errors": {},
                    "artifacts": {},
                    "trace_problems": None,
                }
                action = "capture"
            elif sev > ep["severity"]:
                ep["severity"] = sev
                ep["trigger_class"] = cls
                ep["trigger_payload"] = payload
                ep["trigger_at"] = wall
                ep["recaptures"] += 1
                if len(ep["history"]) < 64:
                    ep["history"].append(
                        {"class": cls, "at": round(wall, 3),
                         "action": "escalate"})
                action = "escalate"
            else:
                ep["suppressed"][cls] = ep["suppressed"].get(cls, 0) + 1
                action = "absorb"
        # metering + queueing OUTSIDE the lock: the counter inc walks
        # the registry's family lock and the queue has its own.  The op
        # carries ITS episode so a capture queued just before the
        # cooldown rolled a new episode still freezes the old bundle.
        _f.INCIDENT_TRIGGERS.labels(cls, action).inc()
        if action in ("capture", "escalate"):
            self._enqueue(("capture", ep))
        else:
            # absorbed triggers only touch memory; a debounced manifest
            # refresh keeps the on-disk suppressed counts roughly live
            # without one write per quarantined row
            self._enqueue(("refresh", ep))

    def _enqueue(self, op) -> None:
        with self._cond:
            self._pending += 1
        self._queue.put(op)

    # -- capture worker ----------------------------------------------------

    def _run(self) -> None:
        last_refresh = 0.0
        while True:
            op = self._queue.get()
            try:
                if op is None or self._stop_ev.is_set():
                    if op is None:
                        return
                    continue
                if op[0] == "capture":
                    self._capture(op[1])
                    last_refresh = self._now()
                elif op[0] == "refresh":
                    if self._now() - last_refresh >= 1.0:
                        self._refresh_manifest(op[1])
                        last_refresh = self._now()
            except Exception:
                # the black box must never take the daemon down
                log.exception("incident capture failed")
            finally:
                with self._cond:
                    self._pending = max(0, self._pending - 1)
                    self._cond.notify_all()

    def _manifest_view(self, ep: dict) -> dict:
        """A JSON-ready copy of the episode's manifest state (caller
        holds the lock); keys starting with "_" are worker-internal."""
        payload = _jsonable(ep["trigger_payload"])
        if len(json.dumps(payload)) > _PAYLOAD_CAP:
            payload = {"truncated": True,
                       "repr": repr(ep["trigger_payload"])[:_PAYLOAD_CAP]}
        return {
            "schema": MANIFEST_SCHEMA,
            "id": ep["id"],
            "trigger": {
                "class": ep["trigger_class"],
                "severity": ep["severity"],
                "at": round(ep["trigger_at"], 3),
                "payload": payload,
            },
            "correlation": _correlation(ep["trigger_class"],
                                        ep["trigger_payload"]),
            "episode": {
                "opened_at": round(ep["opened_at"], 3),
                "cooldown_s": self.cooldown_s,
                "seq": ep["seq"],
            },
            "history": list(ep["history"]),
            "suppressed": dict(ep["suppressed"]),
            "captured_at": ep["captured_at"],
            "recaptures": ep["recaptures"],
            "trace_problems": ep["trace_problems"],
            "capture_errors": dict(ep["capture_errors"]),
            "artifacts": dict(ep["artifacts"]),
            "process": {
                "pid": os.getpid(),
                "argv": list(sys.argv),
                "python": sys.version.split()[0],
            },
            "_dir": os.path.join(self.directory, ep["id"]),
        }

    def _capture(self, ep: dict) -> None:
        """Freeze the correlated bundle for `ep` (worker thread only;
        holds NO lock while collecting or writing)."""
        t0 = time.perf_counter()
        with self._lock:
            snap = self._manifest_view(ep)
        bundle_dir = snap["_dir"]
        os.makedirs(bundle_dir, exist_ok=True)
        artifacts: dict[str, dict] = {}
        errors: dict[str, str] = {}
        trace_problems = None
        for name, builder in (
                ("metrics.json", self._art_metrics),
                ("flight.json", self._art_flight),
                ("trace.json", self._art_trace),
                ("health.json", self._art_health),
                ("resilience.json", self._art_resilience),
                ("knobs.json", self._art_knobs),
                ("journeys.json", self._art_journeys)):
            try:
                obj = builder()
                if name == "trace.json":
                    obj, trace_problems = obj
                path = os.path.join(bundle_dir, name)
                self._write_json(path, obj)
                artifacts[name] = {"bytes": os.path.getsize(path)}
            except Exception as e:
                errors[name] = f"{type(e).__name__}: {e}"
        captured_at = round(time.time(), 3)
        capture_ms = round((time.perf_counter() - t0) * 1e3, 1)
        with self._lock:
            # the episode may have escalated while we wrote: keep the
            # artifact bookkeeping, re-read trigger naming at write time
            ep["captured_at"] = captured_at
            ep["artifacts"] = artifacts
            ep["capture_errors"] = errors
            ep["trace_problems"] = trace_problems
            manifest = self._manifest_view(ep)
            manifest["capture_ms"] = capture_ms
            cls = ep["trigger_class"]
        self._write_json(os.path.join(bundle_dir, "manifest.json"),
                         {k: v for k, v in manifest.items()
                          if not k.startswith("_")})
        _f.INCIDENTS.labels(cls).inc()
        total = self._rotate(keep=snap["id"])
        log.warning("incident bundle frozen: %s trigger=%s (%d artifacts"
                    ", %.0f ms, store %d bytes)", snap["id"], cls,
                    len(artifacts), capture_ms, total)

    def _refresh_manifest(self, ep: dict) -> None:
        """Debounced rewrite of an episode's manifest so absorbed
        trigger counts land on disk (worker thread only)."""
        with self._lock:
            if not ep.get("captured_at"):
                return
            manifest = self._manifest_view(ep)
        self._write_json(os.path.join(manifest["_dir"], "manifest.json"),
                         {k: v for k, v in manifest.items()
                          if not k.startswith("_")})

    @staticmethod
    def _write_json(path: str, obj) -> None:
        """Atomic-rename write so a concurrent reader (RPC, the report
        CLI) never sees a torn artifact."""
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf8") as f:
            json.dump(obj, f, indent=1, default=repr)
        os.replace(tmp, path)

    # -- artifact builders (worker thread; each may raise, the caller
    #    records the error instead of losing the bundle) -------------------

    @staticmethod
    def _art_metrics() -> dict:
        ensure_installed()
        try:
            from .attribution import sample_device_memory
            sample_device_memory()
        except Exception:
            pass
        return REGISTRY.snapshot()

    @staticmethod
    def _art_flight() -> dict:
        from . import flight
        return {"summary": flight.summary(),
                "records": flight.recent()}

    @staticmethod
    def _art_trace():
        from . import flight, traceexport
        obj = traceexport.chrome_trace(_trace.records(), flight.recent())
        problems = traceexport.validate(obj)
        if problems:
            obj["validation_problems"] = problems[:32]
        return obj, len(problems)

    @staticmethod
    def _art_health() -> dict:
        from . import health as _health
        eng = _health.current()
        if eng is None:
            return _health.empty_report()
        return eng.report(
            series=sorted(set(_health.HEADLINE_RATES.values())))

    @staticmethod
    def _art_resilience() -> dict:
        from ..resilience import overload, resilience_snapshot
        return {"resilience": resilience_snapshot(),
                "overload": overload.snapshot()}

    @staticmethod
    def _art_knobs() -> dict:
        return resolve_knobs()

    @staticmethod
    def _art_journeys() -> dict:
        """The per-item journey table at incident time
        (doc/journeys.md): what each recently-sampled entity was doing
        when the trigger fired, stitched by dispatch_id to the
        flight.json records frozen beside it."""
        from . import journey as _journey
        return {"enabled": _journey.enabled(),
                "summary": _journey.summary(),
                "journeys": _journey.recent(limit=50)}

    # -- retention ---------------------------------------------------------

    def _bundle_dirs(self) -> list[tuple[str, int]]:
        """(bundle_id, bytes) pairs on disk, oldest first (ids embed
        their epoch-ms open time, so lexical-by-timestamp sorting is
        chronological)."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            if not _ID_RE.match(name):
                continue
            path = os.path.join(self.directory, name)
            if not os.path.isdir(path):
                continue
            size = 0
            for root, _dirs, files in os.walk(path):
                for fn in files:
                    try:
                        size += os.path.getsize(os.path.join(root, fn))
                    except OSError:
                        pass
            out.append((name, size))
        out.sort(key=lambda p: (int(p[0].split("-")[1]),
                                int(p[0].split("-")[2])))
        return out

    def _rotate(self, keep: str) -> int:
        """Oldest-first rotation to the count/bytes bounds; `keep` (the
        episode just captured) is never removed.  Returns the resident
        byte total (also set on the gauge)."""
        bundles = self._bundle_dirs()
        total = sum(s for _, s in bundles)
        dropped = []
        while bundles and (len(bundles) > self.max_bundles
                           or total > self.max_bytes):
            name, size = bundles[0]
            if name == keep:
                break
            try:
                shutil.rmtree(os.path.join(self.directory, name))
            except OSError:
                break
            bundles.pop(0)
            total -= size
            dropped.append(name)
        _f.INCIDENT_BYTES.set(float(total))
        if dropped:
            log.info("incident rotation dropped %s", ",".join(dropped))
        return total

    # -- crash hooks -------------------------------------------------------

    def _install_process_hooks(self) -> None:
        self._prev_sys_hook = sys.excepthook
        sys.excepthook = self._sys_excepthook
        self._prev_thread_hook = threading.excepthook
        threading.excepthook = self._thread_excepthook
        try:
            path = os.path.join(self.directory, "faulthandler.log")
            self._fault_file = open(path, "a", encoding="utf8")
            if not faulthandler.is_enabled():
                faulthandler.enable(file=self._fault_file,
                                    all_threads=True)
                self._faulthandler_armed = True
        except OSError:
            log.exception("faulthandler arming failed")

    def _restore_process_hooks(self) -> None:
        if self._prev_sys_hook is not None:
            sys.excepthook = self._prev_sys_hook
            self._prev_sys_hook = None
        if self._prev_thread_hook is not None:
            threading.excepthook = self._prev_thread_hook
            self._prev_thread_hook = None
        if self._faulthandler_armed:
            try:
                faulthandler.disable()
            except Exception:
                pass
            self._faulthandler_armed = False
        if self._fault_file is not None:
            try:
                self._fault_file.close()
            except OSError:
                pass
            self._fault_file = None

    def _crash_payload(self, etype, value, tb, thread=None) -> dict:
        return {
            "exception": getattr(etype, "__name__", str(etype)),
            "message": str(value)[:2048],
            "thread": thread or threading.current_thread().name,
            "traceback": "".join(
                traceback.format_exception(etype, value, tb))[-16384:],
        }

    def _sys_excepthook(self, etype, value, tb) -> None:
        try:
            self._trigger("crash", self._crash_payload(etype, value, tb))
            # the interpreter is unwinding: wait for the black box to
            # flush before the process dies (worker is a daemon thread)
            self.drain(10.0)
        except Exception:
            log.exception("crash capture failed")
        finally:
            if self._prev_sys_hook is not None:
                self._prev_sys_hook(etype, value, tb)
            else:
                sys.__excepthook__(etype, value, tb)

    def _thread_excepthook(self, args) -> None:
        try:
            if args.exc_type is not SystemExit:
                self._trigger("thread_crash", self._crash_payload(
                    args.exc_type, args.exc_value, args.exc_traceback,
                    thread=getattr(args.thread, "name", None)))
                self.drain(10.0)
        except Exception:
            log.exception("thread-crash capture failed")
        finally:
            prev = self._prev_thread_hook
            if prev is not None:
                prev(args)

    # -- exposition (the listincidents / getincident handlers) -------------

    def summary(self, limit: int | None = None) -> dict:
        """The listincidents RPC result: newest-first bundle summaries
        off the on-disk manifests, with the open episode's live
        suppressed counts merged in."""
        with self._lock:
            ep = self._episode
            live = (dict(ep["suppressed"]), ep["id"]) if ep else None
        bundles = self._bundle_dirs()
        total = sum(s for _, s in bundles)
        rows = []
        now = time.time()
        for name, size in reversed(bundles):
            if limit is not None and len(rows) >= limit:
                break
            row = {"id": name, "bytes": size, "trigger": None,
                   "captured_at": None, "age_s": None,
                   "recaptures": 0, "suppressed": 0}
            try:
                with open(os.path.join(self.directory, name,
                                       "manifest.json"),
                          encoding="utf8") as f:
                    man = json.load(f)
                row["trigger"] = (man.get("trigger") or {}).get("class")
                row["captured_at"] = man.get("captured_at")
                if row["captured_at"]:
                    row["age_s"] = round(now - row["captured_at"], 1)
                row["recaptures"] = man.get("recaptures", 0)
                suppressed = man.get("suppressed") or {}
                if live is not None and live[1] == name:
                    suppressed = live[0]
                row["suppressed"] = int(sum(suppressed.values()))
                row["correlation"] = man.get("correlation")
            except (OSError, ValueError):
                row["trigger"] = "unreadable"
            rows.append(row)
        return {"incidents": rows, "count": len(bundles),
                "total_bytes": total, "dir": self.directory,
                "enabled": not self.disabled}

    def get(self, incident_id: str, artifact: str | None = None) -> dict:
        """The getincident RPC result: the manifest (always) plus one
        named artifact's content on request.  Raises KeyError on an
        unknown id, ValueError on a malformed id/artifact name."""
        if not _ID_RE.match(incident_id or ""):
            raise ValueError(f"malformed incident id {incident_id!r}")
        if artifact is not None and artifact not in ARTIFACTS:
            raise ValueError(
                f"unknown artifact {artifact!r} (want one of "
                f"{', '.join(ARTIFACTS)})")
        bundle_dir = os.path.join(self.directory, incident_id)
        man_path = os.path.join(bundle_dir, "manifest.json")
        if not os.path.isfile(man_path):
            raise KeyError(incident_id)
        with open(man_path, encoding="utf8") as f:
            out = {"id": incident_id, "manifest": json.load(f)}
        if artifact is not None:
            with open(os.path.join(bundle_dir, artifact),
                      encoding="utf8") as f:
                out["artifact"] = {"name": artifact,
                                   "content": json.load(f)}
        return out


# ---------------------------------------------------------------------------
# process singleton (the RPC surface and tools read this)

_recorder: IncidentRecorder | None = None
_recorder_lock = threading.Lock()


def current() -> IncidentRecorder | None:
    return _recorder


def install(rec: IncidentRecorder | None) -> IncidentRecorder | None:
    """Make `rec` the process's recorder (harnesses install their own;
    None uninstalls).  Does not start/stop it."""
    global _recorder
    with _recorder_lock:
        _recorder = rec
    return rec


def install_from_env(default_dir: str | None = None,
                     **kw) -> IncidentRecorder | None:
    """The daemon entry point's accessor: build + install the singleton
    from the env knobs.  Returns None (and installs nothing) when
    LIGHTNING_TPU_INCIDENT_DISABLE=1 or no directory is resolvable
    (neither LIGHTNING_TPU_INCIDENT_DIR nor a data-dir default)."""
    if os.environ.get("LIGHTNING_TPU_INCIDENT_DISABLE") == "1":
        return None
    directory = os.environ.get("LIGHTNING_TPU_INCIDENT_DIR") or default_dir
    if not directory:
        return None
    return install(IncidentRecorder(directory, **kw))


def reset_for_tests() -> None:
    global _recorder
    with _recorder_lock:
        if _recorder is not None:
            _recorder.stop(timeout=2.0)
        _recorder = None
