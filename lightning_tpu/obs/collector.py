"""Collector: unifies the three pre-existing telemetry silos into the
metrics registry so one scrape answers for all of them.

  * trace spans (utils/trace.py) — every completed span feeds
    ``clntpu_span_duration_seconds{name=...}`` via a trace tap, so span
    timing aggregates survive the span ring's pruning;
  * events bus (utils/events.py)  — every topic emission bumps
    ``clntpu_events_total{topic=...}``;
  * logring (utils/logring.py)   — per-level emit counts plus the
    skip/drop counters are published as gauges at collect time (the
    ring already tracks them; no hot-path hook needed).

Installation is idempotent and survives ``events.reset()`` (tests call
it for isolation): every ``ensure_installed()`` re-checks that the taps
are still attached.
"""
from __future__ import annotations

from . import registry as R


class Collector:
    def __init__(self, reg: R.Registry):
        self.reg = reg
        self._ring = None
        self._span_hist = reg.histogram(
            "clntpu_span_duration_seconds",
            "Duration of completed trace spans, by span name",
            labelnames=("name",), buckets=R.DURATION_BUCKETS)
        self._span_errs = reg.counter(
            "clntpu_span_errors_total",
            "Trace spans that exited with an exception, by span name",
            labelnames=("name",))
        self._events = reg.counter(
            "clntpu_events_total",
            "Events-bus emissions, by topic",
            labelnames=("topic",))
        self._log_entries = reg.gauge(
            "clntpu_log_entries",
            "Entries currently held in the log ring, by level",
            labelnames=("level",))
        self._log_emitted = reg.counter(
            "clntpu_log_emitted_total",
            "Log records accepted into the ring, by level",
            labelnames=("level",))
        self._log_skipped = reg.gauge(
            "clntpu_log_skipped",
            "Log records dropped below the subsystem threshold")

    # -- taps -------------------------------------------------------------

    def _on_span(self, rec: dict) -> None:
        name = rec.get("name", "?")
        self._span_hist.labels(name).observe(
            rec.get("duration_ns", 0) / 1e9)
        if "error" in rec:
            self._span_errs.labels(name).inc()

    def _on_event(self, topic: str, payload: dict) -> None:
        self._events.labels(topic).inc()

    def _on_collect(self) -> None:
        ring = self._ring
        if ring is None:
            return
        from ..utils import logring as LR

        by_level: dict[str, int] = {}
        for e in list(ring.entries):
            lv = LR.level_name(e.levelno)
            by_level[lv] = by_level.get(lv, 0) + 1
        # set EVERY known level, not just the ones present: the bounded
        # ring rotates entries out, and a gauge left at its old value
        # would report a phantom BROKEN entry forever
        for lv in ("IO", "DEBUG", "INFO", "UNUSUAL", "BROKEN"):
            self._log_entries.labels(lv).set(by_level.get(lv, 0))
        self._log_skipped.set(ring.n_skipped)
        # copy: logging threads insert new levels concurrently, and a
        # mid-iteration resize would abort this scrape's log metrics
        for lv, n in dict(getattr(ring, "n_emitted", {})).items():
            c = self._log_emitted.labels(lv)
            delta = n - c.sample()
            if delta > 0:
                c.inc(delta)

    # -- lifecycle --------------------------------------------------------

    def install(self, ring=None) -> None:
        from ..utils import events, trace

        if self._on_span not in getattr(trace, "_taps", ()):
            trace.add_tap(self._on_span)
        if self._on_event not in events._wildcard:
            events.subscribe_all(self._on_event)
        if ring is not None:
            self._ring = ring
        self.reg.on_collect(self._on_collect)

    def uninstall(self) -> None:
        from ..utils import events, trace

        trace.remove_tap(self._on_span)
        events.unsubscribe_all(self._on_event)
        self._ring = None
