"""Declarative RPC command schemas — the single source of truth the
typed client is GENERATED from.

Parity target: doc/schemas/*.json + contrib/msggen (the reference
generates cln-rpc's typed model and the grpc surface from its schema
files; we generate lightning_tpu/clients/generated.py the same way —
edit HERE, then `python -m lightning_tpu.rpcschema.codegen`).

Types: "str" | "int" | "bool" | "hex" (hex-string) | "msat" (int msat)
| "list" | "dict" | "any".  A trailing "?" marks optional params;
result fields are documentation + dataclass members (responses may
carry extra keys; generated classes keep them in `.extra`).
"""

COMMANDS: dict[str, dict] = {
    "getinfo": {
        "params": {},
        "result": {"id": "hex", "version": "str", "num_peers": "int",
                   "num_active_channels": "int", "blockheight": "int",
                   "network": "str"},
    },
    "connect": {
        "params": {"id": "str"},
        "result": {"id": "hex", "features": "hex", "direction": "str"},
    },
    "listpeers": {
        "params": {},
        "result": {"peers": "list"},
    },
    "ping": {
        "params": {"id": "hex", "len": "int?"},
        "result": {"totlen": "int"},
    },
    "newaddr": {
        "params": {"addresstype": "str?"},
        "result": {"bech32": "str"},
    },
    "listfunds": {
        "params": {"spent": "bool?"},
        "result": {"outputs": "list", "channels": "list"},
    },
    "withdraw": {
        "params": {"destination": "str", "satoshi": "any",
                   "feerate": "any?", "minconf": "int?"},
        "result": {"tx": "hex", "txid": "hex"},
    },
    "fundpsbt": {
        "params": {"satoshi": "any", "feerate": "any?",
                   "startweight": "int?", "reserve": "int?"},
        "result": {"psbt": "str", "feerate_per_kw": "int",
                   "excess_msat": "msat"},
    },
    "fundchannel": {
        "params": {"id": "hex", "amount": "any", "push_msat": "int?",
                   "announce": "bool?"},
        "result": {"channel_id": "hex", "funding_txid": "hex",
                   "outnum": "int"},
    },
    "multifundchannel": {
        "params": {"destinations": "list"},
        "result": {"tx": "hex", "txid": "hex", "channel_ids": "list"},
    },
    "splice": {
        "params": {"id": "str", "amount": "any"},
        "result": {"txid": "hex", "channel_id": "hex",
                   "capacity_sat": "int"},
    },
    "close": {
        "params": {"id": "str"},
        "result": {"type": "str", "txid": "hex", "tx": "hex"},
    },
    "listpeerchannels": {
        "params": {"id": "hex?"},
        "result": {"channels": "list"},
    },
    "invoice": {
        "params": {"amount_msat": "any", "label": "str",
                   "description": "str", "expiry": "int?"},
        "result": {"bolt11": "str", "payment_hash": "hex",
                   "payment_secret": "hex", "expires_at": "int"},
    },
    "listinvoices": {
        "params": {"label": "str?"},
        "result": {"invoices": "list"},
    },
    "pay": {
        "params": {"bolt11": "str", "amount_msat": "int?",
                   "retry_for": "int?", "maxfeepercent": "any?",
                   "maxfee": "msat?"},
        "result": {"payment_preimage": "hex", "payment_hash": "hex",
                   "amount_msat": "msat", "amount_sent_msat": "msat",
                   "status": "str"},
    },
    "xpay": {
        "params": {"invstring": "str", "amount_msat": "int?",
                   "retry_for": "int?", "maxfee": "int?"},
        "result": {"payment_preimage": "hex", "payment_hash": "hex",
                   "amount_msat": "msat", "amount_sent_msat": "msat",
                   "status": "str"},
    },
    "listpays": {
        "params": {"bolt11": "str?"},
        "result": {"pays": "list"},
    },
    "decode": {
        "params": {"string": "str"},
        "result": {"type": "str", "valid": "bool"},
    },
    "getroute": {
        "params": {"id": "hex", "amount_msat": "int",
                   "riskfactor": "int?", "cltv": "int?",
                   "fromid": "hex?"},
        "result": {"route": "list"},
    },
    "txprepare": {
        "params": {"outputs": "list", "feerate": "any?"},
        "result": {"txid": "hex", "unsigned_tx": "hex", "psbt": "str"},
    },
    "txsend": {
        "params": {"txid": "hex"},
        "result": {"txid": "hex", "tx": "hex"},
    },
    "txdiscard": {
        "params": {"txid": "hex"},
        "result": {"txid": "hex"},
    },
    "multiwithdraw": {
        "params": {"outputs": "list", "feerate": "any?"},
        "result": {"txid": "hex", "tx": "hex"},
    },
    "offer": {
        "params": {"amount": "any", "description": "str?",
                   "issuer": "str?", "label": "str?",
                   "quantity_max": "int?", "single_use": "bool?",
                   "recurrence": "str?", "recurrence_limit": "int?"},
        "result": {"offer_id": "hex", "bolt12": "str", "active": "bool"},
    },
    "fetchinvoice": {
        # NOTE: new params append AFTER the pre-existing ones —
        # protogen derives protobuf field numbers from dict order, so
        # inserting mid-dict would renumber the wire format under
        # already-compiled binrpc clients
        "params": {"offer": "str", "amount_msat": "int?",
                   "quantity": "int?", "timeout": "int?",
                   "payer_note": "str?", "recurrence_counter": "int?",
                   "recurrence_start": "int?",
                   "recurrence_label": "str?"},
        "result": {"invoice": "str", "amount_msat": "msat",
                   "payment_hash": "hex"},
    },
    "cancelrecurringinvoice": {
        "params": {"offer": "str", "recurrence_counter": "int",
                   "recurrence_label": "str",
                   "recurrence_start": "int?", "payer_note": "str?",
                   "timeout": "int?"},
        "result": {"cancelled": "bool"},
    },
    "waitinvoice": {
        "params": {"label": "str", "timeout": "int?"},
        "result": {"label": "str", "status": "str",
                   "payment_hash": "hex"},
    },
    "waitanyinvoice": {
        "params": {"lastpay_index": "int?", "timeout": "int?"},
        "result": {"label": "str", "status": "str",
                   "pay_index": "int"},
    },
    "delinvoice": {
        "params": {"label": "str", "status": "str?"},
        "result": {"label": "str", "status": "str"},
    },
    "datastore": {
        "params": {"key": "any", "string": "str?", "hex": "hex?",
                   "mode": "str?", "generation": "int?"},
        "result": {"key": "list", "generation": "int", "hex": "hex"},
    },
    "listdatastore": {
        "params": {"key": "any?"},
        "result": {"datastore": "list"},
    },
    "deldatastore": {
        "params": {"key": "any", "generation": "int?"},
        "result": {"key": "list", "generation": "int", "hex": "hex"},
    },
    "keysend": {
        "params": {"destination": "hex", "amount_msat": "any",
                   "retry_for": "int?"},
        "result": {"payment_hash": "hex", "payment_preimage": "hex",
                   "amount_msat": "msat", "status": "str",
                   "destination": "hex"},
    },
    "listhtlcs": {
        "params": {},
        "result": {"htlcs": "list"},
    },
    "listforwards": {
        "params": {},
        "result": {"forwards": "list"},
    },
    "stop": {
        "params": {},
        "result": {"result": "str"},
    },
    "help": {
        "params": {},
        "result": {"help": "list"},
    },
    "check": {
        "params": {"command_to_check": "str"},
        "result": {"command_to_check": "str"},
    },
    "notifications": {
        "params": {"enable": "bool?"},
        "result": {},
    },
    "deprecations": {
        "params": {"enable": "bool?"},
        "result": {},
    },
    "disconnect": {
        "params": {"id": "hex", "force": "bool?"},
        "result": {},
    },
    "sendcustommsg": {
        "params": {"node_id": "hex", "msg": "hex"},
        "result": {"status": "str"},
    },
    "waitblockheight": {
        "params": {"blockheight": "int", "timeout": "int?"},
        "result": {"blockheight": "int"},
    },
    "feerates": {
        "params": {"style": "str?"},
        "result": {"perkw": "dict"},
    },
    "parsefeerate": {
        "params": {"feerate_string": "any"},
        "result": {"perkw": "int"},
    },
    "signmessage": {
        "params": {"message": "str"},
        "result": {"signature": "hex", "recid": "hex", "zbase": "str"},
    },
    "checkmessage": {
        "params": {"message": "str", "zbase": "str", "pubkey": "hex?"},
        "result": {"pubkey": "hex", "verified": "bool"},
    },
    "makesecret": {
        "params": {"hex": "hex?", "string": "str?"},
        "result": {"secret": "hex"},
    },
    "addgossip": {
        "params": {"message": "hex"},
        "result": {},
    },
    "listclosedchannels": {
        "params": {"id": "hex?"},
        "result": {"closedchannels": "list"},
    },
    "delforward": {
        "params": {"in_channel": "any?", "in_htlc_id": "int?",
                   "status": "str?"},
        "result": {"deleted": "int"},
    },
    "delpay": {
        "params": {"payment_hash": "hex", "status": "str"},
        "result": {"payments": "list"},
    },
    "wait": {
        "params": {"subsystem": "str", "indexname": "str",
                   "nextvalue": "int"},
        "result": {"subsystem": "str"},
    },
    "preapproveinvoice": {
        "params": {"bolt11": "str"},
        "result": {},
    },
    "preapprovekeysend": {
        "params": {"destination": "hex", "payment_hash": "hex",
                   "amount_msat": "msat"},
        "result": {},
    },
    "upgradewallet": {
        "params": {"reserved_ok": "bool?"},
        "result": {"upgraded_outs": "int"},
    },
    "listconfigs": {
        "params": {"config": "str?"},
        "result": {"configs": "dict"},
    },
    "setconfig": {
        "params": {"config": "str", "val": "any?"},
        "result": {"config": "dict"},
    },
    "getlog": {
        "params": {"level": "str?"},
        "result": {"log": "list"},
    },
    "getmetrics": {
        "params": {},
        "result": {"metrics": "dict", "resilience": "dict",
                   "dispatches": "dict"},
        # overload + perf sections ride in `.extra` (result fields are
        # documentation; unschema'd keys cross both transports intact)
    },
    "getperf": {
        "params": {"family": "str?", "kernel_rate": "any?"},
        "result": {"generated_at": "any", "epsilon": "any",
                   "kernel_rate": "any", "families": "dict",
                   "retraces": "dict", "device_memory": "dict"},
    },
    "gethealth": {
        "params": {"series": "list?", "points": "int?"},
        "result": {"running": "bool", "state": "str",
                   "state_code": "int", "ticks": "int",
                   "breached": "list", "slos": "dict", "rates": "dict"},
        # burn rates, breaker/overload taps, and requested time-series
        # ring extracts ride in `.extra` (doc/health.md)
    },
    "listdispatches": {
        "params": {"family": "str?", "limit": "int?"},
        "result": {"dispatches": "list", "ring_size": "int"},
    },
    "listincidents": {
        "params": {"limit": "int?"},
        "result": {"incidents": "list", "count": "int",
                   "total_bytes": "int", "dir": "str?",
                   "enabled": "bool"},
    },
    "getincident": {
        "params": {"id": "str", "artifact": "str?"},
        "result": {"id": "str", "manifest": "dict"},
        # the requested artifact's content rides in `.extra`
        # (doc/incidents.md for the bundle layout)
    },
    "gettrace": {
        "params": {"dispatches": "int?"},
        "result": {"traceEvents": "list", "displayTimeUnit": "str"},
    },
    "getjourney": {
        "params": {"scid": "any?", "payment_hash": "hex?",
                   "node_id": "hex?", "limit": "int?"},
        "result": {"enabled": "bool", "summary": "dict",
                   "journeys": "list"},
        # per-entity hop records with dispatch_ids resolvable against
        # listdispatches (doc/journeys.md)
    },
    "listnodes": {
        "params": {},
        "result": {"nodes": "list"},
    },
    "listchannels": {
        "params": {},
        "result": {"channels": "list"},
    },
    "loadgossip": {
        "params": {"path": "str"},
        "result": {"channels": "int", "nodes": "int"},
    },
    "plugin": {
        "params": {"subcommand": "str?", "plugin": "str?"},
        "result": {"plugins": "list"},
    },
    "fundchannel_start": {
        "params": {"id": "hex", "amount": "any", "push_msat": "int?",
                   "announce": "bool?"},
        "result": {"funding_address": "str", "scriptpubkey": "hex"},
    },
    "fundchannel_complete": {
        "params": {"id": "hex", "psbt": "str"},
        "result": {"channel_id": "hex", "commitments_secured": "bool"},
    },
    "openchannel_init": {
        "params": {"id": "hex", "amount": "any", "initialpsbt": "str",
                   "announce": "bool?", "funding_feerate": "any?"},
        "result": {"channel_id": "hex", "psbt": "str",
                   "commitments_secured": "bool", "funding_outnum": "int"},
    },
    "openchannel_update": {
        "params": {"channel_id": "hex", "psbt": "str?"},
        "result": {"channel_id": "hex", "psbt": "str",
                   "commitments_secured": "bool", "funding_outnum": "int"},
    },
    "openchannel_signed": {
        "params": {"channel_id": "hex", "signed_psbt": "str"},
        "result": {"channel_id": "hex", "tx": "hex", "txid": "hex"},
    },
    "openchannel_abort": {
        "params": {"channel_id": "hex"},
        "result": {"channel_id": "hex", "channel_canceled": "bool"},
    },
    "fundchannel_cancel": {
        "params": {"id": "hex"},
        "result": {"cancelled": "str"},
    },
    "renepay": {
        "params": {"invstring": "str", "amount_msat": "int?",
                   "retry_for": "int?"},
        "result": {"payment_preimage": "hex", "payment_hash": "hex",
                   "status": "str"},
    },
    "renepaystatus": {
        "params": {"invstring": "str?"},
        "result": {"paystatus": "list"},
    },
    "createonion": {
        "params": {"hops": "list", "assocdata": "hex",
                   "session_key": "hex?"},
        "result": {"onion": "hex", "shared_secrets": "list"},
    },
    "sendonion": {
        "params": {"onion": "hex", "first_hop": "dict",
                   "payment_hash": "hex", "amount_msat": "int?",
                   "shared_secrets": "list?"},
        "result": {"payment_hash": "hex", "status": "str"},
    },
    "sendpay": {
        "params": {"route": "list", "payment_hash": "hex",
                   "payment_secret": "hex?", "amount_msat": "int?"},
        "result": {"payment_hash": "hex", "status": "str"},
    },
    "waitsendpay": {
        "params": {"payment_hash": "hex", "timeout": "int?",
                   "partid": "int?", "groupid": "int?"},
        "result": {"payment_hash": "hex", "status": "str",
                   "payment_preimage": "hex"},
    },
    "listsendpays": {
        "params": {"bolt11": "str?"},
        "result": {"payments": "list"},
    },
    "setchannel": {
        "params": {"feebase": "int?", "feeppm": "int?",
                   "cltv_delta": "int?"},
        "result": {"fee_base_msat": "msat",
                   "fee_proportional_millionths": "int",
                   "cltv_delta": "int"},
    },
    "createinvoice": {
        "params": {"invstring": "str", "label": "str", "preimage": "hex"},
        "result": {"label": "str", "bolt11": "str",
                   "payment_hash": "hex", "status": "str"},
    },
    "signinvoice": {
        "params": {"invstring": "str"},
        "result": {"bolt11": "str"},
    },
    "decodepay": {
        "params": {"bolt11": "str"},
        "result": {"type": "str", "valid": "bool"},
    },
    "invoicerequest": {
        "params": {"amount_msat": "msat", "description": "str",
                   "issuer": "str?", "label": "str?",
                   "single_use": "bool?"},
        "result": {"invreq_id": "hex", "bolt12": "str",
                   "active": "bool", "single_use": "bool",
                   "used": "bool"},
    },
    "listinvoicerequests": {
        "params": {"invreq_id": "hex?"},
        "result": {"invoicerequests": "list"},
    },
    "disableinvoicerequest": {
        "params": {"invreq_id": "hex"},
        "result": {"invreq_id": "hex", "active": "bool"},
    },
    "sendinvoice": {
        "params": {"invreq": "str", "label": "str",
                   "amount_msat": "int?"},
        "result": {"bolt12": "str", "payment_hash": "hex",
                   "amount_msat": "msat", "label": "str"},
    },
    "sendonionmessage": {
        "params": {"node_ids": "list", "content": "dict?"},
        "result": {"sent": "bool"},
    },
    "listoffers": {
        "params": {},
        "result": {"offers": "list"},
    },
    "disableoffer": {
        "params": {"offer_id": "hex"},
        "result": {"offer_id": "hex", "active": "bool"},
    },
    "signpsbt": {
        "params": {"psbt": "str", "signonly": "list?"},
        "result": {"signed_psbt": "str"},
    },
    "sendpsbt": {
        "params": {"psbt": "str", "reserve": "bool?"},
        "result": {"tx": "hex", "txid": "hex"},
    },
    "utxopsbt": {
        "params": {"satoshi": "any", "feerate": "any?",
                   "startweight": "int?", "utxos": "list?",
                   "reserve": "int?", "reservedok": "bool?"},
        "result": {"psbt": "str", "feerate_per_kw": "int",
                   "excess_msat": "msat"},
    },
    "addpsbtoutput": {
        "params": {"satoshi": "int", "psbt": "str?",
                   "destination": "str?"},
        "result": {"psbt": "str", "outnum": "int"},
    },
    "listtransactions": {
        "params": {},
        "result": {"transactions": "list"},
    },
    "listaddresses": {
        "params": {},
        "result": {"addresses": "list"},
    },
    "reserveinputs": {
        "params": {"psbt": "str?", "outpoints": "list?",
                   "exclusive": "bool?", "reserve": "int?"},
        "result": {"reservations": "list"},
    },
    "unreserveinputs": {
        "params": {"psbt": "str?", "outpoints": "list?"},
        "result": {"reservations": "list"},
    },
    "createrune": {
        "params": {"restrictions": "list?"},
        "result": {"rune": "str", "unique_id": "int"},
    },
    "checkrune": {
        "params": {"rune": "str", "method": "str?", "params": "dict?",
                   "nodeid": "hex?"},
        "result": {"valid": "bool"},
    },
    "showrunes": {
        "params": {"rune": "str?"},
        "result": {"runes": "list"},
    },
    "blacklistrune": {
        "params": {"start": "int", "end": "int?"},
        "result": {"blacklist": "list"},
    },
    "commando": {
        "params": {"peer_id": "hex", "method": "str",
                   "params": "dict?", "rune": "str?"},
        "result": {},
    },
    "commando-rune": {
        "params": {"restrictions": "list?"},
        "result": {"rune": "str", "unique_id": "int"},
    },
    "commando-listrunes": {
        "params": {"rune": "str?"},
        "result": {"runes": "list"},
    },
    "commando-blacklist": {
        "params": {"start": "int", "end": "int?"},
        "result": {"blacklist": "list"},
    },
    "getroutes": {
        "params": {"source": "hex", "destination": "hex",
                   "amount_msat": "msat", "maxfee_msat": "int?",
                   "final_cltv": "int?", "max_parts": "int?",
                   "layers": "list?"},
        "result": {"routes": "list"},
    },
    "askrene-reserve": {
        "params": {"path": "list", "layer": "str?"},
        "result": {"reserved": "int"},
    },
    "askrene-unreserve": {
        "params": {"path": "list", "layer": "str?"},
        "result": {"unreserved": "int"},
    },
    "askrene-bias-channel": {
        "params": {"short_channel_id": "any", "bias": "int",
                   "layer": "str?"},
        "result": {"biases": "int"},
    },
    "askrene-disable-channel": {
        "params": {"short_channel_id": "any", "layer": "str?"},
        "result": {"disabled": "int"},
    },
    "askrene-create-layer": {
        "params": {"layer": "str", "persistent": "bool?"},
        "result": {"layers": "list"},
    },
    "askrene-remove-layer": {
        "params": {"layer": "str"},
        "result": {},
    },
    "askrene-listlayers": {
        "params": {"layer": "str?"},
        "result": {"layers": "list"},
    },
    "askrene-inform-channel": {
        "params": {"short_channel_id": "any", "direction": "int",
                   "layer": "str?", "amount_msat": "int?",
                   "inform": "str?"},
        "result": {"constraints": "list"},
    },
    "askrene-age": {
        "params": {"layer": "str?", "cutoff": "any?"},
        "result": {"layer": "str", "num_removed": "int"},
    },
    "autoclean-configure": {
        "params": {"subsystem": "str?", "age": "int?"},
        "result": {"autoclean": "dict"},
    },
    "autoclean-once": {
        "params": {"subsystem": "str?", "age": "int?"},
        "result": {"autoclean": "dict"},
    },
    "autoclean-status": {
        "params": {},
        "result": {"autoclean": "dict"},
    },
    "bkpr-listaccountevents": {
        "params": {"account": "str?"},
        "result": {"events": "list"},
    },
    "bkpr-listbalances": {
        "params": {},
        "result": {"accounts": "list"},
    },
    "bkpr-listincome": {
        "params": {},
        "result": {"income_events": "list"},
    },
    "sql": {
        "params": {"query": "str"},
        "result": {"rows": "list"},
    },
    "staticbackup": {
        "params": {},
        "result": {"scb": "hex"},
    },
    "emergencyrecover": {
        "params": {"scb": "hex?"},
        "result": {"stubs": "list"},
    },
    "getemergencyrecoverdata": {
        "params": {},
        "result": {"filedata": "hex"},
    },
    "recover": {
        "params": {"hsmsecret": "str?"},
        "result": {},
    },
    "exposesecret": {
        "params": {"passphrase": "str?"},
        "result": {},
    },
    "funderupdate": {
        "params": {"policy": "str?", "policy_mod": "int?",
                   "min_their_funding_msat": "int?",
                   "max_their_funding_msat": "int?"},
        "result": {"policy": "str"},
    },
    "dev-faucet": {
        "params": {"satoshi": "int"},
        "result": {},
    },
    "dev-generate": {
        "params": {"blocks": "int?"},
        "result": {},
    },
    "currencyconvert": {
        "params": {"amount": "any", "currency": "str"},
        "result": {"msat": "msat"},
    },
    "currencyrates": {
        "params": {"currency": "str"},
        "result": {"rates": "dict", "median": "any"},
    },
    "lsps-listprotocols": {
        "params": {"peer_id": "hex"},
        "result": {"protocols": "list"},
    },
    "lsps1-getinfo": {
        "params": {"peer_id": "hex"},
        "result": {"options": "dict"},
    },
    "lsps1-createorder": {
        "params": {"peer_id": "hex", "lsp_balance_sat": "any",
                   "announce_channel": "bool?"},
        "result": {"order_id": "str", "order_state": "str",
                   "payment": "dict"},
    },
    "lsps1-getorder": {
        "params": {"peer_id": "hex", "order_id": "str"},
        "result": {"order_id": "str", "order_state": "str",
                   "payment": "dict", "channel": "dict"},
    },
    "lsps2-getinfo": {
        "params": {"peer_id": "hex"},
        "result": {"opening_fee_params_menu": "list"},
    },
    "lsps2-buy": {
        "params": {"peer_id": "hex", "opening_fee_params": "dict",
                   "payment_size_msat": "any?"},
        "result": {"jit_channel_scid": "str",
                   "lsp_cltv_expiry_delta": "int",
                   "client_trusts_lsp": "bool"},
    },
    # -- round-5 surface growth (reference schema names) ------------------
    "bkpr-inspect": {
        "params": {"account": "str"},
        "result": {"txs": "list"},
    },
    "bkpr-channelsapy": {
        "params": {},
        "result": {"channels_apy": "list"},
    },
    "bkpr-dumpincomecsv": {
        "params": {"csv_format": "str?", "csv_file": "str?"},
        "result": {"csv_format": "str", "csv_file": "str", "csv": "str"},
    },
    "bkpr-editdescriptionbyoutpoint": {
        "params": {"outpoint": "str", "description": "str"},
        "result": {"updated": "list"},
    },
    "bkpr-editdescriptionbypaymentid": {
        "params": {"payment_id": "str", "description": "str"},
        "result": {"updated": "list"},
    },
    "listchainmoves": {
        "params": {},
        "result": {"chain_moves": "list"},
    },
    "listchannelmoves": {
        "params": {},
        "result": {"channel_moves": "list"},
    },
    "askrene-create-channel": {
        "params": {"layer": "str", "source": "hex", "destination": "hex",
                   "short_channel_id": "any", "capacity_msat": "msat"},
        "result": {"channels": "list"},
    },
    "askrene-update-channel": {
        "params": {"layer": "str", "short_channel_id_dir": "any",
                   "enabled": "bool?", "htlc_minimum_msat": "msat?",
                   "htlc_maximum_msat": "msat?", "fee_base_msat": "msat?",
                   "fee_proportional_millionths": "int?",
                   "cltv_expiry_delta": "int?"},
        "result": {"channel_updates": "list"},
    },
    "askrene-remove-channel-update": {
        "params": {"layer": "str", "short_channel_id_dir": "any"},
        "result": {},
    },
    "askrene-disable-node": {
        "params": {"layer": "str", "node": "hex"},
        "result": {"disabled_nodes": "int"},
    },
    "askrene-bias-node": {
        "params": {"node": "hex", "bias": "int", "layer": "str?"},
        "result": {"biases": "list"},
    },
    "askrene-listreservations": {
        "params": {"layer": "str?"},
        "result": {"reservations": "list"},
    },
    "listsqlschemas": {
        "params": {"table": "str?"},
        "result": {"schemas": "list"},
    },
    "sql-template": {
        "params": {"template": "str", "params": "list?"},
        "result": {"rows": "list"},
    },
    "currencyrate": {
        "params": {"currency": "str", "source": "str?"},
        "result": {"currency": "str", "rate": "any"},
    },
    "listcurrencyrates": {
        "params": {"currency": "str"},
        "result": {"rates": "list"},
    },
    "datastoreusage": {
        "params": {"key": "any?"},
        "result": {"datastoreusage": "dict"},
    },
    "enableoffer": {
        "params": {"offer_id": "hex"},
        "result": {"offer_id": "hex", "active": "bool"},
    },
    "recoverchannel": {
        "params": {"scb": "list"},
        "result": {"stubs": "list"},
    },
    "signmessagewithkey": {
        "params": {"message": "str", "address": "str"},
        "result": {"address": "str", "pubkey": "hex",
                   "signature": "str"},
    },
    "listnetworkevents": {
        "params": {"id": "str?", "start": "int?", "limit": "int?"},
        "result": {"networkevents": "list"},
    },
    "delnetworkevent": {
        "params": {"created_index": "int"},
        "result": {"deleted": "dict"},
    },
    "batching": {
        "params": {"enable": "bool?"},
        "result": {},
    },
    "fetchbip353": {
        "params": {"address": "str"},
        "result": {"address": "str", "instructions": "dict"},
    },
    "reckless": {
        "params": {"subcommand": "str", "target": "str?",
                   "lightning_dir": "str?"},
        "result": {},
    },
    "xkeysend": {
        "params": {"destination": "hex", "amount_msat": "any",
                   "retry_for": "int?"},
        "result": {"payment_hash": "hex", "status": "str",
                   "payment_preimage": "hex"},
    },
    "sendamount": {
        "params": {"invstring": "str", "amount_msat": "any",
                   "retry_for": "int?"},
        "result": {"payment_hash": "hex", "status": "str",
                   "amount_msat": "msat", "amount_sent_msat": "msat"},
    },
    "injectpaymentonion": {
        "params": {"onion": "hex", "payment_hash": "hex",
                   "amount_msat": "any", "cltv_expiry": "int",
                   "partid": "int?", "groupid": "int?"},
        "result": {"payment_hash": "hex", "status": "str"},
    },
    "dev-forget-channel": {
        "params": {"id": "hex", "channel_id": "hex?", "force": "bool?"},
        "result": {"forced": "bool", "forgotten": "hex"},
    },
    "openchannel_bump": {
        "params": {"channel_id": "hex", "amount": "any",
                   "initialpsbt": "str", "funding_feerate": "int"},
        "result": {"channel_id": "hex", "tx": "hex", "txid": "hex",
                   "commitments_secured": "bool"},
    },
    "graceful": {
        "params": {"timeout": "int?", "cancel": "bool?"},
        "result": {},
    },
    "injectonionmessage": {
        "params": {"message": "hex", "path_key": "hex"},
        "result": {},
    },
    "clnrest-register-path": {
        "params": {"path": "str", "method": "str"},
        "result": {"path": "str", "method": "str"},
    },
    "splice_init": {
        "params": {"channel_id": "hex", "relative_amount": "any",
                   "initialpsbt": "str?", "feerate_per_kw": "int?"},
        "result": {"channel_id": "hex", "psbt": "str",
                   "commitments_secured": "bool"},
    },
    "splice_update": {
        "params": {"channel_id": "hex", "psbt": "str?"},
        "result": {"channel_id": "hex", "psbt": "str",
                   "commitments_secured": "bool"},
    },
    "splice_signed": {
        "params": {"channel_id": "hex", "psbt": "str"},
        "result": {"channel_id": "hex", "tx": "hex", "txid": "hex"},
    },
    "splicein": {
        "params": {"channel": "str", "amount": "any"},
        "result": {"txid": "hex", "channel_id": "hex",
                   "capacity_sat": "int"},
    },
    "spliceout": {
        "params": {"channel": "str", "amount": "any",
                   "destination": "str?"},
        "result": {"txid": "hex", "channel_id": "hex",
                   "capacity_sat": "int", "outnum": "int"},
    },
    "createproof": {
        "params": {"invstring": "str", "note": "str?"},
        "result": {"proofs": "list"},
    },
    "setpsbtversion": {
        "params": {"psbt": "str", "version": "int"},
        "result": {"psbt": "str"},
    },
    "dev-splice": {
        "params": {"script_or_json": "str", "dryrun": "bool?"},
        "result": {"actions": "list"},
    },
    "bkpr-report": {
        "params": {"format": "str?", "headers": "bool?",
                   "escape": "str?", "start_time": "int?",
                   "end_time": "int?"},
        "result": {"report": "list", "total_income_msat": "msat",
                   "total_expense_msat": "msat", "net_msat": "msat"},
    },
}

_PY_TYPES = {"str": "str", "int": "int", "bool": "bool", "hex": "str",
             "msat": "int", "list": "list", "dict": "dict", "any": "object"}


def py_type(t: str) -> str:
    return _PY_TYPES[t.rstrip("?")]


def is_optional(t: str) -> bool:
    return t.endswith("?")
