"""Declarative RPC command schemas — the single source of truth the
typed client is GENERATED from.

Parity target: doc/schemas/*.json + contrib/msggen (the reference
generates cln-rpc's typed model and the grpc surface from its schema
files; we generate lightning_tpu/clients/generated.py the same way —
edit HERE, then `python -m lightning_tpu.rpcschema.codegen`).

Types: "str" | "int" | "bool" | "hex" (hex-string) | "msat" (int msat)
| "list" | "dict" | "any".  A trailing "?" marks optional params;
result fields are documentation + dataclass members (responses may
carry extra keys; generated classes keep them in `.extra`).
"""

COMMANDS: dict[str, dict] = {
    "getinfo": {
        "params": {},
        "result": {"id": "hex", "version": "str", "num_peers": "int",
                   "num_active_channels": "int", "blockheight": "int",
                   "network": "str"},
    },
    "connect": {
        "params": {"id": "str"},
        "result": {"id": "hex", "features": "hex", "direction": "str"},
    },
    "listpeers": {
        "params": {},
        "result": {"peers": "list"},
    },
    "ping": {
        "params": {"id": "hex", "len": "int?"},
        "result": {"totlen": "int"},
    },
    "newaddr": {
        "params": {"addresstype": "str?"},
        "result": {"bech32": "str"},
    },
    "listfunds": {
        "params": {"spent": "bool?"},
        "result": {"outputs": "list", "channels": "list"},
    },
    "withdraw": {
        "params": {"destination": "str", "satoshi": "any",
                   "feerate": "any?", "minconf": "int?"},
        "result": {"tx": "hex", "txid": "hex"},
    },
    "fundpsbt": {
        "params": {"satoshi": "any", "feerate": "any?",
                   "startweight": "int?", "reserve": "int?"},
        "result": {"psbt": "str", "feerate_per_kw": "int",
                   "excess_msat": "msat"},
    },
    "fundchannel": {
        "params": {"id": "hex", "amount": "any", "push_msat": "int?",
                   "announce": "bool?"},
        "result": {"channel_id": "hex", "funding_txid": "hex",
                   "outnum": "int"},
    },
    "multifundchannel": {
        "params": {"destinations": "list"},
        "result": {"tx": "hex", "txid": "hex", "channel_ids": "list"},
    },
    "splice": {
        "params": {"id": "str", "amount": "any"},
        "result": {"txid": "hex", "channel_id": "hex",
                   "capacity_sat": "int"},
    },
    "close": {
        "params": {"id": "str"},
        "result": {"type": "str", "txid": "hex", "tx": "hex"},
    },
    "listpeerchannels": {
        "params": {"id": "hex?"},
        "result": {"channels": "list"},
    },
    "invoice": {
        "params": {"amount_msat": "any", "label": "str",
                   "description": "str", "expiry": "int?"},
        "result": {"bolt11": "str", "payment_hash": "hex",
                   "payment_secret": "hex", "expires_at": "int"},
    },
    "listinvoices": {
        "params": {"label": "str?"},
        "result": {"invoices": "list"},
    },
    "pay": {
        "params": {"bolt11": "str", "amount_msat": "int?",
                   "retry_for": "int?"},
        "result": {"payment_preimage": "hex", "payment_hash": "hex",
                   "amount_msat": "msat", "amount_sent_msat": "msat",
                   "status": "str"},
    },
    "xpay": {
        "params": {"invstring": "str", "amount_msat": "int?",
                   "retry_for": "int?"},
        "result": {"payment_preimage": "hex", "payment_hash": "hex",
                   "amount_msat": "msat", "amount_sent_msat": "msat",
                   "status": "str"},
    },
    "listpays": {
        "params": {"bolt11": "str?"},
        "result": {"pays": "list"},
    },
    "decode": {
        "params": {"string": "str"},
        "result": {"type": "str", "valid": "bool"},
    },
    "getroute": {
        "params": {"id": "hex", "amount_msat": "int",
                   "riskfactor": "int?", "cltv": "int?",
                   "fromid": "hex?"},
        "result": {"route": "list"},
    },
    "txprepare": {
        "params": {"outputs": "list", "feerate": "any?"},
        "result": {"txid": "hex", "unsigned_tx": "hex", "psbt": "str"},
    },
    "txsend": {
        "params": {"txid": "hex"},
        "result": {"txid": "hex", "tx": "hex"},
    },
    "txdiscard": {
        "params": {"txid": "hex"},
        "result": {"txid": "hex"},
    },
    "multiwithdraw": {
        "params": {"outputs": "list", "feerate": "any?"},
        "result": {"txid": "hex", "tx": "hex"},
    },
    "offer": {
        "params": {"amount": "any", "description": "str?",
                   "issuer": "str?", "label": "str?"},
        "result": {"offer_id": "hex", "bolt12": "str", "active": "bool"},
    },
    "fetchinvoice": {
        "params": {"offer": "str", "amount_msat": "int?",
                   "quantity": "int?", "timeout": "int?"},
        "result": {"invoice": "str", "amount_msat": "msat",
                   "payment_hash": "hex"},
    },
    "waitinvoice": {
        "params": {"label": "str", "timeout": "int?"},
        "result": {"label": "str", "status": "str",
                   "payment_hash": "hex"},
    },
    "waitanyinvoice": {
        "params": {"lastpay_index": "int?", "timeout": "int?"},
        "result": {"label": "str", "status": "str",
                   "pay_index": "int"},
    },
    "delinvoice": {
        "params": {"label": "str", "status": "str?"},
        "result": {"label": "str", "status": "str"},
    },
    "datastore": {
        "params": {"key": "any", "string": "str?", "hex": "hex?",
                   "mode": "str?", "generation": "int?"},
        "result": {"key": "list", "generation": "int", "hex": "hex"},
    },
    "listdatastore": {
        "params": {"key": "any?"},
        "result": {"datastore": "list"},
    },
    "deldatastore": {
        "params": {"key": "any", "generation": "int?"},
        "result": {"key": "list", "generation": "int", "hex": "hex"},
    },
    "keysend": {
        "params": {"destination": "hex", "amount_msat": "any",
                   "retry_for": "int?"},
        "result": {"payment_hash": "hex", "payment_preimage": "hex",
                   "amount_msat": "msat", "status": "str",
                   "destination": "hex"},
    },
    "listhtlcs": {
        "params": {},
        "result": {"htlcs": "list"},
    },
    "listforwards": {
        "params": {},
        "result": {"forwards": "list"},
    },
    "stop": {
        "params": {},
        "result": {"result": "str"},
    },
}

_PY_TYPES = {"str": "str", "int": "int", "bool": "bool", "hex": "str",
             "msat": "int", "list": "list", "dict": "dict", "any": "object"}


def py_type(t: str) -> str:
    return _PY_TYPES[t.rstrip("?")]


def is_optional(t: str) -> bool:
    return t.endswith("?")
