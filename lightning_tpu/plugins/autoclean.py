"""autoclean: periodic deletion of stale node records.

Functional parity target: plugins/autoclean.c — ages (seconds) per
category; 0 disables a category; a cycle timer sweeps
expired invoices, succeeded/failed payments, and resolved forwards,
keeping lifetime deletion counters for autoclean-status.
"""
from __future__ import annotations

import asyncio
import logging
import time

log = logging.getLogger("lightning_tpu.autoclean")

CATEGORIES = ("expiredinvoices", "paidinvoices", "succeededpays",
              "failedpays", "succeededforwards", "failedforwards")


class Autoclean:
    def __init__(self, invoices=None, wallet=None, relay=None,
                 cycle_seconds: float = 3600.0):
        self.invoices = invoices
        self.wallet = wallet
        self.relay = relay
        self.cycle_seconds = cycle_seconds
        self.ages: dict[str, int] = {c: 0 for c in CATEGORIES}
        self.cleaned: dict[str, int] = {c: 0 for c in CATEGORIES}
        self._task: asyncio.Task | None = None

    def configure(self, category: str, age_seconds: int) -> None:
        if category not in CATEGORIES:
            raise ValueError(f"unknown category {category!r}")
        self.ages[category] = age_seconds

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.cycle_seconds)
            try:
                self.clean_once()
            except Exception:
                log.exception("autoclean cycle failed")

    def clean_once(self, now: float | None = None) -> dict[str, int]:
        """One sweep; returns per-category deletions this cycle."""
        now = now if now is not None else time.time()
        done = {c: 0 for c in CATEGORIES}

        if self.invoices is not None:
            for label, rec in list(self.invoices.by_label.items()):
                if rec.status == "expired":
                    cat, ref_t = "expiredinvoices", rec.expires_at
                elif rec.status == "paid":
                    cat, ref_t = "paidinvoices", rec.paid_at or 0
                else:
                    continue
                age = self.ages[cat]
                if age and now - ref_t > age:
                    del self.invoices.by_label[label]
                    self.invoices.by_hash.pop(rec.payment_hash, None)
                    if self.invoices.db is not None:
                        with self.invoices.db.transaction():
                            self.invoices.db.conn.execute(
                                "DELETE FROM invoices WHERE label=?",
                                (label,))
                    done[cat] += 1

        if self.wallet is not None:
            for cat, status in (("succeededpays", "complete"),
                                ("failedpays", "failed")):
                age = self.ages[cat]
                if not age:
                    continue
                with self.wallet.db.transaction():
                    cur = self.wallet.db.conn.execute(
                        "DELETE FROM payments WHERE status=?"
                        " AND completed_at IS NOT NULL"
                        " AND completed_at < ?",
                        (status, int(now - age)))
                done[cat] += cur.rowcount

        if self.relay is not None:
            for cat, status in (("succeededforwards", "settled"),
                                ("failedforwards", "failed")):
                age = self.ages[cat]
                if not age:
                    continue
                # forwards carry no timestamp yet: age>0 sweeps resolved
                before = len(self.relay.forwards)
                self.relay.forwards = [
                    f for f in self.relay.forwards
                    if f.get("status") != status]
                done[cat] += before - len(self.relay.forwards)

        for c, n in done.items():
            self.cleaned[c] += n
        return done


def attach_autoclean_commands(rpc, ac: Autoclean) -> None:
    async def autoclean_status() -> dict:
        return {"autoclean": {
            c: {"enabled": bool(ac.ages[c]), "age": ac.ages[c],
                "cleaned": ac.cleaned[c]} for c in CATEGORIES}}

    async def autoclean_once() -> dict:
        return {"cleaned": ac.clean_once()}

    async def autoclean_configure(category: str, age: int) -> dict:
        ac.configure(category, int(age))
        return {"category": category, "age": int(age)}

    rpc.register("autoclean-status", autoclean_status)
    rpc.register("autoclean-once", autoclean_once)
    rpc.register("autoclean-configure", autoclean_configure)
