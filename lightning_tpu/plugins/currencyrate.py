"""Fiat currency rates: currencyconvert / currencyrates.

Parity target: /root/reference/plugins/currencyrate (queries several
public tickers over HTTPS and serves median rates).  This environment
has zero egress, so the source list is pluggable: the `http` source
speaks real HTTP/1.1 over asyncio streams (tested against an
in-process server; point it at a ticker when egress exists), and the
`static` source serves operator-configured rates (the offline
fallback).  Medianing across sources matches the reference.
"""
from __future__ import annotations

import asyncio
import json
import logging
import statistics

log = logging.getLogger("lightning_tpu.currencyrate")

MSAT_PER_BTC = 100_000_000_000


class RateError(Exception):
    pass


async def http_get_json(host: str, port: int, path: str,
                        timeout: float = 10.0, tls: bool = False) -> dict:
    """Minimal HTTP/1.1 GET → parsed JSON body (Content-Length or
    close-delimited)."""
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port, ssl=tls), timeout)
    try:
        writer.write((f"GET {path} HTTP/1.1\r\nHost: {host}\r\n"
                      "Connection: close\r\n\r\n").encode())
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(-1), timeout)
    finally:
        writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    status = head.split(b"\r\n", 1)[0].split()
    if len(status) < 2 or not status[1].startswith(b"2"):
        raise RateError(f"http status {status[1:2]}")
    if b"chunked" in head.lower():
        # dechunk (tickers often stream chunked)
        out, rest = bytearray(), body
        while rest:
            ln, _, rest = rest.partition(b"\r\n")
            n = int(ln, 16)
            if n == 0:
                break
            out += rest[:n]
            rest = rest[n + 2:]
        body = bytes(out)
    return json.loads(body)


class Source:
    """One rate source; subclasses return BTC price in `currency`."""

    name = "source"

    async def rate(self, currency: str) -> float:
        raise NotImplementedError


class StaticSource(Source):
    """Operator-configured rates (the zero-egress fallback)."""

    name = "static"

    def __init__(self, rates: dict[str, float] | None = None):
        self.rates = {k.upper(): float(v)
                      for k, v in (rates or {}).items()}

    async def rate(self, currency: str) -> float:
        r = self.rates.get(currency.upper())
        if r is None:
            raise RateError(f"no static rate for {currency}")
        return r


class HttpJsonSource(Source):
    """GET {host}{path_template} and walk `field_path` into the JSON
    (e.g. coingecko: path /api/v3/simple/price?ids=bitcoin&
    vs_currencies={currency}, fields ["bitcoin", "{currency}"])."""

    def __init__(self, name: str, host: str, port: int,
                 path_template: str, field_path: list[str],
                 tls: bool = True):
        self.name = name
        self.host = host
        self.port = port
        self.path_template = path_template
        self.field_path = field_path
        self.tls = tls

    async def rate(self, currency: str) -> float:
        cur = currency.lower()
        data = await http_get_json(
            self.host, self.port,
            self.path_template.format(currency=cur), tls=self.tls)
        for key in self.field_path:
            data = data[key.format(currency=cur)]
        return float(data)


class CurrencyRate:
    def __init__(self, sources: list[Source] | None = None):
        self.sources = sources if sources is not None \
            else [StaticSource()]

    async def rates(self, currency: str) -> dict[str, float]:
        """Every source's quote (the reference's listrates shape)."""
        out: dict[str, float] = {}
        results = await asyncio.gather(
            *(s.rate(currency) for s in self.sources),
            return_exceptions=True)
        for s, r in zip(self.sources, results):
            if isinstance(r, BaseException):
                log.info("rate source %s failed: %s", s.name, r)
            else:
                out[s.name] = r
        return out

    async def convert(self, amount: float, currency: str) -> int:
        """amount in `currency` → msat via the MEDIAN across sources
        (currencyrate's aggregation rule)."""
        rates = await self.rates(currency)
        if not rates:
            raise RateError(f"no source could quote {currency}")
        price = statistics.median(rates.values())   # currency per BTC
        return round(amount / price * MSAT_PER_BTC)


def attach_currency_commands(rpc, svc: CurrencyRate) -> None:
    async def currencyconvert(amount, currency: str) -> dict:
        msat = await svc.convert(float(amount), currency)
        return {"msat": msat}

    async def currencyrates(currency: str) -> dict:
        rates = await svc.rates(currency)
        if not rates:
            raise RateError(f"no source could quote {currency}")
        return {"rates": rates,
                "median": statistics.median(rates.values())}

    async def currencyrate(currency: str,
                           source: str | None = None) -> dict:
        """One BTC in `currency` (doc/schemas/currencyrate.json): the
        median across sources, or one named source's quote."""
        rates = await svc.rates(currency)
        if source is not None:
            if source not in rates:
                raise RateError(f"source {source!r} could not quote "
                                f"{currency}")
            return {"currency": currency.upper(), "source": source,
                    "rate": round(rates[source], 3)}
        if not rates:
            raise RateError(f"no source could quote {currency}")
        return {"currency": currency.upper(),
                "rate": round(statistics.median(rates.values()), 3)}

    async def listcurrencyrates(currency: str) -> dict:
        rates = await svc.rates(currency)
        return {"rates": [{"source": s, "currency": currency.upper(),
                           "rate": round(r, 3)}
                          for s, r in sorted(rates.items())]}

    rpc.register("currencyconvert", currencyconvert)
    rpc.register("currencyrates", currencyrates)
    rpc.register("currencyrate", currencyrate)
    rpc.register("listcurrencyrates", listcurrencyrates)
