"""Bookkeeper: double-entry accounting over coin-movement events.

Functional parity target: plugins/bkpr/ (bookkeeper.c + recorder.c:
the accounts/events ledger, listaccountevents, listbalances, income
statements) fed by common/coin_mvt.c's `coin_movement` notifications —
here consumed from the in-process event bus (utils/events.py).

Accounts: "wallet" (on-chain funds), "external" (the rest of the
world), and one account per channel (named by channel id hex).  Every
event credits or debits exactly one account; the invariant
sum(credits) == sum(debits) across the ledger holds because each
emission records both sides' perspective the way coin_mvt.c tags do.

Income statement tags (bkpr income semantics): invoice (received),
invoice_fee (routing fee we paid), routed (forward fee earned),
onchain_fee (close/open fees).
"""
from __future__ import annotations

import asyncio
import time

from ..utils import events


class Bookkeeper:
    """Ledger + query surface.  Pass the wallet Db for persistence, or
    None for an in-memory ledger."""

    def __init__(self, db=None):
        self.db = db
        self.events: list[dict] = []
        if db is not None:
            self._ensure_table()
            for r in db.conn.execute(
                    "SELECT account, tag, credit_msat, debit_msat,"
                    " currency, timestamp, reference, description"
                    " FROM bkpr_events ORDER BY id").fetchall():
                self.events.append({
                    "account": r[0], "tag": r[1], "credit_msat": r[2],
                    "debit_msat": r[3], "currency": r[4],
                    "timestamp": r[5], "reference": r[6],
                    "description": r[7]})
        events.subscribe("coin_movement", self._on_mvt)

    def close(self) -> None:
        events.unsubscribe("coin_movement", self._on_mvt)

    def _ensure_table(self) -> None:
        with self.db.transaction():
            self.db.conn.execute(
                """CREATE TABLE IF NOT EXISTS bkpr_events (
                    id INTEGER PRIMARY KEY,
                    account TEXT NOT NULL,
                    tag TEXT NOT NULL,
                    credit_msat INTEGER NOT NULL DEFAULT 0,
                    debit_msat INTEGER NOT NULL DEFAULT 0,
                    currency TEXT NOT NULL DEFAULT 'bcrt',
                    timestamp INTEGER NOT NULL,
                    reference TEXT,
                    description TEXT
                )""")
            cols = [r[1] for r in self.db.conn.execute(
                "PRAGMA table_info(bkpr_events)").fetchall()]
            if "description" not in cols:   # pre-round-5 table
                self.db.conn.execute(
                    "ALTER TABLE bkpr_events ADD COLUMN description TEXT")

    # -- ingestion ---------------------------------------------------------

    # tags whose movements touch the chain rather than a channel
    # balance (common/coin_mvt.c chain_mvt vs channel_mvt)
    CHAIN_TAGS = ("deposit", "withdrawal", "onchain_fee", "channel_open",
                  "channel_close", "delayed_to_us", "htlc_timeout",
                  "htlc_tx", "anchor", "to_them", "penalty")

    def _on_mvt(self, payload: dict) -> None:
        self.record(
            account=payload.get("account", "wallet"),
            tag=payload.get("tag", "journal"),
            credit_msat=int(payload.get("credit_msat", 0)),
            debit_msat=int(payload.get("debit_msat", 0)),
            reference=payload.get("reference"),
            timestamp=payload.get("timestamp"),
        )

    def record(self, account: str, tag: str, credit_msat: int = 0,
               debit_msat: int = 0, reference: str | None = None,
               timestamp: int | None = None) -> dict:
        ev = {
            "account": account, "tag": tag,
            "credit_msat": credit_msat, "debit_msat": debit_msat,
            "currency": "bcrt",
            "timestamp": int(timestamp if timestamp is not None
                             else time.time()),
            "reference": reference,
            "description": None,
        }
        self.events.append(ev)
        if self.db is not None:
            with self.db.transaction():
                self.db.conn.execute(
                    "INSERT INTO bkpr_events (account, tag, credit_msat,"
                    " debit_msat, currency, timestamp, reference,"
                    " description) VALUES (?,?,?,?,?,?,?,?)",
                    (ev["account"], ev["tag"], ev["credit_msat"],
                     ev["debit_msat"], ev["currency"], ev["timestamp"],
                     ev["reference"], None))
        return ev

    # -- queries (bkpr-* RPC shapes) --------------------------------------

    def listaccountevents(self, account: str | None = None) -> list[dict]:
        return [e for e in self.events
                if account is None or e["account"] == account]

    def listbalances(self) -> list[dict]:
        bal: dict[str, int] = {}
        for e in self.events:
            bal[e["account"]] = (bal.get(e["account"], 0)
                                 + e["credit_msat"] - e["debit_msat"])
        return [{"account": a, "balance_msat": b}
                for a, b in sorted(bal.items())]

    INCOME_TAGS = ("invoice", "routed")
    EXPENSE_TAGS = ("invoice_fee", "onchain_fee", "payment")

    def listincome(self, start: int = 0, end: int | None = None) -> dict:
        """Income statement: credits under income tags minus expense
        debits in [start, end) (bkpr-listincome)."""
        end = end if end is not None else 2 ** 63
        items = []
        income = expense = 0
        for e in self.events:
            if not (start <= e["timestamp"] < end):
                continue
            if e["tag"] in self.INCOME_TAGS and e["credit_msat"]:
                income += e["credit_msat"]
                items.append(e)
            elif e["tag"] in self.EXPENSE_TAGS and e["debit_msat"]:
                expense += e["debit_msat"]
                items.append(e)
        return {"income_events": items, "total_income_msat": income,
                "total_expense_msat": expense,
                "net_msat": income - expense}

    @staticmethod
    def _is_chain(e: dict) -> bool:
        return e["tag"] in Bookkeeper.CHAIN_TAGS or e["account"] in (
            "wallet", "external")

    def listchainmoves(self) -> list[dict]:
        """Movements that touched the chain (bkpr recorder chain_mvt
        rows: deposits, withdrawals, closes, fees)."""
        return [e for e in self.events if Bookkeeper._is_chain(e)]

    def listchannelmoves(self) -> list[dict]:
        """Off-chain balance movements on channel accounts
        (channel_mvt rows: pushes, invoices, routed htlcs)."""
        return [e for e in self.events if not Bookkeeper._is_chain(e)]

    def inspect(self, account: str) -> dict:
        """Events of one channel account grouped by originating tx
        (bkpr-inspect: the channel's on-chain footprint)."""
        txs: dict[str, list[dict]] = {}
        for e in self.events:
            if e["account"] != account:
                continue
            key = (e["reference"] or "").split(":")[0] or "unattributed"
            txs.setdefault(key, []).append(e)
        return {"txs": [{"txid": t, "fees_paid_msat": sum(
            x["debit_msat"] for x in evs if x["tag"] == "onchain_fee"),
            "outputs": evs} for t, evs in sorted(txs.items())]}

    def channelsapy(self) -> list[dict]:
        """Per-channel routing yield (bkpr-channelsapy): fees earned /
        funds deployed, annualized over the account's observed
        lifetime."""
        out = []
        for acct in sorted({e["account"] for e in self.events}):
            if acct in ("wallet", "external"):
                continue
            evs = [e for e in self.events if e["account"] == acct]
            earned = sum(e["credit_msat"] for e in evs
                         if e["tag"] == "routed")
            balance = sum(e["credit_msat"] - e["debit_msat"]
                          for e in evs)
            t0 = min(e["timestamp"] for e in evs)
            t1 = max(e["timestamp"] for e in evs)
            span = max(t1 - t0, 1)
            apy = (earned / balance) * (365 * 86400 / span) * 100 \
                if balance > 0 else 0.0
            out.append({"account": acct,
                        "routed_in_msat": sum(
                            e["credit_msat"] for e in evs
                            if e["tag"] == "routed"),
                        "fees_in_msat": earned,
                        "total_msat": balance,
                        "apy_in": round(apy, 4),
                        "start_time": t0, "end_time": t1})
        return out

    def income_csv(self, csv_format: str = "koinly",
                   start: int = 0, end: int | None = None,
                   headers: bool = True) -> str:
        """Income events as CSV (bkpr-dumpincomecsv formats), over the
        SAME time window listincome uses."""
        import csv
        import io

        buf = io.StringIO()
        w = csv.writer(buf)
        rows = self.listincome(start, end)["income_events"]
        if csv_format == "koinly":
            if headers:
                w.writerow(["Date", "Sent Amount", "Sent Currency",
                            "Received Amount", "Received Currency",
                            "Label", "Description", "TxHash"])
            for e in rows:
                w.writerow([
                    time.strftime("%Y-%m-%d %H:%M UTC",
                                  time.gmtime(e["timestamp"])),
                    e["debit_msat"] / 1e11 or "",
                    "BTC" if e["debit_msat"] else "",
                    e["credit_msat"] / 1e11 or "",
                    "BTC" if e["credit_msat"] else "",
                    e["tag"], e.get("description") or "",
                    e["reference"] or ""])
        else:       # "cointracker" and the generic fallback
            if headers:
                w.writerow(["date", "account", "tag", "credit_msat",
                            "debit_msat", "description", "reference"])
            for e in rows:
                w.writerow([e["timestamp"], e["account"], e["tag"],
                            e["credit_msat"], e["debit_msat"],
                            e.get("description") or "",
                            e["reference"] or ""])
        return buf.getvalue()

    def edit_description(self, match_reference: str,
                         description: str) -> list[dict]:
        """Attach a description to every event whose reference matches
        (bkpr-editdescriptionbyoutpoint / bypaymentid)."""
        hit = []
        for e in self.events:
            if e["reference"] == match_reference:
                e["description"] = description
                hit.append(e)
        if hit and self.db is not None:
            with self.db.transaction():
                self.db.conn.execute(
                    "UPDATE bkpr_events SET description=?"
                    " WHERE reference=?",
                    (description, match_reference))
        return hit


def attach_bookkeeper_commands(rpc, bk: Bookkeeper) -> None:
    async def bkpr_listaccountevents(account: str | None = None) -> dict:
        return {"events": bk.listaccountevents(account)}

    async def bkpr_listbalances() -> dict:
        return {"accounts": bk.listbalances()}

    async def bkpr_listincome(start_time: int = 0,
                              end_time: int | None = None) -> dict:
        return bk.listincome(start_time, end_time)

    async def bkpr_inspect(account: str) -> dict:
        return bk.inspect(account)

    async def bkpr_channelsapy() -> dict:
        return {"channels_apy": bk.channelsapy()}

    async def bkpr_dumpincomecsv(csv_format: str = "koinly",
                                 csv_file: str | None = None) -> dict:
        text = bk.income_csv(csv_format)
        if csv_file:
            # a full income history can be megabytes — write it off
            # the event loop
            def _dump(path: str, body: str) -> None:
                with open(path, "w") as f:
                    f.write(body)

            await asyncio.to_thread(_dump, csv_file, text)
        return {"csv_format": csv_format,
                "csv_file": csv_file or "", "csv": text}

    async def bkpr_editdescriptionbyoutpoint(
            outpoint: str, description: str) -> dict:
        return {"updated": bk.edit_description(outpoint, description)}

    async def bkpr_editdescriptionbypaymentid(
            payment_id: str, description: str) -> dict:
        return {"updated": bk.edit_description(payment_id, description)}

    async def bkpr_report(format: str | None = None,  # noqa: A002
                          headers: bool = True,
                          escape: str | None = None,
                          start_time: int = 0,
                          end_time: int | None = None) -> dict:
        """All income-impacting events in one report (bkpr-report);
        format='csv' returns the CSV text alongside the rows."""
        inc = bk.listincome(start_time, end_time)
        out = {"report": inc["income_events"],
               "total_income_msat": inc["total_income_msat"],
               "total_expense_msat": inc["total_expense_msat"],
               "net_msat": inc["net_msat"]}
        if format == "csv" or escape == "csv":
            # same window as the rows above — the two halves agree
            out["csv"] = bk.income_csv("generic", start_time, end_time,
                                       headers=bool(headers))
        return out

    async def listchainmoves() -> dict:
        return {"chain_moves": bk.listchainmoves()}

    async def listchannelmoves() -> dict:
        return {"channel_moves": bk.listchannelmoves()}

    rpc.register("bkpr-listaccountevents", bkpr_listaccountevents)
    rpc.register("bkpr-listbalances", bkpr_listbalances)
    rpc.register("bkpr-listincome", bkpr_listincome)
    rpc.register("bkpr-inspect", bkpr_inspect)
    rpc.register("bkpr-channelsapy", bkpr_channelsapy)
    rpc.register("bkpr-dumpincomecsv", bkpr_dumpincomecsv)
    rpc.register("bkpr-report", bkpr_report)
    rpc.register("bkpr-editdescriptionbyoutpoint",
                 bkpr_editdescriptionbyoutpoint)
    rpc.register("bkpr-editdescriptionbypaymentid",
                 bkpr_editdescriptionbypaymentid)
    rpc.register("listchainmoves", listchainmoves)
    rpc.register("listchannelmoves", listchannelmoves)
