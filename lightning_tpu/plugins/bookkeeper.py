"""Bookkeeper: double-entry accounting over coin-movement events.

Functional parity target: plugins/bkpr/ (bookkeeper.c + recorder.c:
the accounts/events ledger, listaccountevents, listbalances, income
statements) fed by common/coin_mvt.c's `coin_movement` notifications —
here consumed from the in-process event bus (utils/events.py).

Accounts: "wallet" (on-chain funds), "external" (the rest of the
world), and one account per channel (named by channel id hex).  Every
event credits or debits exactly one account; the invariant
sum(credits) == sum(debits) across the ledger holds because each
emission records both sides' perspective the way coin_mvt.c tags do.

Income statement tags (bkpr income semantics): invoice (received),
invoice_fee (routing fee we paid), routed (forward fee earned),
onchain_fee (close/open fees).
"""
from __future__ import annotations

import time

from ..utils import events


class Bookkeeper:
    """Ledger + query surface.  Pass the wallet Db for persistence, or
    None for an in-memory ledger."""

    def __init__(self, db=None):
        self.db = db
        self.events: list[dict] = []
        if db is not None:
            self._ensure_table()
            for r in db.conn.execute(
                    "SELECT account, tag, credit_msat, debit_msat,"
                    " currency, timestamp, reference FROM bkpr_events"
                    " ORDER BY id").fetchall():
                self.events.append({
                    "account": r[0], "tag": r[1], "credit_msat": r[2],
                    "debit_msat": r[3], "currency": r[4],
                    "timestamp": r[5], "reference": r[6]})
        events.subscribe("coin_movement", self._on_mvt)

    def close(self) -> None:
        events.unsubscribe("coin_movement", self._on_mvt)

    def _ensure_table(self) -> None:
        with self.db.transaction():
            self.db.conn.execute(
                """CREATE TABLE IF NOT EXISTS bkpr_events (
                    id INTEGER PRIMARY KEY,
                    account TEXT NOT NULL,
                    tag TEXT NOT NULL,
                    credit_msat INTEGER NOT NULL DEFAULT 0,
                    debit_msat INTEGER NOT NULL DEFAULT 0,
                    currency TEXT NOT NULL DEFAULT 'bcrt',
                    timestamp INTEGER NOT NULL,
                    reference TEXT
                )""")

    # -- ingestion ---------------------------------------------------------

    def _on_mvt(self, payload: dict) -> None:
        self.record(
            account=payload.get("account", "wallet"),
            tag=payload.get("tag", "journal"),
            credit_msat=int(payload.get("credit_msat", 0)),
            debit_msat=int(payload.get("debit_msat", 0)),
            reference=payload.get("reference"),
            timestamp=payload.get("timestamp"),
        )

    def record(self, account: str, tag: str, credit_msat: int = 0,
               debit_msat: int = 0, reference: str | None = None,
               timestamp: int | None = None) -> dict:
        ev = {
            "account": account, "tag": tag,
            "credit_msat": credit_msat, "debit_msat": debit_msat,
            "currency": "bcrt",
            "timestamp": int(timestamp if timestamp is not None
                             else time.time()),
            "reference": reference,
        }
        self.events.append(ev)
        if self.db is not None:
            with self.db.transaction():
                self.db.conn.execute(
                    "INSERT INTO bkpr_events (account, tag, credit_msat,"
                    " debit_msat, currency, timestamp, reference)"
                    " VALUES (?,?,?,?,?,?,?)",
                    (ev["account"], ev["tag"], ev["credit_msat"],
                     ev["debit_msat"], ev["currency"], ev["timestamp"],
                     ev["reference"]))
        return ev

    # -- queries (bkpr-* RPC shapes) --------------------------------------

    def listaccountevents(self, account: str | None = None) -> list[dict]:
        return [e for e in self.events
                if account is None or e["account"] == account]

    def listbalances(self) -> list[dict]:
        bal: dict[str, int] = {}
        for e in self.events:
            bal[e["account"]] = (bal.get(e["account"], 0)
                                 + e["credit_msat"] - e["debit_msat"])
        return [{"account": a, "balance_msat": b}
                for a, b in sorted(bal.items())]

    INCOME_TAGS = ("invoice", "routed")
    EXPENSE_TAGS = ("invoice_fee", "onchain_fee", "payment")

    def listincome(self, start: int = 0, end: int | None = None) -> dict:
        """Income statement: credits under income tags minus expense
        debits in [start, end) (bkpr-listincome)."""
        end = end if end is not None else 2 ** 63
        items = []
        income = expense = 0
        for e in self.events:
            if not (start <= e["timestamp"] < end):
                continue
            if e["tag"] in self.INCOME_TAGS and e["credit_msat"]:
                income += e["credit_msat"]
                items.append(e)
            elif e["tag"] in self.EXPENSE_TAGS and e["debit_msat"]:
                expense += e["debit_msat"]
                items.append(e)
        return {"income_events": items, "total_income_msat": income,
                "total_expense_msat": expense,
                "net_msat": income - expense}


def attach_bookkeeper_commands(rpc, bk: Bookkeeper) -> None:
    async def bkpr_listaccountevents(account: str | None = None) -> dict:
        return {"events": bk.listaccountevents(account)}

    async def bkpr_listbalances() -> dict:
        return {"accounts": bk.listbalances()}

    async def bkpr_listincome(start_time: int = 0,
                              end_time: int | None = None) -> dict:
        return bk.listincome(start_time, end_time)

    rpc.register("bkpr-listaccountevents", bkpr_listaccountevents)
    rpc.register("bkpr-listbalances", bkpr_listbalances)
    rpc.register("bkpr-listincome", bkpr_listincome)
