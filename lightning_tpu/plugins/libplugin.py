"""Plugin-side framework: write a plugin as decorated Python functions.

Parity target: plugins/libplugin.c (the C framework all in-tree plugins
link against) / contrib/pyln-client's Plugin class — manifest
generation, the getmanifest/init dance, method/hook/subscription
dispatch over the stdin/stdout `\\n\\n`-separated JSON-RPC transport.

Usage (an executable python file):

    from lightning_tpu.plugins.libplugin import Plugin
    p = Plugin()

    @p.method("hello")
    def hello(name="world"):
        return {"greeting": f"hello {name}"}

    @p.hook("htlc_accepted")
    def on_htlc(onion, htlc, **kw):
        return {"result": "continue"}

    @p.subscribe("block_added")
    def on_block(block_added):
        ...

    p.run()
"""
from __future__ import annotations

import inspect
import json
import sys


class Plugin:
    def __init__(self, dynamic: bool = True):
        self.methods: dict[str, object] = {}
        self.method_descs: list[dict] = []
        self.hooks: dict[str, object] = {}
        self.subs: dict[str, object] = {}
        self.options: list[dict] = []
        self.option_values: dict[str, object] = {}
        self.dynamic = dynamic
        self.configuration: dict = {}
        self.on_init = None

    # -- registration decorators -----------------------------------------

    def method(self, name: str, description: str = ""):
        def deco(fn):
            self.methods[name] = fn
            self.method_descs.append(
                {"name": name, "usage": " ".join(
                    inspect.signature(fn).parameters),
                 "description": description or (fn.__doc__ or "")})
            return fn

        return deco

    def hook(self, name: str):
        def deco(fn):
            self.hooks[name] = fn
            return fn

        return deco

    def subscribe(self, topic: str):
        def deco(fn):
            self.subs[topic] = fn
            return fn

        return deco

    def add_option(self, name: str, default=None, description: str = "",
                   opt_type: str = "string") -> None:
        self.options.append({"name": name, "type": opt_type,
                             "default": default,
                             "description": description})

    # -- the stdio loop ---------------------------------------------------

    def _manifest(self) -> dict:
        return {
            "options": self.options,
            "rpcmethods": self.method_descs,
            "hooks": [{"name": h} for h in self.hooks],
            "subscriptions": list(self.subs),
            "dynamic": self.dynamic,
        }

    def _dispatch(self, req: dict):
        method = req["method"]
        params = req.get("params") or {}
        if method == "getmanifest":
            return self._manifest()
        if method == "init":
            self.option_values = params.get("options", {})
            self.configuration = params.get("configuration", {})
            if self.on_init is not None:
                self.on_init(self)
            return {}
        fn = self.methods.get(method) or self.hooks.get(method)
        if fn is None:
            raise ValueError(f"unknown method {method!r}")
        if isinstance(params, list):
            return fn(*params)
        return fn(**params)

    def run(self, infile=None, outfile=None) -> None:
        fin = infile or sys.stdin.buffer
        fout = outfile or sys.stdout.buffer
        buf = b""
        while True:
            chunk = fin.read1(65536) if hasattr(fin, "read1") \
                else fin.read(65536)
            if not chunk:
                return
            buf += chunk
            while b"\n\n" in buf:
                raw, buf = buf.split(b"\n\n", 1)
                if not raw.strip():
                    continue
                req = json.loads(raw)
                rid = req.get("id")
                if rid is None:
                    # notification
                    fn = self.subs.get(req["method"])
                    if fn is not None:
                        try:
                            fn(**(req.get("params") or {}))
                        except Exception:
                            pass
                    continue
                try:
                    result = self._dispatch(req)
                    resp = {"jsonrpc": "2.0", "id": rid, "result": result}
                except Exception as e:
                    resp = {"jsonrpc": "2.0", "id": rid,
                            "error": {"code": -32603, "message": str(e)}}
                fout.write(json.dumps(resp).encode() + b"\n\n")
                fout.flush()
