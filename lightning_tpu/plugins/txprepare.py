"""txprepare/txdiscard/txsend + multiwithdraw + recover + exposesecret.

Parity targets: plugins/txprepare.c (prepare a fully-signed tx with
reserved inputs, send or discard it later), plugins/spender's
multiwithdraw (many destinations, ONE transaction), plugins/recover.c
(kick off recovery from backup material) and plugins/exposesecret.c
(guarded hsm_secret export for disaster backup).
"""
from __future__ import annotations

import asyncio

from ..btc import address as ADDR
from ..btc.tx import Tx, TxOutput
from ..wallet.onchain import OnchainWallet, WalletError


class TxPrepare:
    """Prepared-but-unsent transactions, inputs held reserved."""

    def __init__(self, wallet: OnchainWallet, hsm=None, hsm_client=None,
                 backend=None, topology=None):
        self.wallet = wallet
        self.hsm = hsm
        self.hsm_client = hsm_client
        self.backend = backend
        self.topology = topology
        self.prepared: dict[bytes, tuple[Tx, list]] = {}   # txid -> (tx, utxos)

    def _feerate(self, feerate) -> int:
        from ..wallet.walletrpc import _feerate_per_kw

        return _feerate_per_kw(feerate, self.topology)

    def _sign(self, tx: Tx) -> None:
        meta = self.wallet.utxo_meta(tx)
        if self.hsm is not None:
            self.hsm.sign_withdrawal(self.hsm_client, tx, meta)
        else:
            from ..wallet.onchain import sign_wallet_inputs

            sign_wallet_inputs(tx, meta, self.wallet.keyman)

    def prepare(self, outputs: list[tuple[str, int]],
                feerate=None) -> dict:
        """outputs: [(address, sat)...] → signed tx, inputs reserved."""
        outs = [TxOutput(int(sat),
                         ADDR.to_scriptpubkey(addr, self.wallet.keyman.hrp))
                for addr, sat in outputs]
        tx, picked, _change = self.wallet.fund_tx(
            outs, self._feerate(feerate))
        self._sign(tx)
        txid = tx.txid()
        self.prepared[txid] = (tx, picked)
        return {"txid": txid.hex(), "unsigned_tx": tx.serialize().hex(),
                "psbt": ""}

    def discard(self, txid_hex: str) -> dict:
        txid = bytes.fromhex(txid_hex)
        entry = self.prepared.pop(txid, None)
        if entry is None:
            raise WalletError(f"unknown prepared txid {txid_hex}")
        _tx, picked = entry
        self.wallet.unreserve([u.outpoint for u in picked])
        return {"txid": txid_hex}

    async def send(self, txid_hex: str) -> dict:
        txid = bytes.fromhex(txid_hex)
        entry = self.prepared.pop(txid, None)
        if entry is None:
            raise WalletError(f"unknown prepared txid {txid_hex}")
        tx, picked = entry
        raw = tx.serialize()
        if self.backend is not None:
            ok, err = await self.backend.sendrawtransaction(raw)
            if not ok:
                self.prepared[txid] = entry   # still discardable
                raise WalletError(f"broadcast failed: {err}")
        self.wallet.mark_spent([u.outpoint for u in picked], txid)
        self.wallet.add_unconfirmed_change(tx)
        return {"txid": txid_hex, "tx": raw.hex()}

    async def multiwithdraw(self, outputs: list[tuple[str, int]],
                            feerate=None) -> dict:
        """Many destinations, one tx, broadcast now (spender role)."""
        prep = self.prepare(outputs, feerate)
        return await self.send(prep["txid"])


def attach_txprepare_commands(rpc, prep: TxPrepare, hsm=None,
                              hsm_secret_path: str | None = None) -> None:
    def _parse_outputs(outputs) -> list[tuple[str, int]]:
        out = []
        for o in outputs:
            if isinstance(o, dict):
                ((addr, sat),) = o.items()
            else:
                addr, sat = o
            out.append((addr, int(sat)))
        return out

    async def txprepare(outputs: list, feerate=None) -> dict:
        return prep.prepare(_parse_outputs(outputs), feerate)

    async def txdiscard(txid: str) -> dict:
        return prep.discard(txid)

    async def txsend(txid: str) -> dict:
        return await prep.send(txid)

    async def multiwithdraw(outputs: list, feerate=None) -> dict:
        return await prep.multiwithdraw(_parse_outputs(outputs), feerate)

    async def exposesecret(passphrase: str, identifier: str | None = None
                           ) -> dict:
        """Codex32-free variant of plugins/exposesecret.c: returns the
        hsm secret hex, gated on an explicit passphrase ('expose') so
        no RPC typo can leak it."""
        if passphrase != "expose":
            raise WalletError(
                "exposesecret requires passphrase='expose' (this prints "
                "your node's master secret)")
        if hsm is None:
            raise WalletError("no hsm loaded")
        return {"hsm_secret": hsm._secret.hex()}

    async def recover(hsmsecret: str) -> dict:
        """plugins/recover.c role: validate recovery material and tell
        the operator how to restart into recovery.  (A running node
        cannot hot-swap its identity key; the reference also restarts.)"""
        raw = bytes.fromhex(hsmsecret)
        if len(raw) != 32:
            raise WalletError("hsm_secret must be 32 bytes of hex")
        matches = hsm is not None and raw == hsm._secret
        return {
            "valid": True,
            "matches_running_node": matches,
            "restart_with": "--data-dir <fresh-dir> after writing the "
                            "secret to <fresh-dir>/hsm_secret; channel "
                            "funds then recover via emergencyrecover "
                            "from peer_storage backups",
        }

    rpc.register("txprepare", txprepare)
    rpc.register("txdiscard", txdiscard)
    rpc.register("txsend", txsend)
    rpc.register("multiwithdraw", multiwithdraw)
    rpc.register("exposesecret", exposesecret)
    rpc.register("recover", recover)
