"""Key-path datastore RPC: datastore/listdatastore/deldatastore.

Parity target: lightningd/datastore.c + wallet/datastore.c — an
append-or-replace hierarchical key store plugins use for persistent
state, with generation counters for optimistic concurrency
(must_replace/must_create, generation guards)."""
from __future__ import annotations


class DatastoreError(Exception):
    pass


MIGRATION = """CREATE TABLE IF NOT EXISTS datastore (
    key TEXT PRIMARY KEY,
    data BLOB NOT NULL,
    generation INTEGER NOT NULL DEFAULT 0
)"""


def _key_str(key) -> str:
    """Keys are hierarchical arrays stored as their JSON encoding — a
    separator-based join would let ['a\\x00b'] collide with ['a','b']
    (datastore.c stores the array; a single string is a one-element
    path)."""
    import json

    if isinstance(key, str):
        key = [key]
    return json.dumps([str(k) for k in key])


def _key_list(key_str: str) -> list[str]:
    import json

    return json.loads(key_str)


class Datastore:
    def __init__(self, db):
        self.db = db
        with db.transaction() as c:
            c.execute(MIGRATION)

    def set(self, key, data: bytes, mode: str = "must-create",
            generation: int | None = None) -> dict:
        ks = _key_str(key)
        row = self.db.conn.execute(
            "SELECT generation FROM datastore WHERE key=?",
            (ks,)).fetchone()
        if mode == "must-create" and row is not None:
            raise DatastoreError(f"key {key!r} already exists")
        if mode == "must-replace" and row is None:
            raise DatastoreError(f"key {key!r} does not exist")
        if generation is not None:
            if row is None or row[0] != generation:
                raise DatastoreError(
                    f"generation {generation} does not match "
                    f"{row[0] if row else None}")
        gen = (row[0] + 1) if row is not None else 0
        if mode == "create-or-append" and row is not None:
            old = self.db.conn.execute(
                "SELECT data FROM datastore WHERE key=?",
                (ks,)).fetchone()[0]
            data = bytes(old) + data
        with self.db.transaction() as c:
            c.execute(
                "INSERT INTO datastore (key, data, generation) VALUES"
                " (?,?,?) ON CONFLICT(key) DO UPDATE SET"
                " data=excluded.data, generation=excluded.generation",
                (ks, data, gen))
        return {"key": _key_list(ks), "generation": gen,
                "hex": data.hex()}

    def list(self, key=None) -> list[dict]:
        """datastore.c listing semantics: entries AT the key (with
        data) plus the key's immediate CHILD nodes — interior nodes
        appear once, without data, so callers can walk the hierarchy
        level by level."""
        rows = self.db.conn.execute(
            "SELECT key, data, generation FROM datastore ORDER BY key"
        ).fetchall()
        prefix = _key_list(_key_str(key)) if key else []
        out, interior_seen = [], set()
        for ks, data, gen in rows:
            kl = _key_list(ks)
            if kl[:len(prefix)] != prefix:
                continue
            if len(kl) == len(prefix) and prefix:
                # exact match: the entry itself, with data
                out.append({"key": kl, "generation": gen,
                            "hex": bytes(data).hex()})
            elif len(kl) == len(prefix) + 1:
                # immediate child leaf: with data
                out.append({"key": kl, "generation": gen,
                            "hex": bytes(data).hex()})
            elif len(kl) > len(prefix) + 1:
                # deeper: surface the immediate child as an interior
                # node (no data), once
                child = tuple(kl[:len(prefix) + 1])
                if child not in interior_seen:
                    interior_seen.add(child)
                    out.append({"key": list(child)})
        return out

    def delete(self, key, generation: int | None = None) -> dict:
        ks = _key_str(key)
        row = self.db.conn.execute(
            "SELECT data, generation FROM datastore WHERE key=?",
            (ks,)).fetchone()
        if row is None:
            raise DatastoreError(f"key {key!r} does not exist")
        if generation is not None and row[1] != generation:
            raise DatastoreError(
                f"generation {generation} does not match {row[1]}")
        with self.db.transaction() as c:
            c.execute("DELETE FROM datastore WHERE key=?", (ks,))
        return {"key": _key_list(ks), "generation": row[1],
                "hex": bytes(row[0]).hex()}


def attach_datastore_commands(rpc, store: Datastore) -> None:
    async def datastore(key, string: str | None = None,
                        hex: str | None = None,  # noqa: A002
                        mode: str = "must-create",
                        generation: int | None = None) -> dict:
        if (string is None) == (hex is None):
            raise DatastoreError("pass exactly one of string/hex")
        data = string.encode() if string is not None \
            else bytes.fromhex(hex)
        return store.set(key, data, mode=mode, generation=generation)

    async def listdatastore(key=None) -> dict:
        return {"datastore": store.list(key)}

    async def datastoreusage(key=None) -> dict:
        """Total bytes stored under key — every descendant's data plus
        its key strings (datastore.c json_datastoreusage)."""
        rows = store.db.conn.execute(
            "SELECT key, data FROM datastore").fetchall()
        prefix = _key_list(_key_str(key)) if key else []
        total = 0
        for ks, data in rows:
            kl = _key_list(ks)
            if kl[:len(prefix)] != prefix:
                continue
            total += sum(len(k) for k in kl) + len(data)
        return {"datastoreusage": {
            "key": "[" + ",".join(prefix) + "]",
            "total_bytes": total}}

    async def deldatastore(key, generation: int | None = None) -> dict:
        return store.delete(key, generation=generation)

    rpc.register("datastore", datastore)
    rpc.register("listdatastore", listdatastore)
    rpc.register("deldatastore", deldatastore)
    rpc.register("datastoreusage", datastoreusage)
