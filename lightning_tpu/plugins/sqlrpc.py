"""sql: read-only SQL queries over the node's list commands.

Functional parity target: plugins/sql.c (sqlite3 vtables lazily
populated from listpeers/listchannels/... so operators can JOIN/filter
node state with plain SQL).  Here each query materializes the current
list-command snapshots into an in-memory sqlite database and runs the
(SELECT-only) statement against it — simpler than vtables, same
observable behavior at our scale.
"""
from __future__ import annotations

import json
import sqlite3


class SqlRpcError(Exception):
    pass


# table name -> (rpc method, result list key, column spec)
# columns: (name, type, extractor key or callable)
TABLES = {
    "peers": ("listpeers", "peers", [
        ("id", "TEXT", "id"), ("connected", "INTEGER", "connected"),
        ("features", "TEXT", "features"),
    ]),
    "nodes": ("listnodes", "nodes", [
        ("nodeid", "TEXT", "nodeid"), ("alias", "TEXT", "alias"),
        ("last_timestamp", "INTEGER", "last_timestamp"),
    ]),
    "channels": ("listchannels", "channels", [
        ("short_channel_id", "TEXT", "short_channel_id"),
        ("source", "TEXT", "source"),
        ("destination", "TEXT", "destination"),
        ("amount_msat", "INTEGER", "amount_msat"),
        ("active", "INTEGER", "active"),
        ("base_fee_millisatoshi", "INTEGER", "base_fee_millisatoshi"),
        ("fee_per_millionth", "INTEGER", "fee_per_millionth"),
        ("delay", "INTEGER", "delay"),
    ]),
    "invoices": ("listinvoices", "invoices", [
        ("label", "TEXT", "label"),
        ("payment_hash", "TEXT", "payment_hash"),
        ("status", "TEXT", "status"),
        ("amount_msat", "INTEGER", "amount_msat"),
        ("description", "TEXT", "description"),
        ("expires_at", "INTEGER", "expires_at"),
    ]),
    "payments": ("listpays", "pays", [
        ("payment_hash", "TEXT", "payment_hash"),
        ("status", "TEXT", "status"),
        ("amount_msat", "INTEGER", "amount_msat"),
        ("destination", "TEXT", "destination"),
    ]),
    "forwards": ("listforwards", "forwards", [
        ("in_channel", "TEXT", "in_channel"),
        ("out_channel", "TEXT", "out_channel"),
        ("in_msat", "INTEGER", "in_msat"),
        ("out_msat", "INTEGER", "out_msat"),
        ("fee_msat", "INTEGER", "fee_msat"),
        ("status", "TEXT", "status"),
    ]),
    "bkpr_events": ("bkpr-listaccountevents", "events", [
        ("account", "TEXT", "account"), ("tag", "TEXT", "tag"),
        ("credit_msat", "INTEGER", "credit_msat"),
        ("debit_msat", "INTEGER", "debit_msat"),
        ("timestamp", "INTEGER", "timestamp"),
    ]),
}

FORBIDDEN = ("insert", "update", "delete", "drop", "create", "alter",
             "attach", "pragma", "vacuum", "replace")


async def run_query(rpc, query: str,
                    params: list | None = None) -> list[list]:
    """Populate a scratch db from the list commands the query mentions,
    run it, return rows (sql.c returns arrays per row)."""
    low = " ".join(query.lower().split())
    first = low.split(" ", 1)[0] if low else ""
    if first not in ("select", "with"):
        raise SqlRpcError("only SELECT queries are allowed")
    for bad in FORBIDDEN:
        if f" {bad} " in f" {low} ":
            raise SqlRpcError(f"forbidden keyword {bad!r}")

    import inspect

    db = sqlite3.connect(":memory:")
    try:
        for table, (method, key, cols) in TABLES.items():
            if table not in low:
                continue
            handler = rpc.methods.get(method)
            if handler is None:
                continue
            result = handler()
            if inspect.isawaitable(result):
                result = await result
            rows = result.get(key, []) if isinstance(result, dict) else []
            db.execute(
                f"CREATE TABLE {table} "
                f"({', '.join(f'{n} {t}' for n, t, _ in cols)})")
            for item in rows:
                vals = []
                for _, _, k in cols:
                    v = item.get(k) if isinstance(item, dict) else None
                    if isinstance(v, (dict, list)):
                        v = json.dumps(v)
                    elif isinstance(v, bool):
                        v = int(v)
                    vals.append(v)
                db.execute(
                    f"INSERT INTO {table} VALUES "
                    f"({','.join('?' * len(cols))})", vals)
        try:
            cur = db.execute(query, params or [])
            return [list(r) for r in cur.fetchall()]
        except sqlite3.Error as e:
            raise SqlRpcError(str(e)) from None
    finally:
        db.close()


def attach_sql_command(rpc) -> None:
    from ..daemon.jsonrpc import RpcError

    async def sql(query: str) -> dict:
        try:
            rows = await run_query(rpc, query)
        except SqlRpcError as e:
            raise RpcError(-1, str(e))
        return {"rows": rows}

    async def listsqlschemas(table: str | None = None) -> dict:
        """Schemas of the SQL-queryable tables (sql.c
        json_listsqlschemas)."""
        names = [table] if table else sorted(TABLES)
        out = []
        for n in names:
            spec = TABLES.get(n)
            if spec is None:
                raise RpcError(-1, f"unknown table {n!r}")
            out.append({"tablename": n, "columns": [
                {"name": c, "type": t} for c, t, _ in spec[2]]})
        return {"schemas": out}

    async def sql_template(template: str, params: list | None = None) -> dict:
        """Parameterized SELECT: '?' placeholders bound by sqlite so
        clients never string-interpolate into SQL (sql-template)."""
        try:
            rows = await run_query(rpc, template, params)
        except SqlRpcError as e:
            raise RpcError(-1, str(e))
        return {"rows": rows}

    rpc.register("sql", sql)
    rpc.register("listsqlschemas", listsqlschemas)
    rpc.register("sql-template", sql_template)
