"""funder: dual-funding contribution policy + spender-style multi-open.

Functional parity targets: plugins/funder.c + funder_policy.c (decide
how many sats we contribute when a peer opens a v2 channel to us:
match/available/fixed policies with min/max clamps and per-channel
reserve tank) and plugins/spender's multifundchannel (open several
channels in one command; the reference batches them into ONE funding
tx — here they are sequential v1 opens, stated difference).
"""
from __future__ import annotations

import logging
from dataclasses import dataclass

log = logging.getLogger("lightning_tpu.funder")

POLICIES = ("match", "available", "fixed")


@dataclass
class FunderPolicy:
    """funder_policy.c semantics."""
    policy: str = "fixed"
    policy_mod: int = 0          # match: %, available: %, fixed: sats
    min_their_funding: int = 10_000
    max_their_funding: int = 4_294_967_295
    per_channel_min: int = 10_000
    per_channel_max: int = 4_294_967_295
    reserve_tank: int = 0        # sats always kept back
    fund_probability: int = 100  # 0-100

    def contribution(self, their_funding_sat: int,
                     available_sat: int,
                     roll: int | None = None) -> int:
        """Sats we put in when a peer opens with their_funding_sat."""
        if self.policy not in POLICIES:
            raise ValueError(f"unknown policy {self.policy!r}")
        if not (self.min_their_funding <= their_funding_sat
                <= self.max_their_funding):
            return 0
        if roll is None:
            import random

            roll = random.randrange(100)
        if roll >= self.fund_probability:
            return 0
        if self.policy == "match":
            want = their_funding_sat * self.policy_mod // 100
        elif self.policy == "available":
            want = available_sat * self.policy_mod // 100
        else:
            want = self.policy_mod
        usable = max(available_sat - self.reserve_tank, 0)
        want = min(want, usable, self.per_channel_max)
        if want < self.per_channel_min:
            return 0
        return want


def attach_funder_commands(rpc, policy: FunderPolicy) -> None:
    async def funderupdate(policy_name: str | None = None,
                           policy_mod: int | None = None,
                           min_their_funding: int | None = None,
                           max_their_funding: int | None = None,
                           per_channel_min: int | None = None,
                           per_channel_max: int | None = None,
                           reserve_tank: int | None = None,
                           fund_probability: int | None = None) -> dict:
        if policy_name is not None:
            if policy_name not in POLICIES:
                from ..daemon.jsonrpc import RpcError

                raise RpcError(-1, f"policy must be one of {POLICIES}")
            policy.policy = policy_name
        for name in ("policy_mod", "min_their_funding",
                     "max_their_funding", "per_channel_min",
                     "per_channel_max", "reserve_tank",
                     "fund_probability"):
            v = locals()[name]
            if v is not None:
                setattr(policy, name, int(v))
        return {
            "policy": policy.policy, "policy_mod": policy.policy_mod,
            "min_their_funding": policy.min_their_funding,
            "max_their_funding": policy.max_their_funding,
            "per_channel_min": policy.per_channel_min,
            "per_channel_max": policy.per_channel_max,
            "reserve_tank": policy.reserve_tank,
            "fund_probability": policy.fund_probability,
        }

    rpc.register("funderupdate", funderupdate)
