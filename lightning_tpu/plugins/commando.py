"""Commando: peer-to-peer JSON-RPC over custom wire messages, gated by
runes.

Functional parity target: plugins/commando.c (request/reply custommsg
protocol, rune authorization, reply fragmentation) — using the same
public protocol constants so the shape matches, with our in-loop
JsonRpcServer as the command table instead of a plugin round trip.

Protocol: a frame is `u16 type || u64 request_id || JSON fragment`.
Requests may span several CMD_CONTINUES frames ending with a CMD_TERM;
replies mirror that with REPLY_CONTINUES/REPLY_TERM.  The request JSON
is `{"method":..., "params":..., "rune":...}`.
"""
from __future__ import annotations

import asyncio
import json
import logging

from ..utils.runes import (Restriction, Rune, RuneError, standard_values)
from ..daemon.jsonrpc import RpcError

log = logging.getLogger("lightning_tpu.commando")

CMD_CONTINUES = 0x4C4D
CMD_TERM = 0x4C4F
REPLY_CONTINUES = 0x594B
REPLY_TERM = 0x594D

FRAGMENT = 65000           # max JSON bytes per frame
MAX_REQUEST = 1024 * 1024  # drop silly accumulations

COMMANDO_ERROR = -32600


class Commando:
    """Both sides of the protocol, attached to one node."""

    def __init__(self, node, rpc, master_secret: bytes):
        self.node = node
        self.rpc = rpc                     # JsonRpcServer (command table)
        self.secret = master_secret
        # keys are (peer_id, request_id): replies only count from the
        # peer the request went to (commando.c binds replies likewise —
        # otherwise any connected peer could forge them)
        self.partial: dict[tuple[bytes, int], bytearray] = {}
        self.pending: dict[tuple[bytes, int], asyncio.Future] = {}
        self.reply_buf: dict[tuple[bytes, int], bytearray] = {}
        self._next_id = 1
        for t in (CMD_CONTINUES, CMD_TERM):
            node.raw_handlers[t] = self._on_request_frame
        for t in (REPLY_CONTINUES, REPLY_TERM):
            node.raw_handlers[t] = self._on_reply_frame

    # -- rune management (createrune/checkrune RPC surface) ---------------

    def create_rune(self, restrictions: list[str] | None = None) -> str:
        rune = Rune.from_secret(
            self.secret,
            [Restriction.from_str(r) for r in (restrictions or [])])
        return rune.encode()

    def restrict_rune(self, rune_str: str, restrictions: list[str]) -> str:
        rune = Rune.decode(rune_str)
        for r in restrictions:
            rune.add_restriction(Restriction.from_str(r))
        return rune.encode()

    # set by attach_commando_commands: fn(rune_str) -> bool.  Lives on
    # the Commando object so the PEER command path enforces revocation
    # too, not just the local checkrune RPC.
    blacklist_check = None

    def check_rune(self, rune_str: str, method: str, params: dict,
                   peer_id: bytes) -> str | None:
        if self.blacklist_check is not None \
                and self.blacklist_check(rune_str):
            return "blacklisted"
        try:
            rune = Rune.decode(rune_str)
        except RuneError as e:
            return str(e)
        except Exception as e:
            # e.g. non-UTF8 restriction bytes; never let a junk rune
            # from an unauthenticated peer escape into the peer pump
            return f"unparseable rune: {type(e).__name__}"
        extra = {}
        if isinstance(params, dict):
            for k, v in params.items():
                extra[f"pname{_clean(k)}"] = v
        elif isinstance(params, list):
            for i, v in enumerate(params):
                extra[f"parr{i}"] = v
        values = standard_values(method=method, rune_id=peer_id.hex(),
                                 **extra)
        return rune.check(self.secret, values)

    # -- server side ------------------------------------------------------

    async def _on_request_frame(self, peer, raw: bytes) -> None:
        t = int.from_bytes(raw[:2], "big")
        if len(raw) < 10:
            return
        rid = int.from_bytes(raw[2:10], "big")
        key = (peer.node_id, rid)
        buf = self.partial.setdefault(key, bytearray())
        buf += raw[10:]
        if len(buf) > MAX_REQUEST:
            del self.partial[key]
            return
        if t == CMD_CONTINUES:
            return
        del self.partial[key]
        await self._serve(peer, rid, bytes(buf))

    async def _serve(self, peer, rid: int, body: bytes) -> None:
        try:
            req = json.loads(body)
            method = req["method"]
            params = req.get("params") or {}
            rune_str = req.get("rune")
        except (json.JSONDecodeError, KeyError, TypeError):
            await self._reply(peer, rid, _err(COMMANDO_ERROR, "bad request"))
            return
        if not isinstance(rune_str, str):
            await self._reply(peer, rid,
                              _err(COMMANDO_ERROR, "missing rune"))
            return
        why = self.check_rune(rune_str, method, params, peer.node_id)
        if why is not None:
            await self._reply(peer, rid,
                              _err(COMMANDO_ERROR, f"rune rejected: {why}"))
            return
        handler = self.rpc.methods.get(method)
        if handler is None:
            await self._reply(peer, rid,
                              _err(COMMANDO_ERROR,
                                   f"unknown command {method!r}"))
            return
        try:
            import inspect

            if isinstance(params, list):
                names = [p for p in inspect.signature(handler).parameters]
                params = dict(zip(names, params))
            result = handler(**params)
            if inspect.isawaitable(result):
                result = await result
            await self._reply(peer, rid, {"result": result})
        except RpcError as e:
            await self._reply(peer, rid, _err(e.code, str(e)))
        except TypeError as e:
            await self._reply(peer, rid, _err(COMMANDO_ERROR, str(e)))
        except Exception as e:
            log.exception("commando %s failed", method)
            await self._reply(peer, rid,
                              _err(COMMANDO_ERROR,
                                   f"{type(e).__name__}: {e}"))

    async def _reply(self, peer, rid: int, obj: dict) -> None:
        body = json.dumps(obj).encode()
        frags = [body[i:i + FRAGMENT]
                 for i in range(0, len(body), FRAGMENT)] or [b""]
        for i, frag in enumerate(frags):
            t = REPLY_TERM if i == len(frags) - 1 else REPLY_CONTINUES
            await peer.send_raw(t.to_bytes(2, "big")
                                + rid.to_bytes(8, "big") + frag)

    # -- client side ------------------------------------------------------

    async def call(self, peer, method: str, params=None,
                   rune: str | None = None, timeout: float = 30.0):
        """Run `method` on the remote peer; returns its result or raises
        RpcError with the remote error."""
        rid = self._next_id
        self._next_id += 1
        body = json.dumps({"method": method, "params": params or {},
                           "rune": rune}).encode()
        fut = asyncio.get_running_loop().create_future()
        key = (peer.node_id, rid)
        self.pending[key] = fut
        try:
            frags = [body[i:i + FRAGMENT]
                     for i in range(0, len(body), FRAGMENT)] or [b""]
            for i, frag in enumerate(frags):
                t = CMD_TERM if i == len(frags) - 1 else CMD_CONTINUES
                await peer.send_raw(t.to_bytes(2, "big")
                                    + rid.to_bytes(8, "big") + frag)
            resp = await asyncio.wait_for(fut, timeout)
        finally:
            self.pending.pop(key, None)
            self.reply_buf.pop(key, None)
        if "error" in resp:
            err = resp["error"]
            raise RpcError(err.get("code", COMMANDO_ERROR),
                           err.get("message", "remote error"))
        return resp.get("result")

    async def _on_reply_frame(self, peer, raw: bytes) -> None:
        if len(raw) < 10:
            return
        t = int.from_bytes(raw[:2], "big")
        rid = int.from_bytes(raw[2:10], "big")
        key = (peer.node_id, rid)
        if key not in self.pending:
            return   # unsolicited: don't buffer attacker bytes
        buf = self.reply_buf.setdefault(key, bytearray())
        buf += raw[10:]
        if len(buf) > MAX_REQUEST:
            del self.reply_buf[key]
            return
        if t == REPLY_CONTINUES:
            return
        del self.reply_buf[key]
        fut = self.pending.get(key)
        if fut is None or fut.done():
            return
        try:
            fut.set_result(json.loads(bytes(buf)))
        except json.JSONDecodeError:
            fut.set_result({"error": {"code": COMMANDO_ERROR,
                                      "message": "unparseable reply"}})


def _clean(name: str) -> str:
    return "".join(c for c in name if c.isalnum())


def _err(code: int, message: str) -> dict:
    return {"error": {"code": code, "message": message}}


def attach_commando_commands(rpc, commando: Commando, db=None) -> None:
    """createrune / checkrune / commando RPC entries
    (lightningd/runes.c + plugins/commando.c surfaces).  `db` persists
    the rune registry + blacklist across restarts."""

    # created-rune registry (lightningd/runes.c keeps them in the db;
    # persisted through the vars table when a db is attached so
    # blacklists survive restarts and unique ids are never reused)
    import json as _json

    store: dict[int, dict] = {}
    blacklist: list[tuple[int, int]] = []
    if db is not None:
        raw = db.get_var("runes")
        if raw:
            saved = _json.loads(raw)
            store.update({int(k): v for k, v in saved["store"].items()})
            blacklist.extend(tuple(b) for b in saved["blacklist"])

    def _save() -> None:
        if db is not None:
            db.set_var("runes", _json.dumps(
                {"store": store, "blacklist": blacklist}))

    async def createrune(restrictions: list[str] | None = None) -> dict:
        r = commando.create_rune(restrictions)
        uid = max(store, default=-1) + 1
        store[uid] = {"rune": r, "unique_id": uid,
                      "restrictions": restrictions or []}
        _save()
        return {"rune": r, "unique_id": uid}

    async def showrunes(rune: str | None = None) -> dict:
        rows = [dict(v, blacklisted=any(a <= k <= b
                                        for a, b in blacklist))
                for k, v in store.items()
                if rune is None or v["rune"] == rune]
        return {"runes": rows}

    async def blacklistrune(start: int, end: int | None = None) -> dict:
        blacklist.append((int(start), int(end if end is not None
                                          else start)))
        _save()
        return {"blacklist": [{"start": a, "end": b}
                              for a, b in blacklist]}

    def _is_blacklisted(rune_str: str) -> bool:
        """True for a blacklisted minted rune OR any restricted
        derivative of one (derivation only ever APPENDS restrictions,
        so the parent's restriction list is a prefix of the child's).
        Note: blacklisting an unrestricted master rune therefore
        revokes every rune — the only sound reading, since all runes
        derive from it."""
        try:
            cand = [r.encode() for r in Rune.decode(rune_str).restrictions]
        except Exception:
            return False
        for uid, v in store.items():
            if not any(a <= uid <= b for a, b in blacklist):
                continue
            try:
                prs = [r.encode()
                       for r in Rune.decode(v["rune"]).restrictions]
            except Exception:
                continue
            if cand[:len(prs)] == prs:
                return True
        return False

    commando.blacklist_check = _is_blacklisted

    async def checkrune(rune: str, method: str = "",
                        params: dict | None = None,
                        nodeid: str = "") -> dict:
        # commando.check_rune consults the blacklist itself (via
        # blacklist_check below) — no separate scan here
        why = commando.check_rune(rune, method, params or {},
                                  bytes.fromhex(nodeid) if nodeid else b"")
        if why is not None:
            raise RpcError(COMMANDO_ERROR, f"rune rejected: {why}")
        return {"valid": True}

    async def commando_cmd(peer_id: str, method: str,
                           params: dict | None = None,
                           rune: str = "") -> dict:
        peer = commando.node.peers.get(bytes.fromhex(peer_id))
        if peer is None:
            raise RpcError(COMMANDO_ERROR, "peer not connected")
        result = await commando.call(peer, method, params, rune)
        return result if isinstance(result, dict) else {"result": result}

    rpc.register("createrune", createrune)
    rpc.register("checkrune", checkrune)
    rpc.register("commando", commando_cmd)
    rpc.register("showrunes", showrunes)
    rpc.register("blacklistrune", blacklistrune)
    # the commando plugin's pre-rename names (deprecated in the
    # reference too: plugins/commando.c)
    rpc.register("commando-rune", createrune, deprecated=True)
    rpc.register("commando-listrunes", showrunes, deprecated=True)
    rpc.register("commando-blacklist", blacklistrune, deprecated=True)
